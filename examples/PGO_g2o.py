"""Solve a .g2o pose graph file (SE3:QUAT or SE2) end to end.

The g2o text format is the standard interchange for pose-graph datasets
(sphere2500, garage, manhattan, intel, ...).  The reference ships no
pose-graph support at all (its only loader is the BAL text parser,
examples/BAL_Double.cpp:74-139); this CLI reads a file, solves it on
the TPU PGO pipeline (models/pgo.py), and optionally writes the
optimized graph back out.

    python examples/PGO_g2o.py --path sphere2500.g2o --out solved.g2o

Without --path, a synthetic loop-closure graph is written to a temp
file first and then ingested through the full file route — the sandbox
has no dataset downloads, so this demonstrates the identical code path.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

import numpy as np


def main(argv=None) -> float:
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from megba_tpu.utils.backend import respect_jax_platforms

    respect_jax_platforms()

    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.io.g2o import G2OGraph, read_g2o, solve_g2o, write_g2o
    from megba_tpu.models.pgo import make_synthetic_pose_graph

    ap = argparse.ArgumentParser()
    ap.add_argument("--path", type=str, default="", help=".g2o input file")
    ap.add_argument("--out", type=str, default="",
                    help="write optimized graph here (.g2o)")
    ap.add_argument("--max_iter", type=int, default=30)
    ap.add_argument("--solver_tol", type=float, default=1e-12)
    ap.add_argument("--solver_max_iter", type=int, default=120)
    ap.add_argument("--tau", type=float, default=1e3)
    ap.add_argument("--epsilon1", type=float, default=1e-10)
    ap.add_argument("--epsilon2", type=float, default=1e-14)
    ap.add_argument("--synthetic_poses", type=int, default=64)
    ap.add_argument("--synthetic_loop_closures", type=int, default=10)
    ap.add_argument("--world_size", type=int, default=1,
                    help="shard the edge axis over this many devices")
    ap.add_argument("--robust", choices=["none", "huber", "cauchy"],
                    default="none",
                    help="IRLS robust loss against bad loop closures")
    ap.add_argument("--robust_delta", type=float, default=1.0)
    ap.add_argument("--init", choices=["file", "spanning_tree"],
                    default="file",
                    help="spanning_tree: bootstrap poses from the "
                         "measurements instead of the file's estimates")
    ap.add_argument("--prior_ids", type=str, default="",
                    help="comma-separated g2o vertex ids to anchor at "
                         "their file estimates via unary prior factors "
                         "(soft anchors; see --prior_weight)")
    ap.add_argument("--prior_weight", type=float, default=1e4,
                    help="sqrt-information scale of each prior (W = w*I)")
    args = ap.parse_args(argv)

    path = args.path
    tmp = None
    if not path:
        g = make_synthetic_pose_graph(
            num_poses=args.synthetic_poses,
            loop_closures=args.synthetic_loop_closures)
        n = g.poses0.shape[0]
        fixed = np.zeros(n, bool)
        fixed[0] = True
        graph = G2OGraph(
            poses=g.poses0, edge_i=g.edge_i, edge_j=g.edge_j, meas=g.meas,
            info=np.tile(np.eye(6), (len(g.edge_i), 1, 1)), fixed=fixed,
            ids=np.arange(n, dtype=np.int64))
        tmp = tempfile.NamedTemporaryFile(
            mode="w", suffix=".g2o", delete=False)
        write_g2o(tmp, graph)
        tmp.close()
        path = tmp.name
        print(f"synthetic graph -> {path}")

    try:
        t0 = time.perf_counter()
        graph = read_g2o(path)
        t_parse = time.perf_counter() - t0
        kind = "SE2 (lifted)" if graph.se2 else "SE3"
        print(f"{path}: {len(graph.ids)} poses, {len(graph.edge_i)} edges "
              f"[{kind}], parsed in {t_parse:.2f}s")

        from megba_tpu.ops.robust import RobustKind

        option = ProblemOption(
            dtype=np.float32,
            world_size=args.world_size,
            robust_kind=RobustKind[args.robust.upper()],
            robust_delta=args.robust_delta,
            algo_option=AlgoOption(max_iter=args.max_iter,
                                   initial_region=args.tau,
                                   epsilon1=args.epsilon1,
                                   epsilon2=args.epsilon2),
            solver_option=SolverOption(max_iter=args.solver_max_iter,
                                       tol=args.solver_tol,
                                       refuse_ratio=1e30),
        )
        prior_ids = ([int(v) for v in args.prior_ids.split(",") if v]
                     if args.prior_ids else None)
        t0 = time.perf_counter()
        graph, res = solve_g2o(graph, option, verbose=True,
                               init=args.init, prior_ids=prior_ids,
                               prior_weight=args.prior_weight)
        print(f"solve: {time.perf_counter() - t0:.2f}s")

        if args.out:
            write_g2o(args.out, graph, poses=np.asarray(res.poses))
            print(f"optimized graph -> {args.out}")
    finally:
        if tmp is not None:
            os.unlink(tmp.name)
    return float(res.cost)


if __name__ == "__main__":
    main()
