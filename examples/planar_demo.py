"""Planar (2D) bundle adjustment demo — a second model family.

Shows the dimension-generic solver on SE(2)+focal cameras and 2D points
(megba_tpu/models/planar.py), including the custom-edge route through
the g2o-style facade.  Usage: python examples/planar_demo.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np

jax.config.update("jax_enable_x64", True)

from megba_tpu.common import AlgoOption, JacobianMode, ProblemOption, SolverOption
from megba_tpu.models import planar
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.solve import flat_solve


def main(num_cameras=12, num_points=200, obs_per_point=5,
         max_iter=20, argv=None) -> float:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--num_cameras", type=int, default=num_cameras)
    ap.add_argument("--num_points", type=int, default=num_points)
    ap.add_argument("--obs_per_point", type=int, default=obs_per_point)
    ap.add_argument("--max_iter", type=int, default=max_iter)
    args = ap.parse_args(argv)
    num_cameras, num_points = args.num_cameras, args.num_points
    obs_per_point, max_iter = args.obs_per_point, args.max_iter
    s = planar.make_synthetic_planar(
        num_cameras=num_cameras, num_points=num_points,
        obs_per_point=obs_per_point, noise=0.2, param_noise=3e-2, seed=0)
    f = make_residual_jacobian_fn(residual_fn=planar.residual,
                                  mode=JacobianMode.AUTODIFF)
    option = ProblemOption(
        algo_option=AlgoOption(max_iter=max_iter, epsilon1=1e-10,
                               epsilon2=1e-13),
        solver_option=SolverOption(max_iter=150, tol=1e-12,
                                   refuse_ratio=1e30))
    # The public edge-major boundary (flat_solve) owns the feature-major
    # transpose, padding, and jit caching — same pipeline as the BAL CLIs.
    res = flat_solve(
        f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option,
        verbose=True)
    print(
        f"planar BA: cost {float(res.initial_cost):.4e} -> {float(res.cost):.6e} "
        f"in {int(res.iterations)} iterations")
    return float(res.cost)


if __name__ == "__main__":
    main()
