"""Shared CLI runner for the BAL examples.

Flag names follow the reference examples (BAL_Double.cpp:50-58 and the
README run recipe README.md:56-58): --path, --world_size, --max_iter,
--solver_tol, --solver_refuse_ratio, --solver_max_iter, --tau,
--epsilon1, --epsilon2.  With no --path, a synthetic BAL-like scene is
generated (this sandbox has no dataset downloads); --synthetic_* control
its size.
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser()
    ap.add_argument("--path", type=str, default="", help="BAL problem file")
    ap.add_argument("--world_size", type=int, default=1)
    ap.add_argument("--max_iter", type=int, default=20)
    ap.add_argument("--solver_tol", type=float, default=1e-1)
    ap.add_argument("--solver_refuse_ratio", type=float, default=1.0)
    ap.add_argument("--solver_max_iter", type=int, default=100)
    ap.add_argument("--tau", type=float, default=1e3, help="initial trust region")
    ap.add_argument("--epsilon1", type=float, default=1.0)
    ap.add_argument("--epsilon2", type=float, default=1e-10)
    ap.add_argument("--synthetic_cameras", type=int, default=50)
    ap.add_argument("--synthetic_points", type=int, default=2000)
    ap.add_argument("--synthetic_obs_per_point", type=int, default=6)
    return ap


def run_example(dtype, jacobian_mode, compute_kind, argv=None) -> float:
    import jax  # noqa: F401  (platform must be set before device queries)

    from megba_tpu.utils.backend import respect_jax_platforms

    respect_jax_platforms()

    if np.dtype(dtype) == np.float64:
        jax.config.update("jax_enable_x64", True)

    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.io.bal import load_bal
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    args = build_arg_parser().parse_args(argv)

    if args.path:
        bal = load_bal(args.path, dtype=dtype)
        cameras, points = bal.cameras, bal.points
        obs, cam_idx, pt_idx = bal.obs, bal.cam_idx, bal.pt_idx
    else:
        s = make_synthetic_bal(
            num_cameras=args.synthetic_cameras,
            num_points=args.synthetic_points,
            obs_per_point=args.synthetic_obs_per_point,
            seed=0, param_noise=2e-2, pixel_noise=0.5, dtype=dtype)
        cameras, points = s.cameras0, s.points0
        obs, cam_idx, pt_idx = s.obs, s.cam_idx, s.pt_idx

    option = ProblemOption(
        dtype=dtype,
        world_size=args.world_size,
        compute_kind=compute_kind,
        jacobian_mode=jacobian_mode,
        algo_option=AlgoOption(
            max_iter=args.max_iter, initial_region=args.tau,
            epsilon1=args.epsilon1, epsilon2=args.epsilon2),
        solver_option=SolverOption(
            max_iter=args.solver_max_iter, tol=args.solver_tol,
            refuse_ratio=args.solver_refuse_ratio),
    )
    f = make_residual_jacobian_fn(mode=jacobian_mode)

    print(
        f"solving: {cameras.shape[0]} cameras, {points.shape[0]} points, "
        f"{obs.shape[0]} observations | dtype={np.dtype(dtype).name} "
        f"jacobian={jacobian_mode.name} compute={compute_kind.name} "
        f"world_size={args.world_size}")

    t0 = time.perf_counter()
    result = flat_solve(f, cameras, points, obs, cam_idx, pt_idx, option,
                        verbose=True)
    cost = float(result.cost)
    elapsed = time.perf_counter() - t0
    print(
        f"Finished: cost {float(result.initial_cost):.6e} -> {cost:.6e} "
        f"(log10 {np.log10(max(cost, 1e-300)):.3f}), "
        f"{int(result.iterations)} iterations ({int(result.accepted)} accepted), "
        f"{elapsed * 1000:.1f} ms total")
    return cost
