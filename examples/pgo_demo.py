"""SE(3) pose-graph optimization demo (between-factors, loop closures).

Builds a drifted circular trajectory with loop closures and pulls it
back onto the ground truth.  A family the reference cannot express (its
edges are hard-wired to camera+landmark pairs); here it rides the same
feature-major / segment-reduction / PCG machinery as the BA families.

    python examples/pgo_demo.py --num_poses 64 --loop_closures 10
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def main(argv=None) -> float:
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    from megba_tpu.utils.backend import respect_jax_platforms

    respect_jax_platforms()

    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.models.pgo import make_synthetic_pose_graph, solve_pgo

    ap = argparse.ArgumentParser()
    ap.add_argument("--num_poses", type=int, default=64)
    ap.add_argument("--loop_closures", type=int, default=10)
    ap.add_argument("--drift_noise", type=float, default=0.05)
    ap.add_argument("--meas_noise", type=float, default=0.0)
    ap.add_argument("--max_iter", type=int, default=30)
    args = ap.parse_args(argv)

    g = make_synthetic_pose_graph(
        num_poses=args.num_poses, loop_closures=args.loop_closures,
        drift_noise=args.drift_noise, meas_noise=args.meas_noise)
    option = ProblemOption(
        dtype=np.float32,
        algo_option=AlgoOption(max_iter=args.max_iter, epsilon1=1e-10,
                               epsilon2=1e-14),
        solver_option=SolverOption(max_iter=120, tol=1e-12,
                                   refuse_ratio=1e30),
    )
    res = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, option,
                    verbose=True)
    drift0 = float(np.max(np.linalg.norm(g.poses0 - g.poses_gt, axis=1)))
    drift1 = float(np.max(np.linalg.norm(
        np.asarray(res.poses) - g.poses_gt, axis=1)))
    print(f"max pose drift: {drift0:.4f} -> {drift1:.6f}")
    return float(res.cost)


if __name__ == "__main__":
    main()
