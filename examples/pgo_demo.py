"""SE(3) pose-graph optimization demo (between-factors, loop closures).

Builds a drifted circular trajectory with loop closures and pulls it
back onto the ground truth.  A family the reference cannot express (its
edges are hard-wired to camera+landmark pairs); here it rides the same
feature-major / segment-reduction / PCG machinery as the BA families.

    python examples/pgo_demo.py --num_poses 64 --loop_closures 10
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def main(argv=None) -> float:
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from megba_tpu.utils.backend import respect_jax_platforms

    respect_jax_platforms()

    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.models.pgo import make_synthetic_pose_graph, solve_pgo

    ap = argparse.ArgumentParser()
    ap.add_argument("--num_poses", type=int, default=64)
    ap.add_argument("--loop_closures", type=int, default=10)
    ap.add_argument("--drift_noise", type=float, default=0.05)
    ap.add_argument("--meas_noise", type=float, default=0.0)
    ap.add_argument("--max_iter", type=int, default=30)
    ap.add_argument("--priors", type=int, default=0,
                    help="anchor the first N poses at ground truth via "
                         "unary prior factors (with_priors) instead of "
                         "the default fixed-pose gauge — the "
                         "reference's README TODO 'prior factor (TBD)'")
    args = ap.parse_args(argv)

    g = make_synthetic_pose_graph(
        num_poses=args.num_poses, loop_closures=args.loop_closures,
        drift_noise=args.drift_noise, meas_noise=args.meas_noise)
    option = ProblemOption(
        dtype=np.float32,
        algo_option=AlgoOption(max_iter=args.max_iter, epsilon1=1e-10,
                               epsilon2=1e-14),
        solver_option=SolverOption(max_iter=120, tol=1e-12,
                                   refuse_ratio=1e30),
    )
    start = g.poses0
    if args.priors > 0:
        from megba_tpu.models.pgo import spanning_tree_init, with_priors

        k = min(args.priors, args.num_poses)
        poses0, ei, ej, meas, fixed, si = with_priors(
            g.poses0, g.edge_i, g.edge_j, g.meas,
            prior_idx=np.arange(k), prior_poses=g.poses_gt[:k],
            prior_sqrt_info=np.broadcast_to(np.eye(6) * 10.0, (k, 6, 6)))
        # The prior anchors root the measurement bootstrap; with
        # noise-free odometry the bootstrap alone lands on ground truth
        # and LM only polishes — the staged drift print below shows
        # where the work happened.
        poses0 = spanning_tree_init(poses0, ei, ej, meas, fixed)
        start = poses0[:args.num_poses]
        res = solve_pgo(poses0, ei, ej, meas, option,
                        sqrt_info=si, fixed=fixed, verbose=True)
        res = res._replace(poses=res.poses[:args.num_poses])
    else:
        res = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, option,
                        verbose=True)

    def se3_drift(poses):
        # Chart-independent SE(3) distance to ground truth: rotation
        # geodesic angle + translation norm (raw angle-axis differences
        # can read 2*pi for identical rotations on opposite branches).
        import jax
        import jax.numpy as jnp

        from megba_tpu.ops import geo

        p = jnp.asarray(np.asarray(poses))
        gt = jnp.asarray(g.poses_gt)
        R_p = jax.vmap(geo.angle_axis_to_rotation_matrix)(p[:, :3])
        R_g = jax.vmap(geo.angle_axis_to_rotation_matrix)(gt[:, :3])
        ang = jax.vmap(lambda a, b: jnp.linalg.norm(
            geo.rotation_matrix_to_angle_axis(a.T @ b)))(R_p, R_g)
        trans = jnp.linalg.norm(p[:, 3:] - gt[:, 3:], axis=1)
        return float(jnp.max(ang + trans))

    if args.priors > 0:
        print(f"max pose drift (SE3): raw {se3_drift(g.poses0):.4f} -> "
              f"prior-rooted bootstrap {se3_drift(start):.6f} -> "
              f"solved {se3_drift(res.poses):.6f}")
    else:
        print(f"max pose drift (SE3): {se3_drift(g.poses0):.4f} -> "
              f"{se3_drift(res.poses):.6f}")
    return float(res.cost)


if __name__ == "__main__":
    main()
