"""Parity entry point for the reference's BAL_Double_analytical_implicit example
(reference examples/BAL_Double_analytical_implicit.cpp): float64, analytical Jacobians, implicit Hessian."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from examples.common import run_example
from megba_tpu.common import ComputeKind, JacobianMode

def main(argv=None) -> float:
    return run_example(np.float64, JacobianMode.ANALYTICAL, ComputeKind.IMPLICIT, argv)


if __name__ == "__main__":
    main()
