"""Parity entry point for the reference's BAL_Float_analytical example
(reference examples/BAL_Float_analytical.cpp): float32, analytical Jacobians, explicit Hessian."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from examples.common import run_example
from megba_tpu.common import ComputeKind, JacobianMode

def main(argv=None) -> float:
    return run_example(np.float32, JacobianMode.ANALYTICAL, ComputeKind.EXPLICIT, argv)


if __name__ == "__main__":
    main()
