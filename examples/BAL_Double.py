"""Parity entry point for the reference's BAL_Double example
(reference examples/BAL_Double.cpp): float64, autodiff Jacobians, explicit Hessian."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from examples.common import run_example
from megba_tpu.common import ComputeKind, JacobianMode

def main(argv=None) -> float:
    return run_example(np.float64, JacobianMode.AUTODIFF, ComputeKind.EXPLICIT, argv)


if __name__ == "__main__":
    main()
