from megba_tpu.algo.checkpointed import solve_checkpointed
from megba_tpu.algo.lm import LMResult, lm_solve

__all__ = ["LMResult", "lm_solve", "solve_checkpointed"]
