"""Levenberg-Marquardt trust-region outer loop.

TPU-native replacement for the reference's LMAlgo::solveCUDA
(src/algo/lm_algo.cu:139-223): the same algorithm — damp, solve the Schur
system, test ||dx|| <= eps2(||x|| + eps1), apply, gain ratio rho from the
linearised cost Sum(J dx + e)^2, accept (relinearise, region /= max(1/3,
1-(2 rho - 1)^3), stop when ||g||_inf <= eps1) or reject (region /= v,
v *= 2) — but as a single jitted `lax.while_loop`.

The reference's trickiest machinery disappears in functional form: its
backup/rollback device copies (base_edge.cu:17-44,
schur_LM_linear_system.cu:187-209 — the README.md:15 changelog records a
rollback-correctness bug here) become "carry the old pytree instead of
the new one", and the damping save/restore (recoverDiag) is a pure
function of the undamped blocks.  Each LM iteration runs entirely
on-device: no host-blocking residual-norm or dot reductions
(lm_algo.cu:25-58 syncs the host ~6 times per iteration).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from megba_tpu.common import ComputeKind, ProblemOption
from megba_tpu.linear_system.builder import (
    SchurSystem,
    build_schur_system,
    weight_system_inputs,
)
from megba_tpu.ops.robust import RobustKind, robustify
from megba_tpu.solver.pcg import HI, plain_pcg_solve, schur_pcg_solve

_TINY = 1e-30

# Host-side clock for verbose per-iteration lines; reset by iteration 0's
# callback so elapsed-ms is per-solve even though jitted programs (and
# this closure) are cached across solves.  Known limits: concurrent
# verbose solves share this clock (their lines interleave anyway), and a
# chunked solve restarts it per chunk — elapsed is per-chunk there.
_VERBOSE_CLOCK = {"t0": 0.0}


def _emit_verbose_line(k, c, a, p):
    now = time.perf_counter()
    if int(k) == 0:
        _VERBOSE_CLOCK["t0"] = now
    dt = (now - _VERBOSE_CLOCK["t0"]) * 1e3
    print(
        f"iter {int(k)}: cost {float(c):.6e} "
        f"log10 {np.log10(max(float(c), 1e-300)):.3f} "
        f"accept {bool(a)} pcg_iters {int(p)} "
        f"elapsed {dt:.1f} ms", flush=True)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LMResult:
    """Final state + diagnostics of one LM solve."""

    cameras: jax.Array
    points: jax.Array
    cost: jax.Array  # final accepted cost Sum e^2
    initial_cost: jax.Array
    iterations: jax.Array  # LM iterations executed
    accepted: jax.Array  # number of accepted steps
    region: jax.Array  # final trust region
    v: jax.Array  # final reject back-off factor (resume state)
    stopped: jax.Array  # True when a convergence criterion fired


def lm_solve(
    residual_jac_fn: Callable,
    cameras: jax.Array,
    points: jax.Array,
    obs: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    mask: jax.Array,
    option: ProblemOption,
    sqrt_info: Optional[jax.Array] = None,
    cam_fixed: Optional[jax.Array] = None,
    pt_fixed: Optional[jax.Array] = None,
    axis_name: Optional[str] = None,
    verbose: bool = False,
    cam_sorted: bool = False,
    pallas_plan=None,
    initial_region=None,
    initial_v=None,
) -> LMResult:
    """Run the LM loop to convergence.  Jit/shard_map-compatible.

    `residual_jac_fn(cam_params, pt_params, obs) -> (r, Jc, Jp)` is the
    vectorised engine from ops.residuals.  Edge-axis arrays (obs, cam_idx,
    pt_idx, mask, sqrt_info) may be shard-local when `axis_name` names a
    mesh axis; cameras/points are replicated.

    `initial_region`/`initial_v` override the trust-region start state —
    the resume hook used by utils.checkpoint / solve_checkpointed.
    """
    num_cameras = cameras.shape[0]
    num_points = points.shape[0]
    algo_opt = option.algo_option
    solver_opt = option.solver_option
    compute_kind = option.compute_kind

    def psum(x):
        return jax.lax.psum(x, axis_name) if axis_name is not None else x

    robust = option.robust_kind
    robust_delta = option.robust_delta

    def linearize(cams, pts):
        r, Jc, Jp = residual_jac_fn(jnp.take(cams, cam_idx, axis=0),
                                    jnp.take(pts, pt_idx, axis=0), obs)
        r, Jc, Jp = weight_system_inputs(
            r, Jc, Jp, cam_idx, pt_idx, mask, sqrt_info, cam_fixed, pt_fixed)
        if robust == RobustKind.NONE:
            wcost = psum(jnp.sum(r * r))
            cost = wcost
        else:
            # IRLS reweighting (ops/robust.py); the system is built from
            # the weighted quantities, the accept test uses Sum rho, the
            # quadratic model is measured from the weighted norm.
            r, Jc, Jp, rho_e = robustify(r, Jc, Jp, robust, robust_delta)
            cost = psum(jnp.sum(rho_e))
            wcost = psum(jnp.sum(r * r))
        system = build_schur_system(
            r, Jc, Jp, cam_idx, pt_idx, num_cameras, num_points,
            compute_kind=compute_kind, axis_name=axis_name,
            cam_fixed=cam_fixed, pt_fixed=pt_fixed, cam_sorted=cam_sorted,
            pallas_plan=pallas_plan)
        return r, Jc, Jp, system, cost, wcost

    r0, Jc0, Jp0, system0, cost0, wcost0 = linearize(cameras, points)

    dtype = cameras.dtype
    state0 = dict(
        k=jnp.int32(0),
        accepted=jnp.int32(0),
        cameras=cameras,
        points=points,
        r=r0,
        Jc=Jc0,
        Jp=Jp0,
        system=system0,
        cost=cost0,
        wcost=wcost0,
        region=jnp.asarray(
            algo_opt.initial_region if initial_region is None else initial_region,
            dtype),
        v=jnp.asarray(2.0 if initial_v is None else initial_v, dtype),
        stop=jnp.bool_(False),
    )

    def cond(s):
        return (s["k"] < algo_opt.max_iter) & (~s["stop"])

    pcg_solve = schur_pcg_solve if option.use_schur else plain_pcg_solve

    def body(s):
        pcg = pcg_solve(
            s["system"], s["Jc"], s["Jp"], cam_idx, pt_idx, s["region"],
            max_iter=solver_opt.max_iter, tol=solver_opt.tol,
            refuse_ratio=solver_opt.refuse_ratio,
            tol_relative=solver_opt.tol_relative,
            compute_kind=compute_kind, axis_name=axis_name,
            mixed_precision=option.mixed_precision_pcg, cam_sorted=cam_sorted,
            preconditioner=solver_opt.preconditioner)
        dx_cam, dx_pt = pcg.dx_cam, pcg.dx_pt

        # ||dx|| <= eps2 (||x|| + eps1)  -> converged, don't apply
        # (reference lm_algo.cu:171-179).
        dx_norm = jnp.sqrt(jnp.sum(dx_cam * dx_cam) + jnp.sum(dx_pt * dx_pt))
        x_norm = jnp.sqrt(jnp.sum(s["cameras"] ** 2) + jnp.sum(s["points"] ** 2))
        converged = dx_norm <= algo_opt.epsilon2 * (x_norm + algo_opt.epsilon1)

        cams_new = s["cameras"] + dx_cam
        pts_new = s["points"] + dx_pt

        # Gain-ratio denominator: linearised cost at dx minus old cost
        # (the JdxpF kernel, lm_algo.cu:60-126).  J dx + e per edge:
        jdx = (
            jnp.einsum("eoc,ec->eo", s["Jc"], jnp.take(dx_cam, cam_idx, axis=0), precision=HI)
            + jnp.einsum("eop,ep->eo", s["Jp"], jnp.take(dx_pt, pt_idx, axis=0), precision=HI)
            + s["r"]
        )
        predicted = psum(jnp.sum(jdx * jdx))
        # The quadratic model is in the (robust-)weighted residuals; its
        # decrease is measured from the carried weighted norm, while
        # accept uses the true (robustified) cost.  For RobustKind.NONE
        # both equal Sum r^2 and this reduces to the reference formula.
        # The linearised decrease is <= 0 for any useful step; clamp
        # sign-preservingly so an underflowing denominator can't flip
        # rho's sign and collapse the trust region on an accepted step.
        denominator = jnp.minimum(predicted - s["wcost"], -_TINY)

        # ONE linearisation at the trial point serves both the cost test
        # and the accept branch — the reference's second forward() per
        # iteration whose jets feed buildLinearSystem on accept
        # (lm_algo.cu:183-189).
        r_n, Jc_n, Jp_n, system_n, cost_new, wcost_new = linearize(cams_new, pts_new)
        rho = (cost_new - s["cost"]) / denominator

        # Reference lm_algo.cu breaks BEFORE edges.update() when the
        # step-size test fires — a converged step is never applied.
        accept = (cost_new < s["cost"]) & (~converged)

        g_inf = jnp.maximum(jnp.max(jnp.abs(system_n.g_cam)),
                            jnp.max(jnp.abs(system_n.g_pt)))
        region_accept = s["region"] / jnp.maximum(
            jnp.asarray(1.0 / 3.0, dtype), 1.0 - (2.0 * rho - 1.0) ** 3)
        stop_accept = g_inf <= algo_opt.epsilon1

        # --- reject branch values ---
        region_reject = s["region"] / s["v"]
        v_reject = s["v"] * 2.0

        def pick(new, old):
            return jax.tree_util.tree_map(
                lambda a, b: jnp.where(accept, a, b), new, old)

        s_next = dict(
            k=s["k"] + 1,
            accepted=s["accepted"] + jnp.where(accept, 1, 0).astype(jnp.int32),
            cameras=pick(cams_new, s["cameras"]),
            points=pick(pts_new, s["points"]),
            r=pick(r_n, s["r"]),
            Jc=pick(Jc_n, s["Jc"]),
            Jp=pick(Jp_n, s["Jp"]),
            system=pick(system_n, s["system"]),
            cost=jnp.where(accept, cost_new, s["cost"]),
            wcost=jnp.where(accept, wcost_new, s["wcost"]),
            region=jnp.where(accept, region_accept, region_reject),
            v=jnp.where(accept, jnp.asarray(2.0, dtype), v_reject),
            stop=converged | (accept & stop_accept),
        )
        if verbose:
            def _print(args):
                # Host callback: prints the reference's per-iteration line
                # (cost, log10 cost, elapsed ms — lm_algo.cu:149-162).
                # Elapsed is measured host-side from this solve's first
                # iteration callback (iteration 0 resets the clock — the
                # jitted program is cached across solves, so a trace-time
                # baseline would be frozen at the FIRST solve's start).
                jax.debug.callback(_emit_verbose_line, *args)

            args = (s["k"], cost_new, accept, pcg.iterations)
            if axis_name is None:
                _print(args)
            else:
                # One line per iteration, not one per shard.
                jax.lax.cond(
                    jax.lax.axis_index(axis_name) == 0, _print,
                    lambda _: None, args)
        return s_next

    out = jax.lax.while_loop(cond, body, state0)
    return LMResult(
        cameras=out["cameras"],
        points=out["points"],
        cost=out["cost"],
        initial_cost=cost0,
        iterations=out["k"],
        accepted=out["accepted"],
        region=out["region"],
        v=out["v"],
        stopped=out["stop"],
    )


