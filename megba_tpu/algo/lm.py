"""Levenberg-Marquardt trust-region outer loop.

TPU-native replacement for the reference's LMAlgo::solveCUDA
(src/algo/lm_algo.cu:139-223): the same algorithm — damp, solve the Schur
system, test ||dx|| <= eps2(||x|| + eps1), apply, gain ratio rho from the
linearised cost Sum(J dx + e)^2, accept (relinearise, region /= max(1/3,
1-(2 rho - 1)^3), stop when ||g||_inf <= eps1) or reject (region /= v,
v *= 2) — but as a single jitted `lax.while_loop`.

The reference's trickiest machinery disappears in functional form: its
backup/rollback device copies (base_edge.cu:17-44,
schur_LM_linear_system.cu:187-209 — the README.md:15 changelog records a
rollback-correctness bug here) become "carry the old pytree instead of
the new one", and the damping save/restore (recoverDiag) is a pure
function of the undamped blocks.  Each LM iteration runs entirely
on-device: no host-blocking residual-norm or dot reductions
(lm_algo.cu:25-58 syncs the host ~6 times per iteration).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from megba_tpu.analysis.retrace import note_trace, static_key
from megba_tpu.common import (
    ComputeKind,
    PrecondKind,
    PreconditionerKind,
    ProblemOption,
    SolveStatus,
)
from megba_tpu.linear_system.builder import (
    SchurSystem,
    build_schur_system,
    weight_system_inputs,
)
from megba_tpu.observability.emit import (
    emit_verbose_iteration,
    next_verbose_token,
)
from megba_tpu.observability.trace import SolveTrace
from megba_tpu.ops.accum import comp_sum, comp_sum_sq
from megba_tpu.ops.robust import RobustKind, robustify
from megba_tpu.solver.pcg import HI, plain_pcg_solve, schur_pcg_solve

_TINY = 1e-30

# Verbose-line emission moved to observability/emit.py (the single home
# of human-readable solver output); this alias keeps the historical
# import path working.
_next_verbose_token = next_verbose_token


def initial_forcing_eta(eta_min, eta_max, dtype):
    """Eisenstat-Walker start: half the RHS energy removed is plenty for
    the first (least accurate) linearization, never looser than the cap.
    Shared by the BA and PGO loops."""
    return jnp.clip(
        jnp.minimum(eta_max, jnp.asarray(0.5, dtype)), eta_min, None)


def eisenstat_walker_eta(eta_prev, cost_new, cost_prev, rho, accept,
                         eta_min, eta_max, dtype):
    """One Eisenstat-Walker choice-2 forcing update (gamma=0.9, alpha=2).

    Costs are squared residual norms, so the cost ratio IS the norm
    ratio squared.  Safeguarded against over-tightening while the
    previous eta was still loose; loosened when the gain ratio says the
    linear model is trustworthy; tightened on reject (the failed step
    may be the inexact solve's fault, and the shrunken region makes the
    next system cheaper anyway).  Clamped to [eta_min, eta_max].  The
    ONE home of the forcing schedule — the BA and PGO loops both call
    it, so a tuning change can never leave them on different schedules.
    """
    ratio2 = cost_new / jnp.maximum(cost_prev, jnp.asarray(_TINY, dtype))
    eta_ew = 0.9 * ratio2
    safeguard = 0.9 * eta_prev * eta_prev
    eta_ew = jnp.where(safeguard > 0.1,
                       jnp.maximum(eta_ew, safeguard), eta_ew)
    eta_ew = jnp.where(rho > 0.75, 2.0 * eta_ew, eta_ew)
    return jnp.where(accept,
                     jnp.clip(eta_ew, eta_min, eta_max),
                     jnp.maximum(0.25 * eta_prev, eta_min))


def derive_status(*, stopped, accepted, recoveries, fatal):
    """Termination status code (common.SolveStatus), computed on device.

    Shared by the BA and PGO loops and re-derived by the chunked driver
    from whole-solve aggregates.  Priority: a fatal bail-out trumps
    everything; any contained recovery marks the solve `recovered`
    (callers should treat the result as valid but re-validate inputs);
    otherwise the stop flag separates `converged` from budget
    exhaustion, and zero accepted steps downgrade the latter to
    `stalled`.
    """
    status = jnp.where(
        stopped, jnp.int32(SolveStatus.CONVERGED),
        jnp.where(jnp.asarray(accepted) > 0,
                  jnp.int32(SolveStatus.MAX_ITER),
                  jnp.int32(SolveStatus.STALLED)))
    status = jnp.where(jnp.asarray(recoveries) > 0,
                       jnp.int32(SolveStatus.RECOVERED), status)
    return jnp.where(fatal, jnp.int32(SolveStatus.FATAL_NONFINITE), status)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LMResult:
    """Final state + diagnostics of one LM solve."""

    cameras: jax.Array
    points: jax.Array
    cost: jax.Array  # final accepted cost Sum e^2
    initial_cost: jax.Array
    iterations: jax.Array  # LM iterations executed
    accepted: jax.Array  # number of accepted steps
    pcg_iterations: jax.Array  # total PCG iterations across the solve
    region: jax.Array  # final trust region
    v: jax.Array  # final reject back-off factor (resume state)
    stopped: jax.Array  # True when a convergence criterion fired
    # Per-iteration convergence history ([max_iter] arrays masked by
    # `iterations`), recorded on-device inside the while_loop — see
    # observability/trace.py.  None only for results built by legacy
    # constructors that predate the trace.
    trace: Optional[SolveTrace] = None
    # Warm-start resume state: the last ACCEPTED step (the same layout
    # `cameras` uses — feature-major here, edge-major after flat_solve's
    # boundary transpose).  Populated only under
    # SolverOption.warm_start; the chunked/checkpointed drivers thread
    # it back in as `initial_dx` so warm starts survive chunk
    # boundaries.
    dx_cam: Optional[jax.Array] = None
    # Termination semantics (robustness layer): a common.SolveStatus
    # code (int32 scalar, derive_status) and the number of contained
    # fault recoveries the guards performed (0 with guards off).  None
    # only on results built by legacy constructors.
    status: Optional[jax.Array] = None
    recoveries: Optional[jax.Array] = None


def lm_solve(
    residual_jac_fn: Callable,
    cameras: jax.Array,
    points: jax.Array,
    obs: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    mask: jax.Array,
    option: ProblemOption,
    sqrt_info: Optional[jax.Array] = None,
    cam_fixed: Optional[jax.Array] = None,
    pt_fixed: Optional[jax.Array] = None,
    axis_name: Optional[str] = None,
    verbose: bool = False,
    cam_sorted: bool = False,
    plans=None,
    initial_region=None,
    initial_v=None,
    verbose_token=None,
    initial_dx=None,
    fault_plan=None,
    cluster_plan=None,
    tile_plan=None,
) -> LMResult:
    """Run the LM loop to convergence.  Jit/shard_map-compatible.

    FEATURE-MAJOR contract (core/fm.py): cameras [cd, Nc], points
    [pd, Np], obs [od, nE], sqrt_info [od*od, nE];
    `residual_jac_fn(cam_rows, pt_rows, obs) -> (r, Jc, Jp)` is the
    row-form engine from ops.residuals.  Edge-axis arrays (obs, cam_idx,
    pt_idx, mask, sqrt_info) may be shard-local when `axis_name` names a
    mesh axis; cameras/points are replicated.

    `initial_region`/`initial_v` override the trust-region start state —
    the resume hook used by utils.checkpoint / solve_checkpointed.
    `initial_dx` ([cd, Nc] rows) seeds the warm-start carry under
    SolverOption.warm_start (the cross-chunk resume hook); ignored
    otherwise.

    `plans` (ops/segtiles.DualPlans) turns on the scatter-free tiled
    path: edge arrays must be in the cam plan's slot order (the lowering
    in solve.py arranges this); internally Jp is carried in PT-slot
    order so both Hessian sides and both coupling products reduce over
    sorted block-aligned segments.

    BATCH-AXIS CONTRACT (serving layer): this loop is `jax.vmap`-safe
    over a leading problem axis on every array operand — the carry is a
    pure pytree of per-problem values (no host scalars, no cross-lane
    reductions when `axis_name is None`), so JAX's while_loop batching
    rule gives per-lane convergence masking for free: the lifted
    predicate keeps the loop running while ANY lane is live, and a lane
    whose `cond` has cleared freezes BITWISE (per-lane select on the
    carry) while its batch-mates keep iterating.  Each lane's
    trajectory is a function of its own slice only; `derive_status`,
    the trace and the final scalars all come back per lane.
    `serving/compile_pool._build_batched_solve` is the production
    consumer; verbose emission is the one vmap-hostile feature (host
    callback), so batched programs run `verbose=False`.

    `cluster_plan` (ops/segtiles.DeviceClusterPlan, or
    DeviceMultiLevelPlan for the MULTILEVEL hierarchy) is the
    host-planned camera-cluster coarse space consumed by the
    TWO_LEVEL/MULTILEVEL preconditioners (solver/precond.py); its
    per-edge `pc_slot` stream is in this call's edge order
    (shard-local when `axis_name` names a mesh axis), everything else
    replicated.  Required when `SolverOption.precond` is TWO_LEVEL or
    MULTILEVEL, ignored otherwise — the flat_solve lowering threads it
    automatically.

    `tile_plan` (ops/segtiles.DeviceCameraTilePlan) arms the 2-D mesh
    matvec (solver/pcg.make_matvec_2d): `axis_name` must then be the
    (EDGE_AXIS, CAM_AXIS) tuple — every existing psum site reduces over
    the tuple (the whole world) unchanged, while the PCG body's matvec
    runs the subgroup-scoped tiled pipeline.  The flat_solve 2-D
    lowering threads it automatically; ignored on the 1-D mesh.

    `fault_plan` (robustness.faults.FaultPlan, edge_nan already in this
    call's edge order) injects deterministic faults at the residual /
    linear-system boundary — the CI harness for the RobustOption guards.
    `option.robust_option.guards` arms on-device fault containment: a
    non-finite step (trial cost, dx, or PCG residual energy) is rolled
    back bitwise (the carry already holds the last accepted state), the
    system is relinearised at the rolled-back point, the trust region is
    divided by `damping_inflation`, and after more than `max_recoveries`
    consecutive failures the loop bails out with
    SolveStatus.FATAL_NONFINITE.  Detection reads only replicated,
    already-psum-reduced scalars, so the sharded program gains no
    collectives; with nothing failing every selected value is bitwise
    identical to the unguarded solve.
    """
    # Retrace sentinel (analysis/retrace.py): note_trace counts only
    # under an active jax trace (eager lm_solve calls are not
    # compilations), so the count equals the number of LM-program
    # compilations for this configuration+signature.
    note_trace("algo.lm_solve", cameras, points, obs, cam_idx, pt_idx,
               static=static_key(residual_jac_fn, option, axis_name,
                                 verbose, cam_sorted))
    if tile_plan is not None and not (
            isinstance(axis_name, (tuple, list)) and len(axis_name) == 2):
        # The 2-D tiled matvec needs the (EDGE_AXIS, CAM_AXIS) tuple to
        # scope its subgroup collectives; on a 1-D mesh (or single
        # device) the plan is documented as ignored — dropping it here
        # keeps that true instead of crashing in make_matvec_2d's
        # axis-tuple unpack.
        tile_plan = None
    num_cameras = cameras.shape[1]
    num_points = points.shape[1]
    algo_opt = option.algo_option
    solver_opt = option.solver_option
    compute_kind = option.compute_kind
    robust_opt = option.robust_option
    guards = robust_opt.guards

    def psum(x):
        return jax.lax.psum(x, axis_name) if axis_name is not None else x

    robust = option.robust_kind
    robust_delta = option.robust_delta

    def linearize(cams, pts, k=0):
        # named_scope: zero runtime cost, but the residual+Jacobian ops
        # carry a navigable label in trace_profile output
        # (TensorBoard/Perfetto) instead of dissolving into fused soup.
        with jax.named_scope("megba.residual_jacobian"):
            r, Jc, Jp = residual_jac_fn(jnp.take(cams, cam_idx, axis=1),
                                        jnp.take(pts, pt_idx, axis=1), obs)
            r, Jc, Jp = weight_system_inputs(
                r, Jc, Jp, cam_idx, pt_idx, mask, sqrt_info, cam_fixed,
                pt_fixed)
        if fault_plan is not None:
            # Seeded fault (robustness/faults.py): poison AFTER masking
            # so the injection cannot be laundered away by padding, and
            # stamp the call with the LM iteration whose system it
            # produces (the pre-loop linearisation shares stamp 0 with
            # iteration 0's evaluations).
            from megba_tpu.robustness.faults import poison_residuals

            r = poison_residuals(r, fault_plan, k)
        # Costs use compensated f32 sums (ops/accum.py): at BAL-Final
        # scale (~58M terms) a plain f32 sum's O(n*eps) error would flip
        # accept/reject decisions near convergence; the reference gets
        # this accuracy from f64 cuBLAS dots (lm_algo.cu:25-51).
        if robust == RobustKind.NONE:
            wcost = psum(comp_sum_sq(r))
            cost = wcost
        else:
            # IRLS reweighting (ops/robust.py); the system is built from
            # the weighted quantities, the accept test uses Sum rho, the
            # quadratic model is measured from the weighted norm.
            r, Jc, Jp, rho_e = robustify(r, Jc, Jp, robust, robust_delta)
            cost = psum(comp_sum(rho_e))
            wcost = psum(comp_sum_sq(r))
        if plans is not None:
            # Carry Jp in PT-slot order from here on: the point-side
            # build and both coupling products consume it there (one
            # cross permute per linearisation instead of one per use).
            Jp = plans.to_pt(Jp)
        system = build_schur_system(
            r, Jc, Jp, cam_idx, pt_idx, num_cameras, num_points,
            compute_kind=compute_kind, axis_name=axis_name,
            cam_fixed=cam_fixed, pt_fixed=pt_fixed, cam_sorted=cam_sorted,
            plans=plans)
        if fault_plan is not None:
            # Linear-system boundary fault: Schur-block indefiniteness
            # (chosen Hll blocks negated while the window is open).
            from megba_tpu.robustness.faults import poison_system

            system = poison_system(system, fault_plan, k)
        return r, Jc, Jp, system, cost, wcost

    r0, Jc0, Jp0, system0, cost0, wcost0 = linearize(
        cameras, points, jnp.int32(0))

    dtype = cameras.dtype
    forcing = solver_opt.forcing
    warm_start = solver_opt.warm_start
    # eta_k is a NORM-relative forcing term; the PCG threshold is on the
    # residual ENERGY rho, so eta rides squared into the solver.  With
    # forcing on, `tol` is the eta cap (SolverOption docs).
    eta_min_c = jnp.asarray(solver_opt.eta_min, dtype)
    eta_max_c = jnp.asarray(solver_opt.tol, dtype)
    state0 = dict(
        k=jnp.int32(0),
        accepted=jnp.int32(0),
        pcg_total=jnp.int32(0),
        cameras=cameras,
        points=points,
        r=r0,
        Jc=Jc0,
        Jp=Jp0,
        system=system0,
        cost=cost0,
        wcost=wcost0,
        region=jnp.asarray(
            algo_opt.initial_region if initial_region is None else initial_region,
            dtype),
        v=jnp.asarray(2.0 if initial_v is None else initial_v, dtype),
        stop=jnp.bool_(False),
        # Fixed-size on-device history; one .at[k].set per field per
        # iteration, no host traffic (observability/trace.py).
        trace=SolveTrace.empty(algo_opt.max_iter, dtype),
    )
    if forcing:
        state0["eta"] = initial_forcing_eta(eta_min_c, eta_max_c, dtype)
    if warm_start:
        dx0_cam = (jnp.zeros_like(cameras) if initial_dx is None
                   else jnp.asarray(initial_dx, dtype))
        state0["dx0"] = (dx0_cam if option.use_schur
                         else (dx0_cam, jnp.zeros_like(points)))
    if guards:
        # Fault-containment carry: consecutive-failure streak, total
        # contained recoveries, and the fatal bail-out flag.
        state0["fail_streak"] = jnp.int32(0)
        state0["recoveries"] = jnp.int32(0)
        state0["fatal"] = jnp.bool_(False)

    def cond(s):
        return (s["k"] < algo_opt.max_iter) & (~s["stop"])

    if (option.use_schur and cluster_plan is None
            and solver_opt.precond in (PrecondKind.TWO_LEVEL,
                                       PrecondKind.MULTILEVEL)):
        raise ValueError(
            f"SolverOption.precond={solver_opt.precond.name} needs a "
            "camera-cluster plan operand: solve through flat_solve (which "
            "plans + caches it) or pass cluster_plan="
            "ops.segtiles.device_cluster_plan(...) / "
            "device_multilevel_plan(...)")

    pcg_solve = schur_pcg_solve if option.use_schur else plain_pcg_solve

    def body(s):
        # Per-iteration tolerance: the carried eta_k (squared — see
        # above) under forcing, the static option otherwise.  eta_k and
        # the warm-start carry are replicated across shards (derived
        # from psum-reduced costs and the replicated PCG output), so
        # they ride shard_map like the rest of the LM state.
        tol_k = s["eta"] * s["eta"] if forcing else solver_opt.tol
        tol_rel = True if forcing else solver_opt.tol_relative
        with jax.named_scope("megba.pcg"):
            pcg = pcg_solve(
                s["system"], s["Jc"], s["Jp"], cam_idx, pt_idx, s["region"],
                max_iter=solver_opt.max_iter, tol=tol_k,
                refuse_ratio=solver_opt.refuse_ratio,
                tol_relative=tol_rel,
                compute_kind=compute_kind, axis_name=axis_name,
                mixed_precision=option.mixed_precision_pcg,
                bf16=solver_opt.bf16,
                bf16_collectives=solver_opt.bf16_collectives,
                fused_kernels=solver_opt.fused_kernels,
                cam_sorted=cam_sorted,
                preconditioner=solver_opt.preconditioner, plans=plans,
                x0=s["dx0"] if warm_start else None,
                guard=guards,
                max_restarts=robust_opt.pcg_max_restarts if guards else 0,
                precond=solver_opt.precond,
                neumann_order=solver_opt.neumann_order,
                cluster_plan=cluster_plan, cam_fixed=cam_fixed,
                smooth_omega=solver_opt.smooth_omega,
                tile_plan=tile_plan)
        dx_cam, dx_pt = pcg.dx_cam, pcg.dx_pt

        # ||dx|| <= eps2 (||x|| + eps1)  -> converged, don't apply
        # (reference lm_algo.cu:171-179).
        dx_norm = jnp.sqrt(jnp.sum(dx_cam * dx_cam) + jnp.sum(dx_pt * dx_pt))
        x_norm = jnp.sqrt(jnp.sum(s["cameras"] ** 2) + jnp.sum(s["points"] ** 2))
        converged = dx_norm <= algo_opt.epsilon2 * (x_norm + algo_opt.epsilon1)

        cams_new = s["cameras"] + dx_cam
        pts_new = s["points"] + dx_pt

        # Gain-ratio denominator: linearised cost at dx minus old cost
        # (the JdxpF kernel, lm_algo.cu:60-126).  J dx + e, row form:
        od = s["r"].shape[0]
        cd = dx_cam.shape[0]
        pd = dx_pt.shape[0]
        if plans is not None:
            from megba_tpu.ops.segtiles import coupling_expand

            uk = plans.use_kernels
            # Fused (gather + J.dx) on each side; Jp is PT-ordered, so
            # its [od] product rows hop to cam order for the final sum.
            jc_dx = coupling_expand(dx_cam, s["Jc"], plans.cam, cd, uk)
            jp_dx = plans.to_cam(
                coupling_expand(dx_pt, s["Jp"], plans.pt, pd, uk))
            jdx = (jc_dx + jp_dx + s["r"]).astype(s["r"].dtype)
        else:
            dxc_e = jnp.take(dx_cam, cam_idx, axis=1)  # [cd, nE]
            dxp_e = jnp.take(dx_pt, pt_idx, axis=1)  # [pd, nE]
            jdx = jnp.stack([
                sum(s["Jc"][o * cd + a] * dxc_e[a] for a in range(cd))
                + sum(s["Jp"][o * pd + b] * dxp_e[b] for b in range(pd))
                + s["r"][o]
                for o in range(od)
            ])
        predicted = psum(comp_sum_sq(jdx))
        # The quadratic model is in the (robust-)weighted residuals; its
        # decrease is measured from the carried weighted norm, while
        # accept uses the true (robustified) cost.  For RobustKind.NONE
        # both equal Sum r^2 and this reduces to the reference formula.
        # The linearised decrease is <= 0 for any useful step; clamp
        # sign-preservingly so an underflowing denominator can't flip
        # rho's sign and collapse the trust region on an accepted step.
        denominator = jnp.minimum(predicted - s["wcost"], -_TINY)

        # Trial-point cost WITHOUT paying for Jacobians or the Hessian
        # build: only the cost outputs of this call are used, so XLA's
        # dead-code elimination prunes the J/system computations from the
        # loop body.  This mirrors the reference's cheap second forward()
        # (residual jets only feed the norm unless the step is accepted,
        # lm_algo.cu:183-189,209-214).
        _, _, _, _, cost_new, wcost_new = linearize(cams_new, pts_new,
                                                    s["k"])
        rho = (cost_new - s["cost"]) / denominator

        # Reference lm_algo.cu breaks BEFORE edges.update() when the
        # step-size test fires — a converged step is never applied.
        accept = (cost_new < s["cost"]) & (~converged)
        recover = jnp.bool_(False)
        if guards:
            # Fault containment.  Every detector input is a replicated
            # scalar that already rode the existing psums (NaN
            # propagates through them), so the sharded program gains no
            # collectives.  `step_bad`: the trial cost, the step, or the
            # PCG's final residual energy left the finite range — the
            # latter catches a poisoned CARRIED system, whose zero-
            # iteration PCG exit would otherwise masquerade as a
            # converged dx = 0.  A `broken` PCG (breakdown-restart
            # budget exhausted — the inner solver declared the operator
            # sick) is a step failure too: its carried system needs the
            # same rollback + relinearisation + damping inflation.
            step_bad = ~(jnp.isfinite(cost_new) & jnp.isfinite(dx_norm)
                         & jnp.isfinite(pcg.rho)) | pcg.broken
            converged = converged & ~step_bad
            # Adoption heals a non-finite CARRIED cost (a fault during
            # the linearisation that produced it — e.g. a poisoned
            # chunk-resume relinearisation): once the step evaluates
            # finite again, accept it unconditionally so the carried
            # cost/wcost rejoin the finite regime.
            adopt = (~jnp.isfinite(s["cost"])) & ~step_bad & ~converged
            accept = (accept & ~step_bad) | adopt
            # Recovery = rollback (the reject path already keeps the
            # last accepted parameters bitwise) + relinearisation at the
            # rolled-back point + damping inflation, counted below.
            recover = step_bad

        # Relinearise on accept — and, with guards armed, on a recovery
        # (at the ROLLED-BACK parameters, healing a poisoned carried
        # r/J/system).  lax.cond: the predicate and the selected
        # parameters are replicated across shards, so all replicas take
        # the same branch and the psums inside stay collective-safe.
        # The reference's reject path likewise skips buildLinearSystem
        # (lm_algo.cu:206-214); round 1 paid a full rebuild per rejected
        # step.
        relin = accept | recover

        def _relinearize(_):
            r_n, Jc_n, Jp_n, system_n, _, _ = linearize(
                jnp.where(accept, cams_new, s["cameras"]),
                jnp.where(accept, pts_new, s["points"]), s["k"])
            return r_n, Jc_n, Jp_n, system_n

        def _keep_old(_):
            return s["r"], s["Jc"], s["Jp"], s["system"]

        with jax.named_scope("megba.lm_accept_reject"):
            r_n, Jc_n, Jp_n, system_n = jax.lax.cond(
                relin, _relinearize, _keep_old, None)

        g_inf = jnp.maximum(jnp.max(jnp.abs(system_n.g_cam)),
                            jnp.max(jnp.abs(system_n.g_pt)))
        region_accept = s["region"] / jnp.maximum(
            jnp.asarray(1.0 / 3.0, dtype), 1.0 - (2.0 * rho - 1.0) ** 3)
        if guards:
            # An adopted (carry-healing) accept has rho = NaN by
            # construction (its denominator ran through the non-finite
            # carried cost); the region must not inherit it.
            region_accept = jnp.where(jnp.isfinite(rho), region_accept,
                                      s["region"])
        stop_accept = g_inf <= algo_opt.epsilon1

        # --- reject branch values ---
        region_reject = s["region"] / s["v"]
        v_reject = s["v"] * 2.0
        if guards:
            # A recovery inflates damping by the configured factor
            # instead of the reject back-off (region ∝ 1/damping), and
            # leaves the back-off factor untouched.
            inflation = jnp.asarray(robust_opt.damping_inflation, dtype)
            region_reject = jnp.where(recover, s["region"] / inflation,
                                      region_reject)
            v_reject = jnp.where(recover, s["v"], v_reject)

        def pick(new, old):
            return jax.tree_util.tree_map(
                lambda a, b: jnp.where(accept, a, b), new, old)

        if forcing:
            with jax.named_scope("megba.lm_forcing"):
                eta_next = eisenstat_walker_eta(
                    s["eta"], cost_new, s["cost"], rho, accept,
                    eta_min_c, eta_max_c, dtype)
            if guards:
                # An adopted accept feeds NaN cost ratios through the
                # forcing update; restart the schedule at the cap
                # rather than poisoning every later tolerance.
                eta_next = jnp.where(jnp.isfinite(eta_next), eta_next,
                                     eta_max_c)

        stop = converged | (accept & stop_accept)
        if guards:
            fail_streak = jnp.where(recover, s["fail_streak"] + 1,
                                    jnp.int32(0))
            fatal = fail_streak > robust_opt.max_recoveries
            stop = stop | fatal
        # Robustness trace fields stay None (zero-fill, zero update ops)
        # with guards off; the (enum-coded per-level) precond-fallback
        # count is recorded whenever a preconditioner with a fallback
        # ladder is live — the SCHUR_DIAG block diagonal or any
        # non-JACOBI operator family (solver/precond.py).
        trace_robust = dict(
            precond_fallback=(
                pcg.precond_fallback
                if (solver_opt.preconditioner == PreconditionerKind.SCHUR_DIAG
                    or solver_opt.precond != PrecondKind.JACOBI) else None))
        if guards:
            trace_robust.update(recovery=recover,
                                pcg_breakdown=pcg.breakdowns)
        s_next = dict(
            k=s["k"] + 1,
            accepted=s["accepted"] + jnp.where(accept, 1, 0).astype(jnp.int32),
            pcg_total=s["pcg_total"] + pcg.iterations,
            cameras=pick(cams_new, s["cameras"]),
            points=pick(pts_new, s["points"]),
            # r/Jc/Jp/system already selected by the cond above.
            r=r_n,
            Jc=Jc_n,
            Jp=Jp_n,
            system=system_n,
            cost=jnp.where(accept, cost_new, s["cost"]),
            wcost=jnp.where(accept, wcost_new, s["wcost"]),
            region=jnp.where(accept, region_accept, region_reject),
            v=jnp.where(accept, jnp.asarray(2.0, dtype), v_reject),
            stop=stop,
            # Every recorded value is replicated across shards (costs,
            # g_inf and rho come out of psum-reduced quantities; the
            # trust-region state is carried replicated), so the trace
            # rides shard_map's out_specs=P() unchanged.  `cost` records
            # the TRIAL cost — the same observable the verbose line
            # prints, which the telemetry parity tests pin.
            trace=s["trace"].record(
                s["k"], cost=cost_new, grad_inf_norm=g_inf,
                trust_region=s["region"], rho=rho, accept=accept,
                pcg_iters=pcg.iterations,
                pcg_eta=(s["eta"] if forcing
                         else jnp.asarray(solver_opt.tol, dtype)),
                pcg_r0_ratio=pcg.r0_ratio.astype(dtype),
                **trace_robust),
        )
        if guards:
            s_next["fail_streak"] = fail_streak
            s_next["recoveries"] = s["recoveries"] + recover.astype(jnp.int32)
            s_next["fatal"] = s["fatal"] | fatal
        if forcing:
            s_next["eta"] = eta_next
        if warm_start:
            # Seed the NEXT solve with this iteration's step only when
            # it was accepted; a reject shrinks the trust region (the
            # damped system changes sharply), so the carry is zeroed —
            # bitwise identical to a cold start.
            new_dx = (dx_cam if option.use_schur else (dx_cam, dx_pt))
            s_next["dx0"] = jax.tree_util.tree_map(
                lambda d: jnp.where(accept, d, jnp.zeros_like(d)), new_dx)
        if verbose:
            token = (jnp.int32(0) if verbose_token is None
                     else jnp.asarray(verbose_token, jnp.int32))
            emit_verbose_iteration(token, s["k"], cost_new, accept,
                                   pcg.iterations, axis_name)
        return s_next

    # Under vmap (serving's batched mega-solve) this while_loop batches
    # per-lane: cond lifts to any(pred) and the body's new carry is
    # selected lane-wise against the old one, so a stopped lane costs
    # its share of the batched body's FLOPs but its VALUES are frozen
    # bitwise until the last lane finishes.
    out = jax.lax.while_loop(cond, body, state0)
    dx_final = None
    if warm_start:
        dx_final = out["dx0"] if option.use_schur else out["dx0"][0]
    recoveries = out["recoveries"] if guards else jnp.int32(0)
    fatal = out["fatal"] if guards else jnp.bool_(False)
    status = derive_status(stopped=out["stop"], accepted=out["accepted"],
                           recoveries=recoveries, fatal=fatal)
    return LMResult(
        cameras=out["cameras"],
        points=out["points"],
        cost=out["cost"],
        initial_cost=cost0,
        iterations=out["k"],
        accepted=out["accepted"],
        pcg_iterations=out["pcg_total"],
        region=out["region"],
        v=out["v"],
        stopped=out["stop"],
        trace=out["trace"],
        dx_cam=dx_final,
        status=status,
        recoveries=recoveries,
    )


