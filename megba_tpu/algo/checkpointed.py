"""Preemption-safe solve driver: LM in chunks with on-disk snapshots.

Capability the reference does NOT have (SURVEY.md §5.3/5.4: no failure
recovery, no disk checkpointing — a crash loses the job).  The jitted LM
loop runs in chunks of `checkpoint_every` iterations; between chunks the
full resume state (parameters + trust region + back-off factor +
iteration count) is written atomically, and `solve_checkpointed` resumes
from an existing snapshot transparently — the TPU-pod preemption norm.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

from megba_tpu.algo.lm import LMResult
from megba_tpu.common import ProblemOption
from megba_tpu.utils.checkpoint import load_state, save_state


def _topology_fingerprint(cameras, points, cam_idx, pt_idx) -> np.ndarray:
    """[Nc, Np, nE, blake2b(cam_idx), blake2b(pt_idx)] as int64 — cheap,
    order-sensitive identity of the problem graph."""
    import hashlib

    def h(a):
        digest = hashlib.blake2b(
            np.ascontiguousarray(np.asarray(a, np.int32)).tobytes(),
            digest_size=8).digest()
        return int.from_bytes(digest, "little", signed=True)

    return np.asarray(
        [cameras.shape[0], points.shape[0], np.asarray(cam_idx).shape[0],
         h(cam_idx), h(pt_idx)], np.int64)


def solve_checkpointed(
    residual_jac_fn,
    cameras,
    points,
    obs,
    cam_idx,
    pt_idx,
    option: ProblemOption,
    checkpoint_path: str,
    checkpoint_every: int = 5,
    verbose: bool = False,
    **solve_kwargs,
) -> LMResult:
    """Run the LM solve, snapshotting every `checkpoint_every` iterations.

    If `checkpoint_path` exists, resumes from it (same problem assumed).
    Runs through the shared flat_solve pipeline, so all chunks of the
    same configuration reuse ONE compiled program (the resume state rides
    as dynamic operands).  Extra kwargs flow to `solve.flat_solve`
    (sqrt_info, cam_fixed, pt_fixed, use_tiled...).
    """
    from megba_tpu.solve import flat_solve
    if checkpoint_every < 1:
        raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
    total = option.algo_option.max_iter
    done = 0
    region = None
    v = None
    accepted_total = 0
    pcg_total = 0
    first_cost = None
    already_stopped = False

    # Problem identity guard: a stale/foreign snapshot with mismatched
    # shapes would otherwise be resumed silently (jnp.take clamps
    # out-of-range indices instead of erroring) and yield garbage.  The
    # graph topology is summarised by a cheap order-sensitive hash of the
    # index arrays, not just the counts.
    topo = _topology_fingerprint(cameras, points, cam_idx, pt_idx)

    if os.path.exists(checkpoint_path):
        st = load_state(checkpoint_path)
        saved_topo = st.get("extra_topology")
        if saved_topo is None or not np.array_equal(np.asarray(saved_topo), topo):
            raise ValueError(
                f"checkpoint {checkpoint_path!r} was written for a different "
                f"problem (topology fingerprint "
                f"{None if saved_topo is None else np.asarray(saved_topo).tolist()} "
                f"!= {topo.tolist()}); refusing to resume — delete the "
                "snapshot or point checkpoint_path elsewhere")
        cameras = jnp.asarray(st["cameras"], cameras.dtype)
        points = jnp.asarray(st["points"], points.dtype)
        region = float(st["region"])
        v = float(st["extra_v"])
        done = int(st["iteration"])
        accepted_total = int(st.get("extra_accepted", 0))
        pcg_total = int(st.get("extra_pcg", 0))
        if "extra_first_cost" in st:
            first_cost = jnp.asarray(st["extra_first_cost"])
        already_stopped = bool(st.get("extra_stopped", False))

    result = None
    while not already_stopped and done < total:
        chunk = min(checkpoint_every, total - done)
        chunk_option = dataclasses.replace(
            option,
            algo_option=dataclasses.replace(option.algo_option, max_iter=chunk),
        )
        result = flat_solve(
            residual_jac_fn, cameras, points, obs, cam_idx, pt_idx,
            chunk_option, verbose=verbose,
            initial_region=region, initial_v=v, **solve_kwargs)
        cameras, points = result.cameras, result.points
        region = result.region
        v = result.v
        if first_cost is None:
            first_cost = result.initial_cost
        accepted_total += int(result.accepted)
        pcg_total += int(result.pcg_iterations)
        ran = int(result.iterations)
        done += ran
        stopped = bool(result.stopped) or ran < chunk
        save_state(
            checkpoint_path, np.asarray(cameras), np.asarray(points),
            region=float(region), cost=float(result.cost), iteration=done,
            extra={"v": np.asarray(float(v)),
                   "accepted": np.asarray(accepted_total),
                   "pcg": np.asarray(pcg_total),
                   "first_cost": np.asarray(float(first_cost)),
                   "stopped": np.asarray(stopped),
                   "topology": topo})
        if stopped:
            break  # converged (possibly exactly on the chunk boundary)

    if result is None:  # resumed at/past total (or converged): evaluate state
        result = flat_solve(
            residual_jac_fn, cameras, points, obs, cam_idx, pt_idx,
            dataclasses.replace(
                option,
                algo_option=dataclasses.replace(option.algo_option, max_iter=0)),
            initial_region=region, initial_v=v, verbose=verbose, **solve_kwargs)
        if first_cost is None:
            first_cost = result.initial_cost
        if already_stopped:
            result = dataclasses.replace(result, stopped=jnp.bool_(True))

    # Report whole-solve aggregates, not last-chunk ones.
    return dataclasses.replace(
        result,
        initial_cost=first_cost,
        iterations=jnp.asarray(done, jnp.int32),
        accepted=jnp.asarray(accepted_total, jnp.int32),
        pcg_iterations=jnp.asarray(pcg_total, jnp.int32),
    )
