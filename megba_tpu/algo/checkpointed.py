"""Preemption-safe solve drivers: LM in chunks with on-disk snapshots.

Capability the reference does NOT have (SURVEY.md §5.3/5.4: no failure
recovery, no disk checkpointing — a crash loses the job).  The jitted LM
loop runs in chunks of `checkpoint_every` iterations; between chunks the
full resume state (parameters + trust region + back-off factor +
iteration count) is written atomically, and the drivers resume from an
existing snapshot transparently — the TPU-pod preemption norm.

One generic chunk loop (`_run_chunked`) serves both model families:
`solve_checkpointed` (BA, through the shared flat_solve pipeline so all
chunks of one configuration reuse ONE compiled program) and
`solve_pgo_checkpointed` (SE(3) pose graphs — same property via
models/pgo's cached program; the resume state rides as dynamic
operands in both).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from megba_tpu.algo.lm import LMResult
from megba_tpu.common import ProblemOption
from megba_tpu.observability.trace import (
    TRACE_FIELDS,
    SolveTrace,
    trace_concat,
    trace_filler,
    trace_slice,
)
from megba_tpu.utils.checkpoint import load_state, save_state


def _topology_fingerprint(cameras, points, cam_idx, pt_idx) -> np.ndarray:
    """[Nc, Np, nE, blake2b(cam_idx), blake2b(pt_idx)] as int64 — cheap,
    order-sensitive identity of the problem graph."""
    import hashlib

    def h(a):
        digest = hashlib.blake2b(
            np.ascontiguousarray(np.asarray(a, np.int32)).tobytes(),
            digest_size=8).digest()
        return int.from_bytes(digest, "little", signed=True)

    return np.asarray(
        [cameras.shape[0], points.shape[0], np.asarray(cam_idx).shape[0],
         h(cam_idx), h(pt_idx)], np.int64)


def _replace(result, **fields):
    """dataclasses.replace / NamedTuple._replace, whichever applies."""
    if dataclasses.is_dataclass(result):
        return dataclasses.replace(result, **fields)
    return result._replace(**fields)


def _run_chunked(solve_chunk, params, dump_params, load_params, topo,
                 total, checkpoint_path, checkpoint_every,
                 world_size=1, process_index=0, elastic=None):
    """The shared chunk loop: resume, solve in chunks, snapshot, aggregate.

    `solve_chunk(params, max_iter, region, v, dx, done) -> (result,
    new_params)` runs up to `max_iter` LM iterations from `params` with
    the given trust-region resume state (None, None on a fresh start;
    `dx` is the warm-start resume state — the previous chunk's last
    accepted step — None when unknown or warm starts are off; `done` is
    the GLOBAL iteration the chunk starts at, so per-chunk operands like
    a FaultPlan window can be anchored in whole-solve iterations).
    `result` must expose cost / initial_cost / region / v / iterations /
    accepted / pcg_iterations / stopped.  `dump_params(params)` returns
    the two arrays the snapshot format stores; `load_params(st)` inverts
    it.

    `world_size`/`process_index` are stamped into every snapshot's
    schema-v3 world header; a resume at a DIFFERENT world size warns
    (never fails) and is recorded as a reshard on `elastic`.

    `elastic` (robustness.elastic.ElasticMonitor, already started)
    bounds every chunk dispatch: peers are liveness-checked at each
    chunk boundary and the dispatch itself runs under the collective
    watchdog — a dead or wedged peer surfaces as a typed `WorkerLost` /
    `CollectiveTimeout` within the budget instead of hanging the rank.
    The chunk whose dispatch died is simply never snapshotted, so the
    previous chunk's checksummed snapshot IS the coordinated-abort
    recovery line (resume_elastic continues from it).
    """
    if checkpoint_every < 1:
        raise ValueError(
            f"checkpoint_every must be >= 1, got {checkpoint_every}")

    def dispatch(label, *chunk_args):
        if elastic is None:
            return solve_chunk(*chunk_args)

        def _solve_chunk_sync():
            # jax dispatch is ASYNC: without the barrier the guarded
            # call would return un-materialized arrays and a peer-death
            # transport error would surface later, OUTSIDE the guard,
            # as an unclassified XlaRuntimeError at the first host read.
            # Blocking here keeps the whole chunk — dispatch AND
            # execution — inside the watchdog/liveness envelope.
            out = solve_chunk(*chunk_args)
            return jax.block_until_ready(out)

        elastic.check_peers(label=label)
        # grace_key = the chunk's iteration count (max_iter is the one
        # per-chunk STATIC, so it identifies the compiled program): the
        # first dispatch of each program — including a short final
        # chunk or the 0-iter evaluate — gets the compile grace.
        return elastic.guard(label, _solve_chunk_sync,
                             grace_key=("chunk", chunk_args[1]))
    done = 0
    region = None
    v = None
    dx = None
    accepted_total = 0
    pcg_total = 0
    recoveries_total = 0
    fatal_total = False
    first_cost = None
    already_stopped = False
    # Per-chunk trace slices (host numpy), stitched into one whole-solve
    # SolveTrace at the end — and persisted in the snapshot so a resumed
    # solve reports the same history a straight run would.
    trace_parts = []

    # Problem identity guard: a stale/foreign snapshot with mismatched
    # shapes would otherwise be resumed silently (jnp.take clamps
    # out-of-range indices instead of erroring) and yield garbage.  The
    # graph topology is summarised by a cheap order-sensitive hash of
    # the index arrays, not just the counts.
    if os.path.exists(checkpoint_path):
        st = load_state(checkpoint_path, expect_world_size=world_size)
        saved_ws = st.get("world_size")
        if (elastic is not None and saved_ws is not None
                and int(saved_ws) != int(world_size)):
            # Shrink-world resume through the driver directly (without
            # resume_elastic's own bookkeeping): still a reshard event.
            elastic.record_reshard(int(saved_ws), int(world_size))
        saved_topo = st.get("extra_topology")
        if saved_topo is None or not np.array_equal(
                np.asarray(saved_topo), topo):
            raise ValueError(
                f"checkpoint {checkpoint_path!r} was written for a "
                f"different problem (topology fingerprint "
                f"{None if saved_topo is None else np.asarray(saved_topo).tolist()} "
                f"!= {topo.tolist()}); refusing to resume — delete the "
                "snapshot or point checkpoint_path elsewhere")
        params = load_params(st)
        region = float(st["region"])
        v = float(st["extra_v"])
        done = int(st["iteration"])
        accepted_total = int(st.get("extra_accepted", 0))
        pcg_total = int(st.get("extra_pcg", 0))
        recoveries_total = int(st.get("extra_recoveries", 0))
        fatal_total = bool(st.get("extra_fatal", False))
        if "extra_first_cost" in st:
            first_cost = jnp.asarray(st["extra_first_cost"])
        already_stopped = bool(st.get("extra_stopped", False))
        if "extra_dx" in st:
            dx = np.asarray(st["extra_dx"])
        if "extra_trace_cost" in st:
            # Fields added after a snapshot was written get inert NaN
            # history for the pre-resume iterations (same contract as
            # pre-trace snapshots below).
            filler = trace_filler(
                int(np.asarray(st["extra_trace_cost"]).shape[0]))
            trace_parts.append(SolveTrace(**{
                f: (np.asarray(st[f"extra_trace_{f}"])
                    if f"extra_trace_{f}" in st else getattr(filler, f))
                for f in TRACE_FIELDS}))
        elif done:
            # Snapshot predates the trace: pad the unknowable pre-resume
            # iterations with inert NaN history so the stitched trace
            # still aligns index-for-index with `iterations`.
            trace_parts.append(trace_filler(done))

    result = None
    while not already_stopped and done < total:
        chunk = min(checkpoint_every, total - done)
        result, params = dispatch(
            f"chunk@iter{done}", params, chunk, region, v, dx, done)
        region = float(result.region)
        v = float(result.v)
        if getattr(result, "dx_cam", None) is not None:
            dx = np.asarray(result.dx_cam)
        if first_cost is None:
            first_cost = result.initial_cost
        accepted_total += int(result.accepted)
        pcg_total += int(result.pcg_iterations)
        if getattr(result, "recoveries", None) is not None:
            recoveries_total += int(result.recoveries)
        if getattr(result, "status", None) is not None:
            # Fatality is sticky across chunk boundaries: without this
            # the snapshot records only stopped=True, and a resumed
            # fatal solve would re-derive as recovered/converged.
            from megba_tpu.common import SolveStatus

            fatal_total = fatal_total or (
                int(result.status) == int(SolveStatus.FATAL_NONFINITE))
        ran = int(result.iterations)
        done += ran
        stopped = bool(result.stopped) or ran < chunk
        arr_a, arr_b = dump_params(params)
        extra = {"v": np.asarray(v),
                 "accepted": np.asarray(accepted_total),
                 "pcg": np.asarray(pcg_total),
                 "recoveries": np.asarray(recoveries_total),
                 "fatal": np.asarray(fatal_total),
                 "first_cost": np.asarray(float(first_cost)),
                 "stopped": np.asarray(stopped),
                 "topology": topo}
        if dx is not None:
            # Warm-start resume state (SolverOption.warm_start): the
            # last accepted step, threaded into the next chunk/resume.
            extra["dx"] = dx
        chunk_trace = getattr(result, "trace", None)
        if chunk_trace is not None:
            # Keep only the iterations this chunk actually ran, and
            # snapshot the accumulated history (tiny: a few scalars per
            # LM iteration) so resume preserves the full trace.
            trace_parts.append(trace_slice(chunk_trace, ran))
            acc = trace_concat(trace_parts)
            extra.update({f"trace_{f}": getattr(acc, f)
                          for f in TRACE_FIELDS})
        save_state(
            checkpoint_path, arr_a, arr_b,
            region=region, cost=float(result.cost), iteration=done,
            world_size=world_size, process_index=process_index,
            extra=extra)
        if stopped:
            break  # converged (possibly exactly on the chunk boundary)

    if result is None:  # resumed at/past total (or converged): evaluate
        result, params = dispatch(
            f"evaluate@iter{done}", params, 0, region, v, dx, done)
        if first_cost is None:
            first_cost = result.initial_cost
        if already_stopped:
            result = _replace(result, stopped=jnp.bool_(True))

    # Report whole-solve aggregates, not last-chunk ones.
    fields = dict(
        initial_cost=first_cost,
        iterations=jnp.asarray(done, jnp.int32),
        accepted=jnp.asarray(accepted_total, jnp.int32),
        pcg_iterations=jnp.asarray(pcg_total, jnp.int32),
    )
    if getattr(result, "trace", None) is not None:
        # The whole-solve history (chunks stitched back together); the
        # last chunk's raw [chunk] buffers alone would misreport a
        # resumed/chunked solve.
        fields["trace"] = trace_concat(trace_parts)
    if getattr(result, "status", None) is not None:
        # Whole-solve termination semantics: a fatal last chunk stays
        # fatal; recoveries in ANY chunk mark the solve recovered; the
        # converged/max_iter/stalled split re-derives from whole-solve
        # aggregates (the last chunk alone would call a resumed,
        # long-converged solve "stalled").
        from megba_tpu.algo.lm import derive_status
        from megba_tpu.common import SolveStatus

        # `fatal_total` covers chunks persisted before a resume; the
        # last-chunk check covers the in-process path (it is what set
        # fatal_total on the final loop pass anyway).
        fatal = fatal_total or (
            int(result.status) == int(SolveStatus.FATAL_NONFINITE))
        fields["status"] = derive_status(
            stopped=jnp.bool_(bool(result.stopped)),
            accepted=accepted_total,
            recoveries=recoveries_total,
            fatal=jnp.bool_(fatal))
        if getattr(result, "recoveries", None) is not None:
            fields["recoveries"] = jnp.asarray(recoveries_total, jnp.int32)
    return _replace(result, **fields)


def solve_checkpointed(
    residual_jac_fn,
    cameras,
    points,
    obs,
    cam_idx,
    pt_idx,
    option: ProblemOption,
    checkpoint_path: str,
    checkpoint_every: int = 5,
    verbose: bool = False,
    elastic=None,
    **solve_kwargs,
) -> LMResult:
    """Run the BA LM solve, snapshotting every `checkpoint_every` iters.

    If `checkpoint_path` exists, resumes from it (same problem assumed).
    Runs through the shared flat_solve pipeline, so all chunks of the
    same configuration reuse ONE compiled program (the resume state
    rides as dynamic operands).  Extra kwargs flow to `solve.flat_solve`
    (sqrt_info, cam_fixed, pt_fixed, use_tiled...).

    `elastic` (robustness.elastic.ElasticConfig or ElasticMonitor) arms
    the elastic-distribution contract for world>1 solves: this rank
    heartbeats, every chunk dispatch is watchdog-bounded, and a dead or
    wedged peer raises a typed `WorkerLost`/`CollectiveTimeout` at the
    chunk boundary — the latest snapshot is then the recovery line for
    `robustness.elastic.resume_elastic`.  When telemetry is on, each
    chunk's SolveReport carries the monitor's `elastic` counters.
    """
    from megba_tpu.robustness.elastic import ElasticMonitor
    from megba_tpu.solve import flat_solve

    monitor, owned = ElasticMonitor.ensure(elastic)
    cam_dtype = cameras.dtype
    pt_dtype = points.dtype
    # A seeded FaultPlan is anchored in GLOBAL iterations: each chunk
    # re-offsets it so local iteration 0 maps to the chunk's resume
    # point.  window/offset are dynamic operands, so the slide costs no
    # recompile.
    fault_plan = solve_kwargs.pop("fault_plan", None)

    def solve_chunk(params, max_iter, region, v, dx, done):
        cams, pts = params
        chunk_option = dataclasses.replace(
            option,
            algo_option=dataclasses.replace(
                option.algo_option, max_iter=max_iter))
        kwargs = dict(solve_kwargs)
        if fault_plan is not None:
            from megba_tpu.robustness.faults import with_offset

            kwargs["fault_plan"] = with_offset(fault_plan, done)
        if monitor is not None:
            # Telemetry context: the chunk's SolveReport line carries a
            # snapshot of the elastic ledger (fresh dict per chunk; the
            # aggregator keeps the last snapshot per monitor).
            kwargs["elastic_report"] = monitor.report_block()
        result = flat_solve(
            residual_jac_fn, cams, pts, obs, cam_idx, pt_idx,
            chunk_option, verbose=verbose,
            initial_region=region, initial_v=v, initial_dx=dx,
            **kwargs)
        return result, (result.cameras, result.points)

    try:
        return _run_chunked(
            solve_chunk,
            params=(cameras, points),
            dump_params=lambda p: (np.asarray(p[0]), np.asarray(p[1])),
            load_params=lambda st: (jnp.asarray(st["cameras"], cam_dtype),
                                    jnp.asarray(st["points"], pt_dtype)),
            topo=_topology_fingerprint(cameras, points, cam_idx, pt_idx),
            total=option.algo_option.max_iter,
            checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every,
            world_size=option.world_size,
            process_index=int(jax.process_index()),
            elastic=monitor,
        )
    finally:
        if owned:
            monitor.stop()


def solve_pgo_checkpointed(
    poses0,
    edge_i,
    edge_j,
    meas,
    option: ProblemOption,
    checkpoint_path: str,
    checkpoint_every: int = 5,
    verbose: bool = False,
    elastic=None,
    **solve_kwargs,
):
    """Preemption-safe chunked PGO solve (models/pgo.solve_pgo).

    Same contract as `solve_checkpointed`: chunks of `checkpoint_every`
    LM iterations, atomic snapshots of the full resume state between
    chunks, transparent resume after a topology-fingerprint check, and
    one cached compiled program across chunks (the trust-region state is
    a dynamic operand of models/pgo's program cache).  Extra kwargs flow
    to `solve_pgo` (sqrt_info, fixed...).  The pose table reuses the
    "cameras" slot of the shared snapshot format; "points" carries a
    placeholder.  `elastic` bounds chunk dispatches exactly as in
    `solve_checkpointed` (typed WorkerLost/CollectiveTimeout at chunk
    boundaries; the snapshot is the recovery line).  Unlike the BA
    driver there is no per-chunk `elastic_report` to attach: the PGO
    pipeline emits no SolveReport telemetry at all (see
    observability/report.py — the sink hangs off flat_solve only).
    """
    from megba_tpu.models.pgo import solve_pgo
    from megba_tpu.robustness.elastic import ElasticMonitor

    monitor, owned = ElasticMonitor.ensure(elastic)

    def solve_chunk(params, max_iter, region, v, dx, done):
        # PGO has no cross-chunk warm-start operand (its warm-start
        # carry lives inside the loop only); `dx`/`done` are accepted
        # for the shared chunk-loop contract and unused.
        del dx, done
        chunk_option = dataclasses.replace(
            option,
            algo_option=dataclasses.replace(
                option.algo_option, max_iter=max_iter))
        result = solve_pgo(
            params, edge_i, edge_j, meas, chunk_option, verbose=verbose,
            initial_region=region, initial_v=v, **solve_kwargs)
        return result, np.asarray(result.poses)

    poses = np.asarray(poses0)
    try:
        return _run_chunked(
            solve_chunk,
            params=poses,
            dump_params=lambda p: (np.asarray(p), np.zeros((0, 1))),
            load_params=lambda st: np.asarray(st["cameras"]),
            topo=_topology_fingerprint(poses, np.zeros((0, 1)), edge_i,
                                       edge_j),
            total=option.algo_option.max_iter,
            checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every,
            world_size=option.world_size,
            process_index=int(jax.process_index()),
            elastic=monitor,
        )
    finally:
        if owned:
            monitor.stop()
