"""Common enums and option structs.

TPU-native equivalents of the reference's `include/common.h`: the enum set
(common.h:17-25) and the option structs with identical field names and
defaults (`SolverOption` common.h:27-33, `AlgoOption` common.h:35-42,
`ProblemOption` common.h:44-53), so that configurations written against the
reference map 1:1.  Device here selects the JAX backend platform instead of
CPU/CUDA.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import numpy as np


class Device(enum.Enum):
    """Execution backend (reference common.h:17)."""

    CPU = "cpu"
    TPU = "tpu"


class AlgoKind(enum.Enum):
    """Nonlinear algorithm kind (reference common.h:19)."""

    BASE_ALGO = 0
    LM = 1


class LinearSystemKind(enum.Enum):
    """Linear system kind (reference common.h:21)."""

    BASE_LINEAR_SYSTEM = 0
    SCHUR = 1


class ComputeKind(enum.Enum):
    """Hessian materialisation strategy (reference common.h:23).

    EXPLICIT precomputes the per-edge camera-point coupling blocks
    W_e = Jc_e^T Jp_e once per linearisation; IMPLICIT recomputes the
    Schur matvec from the stored Jacobians each PCG iteration
    (matrix-free, lower memory — reference README.md:19).
    """

    EXPLICIT = 0
    IMPLICIT = 1


class SolverKind(enum.Enum):
    """Linear solver kind (reference common.h:25)."""

    BASE_SOLVER = 0
    PCG = 1


class JacobianMode(enum.Enum):
    """How per-edge Jacobians are produced.

    AUTODIFF = reverse-mode `jax.vjp` under `jax.vmap`: one pullback per
    residual component (od=2 passes for BAL) instead of one JVP per
    parameter (12) — ~3x faster than forward mode for od << cd+pd.  The
    reference's JetVector layer is forward-mode by construction; picking
    the cheaper direction is a deliberate departure (same Jacobian).
    AUTODIFF_FORWARD = `jax.jacfwd` under vmap (the reference-faithful
    direction; useful for residuals with od >= param count).
    ANALYTICAL = hand-derived closed-form Jacobian (the equivalent of
    reference src/geo/analytical_derivatives.cu).
    """

    AUTODIFF = 0
    ANALYTICAL = 1
    AUTODIFF_FORWARD = 2


class PrecondKind(enum.Enum):
    """Preconditioner OPERATOR family for the Schur PCG (solver/precond.py).

    Orthogonal to `PreconditionerKind` (which picks the block DIAGONAL —
    Hpp or the true Schur diagonal — used as the base/smoothing
    operator by every family):

    JACOBI = the base block-Jacobi apply alone — the extracted baseline,
    bitwise identical to the pre-subsystem solver.
    NEUMANN = truncated Neumann/power-series expansion of S applied
    matrix-free: M⁻¹ = Σ_{i<=k} (I − D⁻¹S)^i D⁻¹ with k =
    `SolverOption.neumann_order`.  Each apply costs k extra S·p
    products INSIDE the PCG body (2k extra all-reduces per iteration
    when sharded) — it trades communication for iterations, so wins
    must be pinned in wall-clock, not iteration counts alone.
    TWO_LEVEL = camera-graph two-level scheme: a greedy co-observation-
    weighted aggregation of cameras into O(√Nc) clusters (host plan,
    cached — ops/segtiles.py), the EXACT Galerkin coarse operator
    A_c = R·S_damped·Rᵀ and the coarse coupling G = S_damped·Rᵀ both
    assembled once per PCG solve from the materialised camera blocks +
    per-point aggregated coupling (no black-box S applications), a
    small replicated spectrally-filtered eigendecomposition of A_c
    (solver/dense.dense_filtered_factor), and the block-Jacobi base as
    smoother, combined MULTIPLICATIVELY (symmetrized V(0,1)-ish
    cycle): M⁻¹ = Rᵀ A_c⁺ R + Pᵀ D⁻¹ P with P = I − G A_c⁺ R.  Because
    G is materialised, the per-apply cycle is two tiny dense solves +
    two [cd·Nc, C·cd] contractions + one block smooth — ZERO
    collectives inside the PCG while body (pinned by the
    `ba_twolevel_w2_f32` canonical audit program).  Fallback ladder on
    a non-finite coarse spectrum: two-level → block-Jacobi (the cycle
    becomes exactly the base apply) → Hpp (per-block, SCHUR_DIAG
    only), each level COUNTED in `PCGResult.precond_fallback`
    (enum-coded per level — solver/precond.py encode/decode).
    MULTILEVEL = the TWO_LEVEL scheme generalized to an L-level
    camera-graph hierarchy (solver/precond.py): the level-1 coarse
    space is the same host-planned co-observation aggregation, and
    every coarser level re-aggregates the previous level's cluster
    graph (`SolverOption.coarsen_factor` per level, up to
    `SolverOption.max_levels` levels — planned host-side ONCE,
    ops/segtiles.build_multilevel_plan).  Level 1's Galerkin operator
    and coupling are assembled from the materialised solve quantities
    exactly as TWO_LEVEL's; every deeper level's Galerkin operator
    A_{l+1} = R_l A_l R_lᵀ is a tiny replicated dense contraction, so
    the recursive symmetrized multiplicative V-cycle keeps ZERO
    collectives inside the PCG while body (pinned by the
    `ba_multilevel_w2_f32` canonical audit program) and only the
    COARSEST level pays the dense filtered pseudo-inverse.  Per-level
    health rides the same enum code as a BIT-FIELD (bit l-1 of the
    high half = level-l coarse operator degraded), so a mid-hierarchy
    degrade truncates the cycle at that level, never poisons it.
    Both TWO_LEVEL and MULTILEVEL accept `SolverOption.smooth_omega`:
    smoothed-aggregation prolongators P = Rᵀ − ω D⁻¹ S_d Rᵀ (the
    expander-robust variant — the already-materialised G = S_d Rᵀ
    makes the smoothing itself free; the exact smoothed Galerkin costs
    one extra column-blocked S·(D⁻¹G) pass per build, still outside
    the PCG body).
    """

    JACOBI = 0
    NEUMANN = 1
    TWO_LEVEL = 2
    MULTILEVEL = 3


class EdgeOrder(enum.Enum):
    """Host-side edge-stream ordering applied before lowering.

    NATURAL keeps the caller's edge order (modulo the camera sort the
    lowering always performs) — byte-identical to every pre-existing
    program.  COOBS applies the PI-BA co-observation ordering (arXiv
    1905.02373): edges sorted camera-major, point-minor, so edges that
    share a camera are contiguous and, within a camera, edges touching
    nearby points cluster — each gathered camera/point tile is fully
    consumed before the stream moves on.  Purely a host permutation of
    the operands (sums reorder, so results agree at solver tolerance,
    not bitwise); the tiled paths' reuse factor strictly improves on
    locality-structured scenes (ops/segtiles.edge_stream_reuse).  The
    2-D mesh lowering applies this ordering inside its own tile plan
    unconditionally; the knob exposes it to the 1-D paths too.
    """

    NATURAL = 0
    COOBS = 1


class PreconditionerKind(enum.Enum):
    """Block-Jacobi preconditioner for the Schur PCG.

    HPP = inverted damped camera blocks — the reference's choice
    (schur_pcg_solver.cu:199: invertDistributed on Hpp) and the default.
    SCHUR_DIAG = the TRUE block diagonal of the Schur complement,
    diag_c(S) = Hpp_c - sum_{e in c} W_e Hll^-1_{pt(e)} W_e^T, assembled
    by one extra segment_sum per solve.  The standard stronger choice in
    the BA literature for sparsely-coupled problems (cameras sharing few
    points); NOT universally better — on small densely-coupled scenes it
    can cost more iterations — so benchmark per problem.  Costs a
    transient [nE, cd, cd] buffer per solve (~324 B/edge for BAL): at
    multi-million-edge scale prefer HPP until the fused build lands.
    """

    HPP = 0
    SCHUR_DIAG = 1


class SolveStatus(enum.IntEnum):
    """Termination status of one LM solve (robustness layer).

    Carried as an int32 scalar on `LMResult.status` / `PGOResult.status`
    so the code is computed ON DEVICE inside the jitted program and the
    caller can branch without a second device round trip.  The README
    "Failure semantics" table maps each code to the caller action.
    """

    MAX_ITER = 0  # iteration budget exhausted with progress made
    CONVERGED = 1  # a convergence criterion fired (step size / gradient)
    STALLED = 2  # budget exhausted with ZERO accepted steps
    RECOVERED = 3  # finished after >= 1 contained fault recovery
    FATAL_NONFINITE = 4  # bailed out: max_recoveries consecutive failures


def status_name(code) -> str:
    """Human-readable name of a SolveStatus code (tolerates raw ints)."""
    try:
        return SolveStatus(int(code)).name.lower()
    except ValueError:
        return f"unknown({int(code)})"


# The statuses a fleet service should NOT hand back as-is: STALLED means
# zero accepted steps (distrust the result), FATAL_NONFINITE means the
# guards gave up.  Both are exactly the outcomes a re-solve under
# stronger settings (guards armed, inflated damping, conservative
# preconditioning, f64) can turn into a usable answer — the escalation
# ladder in serving/resilience.py retries them automatically.
RETRYABLE_STATUSES = frozenset(
    {SolveStatus.STALLED, SolveStatus.FATAL_NONFINITE})


def status_retryable(code, final_cost=None,
                     statuses=RETRYABLE_STATUSES) -> bool:
    """Should a fleet-level retry ladder re-solve this outcome?

    True for a status in `statuses` (default `RETRYABLE_STATUSES`;
    `EscalationPolicy.retry_statuses` passes its own set) and for any
    solve whose final cost is non-finite regardless of its code: with
    guards OFF a poisoned carry can still surface as MAX_ITER/CONVERGED
    around a NaN cost (NaN comparisons reject every trial silently),
    and delivering that result would defeat the ladder's purpose.
    Unknown codes are retryable — never deliver something the service
    cannot classify.
    """
    try:
        retry = SolveStatus(int(code)) in statuses
    except ValueError:
        retry = True  # unknown code: never deliver silently
    if final_cost is not None and not np.isfinite(float(final_cost)):
        return True
    return retry


@dataclasses.dataclass(frozen=True)
class RobustOption:
    """Fault-containment knobs (capability beyond the reference).

    `guards=True` arms the on-device fault guards: the LM loop detects
    non-finite steps (trial cost / dx), rolls back to the last ACCEPTED
    state bitwise (the functional carry already holds it), relinearises
    there, inflates damping by `damping_inflation` (the trust region is
    divided by it, so the next system is more diagonally dominant), and
    counts consecutive failures — bailing out with
    `SolveStatus.FATAL_NONFINITE` after more than `max_recoveries`
    consecutive failed recoveries.  The PCG core additionally detects
    recurrence breakdown (non-finite or sign-flipped gamma/delta in the
    Chronopoulos-Gear scalars) and performs up to `pcg_max_restarts`
    in-loop cold restarts from the current iterate before flagging exit.

    Detection piggybacks on scalars that are already psum-reduced (NaN
    propagates through the existing reductions), so the sharded path
    adds ZERO new collectives; with guards armed and nothing failing,
    every selected value is bitwise identical to the unguarded solve
    (tests/test_robustness.py pins this).
    """

    guards: bool = False
    max_recoveries: int = 3
    damping_inflation: float = 4.0
    pcg_max_restarts: int = 2


@dataclasses.dataclass(frozen=True)
class SolverOption:
    """Inner (PCG) solver options — reference common.h:27-33 defaults.

    `tol` follows the reference's semantics: an ABSOLUTE threshold on the
    preconditioned residual energy rho = <r, M^-1 r> (fine when costs are
    large, awkward otherwise).  `tol_relative=True` reinterprets it as a
    fraction of the RHS energy <b, M^-1 b> — the conventional, scale-free
    PCG stopping rule (capability beyond the reference).

    Inexact-LM controls (capabilities beyond the reference):

    `forcing=True` turns on the Eisenstat-Walker (choice 2) adaptive
    forcing sequence: each LM iteration k computes its own tolerance
    eta_k ON DEVICE (inside the jitted while_loop) from the observed cost
    ratio, clamped to `[eta_min, tol]`, tightened on rejected steps and
    loosened after strong gain ratios.  eta_k is a NORM-relative forcing
    term (||r||_{M^-1} <= eta_k ||b||_{M^-1}); with forcing on, `tol`
    becomes the eta cap and `tol_relative` is implied.

    `warm_start=True` seeds each PCG solve with the previous ACCEPTED
    LM step (zeroed on reject — a rejected step shrinks the trust region,
    so the damped system the next solve sees is sharply different).
    Costs one extra S·p product per LM iteration (r0 = b - S x0),
    outside the PCG while body, so the per-iteration collective census
    (2 all-reduces per S·p) is unchanged.
    """

    # Program-family selector: validate_options pins it to its single
    # implemented value (PCG), so no lowering code branches on it today;
    # lowering-relevant BY DECLARATION — a second solver family must key
    # the program surface, and the pragma documents that intent for the
    # identity lane (analysis/identity.py cache-split).
    solver_kind: SolverKind = SolverKind.PCG  # megba: lowering-relevant(solver_option.solver_kind)
    max_iter: int = 100
    tol: float = 1e-1
    refuse_ratio: float = 1.0
    tol_relative: bool = False
    preconditioner: PreconditionerKind = PreconditionerKind.HPP
    # Inexact-LM: adaptive Eisenstat-Walker forcing + warm starts.
    forcing: bool = False
    eta_min: float = 1e-6
    warm_start: bool = False
    # Preconditioner operator family (solver/precond.py): JACOBI is the
    # extracted baseline (bitwise-identical programs); NEUMANN /
    # TWO_LEVEL are the stronger operators targeting the PCG-iteration
    # plateau.  `neumann_order` is the (static) series order k;
    # `coarse_clusters` the two-level coarse-space size target
    # (0 = auto, ~ceil(sqrt(num_cameras))).  BA/Schur path only.
    precond: PrecondKind = PrecondKind.JACOBI
    neumann_order: int = 2
    coarse_clusters: int = 0
    # Multilevel hierarchy knobs (MULTILEVEL only): every level beyond
    # the first re-aggregates the previous level's cluster graph by
    # ~`coarsen_factor`, until `max_levels` total levels (fine level
    # included) or the coarse space stops shrinking.  TWO_LEVEL is
    # exactly MULTILEVEL at max_levels=2.
    coarsen_factor: float = 4.0
    max_levels: int = 3
    # Smoothed-aggregation prolongator weight (TWO_LEVEL/MULTILEVEL):
    # 0 = piecewise-constant aggregation (the PR 7 operator, bitwise);
    # omega > 0 smooths the level-1 prolongator to Rᵀ − ω D⁻¹ S_d Rᵀ,
    # widening the coarse space so it captures smooth error even on
    # cluster-poor (expander-like) camera graphs.  Conventional range
    # (0, 1); ~2/3 is the classical damped-Jacobi choice.
    smooth_omega: float = 0.0
    # 2-D mesh distribution (parallel/mesh.make_mesh_2d): world_size
    # factors into edge_shards x cam_blocks (edge_shards = world_size /
    # cam_blocks), cameras are tiled into cam_blocks contiguous blocks,
    # and the Schur matvec's two world-wide all-reduces become
    # subgroup-scoped stages — a psum over the edge subgroup plus a
    # psum_scatter/all-gather pair over the camera subgroup, with the
    # per-tile point-shard transfer double-buffered against the tile
    # contraction (solver/pcg.make_matvec_2d).  OFF by default: the 1-D
    # path is untouched by construction (every existing program lowers
    # byte-identically).  `cam_blocks` must divide world_size; 0 = auto
    # (largest divisor <= sqrt(world_size) — square-ish meshes keep both
    # subgroups small).  Schur path only; world_size == 1 ignores it.
    mesh_2d: bool = False
    cam_blocks: int = 0
    # Host edge-stream ordering (EdgeOrder): NATURAL = byte-identical
    # legacy order; COOBS = PI-BA co-observation ordering for the 1-D
    # paths (the 2-D plan orders its streams co-observation-first
    # regardless).
    edge_order: EdgeOrder = EdgeOrder.NATURAL
    # bf16 MXU pipeline (the precision-ladder rung below
    # ProblemOption.mixed_precision_pcg — ARCHITECTURE.md "Precision
    # ladder").  `bf16=True` stores the EQUILIBRATED per-edge coupling
    # operands (W or Jc/Jp rows) AND the block-diagonal preconditioner
    # in bfloat16 and feeds them to the products AS bf16 — per-edge
    # multiplies run on bf16 operands (the MXU operand format) with
    # every accumulation upcast to float32 first (the f32-accumulated
    # bf16-contraction discipline of the TPU distributed-linear-algebra
    # playbook, arXiv 2112.09017), where the mixed rung upcasts the
    # stored rows BEFORE multiplying.  Krylov vectors, CG scalars
    # (compensated dots), the Hessian build, the reduced RHS /
    # back-substitution and every coarse-space build stay float32: the
    # allowed-bf16 surface is exactly the census the HLO auditor pins
    # (analysis/program_audit.Bf16Surface).  f32 problems only (refused
    # typed on f64); Schur path only; forces the non-tiled XLA
    # lowering (flat_solve).
    bf16: bool = False
    # Separately gated second half of the rung: cast the IN-BODY
    # collective payloads (the two S·p psums on the 1-D mesh; the
    # psum_scatter / psum / permute / all_gather stages of the 2-D
    # matvec) to bf16 on the wire — halving `collective_bytes_per_sp`,
    # the budget-gate axis that dominates pod-scale iteration time.
    # The cross-shard reduction then accumulates in bf16 (unlike the
    # on-device f32 sums), which is why it is its own gate: requires
    # bf16=True, and the once-per-solve psums (Schur build, reduced
    # RHS, coarse builds, back-substitution) always stay full
    # precision.
    bf16_collectives: bool = False
    # Fused Pallas edge-pipeline kernels (ops/fused.py): run the Schur
    # coupling matvec as ONE gather->contract->scatter kernel per
    # direction (edge tiles stay VMEM-resident — the per-edge expanded
    # rows never touch HBM) and the block-diagonal M⁻¹ apply as one
    # fused kernel pass.  OFF by default — every existing program
    # lowers byte-identically with it unset (the dark-landing
    # guarantee, pinned by test_program_audit).  Composes with the
    # tiled plans, the 2-D mesh ring step, and bf16 (lifting the
    # tiled+bf16 refusal — the fused kernels ARE the bf16-legal tiled
    # lowering); refused typed on the non-tiled XLA lowering
    # (use_tiled=False) and on 1-D multi-device worlds, which keep the
    # existing paths.  Off-TPU the same kernels run under Pallas
    # interpret mode (the CPU-lane parity certificate), so this flag
    # changes PROGRAMS, not semantics.  Stripped by escalation rung 2.
    # (No declared-intent pragma: the field is READ by the lowering —
    # flat_solve's plan/refusal branches and the pcg dispatch — so the
    # identity lane resolves it lowering-relevant from the read-set.)
    fused_kernels: bool = False


@dataclasses.dataclass(frozen=True)
class AlgoOption:
    """Outer (LM) loop options — reference common.h:35-42 defaults."""

    # Program-family selector (validated to LM; see solver_kind note).
    algo_kind: AlgoKind = AlgoKind.LM  # megba: lowering-relevant(algo_option.algo_kind)
    max_iter: int = 20
    initial_region: float = 1e3  # "tau"; trust region radius
    epsilon1: float = 1.0
    epsilon2: float = 1e-10


@dataclasses.dataclass(frozen=True)
class ProblemOption:
    """Problem-level options — reference common.h:44-53.

    Frozen (immutable + hashable): options are jit-trace statics and cache
    keys; use dataclasses.replace to derive variants.

    `world_size` replaces the reference's `deviceUsed` GPU list: the number
    of mesh devices the edge axis is sharded over.  `dtype` replaces the
    float/double template parameter (SPECIALIZE_STRUCT, common.h:9-11);
    note TPU float64 is emulated, so float64 runs are typically pinned to
    the CPU backend for verification.
    """

    use_schur: bool = True
    # Backend selector: platform choice IS a different compiled program
    # by definition, even though no Python lowering code reads it —
    # lowering-relevant by declaration (analysis/identity.py).
    device: Device = Device.TPU  # megba: lowering-relevant(device)
    world_size: int = 1
    # Derived host-side shape hints, never read on any path: operand
    # SHAPES key every jit cache independently, so keying these would
    # only fragment the caches — key-exempt (analysis/identity.py).
    N: int = -1  # grad width (cameraDim + pointDim); derived if -1  # megba: key-exempt(N)
    n_item: int = -1  # number of edges/observations; derived if -1  # megba: key-exempt(n_item)
    dtype: np.dtype = np.float64
    # Program-family selectors (validated to their single implemented
    # value — see SolverOption.solver_kind note).
    algo_kind: AlgoKind = AlgoKind.LM  # megba: lowering-relevant(algo_kind)
    linear_system_kind: LinearSystemKind = LinearSystemKind.SCHUR  # megba: lowering-relevant(linear_system_kind)
    compute_kind: ComputeKind = ComputeKind.IMPLICIT
    jacobian_mode: JacobianMode = JacobianMode.AUTODIFF
    solver_option: SolverOption = dataclasses.field(default_factory=SolverOption)
    algo_option: AlgoOption = dataclasses.field(default_factory=AlgoOption)
    # Fault containment (robustness layer; guards are OFF by default so
    # existing configurations keep their exact compiled programs).
    robust_option: RobustOption = dataclasses.field(default_factory=RobustOption)
    # bf16 inner PCG vectors with fp32 reductions (BASELINE.md config 5).
    mixed_precision_pcg: bool = False
    # Robust loss (capability beyond the reference; Ceres-style kernels).
    robust_kind: "RobustKind" = None  # resolved to RobustKind.NONE below
    robust_delta: float = 1.0
    # Opt-in telemetry sink: a JSONL path each solve appends a structured
    # SolveReport to (observability/report.py).  Equivalent to setting
    # MEGBA_TELEMETRY; the knob wins when both are set.  Purely host-side:
    # solve.flat_solve strips it before program build, so it never
    # fragments the jit caches or changes the compiled program.
    telemetry: Optional[str] = None
    # Opt-in metrics plane (observability/metrics.py): arms the
    # process-local counter/gauge/histogram registry for this solve —
    # equivalent to setting MEGBA_METRICS; either being set arms it.
    # Host-side only and stripped before program build exactly like
    # `telemetry`, so the knob never splits a jit/program/artifact cache
    # and the compiled programs stay byte-identical (HLO-audit-pinned).
    metrics: bool = False

    def __post_init__(self) -> None:
        from megba_tpu.ops.robust import RobustKind

        if self.robust_kind is None:
            object.__setattr__(self, "robust_kind", RobustKind.NONE)
        if self.world_size < 1:
            raise ValueError(f"world_size must be >= 1, got {self.world_size}")
        if self.robust_kind != RobustKind.NONE and not self.robust_delta > 0:
            raise ValueError(
                f"robust_delta must be > 0, got {self.robust_delta}")
        # use_schur=False selects the plain full-system PCG
        # (solver.pcg.plain_pcg_solve) — the path the reference left as a
        # TODO (base_problem.cpp:112-123) but this framework implements.


@dataclasses.dataclass
class AlgoStatus:
    """Mutable LM status — reference common.h:55-60."""

    region: float = 1e3
    recover_diag: bool = False


DTYPE_TO_JAX = {
    np.dtype(np.float32): "float32",
    np.dtype(np.float64): "float64",
}


# The observability strip-list — THE single registry of ProblemOption
# fields that are host-side sinks and must be cleared off an option
# before it reaches any program-identity surface: the jit/program
# caches, retrace static keys, artifact fingerprints, warm-manifest
# option configs, and bucket keys.  Every strip site in the package
# (solve.flat_solve, serving.batcher._strip_telemetry,
# serving.compile_pool._sans_telemetry, models.pgo.solve_pgo,
# serving.federation worker setup) routes through strip_observability
# below, and compile_pool._config_mismatches derives its comparison
# exclusions from this tuple — so the strip set cannot drift per
# surface (analysis/identity.py `key-surface-drift` enforces the
# agreement statically).
OBSERVABILITY_FIELDS = ("telemetry", "metrics")


def strip_observability(option: ProblemOption) -> ProblemOption:
    """Clear the observability sinks (`OBSERVABILITY_FIELDS`) off an
    option before it reaches a program-identity surface.

    Returns `option` ITSELF when nothing is armed: cache fronts call
    this unconditionally, and the identity pass-through keeps
    already-clean options hitting the exact same lru/dict cache slots
    they always did (no key churn for the common case).
    """
    if option.telemetry is not None or option.metrics:
        return dataclasses.replace(option, telemetry=None, metrics=False)
    return option


def validate_options(option: ProblemOption) -> None:
    """Cross-check algo/linear-system/solver kinds.

    Mirrors the ctor-time validation in reference base_problem.cpp:66-73 and
    base_linear_system.cpp:22-25.
    """
    if option.algo_kind != AlgoKind.LM:
        raise ValueError("only AlgoKind.LM is supported")
    if option.use_schur and option.linear_system_kind != LinearSystemKind.SCHUR:
        raise ValueError("use_schur=True requires LinearSystemKind.SCHUR")
    if option.solver_option.solver_kind != SolverKind.PCG:
        raise ValueError("only SolverKind.PCG is supported")
    if not option.solver_option.eta_min > 0:
        raise ValueError(
            f"eta_min must be > 0, got {option.solver_option.eta_min}")
    if (option.solver_option.forcing
            and option.solver_option.eta_min > option.solver_option.tol):
        raise ValueError(
            "forcing=True clamps eta_k to [eta_min, tol]; need "
            f"eta_min <= tol, got eta_min={option.solver_option.eta_min} "
            f"> tol={option.solver_option.tol}")
    if (option.solver_option.precond == PrecondKind.NEUMANN
            and option.solver_option.neumann_order < 1):
        raise ValueError(
            f"neumann_order must be >= 1, got "
            f"{option.solver_option.neumann_order}")
    if option.solver_option.coarse_clusters < 0:
        raise ValueError(
            f"coarse_clusters must be >= 0 (0 = auto sqrt(Nc)), got "
            f"{option.solver_option.coarse_clusters}")
    if not option.solver_option.coarsen_factor > 1.0:
        raise ValueError(
            f"coarsen_factor must be > 1 (each level must shrink), got "
            f"{option.solver_option.coarsen_factor}")
    # The per-level fallback bit-field shares one int32 with the 16-bit
    # block count (solver/precond.py): coarse levels ride bits 16..30.
    if not 2 <= option.solver_option.max_levels <= 15:
        raise ValueError(
            f"max_levels must be in [2, 15] (fine level included; the "
            f"per-level fallback bit-field carries at most 15 coarse "
            f"levels), got {option.solver_option.max_levels}")
    if not 0.0 <= option.solver_option.smooth_omega < 2.0:
        raise ValueError(
            f"smooth_omega must be in [0, 2) (0 = plain aggregation), "
            f"got {option.solver_option.smooth_omega}")
    if (option.solver_option.smooth_omega
            and option.solver_option.precond not in (
                PrecondKind.TWO_LEVEL, PrecondKind.MULTILEVEL)):
        raise ValueError(
            "smooth_omega smooths the camera-graph coarse space; it "
            "requires precond=TWO_LEVEL or MULTILEVEL, got "
            f"{option.solver_option.precond.name}")
    if option.solver_option.cam_blocks < 0:
        raise ValueError(
            f"cam_blocks must be >= 0 (0 = auto factorisation), got "
            f"{option.solver_option.cam_blocks}")
    if option.solver_option.mesh_2d:
        if not option.use_schur:
            raise ValueError(
                "mesh_2d is only implemented for the Schur solver "
                "(use_schur=True); the plain full-system path has no "
                "camera-tiled matvec")
        cb = option.solver_option.cam_blocks
        if cb > 0 and (cb > option.world_size
                       or option.world_size % cb != 0):
            raise ValueError(
                f"mesh_2d needs world_size = edge_shards x cam_blocks: "
                f"cam_blocks={cb} does not divide "
                f"world_size={option.world_size} (pick a divisor, or 0 "
                "for the automatic square-ish factorisation)")
    if (not option.use_schur
            and option.solver_option.precond != PrecondKind.JACOBI):
        raise ValueError(
            "precond=NEUMANN/TWO_LEVEL/MULTILEVEL is only implemented for "
            "the Schur solver (use_schur=True); the plain full-system "
            "solver's exact block diagonal IS its preconditioner")
    if option.robust_option.max_recoveries < 1:
        raise ValueError(
            f"max_recoveries must be >= 1, got "
            f"{option.robust_option.max_recoveries}")
    if not option.robust_option.damping_inflation > 1.0:
        raise ValueError(
            f"damping_inflation must be > 1, got "
            f"{option.robust_option.damping_inflation}")
    if option.robust_option.pcg_max_restarts < 0:
        raise ValueError(
            f"pcg_max_restarts must be >= 0, got "
            f"{option.robust_option.pcg_max_restarts}")
    if not option.use_schur and option.mixed_precision_pcg:
        raise ValueError(
            "mixed_precision_pcg is only implemented for the Schur solver "
            "(use_schur=True)")
    if option.solver_option.bf16:
        if not option.use_schur:
            raise ValueError(
                "SolverOption.bf16 is only implemented for the Schur "
                "solver (use_schur=True); the plain full-system path has "
                "no equilibrated coupling operands to halve")
        if np.dtype(option.dtype) != np.float32:
            raise ValueError(
                "SolverOption.bf16 runs the float32 pipeline with bf16 "
                "coupling storage; a float64 problem asking for bf16 "
                "operands would silently discard the precision it asked "
                f"for — got dtype={np.dtype(option.dtype).name} (solve "
                "f64 without bf16, or cast the problem to f32)")
        if option.mixed_precision_pcg:
            raise ValueError(
                "SolverOption.bf16 and ProblemOption.mixed_precision_pcg "
                "are different rungs of the same precision ladder (bf16 "
                "multiplies in bf16 with f32 accumulation; mixed upcasts "
                "the stored rows before multiplying) — pick one")
    if option.solver_option.fused_kernels and not option.use_schur:
        raise ValueError(
            "SolverOption.fused_kernels fuses the Schur coupling matvec "
            "and M⁻¹ apply (use_schur=True); the plain full-system path "
            "has no edge pipeline to fuse")
    if option.solver_option.bf16_collectives and not option.solver_option.bf16:
        raise ValueError(
            "bf16_collectives compresses the in-body collective payloads "
            "of the bf16 matvec pipeline; it requires SolverOption."
            "bf16=True (the storage rung) — enabling it alone would halve "
            "wire traffic of products that never went bf16")
    if np.dtype(option.dtype) not in DTYPE_TO_JAX:
        raise ValueError(f"unsupported dtype {option.dtype}")
