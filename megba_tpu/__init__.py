"""megba_tpu — a TPU-native distributed Bundle Adjustment framework.

A brand-new JAX/XLA implementation with the capabilities of MegBA
(MegviiRobot/MegBA): large-scale BA via Levenberg-Marquardt with a
distributed Schur-complement PCG solver, vectorised per-edge residual and
forward-mode Jacobian evaluation (autodiff and analytical), explicit and
implicit (matrix-free) Hessian modes, and edge-axis sharding over a TPU
device mesh with `jax.lax.psum` collectives in place of the reference's
NCCL allreduces.

This is an idiomatic TPU-first design, not a port: the reference's
JetVector operator layer (reference include/operator/jet_vector.h),
CUDA memory pool (reference src/resource/memory_pool.cu) and
CSR/cuSPARSE machinery (reference src/linear_system, src/solver)
collapse into vmapped, jitted, mesh-sharded pure functions.
"""

from megba_tpu.common import (
    AlgoKind,
    AlgoOption,
    ComputeKind,
    Device,
    JacobianMode,
    LinearSystemKind,
    PrecondKind,
    PreconditionerKind,
    ProblemOption,
    RobustOption,
    SolverKind,
    SolverOption,
    SolveStatus,
    status_name,
)
from megba_tpu.core.types import BALData, BAState
from megba_tpu.problem import (
    BaseEdge,
    BaseProblem,
    BaseVertex,
    BetweenEdge,
    CameraVertex,
    PointVertex,
    PoseVertex,
    VertexKind,
)
from megba_tpu.ops.robust import RobustKind
from megba_tpu.solve import solve_bal


def solve_pgo(*args, **kwargs):
    """Solve an SE(3) pose graph — see models/pgo.py (lazy import: the
    PGO family is optional for BA-only users)."""
    from megba_tpu.models.pgo import solve_pgo as _solve_pgo

    return _solve_pgo(*args, **kwargs)


def solve_many(*args, **kwargs):
    """Solve many independent BA problems through the serving layer's
    shape-bucketed batched programs — see serving/batcher.py (lazy
    import: the serving layer is optional for single-problem users)."""
    from megba_tpu.serving import solve_many as _solve_many

    return _solve_many(*args, **kwargs)


def solve_g2o(*args, **kwargs):
    """Read + solve a .g2o pose-graph file — see io/g2o.py."""
    from megba_tpu.io.g2o import solve_g2o as _solve_g2o

    return _solve_g2o(*args, **kwargs)


def flat_solve(*args, **kwargs):
    """The flat-array solve pipeline — see solve.py.  With `factor=` a
    registered residual family (megba_tpu/factors/) resolves the
    engine; lazy import keeps package import light."""
    from megba_tpu.solve import flat_solve as _flat_solve

    return _flat_solve(*args, **kwargs)


__version__ = "0.1.0"

__all__ = [
    "AlgoKind",
    "AlgoOption",
    "BALData",
    "BAState",
    "BaseEdge",
    "BaseProblem",
    "BaseVertex",
    "BetweenEdge",
    "CameraVertex",
    "ComputeKind",
    "Device",
    "JacobianMode",
    "LinearSystemKind",
    "PointVertex",
    "PoseVertex",
    "PrecondKind",
    "PreconditionerKind",
    "ProblemOption",
    "RobustKind",
    "RobustOption",
    "SolveStatus",
    "SolverKind",
    "SolverOption",
    "VertexKind",
    "flat_solve",
    "solve_bal",
    "solve_g2o",
    "solve_pgo",
    "status_name",
]
