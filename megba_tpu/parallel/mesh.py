"""Device mesh + edge-axis sharding.

The TPU-native replacement for the reference's entire distribution
machinery: the host-side `for (i < worldSize) cudaSetDevice(i)` loops
(reference src/edge/base_edge.cu:20-25, src/solver/schur_pcg_solver.cu:
164-197), the per-device contiguous edge partition
(MemoryPool::getItemNum, memory_pool.h:48-63; base_problem.cpp:59-74) and
the NCCL allreduce set (SURVEY.md §2.3) become: a 1-D
`jax.sharding.Mesh` over axis "edges", `jax.shard_map` with edge arrays
split on their leading axis (the same contiguous partition, but
equal-size via padding), and `jax.lax.psum` inside the jitted solve.

Unlike the reference (single-process, single-node, ncclCommInitAll —
handle_manager.cpp:17-22), the same code runs multi-host: under
`jax.distributed`, the Mesh spans all hosts' devices, XLA routes the
psums over ICI within a slice and DCN across slices, and nothing here
changes.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import warnings
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from megba_tpu.algo.lm import LMResult, lm_solve
from megba_tpu.analysis.retrace import static_key, traced
from megba_tpu.common import ProblemOption, strip_observability
from megba_tpu.core.types import pad_edges

# jax.shard_map graduated from jax.experimental between jax releases;
# resolve it once here so every solver family rides the same symbol on
# either side of the move (jaxlib in this image still ships the
# experimental spelling).
try:
    shard_map = jax.shard_map
    SHARD_MAP_NATIVE = True
except AttributeError:
    SHARD_MAP_NATIVE = False
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, **kwargs):
        # The 0.4.x experimental shard_map has no replication rule for
        # while_loop, so its check_rep pass rejects the LM solvers
        # outright; the solvers' outputs are psum-derived replicated
        # values under out_specs=P() by construction (tested by the
        # world-1/2/8 parity suite), so the check adds nothing here.
        return _shard_map_exp(f, check_rep=False, **kwargs)

EDGE_AXIS = "edges"
# Second mesh axis of the 2-D distribution (make_mesh_2d): camera
# blocks.  Under a 2-D mesh the edge axis splits over BOTH axes
# (P((EDGE_AXIS, CAM_AXIS))), cameras tile over CAM_AXIS, and the Schur
# matvec's reductions become subgroup-scoped (solver/pcg.make_matvec_2d)
# instead of world-wide.
CAM_AXIS = "cams"


def collective_payload_cast(enabled: bool, compute_dtype=None):
    """(down, up) casts around IN-BODY collective payloads.

    The bf16-collective half of the bf16 MXU pipeline
    (SolverOption.bf16_collectives): `down` casts a partial-sum payload
    to bfloat16 just before it goes on the wire, `up` restores the f32
    compute dtype on the reduced result — halving the bytes every
    in-body psum / psum_scatter / ppermute / all_gather moves, the
    `collective_bytes_per_sp` budget axis.  With `enabled=False` both
    are identity functions that emit NO ops, so every existing program
    lowers byte-identically.

    The cross-shard reduction itself then runs on bf16 values (the
    payload is summed as transmitted); the once-per-solve reductions
    (Schur build, reduced RHS, coarse builds, back-substitution) never
    ride this cast — solver/pcg.py scopes it to the S·p matvec the PCG
    while body dispatches.

    Probed hazard (jaxlib 0.4.36, XLA:CPU): the CPU backend's float
    normalization pass promotes bf16 collectives back to f32 in the
    compiled executable (the convert pair is fused across the
    all-reduce), so on the CPU lane the wire payload this cast DECLARES
    is not the payload that moves — the HLO auditor therefore prices
    the declared (StableHLO) payload and pins it structurally
    (analysis/program_audit.py), which is what a TPU lowering (native
    bf16 collectives) executes.
    """
    if not enabled:
        ident = _payload_identity
        return ident, ident
    cd = jnp.float32 if compute_dtype is None else compute_dtype

    def down(x):
        return x.astype(jnp.bfloat16)

    def up(x):
        return x.astype(cd)

    return down, up


def _payload_identity(x):
    return x


def mesh_axes(mesh: Mesh):
    """The lm_solve `axis_name` for this mesh: the single edge axis for
    the 1-D mesh (every historical program, byte-identical), the
    (edge, camera) tuple for the 2-D mesh — `jax.lax.psum` over the
    tuple reduces over the whole world, so every existing psum site
    (cost sums, Schur build, coarse builds) is correct on both meshes
    without change."""
    names = tuple(mesh.axis_names)
    return names if len(names) > 1 else names[0]


def factor_mesh_2d(world_size: int, cam_blocks: int = 0):
    """Resolve (edge_shards, cam_blocks) for a 2-D mesh of `world_size`
    devices.

    `cam_blocks > 0` must divide world_size (validate_options enforces
    the same contract); 0 selects the largest divisor <=
    sqrt(world_size) — the square-ish factorisation that keeps BOTH
    subgroups small (a 1 x W or W x 1 degenerate mesh reproduces the
    1-D communication pattern on one of the two stages).
    """
    world_size = int(world_size)
    if world_size < 1:
        raise ValueError(f"world_size must be >= 1, got {world_size}")
    cam_blocks = int(cam_blocks)
    if cam_blocks > 0:
        if world_size % cam_blocks or cam_blocks > world_size:
            raise ValueError(
                f"cam_blocks={cam_blocks} does not factor "
                f"world_size={world_size} into edge_shards x cam_blocks")
        return world_size // cam_blocks, cam_blocks
    c = 1
    d = 1
    while d * d <= world_size:
        if world_size % d == 0:
            c = d
        d += 1
    return world_size // c, c


def nearest_cam_blocks(world_size: int, cam_blocks: int) -> int:
    """Largest feasible cam_blocks <= the requested one for this world.

    The elastic shrink-world resume (robustness/elastic.resume_elastic)
    uses this to re-factor a 2-D solve onto a SMALLER 2-D mesh: the
    surviving world keeps as much of the camera-block split as it can
    still factor (degrading to 1 — the 1-D layout — only when the new
    world size shares no divisor with the old camera split).
    """
    world_size = int(world_size)
    cam_blocks = max(1, int(cam_blocks))
    best = 1
    for c in range(1, min(cam_blocks, world_size) + 1):
        if world_size % c == 0:
            best = c
    return best


def make_mesh_2d(
    edge_shards: int,
    cam_blocks: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the 2-D (edge_shards x cam_blocks) mesh.

    Axis order is (EDGE_AXIS, CAM_AXIS): a P((EDGE_AXIS, CAM_AXIS))
    edge split hands device (e, c) the contiguous block e*C + c —
    exactly the block order `ops.segtiles.build_camera_tile_plan` lays
    the padded edge stream out in.  Device sourcing matches `make_mesh`
    (local_devices_only scope, loud CPU fallback).
    """
    E, C = int(edge_shards), int(cam_blocks)
    if E < 1 or C < 1:
        raise ValueError(
            f"edge_shards and cam_blocks must be >= 1, got {E} x {C}")
    world = E * C
    if devices is None:
        base = make_mesh(world, devices)
        devices = list(base.devices.reshape(-1))
    if len(devices) < world:
        raise ValueError(
            f"2-D mesh {E}x{C} needs {world} devices, have {len(devices)}")
    grid = np.asarray(list(devices)[:world]).reshape(E, C)
    return Mesh(grid, (EDGE_AXIS, CAM_AXIS))

# Elastic shrink-world scope (parallel/multihost + robustness/elastic):
# after peers are lost/abandoned, `jax.devices()` STILL lists the dead
# processes' devices — a mesh (or default-device dispatch) touching one
# would address a process that no longer exists.  While this scope is
# active, `make_mesh` draws only from devices THIS process owns.  A
# process-global count, not a thread-local: elastic dispatches run on
# watchdog worker threads, and a dead world is dead for every thread.
_LOCAL_ONLY_DEPTH = 0
_LOCAL_ONLY_LOCK = threading.Lock()


@contextlib.contextmanager
def local_devices_only():
    """Context manager scoping `make_mesh` to this process's devices.

    The shrink-world resume path (`robustness.elastic.resume_elastic`)
    wraps the re-lowered solve in this so the smaller mesh is built
    from surviving local devices regardless of what the stale global
    device list claims.  Re-entrant; affects all threads (see above).
    """
    global _LOCAL_ONLY_DEPTH
    with _LOCAL_ONLY_LOCK:
        _LOCAL_ONLY_DEPTH += 1
    try:
        yield
    finally:
        with _LOCAL_ONLY_LOCK:
            _LOCAL_ONLY_DEPTH -= 1


def local_only_active() -> bool:
    return _LOCAL_ONLY_DEPTH > 0


def _this_process_devices(devices):
    pi = jax.process_index()
    return [d for d in devices if d.process_index == pi]


def make_mesh(
    world_size: int, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build the 1-D edge-sharding mesh.

    `world_size` plays the role of the reference's ProblemOption::deviceUsed
    GPU count (common.h:47, validated against the device count at
    memory_pool.cu:50-56).  Under `local_devices_only()` (elastic
    shrink-world resume) the default device pool is restricted to this
    process's own devices; an explicit `devices=` list is always taken
    as-is — the caller owns it.
    """
    if devices is None:
        devices = jax.devices()
        if local_only_active():
            devices = _this_process_devices(devices)
        if len(devices) < world_size:
            # Fall back to the CPU platform (e.g. virtual multi-device CPU
            # testing while only one accelerator chip is attached) — loudly,
            # so a production solve can't silently leave the accelerator.
            try:
                cpus = jax.devices("cpu")
            except RuntimeError:
                cpus = []
            if local_only_active():
                cpus = _this_process_devices(cpus)
            if len(cpus) >= world_size:
                warnings.warn(
                    f"world_size {world_size} exceeds the {len(devices)} "
                    f"{devices[0].platform} device(s); falling back to "
                    f"{len(cpus)} CPU devices. Pass devices= explicitly to "
                    "silence.",
                    stacklevel=2,
                )
                devices = cpus
    if world_size > len(devices):
        raise ValueError(
            f"world_size {world_size} exceeds available devices {len(devices)}"
        )
    return Mesh(np.asarray(devices[:world_size]), (EDGE_AXIS,))


def shard_edge_arrays(
    obs: np.ndarray,
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    world_size: int,
    dtype=None,
):
    """Pad the edge axis to a multiple of world_size; returns (+mask).

    The mask dtype follows `obs` unless overridden, so a float32 problem
    is never silently upcast by a float64 mask.
    """
    if dtype is None:
        dtype = obs.dtype
    return pad_edges(obs, cam_idx, pt_idx, world_size, dtype=dtype)


def distributed_lm_solve(
    residual_jac_fn,
    cameras: jax.Array,
    points: jax.Array,
    obs: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    mask: jax.Array,
    option: ProblemOption,
    mesh: Mesh,
    sqrt_info: Optional[jax.Array] = None,
    cam_fixed: Optional[jax.Array] = None,
    pt_fixed: Optional[jax.Array] = None,
    verbose: bool = False,
    cam_sorted: bool = False,
    plans=None,
    initial_region=None,
    initial_v=None,
    initial_dx=None,
    fault_plan=None,
    cluster_plan=None,
    tile_plan=None,
    jit_cache: Optional[dict] = None,
    donate: bool = False,
    lower_only: bool = False,
) -> LMResult:
    """Run the full LM solve SPMD over the mesh's edge axis.

    Parameter state (cameras/points, Hessian diagonals, PCG vectors) is
    replicated — the reference's layout exactly (base_problem.cu:21-29,
    base_linear_system.h:33-34) — while every per-edge array lives only on
    its shard.  The entire LM loop, PCG included, is ONE jitted SPMD
    program; per-iteration synchronisation is the psum set documented in
    builder.py/pcg.py.

    DONATION CONTRACT: with `donate=True`, `cameras` and `points` are
    donated — the result's parameter arrays alias their buffers, and
    device arrays passed here are DELETED by the call.  The default is
    False on this PUBLIC entry point so a caller that reuses its device
    arrays after the call keeps its previously-valid usage; the internal
    flat_solve path opts in (it materializes fresh host operands per
    call and never reads them after the solve, so donation is free
    parameter-memory savings there).  Under a multi-process mesh every
    operand is lifted into a global array first
    (parallel/multihost.globalize_for_mesh), so host values are required
    there anyway.

    `lower_only=True` returns the `jax.stages.Lowered` of the exact SPMD
    program this call would dispatch (auditor hook,
    analysis/program_audit.py; single-process only).
    """
    n_edge = obs.shape[-1]
    if n_edge % mesh.devices.size != 0:
        raise ValueError(
            f"edge count {n_edge} not divisible by mesh size "
            f"{mesh.devices.size}; pad with shard_edge_arrays first"
        )

    # Program-identity surface: _cached_sharded_solve and the
    # caller-owned jit_cache both key on `option`, so strip the
    # observability sinks (common.OBSERVABILITY_FIELDS) on this PUBLIC
    # entry — the internal flat_solve path arrives pre-stripped
    # (identity pass-through, same cache slots), but a direct caller
    # with a telemetry/metrics-armed option previously split the
    # program cache (the identity lane's key-surface-drift finding,
    # fixed at the source).
    option = strip_observability(option)

    # Feature-major edge arrays [F, nE] split on the MINOR axis; 1-D
    # index/mask arrays on their only axis; parameters replicated.
    # Under the 2-D mesh the edge axis splits over BOTH mesh axes
    # (edge-shard-major device blocks — the camera-tile plan laid the
    # stream out in exactly this order) and the matvec operand
    # (tile_plan) follows the same split.
    is_2d = len(mesh.axis_names) > 1
    split = (EDGE_AXIS, CAM_AXIS) if is_2d else EDGE_AXIS
    edge = P(None, split)
    edge1d = P(split)
    rep = P()
    if is_2d and plans is not None:
        raise ValueError(
            "the 2-D mesh path does not compose with the Pallas tiled "
            "plans (DualPlans); lower with use_tiled=False")
    if is_2d and tile_plan is None:
        raise ValueError(
            "a 2-D mesh solve needs the camera-tile plan operand: solve "
            "through flat_solve (which plans + caches it) or pass "
            "tile_plan=ops.segtiles.device_camera_tile_plan(...)")

    # Optional operands can't be None inside shard_map specs; pass the
    # present ones positionally with matching specs.
    dtype = cameras.dtype
    ir = option.algo_option.initial_region if initial_region is None else initial_region
    iv = 2.0 if initial_v is None else initial_v
    from megba_tpu.observability.emit import next_verbose_token

    args = [cameras, points, obs, cam_idx, pt_idx, mask,
            jnp.asarray(ir, dtype), jnp.asarray(iv, dtype),
            jnp.asarray(next_verbose_token(), jnp.int32)]
    in_specs = [rep, rep, edge, edge1d, edge1d, edge1d, rep, rep, rep]
    optional = [
        ("sqrt_info", sqrt_info, edge),
        ("cam_fixed", cam_fixed, rep),
        ("pt_fixed", pt_fixed, rep),
        # Warm-start resume state ([cd, Nc] rows): replicated like the
        # parameter blocks; the in-loop warm-start carry it seeds stays
        # replicated too (it is the PCG's psum-derived output), so the
        # solver's out_specs=P() contract is unchanged.
        ("initial_dx", initial_dx, rep),
        # Per-shard tiled plans: every leaf carries a leading shard axis
        # split by the mesh (ops/segtiles.make_sharded_dual_plans).
        ("plans", plans, P(EDGE_AXIS)),
    ]
    if fault_plan is not None:
        # Seeded-fault operand (robustness/faults.py): the edge poison
        # is shard-local like every other edge array; the window/offset
        # scalars and the point mask ride replicated.
        from megba_tpu.robustness.faults import fault_partition_specs

        optional.append(("fault_plan", fault_plan,
                         fault_partition_specs(edge_spec=edge1d)))
    if cluster_plan is not None:
        # Coarse-space plan (ops/segtiles.py; two-level OR multilevel):
        # the per-edge pc_slot stream follows the edge shards, the
        # cluster/incidence/pair/assignment tables ride replicated (the
        # coarse assembly after the V psum — and every dense hierarchy
        # level above it — is identical tiny work per shard).
        from megba_tpu.ops.segtiles import coarse_plan_partition_specs

        optional.append(("cluster_plan", cluster_plan,
                         coarse_plan_partition_specs(cluster_plan,
                                                     edge_spec=edge1d)))
    if tile_plan is not None:
        # 2-D matvec operand: the per-edge cam_local stream and the
        # per-device point-shard bucket tables follow the 2-D edge
        # split (ops/segtiles.tile_plan_partition_specs).
        from megba_tpu.ops.segtiles import tile_plan_partition_specs

        optional.append(("tile_plan", tile_plan,
                         tile_plan_partition_specs(tile_plan, edge1d)))
    keys = tuple(k for k, v, _ in optional if v is not None)
    args += [v for _, v, _ in optional if v is not None]
    in_specs += [spec for _, v, spec in optional if v is not None]

    jitted = get_or_build_program(
        jit_cache, _cached_sharded_solve, _build_sharded_solve,
        residual_jac_fn, mesh, option, keys, tuple(in_specs), verbose,
        cam_sorted, donate)

    if lower_only:
        # Auditor hook (analysis/program_audit.py): hand back the
        # Lowered of the exact SPMD program this call would dispatch.
        # Single-process only — the audit never globalizes operands.
        return jitted.lower(*args)

    from megba_tpu.parallel.multihost import dispatch_on_mesh

    return dispatch_on_mesh(jitted, mesh, args, in_specs)


def get_or_build_program(jit_cache, cached_fn, build_fn, engine, *cfg):
    """Fetch/compile a jitted solve program.

    `jit_cache is None` -> the global lru (`cached_fn`) for long-lived
    engines.  Otherwise the caller-owned dict, keyed by the FULL builder
    argument list `(engine, *cfg)` — the key is exactly what `build_fn`
    receives, so it cannot drift out of sync with the configuration and
    serve a program compiled for different options (and a shared dict can
    never return a program compiled for a different engine).  Used by both
    solve.flat_solve and distributed_lm_solve; per-problem closure engines
    go through the dict path so their programs die with the problem.
    """
    if jit_cache is None:
        return cached_fn(engine, *cfg)
    key = (engine, *cfg)
    prog = jit_cache.get(key)
    if prog is None:
        prog = jit_cache[key] = build_fn(engine, *cfg)
    return prog


def _build_sharded_solve(residual_jac_fn, mesh, option, keys, in_specs, verbose,
                         cam_sorted=False, donate=False):
    """Build the jitted shard_map'ed solve (uncached)."""

    axes = mesh_axes(mesh)

    def fn(cameras, points, obs, cam_idx, pt_idx, mask, init_region, init_v,
           verbose_token, *extras):
        kwargs = dict(zip(keys, extras))
        if "plans" in kwargs:
            # Leaves arrive with a singleton shard axis; drop it so the
            # body sees this shard's own plan.
            from megba_tpu.ops.segtiles import squeeze_plans

            kwargs["plans"] = squeeze_plans(kwargs["plans"])
        return lm_solve(
            residual_jac_fn, cameras, points, obs, cam_idx, pt_idx, mask,
            option, axis_name=axes, verbose=verbose, cam_sorted=cam_sorted,
            initial_region=init_region,
            initial_v=init_v, verbose_token=verbose_token,
            **kwargs)

    # `traced`: retrace sentinel hook (analysis/retrace.py) — one count
    # per compilation of this SPMD program; zero cost once compiled.
    # The static world tag carries the mesh SHAPE, not just its size: a
    # 4-device 1-D mesh and a 2x2 2-D mesh are different programs.
    world_tag = "world" + "x".join(str(n) for n in mesh.devices.shape)
    fn = traced(
        "mesh.sharded", fn,
        static=static_key(residual_jac_fn, world_tag,
                          option, keys, verbose, cam_sorted, donate))
    sharded = shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=P())
    # Donate the replicated parameter blocks only when the caller opted
    # in (the internal flat_solve path does; the public entry point
    # defaults to donate=False so external device arrays survive the
    # call).  NEVER under the experimental fallback: there, donated
    # inputs aliased by replicated (out_specs=P()) outputs intermittently
    # surface freed-buffer garbage in the result (observed as ~1e-310
    # denormals in the world>1 parity tests); parameters are the small
    # arrays, so forgoing donation costs little off the native path.
    return jax.jit(
        sharded,
        donate_argnums=(0, 1) if (donate and SHARD_MAP_NATIVE) else ())


# Global program cache for long-lived engines.  jax.jit caches by callable
# identity, so rebuilding the closure every call would recompile the full
# LM+PCG program per solve; caching on (engine fn, mesh, option, operand
# layout) pays tracing + compilation once per configuration.  ProblemOption
# is frozen/hashable for exactly this purpose.  Per-problem closure engines
# use the caller-owned jit_cache path above instead.
_cached_sharded_solve = functools.lru_cache(maxsize=64)(_build_sharded_solve)
