from megba_tpu.parallel.mesh import (
    EDGE_AXIS,
    distributed_lm_solve,
    make_mesh,
    shard_edge_arrays,
)

__all__ = ["EDGE_AXIS", "distributed_lm_solve", "make_mesh", "shard_edge_arrays"]
