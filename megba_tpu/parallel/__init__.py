from megba_tpu.parallel.mesh import (
    EDGE_AXIS,
    distributed_lm_solve,
    make_mesh,
    shard_edge_arrays,
)
from megba_tpu.parallel.multihost import initialize_multihost

__all__ = [
    "EDGE_AXIS",
    "distributed_lm_solve",
    "initialize_multihost",
    "make_mesh",
    "shard_edge_arrays",
]
