"""Multi-host (multi-process) initialisation.

The reference is hard-capped at one process on one node — its NCCL comm
is created with the single-process `ncclCommInitAll`
(src/resource/handle_manager.cpp:17-22) and SURVEY.md §1 records "no
multi-process / multi-node support".  Here multi-host costs one call:
`initialize_multihost()` wires `jax.distributed`, after which
`jax.devices()` spans every host's chips, `make_mesh(total_chips)`
builds a global edge mesh, and the psums inside the solve ride ICI
within a slice and DCN across slices with zero further code changes.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax

# Parameters this module successfully initialized jax.distributed with
# (None until we did); used to keep repeat calls idempotent.
_initialized_with: Optional[Tuple] = None


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> dict:
    """Initialise JAX's distributed runtime (idempotent).

    With no arguments, relies on the cluster environment (TPU pod
    metadata / SLURM / GKE) exactly as `jax.distributed.initialize`
    does.  Returns a summary dict {process_index, process_count,
    local_devices, global_devices}.
    """
    global _initialized_with
    already = getattr(jax.distributed, "is_initialized", None)
    initialized = callable(already) and already()
    explicit = any(
        a is not None for a in (coordinator_address, num_processes, process_id)
    )
    params = (coordinator_address, num_processes, process_id)
    if initialized:
        # Idempotent on an exact repeat of OUR parameters; anything else
        # (different params, or an init we didn't perform) cannot be
        # applied and failing silently would leave hosts solo-solving.
        if explicit and params != _initialized_with:
            raise RuntimeError(
                "jax.distributed is already initialized with different "
                "parameters; call initialize_multihost before any other "
                "jax.distributed use")
    else:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
            _initialized_with = params
        except (RuntimeError, ValueError):
            # Auto-detection outside a cluster env: degrade to local
            # single-process.  But if the caller named ANY cluster
            # parameter they meant to join a pod — failing silently would
            # leave each host solo-solving, so re-raise.
            if explicit:
                raise
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def mesh_is_multiprocess(mesh) -> bool:
    """True when the mesh's devices span more than one OS process."""
    procs = {d.process_index for d in mesh.devices.flat}
    return len(procs) > 1


def globalize_for_mesh(mesh, x, spec):
    """Lift one host array into a global jax.Array for a multi-process mesh.

    A jitted program over a mesh that spans processes only accepts
    *global* arrays: every process contributes the shards its own
    devices hold.  Each process is expected to hold the FULL host-side
    value (the multi-host contract of flat_solve/solve_pgo: all hosts
    run the same host prep on the same problem), so
    `jax.make_array_from_callback` — which asks for exactly the index
    slices this process's devices own — is correct by construction for
    any device-to-process layout and any per-process device count.
    Pytrees (e.g. the tiled plans) are mapped leaf-wise with the same
    spec.  Call with host numpy values where possible: the callback
    then slices host memory directly (no device round-trip).
    """
    import numpy as np
    from jax.sharding import NamedSharding

    if x is None:
        return None
    sharding = NamedSharding(mesh, spec)

    def lift(leaf):
        arr = np.asarray(leaf)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    return jax.tree_util.tree_map(lift, x)


def dispatch_on_mesh(prog, mesh, args, specs):
    """Run a jitted mesh program with the right operand form.

    Single source of the multi-process dispatch sequence for BOTH solver
    families (parallel/mesh.distributed_lm_solve and models/pgo):
    under a multi-process mesh every operand is lifted into a global
    array per its partition spec, and the default device is pinned to a
    device THIS process owns (the mesh's first device may be remote).
    """
    if mesh_is_multiprocess(mesh):
        args = [globalize_for_mesh(mesh, a, s) for a, s in zip(args, specs)]
        dev0 = next(d for d in mesh.devices.flat
                    if d.process_index == jax.process_index())
    else:
        dev0 = mesh.devices.flat[0]
    with jax.default_device(dev0):
        return prog(*args)
