"""Multi-host (multi-process) initialisation.

The reference is hard-capped at one process on one node — its NCCL comm
is created with the single-process `ncclCommInitAll`
(src/resource/handle_manager.cpp:17-22) and SURVEY.md §1 records "no
multi-process / multi-node support".  Here multi-host costs one call:
`initialize_multihost()` wires `jax.distributed`, after which
`jax.devices()` spans every host's chips, `make_mesh(total_chips)`
builds a global edge mesh, and the psums inside the solve ride ICI
within a slice and DCN across slices with zero further code changes.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax

# Parameters this module successfully initialized jax.distributed with
# (None until we did); used to keep repeat calls idempotent.
_initialized_with: Optional[Tuple] = None


def _distributed_is_initialized() -> bool:
    """Whether jax.distributed is already up.

    `jax.distributed.is_initialized()` only exists on newer jax; on this
    jaxlib (0.4.x) fall back to the distributed global state's client —
    without the fallback an idempotent re-call would re-invoke
    `jax.distributed.initialize()` after backend init, which raises
    "must be called before any JAX computations are executed".
    """
    probe = getattr(jax.distributed, "is_initialized", None)
    if callable(probe):
        return bool(probe())
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return _initialized_with is not None


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> dict:
    """Initialise JAX's distributed runtime (idempotent).

    With no arguments, relies on the cluster environment (TPU pod
    metadata / SLURM / GKE) exactly as `jax.distributed.initialize`
    does.  Returns a summary dict {process_index, process_count,
    local_devices, global_devices}.
    """
    global _initialized_with
    initialized = _distributed_is_initialized()
    explicit = any(
        a is not None for a in (coordinator_address, num_processes, process_id)
    )
    params = (coordinator_address, num_processes, process_id)
    if initialized:
        # Idempotent on an exact repeat of OUR parameters; anything else
        # (different params, or an init we didn't perform) cannot be
        # applied and failing silently would leave hosts solo-solving.
        if explicit and params != _initialized_with:
            raise RuntimeError(
                "jax.distributed is already initialized with different "
                "parameters; call initialize_multihost before any other "
                "jax.distributed use")
    else:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
            _initialized_with = params
        except (RuntimeError, ValueError):
            # Auto-detection outside a cluster env: degrade to local
            # single-process.  But if the caller named ANY cluster
            # parameter they meant to join a pod — failing silently would
            # leave each host solo-solving, so re-raise.
            if explicit:
                raise
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def cpu_cross_process_collectives_available() -> bool:
    """Can this jaxlib's CPU client run MULTIPROCESS computations?

    The plain XLA:CPU client refuses cross-process programs outright
    ("Multiprocess computations aren't implemented on the CPU backend")
    unless it was created with a collectives implementation; jaxlib
    ships gloo TCP collectives on some platforms only.  Tests gate the
    localhost multi-process lane on this probe so a jaxlib without gloo
    skips (naming the limitation) instead of failing tier-1.
    """
    import warnings

    mods = []
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        try:  # the raw pybind module (this jaxlib's spelling)
            from jax.lib import xla_client as _xc

            mods.append(_xc._xla)
        except Exception:
            pass
        try:  # newer re-export
            from jax.lib import xla_extension as _xe

            mods.append(_xe)
        except Exception:
            pass
    return any(hasattr(m, "make_gloo_tcp_collectives") for m in mods)


def enable_cpu_cross_process_collectives() -> bool:
    """Select gloo CPU collectives for cross-process psums.

    Must run BEFORE the CPU backend initialises (the collectives object
    is wired into the client at creation, using the distributed runtime
    client — so `initialize_multihost` must also come before the first
    device query).  Returns False (and changes nothing) when this
    jaxlib has no gloo support, OR when a backend is already up — the
    flag flip would be silently ineffective then, and the caller would
    hit the very "Multiprocess computations aren't implemented on the
    CPU backend" failure this helper exists to prevent.
    """
    if not cpu_cross_process_collectives_available():
        return False
    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            return False  # too late: the client was built without gloo
    except Exception:
        pass  # private API moved; fall through and set the flag anyway
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    return True


def mesh_is_multiprocess(mesh) -> bool:
    """True when the mesh's devices span more than one OS process."""
    procs = {d.process_index for d in mesh.devices.flat}
    return len(procs) > 1


def globalize_for_mesh(mesh, x, spec):
    """Lift one host array into a global jax.Array for a multi-process mesh.

    A jitted program over a mesh that spans processes only accepts
    *global* arrays: every process contributes the shards its own
    devices hold.  Each process is expected to hold the FULL host-side
    value (the multi-host contract of flat_solve/solve_pgo: all hosts
    run the same host prep on the same problem), so
    `jax.make_array_from_callback` — which asks for exactly the index
    slices this process's devices own — is correct by construction for
    any device-to-process layout and any per-process device count.
    Pytrees (e.g. the tiled plans) are mapped leaf-wise with the same
    spec.  Call with host numpy values where possible: the callback
    then slices host memory directly (no device round-trip).
    """
    import numpy as np
    from jax.sharding import NamedSharding

    if x is None:
        return None
    sharding = NamedSharding(mesh, spec)

    def lift(leaf):
        arr = np.asarray(leaf)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    return jax.tree_util.tree_map(lift, x)


def dispatch_on_mesh(prog, mesh, args, specs):
    """Run a jitted mesh program with the right operand form.

    Single source of the multi-process dispatch sequence for BOTH solver
    families (parallel/mesh.distributed_lm_solve and models/pgo):
    under a multi-process mesh every operand is lifted into a global
    array per its partition spec, and the default device is pinned to a
    device THIS process owns (the mesh's first device may be remote).
    """
    if mesh_is_multiprocess(mesh):
        args = [globalize_for_mesh(mesh, a, s) for a, s in zip(args, specs)]
        dev0 = next(d for d in mesh.devices.flat
                    if d.process_index == jax.process_index())
    else:
        dev0 = mesh.devices.flat[0]
    with jax.default_device(dev0):
        return prog(*args)
