"""Multi-host (multi-process) initialisation.

The reference is hard-capped at one process on one node — its NCCL comm
is created with the single-process `ncclCommInitAll`
(src/resource/handle_manager.cpp:17-22) and SURVEY.md §1 records "no
multi-process / multi-node support".  Here multi-host costs one call:
`initialize_multihost()` wires `jax.distributed`, after which
`jax.devices()` spans every host's chips, `make_mesh(total_chips)`
builds a global edge mesh, and the psums inside the solve ride ICI
within a slice and DCN across slices with zero further code changes.
"""

from __future__ import annotations

from typing import Optional

import jax


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> dict:
    """Initialise JAX's distributed runtime (idempotent).

    With no arguments, relies on the cluster environment (TPU pod
    metadata / SLURM / GKE) exactly as `jax.distributed.initialize`
    does.  Returns a summary dict {process_index, process_count,
    local_devices, global_devices}.
    """
    already = getattr(jax.distributed, "is_initialized", None)
    initialized = callable(already) and already()
    explicit = any(
        a is not None for a in (coordinator_address, num_processes, process_id)
    )
    if initialized and explicit:
        raise RuntimeError(
            "jax.distributed is already initialized; explicit cluster "
            "parameters cannot be applied — call initialize_multihost "
            "before any other jax.distributed use")
    if not initialized:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        except (RuntimeError, ValueError):
            # Auto-detection outside a cluster env: degrade to local
            # single-process.  But if the caller named ANY cluster
            # parameter they meant to join a pod — failing silently would
            # leave each host solo-solving, so re-raise.
            if explicit:
                raise
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
