"""Multi-host (multi-process) initialisation.

The reference is hard-capped at one process on one node — its NCCL comm
is created with the single-process `ncclCommInitAll`
(src/resource/handle_manager.cpp:17-22) and SURVEY.md §1 records "no
multi-process / multi-node support".  Here multi-host costs one call:
`initialize_multihost()` wires `jax.distributed`, after which
`jax.devices()` spans every host's chips, `make_mesh(total_chips)`
builds a global edge mesh, and the psums inside the solve ride ICI
within a slice and DCN across slices with zero further code changes.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

import jax

# Parameters this module successfully initialized jax.distributed with
# (None until we did); used to keep repeat calls idempotent.  Reset by
# `shutdown_multihost`, which is what makes a later re-initialization at
# a DIFFERENT world size legal (the elastic shrink-world path).
_initialized_with: Optional[Tuple] = None

# Elastic bring-up heartbeat tuning: effectively-infinite windows.  The
# coordination-service liveness layer is deliberately neutered because
# BOTH of its failure reactions abort the surviving process on this
# jaxlib (probed, see initialize_multihost): app-level liveness
# (robustness/elastic.HeartbeatBoard) is the detector instead.
_ELASTIC_HEARTBEAT_S = 10
_ELASTIC_MAX_MISSING = 1_000_000


def _global_state():
    """jax's distributed global state (indirection for tests)."""
    from jax._src import distributed as _dist

    return _dist.global_state


def _distributed_is_initialized() -> bool:
    """Whether jax.distributed is already up.

    `jax.distributed.is_initialized()` only exists on newer jax; on this
    jaxlib (0.4.x) fall back to the distributed global state's client —
    without the fallback an idempotent re-call would re-invoke
    `jax.distributed.initialize()` after backend init, which raises
    "must be called before any JAX computations are executed".
    """
    probe = getattr(jax.distributed, "is_initialized", None)
    if callable(probe):
        return bool(probe())
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return _initialized_with is not None


def _elastic_connect(coordinator_address: str, process_id: int) -> object:
    """Build + connect a SURVIVABLE distributed-runtime client.

    `jax.distributed.initialize`'s client is built with the defaults
    that make peer loss fatal on this jaxlib (all three probed on
    jax 0.4.37 / jaxlib 0.4.36):

    - the default missed-heartbeat callback LOG(QFATAL)s the process;
    - a PYTHON callback cannot replace it — the pybind Status caster
      aborts (`std::bad_cast`) the moment a non-OK status is delivered,
      so the callback must simply never fire: heartbeat windows are set
      effectively infinite;
    - `shutdown_on_destruction=True` (the default) runs the ShutdownTask
      barrier from the C++ destructor at process exit, which blocks on a
      dead peer and then aborts the survivor.

    Hence: huge windows, a benign (never-invoked) callback, and no
    shutdown-on-destruction.  The client is installed into jax's
    distributed global state BEFORE any backend query, so the CPU
    backend picks it up as the gloo rendezvous KV store exactly as the
    stock path would.
    """
    from jax.lib import xla_extension as _xe

    client = _xe.get_distributed_runtime_client(
        coordinator_address, process_id,
        init_timeout=300,
        heartbeat_interval=_ELASTIC_HEARTBEAT_S,
        max_missing_heartbeats=_ELASTIC_MAX_MISSING,
        missed_heartbeat_callback=lambda *_: None,
        shutdown_on_destruction=False)
    client.connect()
    return client


def _install_distributed_state(client, coordinator_address: str,
                               num_processes: int, process_id: int) -> None:
    """Publish an externally-built client where jax (and the backend
    factories) look for it — the same fields `jax.distributed.initialize`
    fills (indirection point for the multihost unit tests)."""
    state = _global_state()
    state.client = client
    state.coordinator_address = coordinator_address
    state.num_processes = int(num_processes)
    state.process_id = int(process_id)


def serve_rendezvous(port: int, num_processes: int,
                     block: bool = True) -> object:
    """Host the coordination service as a STANDALONE rendezvous process.

    Elastic worlds keep the coordination service OUT of the solver
    ranks: a rank that hosts the service cannot exit cleanly once a
    peer has died (destroying the service cancels the local agent's
    error-poll RPC, whose status delivery aborts the process — the
    probed jaxlib hazard documented on `_elastic_connect`).  A
    sacrificial rendezvous process owns the service instead, exactly
    like an external etcd/rendezvous daemon in elastic training stacks;
    the harness SIGKILLs it when the world is done (no graceful
    teardown exists or is needed).  Run via
    `python -m megba_tpu.parallel.multihost --serve <port> <world>`.
    """
    from jax.lib import xla_extension as _xe

    service = _xe.get_distributed_runtime_service(
        f"[::]:{int(port)}", int(num_processes),
        heartbeat_interval=_ELASTIC_HEARTBEAT_S,
        max_missing_heartbeats=_ELASTIC_MAX_MISSING)
    print(f"rendezvous serving {num_processes} processes on port {port}",
          flush=True)
    if block:
        import time

        while True:  # killed, never joined
            time.sleep(3600)
    return service


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    elastic: bool = False,
) -> dict:
    """Initialise JAX's distributed runtime (idempotent).

    With no arguments, relies on the cluster environment (TPU pod
    metadata / SLURM / GKE) exactly as `jax.distributed.initialize`
    does.  Returns a summary dict {process_index, process_count,
    local_devices, global_devices}.

    `elastic=True` selects the SURVIVABLE bring-up for worlds that must
    outlive peer loss (robustness/elastic.py): explicit rendezvous
    parameters are required, `coordinator_address` must point at an
    external rendezvous process (`serve_rendezvous` — solver ranks are
    clients only), and the client is built so that a dead peer can
    never abort this process (see `_elastic_connect` for the probed
    jaxlib failure modes this avoids).  After `shutdown_multihost`, a
    process may legally re-initialize — including with different
    parameters, the shrink-world resume path.
    """
    global _initialized_with
    initialized = _distributed_is_initialized()
    explicit = any(
        a is not None for a in (coordinator_address, num_processes, process_id)
    )
    params = (coordinator_address, num_processes, process_id, bool(elastic))
    if initialized:
        # Idempotent on an exact repeat of OUR parameters; anything else
        # (different params, or an init we didn't perform) cannot be
        # applied and failing silently would leave hosts solo-solving.
        # Re-initialization at NEW parameters is legal only through
        # shutdown_multihost, which resets this record.
        if explicit and params != _initialized_with:
            raise RuntimeError(
                "jax.distributed is already initialized with different "
                "parameters; call shutdown_multihost() before "
                "re-initializing, or initialize_multihost before any "
                "other jax.distributed use")
    elif elastic:
        if not explicit or None in (coordinator_address, num_processes,
                                    process_id):
            raise ValueError(
                "elastic=True requires explicit coordinator_address / "
                "num_processes / process_id (the rendezvous process is "
                "external; there is no auto-detection)")
        client = _elastic_connect(coordinator_address, process_id)
        _install_distributed_state(
            client, coordinator_address, num_processes, process_id)
        _initialized_with = params
    else:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
            _initialized_with = params
        except (RuntimeError, ValueError):
            # Auto-detection outside a cluster env: degrade to local
            # single-process.  But if the caller named ANY cluster
            # parameter they meant to join a pod — failing silently would
            # leave each host solo-solving, so re-raise.
            if explicit:
                raise
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def shutdown_multihost(abandon: bool = False, timeout_s: float = 5.0) -> bool:
    """Tear down the distributed runtime so re-initialization is legal.

    Returns True when a runtime was actually torn down.  Two modes:

    - **Cooperative** (default): every rank calls this — the normal
      `jax.distributed.shutdown()` runs (its ShutdownTask barrier
      completes because everyone arrives), bounded by `timeout_s` on a
      helper thread.  If it fails to return in time (a peer died on the
      way out), the attempt is abandoned and state is force-reset.
    - **Abandon** (`abandon=True`): peers are presumed DEAD.  The
      barrier-bearing shutdown paths are never invoked — on this jaxlib
      they block on the dead peer and then abort the survivor (probed;
      see `_elastic_connect`) — and the service, if this process hosts
      one, is deliberately left running untouched: destroying it
      cancels the local agent's error-poll RPC, whose status delivery
      aborts the process.  jax-level references are dropped, which is
      all re-initialization (or a purely local shrink-world solve)
      needs.

    Either way `_initialized_with` is cleared, making a subsequent
    `initialize_multihost` — same OR different parameters — legal,
    while an exact-repeat call before shutdown stays idempotent.
    """
    global _initialized_with
    was_initialized = _distributed_is_initialized()
    _initialized_with = None
    if not was_initialized:
        return False
    state = _global_state()
    if not abandon:
        # The helper thread works on CAPTURED references, never on the
        # global state: if it wedges on a dead peer and unblocks only
        # after a later re-initialization installed a NEW client, it
        # must not clobber that state (jax.distributed.shutdown()
        # would — it nulls global_state fields whenever it returns).
        client = state.client
        service = getattr(state, "service", None)
        done = threading.Event()

        def _graceful():
            try:
                if client is not None:
                    client.shutdown()  # the ShutdownTask barrier
                if service is not None:
                    service.shutdown()
            except Exception:
                pass  # force-reset below either way
            finally:
                done.set()

        t = threading.Thread(target=_graceful, daemon=True,
                             name="multihost-shutdown")
        t.start()
        done.wait(timeout_s)
        # Fell through on timeout: the graceful path is wedged on a
        # dead peer; abandon it (daemon thread, captured refs only)
        # and force-reset exactly like abandon=True.
    state.client = None
    state.coordinator_address = None
    if getattr(state, "service", None) is not None and not abandon:
        state.service = None
    if getattr(state, "preemption_sync_manager", None) is not None:
        state.preemption_sync_manager = None
    return True


def cpu_cross_process_collectives_available() -> bool:
    """Can this jaxlib's CPU client run MULTIPROCESS computations?

    The plain XLA:CPU client refuses cross-process programs outright
    ("Multiprocess computations aren't implemented on the CPU backend")
    unless it was created with a collectives implementation; jaxlib
    ships gloo TCP collectives on some platforms only.  Tests gate the
    localhost multi-process lane on this probe so a jaxlib without gloo
    skips (naming the limitation) instead of failing tier-1.
    """
    import warnings

    mods = []
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        try:  # the raw pybind module (this jaxlib's spelling)
            from jax.lib import xla_client as _xc

            mods.append(_xc._xla)
        except Exception:
            pass
        try:  # newer re-export
            from jax.lib import xla_extension as _xe

            mods.append(_xe)
        except Exception:
            pass
    return any(hasattr(m, "make_gloo_tcp_collectives") for m in mods)


def enable_cpu_cross_process_collectives() -> bool:
    """Select gloo CPU collectives for cross-process psums.

    Must run BEFORE the CPU backend initialises (the collectives object
    is wired into the client at creation, using the distributed runtime
    client — so `initialize_multihost` must also come before the first
    device query).  Returns False (and changes nothing) when this
    jaxlib has no gloo support, OR when a backend is already up — the
    flag flip would be silently ineffective then, and the caller would
    hit the very "Multiprocess computations aren't implemented on the
    CPU backend" failure this helper exists to prevent.
    """
    if not cpu_cross_process_collectives_available():
        return False
    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            return False  # too late: the client was built without gloo
    except Exception:
        pass  # private API moved; fall through and set the flag anyway
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    return True


def mesh_is_multiprocess(mesh) -> bool:
    """True when the mesh's devices span more than one OS process."""
    procs = {d.process_index for d in mesh.devices.flat}
    return len(procs) > 1


def globalize_for_mesh(mesh, x, spec):
    """Lift one host array into a global jax.Array for a multi-process mesh.

    A jitted program over a mesh that spans processes only accepts
    *global* arrays: every process contributes the shards its own
    devices hold.  Each process is expected to hold the FULL host-side
    value (the multi-host contract of flat_solve/solve_pgo: all hosts
    run the same host prep on the same problem), so
    `jax.make_array_from_callback` — which asks for exactly the index
    slices this process's devices own — is correct by construction for
    any device-to-process layout and any per-process device count.
    Pytrees (e.g. the tiled plans) are mapped leaf-wise with the same
    spec.  Call with host numpy values where possible: the callback
    then slices host memory directly (no device round-trip).
    """
    import numpy as np
    from jax.sharding import NamedSharding

    if x is None:
        return None
    sharding = NamedSharding(mesh, spec)

    def lift(leaf):
        arr = np.asarray(leaf)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    return jax.tree_util.tree_map(lift, x)


def dispatch_on_mesh(prog, mesh, args, specs):
    """Run a jitted mesh program with the right operand form.

    Single source of the multi-process dispatch sequence for BOTH solver
    families (parallel/mesh.distributed_lm_solve and models/pgo):
    under a multi-process mesh every operand is lifted into a global
    array per its partition spec, and the default device is pinned to a
    device THIS process owns (the mesh's first device may be remote).
    """
    if mesh_is_multiprocess(mesh):
        args = [globalize_for_mesh(mesh, a, s) for a, s in zip(args, specs)]
        dev0 = next(d for d in mesh.devices.flat
                    if d.process_index == jax.process_index())
    else:
        dev0 = mesh.devices.flat[0]
    with jax.default_device(dev0):
        return prog(*args)


def _main(argv=None) -> int:
    """CLI: `python -m megba_tpu.parallel.multihost --serve <port> <world>`
    runs the standalone rendezvous process for an elastic world (see
    serve_rendezvous; SIGKILL it when the world is done)."""
    import sys

    argv = sys.argv[1:] if argv is None else list(argv)
    if len(argv) == 3 and argv[0] == "--serve":
        serve_rendezvous(int(argv[1]), int(argv[2]))
        return 0
    print("usage: python -m megba_tpu.parallel.multihost "
          "--serve <port> <num_processes>")
    return 2


if __name__ == "__main__":
    raise SystemExit(_main())
