"""Flat solve pipeline + one-call BAL convenience.

`flat_solve` is THE lowering pipeline from flat arrays to the jitted
solver — dtype cast, native camera sort, pad/shard, single- or
multi-device dispatch, and jit caching — shared by `BaseProblem.solve`,
`solve_bal`, and the example CLIs so the semantics live in exactly one
place.  The object facade (problem.py) mirrors the reference's g2o-style
API on top; `solve_bal` goes straight from a parsed `BALFile` (or path)
to the solver without building per-edge Python objects.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from megba_tpu.algo.lm import LMResult, lm_solve
from megba_tpu.analysis.retrace import static_key, traced
from megba_tpu.common import (
    PrecondKind,
    ProblemOption,
    strip_observability,
    validate_options,
)
from megba_tpu.core.fm import EDGE_QUANTUM
from megba_tpu.core.types import is_cam_sorted, pad_edges
from megba_tpu.io.bal import BALFile, load_bal
from megba_tpu import observability as _obs
from megba_tpu.observability.emit import next_verbose_token
from megba_tpu.parallel.mesh import (
    distributed_lm_solve,
    get_or_build_program,
    make_mesh,
)
from megba_tpu.utils.backend import warn_if_x64_unavailable
from megba_tpu.utils.timing import PhaseTimer


def default_use_tiled(dtype) -> bool:
    """Whether the scatter-free tiled engine is the default lowering.

    Float32 on TPU backends only: the tiled XLA fallback on CPU is
    slower and fatter than the chunked scatter-add build, and float64
    never rides the kernels.  MEGBA_TILED=1/0 force-enables/disables.
    One definition shared by flat_solve and bench.py so the bench can
    never measure a different engine than production selects.
    """
    if np.dtype(dtype) != np.float32:
        return False
    env = os.environ.get("MEGBA_TILED")
    if env is not None:
        return env != "0"
    return jax.default_backend() == "tpu"


def _build_single_solve(residual_jac_fn, option, keys, verbose, cam_sorted):
    """Jitted single-device solve.  The trust-region resume state rides as
    dynamic operands so chunked/checkpointed solves reuse one compilation;
    `plans` (a DualPlans pytree or None) rides as an operand too, so its
    index arrays are solver inputs rather than baked-in constants."""

    def fn(cameras, points, obs, cam_idx, pt_idx, mask, init_region, init_v,
           verbose_token, plans, *extras):
        return lm_solve(
            residual_jac_fn, cameras, points, obs, cam_idx, pt_idx, mask,
            option, verbose=verbose, cam_sorted=cam_sorted,
            plans=plans, initial_region=init_region,
            initial_v=init_v, verbose_token=verbose_token,
            **dict(zip(keys, extras)))

    # Donate the parameter blocks: the result's cameras/points alias the
    # inputs' buffers instead of allocating fresh ones (at Final scale
    # ~53 MB f32 of params per solve call; matters most for chunked /
    # checkpointed drivers that call the program in a loop).  Safe:
    # flat_solve materializes fresh feature-major operands per call and
    # never reads them after the solve.
    # `traced`: retrace sentinel hook (analysis/retrace.py) — counts one
    # trace per compilation of this program; zero cost once compiled.
    return jax.jit(
        traced("solve.single", fn,
               static=static_key(residual_jac_fn, option, keys, verbose,
                                 cam_sorted)),
        donate_argnums=(0, 1))


# Global program cache for long-lived engines (same pitfall and remedy as
# parallel.mesh._cached_sharded_solve).  Per-problem closure engines must
# NOT land here — a global entry would pin the closure (and the prototype
# edge it captures) past its problem's lifetime; they use a caller-owned
# jit_cache instead (see flat_solve).
_cached_single_solve = functools.lru_cache(maxsize=64)(_build_single_solve)


def flat_solve(
    residual_jac_fn,
    cameras: np.ndarray,
    points: np.ndarray,
    obs: np.ndarray,
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    option: ProblemOption,
    sqrt_info: Optional[np.ndarray] = None,
    edge_mask: Optional[np.ndarray] = None,
    cam_fixed: Optional[np.ndarray] = None,
    pt_fixed: Optional[np.ndarray] = None,
    verbose: bool = False,
    use_tiled: Optional[bool] = None,
    initial_region: Optional[float] = None,
    initial_v: Optional[float] = None,
    initial_dx: Optional[np.ndarray] = None,
    fault_plan=None,
    jit_cache: Optional[dict] = None,
    timer: Optional[PhaseTimer] = None,
    elastic_report: Optional[dict] = None,
    triage=None,
    factor=None,
    lower_only: bool = False,
) -> LMResult:
    """Lower flat arrays and run the solve (single- or multi-device).

    PUBLIC BOUNDARY: accepts the conventional edge-major numpy layout
    (cameras [Nc, cd], obs [nE, od], sqrt_info [nE, od, od]) and returns
    an LMResult with edge-major cameras/points.  Internally everything is
    feature-major (core/fm.py) — the transposes happen exactly once,
    here, on host numpy.

    Edges are camera-sorted here (native counting sort) if they are not
    already; `sqrt_info` rides the same permutation.  The edge axis is
    padded to a multiple of world_size * EDGE_QUANTUM (masked-out edges)
    so chunked builds, the Pallas assembly tiles and equal shards all get
    static shapes.

    `edge_mask` ([nE] 0/1, caller's edge order) multiplies into that
    internal padding mask: a 0 edge is EXACTLY the no-op a padded edge
    is (zero residual weight, zero cost contribution) without changing
    the program's static shape — so callers can soft-delete edges, and
    the serving layer's pre-padded buckets (serving/shape_class.py) can
    be replayed through this entry point bit-for-bit (the fleet parity
    tests drive a bucket lane and `flat_solve(..., edge_mask=...)` on
    identical operands).  Purely an operand: toggling it never
    recompiles.  `option.world_size` selects the mesh; jitted programs
    are cached per configuration — globally for long-lived engines, or in
    the caller-owned `jit_cache` dict when the engine is a per-problem
    closure whose lifetime must not exceed its problem's (BaseProblem
    passes its own dict).

    `initial_dx` ([Nc, cd], edge-major like `cameras`) seeds the
    warm-start carry under `SolverOption.warm_start` — the cross-chunk
    resume hook (`LMResult.dx_cam` of the previous chunk); ignored when
    warm starts are off.

    `use_tiled` selects the scatter-free tiled path (ops/segtiles):
    default ON for float32 solves on TPU backends (where it replaces
    every per-edge scatter/gather with block-aligned MXU reductions),
    OFF otherwise (float64 verification and CPU runs keep the chunked
    scatter-add build, whose transient memory is bounded).
    MEGBA_TILED=1/0 force-enables/disables.

    `fault_plan` (robustness.faults.FaultPlan, edge_nan in the CALLER's
    edge order) seeds a deterministic fault into the solve; its edge
    vector rides the same permutation/padding as `obs` so the poison
    lands on the same physical edges in every lowering, and the plan's
    window/offset are dynamic operands (a chunked driver slides the
    fault without recompiling).  Omitted entirely, the program carries
    no injection ops at all.

    `timer` (utils.timing.PhaseTimer, fresh one by default) accumulates
    the host-side phase wall clocks (lowering / sort / plan / program /
    dispatch — "dispatch" includes jit tracing+compilation on the first
    call of a configuration).  With telemetry enabled
    (MEGBA_TELEMETRY=<path> or `option.telemetry`) an extra blocking
    "execute" phase is timed and a SolveReport JSONL line is appended;
    with it disabled the solve stays fully asynchronous and the sink
    module is never even imported.

    `elastic_report` (a dict, robustness.elastic.ElasticMonitor.
    report_block()) attaches the elastic-distribution ledger to this
    call's SolveReport line — context only, like the serving layer's
    `fleet` block; ignored when telemetry is off and never an operand
    of the compiled program.

    `triage` (robustness.triage.TriagePolicy) arms PRE-FLIGHT health
    checks: the problem is structurally and geometrically checked on
    host (pure NumPy, a "triage" PhaseTimer phase) BEFORE any lowering
    or device work.  Under REJECT a degenerate problem raises the
    typed `ProblemRejected` (HealthReport attached) with ZERO device
    dispatch — the timer records a triage phase and no dispatch phase,
    and the retrace sentinel sees no new traces.  Under REPAIR the
    deterministic repairs merge into this call's operands (edge_mask
    multiplies, fixed masks OR, non-finite values sanitised) and the
    repair counters land as `triage_*` PhaseTimer events; under WARN
    the report is attached and the solve is unchanged.  The
    HealthReport rides `SolveReport.health` when telemetry is on.

    `factor` (a registered factor name or `factors.FactorSpec`) routes
    this solve through the factor registry: the arrays are validated
    against the spec's block dims (typed `FactorError` naming the
    offending axis; an unknown name raises typed `UnknownFactorError`
    HERE, before any device work), `residual_jac_fn=None` is resolved
    to the spec's engine via `factors.engine_for` (memoised — one
    config, one engine object, so the jit caches cannot split), robust
    kernels are refused typed on families with `robust_ok=False`, and
    the spec's triage hooks drive the geometric pre-flight checks.
    Without `factor` the call behaves exactly as it always has (the
    caller owns the engine; triage assumes the BAL family).

    `lower_only=True` returns the `jax.stages.Lowered` of the exact
    program this call would have dispatched — same host prep, same
    operands, same jit cache — without executing it.  This is the
    compiled-program auditor's entry point (analysis/program_audit.py):
    what it inspects IS the production program, not a replica.
    """
    factor_spec = None
    if factor is not None:
        from megba_tpu.factors import (
            engine_for,
            get_factor,
            validate_factor_arrays,
        )
        from megba_tpu.factors.registry import FactorError, require_schur
        from megba_tpu.ops.robust import RobustKind

        factor_spec = require_schur(get_factor(factor), "flat_solve")
        validate_factor_arrays(factor_spec, cameras, points, obs,
                               where="flat_solve")
        # Per-factor solver defaults (registry.resolve_refuse_ratio):
        # no built-in Schur family declares one today, but a custom
        # 7-dof-style factor that does gets the same treatment the
        # sim(3) PGO family gets in solve_pgo.
        from megba_tpu.factors.registry import apply_factor_solver_defaults

        option = apply_factor_solver_defaults(factor_spec, option)
        if (option.robust_kind != RobustKind.NONE
                and not factor_spec.robust_ok):
            raise FactorError(
                f"flat_solve: factor {factor_spec.name!r} is not "
                "robust-kernel eligible (robust_ok=False — e.g. a "
                "marginalization prior must not be IRLS-downweighted); "
                "submit with robust_kind=NONE")
        if residual_jac_fn is None:
            residual_jac_fn = engine_for(factor_spec, option.jacobian_mode)
    if residual_jac_fn is None:
        raise ValueError(
            "flat_solve needs residual_jac_fn or a registered factor= "
            "to resolve one from")
    # Resolve the telemetry target here (knob wins over env), then strip
    # the observability knobs (common.OBSERVABILITY_FIELDS): program
    # caches are keyed on `option` and must stay observability-agnostic
    # — turning telemetry or metrics on can never recompile.
    telemetry = option.telemetry or os.environ.get("MEGBA_TELEMETRY") or None
    report_option = option
    option = strip_observability(option)
    timer = PhaseTimer() if timer is None else timer
    # Touch the span recorder up front when MEGBA_TRACE is armed: its
    # first creation installs the PhaseTimer hook, so even a bare
    # flat_solve (no router/batcher to initialise it) records its
    # lowering/plan/dispatch phases as spans.  One env lookup when off.
    _obs.span_recorder()

    health = None
    if triage is not None:
        from megba_tpu.robustness.triage import triage_problem

        # Pre-flight triage BEFORE any lowering: a REJECT propagates
        # `ProblemRejected` out of this phase with nothing traced,
        # compiled or dispatched (the timer ends with a "triage" phase
        # and no "dispatch" phase — the zero-dispatch assertion the
        # tests pin).
        with timer.phase("triage"):
            # Caller-supplied mask/fixed operands are passed through so
            # the checks see the graph the SOLVER will see (a caller-
            # masked edge doesn't count toward degrees, a caller-fixed
            # point can't be "under-constrained").
            outcome = triage_problem(
                cameras, points, obs, cam_idx, pt_idx, triage,
                edge_mask=edge_mask, cam_fixed=cam_fixed,
                pt_fixed=pt_fixed, factor=factor_spec)
        health = outcome.report.to_dict()
        rep = outcome.repair
        if rep is not None and not rep.is_noop:
            for name, n in rep.counters().items():
                if n:
                    timer.count_event(f"triage_{name}", n)
            cameras, points, obs = rep.merged_arrays(cameras, points, obs)
            edge_mask, cam_fixed, pt_fixed = rep.merge_operands(
                edge_mask, cam_fixed, pt_fixed)

    dtype = np.dtype(option.dtype)
    warn_if_x64_unavailable(dtype)
    with timer.phase("lowering"):
        # copy=False: at Final-13682 scale obs alone is ~70MB; don't
        # duplicate arrays that are already the right dtype.
        cameras = np.asarray(cameras).astype(dtype, copy=False)
        points = np.asarray(points).astype(dtype, copy=False)
        obs = np.asarray(obs).astype(dtype, copy=False)
        cam_idx = np.asarray(cam_idx)
        pt_idx = np.asarray(pt_idx)
    n_edges_raw = int(cam_idx.shape[0])
    em = None
    if edge_mask is not None:
        em = np.asarray(edge_mask).astype(dtype, copy=False).reshape(-1)
        if em.shape[0] != n_edges_raw:
            raise ValueError(
                f"edge_mask has {em.shape[0]} entries for a problem "
                f"with {n_edges_raw} edges")
    fault_edge = None
    if fault_plan is not None:
        fault_edge = np.asarray(fault_plan.edge_nan)
        if fault_edge.shape[0] != n_edges_raw:
            raise ValueError(
                f"fault_plan.edge_nan has {fault_edge.shape[0]} entries "
                f"for a problem with {n_edges_raw} edges")

    ws = option.world_size
    mesh2d = bool(ws > 1 and option.use_schur
                  and option.solver_option.mesh_2d)
    fused = bool(option.use_schur and option.solver_option.fused_kernels)
    if mesh2d:
        if use_tiled:
            raise ValueError(
                "mesh_2d does not compose with the Pallas tiled plans "
                "(use_tiled=True); the 2-D lowering has its own "
                "camera-tile plan — pass use_tiled=False/None")
        use_tiled = False
    if fused and ws > 1 and not mesh2d:
        raise ValueError(
            "SolverOption.fused_kernels is implemented for the "
            "single-device tiled lowering and the 2-D mesh ring step; "
            "the 1-D multi-device lowerings keep the segtiles/XLA "
            "paths — pass fused_kernels=False, or mesh_2d=True for a "
            "fused distributed solve")
    if option.use_schur and option.solver_option.bf16 and not fused:
        # The bf16 MXU pipeline rides the XLA lowering: the tiled
        # coupling kernels (ops/segtiles) have no bf16 operand path, so
        # the default-tiled TPU lane silently measuring f32 kernels
        # would defeat the rung.  Explicit use_tiled=True is refused;
        # the default resolves to the chunked build.  The FUSED
        # edge-pipeline kernels (SolverOption.fused_kernels) DO carry
        # bf16 operand tiles, so the refusal is lifted when they are
        # on (the fused-path tiled+bf16 combination is the legal one).
        if use_tiled:
            raise ValueError(
                "SolverOption.bf16 does not compose with the tiled "
                "plans (use_tiled=True); the bf16 coupling products "
                "ride the XLA lowering — pass use_tiled=False/None, "
                "or enable SolverOption(fused_kernels=True), whose "
                "fused edge-pipeline kernels take bf16 operand tiles")
        use_tiled = False
    if fused and not mesh2d:
        # The fused kernels replace the tiled coupling pipeline; the
        # non-tiled XLA lowering has no edge plan for them to fuse.
        if use_tiled is not None and not use_tiled:
            raise ValueError(
                "SolverOption.fused_kernels needs the tiled edge plans "
                "(they carry the fused bucket ordering); pass "
                "use_tiled=True/None, or fused_kernels=False for the "
                "plain XLA lowering")
        use_tiled = True
    if use_tiled is None:
        use_tiled = default_use_tiled(dtype)

    from megba_tpu.common import EdgeOrder

    if (option.solver_option.edge_order == EdgeOrder.COOBS and not mesh2d):
        # PI-BA co-observation ordering for the 1-D paths (the 2-D plan
        # orders its own streams co-observation-first regardless): a
        # pure host pre-permutation of the caller's edge set — the later
        # camera sorts are stable, so the point-minor order survives
        # into every lowering.  Results agree at solver tolerance (sums
        # reorder), never bitwise; NATURAL keeps every existing program
        # byte-identical.
        from megba_tpu.ops.segtiles import coobservation_edge_order

        with timer.phase("sort"):
            operm = coobservation_edge_order(cam_idx, pt_idx)
            cam_idx, pt_idx, obs = cam_idx[operm], pt_idx[operm], obs[operm]
            if sqrt_info is not None:
                sqrt_info = np.asarray(sqrt_info)[operm]
            if em is not None:
                em = em[operm]
            if fault_edge is not None:
                fault_edge = fault_edge[operm]

    plans = None
    tile_plan_j = None
    tiles_info = None  # per-solve tile/reuse metrics (SolveReport.tiles)
    if mesh2d:
        # 2-D camera x edge lowering: the cached camera-tile plan
        # assigns every edge to its camera tile's column, orders each
        # column co-observation-first, and lays the padded stream out
        # in the P((EDGE_AXIS, CAM_AXIS)) device-block order; the
        # device half rides the program as a pytree operand exactly
        # like the cluster plans, so toggling mesh_2d never bakes
        # indices into a compiled program.
        from megba_tpu.ops.segtiles import (
            cached_camera_tile_plan,
            plan_cache_evictions,
        )
        from megba_tpu.parallel.mesh import factor_mesh_2d

        n_shards, n_blocks = factor_mesh_2d(
            ws, option.solver_option.cam_blocks)
        with timer.phase("plan"):
            evict0 = plan_cache_evictions()
            (tplan, tile_plan_j), plan_hit = cached_camera_tile_plan(
                cam_idx, pt_idx, cameras.shape[0], points.shape[0],
                n_shards, n_blocks)
            if plan_hit:
                timer.count_event("plan_cache_hit")
            evicted = plan_cache_evictions() - evict0
            if evicted:
                timer.count_event("plan_cache_evict", evicted)
            perm, pmask = tplan.perm, tplan.mask
            obs = obs[perm] * pmask[:, None].astype(dtype)
            cam_idx = tplan.cam_idx
            pt_idx = tplan.pt_idx
            mask = pmask.astype(dtype)
            if em is not None:
                # Padding slots repeat caller edge 0 under pmask 0, so
                # the soft-delete mask multiplies in exactly.
                mask = mask * em[perm]
            if sqrt_info is not None:
                sqrt_info = np.asarray(sqrt_info)[perm]
            if fault_edge is not None:
                from megba_tpu.robustness.faults import lower_edge_vector

                fault_edge = lower_edge_vector(fault_edge, perm, pmask)
            n_padded = obs.shape[0]
            tiles_info = {
                "plan": "mesh2d",
                "cam_blocks": tplan.cam_blocks,
                "tile_cams": tplan.tile_cams,
                "shard_points": tplan.shard_points,
                **{k: tplan.reuse[k] for k in sorted(tplan.reuse)},
            }
    elif use_tiled and ws > 1:
        # Sharded tiled lowering: contiguous per-shard edge chunks, each
        # with its own dual plans; the concatenated per-shard slot
        # streams form the edge axis (equal shard sizes by construction).
        from megba_tpu.ops.segtiles import (
            cached_sharded_dual_plans,
            plan_cache_evictions,
        )

        with timer.phase("plan"):
            evict0 = plan_cache_evictions()
            (perms, masks, cam_segs, plans), plan_hit = (
                cached_sharded_dual_plans(
                    cam_idx, pt_idx, cameras.shape[0], points.shape[0], ws))
            if plan_hit:
                timer.count_event("plan_cache_hit")
            evicted = plan_cache_evictions() - evict0
            if evicted:
                timer.count_event("plan_cache_evict", evicted)
            obs = np.concatenate([
                obs[perms[k]] * masks[k][:, None].astype(dtype)
                for k in range(ws)])
            # cam_segs keeps each shard's cam stream non-decreasing
            # (padding carries the block's running-max camera) so the
            # sorted-scatter promise downstream stays honest; masked
            # slots contribute zeros.
            cam_idx_sh = cam_segs.reshape(-1).astype(np.int32)
            pt_idx_sh = np.concatenate([
                np.where(masks[k] > 0, pt_idx[perms[k]], 0)
                for k in range(ws)]).astype(np.int32)
            if sqrt_info is not None:
                sqrt_info = np.concatenate(
                    [np.asarray(sqrt_info)[perms[k]] for k in range(ws)])
            if fault_edge is not None:
                from megba_tpu.robustness.faults import lower_edge_vector

                fault_edge = np.concatenate([
                    lower_edge_vector(fault_edge, perms[k], masks[k])
                    for k in range(ws)])
            cam_idx, pt_idx = cam_idx_sh, pt_idx_sh
            if em is not None:
                # Each shard's slot stream permutes the caller's edge
                # order; the soft-delete mask rides the same perms and
                # lands multiplicatively on the shard padding mask.
                mask = np.concatenate([
                    masks[k].astype(dtype) * em[perms[k]]
                    for k in range(ws)])
            else:
                mask = masks.reshape(-1).astype(dtype)
            n_padded = obs.shape[0]
    elif use_tiled:
        # Tiled lowering: the cam plan's slot order IS the edge axis from
        # here on (it subsumes the camera sort and quantum padding).
        from megba_tpu.ops.segtiles import (
            cached_dual_plans,
            plan_cache_evictions,
        )

        with timer.phase("plan"):
            evict0 = plan_cache_evictions()
            (plan_c, plans), plan_hit = cached_dual_plans(
                cam_idx, pt_idx, cameras.shape[0], points.shape[0])
            if plan_hit:
                timer.count_event("plan_cache_hit")
            evicted = plan_cache_evictions() - evict0
            if evicted:
                timer.count_event("plan_cache_evict", evicted)
            perm, pmask = plan_c.perm, plan_c.mask
            obs = obs[perm] * pmask[:, None].astype(dtype)
            cam_idx = plan_c.seg
            pt_idx = np.where(pmask > 0, pt_idx[perm], 0).astype(np.int32)
            mask = pmask.astype(dtype)
            if em is not None:
                mask = mask * em[perm]
            if sqrt_info is not None:
                sqrt_info = np.asarray(sqrt_info)[perm]
            if fault_edge is not None:
                from megba_tpu.robustness.faults import lower_edge_vector

                fault_edge = lower_edge_vector(fault_edge, perm, pmask)
            n_padded = obs.shape[0]
            if fused:
                # Fused edge-pipeline bucket plans, one per matvec
                # direction, built over the SAME cam-slot stream the
                # dual plans just produced (pmask marks its padding;
                # any soft-delete weights live in the coupling rows,
                # not the plan).  Host numpy, attached as optional
                # pytree fields — with fused_kernels off these stay
                # None and every program lowers byte-identically.
                import dataclasses as _dc

                from megba_tpu.ops.fused import build_fused_dual_plans

                fp_tp, fp_tc, dfp_tp, dfp_tc = build_fused_dual_plans(
                    cam_idx, pt_idx, pmask,
                    cameras.shape[0], points.shape[0])
                plans = _dc.replace(
                    plans, fused_to_pt=dfp_tp, fused_to_cam=dfp_tc)
            # Streaming-reuse + occupancy metrics of the planned stream
            # (SolveReport.tiles): the honest per-solve attribution of
            # what the tile ordering — and the fused kernels, when on —
            # actually have to work with.
            from megba_tpu.ops.fused import fused_plan_summary
            from megba_tpu.ops.segtiles import edge_stream_reuse

            tiles_info = {
                "plan": "tiled_1d",
                "occupancy": round(
                    plan_c.n_edges / max(1, plan_c.n_slots), 4),
                **edge_stream_reuse(cam_idx, pt_idx, plan_c.block,
                                    plans.pt.block, mask=pmask),
            }
            if fused:
                tiles_info["fused_to_pt"] = fused_plan_summary(fp_tp)
                tiles_info["fused_to_cam"] = fused_plan_summary(fp_tc)
    else:
        with timer.phase("sort"):
            if not is_cam_sorted(cam_idx):
                from megba_tpu.native import sort_edges_by_camera

                perm = sort_edges_by_camera(cam_idx, cameras.shape[0])
                cam_idx, pt_idx, obs = cam_idx[perm], pt_idx[perm], obs[perm]
                if sqrt_info is not None:
                    sqrt_info = np.asarray(sqrt_info)[perm]
                if fault_edge is not None:
                    fault_edge = fault_edge[perm]
                if em is not None:
                    em = em[perm]

            # Pad the edge axis: every shard must be a multiple of
            # EDGE_QUANTUM so chunk slices and shards are static-shape.
            obs, cam_idx, pt_idx, mask = pad_edges(
                obs, cam_idx, pt_idx, ws * EDGE_QUANTUM, dtype=dtype)
            n_padded = obs.shape[0]
            if em is not None:
                # 1*em on the real region, 0 on the pad region — for an
                # already-quantum-sized input this IS the caller's mask
                # bit-for-bit (1.0 * {0.0, 1.0} is exact).
                mask = mask * np.concatenate(
                    [em, np.zeros(n_padded - em.shape[0], dtype)])
            if fault_edge is not None:
                from megba_tpu.robustness.faults import lower_edge_vector

                fault_edge = lower_edge_vector(fault_edge,
                                               n_padded=n_padded)
    # Two-level preconditioner coarse space: the camera-cluster plan is
    # pure graph structure over the FINAL (post-sort/-plan, padded) edge
    # stream, planned on host once and cached behind the same
    # content-fingerprint LRU as the tile plans; it rides the program as
    # an ordinary pytree operand (like `plans`), so toggling precond
    # kinds never bakes indices into the compiled program.
    cluster_plan_j = None
    if (option.use_schur
            and option.solver_option.precond == PrecondKind.TWO_LEVEL):
        from megba_tpu.ops.segtiles import cached_cluster_plan

        with timer.phase("plan"):
            (_, cluster_plan_j), cl_hit = cached_cluster_plan(
                np.asarray(cam_idx), np.asarray(pt_idx),
                int(cameras.shape[0]), int(points.shape[0]),
                option.solver_option.coarse_clusters,
                mask=np.asarray(mask), world_size=ws,
                smooth_omega=option.solver_option.smooth_omega)
            if cl_hit:
                timer.count_event("cluster_plan_cache_hit")
    elif (option.use_schur
          and option.solver_option.precond == PrecondKind.MULTILEVEL):
        # Recursive hierarchy: same contract as the two-level plan (one
        # host plan over the final padded edge stream, cached), plus the
        # per-level aggregation chain; EVERY aggregation knob is in the
        # cache fingerprint so a SolverOption flip can never serve a
        # stale hierarchy.
        from megba_tpu.ops.segtiles import cached_multilevel_plan

        with timer.phase("plan"):
            (_, cluster_plan_j), cl_hit = cached_multilevel_plan(
                np.asarray(cam_idx), np.asarray(pt_idx),
                int(cameras.shape[0]), int(points.shape[0]),
                option.solver_option.coarse_clusters,
                mask=np.asarray(mask), world_size=ws,
                coarsen_factor=option.solver_option.coarsen_factor,
                max_levels=option.solver_option.max_levels,
                smooth_omega=option.solver_option.smooth_omega)
            if cl_hit:
                timer.count_event("cluster_plan_cache_hit")

    if sqrt_info is not None:
        si = np.asarray(sqrt_info).astype(dtype, copy=False)
        if si.shape[0] != n_padded:
            pad = n_padded - si.shape[0]
            eye = np.broadcast_to(
                np.eye(si.shape[1], dtype=dtype), (pad,) + si.shape[1:])
            si = np.concatenate([si, eye])
        # [nE, od, od] -> feature-major rows [od*od, nE]
        sqrt_info_j = np.ascontiguousarray(si.reshape(n_padded, -1).T)
    else:
        sqrt_info_j = None
    cam_fixed_j = None if cam_fixed is None else np.asarray(cam_fixed)
    pt_fixed_j = None if pt_fixed is None else np.asarray(pt_fixed)
    # Warm-start resume state rides the same optional-operand mechanism
    # as sqrt_info/fixed masks; feature-major like cameras.  Dropped when
    # warm starts are off so the program cache keys stay stable.
    initial_dx_j = None
    if initial_dx is not None and option.solver_option.warm_start:
        initial_dx_j = np.ascontiguousarray(
            np.asarray(initial_dx).astype(dtype, copy=False).T)
    fault_j = None
    if fault_plan is not None:
        fault_j = dataclasses.replace(
            fault_plan,
            edge_nan=np.ascontiguousarray(fault_edge),
            point_crush=np.asarray(fault_plan.point_crush),
            window=np.asarray(fault_plan.window, np.int32),
            offset=np.asarray(fault_plan.offset, np.int32))

    # Feature-major boundary transposes (host numpy, once per solve).
    # Stay on HOST here: the jitted program uploads each operand exactly
    # once on call — and the multi-process path builds global arrays
    # straight from host memory (a premature jnp.asarray would cost a
    # device->host->device round trip per operand there).
    with timer.phase("lowering"):
        cameras_fm = np.ascontiguousarray(cameras.T)
        points_fm = np.ascontiguousarray(points.T)
        obs_fm = np.ascontiguousarray(obs.T)

    problem_shape = {
        "num_cameras": int(cameras.shape[0]),
        "num_points": int(points.shape[0]),
        "num_edges": n_edges_raw,
        "num_edges_padded": int(n_padded),
        "world_size": ws,
    }
    if mesh2d:
        problem_shape["mesh"] = f"{n_shards}x{n_blocks}"

    if ws > 1:
        if mesh2d:
            from megba_tpu.parallel.mesh import make_mesh_2d

            mesh = make_mesh_2d(n_shards, n_blocks)
        else:
            mesh = make_mesh(ws)
        with timer.phase("dispatch"):
            result = distributed_lm_solve(
                residual_jac_fn, cameras_fm, points_fm,
                obs_fm, np.asarray(cam_idx), np.asarray(pt_idx),
                np.asarray(mask), option, mesh,
                sqrt_info=sqrt_info_j, cam_fixed=cam_fixed_j,
                pt_fixed=pt_fixed_j,
                verbose=verbose, cam_sorted=True, plans=plans,
                initial_region=initial_region, initial_v=initial_v,
                initial_dx=initial_dx_j, fault_plan=fault_j,
                cluster_plan=cluster_plan_j, tile_plan=tile_plan_j,
                jit_cache=jit_cache, donate=True, lower_only=lower_only)
        if lower_only:
            return result
        result = _result_to_edge_major(result)
        _maybe_emit_report(telemetry, report_option, result, timer,
                           problem_shape, elastic=elastic_report,
                           health=health, tiles=tiles_info)
        return result

    optional = [("sqrt_info", sqrt_info_j), ("cam_fixed", cam_fixed_j),
                ("pt_fixed", pt_fixed_j), ("initial_dx", initial_dx_j),
                ("fault_plan", fault_j), ("cluster_plan", cluster_plan_j)]
    keys = tuple(k for k, v in optional if v is not None)
    extras = [v for _, v in optional if v is not None]
    with timer.phase("program"):
        jitted = get_or_build_program(
            jit_cache, _cached_single_solve, _build_single_solve,
            residual_jac_fn, option, keys, verbose, True)
    ir = option.algo_option.initial_region if initial_region is None else initial_region
    iv = 2.0 if initial_v is None else initial_v

    # ONE operand list for both .lower() and the dispatch: the audited
    # program must be byte-for-byte the dispatched one.  Built inside
    # the dispatch phase so the jnp.asarray index/mask uploads stay part
    # of the timed dispatch cost, as they always were (telemetry phase
    # breakdowns must stay comparable across artifacts).
    with timer.phase("dispatch"):
        call_args = (
            cameras_fm, points_fm, obs_fm,
            jnp.asarray(cam_idx), jnp.asarray(pt_idx), jnp.asarray(mask),
            jnp.asarray(ir, dtype), jnp.asarray(iv, dtype),
            jnp.asarray(next_verbose_token(), jnp.int32), plans, *extras)
        if lower_only:
            return jitted.lower(*call_args)
        result = jitted(*call_args)
    result = _result_to_edge_major(result)
    _maybe_emit_report(telemetry, report_option, result, timer,
                       problem_shape, elastic=elastic_report,
                       health=health, tiles=tiles_info)
    return result


def _maybe_emit_report(telemetry, option, result, timer, problem,
                       elastic=None, health=None, tiles=None) -> None:
    """Append a SolveReport JSONL line when telemetry is on, and feed
    the per-solve metrics observables when the metrics plane is armed;
    no-op (no sink import, no device sync) when both are off."""
    registry = _obs.metrics_registry(getattr(option, "metrics", False))
    if not telemetry and registry is None:
        return
    # The report wants final scalars + the trace anyway, so the blocking
    # "execute" phase is honest accounting, not added overhead.  (The
    # metrics-only path pays the same sync: iteration counts live on
    # device.  Neither path adds a dispatch — the program is untouched.)
    with timer.phase("execute") as ph:
        ph.sync(result)
    if jax.process_index() != 0:
        return  # one report line per solve, not one per host
    if registry is not None:
        from megba_tpu.observability import metrics as _metrics
        from megba_tpu.common import status_name as _sn

        status = getattr(result, "status", None)
        registry.histogram(
            "megba_solve_lm_iterations",
            "LM iterations per solved problem",
            buckets=_metrics.ITER_BUCKETS).observe(
                int(result.iterations), bucket="unbatched", factor="-")
        registry.histogram(
            "megba_solve_pcg_iterations",
            "Total PCG iterations per solved problem",
            buckets=_metrics.ITER_BUCKETS).observe(
                int(result.pcg_iterations), bucket="unbatched", factor="-")
        if status is not None:
            registry.counter(
                "megba_solve_status_total",
                "Solve outcomes by SolveStatus name").inc(
                    1, status=_sn(status), bucket="unbatched")
    if not telemetry:
        return
    trace = getattr(result, "trace", None)
    if trace is not None:
        # Surface the robustness counters as PhaseTimer events (the
        # report is already paying the device sync): how many contained
        # recoveries the guards performed, and the per-LEVEL
        # preconditioner fallback counts — the trace carries one
        # enum-coded int32 per iteration (solver/precond.py
        # encode/decode: low bits = SCHUR_DIAG blocks fallen back to
        # Hpp, high bits = two-level coarse factors degraded to
        # block-Jacobi), decoded so a coarse-level degrade is visible
        # as its own event, not laundered into a block count.  (The
        # report module is imported below anyway — telemetry is on.)
        from megba_tpu.observability.report import _decode_fallback_totals

        iters = int(result.iterations)
        level = _decode_fallback_totals(trace, iters) or {}
        if level.get("block"):
            timer.count_event("precond_fallback", level["block"])
        if level.get("coarse"):
            timer.count_event("precond_fallback_coarse", level["coarse"])
        # Multilevel hierarchies: one event per DEGRADED coarse level
        # (bit l-1 of the code's high half), so a mid-hierarchy
        # truncation is visible as its own telemetry stream.
        for li, n in enumerate(level.get("coarse_levels") or []):
            if n:
                timer.count_event(f"precond_fallback_coarse_l{li + 1}", n)
        recov = getattr(result, "recoveries", None)
        if recov is not None and int(recov):
            timer.count_event("fault_recovery", int(recov))
    from megba_tpu.observability.report import append_report, build_report

    append_report(
        build_report(option, result, timer.as_dict(), problem,
                     elastic=elastic, health=health, tiles=tiles),
        telemetry)


def _result_to_edge_major(result: LMResult) -> LMResult:
    """Transpose the solved parameters back to the public [N, d] layout."""
    import dataclasses

    return dataclasses.replace(
        result,
        cameras=jnp.swapaxes(result.cameras, 0, 1),
        points=jnp.swapaxes(result.points, 0, 1),
        dx_cam=(None if result.dx_cam is None
                else jnp.swapaxes(result.dx_cam, 0, 1)))


def solve_bal(
    bal: Union[BALFile, str, os.PathLike],
    option: Optional[ProblemOption] = None,
    verbose: bool = False,
) -> Tuple[BALFile, LMResult]:
    """Solve a BAL problem end to end.

    Accepts a parsed `BALFile` or a path (.txt/.bz2).  Uses
    `option.jacobian_mode`, `option.compute_kind`, `option.world_size`,
    dtype, robust/mixed-precision settings.  Returns (solved BALFile with
    updated cameras/points and the ORIGINAL edge order, LMResult).
    """
    option = option or ProblemOption()
    validate_options(option)
    if not isinstance(bal, BALFile):
        bal = load_bal(bal, dtype=option.dtype)

    if verbose:
        from megba_tpu.native import degree_stats
        from megba_tpu.observability.emit import emit_problem_stats

        _, _, (max_cd, max_pd, nnz) = degree_stats(
            bal.cam_idx, bal.pt_idx, bal.num_cameras, bal.num_points)
        # Shared emitter (observability/emit.py): the same formatter the
        # telemetry pipeline documents, so stdout and reports can't drift.
        emit_problem_stats(bal.num_cameras, bal.num_points,
                           bal.num_observations, max_cd, max_pd, nnz)

    # Registry-dispatched: factor="bal" resolves the IDENTICAL engine
    # object the historical make_residual_jacobian_fn(mode=...) default
    # returned (factors/engine.py canonicalisation), so this refactor
    # is program-cache- and bitwise-neutral.
    result = flat_solve(
        None, bal.cameras, bal.points, bal.obs, bal.cam_idx, bal.pt_idx,
        option, verbose=verbose, factor="bal")

    solved = BALFile(
        cameras=np.asarray(result.cameras, dtype=np.float64),
        points=np.asarray(result.points, dtype=np.float64),
        obs=bal.obs,  # original order/values
        cam_idx=bal.cam_idx,
        pt_idx=bal.pt_idx,
    )
    return solved, result
