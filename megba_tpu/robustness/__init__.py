"""Fault containment and recovery (robustness layer).

Three pieces, spanning the solver stack:

- **On-device guards** live where the state lives: the LM loop
  (algo/lm.py, armed by `common.RobustOption(guards=True)`) detects
  non-finite steps, rolls back to the last accepted state and inflates
  damping; the PCG core (solver/pcg.py) detects Chronopoulos-Gear
  recurrence breakdown and cold-restarts in-loop.  Detection reads only
  scalars that are already psum-reduced, so the sharded path adds zero
  new collectives (the compiled-program auditor pins this with the
  `ba_guarded_w2_f32` canonical program).

- **Deterministic fault injection** (`robustness.faults`): a
  `FaultPlan` pytree rides the jitted program as a dynamic operand and
  poisons chosen edges / point blocks at chosen LM iterations — every
  guard is exercised by a seeded fault in CI, not just clean runs.

- **Host kill-resume harness** (`robustness.harness`): SIGKILLs a
  checkpointed-driver subprocess mid-chunk and resumes it, for
  preemption-safety tests that need a real process death rather than an
  in-process simulation.  `run_world_until_snapshot_then_kill` scales
  it to an N-rank world (kill one rank, assert the survivors exit on
  their own — the elastic no-wedge contract).

- **Pre-flight triage** (`robustness.triage`): host-side health checks
  BEFORE any device work — structural (connectivity, observation
  degrees, duplicate edges) and geometric (non-finite data,
  cheirality, parallax, initial-residual outliers) — with a
  REJECT / REPAIR / WARN policy: reject degenerate problems with a
  typed `ProblemRejected` and zero dispatch, or repair them
  deterministically through operands the programs already carry
  (edge_mask soft-deletes/downweights, cam_fixed/pt_fixed freezes,
  per-component gauge anchors).  The shift-left layer: what the
  guards above would contain at runtime, triage catches in host
  milliseconds.

- **Network fault injection** (`robustness.netfaults`): a
  deterministic in-process TCP proxy (`ChaosTcpProxy`) between a
  `FleetRouter` and its workers, injecting drop / delay / truncate /
  reorder / partition by seeded `NetFaultPlan` — every typed failure
  the federation transport promises (serving/transport.py) is
  exercised by a replayable fault sequence, not a flaky network.

- **Elastic distribution** (`robustness.elastic`): liveness detection
  (per-rank heartbeat files + injected-clock state machines), a
  collective watchdog bounding every chunk dispatch, typed
  `WorkerLost`/`CollectiveTimeout` failures at chunk boundaries, and
  `resume_elastic` — tear down the distributed runtime, re-lower the
  same problem at the surviving world size, continue from the latest
  schema-v3 snapshot.
"""

from megba_tpu.robustness.faults import (  # noqa: F401
    DispatchChaos,
    FaultPlan,
    InjectedDispatchError,
    close_fault_window,
    fault_active,
    fault_partition_specs,
    inert_fault_plan,
    lower_edge_vector,
    lower_fault_plan,
    make_nan_burst,
    make_point_indefinite_burst,
    poison_residuals,
    poison_system,
    stack_fault_plans,
    with_offset,
)
from megba_tpu.robustness.elastic import (  # noqa: F401
    CollectiveTimeout,
    CollectiveWatchdog,
    ElasticConfig,
    ElasticError,
    ElasticMonitor,
    HeartbeatBoard,
    RankState,
    WorkerLost,
    resume_elastic,
)
from megba_tpu.robustness.netfaults import (  # noqa: F401
    ChaosTcpProxy,
    NetFaultPlan,
)
from megba_tpu.robustness.harness import (  # noqa: F401
    WorldKillOutcome,
    run_to_completion,
    run_until_snapshot_then_kill,
    run_world_until_snapshot_then_kill,
)
from megba_tpu.robustness.triage import (  # noqa: F401
    CheckKind,
    Finding,
    HealthReport,
    ProblemRejected,
    TriageAction,
    TriageOutcome,
    TriagePolicy,
    TriageRepair,
    check_problem,
    connected_components,
    plan_repair,
    triage_problem,
)
