"""Pre-flight problem triage: host-side health checks + auto-repair.

Every other robustness layer is *reactive*: the on-device guards
(RobustOption), the fleet escalation ladder (serving/resilience.py) and
elastic resume (robustness/elastic.py) all pay device time — or a whole
failed solve — to discover that a problem was broken on arrival.  The
edge-wise BA formulation makes those failure modes *statically
predictable from the observation graph and the initial estimate*, on
host, in milliseconds:

- a point observed by fewer than two cameras has a (near-)singular Hll
  block — multiplicative LM damping scales its diagonal, it cannot fill
  the single-ray null space, so the Schur complement inherits the
  conditioning blow-up the PCG guards later fight;
- a disconnected camera component carries its own unanchored gauge —
  the system is structurally rank-deficient no matter the data;
- behind-camera / near-plane observations poison the FIRST
  linearisation (the -P/P.z projection divides by ~0), before any
  guard has an accepted state to roll back to;
- non-finite parameters or observations NaN-poison every psum-reduced
  scalar the solver computes;
- duplicate (cam, pt) edges double-count a factor;
- near-zero-parallax points make depth unobservable (near-singular Hll
  again, just through geometry instead of degree);
- extreme initial reprojection residuals are the gross outliers that
  stall the first trust-region steps.

This module detects ALL of the above in one structural pass (pure
NumPy over the index arrays) plus one vectorised geometric pass that
reuses the host projection math (io/synthetic.rotate_batch /
project_batch_depth) — no jit, no device, nothing compiled — and
either REJECTs the problem (typed `ProblemRejected` carrying the
`HealthReport`, ZERO device dispatch), REPAIRs it deterministically
with machinery the solver already trusts, or WARNs (report attached,
solve unchanged).

Repairs never re-index: shapes, shape classes and the retrace sentinel
are untouched.

- degenerate points (deg < 2, behind-camera remnants, non-finite) are
  frozen via `pt_fixed` and their edges soft-deleted through the
  `edge_mask` operand (identical to bucket padding: literal-zero
  contributions to every reduction);
- non-finite parameter blocks are additionally SANITISED to zeros on
  host — the edge mask multiplies residuals, and 0 * NaN is NaN, so a
  masked edge reading NaN params would still poison the cost;
- secondary connected components get one anchor camera each
  (`cam_fixed`), the same anchor-per-component policy the g2o reader
  applies to prior-less pose graphs (io/g2o.py);
- extreme-residual edges are DOWNWEIGHTED through the robust-kernel
  weight (ops/robust.rho_and_weight) folded into the edge mask: the
  mask multiplies r and J, so a mask value of sqrt(w) applies exactly
  the Huber weight w at the initial residual — a static one-shot
  robustification riding an operand the program already has.
"""

from __future__ import annotations

import dataclasses
import enum
import types
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from megba_tpu.utils.timing import monotonic_s


def _default_bal_hooks():
    """The historical BAL geometric hooks (host NumPy twins from
    io/synthetic) — what `factor=None` callers have always gotten.

    Duck-typed (SimpleNamespace with the `factors.FactorTriage` field
    names) rather than the registry dataclass itself: this module's
    contract is that it NEVER imports jax, and importing the factors
    package would pull the jnp-importing residual modules in.  Callers
    that hold a registered spec pass it via `factor=`; its `triage`
    attribute carries the real `FactorTriage` hooks, which this module
    only reads attributes off.
    """
    from megba_tpu.io.synthetic import camera_centers, project_batch_depth

    def project(cam_blocks, pt_blocks, obs):
        del obs
        return project_batch_depth(cam_blocks, pt_blocks)

    return types.SimpleNamespace(project_depth=project, uv_cols=(0, 2),
                                 camera_centers=camera_centers)


# Chunk size for the geometric pass: bounds the [nE, 3] float64
# temporaries the projection materialises (same budget reasoning as
# io/synthetic's generation chunking).
_GEOM_CHUNK = 4_000_000


class TriageAction(enum.Enum):
    """What to do with a problem that has degenerate findings."""

    REJECT = "reject"  # raise ProblemRejected; nothing reaches a device
    REPAIR = "repair"  # apply deterministic repairs, then solve
    WARN = "warn"  # attach the report, solve the problem as submitted


class CheckKind(enum.Enum):
    """One pre-flight check.  `degenerate` marks the kinds that predict
    a broken/poisoned solve (they drive `TriagePolicy.on_degenerate`);
    advisory kinds only ever annotate the report."""

    NONFINITE_CAMERA = "nonfinite_camera"
    NONFINITE_POINT = "nonfinite_point"
    NONFINITE_OBS = "nonfinite_obs"
    DUPLICATE_EDGE = "duplicate_edge"
    ORPHAN_CAMERA = "orphan_camera"  # degree 0 (advisory: runtime contains it)
    UNDER_CONSTRAINED_POINT = "under_constrained_point"  # deg < min_point_degree
    UNDER_CONSTRAINED_CAMERA = "under_constrained_camera"  # advisory
    DISCONNECTED = "disconnected"  # > 1 connected component (gauge-deficient)
    BEHIND_CAMERA = "behind_camera"  # cheirality violation at the initial estimate
    LOW_PARALLAX = "low_parallax"  # max ray spread below threshold
    EXTREME_RESIDUAL = "extreme_residual"  # initial reprojection outlier


# The kinds whose presence makes the problem "degenerate" — i.e. the
# statically-predicted solve-breakers the policy's on_degenerate action
# applies to.  ORPHAN_CAMERA and UNDER_CONSTRAINED_CAMERA are advisory:
# the system builder already gives edge-less blocks an identity
# (linear_system/builder.py) and damping bounds a weakly-observed
# camera, so neither predicts a failed solve.
DEGENERATE_KINDS = frozenset({
    CheckKind.NONFINITE_CAMERA,
    CheckKind.NONFINITE_POINT,
    CheckKind.NONFINITE_OBS,
    CheckKind.DUPLICATE_EDGE,
    CheckKind.UNDER_CONSTRAINED_POINT,
    CheckKind.DISCONNECTED,
    CheckKind.BEHIND_CAMERA,
    CheckKind.LOW_PARALLAX,
    CheckKind.EXTREME_RESIDUAL,
})


@dataclasses.dataclass(frozen=True)
class TriagePolicy:
    """Pre-flight policy: which checks run, thresholds, and the action.

    `on_degenerate` picks what happens when any degenerate finding
    (DEGENERATE_KINDS) is present: REJECT raises `ProblemRejected`
    before anything touches a device, REPAIR applies the deterministic
    repairs below, WARN attaches the report and solves as submitted.

    Thresholds: `min_point_degree` is the observation count below which
    a point's Hll block is predicted (near-)singular; `min_depth` is
    the cheirality margin (camera-frame z > -min_depth counts as
    behind/on the camera plane — BAL's visible half-space is z < 0);
    `min_parallax_rad` bounds the per-point viewing-ray spread below
    which depth is unobservable; `max_residual_px` flags initial
    reprojection outliers.  `geometric=False` skips the projection pass
    (structural checks only — e.g. when initial estimates are known
    garbage and a spanning-tree-style bootstrap follows).
    """

    on_degenerate: TriageAction = TriageAction.REJECT
    min_point_degree: int = 2
    # Advisory camera floor, in OBSERVATIONS: each observation is 2
    # residual rows, so the default of 5 flags cameras with <= 4
    # observations (8 rows) — fewer rows than the 9 camera dof.
    min_camera_degree: int = 5
    min_depth: float = 1e-6
    min_parallax_rad: float = 1e-3
    max_residual_px: float = 1e4
    structural: bool = True
    geometric: bool = True
    downweight_outliers: bool = True
    exemplar_cap: int = 8

    def __post_init__(self) -> None:
        if self.min_point_degree < 1:
            raise ValueError(
                f"min_point_degree must be >= 1, got {self.min_point_degree}")
        if self.min_depth < 0 or self.min_parallax_rad < 0:
            raise ValueError("min_depth and min_parallax_rad must be >= 0")
        if not self.max_residual_px > 0:
            raise ValueError(
                f"max_residual_px must be > 0, got {self.max_residual_px}")
        if self.exemplar_cap < 1:
            raise ValueError(
                f"exemplar_cap must be >= 1, got {self.exemplar_cap}")


@dataclasses.dataclass
class Finding:
    """One check's outcome: how many offenders, and a bounded sample.

    `exemplars` are indices in the check's own axis (camera / point /
    edge index — see `CheckKind`), capped at `TriagePolicy.exemplar_cap`
    so a million-orphan problem cannot turn its own health report into
    a memory problem."""

    kind: CheckKind
    count: int
    exemplars: List[int]
    detail: str = ""

    @property
    def degenerate(self) -> bool:
        return self.kind in DEGENERATE_KINDS

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind.value, "count": int(self.count),
                "exemplars": [int(i) for i in self.exemplars],
                "degenerate": self.degenerate, "detail": self.detail}


@dataclasses.dataclass
class HealthReport:
    """The pre-flight health record of one problem.

    JSON-round-trippable (rides `SolveReport.health` and the REJECT
    exception); `repair` is populated once a repair has been applied —
    the counters the aggregate CLI renders."""

    n_cam: int
    n_pt: int
    n_edge: int
    findings: List[Finding]
    n_components: int = 1
    action: Optional[str] = None  # the policy action actually taken
    triage_s: float = 0.0  # host wall clock of the checks
    repair: Optional[Dict[str, int]] = None  # points_fixed / edges_masked / ...
    # Which check families actually ran (TriagePolicy.structural /
    # .geometric): downstream gates key on this — the serving ingestion
    # gate (serving/batcher._validate_problem) only defers to triage
    # when the structural pass (which subsumes the duplicate-edge
    # check) really happened.
    structural: bool = True
    geometric: bool = True

    @property
    def degenerate(self) -> bool:
        return any(f.degenerate for f in self.findings)

    def counts(self) -> Dict[str, int]:
        """{check kind: offender count} over the non-empty findings."""
        return {f.kind.value: int(f.count) for f in self.findings}

    def finding(self, kind: CheckKind) -> Optional[Finding]:
        for f in self.findings:
            if f.kind == kind:
                return f
        return None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "n_cam": int(self.n_cam), "n_pt": int(self.n_pt),
            "n_edge": int(self.n_edge),
            "findings": [f.to_dict() for f in self.findings],
            "n_components": int(self.n_components),
            "degenerate": self.degenerate,
            "action": self.action,
            "triage_s": float(self.triage_s),
            "repair": None if self.repair is None else dict(self.repair),
            "structural": bool(self.structural),
            "geometric": bool(self.geometric),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "HealthReport":
        return cls(
            n_cam=int(d["n_cam"]), n_pt=int(d["n_pt"]),
            n_edge=int(d["n_edge"]),
            findings=[Finding(kind=CheckKind(f["kind"]),
                              count=int(f["count"]),
                              exemplars=[int(i) for i in f["exemplars"]],
                              detail=f.get("detail", ""))
                      for f in d.get("findings", [])],
            n_components=int(d.get("n_components", 1)),
            action=d.get("action"),
            triage_s=float(d.get("triage_s", 0.0)),
            repair=d.get("repair"),
            structural=bool(d.get("structural", True)),
            geometric=bool(d.get("geometric", True)),
        )

    def summary(self) -> str:
        parts = [f"{f.count} {f.kind.value}" for f in self.findings]
        head = (f"triage: {self.n_cam} cams / {self.n_pt} pts / "
                f"{self.n_edge} edges, {self.n_components} component(s)")
        return head + (": " + ", ".join(parts) if parts else ": clean")


class ProblemRejected(ValueError):
    """Raised when `TriagePolicy(on_degenerate=REJECT)` refuses a
    degenerate problem.  Carries the full `HealthReport` — and the
    contract that NOTHING was dispatched to a device: triage runs
    before lowering, so a rejected problem costs host milliseconds."""

    def __init__(self, report: HealthReport):
        self.report = report
        bad = ", ".join(f"{f.count} {f.kind.value}"
                        for f in report.findings if f.degenerate)
        super().__init__(
            f"problem rejected by pre-flight triage: {bad} "
            f"({report.n_cam} cams / {report.n_pt} pts / "
            f"{report.n_edge} edges)")


@dataclasses.dataclass
class TriageRepair:
    """The deterministic repair derived from a HealthReport.

    Everything is an OPERAND of the existing programs: `edge_mask`
    multiplies into the solve's padding mask (0 = soft-deleted edge,
    (0, 1) = robust downweight), `cam_fixed` / `pt_fixed` freeze
    parameter blocks, and `cameras` / `points` / `obs` are the
    host-sanitised arrays (non-finite values replaced by zeros on
    masked/frozen entries ONLY — a masked edge still multiplies its
    residual by 0, and 0 * NaN is NaN, so poison must be scrubbed at
    the source).  Fields are None when that aspect needed no repair.
    """

    edge_mask: Optional[np.ndarray] = None  # [nE] float64 in [0, 1]
    cam_fixed: Optional[np.ndarray] = None  # [Nc] bool
    pt_fixed: Optional[np.ndarray] = None  # [Np] bool
    cameras: Optional[np.ndarray] = None  # sanitised replacements
    points: Optional[np.ndarray] = None
    obs: Optional[np.ndarray] = None
    points_fixed: int = 0
    cams_fixed: int = 0  # frozen camera blocks (anchors included)
    cams_anchored: int = 0  # the gauge-anchor subset of cams_fixed
    edges_masked: int = 0
    edges_downweighted: int = 0

    @property
    def is_noop(self) -> bool:
        # Keyed on the OPERANDS, not the counters: a repair that only
        # freezes/sanitises a zero-degree non-finite camera has no
        # masked edges or anchors, yet must still be applied (the NaN
        # params would otherwise dispatch unscrubbed).
        return (self.edge_mask is None and self.cam_fixed is None
                and self.pt_fixed is None and self.cameras is None
                and self.points is None and self.obs is None)

    def counters(self) -> Dict[str, int]:
        return {
            "points_fixed": int(self.points_fixed),
            "cams_fixed": int(self.cams_fixed),
            "cams_anchored": int(self.cams_anchored),
            "edges_masked": int(self.edges_masked),
            "edges_downweighted": int(self.edges_downweighted),
        }

    def merge_operands(self, edge_mask=None, cam_fixed=None, pt_fixed=None):
        """Compose this repair with caller-supplied operands: edge masks
        MULTIPLY (a caller-deleted edge stays deleted, a downweight
        stacks), fixed masks OR.  THE one definition both integration
        points use (solve.flat_solve, serving/queue.FleetQueue), so the
        merge semantics cannot diverge.  Returns (edge_mask, cam_fixed,
        pt_fixed), each None when neither side supplied it."""
        em = self.edge_mask
        if em is not None and edge_mask is not None:
            em = np.asarray(edge_mask, np.float64).reshape(-1) * em
        elif em is None:
            em = edge_mask
        cf = self.cam_fixed
        if cf is not None and cam_fixed is not None:
            cf = np.asarray(cam_fixed, bool).reshape(-1) | cf
        elif cf is None:
            cf = cam_fixed
        pf = self.pt_fixed
        if pf is not None and pt_fixed is not None:
            pf = np.asarray(pt_fixed, bool).reshape(-1) | pf
        elif pf is None:
            pf = pt_fixed
        return em, cf, pf

    def merged_arrays(self, cameras, points, obs):
        """(cameras, points, obs) with this repair's host sanitisation
        applied — the original arrays wherever nothing was scrubbed."""
        return (cameras if self.cameras is None else self.cameras,
                points if self.points is None else self.points,
                obs if self.obs is None else self.obs)


@dataclasses.dataclass
class TriageOutcome:
    """What `triage_problem` decided: the report, the action taken, and
    the repair (None under WARN, or when the problem was clean)."""

    report: HealthReport
    action: TriageAction
    repair: Optional[TriageRepair] = None


def connected_components(cam_idx: np.ndarray, pt_idx: np.ndarray,
                         n_cam: int, n_pt: int,
                         edge_alive: Optional[np.ndarray] = None,
                         ) -> Tuple[int, np.ndarray, np.ndarray]:
    """Connected components of the bipartite camera-point graph.

    Pure-NumPy min-label propagation with path halving: each round
    propagates the minimum component label across every (alive) edge in
    both directions and then short-circuits label chains; rounds are
    O(nE + Nc + Np) and the count is logarithmic in the graph diameter
    for the hub-and-spoke co-visibility graphs BA produces.  Returns
    (n_components, cam_comp, pt_comp) with labels renumbered 0..k-1 in
    first-occurrence (camera-major) order — deterministic, so repair
    anchors are reproducible.  Vertices with no alive edges form their
    own singleton components.
    """
    ci = np.asarray(cam_idx, np.int64)
    pi = np.asarray(pt_idx, np.int64)
    if edge_alive is not None:
        keep = np.asarray(edge_alive, bool)
        ci, pi = ci[keep], pi[keep]
    label = np.arange(n_cam + n_pt, dtype=np.int64)
    pj = pi + n_cam
    while True:
        before = label
        m = np.minimum(label[ci], label[pj])
        nxt = label.copy()
        np.minimum.at(nxt, ci, m)
        np.minimum.at(nxt, pj, m)
        # Path halving: a label is itself a vertex id, so chasing it one
        # step collapses chains exponentially.
        nxt = np.minimum(nxt, nxt[nxt])
        label = nxt
        if np.array_equal(label, before):
            break
    # Renumber to dense 0..k-1.  np.unique sorts by label VALUE, and a
    # component's label is its minimum vertex id, so sorted order IS
    # first-occurrence order over the camera-major vertex axis.
    uniq, dense = np.unique(label, return_inverse=True)
    return int(uniq.shape[0]), dense[:n_cam], dense[n_cam:]


def huber_weight(s: np.ndarray, delta: float) -> np.ndarray:
    """IRLS weight rho'(s) of the Huber kernel over squared norms s.

    Host-NumPy twin of ops/robust.rho_and_weight's HUBER branch (same
    Ceres convention: threshold delta^2 on s, rho'(s) = delta/sqrt(s)
    beyond it); pinned against the jnp kernel by tests/test_triage.py.
    """
    d2 = delta * delta
    sqrt_s = np.sqrt(np.maximum(s, 1e-30))
    return np.where(s <= d2, 1.0, delta / sqrt_s)


def _exemplars(idx: np.ndarray, cap: int) -> List[int]:
    return [int(i) for i in idx[:cap]]


def check_problem(
    cameras: np.ndarray,
    points: np.ndarray,
    obs: np.ndarray,
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    policy: Optional[TriagePolicy] = None,
    edge_mask: Optional[np.ndarray] = None,
    cam_fixed: Optional[np.ndarray] = None,
    pt_fixed: Optional[np.ndarray] = None,
    factor=None,
) -> Tuple[HealthReport, Dict[str, np.ndarray]]:
    """Run every enabled check; return (report, internals).

    `factor` (a registered `factors.FactorSpec`, or None for the
    historical BAL behaviour) REGISTRY-DISPATCHES the checks: the
    geometric pass runs through the spec's `triage` hooks (projection +
    depth for cheirality/outliers, camera centers for parallax) — a
    factor WITHOUT hooks (priors, planar) skips the projective checks
    entirely, because cheirality is meaningless for a non-projective
    residual, and the report records `geometric=False` so downstream
    gates know those checks never ran (advisory absence, not a clean
    bill); the duplicate-edge check honours `spec.unique_edges` (a rig
    repeats (body, point) pairs by construction).  Structural and
    non-finite checks are factor-agnostic and always run.

    `edge_mask` / `cam_fixed` / `pt_fixed` are the caller's OWN solve
    operands, and the checks honour them: a caller-masked (mask <= 0)
    edge is already dead, so it does not count toward degrees,
    connectivity, parallax, duplicates or per-edge geometric findings
    — masking one of a point's two observations makes that point deg-1
    here, exactly as the solver will see it; a caller-fixed point or
    camera has an identity Hessian block and zero gradient, so it is
    never flagged under-constrained/low-parallax/orphan, and a
    component containing a caller-fixed camera already has its gauge.
    Non-finite data is flagged REGARDLESS of masks: the edge mask
    multiplies residuals on device and `0 * NaN` is `NaN`, so a NaN
    behind a caller-masked edge still poisons the cost.

    `internals` carries the full per-axis boolean masks the repair
    planner consumes (the report itself only stores counts + bounded
    exemplars): `bad_edge` (edges to soft-delete), `weight`
    ([nE] float downweight for outlier edges, 1.0 elsewhere),
    `bad_cam` / `bad_pt` (blocks to freeze), `sanitize_cam` /
    `sanitize_pt` / `sanitize_obs` (non-finite entries to scrub),
    `pre_dead` / `pre_fixed_cam` / `pre_fixed_pt` (the caller operands
    above), `cam_comp` / `pt_comp` + `n_components`.

    Host NumPy only — nothing here traces, compiles or touches a
    device (tests/test_triage.py pins the module's jit-freedom through
    the analysis callgraph).
    """
    policy = policy or TriagePolicy()
    # Factor dispatch: hooks + duplicate-edge semantics off the spec
    # (duck-typed attribute reads — see _default_bal_hooks on why the
    # registry itself is never imported here).
    if factor is None:
        hooks = _default_bal_hooks()
        unique_edges = True
    else:
        hooks = getattr(factor, "triage", None)
        unique_edges = bool(getattr(factor, "unique_edges", True))
    geometric_on = bool(policy.geometric) and hooks is not None
    t0 = monotonic_s()
    cameras = np.asarray(cameras)
    points = np.asarray(points)
    obs = np.asarray(obs)
    ci = np.asarray(cam_idx, np.int64).reshape(-1)
    pi = np.asarray(pt_idx, np.int64).reshape(-1)
    n_cam, n_pt, n_edge = (int(cameras.shape[0]), int(points.shape[0]),
                           int(ci.shape[0]))
    if pi.shape[0] != n_edge or obs.shape[0] != n_edge:
        raise ValueError(
            f"index/observation length mismatch: cam_idx {n_edge}, "
            f"pt_idx {pi.shape[0]}, obs {obs.shape[0]}")
    if n_edge and (ci.min() < 0 or ci.max() >= n_cam
                   or pi.min() < 0 or pi.max() >= n_pt):
        raise ValueError("observation indices out of range")
    pre_dead = np.zeros(n_edge, bool)
    if edge_mask is not None:
        em = np.asarray(edge_mask).reshape(-1)
        if em.shape[0] != n_edge:
            raise ValueError(
                f"edge_mask has {em.shape[0]} entries for a problem "
                f"with {n_edge} edges")
        pre_dead = ~(em > 0)
    pre_fixed_cam = (np.zeros(n_cam, bool) if cam_fixed is None
                     else np.asarray(cam_fixed, bool).reshape(-1))
    pre_fixed_pt = (np.zeros(n_pt, bool) if pt_fixed is None
                    else np.asarray(pt_fixed, bool).reshape(-1))

    findings: List[Finding] = []
    cap = policy.exemplar_cap
    bad_edge = np.zeros(n_edge, bool)  # edges to soft-delete
    weight = np.ones(n_edge, np.float64)  # robust downweight (1 = keep)
    bad_cam = np.zeros(n_cam, bool)  # camera blocks to freeze
    bad_pt = np.zeros(n_pt, bool)  # point blocks to freeze
    san_cam = np.zeros(n_cam, bool)  # non-finite params to scrub
    san_pt = np.zeros(n_pt, bool)
    san_obs = np.zeros(n_edge, bool)

    def add(kind: CheckKind, mask: np.ndarray, detail: str = "") -> None:
        n = int(np.count_nonzero(mask))
        if n:
            findings.append(Finding(
                kind=kind, count=n,
                exemplars=_exemplars(np.nonzero(mask)[0], cap),
                detail=detail))

    # ---- non-finite data (always on: every later check reads it) -----
    nf_cam = ~np.isfinite(cameras).all(axis=1)
    nf_pt = ~np.isfinite(points).all(axis=1)
    nf_obs = ~np.isfinite(obs).all(axis=1)
    add(CheckKind.NONFINITE_CAMERA, nf_cam, "non-finite camera parameters")
    add(CheckKind.NONFINITE_POINT, nf_pt, "non-finite point coordinates")
    add(CheckKind.NONFINITE_OBS, nf_obs, "non-finite pixel observations")
    san_cam |= nf_cam
    san_pt |= nf_pt
    san_obs |= nf_obs
    bad_cam |= nf_cam
    bad_pt |= nf_pt
    # An edge touching poisoned data is dead either way.
    bad_edge |= nf_obs | nf_cam[ci] | nf_pt[pi]

    if policy.structural and n_edge and unique_edges:
        # ---- duplicate (cam, pt) edges: keep the FIRST occurrence ----
        # Factor-gated: families declaring unique_edges=False (rig,
        # priors) encode repeated index pairs deliberately.
        # Caller-masked copies don't double-count a factor, so the scan
        # runs over the caller-alive subset only.
        live = np.nonzero(~pre_dead)[0]
        key = ci[live] * np.int64(n_pt) + pi[live]
        _, first, counts = np.unique(key, return_index=True,
                                     return_counts=True)
        if (counts > 1).any():
            dup_live = np.ones(live.shape[0], bool)
            dup_live[first] = False  # first occurrence of a key survives
            dup = np.zeros(n_edge, bool)
            dup[live[dup_live]] = True
            add(CheckKind.DUPLICATE_EDGE, dup,
                "duplicate (cam, pt) edges (double-counted factors)")
            bad_edge |= dup

    # Scrubbed float64 working copies for BOTH geometric passes (the
    # projection and the parallax rays): NaN params would make every
    # derived check on those edges NaN — they are already flagged
    # above; zero stand-ins keep the passes finite.
    if geometric_on and n_edge:
        cams_f = np.where(san_cam[:, None], 0.0,
                          cameras.astype(np.float64, copy=False))
        pts_f = np.where(san_pt[:, None], 0.0,
                         points.astype(np.float64, copy=False))
        ob_f = np.where(san_obs[:, None], 0.0,
                        obs.astype(np.float64, copy=False))

    if geometric_on and n_edge:
        uv = np.empty((n_edge, 2))
        depth = np.empty((n_edge,))
        for lo in range(0, n_edge, _GEOM_CHUNK):
            hi = min(lo + _GEOM_CHUNK, n_edge)
            uv[lo:hi], depth[lo:hi] = hooks.project_depth(
                cams_f[ci[lo:hi]], pts_f[pi[lo:hi]], ob_f[lo:hi])

        # ---- cheirality: behind (or on) the camera plane -------------
        # BAL-convention visible half-space is z < 0 (every projective
        # hook returns the camera-frame depth in that convention);
        # z >= -min_depth means the -P/P.z projection is about to
        # divide by ~0 or the point sits behind the camera — either way
        # the first linearisation is poisoned.  Already-dead edges
        # (flagged above, or caller-masked) are excluded so nothing
        # double-reports.
        behind = (depth >= -policy.min_depth) & ~bad_edge & ~pre_dead
        add(CheckKind.BEHIND_CAMERA, behind,
            "point behind/on camera plane at the initial estimate")
        bad_edge |= behind

        # ---- extreme initial reprojection residuals ------------------
        lo_c, hi_c = hooks.uv_cols
        with np.errstate(invalid="ignore", over="ignore"):
            rnorm = np.linalg.norm(uv - ob_f[:, lo_c:hi_c], axis=1)
        extreme = (~np.isfinite(rnorm) | (rnorm > policy.max_residual_px)
                   ) & ~bad_edge & ~pre_dead
        add(CheckKind.EXTREME_RESIDUAL, extreme,
            f"initial reprojection residual > {policy.max_residual_px:g} px")
        if policy.downweight_outliers:
            # Huber weight at the initial residual, delta = the outlier
            # threshold: the NumPy twin of ops/robust.rho_and_weight's
            # HUBER branch (w'(s) = delta/sqrt(s) beyond delta^2;
            # tests/test_triage.py pins the two against each other so
            # the conventions can never drift).  The edge MASK
            # multiplies r and J, so sqrt of the IRLS weight on the
            # mask applies exactly weight rho'(s) to the factor —
            # the robust-kernel path, folded into an operand the
            # program already has.
            finite = np.isfinite(rnorm)
            s = np.where(finite, rnorm, 0.0) ** 2
            w2 = huber_weight(s, policy.max_residual_px)
            weight = np.where(extreme & finite, np.sqrt(w2), weight)
            # A non-finite residual on an otherwise-alive edge cannot be
            # downweighted meaningfully — soft-delete it.
            bad_edge |= extreme & ~finite
        else:
            bad_edge |= extreme

    # ---- degrees on the SURVIVING graph ------------------------------
    # Structural degree checks run on the post-mask graph (check-flagged
    # AND caller-masked edges both excluded) so a repair composes:
    # masking a duplicate/behind-camera edge can drop a point under the
    # degree floor, and that point must be caught in the same pass (no
    # fixpoint iteration needed: freezing a point never revives an
    # edge).
    alive = ~bad_edge & ~pre_dead
    deg_pt = np.bincount(pi[alive], minlength=n_pt)
    deg_cam = np.bincount(ci[alive], minlength=n_cam)

    if policy.structural:
        orphan_cam = (deg_cam == 0) & ~bad_cam & ~pre_fixed_cam
        add(CheckKind.ORPHAN_CAMERA, orphan_cam,
            "camera with zero (surviving) observations")
        # Caller-fixed points are exempt: a fixed block is an identity
        # in the Hessian with a zero gradient — nothing to go singular.
        under_pt = ((deg_pt < policy.min_point_degree)
                    & ~bad_pt & ~pre_fixed_pt)
        add(CheckKind.UNDER_CONSTRAINED_POINT, under_pt,
            f"point observed by < {policy.min_point_degree} cameras "
            "(predicted-singular Hll block)")
        bad_pt |= under_pt
        under_cam = ((deg_cam > 0)
                     & (deg_cam < policy.min_camera_degree)
                     & ~bad_cam & ~pre_fixed_cam)
        # min_camera_degree is in OBSERVATIONS (2 residual rows each);
        # the default 5 flags cameras whose <= 8 rows cannot determine
        # 9 dof.  Advisory — damping bounds the step.
        add(CheckKind.UNDER_CONSTRAINED_CAMERA, under_cam,
            f"camera observed by < {policy.min_camera_degree} edges "
            "(fewer residual rows than camera dof at the default)")

    # ---- low parallax (geometric, needs surviving degrees, a
    # camera-centers hook AND 3D points for the viewing rays) ----------
    if (geometric_on and n_edge and policy.min_parallax_rad > 0
            and hooks.camera_centers is not None
            and points.shape[1] == 3):
        # Camera centers [Nc, 3] from the factor hook (BAL/radial:
        # C = -R^T t; rig: the body center); cams_f / pts_f are the
        # scrubbed copies hoisted above the projection.
        centers = hooks.camera_centers(cams_f)
        # Per-edge unit viewing rays, accumulated per point; the spread
        # proxy is the max angular deviation from the point's mean ray
        # (>= half the true max pairwise angle, <= the full one).
        ray_sum = np.zeros((n_pt, 3))
        min_cos = np.ones(n_pt)
        for lo in range(0, n_edge, _GEOM_CHUNK):
            hi = min(lo + _GEOM_CHUNK, n_edge)
            a = alive[lo:hi]
            ray = pts_f[pi[lo:hi]] - centers[ci[lo:hi]]
            nrm = np.linalg.norm(ray, axis=1, keepdims=True)
            ray = ray / np.where(nrm > 0, nrm, 1.0)
            np.add.at(ray_sum, pi[lo:hi][a], ray[a])
        mean_nrm = np.linalg.norm(ray_sum, axis=1, keepdims=True)
        mean_ray = ray_sum / np.where(mean_nrm > 0, mean_nrm, 1.0)
        for lo in range(0, n_edge, _GEOM_CHUNK):
            hi = min(lo + _GEOM_CHUNK, n_edge)
            a = alive[lo:hi]
            ray = pts_f[pi[lo:hi]] - centers[ci[lo:hi]]
            nrm = np.linalg.norm(ray, axis=1, keepdims=True)
            ray = ray / np.where(nrm > 0, nrm, 1.0)
            cosdev = np.sum(ray * mean_ray[pi[lo:hi]], axis=1)
            np.minimum.at(min_cos, pi[lo:hi][a], cosdev[a])
        spread = np.arccos(np.clip(min_cos, -1.0, 1.0))
        low_parallax = ((deg_pt >= policy.min_point_degree)
                        & (spread < 0.5 * policy.min_parallax_rad)
                        & ~bad_pt & ~pre_fixed_pt)
        add(CheckKind.LOW_PARALLAX, low_parallax,
            f"viewing-ray spread < {policy.min_parallax_rad:g} rad "
            "(depth unobservable)")
    else:
        low_parallax = np.zeros(n_pt, bool)

    # ---- connectivity (on the surviving graph) -----------------------
    n_components = 1
    cam_comp = np.zeros(n_cam, np.int64)
    pt_comp = np.zeros(n_pt, np.int64)
    if policy.structural:
        n_components, cam_comp, pt_comp = connected_components(
            ci, pi, n_cam, n_pt, edge_alive=alive)
        # Count CAMERA-bearing components: orphan points/cameras are
        # their own singletons and are reported separately, and a
        # frozen-singleton component is not a gauge problem.
        comp_cams = np.bincount(cam_comp[deg_cam > 0],
                                minlength=max(n_components, 1))
        real_comps = int(np.count_nonzero(comp_cams))
        # A component already containing a caller-fixed camera has its
        # gauge (the g2o prior-reached case); only UNANCHORED extra
        # components are gauge-deficient — and if no component is
        # anchored, the largest unanchored one keeps the solver's
        # default (damping) gauge handling, matching the single-
        # component no-op.
        anchored = np.zeros(max(n_components, 1), bool)
        anchored[cam_comp[pre_fixed_cam & (deg_cam > 0)]] = True
        unanchored = [int(c) for c in np.nonzero(comp_cams)[0]
                      if not anchored[c]]
        if real_comps > 1 and unanchored:
            if not anchored.any():
                main = max(unanchored, key=lambda c: comp_cams[c])
                flagged = [c for c in unanchored if c != main]
            else:
                flagged = unanchored
            if flagged:
                reps = [int(np.nonzero((cam_comp == c)
                                       & (deg_cam > 0))[0][0])
                        for c in flagged[:cap]]
                findings.append(Finding(
                    kind=CheckKind.DISCONNECTED,
                    count=len(flagged),
                    exemplars=reps,
                    detail=f"{real_comps} camera components "
                           f"({len(flagged)} without a gauge anchor — "
                           "each carries a free gauge)"))

    report = HealthReport(
        n_cam=n_cam, n_pt=n_pt, n_edge=n_edge, findings=findings,
        n_components=n_components, triage_s=monotonic_s() - t0,
        # `geometric` records what actually RAN: a hook-less factor
        # (priors, planar) reports False even under a geometric policy,
        # so downstream gates never mistake "not applicable" for
        # "checked clean".
        structural=policy.structural, geometric=geometric_on)
    internals = {
        "bad_edge": bad_edge, "weight": weight,
        "bad_cam": bad_cam, "bad_pt": bad_pt,
        "low_parallax": low_parallax,
        "sanitize_cam": san_cam, "sanitize_pt": san_pt,
        "sanitize_obs": san_obs,
        "pre_dead": pre_dead, "pre_fixed_cam": pre_fixed_cam,
        "pre_fixed_pt": pre_fixed_pt,
        "deg_cam": deg_cam, "deg_pt": deg_pt,
        "cam_comp": cam_comp, "pt_comp": pt_comp,
        "n_components": n_components,
    }
    return report, internals


def plan_repair(
    cameras: np.ndarray,
    points: np.ndarray,
    obs: np.ndarray,
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    report: HealthReport,
    internals: Dict[str, np.ndarray],
    policy: Optional[TriagePolicy] = None,
) -> TriageRepair:
    """Derive the deterministic repair for a checked problem.

    Composition order (each step only ever REMOVES constraints, so one
    pass is a fixpoint for everything except camera degrees, which stay
    advisory): (1) soft-delete dead edges (non-finite / duplicate /
    behind-camera) and fold the robust downweight into the mask;
    (2) freeze degenerate points (`pt_fixed`) and soft-delete their
    remaining edges — EXCEPT low-parallax points, which are frozen but
    keep their edges (their projections are consistent; as fixed
    landmarks they still constrain rotation, the classic far-point
    treatment); (3) freeze non-finite cameras and anchor one camera per
    secondary connected component (the g2o anchor-per-component policy);
    (4) scrub non-finite params/obs to zeros on frozen/masked entries
    (the mask MULTIPLIES residuals; 0 * NaN is NaN).
    """
    policy = policy or TriagePolicy()
    pi = np.asarray(pt_idx, np.int64).reshape(-1)

    bad_edge = internals["bad_edge"].copy()
    weight = internals["weight"]
    pt_fixed = internals["bad_pt"].copy()
    cam_fixed = internals["bad_cam"].copy()

    # Low-parallax points: freeze, keep edges (see docstring).  Their
    # full membership rides internals (the report only stores bounded
    # exemplars); internals["bad_pt"] excludes them by construction.
    pt_fixed |= internals["low_parallax"]

    # Degenerate (non-low-parallax) points lose their remaining edges
    # (edges the caller already masked are not re-counted as repairs).
    drop_pt = internals["bad_pt"]
    bad_edge |= drop_pt[pi] & ~internals["pre_dead"]

    points_fixed = int(np.count_nonzero(pt_fixed))

    # Gauge anchoring: one camera per unanchored secondary component
    # (components already holding a caller-fixed camera are skipped,
    # and with no anchors anywhere the largest component keeps the
    # solver's default gauge handling — so a clean single-component
    # problem is untouched).  Mirrors the DISCONNECTED finding's
    # flagged set exactly.
    cams_anchored = 0
    disc = report.finding(CheckKind.DISCONNECTED)
    if disc is not None:
        cam_comp = internals["cam_comp"]
        deg_cam = internals["deg_cam"]
        pre_fixed_cam = internals["pre_fixed_cam"]
        n_comp = max(int(internals["n_components"]), 1)
        comp_cams = np.bincount(cam_comp[deg_cam > 0], minlength=n_comp)
        anchored = np.zeros(n_comp, bool)
        anchored[cam_comp[pre_fixed_cam & (deg_cam > 0)]] = True
        unanchored = [int(c) for c in np.nonzero(comp_cams)[0]
                      if not anchored[c]]
        if not anchored.any() and unanchored:
            unanchored.remove(max(unanchored, key=lambda c: comp_cams[c]))
        for c in unanchored:
            anchor = int(np.nonzero((cam_comp == c) & (deg_cam > 0))[0][0])
            if not cam_fixed[anchor]:
                cam_fixed[anchor] = True
                cams_anchored += 1

    edges_masked = int(np.count_nonzero(bad_edge))
    down = (~bad_edge) & (weight < 1.0)
    edges_downweighted = int(np.count_nonzero(down))

    edge_mask = None
    if edges_masked or edges_downweighted:
        edge_mask = np.where(bad_edge, 0.0, weight)

    # Host sanitisation of non-finite values (frozen blocks and masked
    # edges only — finite data is NEVER rewritten).
    cameras_out = points_out = obs_out = None
    if internals["sanitize_cam"].any():
        cameras_out = np.where(internals["sanitize_cam"][:, None],
                               np.zeros((), cameras.dtype), cameras)
    if internals["sanitize_pt"].any():
        points_out = np.where(internals["sanitize_pt"][:, None],
                              np.zeros((), points.dtype), points)
    if internals["sanitize_obs"].any():
        obs_out = np.where(internals["sanitize_obs"][:, None],
                           np.zeros((), obs.dtype), obs)

    return TriageRepair(
        edge_mask=edge_mask,
        cam_fixed=cam_fixed if cam_fixed.any() else None,
        pt_fixed=pt_fixed if pt_fixed.any() else None,
        cameras=cameras_out, points=points_out, obs=obs_out,
        points_fixed=points_fixed,
        cams_fixed=int(np.count_nonzero(cam_fixed)),
        cams_anchored=cams_anchored,
        edges_masked=edges_masked,
        edges_downweighted=edges_downweighted,
    )


def triage_problem(
    cameras: np.ndarray,
    points: np.ndarray,
    obs: np.ndarray,
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    policy: Optional[TriagePolicy] = None,
    edge_mask: Optional[np.ndarray] = None,
    cam_fixed: Optional[np.ndarray] = None,
    pt_fixed: Optional[np.ndarray] = None,
    factor=None,
) -> TriageOutcome:
    """Check one problem and act on the policy.

    `edge_mask` / `cam_fixed` / `pt_fixed` are the caller's own solve
    operands, honoured by the checks (see `check_problem`) — the
    returned repair composes with them via
    `TriageRepair.merge_operands`.  `factor` (a registered
    `factors.FactorSpec` or None = BAL) registry-dispatches the
    geometric hooks and duplicate-edge semantics (see `check_problem`).

    Returns a `TriageOutcome`; raises `ProblemRejected` (report
    attached) when the problem is degenerate under REJECT.  Clean
    problems take the WARN path regardless of policy: no repair, no
    rewriting, report says clean — so arming triage on healthy traffic
    is a pure no-op apart from the host check pass.
    """
    policy = policy or TriagePolicy()
    report, internals = check_problem(
        cameras, points, obs, cam_idx, pt_idx, policy,
        edge_mask=edge_mask, cam_fixed=cam_fixed, pt_fixed=pt_fixed,
        factor=factor)
    if not report.degenerate:
        report.action = TriageAction.WARN.value
        return TriageOutcome(report=report, action=TriageAction.WARN)
    action = policy.on_degenerate
    report.action = action.value
    if action == TriageAction.REJECT:
        raise ProblemRejected(report)
    if action == TriageAction.WARN:
        return TriageOutcome(report=report, action=action)
    repair = plan_repair(cameras, points, obs, cam_idx, pt_idx,
                         report, internals, policy)
    report.repair = repair.counters()
    return TriageOutcome(report=report, action=action, repair=repair)
