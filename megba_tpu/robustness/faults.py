"""Deterministic fault injection at the residual / linear-system boundary.

A `FaultPlan` is a small pytree of DYNAMIC operands that rides the
jitted LM program exactly like the optional sqrt_info / warm-start
operands (solve.flat_solve threads it; parallel/mesh shards `edge_nan`
on the edge axis and replicates the rest).  Because the window and
offset are data, a chunked/checkpointed driver can slide the fault
across chunk boundaries without recompiling, and the same compiled
program serves faulted and clean runs of one configuration.

Two fault families, matching the failure modes the guards contain:

- `edge_nan` ([nE] float): NaN added to the residual rows of chosen
  edges while the window is active — a transient data fault (bad DMA,
  corrupted host buffer) that poisons the cost/gradient reductions.
- `point_crush` ([Np] float): the Hll rows of chosen points are crushed
  toward zero after the system build, so Hll^-1 blows up and the Schur
  complement S = Hpp - Hpl Hll^-1 Hlp goes INDEFINITE while every
  scalar stays finite — the breakdown mode the PCG guard detects via
  sign-flipped gamma/delta.  (Negating Hll would make S *more*
  positive definite — the subtrahend flips sign — which is why the
  indefiniteness fault crushes instead.)

Iteration indexing: a linearisation is stamped with the LM iteration
whose system it produces — the pre-loop linearisation and every
linearisation evaluated at carry `k` share stamp `k`, and the stamp is
shifted into GLOBAL iterations by `offset` (the checkpointed driver
sets it to the chunk's resume iteration).  The window is the half-open
global-iteration range `[start, stop)`.

Injection is exact: inactive windows add literal 0.0 / scale by 1.0, so
a plan whose window never opens changes results only at the level of
`-0.0 + 0.0` normalisation; omitting the plan entirely removes the
injection ops from the program altogether.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One seeded fault: what to poison, and when (global LM iterations).

    Frozen: a FaultPlan of PartitionSpecs doubles as the shard_map
    in_specs tree (fault_partition_specs), which lands in hashable jit
    cache keys.
    """

    edge_nan: jax.Array  # [nE] float: NaN at poisoned edges, 0 elsewhere
    point_crush: jax.Array  # [Np] float: 1 at points whose Hll is crushed
    window: jax.Array  # [2] int32: global-iteration [start, stop)
    offset: jax.Array  # scalar int32: global iteration of local k = 0


# Hll crush factor: small enough that Hll^-1 dominates the Schur
# subtrahend (indefinite S), large enough that every f32 intermediate
# stays finite.
_CRUSH = 1e-8


def make_nan_burst(n_edges: int, edges: Sequence[int], start: int, stop: int,
                   n_points: int = 0, dtype=np.float32) -> FaultPlan:
    """NaN residual burst on `edges` for global iterations [start, stop)."""
    edge_nan = np.zeros((n_edges,), dtype)
    edge_nan[np.asarray(list(edges), np.int64)] = np.nan
    return FaultPlan(
        edge_nan=edge_nan,
        point_crush=np.zeros((n_points,), dtype),
        window=np.asarray([start, stop], np.int32),
        offset=np.int32(0),
    )


def make_point_indefinite_burst(n_points: int, points: Sequence[int],
                                start: int, stop: int, n_edges: int = 0,
                                dtype=np.float32) -> FaultPlan:
    """Crush the Hll blocks of `points` for global iterations [start, stop).

    The crushed blocks invert to huge (finite) values, the Schur
    subtrahend Hpl Hll^-1 Hlp overwhelms Hpp, and S goes indefinite —
    the PCG guard's sign-flipped-delta breakdown mode, with every
    scalar still finite.
    """
    crush = np.zeros((n_points,), dtype)
    crush[np.asarray(list(points), np.int64)] = 1.0
    return FaultPlan(
        edge_nan=np.zeros((n_edges,), dtype),
        point_crush=crush,
        window=np.asarray([start, stop], np.int32),
        offset=np.int32(0),
    )


def with_offset(plan: FaultPlan, offset: int) -> FaultPlan:
    """Shift the plan so local iteration 0 maps to global `offset`."""
    return dataclasses.replace(plan, offset=np.int32(offset))


def inert_fault_plan(n_edges: int, n_points: int = 0,
                     dtype=np.float32) -> FaultPlan:
    """A plan whose window never opens: zero poison, window [0, 0).

    The serving chaos harness stacks one plan per batch lane; lanes
    without a seeded fault ride an inert plan so every lane of the
    faulted program sees an identical operand STRUCTURE.  An inert
    plan's injection is the documented `+ 0.0` / `* 1.0` no-op, and —
    decisive for the batch-mate-isolation contract — two runs that
    differ only in ANOTHER lane's plan rows keep this lane's operands
    bit-identical, so its trajectory is bitwise unchanged.
    """
    return FaultPlan(
        edge_nan=np.zeros((n_edges,), dtype),
        point_crush=np.zeros((n_points,), dtype),
        window=np.zeros((2,), np.int32),
        offset=np.int32(0),
    )


def close_fault_window(plan: FaultPlan) -> FaultPlan:
    """The plan with its window forced shut ([0, 0)) — the unpoisoned
    CONTROL for chaos experiments: same program, same operand shapes,
    only the poison gate differs."""
    return dataclasses.replace(plan, window=np.zeros((2,), np.int32))


def lower_fault_plan(plan: FaultPlan, *, n_edges: int, n_points: int,
                     dtype, perm: Optional[np.ndarray] = None) -> FaultPlan:
    """Lower one plan onto a padded shape class (serving layer).

    `edge_nan` rides the same camera-sort permutation the padded
    problem's edges took (`perm`, from shape_class.pad_to_class) and is
    zero-padded to the bucket's edge count; `point_crush` is zero-padded
    to the bucket's point count (padding points are fixed identity
    blocks — crushing them is meaningless, so zeros are exact).  A plan
    built without an edge/point axis (size 0) lowers to all-zeros.
    """
    edge = np.asarray(plan.edge_nan).astype(dtype, copy=False)
    if edge.shape[0] == 0:
        edge = np.zeros((n_edges,), dtype)
    else:
        edge = lower_edge_vector(edge, perm=perm, n_padded=n_edges)
    if edge.shape[0] != n_edges:
        raise ValueError(
            f"fault plan edge_nan has {np.asarray(plan.edge_nan).shape[0]} "
            f"edges; problem lowers to {n_edges}")
    crush = np.asarray(plan.point_crush).astype(dtype, copy=False)
    if crush.shape[0] > n_points:
        raise ValueError(
            f"fault plan point_crush has {crush.shape[0]} points; bucket "
            f"holds {n_points}")
    if crush.shape[0] < n_points:
        crush = np.concatenate(
            [crush, np.zeros((n_points - crush.shape[0],), dtype)])
    return FaultPlan(edge_nan=edge, point_crush=crush,
                     window=np.asarray(plan.window, np.int32),
                     offset=np.int32(plan.offset))


def stack_fault_plans(plans: Sequence[FaultPlan]) -> FaultPlan:
    """Stack same-shape plans onto a leading lane axis (vmap operand).

    The batched faulted program (serving/compile_pool.py) vmaps the LM
    solve with in_axes=0 on the plan pytree; each lane reads only its
    own rows, so a poisoned lane and its inert batch-mates share one
    compiled program while staying numerically independent.
    """
    if not plans:
        raise ValueError("stack_fault_plans needs at least one plan")
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *plans)


def fault_active(plan: FaultPlan, k) -> jax.Array:
    """Replicated bool scalar: is the window open at local iteration k?"""
    g = jnp.asarray(k, jnp.int32) + plan.offset
    return (g >= plan.window[0]) & (g < plan.window[1])


def poison_residuals(r: jax.Array, plan: FaultPlan, k) -> jax.Array:
    """Add the (window-gated) edge poison to the [od, nE] residual rows."""
    active = fault_active(plan, k)
    poison = jnp.where(active, plan.edge_nan,
                       jnp.zeros_like(plan.edge_nan)).astype(r.dtype)
    return r + poison[None, :]


def poison_system(system, plan: FaultPlan, k):
    """Crush the Hll rows of the planned points while the window is open.

    `system` is a linear_system.builder.SchurSystem; Hll is replicated
    ([pd*pd, Np] rows), so the scale vector is replicated too and the
    sharded path is untouched.
    """
    if plan.point_crush.shape[0] != system.Hll.shape[1]:
        # Plans built without a point axis (pure edge faults) skip the
        # system transform entirely — no dead multiply in the program.
        return system
    active = fault_active(plan, k)
    dt = system.Hll.dtype
    scale = jnp.where(active & (plan.point_crush > 0),
                      jnp.asarray(_CRUSH, dt), jnp.asarray(1.0, dt))
    return dataclasses.replace(
        system, Hll=system.Hll * scale[None, :])


def fault_partition_specs(edge_spec=None):
    """shard_map in_specs tree for a FaultPlan operand (edge axis only
    on `edge_nan`; everything else replicated).  `edge_spec` overrides
    the edge-following spec — the 2-D mesh passes its
    P((EDGE_AXIS, CAM_AXIS)) split."""
    from jax.sharding import PartitionSpec as P

    from megba_tpu.parallel.mesh import EDGE_AXIS

    if edge_spec is None:
        edge_spec = P(EDGE_AXIS)
    return FaultPlan(edge_nan=edge_spec, point_crush=P(),
                     window=P(), offset=P())


class InjectedDispatchError(RuntimeError):
    """The exception DispatchChaos raises — distinguishable from real
    dispatch failures in logs and assertions."""


@dataclasses.dataclass
class DispatchChaos:
    """Deterministic host-level chaos for the fleet dispatch path.

    Where `FaultPlan` poisons the NUMERICS inside a compiled program,
    this poisons the SERVICE around it: the dispatcher consults
    `before_dispatch(bucket)` right after taking a batch, and the hook
    either raises `InjectedDispatchError` (driving the retry /
    circuit-breaker paths) or sleeps `delay_s` (driving deadline-miss
    pressure without racing the wall clock).

    Determinism: `fail_first` fails the first N dispatches of every
    matching bucket — exact, order-independent per bucket.  `fail_rate`
    additionally fails a seeded pseudo-random subset: each bucket gets
    its own `np.random.default_rng` derived from (`seed`, bucket name),
    so a fixed submission order replays the identical failure sequence.
    `buckets` (names as `str(ShapeClass)`) restricts chaos to specific
    buckets; None means all.
    """

    fail_first: int = 0
    fail_rate: float = 0.0
    delay_s: float = 0.0
    seed: int = 0
    buckets: Optional[frozenset] = None

    def __post_init__(self) -> None:
        if not 0.0 <= self.fail_rate <= 1.0:
            raise ValueError(f"fail_rate must be in [0, 1], got "
                             f"{self.fail_rate}")
        if self.fail_first < 0 or self.delay_s < 0:
            raise ValueError("fail_first and delay_s must be >= 0")
        self._lock = threading.Lock()
        self._counts: dict = {}
        self._rngs: dict = {}

    def dispatches(self, bucket: str) -> int:
        """How many dispatches this bucket has seen (tests/telemetry)."""
        with self._lock:
            return self._counts.get(bucket, 0)

    def before_dispatch(self, bucket: str) -> None:
        """Called by the dispatcher with the bucket's name; raises
        `InjectedDispatchError` when this dispatch is chosen to fail."""
        if self.buckets is not None and bucket not in self.buckets:
            return
        with self._lock:
            n = self._counts.get(bucket, 0)
            self._counts[bucket] = n + 1
            if self.fail_rate > 0.0:
                rng = self._rngs.get(bucket)
                if rng is None:
                    rng = np.random.default_rng(np.random.SeedSequence(
                        [self.seed, *bucket.encode()]))
                    self._rngs[bucket] = rng
                roll = float(rng.random())
            else:
                roll = 1.0
        if self.delay_s > 0.0:
            time.sleep(self.delay_s)
        if n < self.fail_first or roll < self.fail_rate:
            from megba_tpu import observability as _obs

            flight = _obs.flight_recorder()
            if flight is not None:
                # Injected faults land in the flight ring like real
                # ones: a crash dump must show the chaos that drove it.
                flight.record("chaos_injection", bucket=bucket,
                              dispatch=n)
            raise InjectedDispatchError(
                f"chaos: injected dispatch failure #{n} for bucket "
                f"{bucket}")


def lower_edge_vector(vec: np.ndarray, perm: Optional[np.ndarray] = None,
                      mask: Optional[np.ndarray] = None,
                      n_padded: Optional[int] = None) -> np.ndarray:
    """Apply the solve lowering's edge permutation/padding to a [nE] vector.

    Mirrors what flat_solve does to `obs`: optional permutation into
    slot/sort order, explicit zeroing of padding slots (np.where, never a
    multiply — 0 * NaN is NaN), and zero-padding up to the padded edge
    count.  Used to carry FaultPlan.edge_nan through every lowering
    branch so the poison lands on the same physical edges the solver
    sees.
    """
    v = np.asarray(vec)
    if perm is not None:
        v = v[np.asarray(perm)]
    if mask is not None:
        v = np.where(np.asarray(mask) > 0, v, np.zeros_like(v))
    if n_padded is not None and v.shape[0] < n_padded:
        v = np.concatenate([v, np.zeros((n_padded - v.shape[0],), v.dtype)])
    return np.ascontiguousarray(v)
