"""Host-level kill-resume harness for preemption-safety tests.

The checkpointed drivers (algo/checkpointed.py) promise that a solve
killed mid-chunk and resumed is indistinguishable from an uninterrupted
one.  In-process tests can only simulate that promise; this harness
delivers a REAL process death: it launches a worker subprocess, polls
for the first durable snapshot, SIGKILLs the worker (no atexit, no
signal handler, no flush — exactly a preempted host), and reruns the
worker to completion against the surviving snapshot.

`run_world_until_snapshot_then_kill` is the N-process (elastic) upgrade
of the same idea: a whole WORLD of rank processes (plus an optional
sacrificial rendezvous daemon, `parallel.multihost.serve_rendezvous`)
runs until the first world-level snapshot lands, one rank is SIGKILLed
mid-solve, and the SURVIVORS must exit on their own within the grace
budget — the no-wedge contract: detection (robustness/elastic.py) plus
shrink-world resume are bounded, so a survivor still running is itself
the failure being tested for.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Sequence


def _snapshot_ready(path: str) -> bool:
    """A snapshot counts once it exists with nonzero size.  save_state
    writes tmp + fsync + os.replace, so existence implies completeness."""
    try:
        return os.path.getsize(path) > 0
    except OSError:
        return False


def run_until_snapshot_then_kill(
    argv: Sequence[str],
    checkpoint_path: str,
    timeout: float = 300.0,
    settle: float = 0.0,
    env: Optional[dict] = None,
) -> int:
    """Run `argv`, SIGKILL it as soon as `checkpoint_path` appears.

    Returns the (negative-signal) returncode.  `settle` optionally lets
    the worker run a little past the first snapshot so the kill lands
    mid-chunk rather than at the exact chunk boundary.  Raises
    TimeoutError if no snapshot (or exit) happens within `timeout`.

    Worker output goes to an unbuffered temp file, not a pipe: the
    harness never drains while polling, and a worker chatty enough to
    fill a ~64 KB pipe buffer before its first snapshot would deadlock
    against an undrained pipe (the output is read back only on the
    error paths, where it explains the failure).
    """
    # The kill must land while the worker still has chunks to run.  The
    # worker's remaining work after snapshot 1 includes several fsync'd
    # snapshot writes, so a millisecond-scale poll leaves orders of
    # magnitude of margin — but if the worker ever does outrun the
    # SIGKILL, fail with the race named rather than returning rc=0 for
    # callers to misread as "killed".
    with tempfile.TemporaryFile() as log:
        proc = subprocess.Popen(
            list(argv), env=env, stdout=log, stderr=subprocess.STDOUT)

        def drain():
            log.seek(0)
            return log.read().decode(errors="replace")

        deadline = time.monotonic() + timeout
        try:
            while True:
                if _snapshot_ready(checkpoint_path):
                    if settle:
                        time.sleep(settle)
                    proc.kill()  # SIGKILL: uncatchable, nothing flushes
                    proc.wait(timeout=60)
                    if proc.returncode == 0:
                        raise AssertionError(
                            "worker finished before the SIGKILL landed "
                            "(the run completed cleanly — nothing was "
                            f"interrupted):\n{drain()}")
                    return proc.returncode
                rc = proc.poll()
                if rc is not None:
                    raise AssertionError(
                        f"worker exited (rc={rc}) before writing a "
                        f"snapshot at {checkpoint_path!r}:\n{drain()}")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no snapshot at {checkpoint_path!r} within "
                        f"{timeout}s; worker output:\n{drain()}")
                time.sleep(0.002)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=60)


@dataclasses.dataclass
class WorldKillOutcome:
    """What `run_world_until_snapshot_then_kill` observed.

    `returncodes[kill_rank]` is the negative SIGKILL code; every other
    rank's code is whatever it EXITED with on its own (the elastic
    workers exit 0 after detect + shrink-world resume).  `outputs` maps
    rank -> combined stdout/stderr.  `kill_monotonic` is the harness
    clock at SIGKILL delivery, for latency cross-checks.
    """

    kill_rank: int
    returncodes: Dict[int, int]
    outputs: Dict[int, str]
    kill_monotonic: float


def run_world_until_snapshot_then_kill(
    worker_argvs: Sequence[Sequence[str]],
    snapshot_path: str,
    kill_rank: int = 1,
    rendezvous_argv: Optional[Sequence[str]] = None,
    timeout: float = 600.0,
    settle: float = 0.0,
    survivor_timeout: float = 600.0,
    env: Optional[dict] = None,
) -> WorldKillOutcome:
    """Run an N-rank world; SIGKILL `kill_rank` at the first snapshot.

    `worker_argvs[i]` is rank i's argv.  `snapshot_path` is the durable
    world-level snapshot to poll (conventionally rank 0's checkpoint —
    atomic by `save_state`'s temp+fsync+rename contract, so existence
    implies completeness).  The kill is a true SIGKILL mid-solve, after
    an optional `settle`.  Every surviving rank must then EXIT ON ITS
    OWN within `survivor_timeout` — the elastic no-wedge contract; a
    survivor still running is killed and reported as a TimeoutError
    naming the wedge.  `rendezvous_argv`, when given, is launched first
    and SIGKILLed last (the sacrificial coordination-service daemon,
    `python -m megba_tpu.parallel.multihost --serve <port> <world>` —
    it has no graceful teardown by design).

    Output handling matches `run_until_snapshot_then_kill`: unbuffered
    temp files, never pipes, so a chatty worker can't deadlock the poll
    loop.
    """
    n = len(worker_argvs)
    if not 0 <= kill_rank < n:
        raise ValueError(f"kill_rank {kill_rank} outside world {n}")
    rdv = None
    logs = [tempfile.TemporaryFile() for _ in range(n)]
    procs: List[subprocess.Popen] = []

    def drain(i: int) -> str:
        logs[i].seek(0)
        return logs[i].read().decode(errors="replace")

    def drain_all() -> str:
        return "\n".join(f"--- rank {i} ---\n{drain(i)}" for i in range(n))

    try:
        if rendezvous_argv is not None:
            rdv = subprocess.Popen(
                list(rendezvous_argv), env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        # Appended one by one (not a comprehension): if a later spawn
        # raises, the already-running ranks are in `procs` and the
        # finally block reaps them instead of leaking live solvers.
        for i, argv in enumerate(worker_argvs):
            procs.append(subprocess.Popen(
                list(argv), env=env, stdout=logs[i],
                stderr=subprocess.STDOUT))
        deadline = time.monotonic() + timeout
        while True:
            if _snapshot_ready(snapshot_path):
                break
            for i, p in enumerate(procs):
                rc = p.poll()
                if rc is not None:
                    raise AssertionError(
                        f"rank {i} exited (rc={rc}) before the first "
                        f"snapshot at {snapshot_path!r}:\n{drain_all()}")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no snapshot at {snapshot_path!r} within {timeout}s:"
                    f"\n{drain_all()}")
            time.sleep(0.005)
        if settle:
            time.sleep(settle)
        if procs[kill_rank].poll() is not None:
            raise AssertionError(
                f"rank {kill_rank} finished before the SIGKILL landed "
                f"(nothing was interrupted):\n{drain_all()}")
        kill_monotonic = time.monotonic()
        procs[kill_rank].kill()  # SIGKILL: uncatchable, nothing flushes
        procs[kill_rank].wait(timeout=60)

        # The no-wedge contract: survivors exit on their own, bounded.
        survivor_deadline = time.monotonic() + survivor_timeout
        for i, p in enumerate(procs):
            if i == kill_rank:
                continue
            remaining = survivor_deadline - time.monotonic()
            try:
                p.wait(timeout=max(remaining, 0.001))
            except subprocess.TimeoutExpired:
                raise TimeoutError(
                    f"survivor rank {i} still running "
                    f"{survivor_timeout}s after the kill — wedged past "
                    f"the watchdog budget:\n{drain_all()}")
        return WorldKillOutcome(
            kill_rank=kill_rank,
            returncodes={i: p.returncode for i, p in enumerate(procs)},
            outputs={i: drain(i) for i in range(n)},
            kill_monotonic=kill_monotonic,
        )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=60)
        if rdv is not None and rdv.poll() is None:
            rdv.kill()
            rdv.wait(timeout=60)
        for log in logs:
            log.close()


def run_to_completion(argv: Sequence[str], timeout: float = 600.0,
                      env: Optional[dict] = None) -> str:
    """Run `argv` to completion; returns combined stdout/stderr.  Raises
    with the captured output on a nonzero exit."""
    res = subprocess.run(
        list(argv), env=env, timeout=timeout,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out = res.stdout.decode(errors="replace")
    if res.returncode != 0:
        raise AssertionError(
            f"worker failed (rc={res.returncode}):\n{out}")
    return out


def python_worker(script_path: str, *args: str) -> List[str]:
    """argv for running a worker script under this interpreter."""
    return [sys.executable, script_path, *map(str, args)]


# Re-exported for workers that want to confirm they were SIGKILLed.
SIGKILL = int(getattr(signal, "SIGKILL", 9))
