"""Host-level kill-resume harness for preemption-safety tests.

The checkpointed drivers (algo/checkpointed.py) promise that a solve
killed mid-chunk and resumed is indistinguishable from an uninterrupted
one.  In-process tests can only simulate that promise; this harness
delivers a REAL process death: it launches a worker subprocess, polls
for the first durable snapshot, SIGKILLs the worker (no atexit, no
signal handler, no flush — exactly a preempted host), and reruns the
worker to completion against the surviving snapshot.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import List, Optional, Sequence


def _snapshot_ready(path: str) -> bool:
    """A snapshot counts once it exists with nonzero size.  save_state
    writes tmp + fsync + os.replace, so existence implies completeness."""
    try:
        return os.path.getsize(path) > 0
    except OSError:
        return False


def run_until_snapshot_then_kill(
    argv: Sequence[str],
    checkpoint_path: str,
    timeout: float = 300.0,
    settle: float = 0.0,
    env: Optional[dict] = None,
) -> int:
    """Run `argv`, SIGKILL it as soon as `checkpoint_path` appears.

    Returns the (negative-signal) returncode.  `settle` optionally lets
    the worker run a little past the first snapshot so the kill lands
    mid-chunk rather than at the exact chunk boundary.  Raises
    TimeoutError if no snapshot (or exit) happens within `timeout`.

    Worker output goes to an unbuffered temp file, not a pipe: the
    harness never drains while polling, and a worker chatty enough to
    fill a ~64 KB pipe buffer before its first snapshot would deadlock
    against an undrained pipe (the output is read back only on the
    error paths, where it explains the failure).
    """
    # The kill must land while the worker still has chunks to run.  The
    # worker's remaining work after snapshot 1 includes several fsync'd
    # snapshot writes, so a millisecond-scale poll leaves orders of
    # magnitude of margin — but if the worker ever does outrun the
    # SIGKILL, fail with the race named rather than returning rc=0 for
    # callers to misread as "killed".
    with tempfile.TemporaryFile() as log:
        proc = subprocess.Popen(
            list(argv), env=env, stdout=log, stderr=subprocess.STDOUT)

        def drain():
            log.seek(0)
            return log.read().decode(errors="replace")

        deadline = time.monotonic() + timeout
        try:
            while True:
                if _snapshot_ready(checkpoint_path):
                    if settle:
                        time.sleep(settle)
                    proc.kill()  # SIGKILL: uncatchable, nothing flushes
                    proc.wait(timeout=60)
                    if proc.returncode == 0:
                        raise AssertionError(
                            "worker finished before the SIGKILL landed "
                            "(the run completed cleanly — nothing was "
                            f"interrupted):\n{drain()}")
                    return proc.returncode
                rc = proc.poll()
                if rc is not None:
                    raise AssertionError(
                        f"worker exited (rc={rc}) before writing a "
                        f"snapshot at {checkpoint_path!r}:\n{drain()}")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no snapshot at {checkpoint_path!r} within "
                        f"{timeout}s; worker output:\n{drain()}")
                time.sleep(0.002)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=60)


def run_to_completion(argv: Sequence[str], timeout: float = 600.0,
                      env: Optional[dict] = None) -> str:
    """Run `argv` to completion; returns combined stdout/stderr.  Raises
    with the captured output on a nonzero exit."""
    res = subprocess.run(
        list(argv), env=env, timeout=timeout,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out = res.stdout.decode(errors="replace")
    if res.returncode != 0:
        raise AssertionError(
            f"worker failed (rc={res.returncode}):\n{out}")
    return out


def python_worker(script_path: str, *args: str) -> List[str]:
    """argv for running a worker script under this interpreter."""
    return [sys.executable, script_path, *map(str, args)]


# Re-exported for workers that want to confirm they were SIGKILLed.
SIGKILL = int(getattr(signal, "SIGKILL", 9))
