"""Deterministic network fault injection for the federation transport.

`ChaosTcpProxy` is an in-process TCP proxy that sits between a
`FleetRouter` and its workers (point the router's `advertise` address,
or a worker's `--connect`, at the proxy) and injects faults by SEEDED
plan — the same `NetFaultPlan` always produces the same fault sequence
on the same connection order, so a chaos smoke is a regression test,
not a flake generator.

Fault families, chosen to exercise each typed failure the transport
layer promises (serving/transport.py):

- **drop**: the connection is severed abruptly mid-stream — the peer
  sees EOF/ECONNRESET and enters the reconnect window.
- **delay**: a chunk is forwarded late — exercises heartbeat-silence
  detection (`_ConnSuspect`) without actually losing the link.
- **truncate**: a PREFIX of a chunk is forwarded, then the connection
  is severed — the peer's codec raises `FrameTruncatedError` naming
  got/need bytes (never unpickles garbage).
- **reorder**: a chunk is held and forwarded after its successor —
  byte-stream corruption, surfacing as `FrameMagicError` or
  `FrameDigestError` downstream.
- **partition**: `partition()` severs every live connection and
  refuses new ones (accept-then-close, the router keeps seeing a
  listening port — a network partition, not a dead host) until
  `heal()`.

Every injected fault is appended to `proxy.events` for assertions.

Decisions draw from `np.random.default_rng(SeedSequence([seed,
conn_index, direction]))`: per-connection, per-direction streams, so
adding a fault family or a connection does not shift any other
stream's decisions.

Clock discipline: this module is on the strict raw-clock lint lane —
no wall/CPU/monotonic reads at all (the proxy needs only `time.sleep`
for delay injection); any future timing goes through `utils/timing`.
"""

from __future__ import annotations

import contextlib
import dataclasses
import socket
import threading
import time
from typing import Any, List, Optional, Tuple

import numpy as np

from megba_tpu.serving.transport import parse_address

_CHUNK = 1 << 16


@dataclasses.dataclass(frozen=True)
class NetFaultPlan:
    """Seeded per-chunk fault probabilities for one proxy.

    Rates are per forwarded chunk and cascade in order drop →
    truncate → reorder → delay (at most one fault per chunk).  The
    default plan is CLEAN — a proxy with `NetFaultPlan()` is a
    transparent relay, the control arm of any chaos experiment.
    """

    seed: int = 0
    drop_rate: float = 0.0
    truncate_rate: float = 0.0
    reorder_rate: float = 0.0
    delay_rate: float = 0.0
    delay_s: float = 0.05

    def __post_init__(self) -> None:
        for name in ("drop_rate", "truncate_rate", "reorder_rate",
                     "delay_rate"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {v}")
        if self.delay_s < 0:
            raise ValueError(f"delay_s must be >= 0, got {self.delay_s}")

    def rng(self, conn_index: int, direction: int) -> np.random.Generator:
        return np.random.default_rng(np.random.SeedSequence(
            [self.seed, conn_index, direction]))


class ChaosTcpProxy:
    """Deterministic in-process TCP proxy (see module docstring).

    Listens on 127.0.0.1 (ephemeral port, read `address`), dials
    `upstream` once per accepted connection, and pumps bytes both ways
    through the fault plan.  Use as a context manager or call
    `close()`.
    """

    def __init__(self, upstream: str,
                 plan: Optional[NetFaultPlan] = None) -> None:
        self.upstream = parse_address(upstream)
        self.plan = plan or NetFaultPlan()
        self._lock = threading.Lock()
        self._partitioned = False  # megba: guarded-by(_lock)
        self._closing = False  # megba: guarded-by(_lock)
        self._conns: List[socket.socket] = []  # megba: guarded-by(_lock)
        self.events: List[Tuple[Any, ...]] = []  # megba: guarded-by(_lock)
        self._nconn = 0  # megba: guarded-by(_lock); connection index
        self._pumps: List[threading.Thread] = []  # megba: guarded-by(_lock)
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(64)
        lsock.settimeout(0.2)  # accept slices re-check the closing flag
        self._lsock = lsock
        bound = lsock.getsockname()
        self.address = f"{bound[0]}:{bound[1]}"
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="megba-chaos-accept")
        self._accept_thread.start()

    # -- fault control ---------------------------------------------------
    def _record(self, *event: Any) -> None:
        with self._lock:
            self.events.append(event)

    def partition(self) -> None:
        """Sever every live connection and refuse new ones until
        `heal()` — the port stays open (a partition, not a death)."""
        with self._lock:
            self._partitioned = True
            conns, self._conns = self._conns, []
            self.events.append(("partition", len(conns)))
        for s in conns:
            _kill_socket(s)

    def heal(self) -> None:
        with self._lock:
            self._partitioned = False
            self.events.append(("heal",))

    def event_counts(self) -> dict:
        with self._lock:
            counts: dict = {}
            for ev in self.events:
                counts[ev[0]] = counts.get(ev[0], 0) + 1
            return counts

    def close(self) -> None:
        with self._lock:
            if self._closing:
                return
            self._closing = True
            conns, self._conns = self._conns, []
            pumps = list(self._pumps)
        try:
            self._lsock.close()
        except OSError:
            pass
        for s in conns:
            _kill_socket(s)
        self._accept_thread.join(timeout=5.0)
        for t in pumps:
            t.join(timeout=5.0)

    def __enter__(self) -> "ChaosTcpProxy":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    # -- relay machinery -------------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                down, _peer = self._lsock.accept()
            except TimeoutError:
                continue
            except OSError:
                return  # listener closed
            with self._lock:
                if self._closing:
                    _kill_socket(down)
                    return
                refused = self._partitioned
                idx = self._nconn
                self._nconn += 1
            if refused:
                self._record("refused", idx)
                _kill_socket(down)
                continue
            try:
                up = socket.create_connection(self.upstream, timeout=5.0)
                up.settimeout(None)
            except OSError:
                self._record("upstream_unreachable", idx)
                _kill_socket(down)
                continue
            self._record("accept", idx)
            with self._lock:
                if self._closing or self._partitioned:
                    pair: List[socket.socket] = []
                else:
                    self._conns.extend((down, up))
                    pair = [down, up]
            if not pair:
                _kill_socket(down)
                _kill_socket(up)
                continue
            for direction, (src, dst) in enumerate(((down, up),
                                                    (up, down))):
                t = threading.Thread(
                    target=self._pump,
                    args=(src, dst, idx, direction), daemon=True,
                    name=f"megba-chaos-pump-{idx}-{direction}")
                with self._lock:
                    self._pumps.append(t)
                t.start()

    def _pump(self, src: socket.socket, dst: socket.socket,
              idx: int, direction: int) -> None:
        rng = self.plan.rng(idx, direction)
        plan = self.plan
        held: Optional[bytes] = None
        try:
            while True:
                try:
                    chunk = src.recv(_CHUNK)
                except OSError:
                    break
                if not chunk:
                    break
                u = float(rng.random())
                if u < plan.drop_rate:
                    self._record("drop", idx, direction)
                    break
                u -= plan.drop_rate
                if u < plan.truncate_rate and len(chunk) > 1:
                    self._record("truncate", idx, direction,
                                 len(chunk) // 2, len(chunk))
                    with contextlib.suppress(OSError):
                        dst.sendall(chunk[:len(chunk) // 2])
                    break
                u -= plan.truncate_rate
                if u < plan.reorder_rate and held is None:
                    # Hold this chunk; it goes out AFTER its successor.
                    self._record("reorder", idx, direction)
                    held = chunk
                    continue
                u -= plan.reorder_rate
                if u < plan.delay_rate and plan.delay_s > 0:
                    self._record("delay", idx, direction)
                    time.sleep(plan.delay_s)
                try:
                    dst.sendall(chunk)
                    if held is not None:
                        dst.sendall(held)
                        held = None
                except OSError:
                    break
        finally:
            _kill_socket(src)
            _kill_socket(dst)


def _kill_socket(s: socket.socket) -> None:
    try:
        s.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        s.close()
    except OSError:
        pass
