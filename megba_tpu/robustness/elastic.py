"""Elastic distributed solves: liveness, bounded collectives, shrink-world.

The sharded solve's per-iteration psums assume every participant
survives the whole solve — the reference's assumption too, hard-capped
at one process (SURVEY.md §1).  At pod scale preemption is routine, and
a lost rank turns each surviving rank's next collective into either an
abrupt transport error or an unbounded block.  This module is the
failure-semantics contract for the world>1 path, four pieces:

- **HeartbeatBoard** — per-rank heartbeat files under a shared
  rendezvous directory.  Each rank's beat is a monotonically increasing
  counter written atomically; the monitor classifies peers ALIVE /
  STRAGGLER / DEAD by how long its OWN clock has gone without observing
  a counter *change* — wall clocks are never compared across processes.
  Pure state machine over an injected clock, unit-testable without
  processes (the PR 8 `resilience.py` style).

- **CollectiveWatchdog** — arms a deadline around each guarded dispatch
  so a wedged-but-beating peer (hung, not dead) still surfaces as a
  typed `CollectiveTimeout` within the watchdog budget instead of an
  infinite hang.  Also a pure injected-clock state machine; the
  threaded driver lives in `ElasticMonitor.guard`.

- **ElasticMonitor** — the host-side runtime: beats on a background
  thread, guards each chunk dispatch (worker thread + poll loop: peer
  liveness first, deadline second), classifies dispatch exceptions
  (a gloo transport error with a freshly-dead peer IS a `WorkerLost`,
  not a generic ValueError), and accumulates the elastic counters that
  ride `SolveReport.elastic` (worker_lost / collective_timeout /
  reshard / elastic_resume + time-to-detection samples).

- **resume_elastic** — the shrink-world path: tear down the distributed
  runtime (`parallel.multihost.shutdown_multihost`, abandoning dead
  peers without touching the teardown paths that abort the process —
  see that module's docstring for the probed jaxlib hazards), re-lower
  the SAME problem onto a mesh of THIS process's surviving local
  devices (`parallel.mesh.local_devices_only`), and continue the
  chunked solve from the latest preemption-safe snapshot (PR 5), whose
  schema-v3 header now records the world it was written at.

Detection is host-side ONLY: nothing here adds a collective, an operand
or a single HLO op to the jitted solve — the canonical audit programs'
budgets are untouched (`analysis/audit --check` stays the gate).
Aborts happen at chunk boundaries by construction: a chunk whose
dispatch dies is simply never snapshotted, so the previous chunk's
checksummed snapshot is the recovery line and a resumed solve replays
from there (the PR 5 bitwise kill-resume contract, now across ranks).
"""

from __future__ import annotations

import dataclasses
import enum
import os
import tempfile
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from megba_tpu.utils.timing import PhaseTimer


class ElasticError(RuntimeError):
    """Base of the elastic-distribution failure taxonomy."""


class WorkerLost(ElasticError):
    """One or more peer ranks stopped beating past the death threshold.

    `ranks` are the lost peers; `detected_after_s` is the staleness of
    the deadest peer at declaration (time since its last observed beat,
    on the DETECTING rank's clock) — the time-to-detection the harness
    asserts against the watchdog budget; `label` names the dispatch (or
    liveness check) that surfaced the loss.
    """

    def __init__(self, ranks: Sequence[int], label: str = "",
                 detected_after_s: float = 0.0) -> None:
        self.ranks = tuple(sorted(int(r) for r in ranks))
        self.label = label
        self.detected_after_s = float(detected_after_s)
        super().__init__(
            f"worker rank(s) {list(self.ranks)} lost "
            f"(no heartbeat for {self.detected_after_s:.3f}s)"
            + (f" during {label!r}" if label else ""))


class CollectiveTimeout(ElasticError):
    """A guarded dispatch exceeded its watchdog budget with every peer
    still beating — a wedged (hung/straggling) collective, not a death.
    """

    def __init__(self, label: str, budget_s: float, elapsed_s: float) -> None:
        self.label = label
        self.budget_s = float(budget_s)
        self.elapsed_s = float(elapsed_s)
        super().__init__(
            f"dispatch {label!r} exceeded its {self.budget_s:.3f}s "
            f"watchdog budget (elapsed {self.elapsed_s:.3f}s) with all "
            "peers still beating")


class RankState(enum.Enum):
    UNKNOWN = 0  # never observed a beat, still inside the join grace
    ALIVE = 1  # beat observed within straggler_after_s
    STRAGGLER = 2  # stale past straggler_after_s but not yet declared dead
    DEAD = 3  # stale past dead_after_s (or never joined within it)


class HeartbeatBoard:
    """Per-rank heartbeat files under a rendezvous directory.

    `beat()` atomically replaces this rank's file with an incremented
    counter.  `observe()` classifies every PEER by the time since its
    counter last CHANGED, measured on this process's own (injectable)
    clock — immune to cross-host clock skew, and deterministic under an
    injected clock for tests.  A rank that has never beaten is UNKNOWN
    until the join grace (`dead_after_s` from the first observation)
    expires, then DEAD: a worker that never came up is as lost as one
    that died.
    """

    def __init__(self, directory: str, rank: int, world: int, *,
                 straggler_after_s: float = 1.0, dead_after_s: float = 3.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        if not 0 <= rank < world:
            raise ValueError(f"rank {rank} outside world {world}")
        if not 0 < straggler_after_s <= dead_after_s:
            raise ValueError(
                f"need 0 < straggler_after_s <= dead_after_s, got "
                f"{straggler_after_s} / {dead_after_s}")
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.rank = int(rank)
        self.world = int(world)
        self.straggler_after_s = float(straggler_after_s)
        self.dead_after_s = float(dead_after_s)
        self._clock = clock
        # Thread-confined, not locked: `_counter` belongs to whichever
        # single thread drives beat() (the monitor's beater thread —
        # start()'s one pre-spawn beat orders-before via Thread.start),
        # and the observation maps belong to the observing thread
        # (ElasticMonitor's guard poll loop).  Cross-thread publication
        # happens through the filesystem (atomic replace), never these.
        self._counter = 0
        self._last_value: Dict[int, int] = {}
        self._last_change: Dict[int, float] = {}

    def path_for(self, rank: int) -> str:
        return os.path.join(self.directory, f"rank{int(rank)}.hb")

    def beat(self) -> int:
        """Publish one heartbeat (atomic replace: a concurrent reader
        sees the old beat or the new one, never a torn file)."""
        self._counter += 1
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as fh:
                fh.write(f"{self._counter} {os.getpid()}\n")
            os.replace(tmp, self.path_for(self.rank))
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return self._counter

    def _read_counter(self, rank: int) -> Optional[int]:
        try:
            with open(self.path_for(rank)) as fh:
                return int(fh.read().split()[0])
        except (OSError, ValueError, IndexError):
            return None  # missing or torn-by-external-tooling: no beat

    def observe(self, now: Optional[float] = None) -> Dict[int, RankState]:
        """Classify every peer rank (self excluded) at `now`."""
        now = self._clock() if now is None else now
        out: Dict[int, RankState] = {}
        for r in range(self.world):
            if r == self.rank:
                continue
            value = self._read_counter(r)
            seen_before = r in self._last_value
            if value is not None and (
                    not seen_before or value != self._last_value[r]):
                self._last_value[r] = value
                self._last_change[r] = now
            if r not in self._last_change:
                # First-ever observation of a silent rank: anchor the
                # join grace here, not at process start.
                self._last_change[r] = now
            stale = now - self._last_change[r]
            if stale >= self.dead_after_s:
                out[r] = RankState.DEAD
            elif r not in self._last_value:
                out[r] = RankState.UNKNOWN
            elif stale >= self.straggler_after_s:
                out[r] = RankState.STRAGGLER
            else:
                out[r] = RankState.ALIVE
        return out

    def staleness(self, rank: int, now: Optional[float] = None) -> float:
        """Seconds since `rank`'s beat counter last changed (inf if it
        was never observed at all)."""
        now = self._clock() if now is None else now
        anchor = self._last_change.get(int(rank))
        return float("inf") if anchor is None else now - anchor

    def dead_ranks(self, now: Optional[float] = None) -> List[int]:
        return [r for r, s in self.observe(now).items()
                if s is RankState.DEAD]


@dataclasses.dataclass
class _Armed:
    token: int
    label: str
    armed_at: float
    budget_s: float


class CollectiveWatchdog:
    """Deadline bookkeeping for in-flight guarded dispatches.

    Pure injected-clock state machine: `arm` registers a dispatch with
    a budget, `check`/`expired` compare against the clock, `disarm`
    retires it and returns the elapsed time.  `ElasticMonitor.guard`
    drives it from the poll loop; tests drive it with explicit `now=`
    values (arming/disarming across dispatches, timeout payloads).
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        self._armed: Dict[int, _Armed] = {}  # megba: guarded-by(_lock)
        self._next_token = 0  # megba: guarded-by(_lock)
        self.timeouts = 0  # megba: guarded-by(_lock); deadlines fired

    def arm(self, label: str, budget_s: float,
            now: Optional[float] = None) -> int:
        if budget_s <= 0:
            raise ValueError(f"budget_s must be > 0, got {budget_s}")
        now = self._clock() if now is None else now
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._armed[token] = _Armed(token, label, now, float(budget_s))
        return token

    def disarm(self, token: int, now: Optional[float] = None) -> float:
        now = self._clock() if now is None else now
        with self._lock:
            armed = self._armed.pop(token, None)
        if armed is None:
            raise ValueError(f"token {token} is not armed")
        return now - armed.armed_at

    def armed_count(self) -> int:
        with self._lock:
            return len(self._armed)

    def expired(self, now: Optional[float] = None) -> List[Tuple[int, str, float]]:
        """[(token, label, elapsed_s)] for every armed dispatch past its
        budget at `now` — inspection only, no state change."""
        now = self._clock() if now is None else now
        with self._lock:
            return [(a.token, a.label, now - a.armed_at)
                    for a in self._armed.values()
                    if now - a.armed_at > a.budget_s]

    def check(self, token: int, now: Optional[float] = None) -> float:
        """Elapsed seconds for `token`; raises `CollectiveTimeout` (and
        counts it) once past the budget.  The token stays armed so the
        caller's cleanup path still owns the disarm."""
        now = self._clock() if now is None else now
        with self._lock:
            armed = self._armed.get(token)
            if armed is None:
                raise ValueError(f"token {token} is not armed")
            elapsed = now - armed.armed_at
            if elapsed > armed.budget_s:
                self.timeouts += 1
                raise CollectiveTimeout(armed.label, armed.budget_s, elapsed)
        return elapsed


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Tuning for one rank's elastic monitor.

    `heartbeat_dir` must be shared by every rank (same host: any tmp
    dir; multi-host: a shared filesystem — the rendezvous dir).
    `watchdog_s` bounds each steady-state dispatch; the FIRST guarded
    dispatch of each compiled program (per `guard(grace_key=...)`,
    re-granted after a reshard) gets `compile_grace_s` on top, because
    jit tracing+compilation legitimately rides the first call of a
    program and must not read as a wedged collective.  Liveness is the
    fast detector either way: a dead peer surfaces within
    ~`dead_after_s` + `poll_s` even while a long first compile is in
    flight.
    """

    heartbeat_dir: str
    rank: int = 0
    world: int = 1
    interval_s: float = 0.25
    straggler_after_s: float = 1.0
    dead_after_s: float = 3.0
    watchdog_s: float = 60.0
    compile_grace_s: float = 600.0
    poll_s: float = 0.05

    def __post_init__(self) -> None:
        if self.world < 1:
            raise ValueError(f"world must be >= 1, got {self.world}")
        if not 0 <= self.rank < self.world:
            raise ValueError(f"rank {self.rank} outside world {self.world}")
        if self.interval_s <= 0 or self.poll_s <= 0:
            raise ValueError("interval_s and poll_s must be > 0")
        if not 0 < self.straggler_after_s <= self.dead_after_s:
            raise ValueError(
                "need 0 < straggler_after_s <= dead_after_s")
        if self.watchdog_s <= 0 or self.compile_grace_s < 0:
            raise ValueError(
                "watchdog_s must be > 0 and compile_grace_s >= 0")


class ElasticMonitor:
    """One rank's liveness + watchdog runtime, and its elastic ledger.

    Owns the heartbeat thread, the guarded-dispatch driver, and the
    counters that become `SolveReport.elastic`.  Every transition also
    lands as a zero-duration PhaseTimer event on `self.timer`
    (`elastic_worker_lost`, `elastic_collective_timeout`,
    `elastic_reshard`, `elastic_resume`) so phase breakdowns and the
    elastic block tell one story.
    """

    def __init__(self, config: ElasticConfig,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.config = config
        self.board = HeartbeatBoard(
            config.heartbeat_dir, config.rank, config.world,
            straggler_after_s=config.straggler_after_s,
            dead_after_s=config.dead_after_s, clock=clock)
        self.watchdog = CollectiveWatchdog(clock=clock)
        self.timer = PhaseTimer()
        self.monitor_id = uuid.uuid4().hex[:12]
        self._clock = clock
        self.workers_lost = 0
        self.collective_timeouts = 0
        self.reshards = 0
        self.resumes = 0
        self.detection_s: List[float] = []
        self._lost_ranks: set = set()
        self._peers_retired = config.world <= 1
        self._graced_keys: set = set()
        self._reshard_worlds: Optional[Tuple[int, int]] = None
        self._beater: Optional[threading.Thread] = None
        self._stop_beating = threading.Event()

    # -- lifecycle -------------------------------------------------------
    @classmethod
    def ensure(cls, elastic) -> Tuple[Optional["ElasticMonitor"], bool]:
        """Normalize a driver's `elastic=` argument.

        None -> (None, False); an `ElasticConfig` -> a fresh STARTED
        monitor the caller now owns (owned=True: the driver must stop
        it); an already-built monitor -> started if needed, not owned.
        """
        if elastic is None:
            return None, False
        if isinstance(elastic, ElasticMonitor):
            elastic.start()
            return elastic, False
        if isinstance(elastic, ElasticConfig):
            monitor = cls(elastic)
            monitor.start()
            return monitor, True
        raise TypeError(
            f"elastic must be an ElasticConfig or ElasticMonitor, got "
            f"{type(elastic).__name__}")

    def start(self) -> None:
        """Beat once now and keep beating on a daemon thread
        (idempotent).  The immediate beat matters: peers' join grace is
        anchored at their first observation, and a rank that only beat
        lazily would burn into it."""
        if self._beater is not None and self._beater.is_alive():
            return
        self.board.beat()
        self._stop_beating.clear()

        def _beat_loop():
            while not self._stop_beating.wait(self.config.interval_s):
                try:
                    self.board.beat()
                except OSError:
                    # A torn rendezvous dir must not kill the beater;
                    # peers will classify us from the last good beat.
                    pass

        self._beater = threading.Thread(
            target=_beat_loop, daemon=True,
            name=f"elastic-beat-r{self.config.rank}")
        self._beater.start()

    def stop(self) -> None:
        self._stop_beating.set()
        if self._beater is not None:
            self._beater.join(timeout=2.0)
            self._beater = None

    def __enter__(self) -> "ElasticMonitor":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- liveness --------------------------------------------------------
    def check_peers(self, now: Optional[float] = None,
                    label: str = "liveness") -> None:
        """Raise `WorkerLost` if any peer is DEAD (no-op once the world
        has been resharded past them, or for a world of one)."""
        if self._peers_retired:
            return
        states = self.board.observe(now)
        dead = [r for r, s in states.items() if s is RankState.DEAD]
        if dead:
            raise self._declare_lost(dead, label, now)

    def _declare_lost(self, ranks: Sequence[int], label: str,
                      now: Optional[float] = None) -> WorkerLost:
        staleness = max(self.board.staleness(r, now) for r in ranks)
        fresh = [r for r in ranks if r not in self._lost_ranks]
        if fresh:
            self._lost_ranks.update(fresh)
            self.workers_lost += len(fresh)
            self.timer.count_event("elastic_worker_lost", len(fresh))
            self.detection_s.extend(
                self.board.staleness(r, now) for r in fresh)
        return WorkerLost(ranks, label=label, detected_after_s=staleness)

    # -- guarded dispatch ------------------------------------------------
    def guard(self, label: str, fn: Callable, *args,
              grace_key=None, **kwargs):
        """Run one dispatch bounded by liveness + the watchdog.

        `fn` runs on a dedicated worker thread; this thread polls the
        heartbeat board (dead peer -> `WorkerLost`, the fast path) and
        the armed deadline (-> `CollectiveTimeout`).  On either, the
        worker thread is abandoned — it is parked inside a collective
        whose peers will never answer; it is a daemon thread whose
        eventual transport error is swallowed — and the CALLER gets
        control back within the budget: the no-wedge contract.  A
        dispatch exception with a freshly-dead peer is classified as
        `WorkerLost` (gloo surfaces peer death as a transport error
        faster than the death threshold elapses).

        `grace_key` identifies the compiled program this dispatch runs
        (the chunked driver passes the chunk's iteration count — the
        one per-chunk static): the FIRST guard per key gets
        `compile_grace_s` on top of the budget, because jit
        tracing+compilation rides a program's first call and must not
        read as a wedged collective.  A reshard clears the granted set
        (the shrunk mesh re-lowers every program).
        """
        key = ("__default__",) if grace_key is None else grace_key
        grace = 0.0
        if key not in self._graced_keys:
            self._graced_keys.add(key)
            grace = self.config.compile_grace_s
        budget = self.config.watchdog_s + grace
        token = self.watchdog.arm(label, budget)
        box: dict = {}
        finished = threading.Event()

        def _run():
            try:
                box["value"] = fn(*args, **kwargs)
            except BaseException as exc:  # delivered to the caller below
                box["error"] = exc
            finally:
                finished.set()

        worker = threading.Thread(
            target=_run, daemon=True, name=f"elastic-dispatch-{label}")
        worker.start()
        try:
            while not finished.wait(self.config.poll_s):
                self.check_peers(label=label)
                self.watchdog.check(token)
        except WorkerLost:
            self.watchdog.disarm(token)
            raise
        except CollectiveTimeout:
            self.collective_timeouts += 1
            self.timer.count_event("elastic_collective_timeout")
            self.watchdog.disarm(token)
            raise
        self.watchdog.disarm(token)
        if "error" in box:
            raise self._classify(box["error"], label)
        return box["value"]

    def _classify(self, error: BaseException, label: str) -> BaseException:
        """A dispatch exception while a peer just died IS the loss.

        gloo reports a SIGKILL'd peer as a TCP reset within
        milliseconds — before the heartbeat threshold can elapse — so
        wait up to one death window for the silence to become official
        before deciding the error was the peer's death rather than a
        genuine program failure.  The wait is bounded on the REAL clock
        (it sleeps real time): with an injected frozen clock the loop
        would otherwise never reach its deadline.
        """
        if self._peers_retired:
            return error
        deadline = time.monotonic() + self.config.dead_after_s \
            + 3 * self.config.poll_s
        while True:
            dead = self.board.dead_ranks()
            if dead:
                lost = self._declare_lost(dead, label)
                lost.__cause__ = error
                return lost
            if time.monotonic() >= deadline:
                return error
            time.sleep(self.config.poll_s)

    # -- reshard / resume ledger ----------------------------------------
    def record_reshard(self, old_world: int, new_world: int) -> None:
        """The world is being re-lowered at `new_world`: retire ALL
        peers and re-grant the first-dispatch compile grace (the shrunk
        mesh re-lowers every program).  Retiring every peer is correct
        for the supported topology — `resume_elastic` always continues
        on THIS process's local devices after the distributed runtime
        is torn down, so no cross-process peers remain; a future
        multi-process regroup would re-initialize a fresh cluster (and
        a fresh monitor) through `initialize_multihost` instead.
        Idempotent per (old, new) transition — `resume_elastic` records
        it AND the chunked driver re-detects it from the snapshot's
        world header; one transition must count once.
        """
        pair = (int(old_world), int(new_world))
        self._peers_retired = True
        self._graced_keys.clear()
        if self._reshard_worlds == pair:
            return
        self._reshard_worlds = pair
        self.reshards += 1
        self.timer.count_event("elastic_reshard")

    def record_resume(self) -> None:
        self.resumes += 1
        self.timer.count_event("elastic_resume")

    def report_block(self) -> Dict[str, object]:
        """The `SolveReport.elastic` payload: a snapshot of this
        monitor's cumulative counters.  `monitor` identifies the rank's
        monitor instance so an aggregator can take the LAST snapshot
        per monitor and sum ACROSS monitors without double counting."""
        return {
            "monitor": self.monitor_id,
            "rank": self.config.rank,
            "world": self.config.world,
            "workers_lost": self.workers_lost,
            "collective_timeouts": self.collective_timeouts,
            "reshards": self.reshards,
            "resumes": self.resumes,
            "detection_s": [round(float(s), 6) for s in self.detection_s],
        }


def resume_elastic(
    residual_jac_fn,
    cameras,
    points,
    obs,
    cam_idx,
    pt_idx,
    option,
    checkpoint_path: str,
    *,
    world_size: Optional[int] = None,
    monitor: Optional[ElasticMonitor] = None,
    checkpoint_every: int = 5,
    cooperative: bool = False,
    shutdown_timeout_s: float = 5.0,
    verbose: bool = False,
    **solve_kwargs,
):
    """Shrink-world resume: re-lower the SAME problem at the surviving
    world size and continue from the latest snapshot.

    Tears down the distributed runtime (`shutdown_multihost`; by
    default `abandon=True` — peers are presumed dead, so the barrier
    paths that would block or abort are never touched; pass
    `cooperative=True` for a planned reshard where every rank calls
    this), then re-runs `solve_checkpointed` with
    `option.world_size = world_size` (default: this process's local
    device count) under `parallel.mesh.local_devices_only()` — the
    shrunk mesh is built from devices THIS process owns, never a dead
    peer's, and the single-device path is pinned to a local device the
    same way.  The re-lowering is a new shape class (world size is
    static in the program), so the first resumed dispatch compiles
    exactly once — the retrace sentinel certifies ≤1 compile in the
    elastic tests — and the snapshot's schema-v3 world header turns a
    world mismatch into a warning + reshard event, not a refusal.

    Parity contract (pinned by tests + the run_tests.sh elastic smoke):
    an interrupted world-W solve resumed at world W' matches the
    uninterrupted world-W run at the sharded-parity tolerance (rtol
    1e-6 on final cost and parameters, equal `SolveStatus`).

    A 2-D solve (SolverOption.mesh_2d) resumes onto a SMALLER 2-D
    mesh: the world factorisation is recomputed
    (parallel.mesh.nearest_cam_blocks — the largest camera-block count
    the surviving world still factors), the camera-tile plan is
    re-planned, and the same single-recompile/parity contract holds
    (tests/test_mesh2d.py's resume_elastic stub-world tests pin the
    refactorisation, incl. the prime-world 1-D degrade).
    """
    import dataclasses as _dc

    import jax

    from megba_tpu.parallel.mesh import local_devices_only
    from megba_tpu.parallel.multihost import shutdown_multihost

    shutdown_multihost(abandon=not cooperative, timeout_s=shutdown_timeout_s)
    if world_size is None:
        world_size = len(jax.local_devices())
    old_world = option.world_size
    option = _dc.replace(option, world_size=int(world_size))
    if option.solver_option.mesh_2d:
        # 2-D solve resuming onto a smaller world: RE-FACTOR the mesh
        # instead of falling back to the 1-D layout — the surviving
        # world keeps the largest camera-block split it can still
        # factor (parallel.mesh.nearest_cam_blocks; degrading to
        # cam_blocks=1 — 1-D communication on a 2-D program — only when
        # no divisor survives).  The re-lowering below is one new
        # compile either way (world size AND mesh shape are static in
        # the program), and the camera-tile plan is re-planned for the
        # new factorisation by flat_solve's 2-D lowering.
        from megba_tpu.parallel.mesh import nearest_cam_blocks

        old_cb = option.solver_option.cam_blocks
        if old_cb <= 0:
            from megba_tpu.parallel.mesh import factor_mesh_2d

            _, old_cb = factor_mesh_2d(max(old_world, 1), 0)
        new_cb = nearest_cam_blocks(int(world_size), old_cb)
        option = _dc.replace(option, solver_option=_dc.replace(
            option.solver_option, cam_blocks=new_cb))
    if monitor is not None:
        monitor.record_reshard(old_world, world_size)
        monitor.record_resume()

    from megba_tpu.algo.checkpointed import solve_checkpointed

    local0 = jax.local_devices()[0]
    with local_devices_only(), jax.default_device(local0):
        result = solve_checkpointed(
            residual_jac_fn, cameras, points, obs, cam_idx, pt_idx,
            option, checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every, verbose=verbose,
            elastic=monitor, **solve_kwargs)

    telemetry = option.telemetry or os.environ.get("MEGBA_TELEMETRY") or None
    if telemetry and monitor is not None and jax.process_index() == 0:
        _append_elastic_report(monitor, result, telemetry)
    return result


def _append_elastic_report(monitor: ElasticMonitor, result,
                           telemetry: str) -> None:
    """One terminal JSONL line carrying the monitor's final elastic
    ledger (chunk lines carry interim snapshots; this one is the
    complete story, and `summarize --aggregate` keeps the last snapshot
    per monitor)."""
    from megba_tpu.common import status_name
    from megba_tpu.observability.report import (
        SolveReport,
        append_report,
        backend_topology,
    )
    from megba_tpu.utils.timing import wall_unix

    status = getattr(result, "status", None)
    rep = SolveReport(
        problem={},
        config={},
        backend=backend_topology(),
        phases=monitor.timer.as_dict(),
        result={
            "final_cost": float(result.cost),
            "iterations": int(result.iterations),
            "status": None if status is None else int(status),
            "status_name": (None if status is None
                            else status_name(status)),
        },
        elastic=monitor.report_block(),
        created_unix=wall_unix(),
    )
    append_report(rep, telemetry)
