"""Block-structured Schur linear system assembly.

TPU-native replacement for the reference's Hessian assembly + CSR
machinery: the `makeHSchur` atomicAdd kernels
(reference src/edge/build_linear_system.cu:88-146), the CSR skeleton
builders (reference src/linear_system/schur_LM_linear_system.cpp:20-84)
and the positionContainer relativePosition indexing
(reference src/edge/base_edge.cpp:224-262) all collapse into
`jax.ops.segment_sum` over gather indices on block-dense arrays:

  Hpp [num_cameras, cd, cd]   block-diagonal camera Hessian
  Hll [num_points,  pd, pd]   block-diagonal point Hessian
  g   ([num_cameras, cd], [num_points, pd])   gradient -J^T r

The camera-point coupling Hpl is either materialised as per-edge blocks
W_e = Jc_e^T Jp_e (EXPLICIT — the analog of the reference's Hpl/Hlp CSR,
schur_linear_system.h:22-29) or recomputed from the stored Jacobians at
every matvec (IMPLICIT — the analog of
reference src/solver/implicit_schur_pcg_solver.cu:20-90).  In both modes
Hpl stays shard-local when the edge axis is sharded: only the
block-diagonals and the gradient are psum-reduced, mirroring the
reference's allreduce set (build_linear_system.cu:403-422, where Hpl/Hlp
are deliberately NOT reduced).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from megba_tpu.common import ComputeKind
from megba_tpu.ops.residuals import apply_sqrt_info

# Hessian contractions (J^T J outer products, batched small matmuls) always
# run at full float32: on TPU the default bf16 matmul precision would
# corrupt the normal equations.  bf16 is an explicit opt-in for the PCG
# matvecs only (ProblemOption.mixed_precision_pcg).
HI = jax.lax.Precision.HIGHEST


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SchurSystem:
    """The assembled (undamped) normal equations in Schur block form.

    Equivalent of the reference's SchurLMLinearSystem containers
    (include/linear_system/schur_linear_system.h:22-29): csrVal[2]=Hpp,
    csrVal[3]=Hll, g — plus the per-edge W blocks in EXPLICIT mode
    (csrVal[0]/csrVal[1]=Hpl/Hlp there).  Undamped; LM damping is applied
    functionally by `damp_blocks` (the reference's in-place
    processDiag/recoverDiag save-restore dance,
    schur_LM_linear_system.cu:112-185, is unnecessary in functional form).
    """

    Hpp: jax.Array  # [Nc, cd, cd], psum-reduced (replicated across shards)
    Hll: jax.Array  # [Np, pd, pd], psum-reduced
    g_cam: jax.Array  # [Nc, cd], psum-reduced
    g_pt: jax.Array  # [Np, pd], psum-reduced
    W: Optional[jax.Array] = None  # [nE_local, cd, pd], shard-local (EXPLICIT)


def weight_system_inputs(
    r: jax.Array,
    Jc: jax.Array,
    Jp: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    mask: jax.Array,
    sqrt_info: Optional[jax.Array] = None,
    cam_fixed: Optional[jax.Array] = None,
    pt_fixed: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Apply sqrt-information, padding mask and fixed-vertex masks ONCE.

    The returned (r, Jc, Jp) are what both `build_schur_system` and the
    PCG matvecs consume, so masking can never be double-applied.  Covers
    the reference's JMulInfo pre-weighting
    (build_linear_system.cu:148-239) and its gradShape=0 exclusion of
    fixed vertices (base_vertex.h:48-50).  mask is 0/1 so H = J^T J picks
    up mask^2 = mask and g = -J^T r picks up mask^2 as well — padding
    edges contribute exactly nothing.
    """
    r, Jc, Jp = apply_sqrt_info(r, Jc, Jp, sqrt_info)
    r = r * mask[:, None]
    Jc = Jc * mask[:, None, None]
    Jp = Jp * mask[:, None, None]
    if cam_fixed is not None:
        Jc = jnp.where(cam_fixed[cam_idx][:, None, None], 0.0, Jc)
    if pt_fixed is not None:
        Jp = jnp.where(pt_fixed[pt_idx][:, None, None], 0.0, Jp)
    return r, Jc, Jp


def build_schur_system(
    r: jax.Array,
    Jc: jax.Array,
    Jp: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    num_cameras: int,
    num_points: int,
    compute_kind: ComputeKind = ComputeKind.IMPLICIT,
    axis_name: Optional[str] = None,
    cam_fixed: Optional[jax.Array] = None,
    pt_fixed: Optional[jax.Array] = None,
    cam_sorted: bool = False,
    pallas_plan: Optional[Tuple[int, int]] = None,
) -> SchurSystem:
    """Assemble the Schur-form normal equations from per-edge Jacobians.

    `cam_sorted=True` asserts edges are ordered by cam_idx (BAL files are;
    BaseProblem sorts at lowering) — the camera-side scatter-reduces then
    run as sorted segment reductions, the cheap path on TPU.

    `pallas_plan=(tile, window)` (requires cam_sorted) routes the
    camera-side build through the fused Pallas kernel
    (ops/pallas_kernels.py) instead of materialising per-edge outer
    products; obtain the plan from `camera_window_plan` host-side.

    Args:
      r: [nE, od] residuals, Jc: [nE, od, cd], Jp: [nE, od, pd] — all
        already weighted by `weight_system_inputs`.
      cam_idx / pt_idx: [nE] int32 gather indices.
      axis_name: mesh axis to psum over when the edge axis is sharded
        (the reference's ncclAllReduce of Hpp/Hll/g,
        build_linear_system.cu:403-422); None on a single device.
      cam_fixed / pt_fixed: optional bool masks; fixed vertices get an
        identity Hessian block and zero gradient so their update is
        exactly zero.
    """
    # Per-edge outer products, then scatter-reduce by vertex — the
    # race-free functional form of the reference's atomicAdd makeHpp /
    # makeHll (build_linear_system.cu:116-134).
    if pallas_plan is not None:
        from megba_tpu.ops.pallas_kernels import camera_hessian_gradient

        if not cam_sorted:
            # The kernel's windowed one-hot silently drops out-of-window
            # edges; without the sortedness guarantee that is data loss,
            # not an optimisation.
            raise ValueError("pallas_plan requires cam_sorted=True")
        if r.dtype != jnp.float32:
            # The kernel accumulates in float32; silently downgrading a
            # float64 build would corrupt the double-precision pipeline.
            raise ValueError(
                f"pallas_plan requires float32 inputs, got {r.dtype}; "
                "use the XLA path (pallas_plan=None) for other dtypes"
            )
        tile, window = pallas_plan
        Hpp, g_cam = camera_hessian_gradient(
            Jc, r, cam_idx, num_cameras=num_cameras, tile=tile,
            window=window, interpret=jax.default_backend() != "tpu")
    else:
        hpp_e = jnp.einsum("eoi,eoj->eij", Jc, Jc, precision=HI)
        g_cam_e = -jnp.einsum("eoi,eo->ei", Jc, r, precision=HI)
        Hpp = jax.ops.segment_sum(hpp_e, cam_idx, num_segments=num_cameras,
                                  indices_are_sorted=cam_sorted)
        g_cam = jax.ops.segment_sum(g_cam_e, cam_idx, num_segments=num_cameras,
                                    indices_are_sorted=cam_sorted)

    hll_e = jnp.einsum("eoi,eoj->eij", Jp, Jp, precision=HI)
    g_pt_e = -jnp.einsum("eoi,eo->ei", Jp, r, precision=HI)
    Hll = jax.ops.segment_sum(hll_e, pt_idx, num_segments=num_points)
    g_pt = jax.ops.segment_sum(g_pt_e, pt_idx, num_segments=num_points)

    if axis_name is not None:
        Hpp, Hll, g_cam, g_pt = jax.lax.psum((Hpp, Hll, g_cam, g_pt), axis_name)

    # Fixed vertices: identity block + zero gradient pins delta to zero.
    eye_c = jnp.eye(Hpp.shape[-1], dtype=Hpp.dtype)
    eye_p = jnp.eye(Hll.shape[-1], dtype=Hll.dtype)
    if cam_fixed is not None:
        Hpp = jnp.where(cam_fixed[:, None, None], eye_c, Hpp)
        g_cam = jnp.where(cam_fixed[:, None], 0.0, g_cam)
    if pt_fixed is not None:
        Hll = jnp.where(pt_fixed[:, None, None], eye_p, Hll)
        g_pt = jnp.where(pt_fixed[:, None], 0.0, g_pt)

    # Edge-less vertices (possible in filtered real datasets) would leave a
    # zero block that stays singular through multiplicative damping and
    # NaN-poisons the Cholesky in block_inv.  J^T J is PSD, so a zero
    # trace identifies exactly the empty blocks; give them an identity
    # (their gradient is already zero, so their update is exactly zero).
    empty_c = jnp.trace(Hpp, axis1=-2, axis2=-1) == 0.0
    empty_p = jnp.trace(Hll, axis1=-2, axis2=-1) == 0.0
    Hpp = jnp.where(empty_c[:, None, None], eye_c, Hpp)
    Hll = jnp.where(empty_p[:, None, None], eye_p, Hll)

    W = None
    if compute_kind == ComputeKind.EXPLICIT:
        # Shard-local coupling blocks (NOT reduced — the distributed
        # matvec psums the product instead, mirroring the reference's
        # beta=1/worldSize trick + product allreduce,
        # schur_pcg_solver.cu:478-509).
        W = jnp.einsum("eoi,eoj->eij", Jc, Jp, precision=HI)
    return SchurSystem(Hpp=Hpp, Hll=Hll, g_cam=g_cam, g_pt=g_pt, W=W)


def damp_blocks(H: jax.Array, region: jax.Array) -> jax.Array:
    """LM damping: scale block-diagonal entries by (1 + 1/region).

    The multiplicative damping of the reference's
    extractOldAndApplyNewDiag kernel (schur_LM_linear_system.cu:112-160);
    being functional, there is nothing to save or recover on reject.
    """
    d = H.shape[-1]
    eye = jnp.eye(d, dtype=H.dtype)
    factor = 1.0 + eye / region
    return H * factor


