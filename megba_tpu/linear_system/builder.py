"""Block-structured Schur linear system assembly (feature-major).

TPU-native replacement for the reference's Hessian assembly + CSR
machinery: the `makeHSchur` atomicAdd kernels
(reference src/edge/build_linear_system.cu:88-146), the CSR skeleton
builders (reference src/linear_system/schur_LM_linear_system.cpp:20-84)
and the positionContainer relativePosition indexing
(reference src/edge/base_edge.cpp:224-262) all collapse into chunked
scatter-adds of per-edge outer-product ROWS (see core/fm.py for the
feature-major layout rationale):

  Hpp [num_cameras, cd, cd]   block-diagonal camera Hessian (small)
  Hll [pd*pd, num_points]     block-diagonal point Hessian, row form
  g_cam [cd, num_cameras], g_pt [pd, num_points]   gradient -J^T r

The per-edge outer products are never materialised over the full edge
axis: the build scans edge CHUNKS, building each chunk's feature rows
[~F, chunk] in registers/VMEM-sized transients and scatter-adding into
the accumulators — bounding transient HBM to ~100 MB at ANY problem
scale (the edge-major einsum+segment_sum form needs 41 GB at Venice
scale from (8,128) tile padding alone).

The camera-point coupling Hpl is either materialised as per-edge block
rows W [cd*pd, nE] (EXPLICIT — the analog of the reference's Hpl/Hlp
CSR, schur_linear_system.h:22-29) or recomputed from the stored
Jacobians at every matvec (IMPLICIT — the analog of
reference src/solver/implicit_schur_pcg_solver.cu:20-90).  In both modes
Hpl stays shard-local when the edge axis is sharded: only the
block-diagonals and the gradient are psum-reduced, mirroring the
reference's allreduce set (build_linear_system.cu:403-422, where Hpl/Hlp
are deliberately NOT reduced).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from megba_tpu.common import ComputeKind
from megba_tpu.core.fm import chunked_edge_reduce, coupling_rows, slice_fm
from megba_tpu.ops.residuals import apply_sqrt_info
from megba_tpu.ops.segtiles import DualPlans, jtj_grad_reduce

# Hessian contractions always run at full float32: on TPU the default
# bf16 matmul precision would corrupt the normal equations.  bf16 is an
# explicit opt-in for the PCG matvecs only
# (ProblemOption.mixed_precision_pcg).
HI = jax.lax.Precision.HIGHEST


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SchurSystem:
    """The assembled (undamped) normal equations in Schur block form.

    Equivalent of the reference's SchurLMLinearSystem containers
    (include/linear_system/schur_linear_system.h:22-29): csrVal[2]=Hpp,
    csrVal[3]=Hll, g — plus the per-edge W rows in EXPLICIT mode
    (csrVal[0]/csrVal[1]=Hpl/Hlp there).  Undamped; LM damping is applied
    functionally (`damp_blocks` / `core.fm.damp_rows_fm` — the
    reference's in-place processDiag/recoverDiag save-restore dance,
    schur_LM_linear_system.cu:112-185, is unnecessary in functional
    form).  Point-side containers are feature-major rows; the camera side
    is small enough to stay block-batched.
    """

    Hpp: jax.Array  # [Nc, cd, cd], psum-reduced (replicated across shards)
    Hll: jax.Array  # [pd*pd, Np] rows, psum-reduced
    g_cam: jax.Array  # [cd, Nc], psum-reduced
    g_pt: jax.Array  # [pd, Np], psum-reduced
    W: Optional[jax.Array] = None  # [cd*pd, nE_local], shard-local (EXPLICIT)


def weight_system_inputs(
    r: jax.Array,
    Jc: jax.Array,
    Jp: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    mask: jax.Array,
    sqrt_info: Optional[jax.Array] = None,
    cam_fixed: Optional[jax.Array] = None,
    pt_fixed: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Apply sqrt-information, padding mask and fixed-vertex masks ONCE.

    Feature-major: r [od, nE], Jc [od*cd, nE], Jp [od*pd, nE], mask [nE].
    The returned (r, Jc, Jp) are what both `build_schur_system` and the
    PCG matvecs consume, so masking can never be double-applied.  Covers
    the reference's JMulInfo pre-weighting
    (build_linear_system.cu:148-239) and its gradShape=0 exclusion of
    fixed vertices (base_vertex.h:48-50).  mask is 0/1 so H = J^T J picks
    up mask^2 = mask and g = -J^T r picks up mask^2 as well — padding
    edges contribute exactly nothing.
    """
    r, Jc, Jp = apply_sqrt_info(r, Jc, Jp, sqrt_info)
    m = mask[None, :]
    r = r * m
    Jc = Jc * m
    Jp = Jp * m
    if cam_fixed is not None:
        # zeros_like: the weak literal 0.0 would ride in as a f64
        # constant tensor in f32 programs (dtype-census leak).
        Jc = jnp.where(cam_fixed[cam_idx][None, :], jnp.zeros_like(Jc), Jc)
    if pt_fixed is not None:
        Jp = jnp.where(pt_fixed[pt_idx][None, :], jnp.zeros_like(Jp), Jp)
    return r, Jc, Jp


def _outer_rows(J: jax.Array, od: int, d: int) -> jax.Array:
    """[od*d, n] Jacobian rows -> [d*d, n] rows of J^T J (sum over od)."""
    return jnp.stack([
        sum(J[o * d + a] * J[o * d + b] for o in range(od))
        for a in range(d) for b in range(d)
    ])


def _grad_rows(J: jax.Array, r: jax.Array, od: int, d: int) -> jax.Array:
    """[od*d, n] Jacobian rows, [od, n] residual -> [d, n] rows of -J^T r."""
    return jnp.stack([
        -sum(J[o * d + a] * r[o] for o in range(od)) for a in range(d)
    ])


# named_scope: labels every assembly op in profiler traces
# (TensorBoard/Perfetto via utils.timing.trace_profile) at zero runtime
# cost — the Schur build is a hot phase worth finding at a glance.
@jax.named_scope("megba.schur_build")
def build_schur_system(
    r: jax.Array,
    Jc: jax.Array,
    Jp: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    num_cameras: int,
    num_points: int,
    compute_kind: ComputeKind = ComputeKind.IMPLICIT,
    axis_name: Optional[str] = None,
    cam_fixed: Optional[jax.Array] = None,
    pt_fixed: Optional[jax.Array] = None,
    cam_sorted: bool = False,
    plans: Optional[DualPlans] = None,
) -> SchurSystem:
    """Assemble the Schur-form normal equations from per-edge Jacobians.

    Feature-major inputs (already weighted by `weight_system_inputs`):
    r [od, nE], Jc [od*cd, nE], Jp [od*pd, nE]; cam_idx/pt_idx [nE] int32.

    `cam_sorted=True` asserts edges are ordered by cam_idx (BAL files
    are; BaseProblem sorts at lowering) — camera-side scatters then run
    as sorted segment reductions.

    `plans` (ops/segtiles.DualPlans) selects the scatter-free tiled
    build: `Jc`/`r` are in cam-plan slot order, `Jp` is in PT-plan slot
    order, and both block-diagonals come from the fused
    `jtj_grad_reduce` kernel (the reference's makeHSchur / makeHppHllSchur
    fusion, build_linear_system.cu:88-146 /
    build_implicit_linear_system.cu:65-111, re-expressed as one-hot MXU
    matmuls).  Without plans, the chunked scatter-add path runs (CPU /
    f64 / sharded mesh).

    `axis_name`: mesh axis (or, on the 2-D camera x edge mesh, the
    (EDGE_AXIS, CAM_AXIS) tuple — `jax.lax.psum` over the tuple reduces
    over the whole world) to psum over when the edge axis is sharded
    (the reference's ncclAllReduce of Hpp/Hll/g,
    build_linear_system.cu:403-422); None on a single device.  The
    build runs once per LINEARISATION, so these stay whole-world
    reductions on both mesh shapes — only the per-PCG-iteration matvec
    pays for subgroup scoping (solver/pcg.make_matvec_2d).
    `cam_fixed` / `pt_fixed`: optional bool masks; fixed vertices get an
    identity Hessian block and zero gradient so their update is exactly
    zero.
    """
    od = r.shape[0]
    cd = Jc.shape[0] // od
    pd = Jp.shape[0] // od
    nE = r.shape[1]
    dtype = r.dtype

    if plans is not None:
        if dtype != jnp.float32:
            # The kernels accumulate in float32; silently downgrading a
            # float64 build would corrupt the double-precision pipeline.
            raise ValueError(
                f"plans requires float32 inputs, got {dtype}; "
                "use the XLA path (plans=None) for other dtypes")
        hpp_rows, g_cam = jtj_grad_reduce(
            Jc, r, plans.cam, plans.use_kernels)
        r_pt = plans.to_pt(r)
        hll_acc = jnp.concatenate(
            jtj_grad_reduce(Jp, r_pt, plans.pt, plans.use_kernels))
    else:
        # Chunked scatter-add build: per chunk, form the outer-product
        # rows [d*d + d, chunk] and accumulate — the race-free functional
        # form of the reference's atomicAdd makeHpp / makeHll
        # (build_linear_system.cu:116-134) with bounded transients.
        def body(start, size, accs):
            hpp_a, hll_a = accs
            jp = slice_fm(Jp, start, size)
            rr = slice_fm(r, start, size)
            pi = jax.lax.dynamic_slice_in_dim(pt_idx, start, size)
            jc = slice_fm(Jc, start, size)
            ci = jax.lax.dynamic_slice_in_dim(cam_idx, start, size)
            cam_feat = jnp.concatenate(
                [_outer_rows(jc, od, cd), _grad_rows(jc, rr, od, cd)])
            hpp_a = hpp_a.at[:, ci].add(
                cam_feat, indices_are_sorted=cam_sorted, mode="drop")
            pt_feat = jnp.concatenate(
                [_outer_rows(jp, od, pd), _grad_rows(jp, rr, od, pd)])
            hll_a = hll_a.at[:, pi].add(pt_feat, mode="drop")
            return hpp_a, hll_a

        hpp_init = jnp.zeros((cd * cd + cd, num_cameras), dtype)
        hll_init = jnp.zeros((pd * pd + pd, num_points), dtype)
        hpp_acc, hll_acc = chunked_edge_reduce(
            nE, (hpp_init, hll_init), body)
        hpp_rows = hpp_acc[: cd * cd]
        g_cam = hpp_acc[cd * cd:]
    Hll = hll_acc[: pd * pd]
    g_pt = hll_acc[pd * pd:]

    if axis_name is not None:
        hpp_rows, g_cam, Hll, g_pt = jax.lax.psum(
            (hpp_rows, g_cam, Hll, g_pt), axis_name)

    # Camera blocks to batched [Nc, cd, cd] (small; dense-block ops and
    # the 9x9 Cholesky inverse want this form).
    Hpp = jnp.moveaxis(hpp_rows.reshape(cd, cd, num_cameras), -1, 0)

    # Fixed vertices: identity block + zero gradient pins delta to zero.
    eye_c = jnp.eye(cd, dtype=dtype)
    eye_p_rows = jnp.asarray(
        [1.0 if i % (pd + 1) == 0 else 0.0 for i in range(pd * pd)], dtype)
    if cam_fixed is not None:
        Hpp = jnp.where(cam_fixed[:, None, None], eye_c, Hpp)
        # zeros_like, not the literal 0.0: a weak f64 scalar constant
        # would materialise as tensor<f64> in f32 programs (the dtype
        # census flags it — same class of leak as the ops/geo.py ones).
        g_cam = jnp.where(cam_fixed[None, :], jnp.zeros_like(g_cam), g_cam)
    if pt_fixed is not None:
        Hll = jnp.where(pt_fixed[None, :], eye_p_rows[:, None], Hll)
        g_pt = jnp.where(pt_fixed[None, :], jnp.zeros_like(g_pt), g_pt)

    # Edge-less vertices (possible in filtered real datasets) would leave
    # a zero block that stays singular through multiplicative damping and
    # NaN-poisons the inverse.  J^T J is PSD, so a zero trace identifies
    # exactly the empty blocks; give them an identity (their gradient is
    # already zero, so their update is exactly zero).
    empty_c = jnp.trace(Hpp, axis1=-2, axis2=-1) == 0.0
    Hpp = jnp.where(empty_c[:, None, None], eye_c, Hpp)
    tr_rows = [i for i in range(pd * pd) if i % (pd + 1) == 0]
    empty_p = sum(Hll[i] for i in tr_rows) == 0.0
    Hll = jnp.where(empty_p[None, :], eye_p_rows[:, None], Hll)

    W = None
    if compute_kind == ComputeKind.EXPLICIT:
        # Shard-local coupling rows (NOT reduced — the distributed matvec
        # psums the product instead, mirroring the reference's
        # beta=1/worldSize trick + product allreduce,
        # schur_pcg_solver.cu:478-509).  W lives in cam-slot order; under
        # plans, Jp is pt-ordered and must be brought over first.
        Jp_cam = plans.to_cam(Jp) if plans is not None else Jp
        W = coupling_rows(Jc, Jp_cam, od)
    return SchurSystem(Hpp=Hpp, Hll=Hll, g_cam=g_cam, g_pt=g_pt, W=W)


def coupling_row_provider(
    W: Optional[jax.Array],
    Jc: Optional[jax.Array],
    Jp: Optional[jax.Array],
    od: int,
    compute_kind: ComputeKind,
    dtype,
    plans: Optional[DualPlans] = None,
):
    """Chunk accessor for the per-edge coupling block rows W_e = Jc_eᵀJp_e.

    Returns `rows(start, size) -> [cd*pd, size]` in the CAM edge order
    and the solve dtype, reading the materialised `W` rows in EXPLICIT
    mode and recomputing from the stored Jacobians in IMPLICIT mode
    (upcast from bf16 under mixed precision) — the ONE definition of
    "give me this edge chunk's coupling blocks" shared by the
    Schur-diagonal preconditioner build and the two-level coarse
    operator assembly (solver/precond.py), so the two consumers can
    never disagree about layout or precision.  Under `plans`, `Jp` is
    carried PT-ordered (algo/lm.py) and is brought to cam order once
    here.
    """
    if compute_kind == ComputeKind.EXPLICIT:
        def rows(start, size):
            return slice_fm(W, start, size).astype(dtype)

        return rows
    if plans is not None and Jp is not None:
        Jp = plans.to_cam(Jp)

    def rows(start, size):
        jc = slice_fm(Jc, start, size).astype(dtype)
        jp = slice_fm(Jp, start, size).astype(dtype)
        return coupling_rows(jc, jp, od)

    return rows


def coupling_row_gather(
    W: Optional[jax.Array],
    Jc: Optional[jax.Array],
    Jp: Optional[jax.Array],
    od: int,
    compute_kind: ComputeKind,
    dtype,
    plans: Optional[DualPlans] = None,
):
    """`coupling_row_provider`'s random-access sibling: returns
    `rows_at(idx) -> [cd*pd, len(idx)]` gathering the coupling block
    rows at arbitrary (quasi-sorted) edge indices instead of contiguous
    chunks — the access pattern of the two-level coarse build's
    ec-pair stream, where each edge appears once per cluster of its
    point (solver/precond.py)."""
    from megba_tpu.core.fm import gather_fm

    if compute_kind == ComputeKind.EXPLICIT:
        def rows_at(idx):
            return gather_fm(W, idx).astype(dtype)

        return rows_at
    if plans is not None and Jp is not None:
        Jp = plans.to_cam(Jp)

    def rows_at(idx):
        jc = gather_fm(Jc, idx).astype(dtype)
        jp = gather_fm(Jp, idx).astype(dtype)
        return coupling_rows(jc, jp, od)

    return rows_at


def damp_blocks(H: jax.Array, region: jax.Array) -> jax.Array:
    """LM damping on batched [N, d, d] blocks: diagonal scales by
    (1 + 1/region).

    The multiplicative damping of the reference's
    extractOldAndApplyNewDiag kernel (schur_LM_linear_system.cu:112-160);
    being functional, there is nothing to save or recover on reject.
    Row-form point blocks use `core.fm.damp_rows_fm`.
    """
    d = H.shape[-1]
    eye = jnp.eye(d, dtype=H.dtype)
    factor = 1.0 + eye / region
    return H * factor
