from megba_tpu.linear_system.builder import (
    SchurSystem,
    build_schur_system,
    damp_blocks,
    weight_system_inputs,
)

__all__ = [
    "SchurSystem",
    "build_schur_system",
    "damp_blocks",
    "weight_system_inputs",
]
