"""Textual census passes over lowered StableHLO / compiled HLO.

The compiled-program auditor (analysis/program_audit.py) works on two
artifacts of one jitted solve program, both plain text:

- the **StableHLO** module from `jax.jit(...).lower(...).as_text()` —
  pre-optimization, so every effectful op the traced Python emitted is
  still present (host callbacks cannot be DCE'd away) and every weak
  Python scalar that materialised as a wide constant is still visible;
- the **optimized HLO** from `.compile().as_text()` — post-DCE/fusion
  truth of what actually runs, whose op `metadata={op_name=...}` carries
  the `jax.named_scope` path (e.g. `megba.pcg/megba.pcg_core/while/
  body/psum`), which is how collectives are attributed to the PCG inner
  loop without any private JAX API.

Everything here is stdlib-only string analysis: no jax import, no
execution, no dialect bindings — the parsers accept the exact textual
forms jaxlib 0.4.x prints and degrade to "op not recognised" (never a
crash) on anything else.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Collective op mnemonics, normalised to underscore form.  StableHLO
# spells them `stablehlo.all_reduce`; optimized HLO spells them
# `all-reduce` (plus the async `-start`/`-done` pair forms).
COLLECTIVE_KINDS = (
    "all_reduce", "all_gather", "all_to_all", "collective_permute",
    "reduce_scatter", "collective_broadcast",
)

# custom_call targets that move data between host and device (or name a
# host callback).  Compute custom_calls (lapack_*, cu*, Sharding
# annotations) do not match.
_TRANSFER_TARGET_RE = re.compile(
    r"callback|host_|_host|infeed|outfeed|xla_ffi_partial_buffer",
    re.IGNORECASE)

# Op kinds that are host transfers by construction.
_TRANSFER_KINDS = frozenset(
    {"infeed", "outfeed", "send", "recv", "send_done", "recv_done"})


@dataclasses.dataclass(frozen=True)
class HloOp:
    """One interesting op occurrence in an HLO/StableHLO text module."""

    kind: str  # normalised mnemonic, e.g. "all_reduce", "custom_call"
    line: int  # 1-based line number in the module text
    text: str  # the stripped source line (truncated for reporting)
    while_depth: int = 0  # enclosing `stablehlo.while` regions (StableHLO)
    target: Optional[str] = None  # custom_call target, when present
    op_name: Optional[str] = None  # compiled-HLO metadata scope path
    result_dtype: Optional[str] = None
    result_elems: Optional[int] = None

    def where(self) -> str:
        scope = f" [{self.op_name}]" if self.op_name else ""
        tgt = f" @{self.target}" if self.target else ""
        return f"line {self.line}: {self.kind}{tgt}{scope}"


_STRING_RE = re.compile(r'"[^"]*"')
_SHLO_OP_RE = re.compile(r'"?stablehlo\.(\w+)"?')
_SHLO_TARGET_RE = re.compile(
    r'stablehlo\.custom_call\s+@([\w.\-]+)|call_target_name\s*=\s*"([^"]+)"')
_TENSOR_RE = re.compile(r"tensor<((?:\d+x)*)([a-z][a-z0-9]*)>")


def _dims_elems(dims: str) -> int:
    n = 1
    for d in dims.split("x"):
        if d:
            n *= int(d)
    return n


def parse_stablehlo_ops(text: str) -> List[HloOp]:
    """Scan a StableHLO module for ops, tracking while-region nesting.

    Only op-defining lines are recorded (one op per line in jax's pretty
    printer).  `while_depth` counts enclosing `stablehlo.while` regions,
    so depth >= 1 means "inside some loop body/cond".
    """
    ops: List[HloOp] = []
    depth = 0  # brace depth, strings stripped
    # Each entry: [brace depth at the `while` line, region-opened flag].
    # The regions (`cond { ... } do { ... }`) open on LATER lines, so a
    # frame only becomes poppable once depth has risen above its
    # threshold — otherwise the while line itself would pop it.
    while_stack: List[List] = []
    for lineno, raw in enumerate(text.splitlines(), start=1):
        # Strip string literals for BRACE counting only (attr strings can
        # contain braces); ops are matched on the raw line — the generic
        # print form quotes the op name (`"stablehlo.all_reduce"(...)`).
        line = _STRING_RE.sub('""', raw)
        m = _SHLO_OP_RE.search(raw)
        if m:
            kind = m.group(1)
            target = None
            if kind == "custom_call":
                tm = _SHLO_TARGET_RE.search(raw)
                if tm:
                    target = tm.group(1) or tm.group(2)
            rd, re_ = _stablehlo_result(line)
            ops.append(HloOp(
                kind=kind, line=lineno, text=raw.strip()[:200],
                while_depth=len(while_stack), target=target,
                result_dtype=rd, result_elems=re_))
            if kind == "while":
                opens, closes = line.count("{"), line.count("}")
                if not (opens and opens == closes):
                    # jax's pretty form opens the regions on LATER lines
                    # (push unopened); the generic one-line form
                    # `"stablehlo.while"(...) ({...}, {...})` is fully
                    # self-contained — pushing it would leak a frame
                    # (net brace delta 0 never pops), so skip it.
                    while_stack.append([depth, opens > closes])
        depth += line.count("{") - line.count("}")
        while while_stack:
            threshold, opened = while_stack[-1]
            if not opened:
                if depth > threshold:
                    while_stack[-1][1] = True
                break
            if depth <= threshold:
                while_stack.pop()
            else:
                break
    return ops


def _stablehlo_result(line: str) -> Tuple[Optional[str], Optional[int]]:
    """Element dtype/count of an op line's (last) result tensor type."""
    # Result types trail the op: `... : (in) -> tensor<...>` or
    # `... : tensor<...>`; take the last tensor token on the line.
    matches = _TENSOR_RE.findall(line)
    if not matches:
        return None, None
    dims, dtype = matches[-1]
    return dtype, _dims_elems(dims)


# Optimized-HLO op definitions: `%name = f32[9,24]{1,0} all-reduce(...)`.
# The result may be a TUPLE type `(f32[..]{..}, s32[..]{..})` — XLA's
# AllReduceCombiner emits combined collectives in exactly that form, so
# the tuple alternative must come first or a merged all-reduce would be
# invisible to the census.
# (scalar result types like `f32[]` match the empty-bracket form too).
_HLO_DEF_RE = re.compile(
    r"=\s*(\([^)]*\)|[a-z0-9]+\[[0-9,]*\]\S*)\s+([a-z][a-z0-9\-]*)\(")
_HLO_TYPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_OP_NAME_RE = re.compile(r'op_name="([^"]+)"')
_HLO_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')


def parse_compiled_ops(text: str) -> List[HloOp]:
    """Scan an optimized-HLO module for op definitions with metadata."""
    ops: List[HloOp] = []
    for lineno, raw in enumerate(text.splitlines(), start=1):
        m = _HLO_DEF_RE.search(raw)
        if not m:
            continue
        kind = m.group(2).replace("-", "_")
        # The async pair forms count once, at the -start op.
        if kind.endswith("_done"):
            kind_base = kind[:-5]
            if kind_base in COLLECTIVE_KINDS:
                continue
        if kind.endswith("_start"):
            kind = kind[:-6]
        tm = _HLO_TYPE_RE.search(m.group(1))
        rd = tm.group(1) if tm else None
        re_ = _dims_elems(tm.group(2).replace(",", "x")) if tm else None
        nm = _OP_NAME_RE.search(raw)
        tg = _HLO_TARGET_RE.search(raw)
        ops.append(HloOp(
            kind=kind, line=lineno, text=raw.strip()[:200],
            target=tg.group(1) if tg else None,
            op_name=nm.group(1) if nm else None,
            result_dtype=rd, result_elems=re_))
    return ops


def transfer_ops(ops: Iterable[HloOp],
                 allow: Sequence[str] = ()) -> List[HloOp]:
    """Host-transfer ops: infeed/outfeed/send/recv + callback custom_calls.

    `allow` lists custom_call targets that are sanctioned (the
    observability layer's trace outputs); everything else that matches
    the transfer pattern is a violation.
    """
    out = []
    for op in ops:
        if op.kind in _TRANSFER_KINDS:
            out.append(op)
        elif op.kind == "custom_call" and op.target:
            if op.target in allow:
                continue
            if _TRANSFER_TARGET_RE.search(op.target):
                out.append(op)
    return out


def collective_ops(ops: Iterable[HloOp]) -> List[HloOp]:
    return [op for op in ops if op.kind in COLLECTIVE_KINDS]


def dtype_census(text: str) -> Dict[str, int]:
    """tensor element-type -> occurrence count over a StableHLO module."""
    census: Dict[str, int] = {}
    for dims, dtype in _TENSOR_RE.findall(text):
        census[dtype] = census.get(dtype, 0) + 1
    return census


def lines_with_dtype(text: str, dtype: str, limit: int = 5
                     ) -> List[Tuple[int, str]]:
    """First `limit` (lineno, line) occurrences of tensor<...x{dtype}>."""
    needle = re.compile(r"tensor<(?:\d+x)*" + re.escape(dtype) + ">")
    out: List[Tuple[int, str]] = []
    for lineno, raw in enumerate(text.splitlines(), start=1):
        if needle.search(raw):
            out.append((lineno, raw.strip()[:200]))
            if len(out) >= limit:
                break
    return out


# `input_output_alias={ {5}: (0, {}, may-alias), ... }` in the module
# header: output-index-tuple -> (parameter, param_index_tuple, kind).
_ALIAS_ENTRY_RE = re.compile(r"\{([\d,\s]*)\}:\s*\((\d+)")


def input_output_aliases(compiled_text: str) -> List[Tuple[str, int]]:
    """[(output_index_tuple, parameter_number)] of the entry computation.

    Empty when the compiled executable materialised no aliasing (i.e.
    declared donation was dropped).
    """
    # The alias map lives on the `HloModule` header line.
    start = compiled_text.find("input_output_alias={")
    if start < 0:
        return []
    # The block nests one level of braces per entry; scan to the close
    # (no length cap: a truncated scan would read as "donation dropped"
    # and fail the gate with a wrong answer — the loop terminates at the
    # matching brace anyway).
    i = compiled_text.find("{", start)
    depth = 0
    block = ""
    for j in range(i, len(compiled_text)):
        if compiled_text[j] == "{":
            depth += 1
        elif compiled_text[j] == "}":
            depth -= 1
            if depth == 0:
                block = compiled_text[i:j + 1]
                break
    if not block:
        return []
    return [(m.group(1).strip(), int(m.group(2)))
            for m in _ALIAS_ENTRY_RE.finditer(block)]


def aliased_parameters(compiled_text: str) -> frozenset:
    """The set of entry parameters that alias some output."""
    return frozenset(p for _, p in input_output_aliases(compiled_text))
