"""Textual census passes over lowered StableHLO / compiled HLO.

The compiled-program auditor (analysis/program_audit.py) works on two
artifacts of one jitted solve program, both plain text:

- the **StableHLO** module from `jax.jit(...).lower(...).as_text()` —
  pre-optimization, so every effectful op the traced Python emitted is
  still present (host callbacks cannot be DCE'd away) and every weak
  Python scalar that materialised as a wide constant is still visible;
- the **optimized HLO** from `.compile().as_text()` — post-DCE/fusion
  truth of what actually runs, whose op `metadata={op_name=...}` carries
  the `jax.named_scope` path (e.g. `megba.pcg/megba.pcg_core/while/
  body/psum`), which is how collectives are attributed to the PCG inner
  loop without any private JAX API.

Everything here is stdlib-only string analysis: no jax import, no
execution, no dialect bindings — the parsers accept the exact textual
forms jaxlib 0.4.x prints and degrade to "op not recognised" (never a
crash) on anything else.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Collective op mnemonics, normalised to underscore form.  StableHLO
# spells them `stablehlo.all_reduce`; optimized HLO spells them
# `all-reduce` (plus the async `-start`/`-done` pair forms).
COLLECTIVE_KINDS = (
    "all_reduce", "all_gather", "all_to_all", "collective_permute",
    "reduce_scatter", "collective_broadcast",
)

# custom_call targets that move data between host and device (or name a
# host callback).  Compute custom_calls (lapack_*, cu*, Sharding
# annotations) do not match.
_TRANSFER_TARGET_RE = re.compile(
    r"callback|host_|_host|infeed|outfeed|xla_ffi_partial_buffer",
    re.IGNORECASE)

# Op kinds that are host transfers by construction.
_TRANSFER_KINDS = frozenset(
    {"infeed", "outfeed", "send", "recv", "send_done", "recv_done"})


@dataclasses.dataclass(frozen=True)
class HloOp:
    """One interesting op occurrence in an HLO/StableHLO text module."""

    kind: str  # normalised mnemonic, e.g. "all_reduce", "custom_call"
    line: int  # 1-based line number in the module text
    text: str  # the stripped source line (truncated for reporting)
    while_depth: int = 0  # enclosing `stablehlo.while` regions (StableHLO)
    target: Optional[str] = None  # custom_call target, when present
    op_name: Optional[str] = None  # compiled-HLO metadata scope path
    result_dtype: Optional[str] = None
    result_elems: Optional[int] = None
    # Total result payload in bytes across ALL tuple components (None
    # when unknown): combined collectives (AllReduceCombiner) price the
    # SUM of their component tensors, async -start forms the LARGEST
    # component (their tuples alias the operand beside the output, plus
    # negligible context scalars).  result_dtype/result_elems keep the
    # first component only.
    result_bytes: Optional[float] = None
    # Collective replica groups from the compiled HLO (None when the op
    # carries none / the form was not recognised): a tuple of
    # device-id tuples.  collective_permute carries its
    # source_target_pairs here instead (pairs, not groups).
    replica_groups: Optional[Tuple[Tuple[int, ...], ...]] = None

    def where(self) -> str:
        scope = f" [{self.op_name}]" if self.op_name else ""
        tgt = f" @{self.target}" if self.target else ""
        return f"line {self.line}: {self.kind}{tgt}{scope}"

    def group_size(self, world: Optional[int] = None) -> Optional[int]:
        """Largest replica-group size.  collective_permute carries
        source->target pairs, not groups: the devices a permute spans
        are the largest weakly-connected component of its pair graph
        (a ring over a subgroup of g devices is one g-cycle; an open
        chain 0->1->2->3 still spans 4 devices — a cycle walk would
        undercount it and mis-certify a world-spanning permute as
        subgroup-scoped).  XLA's explicit empty form
        `replica_groups={}` means ONE group spanning every device:
        resolved to `world` when the caller supplies it (None
        otherwise — absent metadata stays uncertifiable)."""
        if not self.replica_groups:
            return None
        if self.replica_groups == ((),):
            return int(world) if world else None
        if self.kind == "collective_permute":
            parent: dict = {}

            def find(x):
                parent.setdefault(x, x)
                while parent[x] != x:
                    parent[x] = parent[parent[x]]
                    x = parent[x]
                return x

            for a, b in self.replica_groups:
                parent[find(a)] = find(b)
            sizes: dict = {}
            for x in parent:
                r = find(x)
                sizes[r] = sizes.get(r, 0) + 1
            return max(sizes.values()) if sizes else 1
        return max(len(g) for g in self.replica_groups)


_STRING_RE = re.compile(r'"[^"]*"')
_SHLO_OP_RE = re.compile(r'"?stablehlo\.(\w+)"?')
_SHLO_TARGET_RE = re.compile(
    r'stablehlo\.custom_call\s+@([\w.\-]+)|call_target_name\s*=\s*"([^"]+)"')
_TENSOR_RE = re.compile(r"tensor<((?:\d+x)*)([a-z][a-z0-9]*)>")


def _dims_elems(dims: str) -> int:
    n = 1
    for d in dims.split("x"):
        if d:
            n *= int(d)
    return n


def parse_stablehlo_ops(text: str) -> List[HloOp]:
    """Scan a StableHLO module for ops, tracking while-region nesting.

    Only op-defining lines are recorded (one op per line in jax's pretty
    printer).  `while_depth` counts enclosing `stablehlo.while` regions,
    so depth >= 1 means "inside some loop body/cond".
    """
    ops: List[HloOp] = []
    depth = 0  # brace depth, strings stripped
    # Each entry: [brace depth at the `while` line, region-opened flag].
    # The regions (`cond { ... } do { ... }`) open on LATER lines, so a
    # frame only becomes poppable once depth has risen above its
    # threshold — otherwise the while line itself would pop it.
    while_stack: List[List] = []
    for lineno, raw in enumerate(text.splitlines(), start=1):
        # Strip string literals for BRACE counting only (attr strings can
        # contain braces); ops are matched on the raw line — the generic
        # print form quotes the op name (`"stablehlo.all_reduce"(...)`).
        line = _STRING_RE.sub('""', raw)
        m = _SHLO_OP_RE.search(raw)
        if m:
            kind = m.group(1)
            target = None
            if kind == "custom_call":
                tm = _SHLO_TARGET_RE.search(raw)
                if tm:
                    target = tm.group(1) or tm.group(2)
            rd, re_ = _stablehlo_result(line)
            ops.append(HloOp(
                kind=kind, line=lineno, text=raw.strip()[:200],
                while_depth=len(while_stack), target=target,
                result_dtype=rd, result_elems=re_))
            if kind == "while":
                opens, closes = line.count("{"), line.count("}")
                if not (opens and opens == closes):
                    # jax's pretty form opens the regions on LATER lines
                    # (push unopened); the generic one-line form
                    # `"stablehlo.while"(...) ({...}, {...})` is fully
                    # self-contained — pushing it would leak a frame
                    # (net brace delta 0 never pops), so skip it.
                    while_stack.append([depth, opens > closes])
        depth += line.count("{") - line.count("}")
        while while_stack:
            threshold, opened = while_stack[-1]
            if not opened:
                if depth > threshold:
                    while_stack[-1][1] = True
                break
            if depth <= threshold:
                while_stack.pop()
            else:
                break
    return ops


def _stablehlo_result(line: str) -> Tuple[Optional[str], Optional[int]]:
    """Element dtype/count of an op line's (last) result tensor type."""
    # Result types trail the op: `... : (in) -> tensor<...>` or
    # `... : tensor<...>`; take the last tensor token on the line.
    matches = _TENSOR_RE.findall(line)
    if not matches:
        return None, None
    dims, dtype = matches[-1]
    return dtype, _dims_elems(dims)


# Replica groups on compiled collectives.  Two textual forms exist:
# the explicit list `replica_groups={{0,1},{2,3}}` and the iota form
# `replica_groups=[2,2]<=[4]` (optionally `[2,2]<=[2,2]T(1,0)`: iota
# over the <= dims, transposed by T's permutation, then reshaped to
# [num_groups, group_size]).  collective_permute carries
# `source_target_pairs={{0,1},{1,0}}` instead.
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{((?:\{[\d,]*\},?)*)\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{[\d,]*\},?)*)\}")
_GROUP_RE = re.compile(r"\{([\d,]*)\}")


def _parse_groups(raw: str) -> Optional[Tuple[Tuple[int, ...], ...]]:
    """Replica groups (or permute pairs) of one compiled-HLO op line."""
    m = _PAIRS_RE.search(raw)
    if m:
        return tuple(
            tuple(int(x) for x in g.group(1).split(",") if x != "")
            for g in _GROUP_RE.finditer(m.group(1)))
    m = _GROUPS_LIST_RE.search(raw)
    if m:
        groups = tuple(
            tuple(int(x) for x in g.group(1).split(",") if x != "")
            for g in _GROUP_RE.finditer(m.group(1)))
        # XLA's explicit empty form `replica_groups={}` is ONE group
        # over all devices (world scope), kept as the ((),) marker so
        # group_size(world=...) can resolve it — distinct from
        # replica_groups=None (no metadata at all).
        return groups or ((),)
    m = _GROUPS_IOTA_RE.search(raw)
    if m:
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        total = 1
        for d in dims:
            total *= d
        ids = list(range(total))
        if m.group(4):
            perm = [int(x) for x in m.group(4).split(",")]
            # Transpose the iota over `dims` by `perm`, then flatten.
            strides = [0] * len(dims)
            acc = 1
            for i in range(len(dims) - 1, -1, -1):
                strides[i] = acc
                acc *= dims[i]
            tdims = [dims[p] for p in perm]
            tstrides = [strides[p] for p in perm]
            out = []

            def rec(i, off):
                if i == len(tdims):
                    out.append(off)
                    return
                for k in range(tdims[i]):
                    rec(i + 1, off + k * tstrides[i])

            rec(0, 0)
            ids = out
        if n_groups * group_size != total:
            return None
        return tuple(
            tuple(ids[g * group_size:(g + 1) * group_size])
            for g in range(n_groups))
    return None


# Tensor element sizes (bytes) for the collective byte model.
DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4,
    "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1,
    "c64": 8, "c128": 16,
}


def collective_bytes_moved(op: HloOp, world: int) -> float:
    """Ring-model bytes moved PER DEVICE by one collective op.

    The standard bandwidth-optimal ring costs, in operand bytes B and
    replica-group size g (defaulting to `world` when the op carries no
    groups):

      all_reduce          2 B (g-1)/g     (reduce-scatter + all-gather)
      reduce_scatter      B_out (g-1)     (input = B_out * g)
      all_gather          B_out (g-1)/g
      all_to_all          B (g-1)/g
      collective_permute  B               (every device sends its block)

    `op.result_bytes` supplies B from the op's FULL result payload
    (tuple components summed for combined collectives, largest for
    async -start forms); ops parsed without it fall back to
    `result_elems` x `result_dtype` (first component — exact for every
    single-tensor result).  For reduce_scatter the result is the 1/g
    shard, hence the (g-1) factor against B_out.  Unknown kinds/dtypes
    cost 0 — the census still counts them, so a new kind can never
    silently pass the exact count gates while being mis-priced here.
    """
    if op.result_bytes is not None:
        b = op.result_bytes
    elif op.result_elems is not None:
        b = float(op.result_elems) * DTYPE_BYTES.get(op.result_dtype or "", 0)
    else:
        return 0.0
    g = op.group_size(world) or max(int(world), 1)
    g = max(g, 1)
    if op.kind == "all_reduce":
        return 2.0 * b * (g - 1) / g
    if op.kind == "reduce_scatter":
        return b * (g - 1)
    if op.kind in ("all_gather", "all_to_all", "collective_broadcast"):
        return b * (g - 1) / g
    if op.kind == "collective_permute":
        return b
    return 0.0


# Optimized-HLO op definitions: `%name = f32[9,24]{1,0} all-reduce(...)`.
# The result may be a TUPLE type `(f32[..]{..}, s32[..]{..})` — XLA's
# AllReduceCombiner emits combined collectives in exactly that form, so
# the tuple alternative must come first or a merged all-reduce would be
# invisible to the census.
# (scalar result types like `f32[]` match the empty-bracket form too).
_HLO_DEF_RE = re.compile(
    r"=\s*(\([^)]*\)|[a-z0-9]+\[[0-9,]*\]\S*)\s+([a-z][a-z0-9\-]*)\(")
_HLO_TYPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_OP_NAME_RE = re.compile(r'op_name="([^"]+)"')
_HLO_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')


def parse_compiled_ops(text: str) -> List[HloOp]:
    """Scan an optimized-HLO module for op definitions with metadata."""
    ops: List[HloOp] = []
    for lineno, raw in enumerate(text.splitlines(), start=1):
        m = _HLO_DEF_RE.search(raw)
        if not m:
            continue
        kind = m.group(2).replace("-", "_")
        # The async pair forms count once, at the -start op.
        if kind.endswith("_done"):
            kind_base = kind[:-5]
            if kind_base in COLLECTIVE_KINDS:
                continue
        is_async = kind.endswith("_start")
        if is_async:
            kind = kind[:-6]
        tm = _HLO_TYPE_RE.search(m.group(1))
        rd = tm.group(1) if tm else None
        re_ = _dims_elems(tm.group(2).replace(",", "x")) if tm else None
        # Per-component payload over the whole (possibly tuple) result:
        # a combined collective's components are independent outputs
        # (sum them); an async -start tuple aliases the operand beside
        # the output plus tiny context scalars (largest component is
        # the payload for every dedicated -start form: all-reduce and
        # collective-permute move input-sized blocks, all-gather's
        # output dominates its input shard).
        comp = [_dims_elems(c.group(2).replace(",", "x")) *
                DTYPE_BYTES.get(c.group(1), 0)
                for c in _HLO_TYPE_RE.finditer(m.group(1))]
        rb = None
        if comp:
            rb = float(max(comp) if is_async else sum(comp))
        nm = _OP_NAME_RE.search(raw)
        tg = _HLO_TARGET_RE.search(raw)
        groups = (_parse_groups(raw)
                  if kind in COLLECTIVE_KINDS else None)
        ops.append(HloOp(
            kind=kind, line=lineno, text=raw.strip()[:200],
            target=tg.group(1) if tg else None,
            op_name=nm.group(1) if nm else None,
            result_dtype=rd, result_elems=re_, result_bytes=rb,
            replica_groups=groups))
    return ops


def transfer_ops(ops: Iterable[HloOp],
                 allow: Sequence[str] = ()) -> List[HloOp]:
    """Host-transfer ops: infeed/outfeed/send/recv + callback custom_calls.

    `allow` lists custom_call targets that are sanctioned (the
    observability layer's trace outputs); everything else that matches
    the transfer pattern is a violation.
    """
    out = []
    for op in ops:
        if op.kind in _TRANSFER_KINDS:
            out.append(op)
        elif op.kind == "custom_call" and op.target:
            if op.target in allow:
                continue
            if _TRANSFER_TARGET_RE.search(op.target):
                out.append(op)
    return out


def collective_ops(ops: Iterable[HloOp]) -> List[HloOp]:
    return [op for op in ops if op.kind in COLLECTIVE_KINDS]


def custom_call_census(ops: Iterable[HloOp]) -> Dict[str, int]:
    """target -> count of every custom_call in the op stream.

    The transfer pass answers "does this program leave the device?";
    this census answers "what OPAQUE code does it run?".  A Pallas
    kernel lowers to a custom_call (`tpu_custom_call` on TPU; interpret
    mode on the CPU lane lowers to plain HLO and leaves no trace here),
    so the canonical fused-OFF programs pin an empty/kernel-free census
    — a Pallas call leaking into a default-option lowering is a dark-
    launch violation, caught by name."""
    out: Dict[str, int] = {}
    for op in ops:
        if op.kind == "custom_call":
            t = op.target or "<unknown>"
            out[t] = out.get(t, 0) + 1
    return out


def _walk_stablehlo_lines(text: str):
    """Yield (lineno, raw, kind-or-None, while_depth, brace_depth) for
    every line of a StableHLO module.

    The ONE copy of the while-region/brace state machine shared by the
    bf16 scanners below (`parse_stablehlo_ops` predates it and keeps
    its own in-loop copy — its op-recording behaviour is pinned by the
    existing census baselines, so it is not re-threaded here).
    `brace_depth` is the depth at the START of the line; `while_depth`
    counts enclosing `stablehlo.while` regions, with the same
    deferred-open handling as `parse_stablehlo_ops` (the regions open
    on later lines; the generic one-line self-contained form is never
    pushed).
    """
    depth = 0
    while_stack: List[List] = []
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = _STRING_RE.sub('""', raw)
        m = _SHLO_OP_RE.search(raw)
        kind = m.group(1) if m else None
        yield lineno, raw, kind, len(while_stack), depth
        if kind == "while":
            opens, closes = line.count("{"), line.count("}")
            if not (opens and opens == closes):
                while_stack.append([depth, opens > closes])
        depth += line.count("{") - line.count("}")
        while while_stack:
            threshold, opened = while_stack[-1]
            if not opened:
                if depth > threshold:
                    while_stack[-1][1] = True
                break
            if depth <= threshold:
                while_stack.pop()
            else:
                break


@dataclasses.dataclass(frozen=True)
class CollectivePayload:
    """One StableHLO collective with its DECLARED payload type.

    Region-bearing collectives (all_reduce, reduce_scatter) print
    their type signature on the REGION-CLOSING line (`}) : (...) ->
    tensor<...>`), not the op line — `stablehlo_collective_payloads`
    stitches the two; regionless kinds (all_gather,
    collective_permute) carry it inline."""

    kind: str
    line: int
    result_dtype: Optional[str]
    result_elems: Optional[int]
    while_depth: int


def stablehlo_collective_payloads(text: str) -> List[CollectivePayload]:
    """Every StableHLO collective op with its declared result payload.

    The declared payload is what the byte model prices for bf16-
    collective programs (analysis/program_audit): the compiled
    executable's payload dtype is backend-normalized (XLA:CPU promotes
    bf16 collectives to f32), while the StableHLO records what the
    program asked the wire to carry.  `while_depth` >= 2 marks the PCG
    while body (the LM loop is depth 1).
    """
    out: List[CollectivePayload] = []
    # (kind, lineno, while_depth, brace depth at open) of region-form
    # collectives whose type signature is still pending.
    pending: List[Tuple[str, int, int, int]] = []
    for lineno, raw, kind, wdepth, depth in _walk_stablehlo_lines(text):
        if kind in COLLECTIVE_KINDS:
            matches = _TENSOR_RE.findall(
                raw.split("->")[-1]) if "->" in raw else []
            if matches:
                # Inline form: the full signature is on the op line.
                dims, dt = matches[-1]
                out.append(CollectivePayload(
                    kind=kind, line=lineno, result_dtype=dt,
                    result_elems=_dims_elems(dims), while_depth=wdepth))
            else:
                pending.append((kind, lineno, wdepth, depth))
        elif (pending and "->" in raw and kind is None
              and depth + (s := _STRING_RE.sub('""', raw)).count("{")
              - s.count("}") <= pending[-1][3]):
            # Region-closing signature line of the innermost pending
            # collective: `}) : (tensor<..>) -> tensor<..>`.
            k, ln, wd, _ = pending.pop()
            matches = _TENSOR_RE.findall(raw.split("->")[-1])
            if matches:
                dims, dt = matches[-1]
                out.append(CollectivePayload(
                    kind=k, line=ln, result_dtype=dt,
                    result_elems=_dims_elems(dims), while_depth=wd))
            else:
                out.append(CollectivePayload(
                    kind=k, line=ln, result_dtype=None,
                    result_elems=None, while_depth=wd))
    return out


@dataclasses.dataclass(frozen=True)
class Bf16Op:
    """One StableHLO op line that touches a bf16 tensor (operand or
    result) — the unit of the allowed-bf16-surface pass
    (analysis/program_audit.Bf16Surface)."""

    kind: str  # stablehlo mnemonic, e.g. "multiply", "convert"
    line: int
    text: str  # the stripped source line (truncated for reporting)
    dtypes: Tuple[str, ...]  # every tensor element type on the line
    result_dtype: Optional[str]  # last tensor token = the result
    result_scalar: bool  # result tensor has no dims (rank 0)
    while_depth: int


def bf16_stablehlo_ops(text: str) -> List[Bf16Op]:
    """Every StableHLO op line carrying a bf16 tensor, with the FULL
    dtype tuple of the line (operands + result).

    Scans raw lines (not the truncated `HloOp.text`) so a long line's
    trailing result type cannot be cut out of the census; block-
    argument and function-signature lines (no `stablehlo.` op) are
    types, not ops, and are skipped.  While-region nesting is tracked
    exactly as in `parse_stablehlo_ops` so the surface pass can tell
    in-body ops from build-time ones.
    """
    out: List[Bf16Op] = []
    for lineno, raw, kind, wdepth, _ in _walk_stablehlo_lines(text):
        if kind is None or "bf16" not in raw:
            continue
        matches = _TENSOR_RE.findall(raw)
        dtypes = tuple(dt for _, dt in matches)
        if "bf16" not in dtypes:
            continue
        out.append(Bf16Op(
            kind=kind, line=lineno, text=raw.strip()[:200],
            dtypes=dtypes, result_dtype=matches[-1][1],
            result_scalar=matches[-1][0] == "", while_depth=wdepth))
    return out


def dtype_census(text: str) -> Dict[str, int]:
    """tensor element-type -> occurrence count over a StableHLO module."""
    census: Dict[str, int] = {}
    for dims, dtype in _TENSOR_RE.findall(text):
        census[dtype] = census.get(dtype, 0) + 1
    return census


def lines_with_dtype(text: str, dtype: str, limit: int = 5
                     ) -> List[Tuple[int, str]]:
    """First `limit` (lineno, line) occurrences of tensor<...x{dtype}>."""
    needle = re.compile(r"tensor<(?:\d+x)*" + re.escape(dtype) + ">")
    out: List[Tuple[int, str]] = []
    for lineno, raw in enumerate(text.splitlines(), start=1):
        if needle.search(raw):
            out.append((lineno, raw.strip()[:200]))
            if len(out) >= limit:
                break
    return out


# `input_output_alias={ {5}: (0, {}, may-alias), ... }` in the module
# header: output-index-tuple -> (parameter, param_index_tuple, kind).
_ALIAS_ENTRY_RE = re.compile(r"\{([\d,\s]*)\}:\s*\((\d+)")


def input_output_aliases(compiled_text: str) -> List[Tuple[str, int]]:
    """[(output_index_tuple, parameter_number)] of the entry computation.

    Empty when the compiled executable materialised no aliasing (i.e.
    declared donation was dropped).
    """
    # The alias map lives on the `HloModule` header line.
    start = compiled_text.find("input_output_alias={")
    if start < 0:
        return []
    # The block nests one level of braces per entry; scan to the close
    # (no length cap: a truncated scan would read as "donation dropped"
    # and fail the gate with a wrong answer — the loop terminates at the
    # matching brace anyway).
    i = compiled_text.find("{", start)
    depth = 0
    block = ""
    for j in range(i, len(compiled_text)):
        if compiled_text[j] == "{":
            depth += 1
        elif compiled_text[j] == "}":
            depth -= 1
            if depth == 0:
                block = compiled_text[i:j + 1]
                break
    if not block:
        return []
    return [(m.group(1).strip(), int(m.group(2)))
            for m in _ALIAS_ENTRY_RE.finditer(block)]


def aliased_parameters(compiled_text: str) -> frozenset:
    """The set of entry parameters that alias some output."""
    return frozenset(p for _, p in input_output_aliases(compiled_text))
