"""AST-level concurrency contract analyzer for the host serving tier.

Pure standard library (ast) over the existing PackageIndex — nothing
here imports or executes the code under analysis.  Three passes, each a
lint rule (analysis/rules.py registers them; lane 6 of scripts/lint.sh
gates the package on all three):

**guarded-by** — race detection for shared mutable attributes of
classes that own a `threading.Lock/RLock/Condition`.  A field's lock is
declared with an inline `# megba: guarded-by(<lockattr>)` pragma on its
assignment (conventionally in `__init__`), or *inferred* when at least
80% of its post-construction accesses (and at least 5 of them) happen
under one owned lock.  Any other read/write of a guarded field outside
a `with <lock>` block is a finding — for declared fields always (the
pragma IS the contract), for inferred fields only when the class is
reachable from a second thread per the `threading.Thread(target=...)`
census this pass also builds (a class whose method is a thread target,
or that spawns threads itself).  `# megba: allow-unguarded` on the
access line is the escape hatch (equivalent to `allow-guarded-by`).

**lock-order** — deadlock analysis.  The pass builds the
acquires-while-holding digraph across the whole package: nested `with`
blocks, acquisitions inside functions *called* while a lock is held
(through the callgraph, including `self.method()` edges resolved to the
defining class), and `Condition.wait` re-acquires (waiting on a
condition while holding another lock re-acquires the condition LAST —
the edge that turns an innocuous-looking wait into an inversion).  Any
cycle is a finding, reported with the witness path
(`A._a -> B._b (file:line) -> A._a (file:line)`).

**blocking-under-lock** — the classic serve-loop stall shape: a call
from a curated blocking set made while any lock is held.  The curated
set: `*.result(...)` (Future.result), `*.get()` with no positional
arguments (queue.Queue.get — dict `.get(key)` always passes the key),
`*.join()` / `*.join(<number>)` (Thread/Queue join; `sep.join(parts)`
passes a non-literal), `*.wait(...)` on anything that is not a held
Condition of the same class (Event.wait, Popen.wait — waiting on a
HELD condition releases it and is the sanctioned pattern),
socket/pipe-style `*.recv/recv_bytes/recv_into/_recv_frame(...)` (the
lockstep-RPC shape), and `time.sleep` above a 0.05 s threshold (or
with a non-constant duration — a backoff sleep under a lock stalls
every other holder).

Deliberate conservatisms (the linter never guesses): lock identities
are `self.<attr>` of the owning class (constructed locally, or named by
a `guarded-by` pragma — a declared guard counts as an owned lock even
when the object is handed in or aliased) and module-level
`NAME = threading.Lock()` globals — locks reached through another
object's attribute are otherwise invisible; subclass methods are not
checked against a base class's declarations (inheritance is not
walked); closures nested inside methods are
analyzed as separate functions with an empty held-set and their `self`
accesses are not attributed to the class; inheritance is not walked.
Private methods (leading underscore) that are only ever called from
under a lock inherit that lock as held-at-entry (fixed point over the
class's internal callgraph), so `_foo_locked()` helpers need no
annotation; a private method referenced without being called (thread
target, callback registration) escapes and is analyzed lock-free.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from megba_tpu.analysis.callgraph import (
    FunctionInfo,
    ModuleInfo,
    PackageIndex,
    _dotted,
)

# `# megba: guarded-by(<lockattr>)` — parsed separately from the token
# pragmas (callgraph.PRAGMA_RE stops at the parenthesis).
_GUARDED_BY_RE = re.compile(r"#\s*megba:.*?guarded-by\(\s*(\w+)\s*\)")
# `# megba: allow-unguarded` rides the normal token-pragma syntax.
_ALLOW_UNGUARDED_RE = re.compile(r"#\s*megba:.*?\ballow-unguarded\b")

_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
}

_RECV_TAILS = {"recv", "recv_bytes", "recv_into", "_recv_frame"}
_SLEEP_THRESHOLD_S = 0.05

# Fully-qualified call heads whose `.join` is path/string assembly, not
# a thread join.
_JOIN_EXEMPT_PREFIXES = ("os.path.", "posixpath.", "ntpath.")


# --------------------------------------------------------------- model


@dataclasses.dataclass
class _ClassModel:
    qualname: str  # dotted class qualname (module path included)
    module: str
    locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    # lock attr -> ctor kind ("Lock" | "RLock" | "Condition")
    cond_alias: Dict[str, str] = dataclasses.field(default_factory=dict)
    # condition attr -> underlying lock attr (threading.Condition(self.X))
    declared: Dict[str, Tuple[str, int]] = dataclasses.field(
        default_factory=dict)  # field -> (lock attr, decl line)
    methods: Dict[str, str] = dataclasses.field(default_factory=dict)
    # method simple name -> function qualname
    threaded: bool = False  # census: shares state with a second thread

    def lock_id(self, attr: str) -> str:
        return f"{_short(self.qualname)}.{self.canonical(attr)}"

    def canonical(self, attr: str) -> str:
        return self.cond_alias.get(attr, attr)


@dataclasses.dataclass
class _Access:
    attr: str
    is_write: bool
    held: frozenset  # lexical held set (lock attrs of the class)
    line: int
    col: int
    in_init: bool
    method: str  # method simple name


@dataclasses.dataclass
class _Scan:
    """Per-function lexical facts, entry-held-independent."""

    accesses: List[_Access] = dataclasses.field(default_factory=list)
    # with-block acquisitions: (lock id, lexical held ids, line, col)
    acquires: List[Tuple[str, frozenset, int, int]] = dataclasses.field(
        default_factory=list)
    # resolved calls: (callee qualname, lexical held ids, line, col)
    calls: List[Tuple[str, frozenset, int, int]] = dataclasses.field(
        default_factory=list)
    # curated blocking calls: (label, lexical held ids, line, col)
    blocking: List[Tuple[str, frozenset, int, int]] = dataclasses.field(
        default_factory=list)
    # Condition.wait sites: (cond lock id, lexical held ids, line, col)
    waits: List[Tuple[str, frozenset, int, int]] = dataclasses.field(
        default_factory=list)
    # self-method names referenced WITHOUT a call (escapes: callbacks,
    # thread targets) — such methods run lock-free at entry
    escapes: Set[str] = dataclasses.field(default_factory=set)
    spawns_thread: bool = False
    # thread targets: resolved function qualnames
    thread_targets: Set[str] = dataclasses.field(default_factory=set)


def _short(qualname: str) -> str:
    """`megba_tpu.serving.queue.FleetQueue` -> `queue.FleetQueue` —
    findings stay readable without losing which module owns the lock."""
    parts = qualname.split(".")
    return ".".join(parts[-2:]) if len(parts) > 2 else qualname


def _alias_target(mod: ModuleInfo, dotted: Optional[str]) -> Optional[str]:
    if dotted is None:
        return None
    head, *rest = dotted.split(".")
    target = mod.imports.get(head, head)
    return ".".join([target] + rest)


def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.X` -> "X", else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _const_number(node: ast.AST) -> Optional[float]:
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)) and not isinstance(node.value, bool):
        return float(node.value)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _const_number(node.operand)
        return -inner if inner is not None else None
    return None


def _classname_of(index: PackageIndex, info: FunctionInfo) -> Optional[str]:
    """Innermost enclosing class, walking out through nested defs."""
    cur: Optional[FunctionInfo] = info
    while cur is not None:
        if cur.classname is not None:
            return cur.classname
        cur = index.functions.get(cur.parent) if cur.parent else None
    return None


# ------------------------------------------------------------ analyzer


class _Analyzer:
    """One full concurrency model per PackageIndex (memoised on it)."""

    def __init__(self, index: PackageIndex) -> None:
        self.index = index
        self.classes: Dict[str, _ClassModel] = {}
        self.module_locks: Dict[str, str] = {}  # "mod.NAME" -> lock id
        self.scans: Dict[str, _Scan] = {}
        self.entry_held: Dict[str, frozenset] = {}
        self._acq_summary: Dict[str, Dict[str, Tuple[str, int, int]]] = {}
        self._build()

    # ------------------------------------------------------------ build
    def _build(self) -> None:
        self._collect_module_locks()
        self._collect_classes()
        for qual, info in sorted(self.index.functions.items()):
            self.scans[qual] = self._scan_function(qual, info)
        self._census()
        self._solve_entry_held()

    def _collect_module_locks(self) -> None:
        for mod in self.index.modules.values():
            for stmt in mod.tree.body:
                if not (isinstance(stmt, ast.Assign)
                        and isinstance(stmt.value, ast.Call)):
                    continue
                full = _alias_target(mod, _dotted(stmt.value.func))
                if full not in _LOCK_CTORS:
                    continue
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        key = f"{mod.name}.{tgt.id}"
                        self.module_locks[key] = (
                            f"{_short(mod.name)}.{tgt.id}")

    def _collect_classes(self) -> None:
        for cls_qual, methods in self.index.classes.items():
            any_q = next(iter(methods.values()))
            modname = self.index.functions[any_q].module
            mod = self.index.modules[modname]
            cm = _ClassModel(qualname=cls_qual, module=modname)
            cm.methods = dict(methods)
            for mname, fq in sorted(methods.items()):
                fn = self.index.functions[fq].node
                for node in ast.walk(fn):
                    if isinstance(node, ast.Assign):
                        targets = node.targets
                    elif isinstance(node, ast.AnnAssign):
                        targets = [node.target]  # self.x: T = ... pragmas
                    else:
                        continue
                    for tgt in targets:
                        attr = _self_attr(tgt)
                        if attr is None:
                            continue
                        if isinstance(node.value, ast.Call):
                            full = _alias_target(
                                mod, _dotted(node.value.func))
                            kind = _LOCK_CTORS.get(full or "")
                            if kind is not None:
                                cm.locks[attr] = kind
                                if kind == "Condition" and node.value.args:
                                    inner = _self_attr(node.value.args[0])
                                    if inner is not None:
                                        cm.cond_alias[attr] = inner
                        # A multi-line assignment carries its pragma on
                        # the closing line; scan the statement's span.
                        end = getattr(node, "end_lineno", node.lineno)
                        decl = None
                        for ln in range(node.lineno,
                                        min(end, len(mod.source_lines)) + 1):
                            decl = _GUARDED_BY_RE.search(
                                mod.source_lines[ln - 1])
                            if decl is not None:
                                break
                        if decl is not None:
                            cm.declared[attr] = (decl.group(1), node.lineno)
            # A declared guard that is not locally constructed (a lock
            # handed in or aliased from another object) still IS the
            # contract: register it so `with self.<guard>` is tracked
            # and unlocked accesses of the declaring field flag.
            for _field, (lockattr, _line) in sorted(cm.declared.items()):
                cm.locks.setdefault(lockattr, "Lock")
            if cm.locks:
                self.classes[cls_qual] = cm

    # ------------------------------------------------------------- scan
    def _scan_function(self, qual: str, info: FunctionInfo) -> _Scan:
        scan = _Scan()
        mod = self.index.modules[info.module]
        cm = (self.classes.get(info.classname)
              if info.classname is not None else None)
        in_init = bool(cm is not None
                       and qual.rsplit(".", 1)[-1] == "__init__")
        method = qual.rsplit(".", 1)[-1]

        def lock_of(expr: ast.AST) -> Optional[str]:
            attr = _self_attr(expr)
            if attr is not None and cm is not None and attr in cm.locks:
                return cm.lock_id(attr)
            full = _alias_target(mod, _dotted(expr))
            if full in self.module_locks:
                return self.module_locks[full]
            # A bare name in its defining module: qualify and retry.
            local = f"{mod.name}.{full}"
            if local in self.module_locks:
                return self.module_locks[local]
            return None

        def handle_call(node: ast.Call, held: frozenset) -> None:
            func = node.func
            dotted = _dotted(func)
            full = _alias_target(mod, dotted)
            # threading.Thread(target=...) census
            if full == "threading.Thread":
                scan.spawns_thread = True
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    tattr = _self_attr(kw.value)
                    if tattr is not None and cm is not None:
                        tq = cm.methods.get(tattr)
                        if tq is not None:
                            scan.thread_targets.add(tq)
                    else:
                        tq = self.index.resolve(mod, info, kw.value)
                        if tq is not None:
                            scan.thread_targets.add(tq)
                return
            # Condition.wait on an owned lock: sanctioned release +
            # re-acquire (the re-acquire edge rides scan.waits)
            if (isinstance(func, ast.Attribute)
                    and func.attr in ("wait", "wait_for")):
                recv_lock = lock_of(func.value)
                if recv_lock is not None:
                    scan.waits.append(
                        (recv_lock, held, node.lineno, node.col_offset))
                    return
            # curated blocking set
            label = self._blocking_label(mod, node)
            if label is not None:
                scan.blocking.append(
                    (label, held, node.lineno, node.col_offset))
            # resolved calls (self.method() included via callgraph)
            callee = self.index.resolve(mod, info, func)
            if callee is not None and callee != qual:
                scan.calls.append(
                    (callee, held, node.lineno, node.col_offset))

        def visit(node: ast.AST, held: frozenset) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                return  # separate scope: analyzed as its own function
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = held
                for item in node.items:
                    lock = lock_of(item.context_expr)
                    if lock is not None and lock not in inner:
                        scan.acquires.append(
                            (lock, inner, item.context_expr.lineno,
                             item.context_expr.col_offset))
                        inner = inner | {lock}
                for item in node.items:
                    visit(item.context_expr, held)
                    if item.optional_vars is not None:
                        visit(item.optional_vars, held)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, ast.Call):
                handle_call(node, held)
            if isinstance(node, ast.Attribute) and cm is not None:
                attr = _self_attr(node)
                if (attr is not None and attr not in cm.locks
                        and attr not in cm.methods):
                    is_write = isinstance(node.ctx, (ast.Store, ast.Del))
                    scan.accesses.append(_Access(
                        attr=attr, is_write=is_write, held=held,
                        line=node.lineno, col=node.col_offset,
                        in_init=in_init, method=method))
                elif (attr is not None and attr in cm.methods
                      and isinstance(node.ctx, ast.Load)):
                    scan.escapes.add(attr)  # may be pruned at call sites
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in ast.iter_child_nodes(info.node):
            visit(stmt, frozenset())

        # `self.m(...)` loads the attribute then calls it — an escape
        # survives only if some Load of the name is NOT the func of a
        # Call (a bare reference: callback registration, thread target).
        loads: Dict[str, int] = {}
        call_loads: Dict[str, int] = {}
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call):
                attr = _self_attr(node.func)
                if attr is not None:
                    call_loads[attr] = call_loads.get(attr, 0) + 1
            if isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Load):
                attr = _self_attr(node)
                if attr is not None:
                    loads[attr] = loads.get(attr, 0) + 1
        scan.escapes = {a for a in scan.escapes
                        if loads.get(a, 0) > call_loads.get(a, 0)}
        return scan

    def _blocking_label(self, mod: ModuleInfo,
                        node: ast.Call) -> Optional[str]:
        func = node.func
        dotted = _dotted(func)
        full = _alias_target(mod, dotted)
        if full == "time.sleep":
            if not node.args:
                return None
            dur = _const_number(node.args[0])
            if dur is None:
                return f"`{dotted}(<non-constant>)`"
            if dur > _SLEEP_THRESHOLD_S:
                return f"`{dotted}({dur:g})`"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        tail = func.attr
        recv_is_literal = isinstance(func.value, ast.Constant)
        if tail in _RECV_TAILS:
            return f"`{dotted or tail}(...)`"
        if tail == "result":
            if recv_is_literal:
                return None
            return f"`{dotted or tail}(...)` (Future.result)"
        if tail == "get":
            if node.args:  # dict.get(key[, default]) always passes the key
                return None
            return f"`{dotted or tail}()` (queue get)"
        if tail == "join":
            if recv_is_literal:
                return None  # "sep".join(...)
            if full is not None and full.startswith(_JOIN_EXEMPT_PREFIXES):
                return None
            if node.args and (len(node.args) > 1
                              or _const_number(node.args[0]) is None):
                return None  # sep.join(parts) / path join
            return f"`{dotted or tail}(...)` (thread/queue join)"
        if tail in ("wait", "wait_for"):
            # a *held* Condition's wait is sanctioned and handled before
            # this point; any other .wait under a lock blocks the holder
            return f"`{dotted or tail}(...)`"
        return None

    # ----------------------------------------------------------- census
    def _census(self) -> None:
        roots: Set[str] = set()
        for qual, scan in self.scans.items():
            roots |= scan.thread_targets
        # transitive: everything a thread root calls runs on that thread
        frontier = sorted(roots)
        seen = set(frontier)
        while frontier:
            q = frontier.pop()
            for callee, _, _, _ in self.scans.get(q, _Scan()).calls:
                if callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
        self.thread_reachable = seen
        for cls_qual, cm in self.classes.items():
            for mname, fq in cm.methods.items():
                if fq in seen:
                    cm.threaded = True
                if self.scans.get(fq, _Scan()).spawns_thread:
                    cm.threaded = True

    # --------------------------------------------------- entry-held sets
    def _solve_entry_held(self) -> None:
        """Greatest fixed point: a private method only ever called with
        lock L held is analyzed as holding L at entry."""
        entry: Dict[str, frozenset] = {
            q: frozenset() for q in self.index.functions}
        # call sites per callee, restricted to same-class self calls
        sites: Dict[str, List[Tuple[str, frozenset]]] = {}
        for caller, scan in self.scans.items():
            for callee, held, _, _ in scan.calls:
                sites.setdefault(callee, []).append((caller, held))
        candidates = []
        for cls_qual, cm in self.classes.items():
            lock_ids = frozenset(cm.lock_id(a) for a in cm.locks)
            escaped = set()
            for fq in cm.methods.values():
                for a in self.scans[fq].escapes:
                    if a in cm.methods:
                        escaped.add(cm.methods[a])
            for mname, fq in cm.methods.items():
                if (mname.startswith("_") and not mname.startswith("__")
                        and fq not in self.thread_reachable_roots()
                        and fq not in escaped
                        and sites.get(fq)):
                    entry[fq] = lock_ids
                    candidates.append(fq)
        changed = True
        while changed:
            changed = False
            for fq in candidates:
                new = None
                for caller, held in sites[fq]:
                    at_site = held | entry.get(caller, frozenset())
                    new = at_site if new is None else (new & at_site)
                new = new if new is not None else frozenset()
                if new != entry[fq]:
                    entry[fq] = new
                    changed = True
        self.entry_held = entry

    def thread_reachable_roots(self) -> Set[str]:
        roots: Set[str] = set()
        for scan in self.scans.values():
            roots |= scan.thread_targets
        return roots

    def held_at(self, qual: str, lexical: frozenset) -> frozenset:
        return lexical | self.entry_held.get(qual, frozenset())

    # ------------------------------------------------- acquire summaries
    def _acquires_of(self, qual: str,
                     stack: Optional[Set[str]] = None
                     ) -> Dict[str, Tuple[str, int, int]]:
        """Locks (transitively) acquired inside `qual`:
        lock id -> (path, line, col) of the acquisition site."""
        if qual in self._acq_summary:
            return self._acq_summary[qual]
        stack = stack or set()
        if qual in stack:
            return {}
        stack.add(qual)
        out: Dict[str, Tuple[str, int, int]] = {}
        scan = self.scans.get(qual)
        info = self.index.functions.get(qual)
        if scan is None or info is None:
            return {}
        path = self.index.modules[info.module].path
        for lock, _, line, col in scan.acquires:
            out.setdefault(lock, (path, line, col))
        for callee, _, _, _ in scan.calls:
            for lock, site in self._acquires_of(callee, stack).items():
                out.setdefault(lock, site)
        stack.discard(qual)
        self._acq_summary[qual] = out
        return out


def _analyzer(index: PackageIndex) -> _Analyzer:
    cached = getattr(index, "_megba_concurrency", None)
    if cached is None:
        cached = _Analyzer(index)
        index._megba_concurrency = cached  # type: ignore[attr-defined]
    return cached


# ---------------------------------------------------------------- rules


def find_guarded_by(index: PackageIndex):
    """Yields (path, line, col, message) for guarded-by races."""
    an = _analyzer(index)
    for cls_qual in sorted(an.classes):
        cm = an.classes[cls_qual]
        mod = index.modules[cm.module]
        # gather every self.<attr> access across the class's methods
        per_field: Dict[str, List[Tuple[str, _Access]]] = {}
        for mname, fq in sorted(cm.methods.items()):
            for acc in an.scans[fq].accesses:
                per_field.setdefault(acc.attr, []).append((fq, acc))
        for field in sorted(per_field):
            accs = per_field[field]
            post = [(fq, a) for fq, a in accs if not a.in_init]
            if not any(a.is_write for _, a in post):
                continue  # settled in __init__: publication is safe
            declared = cm.declared.get(field)
            guard: Optional[str] = None
            how = ""
            if declared is not None:
                guard = cm.canonical(declared[0])
                how = "declared"
            else:
                n = len(post)
                if n >= 5:
                    best, best_n = None, 0
                    for attr in cm.locks:
                        lid = cm.lock_id(attr)
                        n_under = sum(
                            1 for fq, a in post
                            if lid in an.held_at(fq, a.held))
                        if n_under > best_n:
                            best, best_n = attr, n_under
                    if best is not None and best_n / n >= 0.8:
                        guard = cm.canonical(best)
                        how = (f"inferred: {best_n}/{n} accesses "
                               f"hold it")
            if guard is None or guard not in cm.locks:
                continue
            if how != "declared" and not cm.threaded:
                continue  # census: no second thread reaches this class
            lock_id = cm.lock_id(guard)
            for fq, a in post:
                if lock_id in an.held_at(fq, a.held):
                    continue
                line_src = (mod.source_lines[a.line - 1]
                            if a.line <= len(mod.source_lines) else "")
                if _ALLOW_UNGUARDED_RE.search(line_src):
                    continue
                kind = "write" if a.is_write else "read"
                yield (
                    mod.path, a.line, a.col,
                    f"{kind} of `{_short(cls_qual)}.{field}` without "
                    f"`self.{guard}` ({how}); a concurrent holder can "
                    "race this access — take the lock or annotate the "
                    "line with `# megba: allow-unguarded`")


def find_lock_order(index: PackageIndex):
    """Yields (path, line, col, message) — one per lock-order cycle."""
    an = _analyzer(index)
    # edge (a -> b) -> (path, line, col, note); first (sorted) site wins
    edges: Dict[Tuple[str, str], Tuple[str, int, int, str]] = {}

    def add_edge(a: str, b: str, site: Tuple[str, int, int],
                 note: str) -> None:
        if a != b:
            edges.setdefault((a, b), (site[0], site[1], site[2], note))

    for qual in sorted(an.scans):
        scan = an.scans[qual]
        info = index.functions[qual]
        path = index.modules[info.module].path
        for lock, lexical, line, col in scan.acquires:
            for h in sorted(an.held_at(qual, lexical)):
                add_edge(h, lock, (path, line, col), "acquire")
        for callee, lexical, line, col in scan.calls:
            held = an.held_at(qual, lexical)
            if not held:
                continue
            for lock, site in sorted(an._acquires_of(callee).items()):
                if lock in held:
                    continue
                for h in sorted(held):
                    add_edge(h, lock, site,
                             f"via call on {path}:{line}")
        for cond, lexical, line, col in scan.waits:
            held = an.held_at(qual, lexical)
            for h in sorted(held - {cond}):
                # wait releases the condition, then re-acquires it LAST
                # — while still holding h
                add_edge(h, cond, (path, line, col),
                         "Condition.wait re-acquire")

    # cycle detection: DFS with colouring; report each cycle once,
    # canonicalised by rotating to its smallest node
    adj: Dict[str, List[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
    for a in adj:
        adj[a].sort()
    seen_cycles: Set[Tuple[str, ...]] = set()
    findings = []

    def dfs(node: str, stack: List[str], on_stack: Set[str],
            visited: Set[str]) -> None:
        visited.add(node)
        on_stack.add(node)
        stack.append(node)
        for nxt in adj.get(node, ()):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):]
                k = min(range(len(cyc)), key=lambda i: cyc[i])
                canon = tuple(cyc[k:] + cyc[:k])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    findings.append(canon)
            elif nxt not in visited:
                dfs(nxt, stack, on_stack, visited)
        stack.pop()
        on_stack.discard(node)

    visited: Set[str] = set()
    for node in sorted(adj):
        if node not in visited:
            dfs(node, [], set(), visited)

    for cyc in sorted(findings):
        hops = []
        for i, a in enumerate(cyc):
            b = cyc[(i + 1) % len(cyc)]
            path, line, col, note = edges[(a, b)]
            hops.append(f"{b} ({path}:{line}, {note})")
        first = edges[(cyc[0], cyc[1 % len(cyc)])]
        witness = " -> ".join([cyc[0]] + hops)
        yield (
            first[0], first[1], first[2],
            f"lock-order cycle (deadlock witness path): {witness}; "
            "acquire these locks in one global order")


def find_blocking_under_lock(index: PackageIndex):
    """Yields (path, line, col, message) for blocking calls under a
    held lock."""
    an = _analyzer(index)
    for qual in sorted(an.scans):
        scan = an.scans[qual]
        info = index.functions[qual]
        path = index.modules[info.module].path
        for label, lexical, line, col in scan.blocking:
            held = an.held_at(qual, lexical)
            if not held:
                continue
            locks = ", ".join(f"`{h}`" for h in sorted(held))
            yield (
                path, line, col,
                f"blocking call {label} while holding {locks}: every "
                "other thread needing the lock stalls behind this I/O "
                "(the serve-loop stall shape); move the blocking call "
                "outside the critical section")
        # waiting on a condition while holding ANOTHER lock is both a
        # stall and a re-acquire inversion; the lock-order pass reports
        # the cycle, this pass reports the stall
        for cond, lexical, line, col in scan.waits:
            others = an.held_at(qual, lexical) - {cond}
            if not others:
                continue
            locks = ", ".join(f"`{h}`" for h in sorted(others))
            yield (
                path, line, col,
                f"`{cond}.wait()` releases only its own condition; "
                f"still holding {locks} while blocked — every other "
                "holder of that lock stalls for the wakeup")
