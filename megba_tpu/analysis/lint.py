"""JAX-contract linter CLI: `python -m megba_tpu.analysis.lint <paths>`.

The analysis itself is standard-library only (ast; it never imports or
executes the code under lint): parses the given files/packages, builds
the jit-reachability call graph (analysis/callgraph.py) and runs the
repo-specific rules (analysis/rules.py).  Exit status: 0 clean,
1 findings, 2 usage/path error.

Findings print as `path:line:col: <rule> <message>`, one per line, so
editors and CI logs link straight to the site.  Suppress a single
finding with an inline `# megba: allow-<rule>` pragma on the flagged
line; mark engine functions only ever traced through a parameter with
`# megba: jit-entry` (see ARCHITECTURE.md "Analysis layer").
"""

from __future__ import annotations

import argparse
import sys
from typing import Iterable, List, Optional, Sequence

from megba_tpu.analysis.callgraph import PackageIndex, pragmas_on_line
from megba_tpu.analysis.rules import ALL_RULES, RULES, Finding


def lint_paths(paths: Iterable[str],
               rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the (selected) rules over `paths`; returns kept findings.

    Findings on lines carrying the matching `# megba: allow-<rule>`
    pragma are dropped here, so every caller — CLI, tests, CI — sees
    identical suppression semantics.
    """
    index = PackageIndex.build(paths)
    selected = list(rules) if rules else list(ALL_RULES)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(unknown)}; "
                         f"known: {', '.join(ALL_RULES)}")
    findings: List[Finding] = []
    lines_by_path = {m.path: m.source_lines for m in index.modules.values()}
    for rule in selected:
        for f in RULES[rule](index):
            allowed = pragmas_on_line(lines_by_path.get(f.path, []), f.line)
            if f"allow-{f.rule}" in allowed:
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def list_suppressions(paths: Iterable[str]):
    """Every inline `# megba: allow-<rule>` pragma under `paths`.

    Returns sorted (path, line, [allow-tokens], source-line) tuples —
    the audit trail of accumulated suppressions, so a pragma can never
    quietly outlive the code smell it excused.  Only well-formed
    `allow-<rule>` tokens count (a docstring's literal `allow-<rule>`
    placeholder captures as a bare "allow-" and is not a suppression).
    """
    import re

    well_formed = re.compile(r"allow-[A-Za-z0-9_][A-Za-z0-9_-]*$")
    index = PackageIndex.build(paths)
    out = []
    for mod in index.modules.values():
        for lineno in range(1, len(mod.source_lines) + 1):
            allows = sorted(
                t for t in pragmas_on_line(mod.source_lines, lineno)
                if well_formed.fullmatch(t))
            if allows:
                out.append((mod.path, lineno, allows,
                            mod.source_lines[lineno - 1].strip()))
    return sorted(out)


def run_lint(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m megba_tpu.analysis.lint",
        description="MegBA-TPU JAX-contract linter")
    parser.add_argument("paths", nargs="*",
                        help="package dirs or .py files to lint")
    parser.add_argument("--rule", action="append", dest="rules",
                        metavar="RULE",
                        help="run only this rule (repeatable)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule ids and exit")
    parser.add_argument("--list-suppressions", action="store_true",
                        help="print every inline `# megba: allow-<rule>` "
                             "pragma under the given paths with file:line")
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(r)
        return 0
    if not args.paths:
        parser.print_usage(sys.stderr)
        return 2
    if args.list_suppressions:
        try:
            found = list_suppressions(args.paths)
        except ValueError as exc:  # bad path: usage error, not traceback
            print(f"error: {exc}", file=sys.stderr)
            return 2
        for path, lineno, allows, source in found:
            print(f"{path}:{lineno}: {', '.join(allows)} | {source}")
        print(f"{len(found)} suppression(s)", file=sys.stderr)
        return 0
    try:
        findings = lint_paths(args.paths, rules=args.rules)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    for f in findings:
        print(f.format())
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(run_lint())
