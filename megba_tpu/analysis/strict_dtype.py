"""Strict-promotion sanitizer lane.

`python -m megba_tpu.analysis.strict_dtype` runs small end-to-end BA and
PGO solves with `jax_numpy_dtype_promotion='strict'` (every implicit
dtype promotion between non-weak types becomes a hard TypePromotionError
at trace time) and `jax_debug_nans=True` (any NaN surfacing from a
jitted computation raises instead of propagating).  This is the dynamic
complement of the AST linter: the linter catches the *patterns* that
cause weak-type/promotion bugs, this lane proves the real solve
pipelines trace clean under the strictest dtype discipline JAX offers.

Wired into scripts/lint.sh (and through it scripts/run_tests.sh), so
tier-1 cannot pass with a promotion regression.  Exit 0 on success.
"""

from __future__ import annotations

import contextlib
import sys


@contextlib.contextmanager
def strict_promotion(debug_nans: bool = True):
    """Temporarily enable strict dtype promotion (+ NaN checking)."""
    import jax

    old_promo = jax.config.jax_numpy_dtype_promotion
    old_nans = jax.config.jax_debug_nans
    jax.config.update("jax_numpy_dtype_promotion", "strict")
    jax.config.update("jax_debug_nans", debug_nans)
    try:
        yield
    finally:
        jax.config.update("jax_numpy_dtype_promotion", old_promo)
        jax.config.update("jax_debug_nans", old_nans)


def run_ba_smoke(dtype=None, world_size: int = 1):
    """One tiny BA solve under the sanitizer; returns the LMResult."""
    import numpy as np

    from megba_tpu.common import (
        AlgoOption, JacobianMode, ProblemOption, SolverOption)
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    dtype = np.float32 if dtype is None else dtype
    s = make_synthetic_bal(num_cameras=4, num_points=24, obs_per_point=3,
                           seed=0, param_noise=4e-2, pixel_noise=0.3,
                           dtype=dtype)
    option = ProblemOption(
        dtype=dtype, world_size=world_size,
        algo_option=AlgoOption(max_iter=4),
        solver_option=SolverOption(max_iter=10, tol=1e-8))
    res = flat_solve(
        make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF),
        s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option)
    _check_decrease("ba", res.initial_cost, res.cost, res.iterations)
    return res


def run_pgo_smoke(dtype=None):
    """One tiny pose-graph solve under the sanitizer."""
    import numpy as np

    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.models.pgo import make_synthetic_pose_graph, solve_pgo

    dtype = np.float32 if dtype is None else dtype
    g = make_synthetic_pose_graph(num_poses=12, seed=0)
    option = ProblemOption(
        dtype=dtype,
        algo_option=AlgoOption(max_iter=4),
        solver_option=SolverOption(max_iter=10, tol=1e-8))
    res = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, option)
    _check_decrease("pgo", res.initial_cost, res.cost, res.iterations)
    return res


def _check_decrease(label, cost0, cost, iters) -> None:
    import numpy as np

    c0, c1 = float(cost0), float(cost)
    if not (np.isfinite(c0) and np.isfinite(c1)):
        raise AssertionError(f"[{label}] non-finite cost: {c0} -> {c1}")
    if not c1 <= c0:
        raise AssertionError(f"[{label}] cost did not decrease: "
                             f"{c0:.6e} -> {c1:.6e}")
    print(f"[strict-dtype] {label}: {c0:.6e} -> {c1:.6e} "
          f"in {int(iters)} iters OK", flush=True)


def main(argv=None) -> int:
    import numpy as np

    import jax

    dtypes = [np.float32]
    if jax.config.jax_enable_x64:
        dtypes.append(np.float64)
    with strict_promotion():
        for dt in dtypes:
            run_ba_smoke(dtype=dt)
        run_pgo_smoke()
    print("strict-dtype sanitizer lane OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
