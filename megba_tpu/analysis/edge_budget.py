"""Analytical per-CG-step compute/traffic model of the edge pipeline.

The budget gate's `flops` / `bytes_accessed` axes are XLA's cost model:
they move whenever the compiler fuses differently, which is why they get
a 15% band.  This module is the other kind of axis — a DECLARED
structural contract, priced from the problem geometry, the edge-stream
plan, and the dtype surface, with zero compiler in the loop:

- ``flops_per_sp``    — useful floating-point work one device performs
  per S·p product (one PCG iteration's matvec), MAC = 2 flops.
- ``bytes_touched_per_sp`` — HBM bytes one device streams per S·p
  through the coupling pipeline: coupling-row reads, Krylov
  gather/scatter traffic, block-diagonal reads, and — on the unfused
  lowerings — the per-edge transient round-trips (gathered operand
  tiles, the intermediate u rows, the pre-reduction products) that the
  fused Pallas kernels (ops/fused.py) keep VMEM-resident.

Both are exact-gated (tolerance 0.0 in budget.TOLERANCES): the same
pure function prices the axis at ``--update`` and ``--check`` time, so
the committed number pins the INPUTS — edge-stream length (padding
included: padded slots ride the MXU too), block dims, compute kind,
operand dtype.  A plan change, a quantum bump, or a dtype-surface edit
shows up as an exact-match failure naming the program.  The fused-
kernel option's whole bytes story is the ``transient_roundtrips=False``
arm: tests pin that fused pricing is strictly below unfused on the
same geometry, and the canonical (fused-off) baselines stay priced on
the unfused arm.

Model assumptions, stated so the numbers are auditable:

- Per-DEVICE accounting, matching ``collective_bytes_per_sp``: the 1-D
  sharded lowerings replicate the parameter blocks, so the block-
  diagonal applies are counted at full Nc/Np on every device while the
  edge stream is the per-device shard.
- IMPLICIT compute kind (the SolverOption default every canonical
  program lowers with): per edge and direction the coupling does
  rd·(cd+pd) MACs through the intermediate u = J_in·p rows.  EXPLICIT
  W-based programs price cd·pd MACs per edge per direction.
- Transients are priced as one write + one read (round-trip) of each
  per-edge intermediate at accumulator width; the fused kernels'
  pricing drops exactly this term and nothing else.

All stdlib + dataclasses, no jax, no numpy: importable by the audit CLI
and the cripple-mode tests without touching a backend.
"""

from __future__ import annotations

from typing import Dict

# Operand storage widths the pricing understands (bytes per element).
OPERAND_BYTES: Dict[str, int] = {"bf16": 2, "f32": 4, "f64": 8}


def coupling_rows_per_edge(cd: int, pd: int, rd: int,
                           explicit: bool = False) -> int:
    """Stored coupling-row elements per edge slot (one direction's
    operand stream): the W block (explicit) or the Jc+Jp row pair
    (implicit) — the elements a matvec direction must read per slot."""
    return cd * pd if explicit else rd * (cd + pd)


def coupling_macs_per_edge(cd: int, pd: int, rd: int,
                           explicit: bool = False) -> int:
    """MACs per edge slot per direction: W·p (explicit) or the two-stage
    J_outᵀ(J_in·p) contraction (implicit)."""
    return cd * pd if explicit else rd * (cd + pd)


def schur_sp_budget(num_cameras: int, cd: int, num_points: int, pd: int,
                    rd: int, edge_slots: int, *,
                    explicit: bool = False,
                    operand: str = "f32",
                    param: str = "f32",
                    acc: str = "f32",
                    transient_roundtrips: bool = True,
                    lanes: int = 1) -> Dict[str, float]:
    """Per-device, per-S·p budget of the Schur-complement matvec
    S·p = Hpp·p − Hpl·Hll⁻¹·Hlp·p on one edge-stream shard.

    ``edge_slots`` is the PADDED per-device edge-stream length (quantum
    padding / tile-plan slots included — padding slots do the same MXU
    work and move the same bytes as real edges).  ``operand`` prices
    the coupling rows (bf16 under the mixed-precision rung), ``param``
    the parameter-space vectors and block diagonals, ``acc`` the
    transient intermediates.  ``transient_roundtrips=False`` is the
    fused-kernel arm: gather→contract→scatter stays VMEM-resident, so
    the per-edge intermediates never touch HBM.  ``lanes`` scales
    everything for the vmapped batched program.
    """
    ob = OPERAND_BYTES[operand]
    pb = OPERAND_BYTES[param]
    ab = OPERAND_BYTES[acc]
    macs = coupling_macs_per_edge(cd, pd, rd, explicit)
    rows = coupling_rows_per_edge(cd, pd, rd, explicit)
    # Two coupling traversals per S·p (hlp: cam→pt, hpl: pt→cam), plus
    # the camera block-diagonal apply and the point-block Hll⁻¹ apply.
    flops = 2.0 * (num_cameras * cd * cd
                   + num_points * pd * pd
                   + 2 * edge_slots * macs)
    # Per-direction traffic: coupling rows read once; gather source and
    # scatter destination vectors touched once each at param width.
    vec_elems = num_cameras * cd + num_points * pd
    bytes_touched = 2.0 * (edge_slots * rows * ob + vec_elems * pb)
    # Block diagonals read once per apply (Hpp blocks + Hll⁻¹ blocks).
    bytes_touched += (num_cameras * cd * cd + num_points * pd * pd) * pb
    if transient_roundtrips:
        # Unfused lowerings round-trip the per-edge intermediates:
        # gathered input tiles [d_in, E], the u rows [rd, E] (implicit
        # only), and the pre-reduction products [d_out, E] — write +
        # read each, both directions.  This is the exact term the
        # fused kernels delete.
        per_dir = cd + pd + (0 if explicit else rd)
        bytes_touched += 2.0 * 2 * edge_slots * per_dir * ab
    return {"flops_per_sp": float(flops) * lanes,
            "bytes_touched_per_sp": float(bytes_touched) * lanes}


def pgo_sp_budget(num_poses: int, pose_dim: int, rd: int,
                  edge_slots: int, *,
                  param: str = "f64") -> Dict[str, float]:
    """Per-device, per-H·x budget of PGO's matrix-free Gauss-Newton
    matvec: one edge-stream traversal computing Jᵀ(J·x) through the
    rd-row residual blocks (both endpoint Jacobians, pose_dim each),
    plus the block-Jacobi diagonal apply.  One traversal — PGO's body
    has a single reduction site, not the Schur pair."""
    pb = OPERAND_BYTES[param]
    macs = rd * (2 * pose_dim)  # J·x per edge; same again for Jᵀu
    flops = 2.0 * (num_poses * pose_dim * pose_dim
                   + 2 * edge_slots * macs)
    rows = rd * 2 * pose_dim  # stored endpoint Jacobian pair per edge
    vec_elems = num_poses * pose_dim
    bytes_touched = (edge_slots * rows * pb + 2.0 * vec_elems * pb
                     + num_poses * pose_dim * pose_dim * pb)
    # Transient round-trips (gathered endpoint pair, u rows, products):
    # PGO has no fused lowering, so the term is unconditional.
    bytes_touched += 2.0 * edge_slots * (2 * pose_dim + rd + 2 * pose_dim) * pb
    return {"flops_per_sp": float(flops),
            "bytes_touched_per_sp": float(bytes_touched)}
