"""Runtime retrace sentinel: count jit traces per (site, signature).

Every `jax.jit` cache miss re-invokes the wrapped Python callable to
trace it (and each trace is followed by an XLA compile), so counting
executions of the Python function body counts compilations exactly —
no private jax APIs, no monitoring hooks, zero cost once compiled.

The solver's jitted entry points (solve.py `_build_single_solve`,
parallel/mesh.py `_build_sharded_solve`, models/pgo.py `_pgo_program`)
wrap their to-be-jitted functions with `traced(site, fn, static=...)`;
the inner hot functions (algo/lm.py `lm_solve`, solver/pcg.py solves)
call `note_trace(site, args...)` directly — they only ever execute at
trace time, so the counter increments exactly once per compilation.

`sentinel()` wraps a window (a test, a benchmark phase) and fails it on:

- a *duplicate* trace: the same (site, static config, operand signature)
  traced a second time — a jit cache bust (typically a program rebuilt
  around a fresh closure per call, the classic silent-retrace bug);
- more new compilations than `max_compiles` allows (shape-unstable call
  patterns: every call a new signature, every call a compile).

The pytest fixture `retrace_sentinel` (tests/conftest.py) exposes this
per test: request it and the test fails on any unexpected recompile.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

_LOCK = threading.Lock()
# (site, static, signature) -> trace count, process lifetime
_COUNTS: Dict[Tuple[str, str, str], int] = {}


class RetraceError(AssertionError):
    """An unexpected jit retrace (cache bust or shape instability)."""


def _describe(x) -> str:
    """Stable abstract-value description of one operand (shape/dtype,
    never values — tracers have no values at trace time)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None and dtype is None:
        if x is None or isinstance(x, (bool, int, float, str)):
            return repr(x)
        return type(x).__name__
    return f"{dtype}{list(shape) if shape is not None else ''}"


def signature_of(args, kwargs=None) -> str:
    import jax

    leaves = jax.tree_util.tree_leaves((args, kwargs or {}))
    return ",".join(_describe(leaf) for leaf in leaves)


def note_trace(site: str, *args, static: str = "",
               force: bool = False) -> None:
    """Record one trace of `site` with the given operands.

    Counts ONLY while jax is actually tracing: the instrumented solver
    layers (lm_solve, the PCG solves) are also supported as plain eager
    calls, and an eager execution is not a compilation — without this
    guard two identical eager calls would read as a duplicate-signature
    cache bust.  `force=True` bypasses the guard (tests exercising the
    sentinel machinery without a real trace).
    """
    if not force:
        import jax

        try:
            if jax.core.trace_state_clean():
                return  # eager execution, not a compilation
        except AttributeError:  # API moved; fail open (count anyway)
            pass
    key = (site, static, signature_of(args, {}))
    with _LOCK:
        _COUNTS[key] = _COUNTS.get(key, 0) + 1


def static_key(*parts) -> str:
    """Compact stable string for a jit program's static configuration.

    Callables contribute their qualname (NOT their identity): two
    closures of the same factory with identical config and operand
    signature produce the SAME key, so a program needlessly rebuilt
    around a fresh closure per call shows up as a duplicate trace —
    the classic silent-retrace bug this sentinel exists to catch.
    """
    out = []
    for p in parts:
        if callable(p):
            out.append(getattr(p, "__qualname__", None)
                       or type(p).__name__)
        else:
            out.append(repr(p))
    return "|".join(out)


def traced(site: str, fn, static: str = ""):
    """Wrap a to-be-jitted callable so every trace is counted.

    The wrapper is transparent to jit (plain *args/**kwargs passthrough,
    donate_argnums keeps working positionally) and adds zero runtime
    cost: it only executes on cache miss.
    """

    def wrapper(*args, **kwargs):
        note_trace(site, *args, *kwargs.values(), static=static)
        return fn(*args, **kwargs)

    wrapper.__name__ = getattr(fn, "__name__", "fn")
    wrapper.__qualname__ = getattr(fn, "__qualname__", wrapper.__name__)
    return wrapper


def snapshot() -> Dict[Tuple[str, str, str], int]:
    with _LOCK:
        return dict(_COUNTS)


class RetraceSentinel:
    """Context manager guarding a window against unexpected recompiles."""

    def __init__(self, max_compiles: Optional[int] = None) -> None:
        self.max_compiles = max_compiles
        self._allowed_duplicates = 0
        self._allowed_extra = 0
        self._base: Dict[Tuple[str, str, str], int] = {}

    # -- in-window adjustments -----------------------------------------
    def allow(self, duplicates: int = 0, extra_compiles: int = 0) -> None:
        """Raise the window's tolerance (e.g. a test that legitimately
        rebuilds an identical program around a fresh per-problem
        closure)."""
        self._allowed_duplicates += duplicates
        self._allowed_extra += extra_compiles

    # -- observations --------------------------------------------------
    def new_compiles(self) -> Dict[Tuple[str, str, str], int]:
        """(site, static, signature) -> traces since the window opened."""
        now = snapshot()
        return {k: v - self._base.get(k, 0)
                for k, v in now.items() if v > self._base.get(k, 0)}

    def total_new(self) -> int:
        return sum(self.new_compiles().values())

    def duplicates(self):
        """Signatures traced more than once within the window, or traced
        in the window after already being compiled before it."""
        out = []
        for key, delta in self.new_compiles().items():
            before = self._base.get(key, 0)
            if delta + min(before, 1) > 1:
                out.append((key, delta))
        return out

    # -- context protocol ----------------------------------------------
    def __enter__(self) -> "RetraceSentinel":
        self._base = snapshot()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            return  # don't mask the real failure
        self.check()

    def check(self) -> None:
        dups = self.duplicates()
        if len(dups) > self._allowed_duplicates:
            lines = "\n".join(
                f"  {site} [{static or 'no static'}] sig={sig} "
                f"traced +{delta}x"
                for (site, static, sig), delta in dups)
            raise RetraceError(
                "unexpected jit retrace — identical (site, config, "
                "signature) compiled more than once (cache bust; is a "
                "program being rebuilt around a fresh closure per call?):\n"
                + lines)
        total = self.total_new()
        budget = (None if self.max_compiles is None
                  else self.max_compiles + self._allowed_extra)
        if budget is not None and total > budget:
            lines = "\n".join(
                f"  {site} [{static or 'no static'}] sig={sig} x{delta}"
                for (site, static, sig), delta in
                sorted(self.new_compiles().items()))
            raise RetraceError(
                f"{total} compilation(s) in a window budgeted for "
                f"{budget} — shape-unstable call pattern? new traces:\n"
                + lines)


def sentinel(max_compiles: Optional[int] = None) -> RetraceSentinel:
    """`with sentinel(max_compiles=1): ...` — see RetraceSentinel."""
    return RetraceSentinel(max_compiles=max_compiles)
