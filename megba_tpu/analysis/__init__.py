"""Static + runtime correctness tooling for the jitted solver contract.

MegBA's value proposition is that every hot path stays inside one fused
device program; nothing about that is enforced by the language.  This
package is the enforcement layer:

- `analysis.lint` — zero-dependency AST linter with repo-specific rules
  (host callbacks confined to the observability layer, no host numpy /
  Python coercions reachable from a jitted entry point, explicit dtypes
  on jnp constructors, no strongly-typed scalar promotion, donated
  buffers never reused).  `python -m megba_tpu.analysis.lint megba_tpu/`.
- `analysis.retrace` — runtime retrace sentinel: counts jit traces per
  (site, signature) at the solver entry points and fails tests that
  trigger unexpected recompiles.
- `analysis.strict_dtype` — the sanitizer lane: a small end-to-end solve
  under `jax_numpy_dtype_promotion=strict` + `jax_debug_nans`.
- `analysis.program_audit` (+ `hlo`, `budget`) — the compiled-program
  auditor: AOT-lowers the canonical solver programs and audits the
  StableHLO / optimized HLO for host transfers, the per-PCG-iteration
  collective pattern, dtype leaks and materialised donation, plus an
  AOT FLOP/byte budget gate against the committed ANALYSIS_BUDGET.json.
  CLI: `python -m megba_tpu.analysis.audit --check` / `--update`.

Suppress a single lint finding with an inline `# megba: allow-<rule>`
pragma on the flagged line; mark a function that is only ever called
from inside a jitted computation (so the call graph cannot see it) with
`# megba: jit-entry` on its `def` line.  See ARCHITECTURE.md "Analysis
layer".

Submodules are loaded lazily: `python -m megba_tpu.analysis.lint` must
not re-import the module it is executing (runpy warns), and the solver's
retrace hooks must not drag the linter in on the production import path.
"""

_EXPORTS = {
    "lint_paths": "lint", "run_lint": "lint",
    "RetraceError": "retrace", "RetraceSentinel": "retrace",
    "note_trace": "retrace", "sentinel": "retrace", "traced": "retrace",
    "strict_promotion": "strict_dtype",
    "ProgramAudit": "program_audit", "ProgramSpec": "program_audit",
    "audit_all": "program_audit", "audit_program": "program_audit",
    "program_specs": "program_audit",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(
            f"megba_tpu.analysis.{_EXPORTS[name]}")
        return getattr(mod, name)
    raise AttributeError(f"module 'megba_tpu.analysis' has no attribute "
                         f"{name!r}")
