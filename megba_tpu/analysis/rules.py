"""The lint rules.  Each rule is a generator over a PackageIndex.

Rule ids are kebab-case; suppress one finding with an inline
`# megba: allow-<rule>` pragma on the flagged physical line.

| id | contract it enforces |
|---|---|
| host-callback | `jax.debug.callback` / `jax.debug.print` / `io_callback` / `pure_callback` only inside the designated host-interop modules (observability/, utils/debug.py) — anywhere else a callback silently punches a host round-trip into the fused device program |
| np-in-jit | no `np.*` calls, `float(...)` or `.item()` coercions in functions reachable from a jitted entry point — each is either a trace-time constant bake (silent retrace per value) or a ConcretizationError waiting for the first non-static input |
| implicit-dtype | `jnp.zeros/ones/empty/full/arange/eye/linspace/identity` must state a dtype (keyword or the documented positional slot); `jnp.array`/`jnp.asarray` of pure Python literals too — the f32 default silently breaks the f64/f32 parity evidence (DOUBLE_PARITY.json) |
| scalar-promotion | no strongly-typed scalar constructors (`np.float64(x)`, `jnp.int32(k)`, ...) as operands of array arithmetic in jit-reachable code — unlike weak Python scalars they promote the whole expression's dtype |
| donated-reuse | an argument passed at a `donate_argnums` position of a locally-built `jax.jit` program must not be read after the call — the buffer is deleted by the call |
| weak-literal | no BARE float literal as a `jnp.where` branch or `jnp.clip` bound in jit-reachable code — probed on this jaxlib: under x64 those positions materialise a `tensor<f64>` constant (plus a convert) in f32 programs, the dtype-census leak hand-fixed in PRs 3 and 6 (`jnp.where(safe, θ², 1.0)`, `jnp.where(..., 0.0, ...)`); use `zeros_like`/`ones_like`/`jnp.asarray(c, x.dtype)`.  Plain arithmetic (`2.0 * x`) and `jnp.maximum/minimum` literals promote weakly and are clean — the rule matches only the probed leaky positions |
| raw-clock | no raw `time.time()` / `time.perf_counter()` outside the sanctioned clock homes (`utils/timing.py`, `observability/`) — scattered raw reads fragment the timing story the observability plane narrates (PhaseTimer phases, span timestamps, report `created_unix` all flow from ONE seam); use `utils.timing.monotonic_s()` for durations and `utils.timing.wall_unix()` for epoch stamps.  `time.monotonic()` deadline arithmetic and `time.sleep` are clean — the rule bans the two reads that LOOK interchangeable but are not.  STRICT lane (`serving/transport.py`, `robustness/netfaults.py`): `time.monotonic()` is banned there too — transport deadlines ride `monotonic_s()` exclusively, and a second monotonic epoch would be compared against it |
| guarded-by | shared mutable attributes of lock-owning classes, declared with `# megba: guarded-by(<lockattr>)` on the assignment (or inferred at >= 80% locked accesses in thread-reachable classes), must not be read/written outside a `with <lock>` block — the host serving tier's race detector (analysis/concurrency.py); `# megba: allow-unguarded` is the per-line escape hatch |
| lock-order | the package-wide acquires-while-holding digraph (nested `with` blocks, cross-method/cross-class edges through the callgraph, `Condition.wait` re-acquires) must be acyclic — a cycle is a deadlock waiting for the right interleaving; the finding prints the witness path |
| stale-program | every option field READ on the lowering closure (flat_solve / distributed_lm_solve / batched_solve_program / lower_bucket / solve_pgo and everything they reach) must be visible to the program's static key — a strip-listed or key-exempt-declared field read under tracing is a wrong-program hazard, and a builder whose `static_key(...)` omits its option parameter hides every field (analysis/identity.py); consume-and-strip in the same function is the sanctioned shape |
| cache-split | an option field that reaches the key surfaces (static_key reprs the whole frozen option; artifact fingerprints, warm manifests and bucket keys follow) but is never lowering-read and is not on the observability strip-list silently fragments every cache — declare intent with a field-scoped lowering-relevant pragma (program-family selectors) or key-exempt pragma (true host-only knobs) on the declaration line |
| key-surface-drift | the strip-list is ONE registry (common.OBSERVABILITY_FIELDS): partial strips, non-conforming strip helpers, hardcoded membership tuples that disagree with it, un-stripped memoised-cache fronts, contradictory/unknown-field pragmas, and operand-declared values branched on in Python inside traced code (operand-as-static; `is None` presence checks sanctioned) all drift a key surface away from the contract |
| blocking-under-lock | no call from the curated blocking set (`Future.result`, `queue.get`/`join`, socket/pipe `recv*`, `subprocess`-style `.wait`, `time.sleep` above 0.05 s, the RPC `_recv_frame`) while any lock is held — the classic serve-loop stall shape; waiting on a HELD Condition is the sanctioned exception (it releases the lock) |
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from megba_tpu.analysis import concurrency
from megba_tpu.analysis.callgraph import (
    FunctionInfo,
    ModuleInfo,
    PackageIndex,
    _dotted,
)

# Modules allowed to host callbacks / host coercions: the designated
# host-interop layer.  Matched on dotted module-name suffixes so the
# linter works from any invocation directory.
HOST_INTEROP_MODULES = (
    "observability",
    "utils.debug",
)

_CALLBACK_TAILS = {"io_callback", "pure_callback"}
_CALLBACK_DOTTED_TAILS = ("debug.callback", "debug.print")

_NUMPY_HEADS = {"numpy"}
_JNP_HEADS = {"jax.numpy"}

# constructor name -> positional index where dtype may legally appear
# (None: keyword-only in practice for this repo's call shapes)
_DTYPE_SLOT = {
    "zeros": 1, "ones": 1, "empty": 1, "array": 1, "asarray": 1,
    "full": 2, "arange": 3, "eye": 3, "identity": 1, "linspace": None,
}

_SCALAR_CTORS = {
    "float16", "float32", "float64", "bfloat16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool_",
}

ALL_RULES = (
    "host-callback",
    "np-in-jit",
    "implicit-dtype",
    "scalar-promotion",
    "donated-reuse",
    "weak-literal",
    "raw-clock",
    "guarded-by",
    "lock-order",
    "blocking-under-lock",
    "stale-program",
    "cache-split",
    "key-surface-drift",
)

# Fully-resolved call targets the raw-clock rule bans (time.monotonic,
# time.sleep etc. stay legal — only the two reads that masquerade as
# each other are fenced into the clock homes).
_RAW_CLOCK_TARGETS = {"time.time", "time.perf_counter"}

# Modules on the STRICT clock lane: deadline arithmetic here rides
# `utils.timing.monotonic_s` exclusively, so even `time.monotonic()` is
# banned — a second monotonic epoch in the transport/chaos layer would
# let a deadline computed on one clock be compared against the other
# (they share no epoch, only a rate).
_STRICT_CLOCK_MODULES = ("serving.transport", "robustness.netfaults")
_STRICT_CLOCK_TARGETS = _RAW_CLOCK_TARGETS | {"time.monotonic"}


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


def _is_host_interop(mod: ModuleInfo) -> bool:
    parts = mod.name.split(".")
    return "observability" in parts or mod.name.endswith("utils.debug")


def _alias_target(mod: ModuleInfo, dotted: Optional[str]) -> Optional[str]:
    """Resolve the head alias of a dotted chain through the module's
    imports: "np.zeros" -> "numpy.zeros", "jnp.array" -> "jax.numpy.array"."""
    if dotted is None:
        return None
    head, *rest = dotted.split(".")
    target = mod.imports.get(head, head)
    return ".".join([target] + rest)


def _own_nodes(info: FunctionInfo) -> Iterator[ast.AST]:
    """Walk a function's own body, not descending into nested defs
    (those are indexed and checked as functions in their own right)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(info.node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# --------------------------------------------------------------- rules

def rule_host_callback(index: PackageIndex) -> Iterator[Finding]:
    for mod in index.modules.values():
        if _is_host_interop(mod):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted is None:
                continue
            full = _alias_target(mod, dotted)
            tail = dotted.split(".")[-1]
            hit = tail in _CALLBACK_TAILS or any(
                dotted.endswith(t) or (full or "").endswith(t)
                for t in _CALLBACK_DOTTED_TAILS)
            if hit:
                yield Finding(
                    mod.path, node.lineno, node.col_offset, "host-callback",
                    f"`{dotted}` outside the host-interop layer "
                    "(observability/, utils/debug.py): callbacks break the "
                    "single-fused-program contract; route host output "
                    "through observability/emit.py")


def rule_np_in_jit(index: PackageIndex) -> Iterator[Finding]:
    for qual in sorted(index.reachable):
        info = index.functions[qual]
        mod = index.modules[info.module]
        if _is_host_interop(mod):
            continue
        for node in _own_nodes(info):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            full = _alias_target(mod, dotted)
            if full is not None and full.split(".")[0] in _NUMPY_HEADS:
                yield Finding(
                    mod.path, node.lineno, node.col_offset, "np-in-jit",
                    f"host numpy call `{dotted}` inside jit-reachable "
                    f"`{qual.split('.')[-1]}`: it runs at trace time and "
                    "bakes a constant (or retraces per value); use jnp")
            elif (isinstance(node.func, ast.Name)
                  and node.func.id == "float" and node.args):
                yield Finding(
                    mod.path, node.lineno, node.col_offset, "np-in-jit",
                    "`float(...)` inside jit-reachable "
                    f"`{qual.split('.')[-1]}`: concretizes a traced value "
                    "(ConcretizationError on non-static input)")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "item" and not node.args):
                yield Finding(
                    mod.path, node.lineno, node.col_offset, "np-in-jit",
                    "`.item()` inside jit-reachable "
                    f"`{qual.split('.')[-1]}`: host sync/concretization in "
                    "traced code")


def _literal_only(node: ast.AST) -> bool:
    """True when the expression tree is pure Python literals (the cases
    where jnp.array has no operand dtype to inherit)."""
    if isinstance(node, ast.Constant):
        return not isinstance(node.value, (str, bytes, type(None)))
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_literal_only(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp) and isinstance(
            node.op, (ast.USub, ast.UAdd)):
        return _literal_only(node.operand)
    return False


def rule_implicit_dtype(index: PackageIndex) -> Iterator[Finding]:
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            full = _alias_target(mod, dotted)
            if full is None:
                continue
            head, _, tail = full.rpartition(".")
            if head not in _JNP_HEADS or tail not in _DTYPE_SLOT:
                continue
            if any(kw.arg == "dtype" for kw in node.keywords):
                continue
            slot = _DTYPE_SLOT[tail]
            if slot is not None and len(node.args) > slot:
                continue  # positional dtype present
            if tail in ("array", "asarray"):
                if not (node.args and _literal_only(node.args[0])):
                    continue  # inherits dtype from its operands
            yield Finding(
                mod.path, node.lineno, node.col_offset, "implicit-dtype",
                f"`jnp.{tail}` without an explicit dtype defaults to "
                "float32/weak: state the dtype (problem dtype, operand "
                ".dtype, or jnp.int32 for indices) so f64 runs stay f64")


def rule_scalar_promotion(index: PackageIndex) -> Iterator[Finding]:
    for qual in sorted(index.reachable):
        info = index.functions[qual]
        mod = index.modules[info.module]
        for node in _own_nodes(info):
            if not isinstance(node, ast.BinOp):
                continue
            for side in (node.left, node.right):
                if not isinstance(side, ast.Call):
                    continue
                full = _alias_target(mod, _dotted(side.func)) or ""
                head, _, tail = full.rpartition(".")
                if (head in _NUMPY_HEADS | _JNP_HEADS
                        and tail in _SCALAR_CTORS):
                    yield Finding(
                        mod.path, node.lineno, node.col_offset,
                        "scalar-promotion",
                        f"strongly-typed scalar `{_dotted(side.func)}` in "
                        "array arithmetic promotes the whole expression's "
                        "dtype (weak Python scalars would not); cast with "
                        "jnp.asarray(x, arr.dtype) instead")


def _float_literal(node: ast.AST) -> bool:
    """A bare Python float literal (optionally signed) — the weak
    scalar that materialises as a wide constant in the leaky call
    positions."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, float)
    if isinstance(node, ast.UnaryOp) and isinstance(
            node.op, (ast.USub, ast.UAdd)):
        return _float_literal(node.operand)
    return False


# call tail -> the positional argument slots whose bare float literals
# leak (jnp.where branches; jnp.clip bounds) + the keyword spellings of
# the same slots.
_WEAK_LITERAL_SLOTS = {
    "where": ((1, 2), ("x", "y")),
    "clip": ((1, 2), ("a_min", "a_max", "min", "max")),
}


def rule_weak_literal(index: PackageIndex) -> Iterator[Finding]:
    # ALL functions, not just the jit-reachable set: the leak class was
    # found in the ANALYTICAL Jacobian chain (ops/geo.py), which is
    # jitted through an engine reference the call graph cannot follow —
    # exactly the blind spot that let it survive PR 3's census fixes.
    for qual, info in sorted(index.functions.items()):
        mod = index.modules[info.module]
        for node in _own_nodes(info):
            if not isinstance(node, ast.Call):
                continue
            full = _alias_target(mod, _dotted(node.func)) or ""
            head, _, tail = full.rpartition(".")
            if head not in _JNP_HEADS or tail not in _WEAK_LITERAL_SLOTS:
                continue
            slots, kwnames = _WEAK_LITERAL_SLOTS[tail]
            hits = [node.args[p] for p in slots if p < len(node.args)
                    and _float_literal(node.args[p])]
            hits += [kw.value for kw in node.keywords
                     if kw.arg in kwnames and _float_literal(kw.value)]
            for h in hits:
                yield Finding(
                    mod.path, h.lineno, h.col_offset, "weak-literal",
                    f"bare float literal as a `jnp.{tail}` "
                    f"{'branch' if tail == 'where' else 'bound'} "
                    "materialises a wide (f64-under-x64) constant "
                    "tensor in f32 programs (dtype-census leak); use "
                    "zeros_like/ones_like or jnp.asarray(c, x.dtype)")


def _is_clock_home(mod: ModuleInfo) -> bool:
    parts = mod.name.split(".")
    return "observability" in parts or mod.name.endswith("utils.timing")


def rule_raw_clock(index: PackageIndex) -> Iterator[Finding]:
    for mod in index.modules.values():
        if _is_clock_home(mod):
            continue
        strict = mod.name.endswith(_STRICT_CLOCK_MODULES)
        targets = _STRICT_CLOCK_TARGETS if strict else _RAW_CLOCK_TARGETS
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            full = _alias_target(mod, dotted)
            if full in targets:
                helper = ("wall_unix()" if full == "time.time"
                          else "monotonic_s()")
                if strict and full == "time.monotonic":
                    yield Finding(
                        mod.path, node.lineno, node.col_offset,
                        "raw-clock",
                        f"raw `{dotted}()` in a strict-clock module "
                        "(transport/netfaults deadline arithmetic): use "
                        "megba_tpu.utils.timing.monotonic_s() — a "
                        "second monotonic epoch here would be compared "
                        "against monotonic_s() deadlines")
                else:
                    yield Finding(
                        mod.path, node.lineno, node.col_offset,
                        "raw-clock",
                        f"raw `{dotted}()` outside the clock homes "
                        "(utils/timing.py, observability/): use "
                        f"megba_tpu.utils.timing.{helper} so durations "
                        "and epoch stamps flow from one seam")


def rule_donated_reuse(index: PackageIndex) -> Iterator[Finding]:
    for qual, info in sorted(index.functions.items()):
        mod = index.modules[info.module]
        yield from _donated_reuse_in(mod, info)


def _donated_reuse_in(mod: ModuleInfo,
                      info: FunctionInfo) -> Iterator[Finding]:
    donated_fns: Dict[str, Tuple[int, ...]] = {}
    # (var name tainted, donating call first line, call last line)
    taints: List[Tuple[str, int, int]] = []

    nodes = sorted(
        (n for n in _own_nodes(info)),
        key=lambda n: (getattr(n, "lineno", 0), getattr(n, "col_offset", 0)))

    for node in nodes:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            dotted = _dotted(node.value.func) or ""
            if dotted.split(".")[-1] == "jit":
                positions = _donate_positions(node.value)
                if positions:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            donated_fns[tgt.id] = positions
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            positions = donated_fns.get(node.func.id)
            if positions:
                for p in positions:
                    if p < len(node.args) and isinstance(
                            node.args[p], ast.Name):
                        # Taint from the call's LAST line: a wrapped
                        # call's own arguments on continuation lines are
                        # not reads-after-donation.
                        taints.append((
                            node.args[p].id, node.lineno,
                            getattr(node, "end_lineno", node.lineno)
                            or node.lineno))

    if not taints:
        return
    # Any Load of a tainted name strictly after its donating call (and
    # before a rebinding Store) is a use of a deleted buffer.
    events: Dict[str, List[Tuple[int, int, str, ast.AST]]] = {}
    for node in _own_nodes(info):
        if isinstance(node, ast.Name):
            kind = "load" if isinstance(node.ctx, ast.Load) else "store"
            events.setdefault(node.id, []).append(
                (node.lineno, node.col_offset, kind, node))
    for name, call_line, call_end in taints:
        for lineno, col, kind, node in sorted(events.get(name, [])):
            if lineno < call_line:
                continue
            if lineno <= call_end:
                if kind == "store":
                    break  # `x = prog(x, ...)`: rebound to the result
                continue  # the donating call's own argument load
            if kind == "store":
                break  # rebound: taint ends
            yield Finding(
                mod.path, lineno, col, "donated-reuse",
                f"`{name}` was donated to a jitted call on line "
                f"{call_line} (its device buffer is deleted by the call); "
                "reading it afterwards raises 'Array has been deleted'")
            break  # one finding per taint is enough


def _donate_positions(call: ast.Call) -> Tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(
                            e.value, int):
                        out.append(e.value)
                return tuple(out)
    return ()


# ------------------------------------------------ concurrency rules
# The analysis lives in analysis/concurrency.py (it yields plain
# (path, line, col, message) tuples so it never needs this module);
# these wrappers stamp the rule ids.


def rule_guarded_by(index: PackageIndex) -> Iterator[Finding]:
    for path, line, col, msg in concurrency.find_guarded_by(index):
        yield Finding(path, line, col, "guarded-by", msg)


def rule_lock_order(index: PackageIndex) -> Iterator[Finding]:
    for path, line, col, msg in concurrency.find_lock_order(index):
        yield Finding(path, line, col, "lock-order", msg)


def rule_blocking_under_lock(index: PackageIndex) -> Iterator[Finding]:
    for path, line, col, msg in concurrency.find_blocking_under_lock(index):
        yield Finding(path, line, col, "blocking-under-lock", msg)


# -------------------------------------------- program-identity rules
# The analysis lives in analysis/identity.py (same contract as the
# concurrency lane: plain (path, line, col, message) tuples, memoised
# on the index); these wrappers stamp the rule ids.


def rule_stale_program(index: PackageIndex) -> Iterator[Finding]:
    from megba_tpu.analysis import identity

    for path, line, col, msg in identity.find_stale_program(index):
        yield Finding(path, line, col, "stale-program", msg)


def rule_cache_split(index: PackageIndex) -> Iterator[Finding]:
    from megba_tpu.analysis import identity

    for path, line, col, msg in identity.find_cache_split(index):
        yield Finding(path, line, col, "cache-split", msg)


def rule_key_surface_drift(index: PackageIndex) -> Iterator[Finding]:
    from megba_tpu.analysis import identity

    for path, line, col, msg in identity.find_key_surface_drift(index):
        yield Finding(path, line, col, "key-surface-drift", msg)


RULES = {
    "host-callback": rule_host_callback,
    "np-in-jit": rule_np_in_jit,
    "implicit-dtype": rule_implicit_dtype,
    "scalar-promotion": rule_scalar_promotion,
    "donated-reuse": rule_donated_reuse,
    "weak-literal": rule_weak_literal,
    "raw-clock": rule_raw_clock,
    "guarded-by": rule_guarded_by,
    "lock-order": rule_lock_order,
    "blocking-under-lock": rule_blocking_under_lock,
    "stale-program": rule_stale_program,
    "cache-split": rule_cache_split,
    "key-surface-drift": rule_key_surface_drift,
}
