"""Program-identity contract analysis (lint lane 7).

Every serving-tier correctness story hangs on key discipline: the
retrace sentinel's `static_key`, the artifact store's
`option_fingerprint`, the warm-manifest `option_config`, and the
compile-pool bucket keys must all agree on which option fields change
the lowered program.  `static_key` reprs the frozen option dataclasses
WHOLE, so the failure modes are exactly two:

- a field the lowering path READS but some surface strips (or a
  builder's key omits the option entirely) serves a *wrong program*
  on a cache hit — the `stale-program` rule;
- a field NO lowering path reads, that is not on the observability
  strip-list, reaches every key anyway and silently *fragments* the
  compile cache, artifact store and warm manifests — the `cache-split`
  rule;
- and the strip-list itself is a contract: every strip site, exclusion
  list and cache front must derive from the ONE extracted registry
  (`OBSERVABILITY_FIELDS`), and operand-declared values must never be
  branched on inside traced code — the `key-surface-drift` rule.

Pure standard library (ast) over the callgraph index
(analysis/callgraph.py), in the concurrency lane's mold: this module
never imports or executes the code under analysis.  Everything is
name-convention driven — option classes are recognised by class NAME
(ProblemOption / SolverOption / AlgoOption / RobustOption), lowering
entry points by function name (flat_solve, batched_solve_program,
lower_bucket, solve_pgo, distributed_lm_solve) or an inline
`# megba: lowering-entry` pragma, and strip helpers by name
(strip_observability / _sans_telemetry / _strip_telemetry) — so the
seeded fixtures under tests/data/lint_fixtures/ exercise every rule
without importing the package.

The option-field read set is computed from the callgraph's
per-function attribute-read pass (`FunctionInfo.attr_reads`), resolved
against named parameters through each function's lexical scope chain:
a nested closure reading `solver_opt.tol` where the enclosing function
assigned `solver_opt = option.solver_option` attributes the read to
`solver_option.tol` on the enclosing `option` parameter.  Parameter
types come from annotations first, then the repo's naming conventions
(`option`/`opt` -> ProblemOption, `solver_opt[ion]` -> SolverOption,
...).  Resolution is conservative: an unresolvable read is ignored
(never guessed), which can only make `cache-split` fire — and a false
fire is answered with one of the two declared-intent pragmas, each a
visible, greppable statement of why a field is keyed.

Declared-intent escape hatches (field-scoped pragmas, parsed with a
dedicated regex because the parenthesised form stops the generic
pragma tokenizer):

- a `lowering-relevant` pragma on a field declaration asserts the
  field selects a program family even though no lowering code branches
  on it today (e.g. validated-to-one-value kind selectors, the backend
  `device` knob);
- a `key-exempt` pragma asserts a field is truly host-only and keying
  it would only fragment caches (derived shape hints).  A key-exempt
  field READ on the lowering path is a contradiction and fires
  `stale-program`.

Per-line suppression composes as everywhere else:
`# megba: allow-stale-program` / `allow-cache-split` /
`allow-key-surface-drift` on the flagged line.
"""

from __future__ import annotations

import ast
import re
from typing import (
    Dict,
    Iterator,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from megba_tpu.analysis.callgraph import (
    FunctionInfo,
    ModuleInfo,
    PackageIndex,
    _dotted,
    pragmas_on_line,
)

# ---------------------------------------------------------------- names

OPTION_CLASS_NAMES = ("ProblemOption", "SolverOption", "AlgoOption",
                      "RobustOption")
ROOT_OPTION_CLASS = "ProblemOption"

# Canonical ProblemOption container-field spelling per sub-option
# class, used when a class is analysed without a ProblemOption that
# references it (fixtures), or to rescue alias reads.
_CLASS_PREFIX = {
    "SolverOption": "solver_option",
    "AlgoOption": "algo_option",
    "RobustOption": "robust_option",
}

# Parameter-name conventions (annotation wins when present).
PARAM_NAME_TYPES = {
    "option": "ProblemOption",
    "opt": "ProblemOption",
    "problem_option": "ProblemOption",
    "base_option": "ProblemOption",
    "report_option": "ProblemOption",
    "solve_option": "ProblemOption",
    "compare_option": "ProblemOption",
    "solver_option": "SolverOption",
    "solver_opt": "SolverOption",
    "algo_option": "AlgoOption",
    "algo_opt": "AlgoOption",
    "robust_option": "RobustOption",
    "robust_opt": "RobustOption",
}

# The lowering entry points: flat_solve's three paths (single, sharded
# and tiled all go through flat_solve / distributed_lm_solve), the
# serving batched front + bucket lowering, and the PGO driver.
LOWERING_ENTRY_NAMES = frozenset({
    "flat_solve",
    "distributed_lm_solve",
    "batched_solve_program",
    "lower_bucket",
    "solve_pgo",
})

# Canonical strip helpers: a function with one of these names (or one
# that references one) is a declared observability-strip site.
STRIP_HELPER_NAMES = frozenset({
    "strip_observability",
    "_sans_telemetry",
    "_strip_telemetry",
})

# The one extracted strip registry (common.OBSERVABILITY_FIELDS).
REGISTRY_NAME = "OBSERVABILITY_FIELDS"

# Key-constructor call tails: a static program/artifact key surface.
KEY_FN_TAILS = frozenset({"static_key"})

# Operand-declared values (runtime data fed into traced programs as
# arguments).  Branching on one in Python inside traced code bakes the
# traced value static (operand-as-static); only `is None` presence
# checks are sanctioned.
OPERAND_NAMES = frozenset({
    "edge_mask",
    "mask",
    "sqrt_info",
    "cam_fixed",
    "pt_fixed",
    "initial_region",
    "init_region",
    "initial_v",
    "init_v",
    "initial_dx",
    "fault_plan",
    "verbose_token",
})

# Field-scoped pragmas need their own regexes: the parenthesised form
# stops callgraph.PRAGMA_RE at the "(" (same situation as the
# concurrency lane's guarded-by pragma).
_MEGBA_COMMENT_RE = re.compile(r"#\s*megba:(.*)$")
_LOWERING_RELEVANT_RE = re.compile(r"lowering-relevant\(\s*([\w.]+)\s*\)")
_KEY_EXEMPT_RE = re.compile(r"key-exempt\(\s*([\w.]+)\s*\)")


# ------------------------------------------------------------- helpers

def _short(qualname: str) -> str:
    return ".".join(qualname.rsplit(".", 2)[-2:])


def _own_nodes(info: FunctionInfo) -> Iterator[ast.AST]:
    """Every node in `info`'s own body, skipping nested defs (they are
    indexed functions of their own and analysed separately)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(info.node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_cleared_const(node: ast.AST) -> bool:
    """A "cleared" strip value: None / False / 0 / "" literal."""
    return (isinstance(node, ast.Constant)
            and (node.value is None or node.value is False
                 or node.value == 0 or node.value == ""))


def _annotation_class(node: Optional[ast.AST]) -> Optional[str]:
    """Option class named by an annotation (handles Optional[...] and
    string annotations); None when it names no option class."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        tail = node.value.split(".")[-1].strip("'\" ")
        return tail if tail in OPTION_CLASS_NAMES else None
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in OPTION_CLASS_NAMES:
            return sub.id
        if (isinstance(sub, ast.Attribute)
                and sub.attr in OPTION_CLASS_NAMES):
            return sub.attr
    return None


def _param_names(node: ast.AST) -> List[ast.arg]:
    args = node.args
    return list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)


# ------------------------------------------------------------ registry

class _Registry:
    """The extracted program-identity field registry: option classes,
    their leaf fields (dotted from ProblemOption), the observability
    strip-list, and the declared-intent pragmas."""

    def __init__(self) -> None:
        # class name -> {field name -> sub-option class} (containers)
        self.containers: Dict[str, Dict[str, str]] = {}
        # class name -> {field name -> (path, lineno)} (leaves)
        self.leaves: Dict[str, Dict[str, Tuple[str, int]]] = {}
        self.defined: Set[str] = set()
        # dotted-from-ProblemOption leaf path -> (path, lineno)
        self.leaf_paths: Dict[str, Tuple[str, int]] = {}
        self.strip_fields: Tuple[str, ...] = ()
        # pragma kind -> {field path}
        self.pragmas: Dict[str, Set[str]] = {
            "lowering-relevant": set(), "key-exempt": set()}
        # (kind, field, path, lineno) for reporting
        self.pragma_sites: List[Tuple[str, str, str, int]] = []

    def prefix_for(self, classname: str) -> str:
        """Dotted-path prefix for fields of `classname` ("" for the
        root class, "solver_option." for SolverOption, ...)."""
        if classname == ROOT_OPTION_CLASS:
            return ""
        for field, cls in self.containers.get(
                ROOT_OPTION_CLASS, {}).items():
            if cls == classname:
                return field + "."
        fallback = _CLASS_PREFIX.get(classname)
        return fallback + "." if fallback else classname + "."


def _extract_registry(index: PackageIndex) -> _Registry:
    reg = _Registry()
    # -- option class declarations (prefer the ProblemOption module on
    # duplicate definitions, so a vendored copy cannot shadow the
    # canonical one when both are under the linted paths).
    defs: Dict[str, Tuple[ModuleInfo, ast.ClassDef]] = {}
    root_mod: Optional[str] = None
    for modname in sorted(index.modules):
        mod = index.modules[modname]
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.ClassDef)
                    and node.name in OPTION_CLASS_NAMES):
                if node.name == ROOT_OPTION_CLASS and root_mod is None:
                    root_mod = modname
                if node.name not in defs:
                    defs[node.name] = (mod, node)
    if root_mod is not None:
        mod = index.modules[root_mod]
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.ClassDef)
                    and node.name in OPTION_CLASS_NAMES):
                defs[node.name] = (mod, node)

    for classname, (mod, node) in defs.items():
        reg.defined.add(classname)
        reg.containers.setdefault(classname, {})
        reg.leaves.setdefault(classname, {})
        for stmt in node.body:
            if not (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                continue
            field = stmt.target.id
            sub = _annotation_class(stmt.annotation)
            if sub is not None and sub != classname:
                reg.containers[classname][field] = sub
            else:
                reg.leaves[classname][field] = (mod.path, stmt.lineno)

    # -- dotted leaf paths (one container level, the repo's shape)
    for field, loc in reg.leaves.get(ROOT_OPTION_CLASS, {}).items():
        reg.leaf_paths[field] = loc
    for cfield, cls in reg.containers.get(ROOT_OPTION_CLASS, {}).items():
        for field, loc in reg.leaves.get(cls, {}).items():
            reg.leaf_paths[f"{cfield}.{field}"] = loc
    # Sub-option classes analysed without a referencing ProblemOption
    # (single-file fixtures) still contribute under their canonical
    # prefix.
    referenced = set(reg.containers.get(ROOT_OPTION_CLASS, {}).values())
    for cls in reg.defined - {ROOT_OPTION_CLASS} - referenced:
        prefix = reg.prefix_for(cls)
        for field, loc in reg.leaves.get(cls, {}).items():
            reg.leaf_paths.setdefault(prefix + field, loc)

    # -- the strip-list: the module-level OBSERVABILITY_FIELDS tuple
    # (ProblemOption's module wins), falling back to the union of
    # cleared kwargs in the declared strip helpers.
    candidates: List[Tuple[str, Tuple[str, ...]]] = []
    for modname in sorted(index.modules):
        mod = index.modules[modname]
        for stmt in mod.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == REGISTRY_NAME
                    and isinstance(stmt.value, (ast.Tuple, ast.List))):
                names = tuple(
                    e.value for e in stmt.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))
                if names:
                    candidates.append((modname, names))
    for modname, names in candidates:
        if modname == root_mod:
            reg.strip_fields = names
            break
    else:
        if candidates:
            reg.strip_fields = candidates[0][1]
    if not reg.strip_fields:
        cleared: Set[str] = set()
        for info in index.functions.values():
            if info.qualname.rsplit(".", 1)[-1] in STRIP_HELPER_NAMES:
                for _line, fields in _strip_replaces(info):
                    cleared |= fields
        reg.strip_fields = tuple(sorted(cleared))

    # -- declared-intent pragmas, anywhere under the linted paths
    for mod in index.modules.values():
        for lineno, line in enumerate(mod.source_lines, start=1):
            m = _MEGBA_COMMENT_RE.search(line)
            if not m:
                continue
            tail = m.group(1)
            for rx, kind in ((_LOWERING_RELEVANT_RE, "lowering-relevant"),
                             (_KEY_EXEMPT_RE, "key-exempt")):
                for pm in rx.finditer(tail):
                    reg.pragmas[kind].add(pm.group(1))
                    reg.pragma_sites.append(
                        (kind, pm.group(1), mod.path, lineno))
    return reg


def _strip_replaces(info: FunctionInfo) -> List[Tuple[int, Set[str]]]:
    """(lineno, {cleared field names}) for every `replace(...)` call in
    `info`'s own body that clears at least one keyword to a cleared
    constant (None/False/0/"")."""
    out: List[Tuple[int, Set[str]]] = []
    for node in _own_nodes(info):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        if callee is None or callee.split(".")[-1] != "replace":
            continue
        cleared = {kw.arg for kw in node.keywords
                   if kw.arg is not None and _is_cleared_const(kw.value)}
        if cleared:
            out.append((node.lineno, cleared))
    return out


# ------------------------------------------------------------ analyzer

class _Analyzer:
    """One shared pass per PackageIndex (memoised on the index): the
    registry, the lowering-closure, the resolved option-field read set,
    and the key/cache surfaces the three rules consume."""

    def __init__(self, index: PackageIndex) -> None:
        self.index = index
        self.reg = _extract_registry(index)
        # module-qualified cache-alias name -> builder function qualname
        # (`_cached_x = lru_cache(...)(_build_x)` module assigns).
        self.cache_aliases: Dict[str, str] = {}
        self._collect_cache_aliases()
        self.entries: List[str] = self._find_entries()
        self.closure: Set[str] = self._closure()
        # dotted leaf path -> sorted qualnames of closure readers
        self.reads: Dict[str, List[str]] = {}
        self._collect_reads()

    # -- cache fronts ------------------------------------------------
    def _collect_cache_aliases(self) -> None:
        for mod in self.index.modules.values():
            for stmt in mod.tree.body:
                if not (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and isinstance(stmt.value, ast.Call)):
                    continue
                if not self._is_cache_wrapper(stmt.value):
                    continue
                builder = None
                for sub in ast.walk(stmt.value):
                    if isinstance(sub, (ast.Name, ast.Attribute)):
                        q = self.index.resolve(mod, None, sub)
                        if q is not None:
                            builder = q
                            break
                if builder is not None:
                    alias = f"{mod.name}.{stmt.targets[0].id}"
                    self.cache_aliases[alias] = builder

    @staticmethod
    def _is_cache_wrapper(call: ast.Call) -> bool:
        """`lru_cache(...)(fn)` / `normalized_lru_cache(...)(fn)` shape:
        the callee is itself a call whose name tail mentions cache."""
        fn = call.func
        if isinstance(fn, ast.Call):
            inner = _dotted(fn.func)
            return inner is not None and "cache" in inner.split(".")[-1]
        dotted = _dotted(fn)
        return dotted is not None and "cache" in dotted.split(".")[-1]

    def _cache_refs(self, info: FunctionInfo) -> List[str]:
        """Memoised-program references in `info`'s own body: cache
        aliases it names, plus refs to cache-DECORATED functions."""
        mod = self.index.modules[info.module]
        out: List[str] = []
        for node in _own_nodes(info):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                alias = f"{mod.name}.{node.id}"
                target = self.cache_aliases.get(
                    alias) or self.cache_aliases.get(
                        mod.imports.get(node.id, ""))
                if target is not None:
                    out.append(target)
        for q in info.refs:
            ref = self.index.functions.get(q)
            if ref is not None and _is_cache_decorated(ref.node):
                out.append(q)
        return out

    # -- closure -----------------------------------------------------
    def _find_entries(self) -> List[str]:
        out = []
        for q, info in self.index.functions.items():
            simple = q.rsplit(".", 1)[-1]
            mod = self.index.modules[info.module]
            if simple in LOWERING_ENTRY_NAMES or "lowering-entry" in (
                    pragmas_on_line(mod.source_lines, info.node.lineno)):
                out.append(q)
        return sorted(out)

    def _closure(self) -> Set[str]:
        seen = set(self.entries)
        frontier = list(self.entries)
        while frontier:
            q = frontier.pop()
            info = self.index.functions[q]
            nxt = (list(info.refs) + list(info.children)
                   + self._cache_refs(info))
            for n in nxt:
                if n in self.index.functions and n not in seen:
                    seen.add(n)
                    frontier.append(n)
        return seen

    # -- read resolution ---------------------------------------------
    def _scope_chain(self, info: FunctionInfo) -> List[FunctionInfo]:
        chain = [info]
        cur = info
        while cur.parent is not None:
            cur = self.index.functions.get(cur.parent)
            if cur is None:
                break
            chain.append(cur)
        return chain

    def param_types(self, info: FunctionInfo) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for a in _param_names(info.node):
            cls = _annotation_class(a.annotation)
            if cls is None:
                cls = PARAM_NAME_TYPES.get(a.arg)
            if cls is not None and cls in self.reg.defined:
                out[a.arg] = cls
        return out

    def root_type(self, info: FunctionInfo, root: str,
                  _depth: int = 0) -> Optional[str]:
        """Option class of `root` in `info`'s scope chain: own params
        and aliases first, then each enclosing function's (closure
        capture)."""
        if _depth > 8:
            return None
        for scope in self._scope_chain(info):
            ptypes = self.param_types(scope)
            if root in ptypes:
                return ptypes[root]
            if root in scope.assigns:
                val = scope.assigns[root]
                vroot, _, vchain = val.partition(".")
                if vroot == root and not vchain:
                    return None
                base = self.root_type(scope, vroot, _depth + 1)
                if base is None:
                    return None
                return self._walk_containers(base, vchain)
        return None

    def _walk_containers(self, cls: str, chain: str) -> Optional[str]:
        if not chain:
            return cls
        for comp in chain.split("."):
            nxt = self.reg.containers.get(cls, {}).get(comp)
            if nxt is None:
                return None
            cls = nxt
        return cls

    def resolve_read(self, info: FunctionInfo, root: str,
                     chain: str) -> Optional[str]:
        """Dotted-from-ProblemOption leaf path of the attribute read
        `root.chain` in `info`, or None when it is not an option-field
        read (unknown root, method access, off-registry attribute)."""
        if not chain:
            return None
        cls = self.root_type(info, root)
        if cls is None:
            return None
        consumed: List[str] = []
        for comp in chain.split("."):
            sub = self.reg.containers.get(cls, {}).get(comp)
            if sub is not None:
                consumed.append(comp)
                cls = sub
                continue
            if comp in self.reg.leaves.get(cls, {}):
                # Path rooted at the read's OWN class, then prefixed
                # back to ProblemOption.
                start = self.root_type(info, root)
                return (self.reg.prefix_for(start)
                        + ".".join(consumed + [comp]))
            return None
        return None  # pure container access, no leaf touched

    def _collect_reads(self) -> None:
        for q in sorted(self.closure):
            info = self.index.functions[q]
            for root, chains in info.attr_reads.items():
                for chain in chains:
                    path = self.resolve_read(info, root, chain)
                    if path is not None:
                        self.reads.setdefault(path, []).append(q)
        for readers in self.reads.values():
            readers.sort()

    # -- located lookups (only used when emitting findings) ----------
    def locate_reads(self, info: FunctionInfo,
                     leaf: str) -> List[Tuple[int, int]]:
        """(line, col) of every outermost attribute read in `info`'s
        own body whose chain ends in `leaf` and resolves to an option
        field ending in `leaf`."""
        out = []
        for node in _own_nodes(info):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)):
                continue
            dotted = _dotted(node)
            if dotted is None or dotted.split(".")[-1] != leaf:
                continue
            root, _, chain = dotted.partition(".")
            path = self.resolve_read(info, root, chain)
            if path is not None and path.split(".")[-1] == leaf:
                out.append((node.lineno, node.col_offset))
        return sorted(set(out))

    # -- strip discipline --------------------------------------------
    def is_strip_helper(self, info: FunctionInfo) -> bool:
        return info.qualname.rsplit(".", 1)[-1] in STRIP_HELPER_NAMES

    def references_strip_helper(self, info: FunctionInfo) -> bool:
        for node in _own_nodes(info):
            if (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in STRIP_HELPER_NAMES):
                return True
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and node.attr in STRIP_HELPER_NAMES):
                return True
        return False

    def strips_fully(self, info: FunctionInfo) -> bool:
        """`info` clears the whole strip-list itself, or routes through
        a declared strip helper."""
        if self.is_strip_helper(info) or self.references_strip_helper(info):
            return True
        strip = set(self.reg.strip_fields)
        return any(strip <= cleared
                   for _line, cleared in _strip_replaces(info))

    def strip_exempt_fields(self, info: FunctionInfo) -> Set[str]:
        """Strip-listed fields `info` may legitimately READ: the
        consume-and-strip shape (resolve the sink, then clear it in the
        same function, inline or via a helper)."""
        if self.is_strip_helper(info) or self.references_strip_helper(info):
            return set(self.reg.strip_fields)
        out: Set[str] = set()
        for _line, cleared in _strip_replaces(info):
            out |= cleared & set(self.reg.strip_fields)
        return out


def _is_cache_decorated(node: ast.AST) -> bool:
    for dec in getattr(node, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        dotted = _dotted(target)
        if dotted is not None and "cache" in dotted.split(".")[-1]:
            return True
    return False


def _analyzer(index: PackageIndex) -> _Analyzer:
    cached = getattr(index, "_megba_identity", None)
    if cached is None:
        cached = _Analyzer(index)
        index._megba_identity = cached  # type: ignore[attr-defined]
    return cached


# ------------------------------------------------------- rule: stale

def find_stale_program(
        index: PackageIndex) -> Iterator[Tuple[str, int, int, str]]:
    """Wrong-program hazards.

    (a) a strip-listed (or key-exempt-declared) field READ by a
        function on the lowering closure that does not itself strip it
        — the compiled program depends on a knob every cache key has
        had cleared, so a sink/flag flip silently serves a stale
        program;
    (b) a closure function with an option parameter that builds a
        `static_key(...)` WITHOUT the option — every option field is
        invisible to that program's identity.
    """
    a = _analyzer(index)
    if not a.reg.leaf_paths:
        return
    hidden = set(a.reg.strip_fields) | {
        p for p in a.reg.pragmas["key-exempt"]}
    for q in sorted(a.closure):
        info = index.functions[q]
        mod = index.modules[info.module]
        exempt = a.strip_exempt_fields(info)
        # (a) hidden-field reads
        for root, chains in sorted(info.attr_reads.items()):
            for chain in sorted(chains):
                path = a.resolve_read(info, root, chain)
                if path is None or path not in hidden or path in exempt:
                    continue
                leaf = path.split(".")[-1]
                locs = a.locate_reads(info, leaf) or [
                    (info.node.lineno, info.node.col_offset)]
                what = ("is on the observability strip-list"
                        if path in a.reg.strip_fields
                        else "is declared key-exempt")
                for line, col in locs:
                    yield (mod.path, line, col,
                           f"option field `{path}` is read on the "
                           f"lowering path ({_short(q)}) but {what} — "
                           "the compiled program depends on a knob its "
                           "cache keys never see (wrong-program "
                           "hazard); key the field, or consume it and "
                           "strip it in this same function")
    # (b) static keys that omit the option
    for q, info in sorted(index.functions.items()):
        in_scope: Dict[str, str] = {}
        for scope in a._scope_chain(info):
            for name, cls in a.param_types(scope).items():
                in_scope.setdefault(name, cls)
        option_params = {n for n, c in in_scope.items()
                         if c == ROOT_OPTION_CLASS}
        if not option_params:
            continue
        mod = index.modules[info.module]
        # Option taint: a local assigned from ANY expression containing
        # an option parameter (e.g. `compare_option =
        # _sans_telemetry(option)`) carries the option into the key.
        tainted = set(option_params)
        for _ in range(3):  # tiny fixpoint; chains are short
            grew = False
            for node in _own_nodes(info):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                if any(isinstance(sub, ast.Name) and sub.id in tainted
                       for sub in ast.walk(node.value)):
                    if node.targets[0].id not in tainted:
                        tainted.add(node.targets[0].id)
                        grew = True
            if not grew:
                break
        for node in _own_nodes(info):
            if not isinstance(node, ast.Call):
                continue
            callee = _dotted(node.func)
            if callee is None or callee.split(".")[-1] not in KEY_FN_TAILS:
                continue
            arg_names: Set[str] = set()
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        arg_names.add(sub.id)
            if tainted & arg_names:
                continue
            yield (mod.path, node.lineno, node.col_offset,
                   f"{_short(q)} builds a static key that omits its "
                   f"option parameter "
                   f"`{sorted(option_params)[0]}` — every option field "
                   "is invisible to this program's identity "
                   "(wrong-program hazard); pass the (stripped) option "
                   "into the key")


# -------------------------------------------------- rule: cache-split

def find_cache_split(
        index: PackageIndex) -> Iterator[Tuple[str, int, int, str]]:
    """Fields that fragment every key surface for nothing: present in
    the option dataclasses (and therefore in every `static_key` repr,
    artifact fingerprint, manifest config and bucket key), never read
    on the lowering closure, not on the observability strip-list, and
    carrying no declared-intent pragma."""
    a = _analyzer(index)
    strip = set(a.reg.strip_fields)
    declared = (a.reg.pragmas["lowering-relevant"]
                | a.reg.pragmas["key-exempt"])
    for path in sorted(a.reg.leaf_paths):
        if path in strip or path.split(".")[-1] in strip:
            continue
        if path in declared:
            continue
        if path in a.reads:
            continue
        fpath, lineno = a.reg.leaf_paths[path]
        yield (fpath, lineno, 0,
               f"option field `{path}` reaches every key surface "
               "(static_key reprs the whole option; artifact "
               "fingerprints, warm manifests and bucket keys follow) "
               "but is never read on the lowering path — it silently "
               "fragments the compile cache, artifact store and warm "
               "manifests; declare it lowering-relevant(...) if it "
               "selects a program family, key-exempt(...) if it is "
               "host-only, or add it to the observability strip-list")


# -------------------------------------------- rule: key-surface-drift

def find_key_surface_drift(
        index: PackageIndex) -> Iterator[Tuple[str, int, int, str]]:
    """The strip-list is one registry and every surface must derive
    from it.

    (a) partial strips: a `replace(...)` clearing a non-empty PROPER
        subset of the strip-list (the un-cleared knob fragments that
        surface's keys);
    (b) a declared strip helper that neither clears the full list nor
        routes through another helper;
    (c) hardcoded membership tuples that overlap the strip-list but
        disagree with it (the manifest-comparison exclusion bug
        class);
    (d) a function with an option parameter fronting a memoised
        program cache without stripping first (the un-stripped public
        cache-front bug class);
    (e) a field carrying BOTH declared-intent pragmas, or a pragma
        naming a field the registry does not define;
    (f) operand-declared values branched on in Python inside traced
        code (operand-as-static) — `is None` presence checks
        sanctioned.
    """
    a = _analyzer(index)
    strip = set(a.reg.strip_fields)

    if strip:
        for q, info in sorted(index.functions.items()):
            mod = index.modules[info.module]
            is_helper = a.is_strip_helper(info)
            conforming = False
            for lineno, cleared in _strip_replaces(info):
                inter = cleared & strip
                if not inter:
                    continue
                if strip <= cleared:
                    conforming = True
                    continue
                missing = sorted(strip - cleared)
                yield (mod.path, lineno, 0,
                       f"partial observability strip in {_short(q)}: "
                       f"clears {sorted(inter)} but the declared "
                       f"strip-list is {sorted(strip)} — the "
                       f"un-cleared {missing} still fragments this "
                       "key surface; route through the canonical "
                       "strip helper")
            # (b) helper conformance
            if (is_helper and not conforming
                    and not a.references_strip_helper(info)):
                yield (mod.path, info.node.lineno, info.node.col_offset,
                       f"strip helper {_short(q)} clears neither the "
                       f"full strip-list {sorted(strip)} nor routes "
                       "through another declared helper — surfaces "
                       "keyed through it drift from the registry")

        # (c) hardcoded exclusion tuples
        for q, info in sorted(index.functions.items()):
            mod = index.modules[info.module]
            for node in _own_nodes(info):
                if not (isinstance(node, ast.Compare)
                        and len(node.ops) == 1
                        and isinstance(node.ops[0], (ast.In, ast.NotIn))
                        and isinstance(node.comparators[0],
                                       (ast.Tuple, ast.List, ast.Set))):
                    continue
                consts = {e.value for e in node.comparators[0].elts
                          if isinstance(e, ast.Constant)
                          and isinstance(e.value, str)}
                if not consts or not (consts & strip):
                    continue
                if consts == strip:
                    continue
                yield (mod.path, node.lineno, node.col_offset,
                       f"hardcoded key-exclusion {sorted(consts)} in "
                       f"{_short(q)} drifts from the observability "
                       f"registry {sorted(strip)} — derive the "
                       f"membership test from {REGISTRY_NAME} so the "
                       "comparison surface cannot disagree with the "
                       "strip sites")

        # (d) un-stripped cache fronts
        for q in sorted(a.closure):
            info = index.functions[q]
            if a.is_strip_helper(info):
                continue
            if ROOT_OPTION_CLASS not in a.param_types(info).values():
                continue
            fronts = a._cache_refs(info)
            if not fronts or a.strips_fully(info):
                continue
            mod = index.modules[info.module]
            yield (mod.path, info.node.lineno, info.node.col_offset,
                   f"{_short(q)} fronts the memoised program cache "
                   f"({_short(sorted(fronts)[0])}) with an un-stripped "
                   "option — a telemetry/metrics-armed option splits "
                   "the compile cache and warm keys per sink value; "
                   "strip the observability fields before the cache "
                   "lookup")

    # (e) pragma hygiene
    both = (a.reg.pragmas["lowering-relevant"]
            & a.reg.pragmas["key-exempt"])
    known = set(a.reg.leaf_paths)
    for kind, field, path, lineno in sorted(a.reg.pragma_sites):
        if field in both and kind == "key-exempt":
            yield (path, lineno, 0,
                   f"option field `{field}` carries BOTH "
                   "lowering-relevant and key-exempt pragmas — the "
                   "declarations contradict; a field either shapes "
                   "the program or it does not")
        if known and field not in known:
            yield (path, lineno, 0,
                   f"identity pragma names `{field}`, which is not a "
                   "declared option field — a renamed or removed "
                   "field must take its pragma with it")

    # (f) operand-as-static branches in traced code
    for q in sorted(index.reachable):
        info = index.functions.get(q)
        if info is None:
            continue
        mod = index.modules[info.module]
        operand_params: Set[str] = set()
        for scope in a._scope_chain(info):
            operand_params |= {p.arg for p in _param_names(scope.node)
                               if p.arg in OPERAND_NAMES}
        if not operand_params:
            continue
        seen: Set[Tuple[str, int]] = set()
        for node in _own_nodes(info):
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                for name, lineno, col in _unsanctioned_operands(
                        node.test, operand_params):
                    if (name, lineno) in seen:
                        continue
                    seen.add((name, lineno))
                    yield (mod.path, lineno, col,
                           f"operand `{name}` appears in a "
                           f"Python-level branch inside traced code "
                           f"({_short(q)}) — a branch on a traced "
                           "value bakes it static "
                           "(operand-as-static); only `is None` "
                           "presence checks are host decisions, use "
                           "lax.cond/jnp.where for value branches")


def _unsanctioned_operands(
        test: ast.AST,
        operand_params: Set[str]) -> List[Tuple[str, int, int]]:
    """Operand-name loads inside a branch test that are NOT of the
    sanctioned `x is None` / `x is not None` presence-check shape."""
    sanctioned: Set[int] = set()
    for node in ast.walk(test):
        if (isinstance(node, ast.Compare) and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.Is, ast.IsNot))
                and isinstance(node.comparators[0], ast.Constant)
                and node.comparators[0].value is None):
            for sub in ast.walk(node.left):
                if isinstance(sub, ast.Name):
                    sanctioned.add(id(sub))
    out = []
    for node in ast.walk(test):
        if (isinstance(node, ast.Name) and node.id in operand_params
                and id(node) not in sanctioned):
            out.append((node.id, node.lineno, node.col_offset))
    return out


# ----------------------------------------------------------- summary

def identity_summary(index: PackageIndex) -> Dict[str, object]:
    """Inspection hook (tests, docs): the extracted registry, entry
    points, closure size and resolved read set."""
    a = _analyzer(index)
    return {
        "entries": list(a.entries),
        "closure": sorted(a.closure),
        "strip_fields": tuple(a.reg.strip_fields),
        "leaf_paths": sorted(a.reg.leaf_paths),
        "reads": {k: list(v) for k, v in sorted(a.reads.items())},
        "pragmas": {k: sorted(v) for k, v in a.reg.pragmas.items()},
        "cache_aliases": dict(sorted(a.cache_aliases.items())),
    }
