"""Compiled-program auditor: static CI gates on the lowered executables.

PR 2's AST linter sees Python source; this layer sees what XLA actually
emitted.  Each canonical solver program — the single-device `flat_solve`
program, its tiled variant, the sharded SPMD program from
`parallel/mesh.py`, and the PGO program (single + sharded) — is
AOT-lowered on small synthetic problems via the production entry points
themselves (`flat_solve(..., lower_only=True)` / `solve_pgo(...,
lower_only=True)`: same host prep, same jit caches, same donation
flags), compiled, and audited in four passes:

1. **transfer-freedom** — walk the StableHLO for host callbacks /
   infeed / outfeed / send / recv custom_calls; any occurrence outside
   the observability-sanctioned targets fails (MegBA's contract: one
   fused device program per solve, zero host round-trips — arxiv
   2112.01349 §4).
2. **collective census** — enumerate all-reduce / all-gather /
   collective-permute ops in the *optimized* HLO (post-DCE truth),
   attribute them to program regions via the `jax.named_scope` paths in
   op metadata, and compare against the analytic per-PCG-iteration
   expectation: exactly TWO reductions inside the PCG while body for
   the Schur solve (hlp + hpl per S·p product), ONE for PGO's
   matrix-free H·x.  An accidental extra sync is a lint failure with
   the offending op named.
3. **dtype census + donation** — no f64 tensor in an f32 solve (and
   vice versa; weak Python literals that materialise as wide constants
   count), and every declared donation must have materialised as
   input-output aliasing in the compiled executable.
4. **budget gate** — `cost_analysis()` FLOPs / bytes-accessed and
   `memory_analysis()` peak temp size against the committed
   `ANALYSIS_BUDGET.json` (analysis/budget.py): >15% drift fails,
   collective-count changes fail exactly.

The CLI lives in `python -m megba_tpu.analysis.audit` (--check /
--update); scripts/lint.sh runs it as gate 4.  Everything is
CPU-lowered: passes run without executing a single solver FLOP.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from megba_tpu.analysis import hlo

# Scope-path fragment that marks the PCG inner loop's body in compiled
# op metadata (jax.named_scope "megba.pcg_core" + the while lowering).
PCG_BODY_MARK = "megba.pcg_core/while/body"


def pcg_body_collective_summary(
    compiled_ops: Sequence[hlo.HloOp], world: int,
) -> Tuple[List[hlo.HloOp], Dict[str, int], float]:
    """PCG-body collectives of a compiled program: (ops, kind -> count
    census, ring-model bytes moved per device per CG step).

    The single body-mark filter + byte model behind both the
    ProgramAudit.pcg_body_* passes and bench.py's mesh2d head-to-head,
    so the bench census can never diverge from what the budget gate's
    `collective_bytes_per_sp` axis pins."""
    body = [op for op in hlo.collective_ops(compiled_ops)
            if op.op_name and PCG_BODY_MARK in op.op_name]
    census: Dict[str, int] = {}
    for op in body:
        census[op.kind] = census.get(op.kind, 0) + 1
    bytes_moved = float(sum(
        hlo.collective_bytes_moved(op, world) for op in body))
    return body, census, bytes_moved

# custom_call targets the observability layer is allowed to emit (the
# sanctioned trace outputs).  The canonical audited programs are built
# verbose=False so none should appear at all, but the allowance keeps
# the pass honest if a sanctioned trace output ever becomes part of a
# canonical program.
SANCTIONED_TRANSFER_TARGETS: Tuple[str, ...] = ()

_WRONG_FAMILY = {
    "f32": ("f64", "bf16", "f16"),
    "f64": ("f32", "bf16", "f16"),
}

# StableHLO op kinds a declared bf16 surface may carry bf16 tensors in:
# storage/movement ops (converts, gathers, slices, layout shuffles),
# the bf16 multiply itself, and dot_general (whose RESULT must still be
# f32 — checked separately).  Collective kinds are allowed only when
# the surface declares `collectives=True`.  Accumulation kinds (add /
# subtract / reduce) are NEVER allowed on non-scalar bf16 tensors —
# f32 accumulation is the contract — except the rank-0 adds inside a
# declared collective's reduction region (the wire-payload sum the
# collective gate explicitly buys).
BF16_ALLOWED_KINDS: Tuple[str, ...] = (
    "convert", "multiply", "dot_general",
    "gather", "dynamic_slice", "slice", "dynamic_update_slice",
    "reshape", "transpose", "broadcast_in_dim", "concatenate",
    "select", "pad", "constant", "optimization_barrier", "return",
    "custom_call",
    # jax's while lowering threads closure arrays (the bf16 coupling
    # rows / M⁻¹ copy) through the loop as invariant carries, so the
    # while op's own signature legitimately names bf16 tensors.
    "while",
)

_BF16_ACCUM_KINDS = frozenset({"add", "subtract", "reduce", "dot_general"})


@dataclasses.dataclass(frozen=True)
class Bf16Surface:
    """The DECLARED bf16 surface of one canonical program.

    A program spec carrying one of these opts into the bf16 audit pass
    (`ProgramAudit.bf16_surface_violations`) instead of the blanket
    "bf16 is a wrong-family dtype" rule:

    - every StableHLO op touching a bf16 tensor must be of an
      `allowed_kinds` kind (collective kinds additionally need
      `collectives=True`) — a bf16 op leaking outside the declared
      surface fails the audit naming the op;
    - NO accumulation may produce a bf16 result: an add/subtract/
      reduce with a non-scalar bf16 result, or a dot_general whose
      result is bf16 (preferred_element_type dropped), is exactly the
      "accumulation not f32" regression this pass exists to catch.
      Rank-0 bf16 adds are the reduction regions of the declared
      collectives and are allowed iff `collectives=True`;
    - converts may only cross between bf16 and f32 — a bf16<->f64
      convert is a family leak;
    - at least `min_compute_ops` bf16 multiplies / bf16-operand
      dot_generals must EXIST: a refactor that silently upcasts the
      operands before every product (the compiler or a well-meaning
      edit) leaves a program that still carries bf16 tensors but runs
      f32 math — the win evaporates while the census stays green, so
      its absence is a violation, not a shrug.
    """

    allowed_kinds: Tuple[str, ...] = BF16_ALLOWED_KINDS
    collectives: bool = False
    min_compute_ops: int = 1


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One canonical program: how to lower it + its audited invariants."""

    name: str
    float_family: str  # "f32" | "f64" — every float tensor must be this
    world: int  # mesh size; 1 => no collectives allowed at all
    pcg_psums: int  # all-reduces expected inside the PCG while body
    donate_leaves: Tuple[int, ...]  # flat params declared donated
    build: Callable[[], object]  # () -> jax.stages.Lowered
    # Collective kinds this program may emit anywhere (psum lowers to
    # all-reduce; the 2-D mesh programs additionally carry the
    # subgroup-stage kinds).  Any other kind is a violation.
    allowed_kinds: Tuple[str, ...] = ("all_reduce",)
    # Exact kind -> count census of the PCG while BODY (one CG step),
    # as (kind, count) pairs.  None = only the all-reduce count above
    # is pinned (the historical 1-D contract, byte-identical).
    pcg_body_census: Optional[Tuple[Tuple[str, int], ...]] = None
    # When True, every collective inside the PCG body must be
    # SUBGROUP-scoped: its replica groups (permute: its ring cycles)
    # span strictly fewer than `world` devices.  The 2-D mesh's whole
    # point — a world-wide reduce sneaking back into the body is
    # exactly the regression this pins against.
    pcg_subgroup_only: bool = False
    # Declared bf16 surface (None = any bf16 occurrence is a
    # wrong-family dtype leak, the historical rule).  With
    # `collectives=True` the PCG-body byte model additionally prices
    # the DECLARED (StableHLO) payload dtype instead of the compiled
    # one: probed on this jaxlib (0.4.36, XLA:CPU), the CPU backend's
    # float-normalization pass promotes bf16 collectives back to f32
    # in the compiled executable, so the CPU audit lane would price
    # wire bytes the program never asked to move — a TPU lowering
    # (native bf16 collectives) moves the declared payload, and the
    # surface pass pins that the declaration exists.
    bf16_surface: Optional[Bf16Surface] = None
    # Declared analytical per-S·p budget (analysis/edge_budget.py):
    # (metric, value) pairs — `flops_per_sp` / `bytes_touched_per_sp` —
    # priced from the problem geometry, the edge-stream plan (padding
    # included) and the dtype surface, with zero compiler in the loop.
    # Exact-gated in ANALYSIS_BUDGET.json: the committed number pins
    # the INPUTS, so a plan change, a quantum bump, or a dtype-surface
    # edit fails `--check` naming the program.  Spec-carried, not
    # measured, so the axes survive a backend without cost analysis.
    sp_budget: Optional[Tuple[Tuple[str, float], ...]] = None


@dataclasses.dataclass
class ProgramAudit:
    """Artifacts + derived census of one lowered/compiled program."""

    spec: ProgramSpec
    stablehlo: str
    compiled_text: str
    flops: float
    bytes_accessed: float
    peak_temp_bytes: float
    argument_bytes: float
    output_bytes: float

    @functools.cached_property
    def stablehlo_ops(self) -> List[hlo.HloOp]:
        return hlo.parse_stablehlo_ops(self.stablehlo)

    @functools.cached_property
    def compiled_ops(self) -> List[hlo.HloOp]:
        return hlo.parse_compiled_ops(self.compiled_text)

    @functools.cached_property
    def collectives(self) -> List[hlo.HloOp]:
        return hlo.collective_ops(self.compiled_ops)

    @functools.cached_property
    def declared_collective_payloads(self) -> List[hlo.CollectivePayload]:
        """StableHLO-declared collective payloads, parsed once (the
        byte repricing and the bf16 surface pass both read them)."""
        return hlo.stablehlo_collective_payloads(self.stablehlo)

    # ---- pass 1: transfer freedom ------------------------------------
    def transfer_violations(self) -> List[str]:
        bad = hlo.transfer_ops(self.stablehlo_ops,
                               allow=SANCTIONED_TRANSFER_TARGETS)
        return [
            f"{self.spec.name}: host transfer in compiled program — "
            f"{op.where()} :: {op.text[:120]}"
            for op in bad
        ]

    # ---- pass 2: collective census -----------------------------------
    @functools.cached_property
    def _pcg_body_summary(self) -> Tuple[
            List[hlo.HloOp], Dict[str, int], float]:
        body, census, bytes_moved = pcg_body_collective_summary(
            self.compiled_ops, self.spec.world)
        surf = self.spec.bf16_surface
        if surf is not None and surf.collectives and body:
            repriced = self._declared_payload_bytes(body)
            if repriced is not None:
                bytes_moved = repriced
        return body, census, bytes_moved

    def _declared_payload_bytes(self, body) -> Optional[float]:
        """Ring-model PCG-body bytes priced at the DECLARED (StableHLO)
        payload dtype, replica-group structure from the compiled op.

        Pairs every compiled in-body collective with a StableHLO
        collective at while depth >= 2 (the PCG while body — the LM
        loop is depth 1) by (kind, element count).  Returns None when
        the pairing is incomplete — `bf16_surface_violations` raises
        that as an explicit violation, so the byte axis can never
        silently fall back to a mis-priced payload.  Why this exists:
        XLA:CPU's float normalization promotes bf16 collectives to f32
        in the compiled executable (probed — see ProgramSpec), so the
        compiled dtype on the audit lane is not the payload a bf16-
        capable backend moves.
        """
        declared = [op for op in self.declared_collective_payloads
                    if op.while_depth >= 2]
        pool: Dict[Tuple[str, Optional[int]], list] = {}
        for op in declared:
            pool.setdefault((op.kind, op.result_elems), []).append(op)
        total = 0.0
        for cop in body:
            cand = pool.get((cop.kind, cop.result_elems))
            if not cand:
                return None
            dop = cand.pop()
            b = (float(cop.result_elems or 0)
                 * hlo.DTYPE_BYTES.get(dop.result_dtype or "", 0))
            total += hlo.collective_bytes_moved(
                dataclasses.replace(cop, result_bytes=b,
                                    result_dtype=dop.result_dtype),
                self.spec.world)
        return total

    def pcg_body_collectives(self) -> List[hlo.HloOp]:
        return self._pcg_body_summary[0]

    def pcg_body_kind_census(self) -> Dict[str, int]:
        """kind -> count of the collectives inside the PCG while body."""
        return self._pcg_body_summary[1]

    def pcg_body_collective_bytes(self) -> float:
        """Ring-model bytes moved per device per CG step: the sum of
        `hlo.collective_bytes_moved` over the PCG body's collectives —
        the budget gate's `collective_bytes_per_sp` axis."""
        return self._pcg_body_summary[2]

    def collective_violations(self) -> List[str]:
        out: List[str] = []
        if self.spec.world == 1:
            for op in self.collectives:
                out.append(
                    f"{self.spec.name}: collective in a single-device "
                    f"program — {op.where()}")
            return out
        allowed = frozenset(self.spec.allowed_kinds)
        bad_kind = [op for op in self.collectives if op.kind not in allowed]
        for op in bad_kind:
            out.append(
                f"{self.spec.name}: unexpected collective kind "
                f"(allowed: {sorted(allowed)}) — {op.where()}")
        pcg = self.pcg_body_collectives()
        n_ar = sum(1 for op in pcg if op.kind == "all_reduce")
        # Single source of truth for the all-reduce expectation: the
        # full kind census when the spec pins one (the 2-D program),
        # the scalar pcg_psums otherwise — never two hand-synced pins.
        want_ar = (dict(self.spec.pcg_body_census).get("all_reduce", 0)
                   if self.spec.pcg_body_census is not None
                   else self.spec.pcg_psums)
        if n_ar != want_ar:
            ops = "\n".join(f"    {op.where()}" for op in pcg) or "    (none)"
            out.append(
                f"{self.spec.name}: {n_ar} all-reduce(s) inside the "
                f"PCG while body, analytic expectation is "
                f"{want_ar} per CG step "
                f"(MegBA per-iteration collective pattern):\n{ops}")
        if self.spec.pcg_body_census is not None:
            want = dict(self.spec.pcg_body_census)
            got = self.pcg_body_kind_census()
            if got != want:
                out.append(
                    f"{self.spec.name}: PCG-body collective census "
                    f"{got} != pinned expectation {want} — the "
                    "per-iteration communication pattern changed")
        if self.spec.pcg_subgroup_only:
            for op in pcg:
                g = op.group_size(self.spec.world)
                if g is None:
                    out.append(
                        f"{self.spec.name}: PCG-body collective carries "
                        f"no parseable replica groups (cannot certify "
                        f"subgroup scope) — {op.where()}")
                elif g >= self.spec.world:
                    out.append(
                        f"{self.spec.name}: WORLD-spanning collective "
                        f"(group size {g} of world {self.spec.world}) "
                        f"inside the PCG body — the 2-D mesh contract "
                        f"is subgroup-scoped stages — {op.where()}")
        return out

    # ---- pass 3: dtype census + donation + bf16 surface --------------
    def dtype_violations(self) -> List[str]:
        census = hlo.dtype_census(self.stablehlo)
        out: List[str] = []
        wrongs = _WRONG_FAMILY[self.spec.float_family]
        if self.spec.bf16_surface is not None:
            # bf16 is the declared surface, not a leak; f64/f16 stay
            # wrong, and the surface pass polices WHERE bf16 appears.
            wrongs = tuple(w for w in wrongs if w != "bf16")
        for wrong in wrongs:
            n = census.get(wrong, 0)
            if not n:
                continue
            sites = hlo.lines_with_dtype(self.stablehlo, wrong, limit=3)
            where = "\n".join(f"    line {ln}: {txt[:140]}"
                              for ln, txt in sites)
            out.append(
                f"{self.spec.name}: {n} {wrong} tensor occurrence(s) in "
                f"a {self.spec.float_family} solve (dtype leak):\n{where}")
        return out

    def bf16_surface_violations(self) -> List[str]:
        """The allowed-bf16-surface pass (specs with `bf16_surface`).

        Without a declared surface this pass is empty — any bf16 then
        already fails the wrong-family census above.  With one, four
        contracts are enforced (Bf16Surface docstring): kind
        allow-list, f32 accumulation, converts confined to bf16<->f32,
        and the presence of actual bf16 compute (the silent-upcast
        guard).  Under `collectives=True` the declared in-body
        payloads must ALSO all be bf16 and pair 1:1 with the compiled
        census — otherwise the halved `collective_bytes_per_sp` the
        budget pins would be priced off a payload the program never
        declared.
        """
        surf = self.spec.bf16_surface
        if surf is None:
            return []
        name = self.spec.name
        allowed = frozenset(surf.allowed_kinds)
        out: List[str] = []
        compute = 0
        # Collectives are detected through the payload scanner, NOT the
        # per-line bf16 scan: a region-form all_reduce's op line does
        # not carry its payload type (it sits on the region-closing
        # line), so a line scan would see only the scalar region add.
        for p in self.declared_collective_payloads:
            if p.result_dtype == "bf16" and not surf.collectives:
                out.append(
                    f"{name}: bf16 collective payload without a "
                    f"declared bf16_collectives surface — line {p.line}")
        for op in hlo.bf16_stablehlo_ops(self.stablehlo):
            if op.kind in hlo.COLLECTIVE_KINDS:
                continue  # payload-checked above
            if op.kind == "add" and op.result_scalar and surf.collectives:
                continue  # a declared collective's reduction region
            if op.kind in _BF16_ACCUM_KINDS:
                if op.kind == "dot_general":
                    if op.result_dtype == "bf16":
                        out.append(
                            f"{name}: dot_general ACCUMULATES in bf16 "
                            f"(preferred_element_type dropped?) — line "
                            f"{op.line}: {op.text[:120]}")
                    else:
                        compute += 1
                    continue
                out.append(
                    f"{name}: bf16 accumulation ({op.kind}) — the "
                    f"surface contract is bf16 storage with f32 "
                    f"accumulation — line {op.line}: {op.text[:120]}")
                continue
            if op.kind == "convert":
                # Only FLOAT-family crossings are leaks (f64/f16 would
                # smuggle a different precision family in); an integer
                # operand cast to bf16 (the 2-D tile masks) is exact.
                bad = [d for d in op.dtypes
                       if d not in ("bf16", "f32")
                       and (d.startswith("f") or d.startswith("c"))]
                if bad:
                    out.append(
                        f"{name}: convert crosses bf16<->{bad[0]} "
                        f"(family leak; only bf16<->f32 is on the "
                        f"surface) — line {op.line}: {op.text[:120]}")
                continue
            if op.kind not in allowed:
                out.append(
                    f"{name}: bf16 tensor in op kind {op.kind!r} "
                    f"outside the declared surface — line {op.line}: "
                    f"{op.text[:120]}")
                continue
            if op.kind == "multiply" and op.result_dtype == "bf16":
                compute += 1
        if compute < surf.min_compute_ops:
            out.append(
                f"{name}: declared bf16 surface carries only {compute} "
                f"bf16 compute op(s) (< {surf.min_compute_ops}) — the "
                "operands were silently upcast and the program runs "
                "f32 math under a bf16 flag")
        if surf.collectives:
            body = self.pcg_body_collectives()
            if body and self._declared_payload_bytes(body) is None:
                out.append(
                    f"{name}: compiled PCG-body collectives could not "
                    "be paired with declared StableHLO payloads — the "
                    "byte axis cannot certify the bf16 wire payload")
            # Every in-body declared payload must be bf16.  SCOPE NOTE:
            # this assumes the edge-local preconditioner families
            # (JACOBI/NEUMANN/SCHUR_DIAG — the current bf16 canonical
            # programs), where every in-body collective belongs to the
            # compressed S·p matvec.  A future bf16 canonical program
            # with a TWO_LEVEL/MULTILEVEL precond would carry
            # legitimate FULL-precision coarse-correction psums in the
            # body (the documented contract — solver/pcg.py): scope
            # this check to the matvec's census before declaring such
            # a spec, or it will flag the f32 coarse payloads.
            declared = [op for op in self.declared_collective_payloads
                        if op.while_depth >= 2]
            for op in declared:
                if op.result_dtype != "bf16":
                    out.append(
                        f"{name}: in-body collective declares a "
                        f"{op.result_dtype} payload under a "
                        f"bf16-collectives surface (compression "
                        f"dropped) — line {op.line}")
        return out

    def donation_violations(self) -> List[str]:
        got = hlo.aliased_parameters(self.compiled_text)
        want = frozenset(self.spec.donate_leaves)
        out: List[str] = []
        missing = sorted(want - got)
        if missing:
            out.append(
                f"{self.spec.name}: declared donation of parameter(s) "
                f"{missing} did not materialise as input-output aliasing "
                "in the compiled executable (buffer savings silently "
                "lost; did an output stop aliasing its input?)")
        unexpected = sorted(got - want)
        if unexpected:
            out.append(
                f"{self.spec.name}: parameter(s) {unexpected} alias "
                "outputs without a declared donation (audit expectation "
                "out of date — update ProgramSpec.donate_leaves)")
        return out

    # ---- pass 4: budget metrics --------------------------------------
    def metrics(self) -> Dict[str, float]:
        other = [op for op in self.collectives if op.kind != "all_reduce"]
        out = {
            "flops": float(self.flops),
            "bytes_accessed": float(self.bytes_accessed),
            "peak_temp_bytes": float(self.peak_temp_bytes),
            "argument_bytes": float(self.argument_bytes),
            "output_bytes": float(self.output_bytes),
        }
        # A backend without cost/memory analysis yields -1 sentinels:
        # OMIT those rather than letting "-1" flow into the budget gate
        # as a measurement (budget.compare reports a gated metric that
        # went missing, so the gate degrades loudly, not silently).
        out = {k: v for k, v in out.items() if v >= 0.0}
        out["all_reduce_count"] = float(
            sum(1 for op in self.collectives if op.kind == "all_reduce"))
        out["other_collective_count"] = float(len(other))
        # Bytes-moved-per-iteration axis (ROADMAP item 3): ring-model
        # bytes each device moves per CG step (the PCG body executes
        # once per iteration), from per-op operand bytes x replica-group
        # shape.  Exact-match gated (budget.TOLERANCES) so an overlap /
        # subgroup win is PINNED, not anecdotal — and a fatter
        # collective sneaking into the body fails audit --check.
        out["collective_bytes_per_sp"] = self.pcg_body_collective_bytes()
        # Declared analytical axes (analysis/edge_budget.py): priced
        # from the spec, not measured from the backend, so they are
        # present — and exact-gated — even when cost_analysis is not.
        if self.spec.sp_budget is not None:
            for k, v in self.spec.sp_budget:
                out[k] = float(v)
        return out

    def violations(self) -> List[str]:
        return (self.transfer_violations() + self.collective_violations()
                + self.dtype_violations() + self.bf16_surface_violations()
                + self.donation_violations())

    def summary(self) -> Dict[str, object]:
        """JSON-able per-program audit summary (for bench.py and
        SolveReport embedding)."""
        pcg = self.pcg_body_collectives()
        return {
            "program": self.spec.name,
            "metrics": self.metrics(),
            "pcg_body_all_reduces": sum(
                1 for op in pcg if op.kind == "all_reduce"),
            "pcg_body_census": self.pcg_body_kind_census(),
            # Opaque-code census: every custom_call target in the
            # StableHLO, counted.  A Pallas kernel lowers to one
            # (tpu_custom_call) on TPU; the canonical fused-OFF
            # programs must stay kernel-free here (dark-launch pin,
            # tests/test_fused.py).
            "custom_calls": hlo.custom_call_census(self.stablehlo_ops),
            "collectives": [
                {"kind": op.kind, "elems": op.result_elems,
                 "dtype": op.result_dtype, "scope": op.op_name,
                 "group_size": op.group_size(self.spec.world),
                 "bytes_moved": hlo.collective_bytes_moved(
                     op, self.spec.world)}
                for op in self.collectives
            ],
            "violations": self.violations(),
        }


# --------------------------------------------------------------------------
# Canonical programs.  Sizes are deliberately tiny (lowering cost, not
# solve cost, dominates) but non-degenerate: enough edges to pad to one
# EDGE_QUANTUM per shard, both loops live, every psum site reachable.
# --------------------------------------------------------------------------

def _ba_problem():
    from megba_tpu.io.synthetic import make_synthetic_bal

    return make_synthetic_bal(
        num_cameras=4, num_points=24, obs_per_point=3, seed=0,
        param_noise=4e-2, pixel_noise=0.3, dtype=np.float32)


def _ba_option():
    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption

    return ProblemOption(
        dtype=np.float32,
        algo_option=AlgoOption(max_iter=3),
        solver_option=SolverOption(max_iter=8, tol=1e-8))


def _ba_ml_problem():
    # The multilevel canonical program needs a camera graph big enough
    # to plan >= 2 coarse levels (the 4-camera problem aggregates to 2
    # clusters, under the hierarchy's own coarsest floor) — a small
    # RING-locality scene, the structure the operator targets.
    from megba_tpu.io.synthetic import make_synthetic_bal

    return make_synthetic_bal(
        num_cameras=12, num_points=60, obs_per_point=3, seed=0,
        param_noise=4e-2, pixel_noise=0.3, dtype=np.float32,
        locality="ring")


def _lower_ba(world: int, use_tiled: bool, forcing: bool = False,
              guarded: bool = False, twolevel: bool = False,
              multilevel: bool = False, mesh2d: bool = False,
              bf16: bool = False):
    import dataclasses as _dc

    from megba_tpu.common import JacobianMode, RobustOption, SolverOption
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    s = _ba_ml_problem() if multilevel else _ba_problem()
    option = _ba_option()
    if world > 1:
        option = _dc.replace(option, world_size=world)
    if mesh2d:
        # 2-D mesh canonical program: world 4 factored 2x2 — the
        # subgroup-collective matvec pipeline on the SAME tiny problem
        # as the 1-D programs, so the bytes-moved axis is comparable
        # operand-for-operand.
        option = _dc.replace(option, solver_option=_dc.replace(
            option.solver_option, mesh_2d=True, cam_blocks=2))
    if forcing:
        # Inexact-LM canonical program: adaptive Eisenstat-Walker
        # forcing (eta_k a traced while-carry scalar) + warm starts.
        option = _dc.replace(option, solver_option=SolverOption(
            max_iter=8, tol=1e-1, forcing=True, warm_start=True))
    if guarded:
        # Fault-containment canonical program: LM rollback/recovery +
        # PCG breakdown restarts armed (robustness layer).
        option = _dc.replace(option, robust_option=RobustOption(guards=True))
    if twolevel:
        # Two-level preconditioner canonical program: the camera-graph
        # coarse space rides as a DeviceClusterPlan operand (flat_solve
        # plans + caches it) and the cycle runs inside the fused PCG
        # body (solver/precond.py).
        from megba_tpu.common import PrecondKind

        option = _dc.replace(option, solver_option=_dc.replace(
            option.solver_option, precond=PrecondKind.TWO_LEVEL))
    if multilevel:
        # Recursive-hierarchy canonical program: the
        # DeviceMultiLevelPlan operand carries the level-1 cluster plan
        # + coarse assignment chain; every per-level Galerkin build
        # (edge-scale level 1, dense above) lives OUTSIDE pcg_core.
        from megba_tpu.common import PrecondKind

        option = _dc.replace(option, solver_option=_dc.replace(
            option.solver_option, precond=PrecondKind.MULTILEVEL,
            coarsen_factor=2.0, max_levels=3))
    if bf16:
        # bf16 MXU pipeline canonical programs: storage + collective
        # gates BOTH on — the full rung, so the allowed-surface pass
        # and the halved bytes axis are pinned together.
        option = _dc.replace(option, solver_option=_dc.replace(
            option.solver_option, bf16=True, bf16_collectives=True))
    f = make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF)
    return flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx,
                      option, use_tiled=use_tiled, lower_only=True)


def _lower_batched(lanes: int):
    """The serving layer's batched mega-solve (vmapped LM, lane axis 4).

    Lowered through the compile pool's own AOT entry point
    (serving/compile_pool.lower_bucket) — the same builder, operand
    layout and donation flags every fleet dispatch uses — at the shape
    class the canonical tiny BA problem buckets to under the default
    ladder."""
    from megba_tpu.common import JacobianMode
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.serving.compile_pool import lower_bucket
    from megba_tpu.serving.shape_class import BucketLadder, classify

    s = _ba_problem()
    option = _ba_option()
    shape = classify(s.cameras0.shape[0], s.points0.shape[0],
                     s.obs.shape[0], option.dtype, BucketLadder())
    engine = make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF)
    return lower_bucket(engine, option, shape, lanes,
                        cd=s.cameras0.shape[1], pd=s.points0.shape[1],
                        od=s.obs.shape[1])


def _lower_pgo(world: int):
    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.models.pgo import make_synthetic_pose_graph, solve_pgo

    g = make_synthetic_pose_graph(num_poses=16, loop_closures=4, seed=1)
    option = ProblemOption(
        dtype=np.float64, world_size=world,
        algo_option=AlgoOption(max_iter=3),
        solver_option=SolverOption(max_iter=8, tol=1e-10))
    return solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, option,
                     lower_only=True)


def _lower_factor(factor: str, dtype=np.float32):
    """Canonical program of one registered Schur factor family, lowered
    through flat_solve's registry dispatch (the production seam every
    factor solve rides — engine resolution included)."""
    import dataclasses as _dc

    from megba_tpu.solve import flat_solve

    if factor == "rig":
        from megba_tpu.factors.rig import make_synthetic_rig

        s = make_synthetic_rig(num_bodies=4, num_points=24, seed=0,
                               dtype=dtype)
    elif factor == "pinhole_radial":
        from megba_tpu.factors.radial import make_synthetic_radial

        s = make_synthetic_radial(num_cameras=4, num_points=24, seed=0,
                                  dtype=dtype)
    elif factor == "pose_prior":
        from megba_tpu.factors.priors import make_synthetic_priors

        s = make_synthetic_priors(num_poses=8, seed=0, dtype=dtype)
    else:
        raise ValueError(f"no canonical problem for factor {factor!r}")
    option = _dc.replace(_ba_option(), dtype=dtype)
    return flat_solve(None, s.cameras0, s.points0, s.obs, s.cam_idx,
                      s.pt_idx, option, use_tiled=False, factor=factor,
                      lower_only=True)


def _lower_sim3(world: int):
    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.factors.sim3 import make_synthetic_sim3_graph
    from megba_tpu.models.pgo import solve_pgo

    g = make_synthetic_sim3_graph(num_poses=16, loop_closures=4, seed=1)
    option = ProblemOption(
        dtype=np.float64, world_size=world,
        algo_option=AlgoOption(max_iter=3),
        solver_option=SolverOption(max_iter=8, tol=1e-10))
    return solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, option,
                     factor="sim3_between", lower_only=True)


def _sharded_donation() -> Tuple[int, ...]:
    # Donation of the replicated parameter blocks is gated off under the
    # experimental shard_map fallback (freed-buffer aliasing hazard —
    # parallel/mesh.py); the audit expects exactly what production does.
    from megba_tpu.parallel.mesh import SHARD_MAP_NATIVE

    return (0, 1) if SHARD_MAP_NATIVE else ()


def _pgo_sharded_donation() -> Tuple[int, ...]:
    from megba_tpu.parallel.mesh import SHARD_MAP_NATIVE

    return (0,) if SHARD_MAP_NATIVE else ()


# --------------------------------------------------------------------------
# Declared per-S·p budgets (edge_budget.py pricing over the SAME host
# planning the lowering runs — the lru caches make the later build a
# plan-cache hit, so the audit never plans twice).  Everything is
# derived live: if the quantum, a tile plan, or the bucket ladder
# changes, the priced number moves WITH the program and the committed
# ANALYSIS_BUDGET.json entry fails exact-match, naming the drift.
# --------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _sp_budget_ba(world: int, use_tiled: bool, mesh2d: bool = False,
                  bf16: bool = False, multilevel: bool = False,
                  lanes: int = 1,
                  factor: Optional[str] = None,
                  ) -> Tuple[Tuple[str, float], ...]:
    from megba_tpu.analysis import edge_budget
    from megba_tpu.core.fm import EDGE_QUANTUM

    fam = "f32"
    rd = 2  # BAL / rig / radial pinhole residual rows
    if factor == "rig":
        from megba_tpu.factors.rig import make_synthetic_rig

        s = make_synthetic_rig(num_bodies=4, num_points=24, seed=0,
                               dtype=np.float32)
    elif factor == "pinhole_radial":
        from megba_tpu.factors.radial import make_synthetic_radial

        s = make_synthetic_radial(num_cameras=4, num_points=24, seed=0,
                                  dtype=np.float32)
    elif factor == "pose_prior":
        from megba_tpu.factors.priors import make_synthetic_priors

        s = make_synthetic_priors(num_poses=8, seed=0, dtype=np.float64)
        fam, rd = "f64", 6
    else:
        s = _ba_ml_problem() if multilevel else _ba_problem()
    nc, cd = s.cameras0.shape
    npts, pd = s.points0.shape
    ne = s.obs.shape[0]
    if lanes > 1:
        # The batched program solves at its BUCKET shape (the compile
        # pool's ladder), not the raw problem shape.
        from megba_tpu.serving.shape_class import BucketLadder, classify

        shape = classify(nc, npts, ne, np.float32, BucketLadder())
        nc, npts, ne = shape.n_cam, shape.n_pt, shape.n_edge
    if mesh2d:
        from megba_tpu.ops.segtiles import cached_camera_tile_plan
        from megba_tpu.parallel.mesh import factor_mesh_2d

        n_shards, n_blocks = factor_mesh_2d(world, 2)
        (tplan, _), _ = cached_camera_tile_plan(
            s.cam_idx, s.pt_idx, nc, npts, n_shards, n_blocks)
        slots = tplan.perm.shape[0] // world  # one (shard, block) cell
    elif use_tiled:
        from megba_tpu.ops.segtiles import cached_dual_plans

        (plan_c, _), _ = cached_dual_plans(s.cam_idx, s.pt_idx, nc, npts)
        slots = plan_c.n_slots
    else:
        q = world * EDGE_QUANTUM
        slots = (-(-ne // q) * q) // world
    b = edge_budget.schur_sp_budget(
        nc, cd, npts, pd, rd, slots,
        operand="bf16" if bf16 else fam, param=fam, acc=fam, lanes=lanes)
    return tuple(sorted(b.items()))


@functools.lru_cache(maxsize=None)
def _sp_budget_pgo(world: int,
                   pose_dim: int = 6) -> Tuple[Tuple[str, float], ...]:
    # The canonical pose graphs: 16 poses, 15 odometry + 4 loop edges,
    # padded to a multiple of world (models/pgo.py pads by world, not
    # by EDGE_QUANTUM); residual rows = pose_dim for both SE(3) and
    # Sim(3).
    ne = 19
    slots = (ne + (-ne) % world) // world
    from megba_tpu.analysis import edge_budget

    b = edge_budget.pgo_sp_budget(16, pose_dim, pose_dim, slots)
    return tuple(sorted(b.items()))


def program_specs() -> Dict[str, ProgramSpec]:
    """name -> spec for every canonical audited program."""
    return {
        "ba_single_f32": ProgramSpec(
            name="ba_single_f32", float_family="f32", world=1, pcg_psums=0,
            donate_leaves=(0, 1),
            sp_budget=_sp_budget_ba(world=1, use_tiled=False),
            build=lambda: _lower_ba(world=1, use_tiled=False)),
        "ba_tiled_f32": ProgramSpec(
            name="ba_tiled_f32", float_family="f32", world=1, pcg_psums=0,
            donate_leaves=(0, 1),
            sp_budget=_sp_budget_ba(world=1, use_tiled=True),
            build=lambda: _lower_ba(world=1, use_tiled=True)),
        "ba_sharded_w2_f32": ProgramSpec(
            name="ba_sharded_w2_f32", float_family="f32", world=2,
            # Schur S·p = Hpp p - Hpl Hll^-1 Hlp p: one psum in hlp, one
            # in hpl — exactly two reductions per CG step (solver/pcg.py).
            pcg_psums=2,
            donate_leaves=_sharded_donation(),
            sp_budget=_sp_budget_ba(world=2, use_tiled=False),
            build=lambda: _lower_ba(world=2, use_tiled=False)),
        "ba_forcing_w2_f32": ProgramSpec(
            name="ba_forcing_w2_f32", float_family="f32", world=2,
            # Inexact LM (forcing + warm_start): the adaptive tolerance
            # is a traced carry scalar and the warm-start r0 = b - S x0
            # / recurrence-priming S·u0 products live OUTSIDE the PCG
            # while body, so the per-CG-step census is UNCHANGED —
            # exactly two all-reduces.  Adaptive forcing adding a
            # collective or a host transfer is precisely the regression
            # this spec pins against.
            pcg_psums=2,
            donate_leaves=_sharded_donation(),
            sp_budget=_sp_budget_ba(world=2, use_tiled=False),
            build=lambda: _lower_ba(world=2, use_tiled=False,
                                    forcing=True)),
        "ba_guarded_w2_f32": ProgramSpec(
            name="ba_guarded_w2_f32", float_family="f32", world=2,
            # RobustOption guards: detection reads only the already-
            # psum-reduced scalars (NaN propagates through the existing
            # reductions) and the PCG restart reuses the body's single
            # matvec slot, so the guarded while body carries EXACTLY the
            # same two all-reduces as the unguarded Schur solve — a
            # guard that added a sync or a host transfer is precisely
            # the regression this spec pins against.
            pcg_psums=2,
            donate_leaves=_sharded_donation(),
            sp_budget=_sp_budget_ba(world=2, use_tiled=False),
            build=lambda: _lower_ba(world=2, use_tiled=False,
                                    guarded=True)),
        "ba_twolevel_w2_f32": ProgramSpec(
            name="ba_twolevel_w2_f32", float_family="f32", world=2,
            # Two-level Schur preconditioner: the coarse-space build
            # psums V and G ONCE per PCG solve (outside the while
            # body), and the per-apply cycle is replicated dense work
            # on materialised G/A_c — so the while-BODY census stays
            # exactly two all-reduces per S·p, identical to plain
            # block-Jacobi.  A coarse correction that added an in-body
            # collective (e.g. a naive matrix-free R S Rᵀ apply) is
            # precisely the regression this spec pins against.
            pcg_psums=2,
            donate_leaves=_sharded_donation(),
            sp_budget=_sp_budget_ba(world=2, use_tiled=False),
            build=lambda: _lower_ba(world=2, use_tiled=False,
                                    twolevel=True)),
        "ba_multilevel_w2_f32": ProgramSpec(
            name="ba_multilevel_w2_f32", float_family="f32", world=2,
            # Recursive multilevel Schur preconditioner (3-level
            # hierarchy on a ring-locality scene): the level-1 coarse
            # build psums V and G once per PCG solve and every DEEPER
            # level is a replicated dense Galerkin contraction with
            # ZERO collectives of its own — so the while-BODY census
            # stays exactly two all-reduces per S·p, identical to
            # block-Jacobi and the two-level cycle.  A hierarchy level
            # that added an in-body collective (or a per-level build
            # that slid inside pcg_core) is precisely the regression
            # this spec pins against.
            pcg_psums=2,
            donate_leaves=_sharded_donation(),
            sp_budget=_sp_budget_ba(world=2, use_tiled=False,
                                     multilevel=True),
            build=lambda: _lower_ba(world=2, use_tiled=False,
                                    multilevel=True)),
        "ba_2d_w4_f32": ProgramSpec(
            name="ba_2d_w4_f32", float_family="f32", world=4,
            # 2-D (2 edge shards x 2 camera blocks) mesh: the matvec's
            # two WORLD all-reduces become subgroup stages — one
            # psum_scatter over the camera subgroup + one edge-subgroup
            # psum on the point side, C-1 double-buffered
            # collective_permutes rotating the point shard, and one
            # edge-subgroup psum + camera-subgroup all_gather on the
            # camera side.  Exactly 2 all-reduces remain in the body
            # (both EDGE-subgroup), every body collective is pinned
            # subgroup-scoped (group size 2 < world 4), and the
            # bytes-moved axis must come in strictly below the 1-D
            # all-reduce scaling law (tests/test_program_audit.py
            # asserts the comparison against ba_sharded_w2_f32's law).
            pcg_psums=2,
            donate_leaves=_sharded_donation(),
            allowed_kinds=("all_reduce", "reduce_scatter", "all_gather",
                           "collective_permute"),
            pcg_body_census=(("all_reduce", 2), ("reduce_scatter", 1),
                             ("all_gather", 1), ("collective_permute", 1)),
            pcg_subgroup_only=True,
            sp_budget=_sp_budget_ba(world=4, use_tiled=False,
                                     mesh2d=True),
            build=lambda: _lower_ba(world=4, use_tiled=False,
                                    mesh2d=True)),
        "ba_bf16_w2_f32": ProgramSpec(
            name="ba_bf16_w2_f32", float_family="f32", world=2,
            # The bf16 MXU pipeline on the 1-D mesh: per-edge products
            # on bf16 operands with f32 accumulation, bf16 M⁻¹ apply,
            # and bf16 in-body collective payloads.  The body census
            # stays exactly two all-reduces per S·p (the textbook-
            # recurrence body has the same matvec-only collective
            # site); the allowed-surface pass pins bf16 to the
            # declared op kinds with f32 accumulation, and the budget
            # entry pins `collective_bytes_per_sp` at exactly HALF
            # ba_sharded_w2_f32's (tests/test_program_audit.py asserts
            # the ratio) — priced at the DECLARED payload (see
            # bf16_surface field note).
            pcg_psums=2,
            donate_leaves=_sharded_donation(),
            bf16_surface=Bf16Surface(collectives=True),
            sp_budget=_sp_budget_ba(world=2, use_tiled=False,
                                     bf16=True),
            build=lambda: _lower_ba(world=2, use_tiled=False,
                                    bf16=True)),
        "ba_bf16_2d_w4_f32": ProgramSpec(
            name="ba_bf16_2d_w4_f32", float_family="f32", world=4,
            # The bf16 pipeline composed with PR 14's 2-D mesh: the
            # same subgroup-scoped five-collective census as
            # ba_2d_w4_f32, every payload bf16 on the wire — the
            # budget entry pins the bytes axis at exactly half the f32
            # 2-D program's.  This is the pod-scale configuration the
            # rung exists for: subgroup scoping divides the payload by
            # the mesh factor, bf16 halves what remains.
            pcg_psums=2,
            donate_leaves=_sharded_donation(),
            allowed_kinds=("all_reduce", "reduce_scatter", "all_gather",
                           "collective_permute"),
            pcg_body_census=(("all_reduce", 2), ("reduce_scatter", 1),
                             ("all_gather", 1), ("collective_permute", 1)),
            pcg_subgroup_only=True,
            bf16_surface=Bf16Surface(collectives=True),
            sp_budget=_sp_budget_ba(world=4, use_tiled=False,
                                     mesh2d=True, bf16=True),
            build=lambda: _lower_ba(world=4, use_tiled=False,
                                    mesh2d=True, bf16=True)),
        "ba_batched_b4_f32": ProgramSpec(
            name="ba_batched_b4_f32", float_family="f32", world=1,
            # The batched program is a vmap over a LANE axis on one
            # device: per-lane convergence masking is pure selects, so
            # a collective (or a host transfer) appearing here means
            # the serving layer broke the fleet contract.
            pcg_psums=0,
            # The batcher donates the stacked parameter lanes
            # (compile_pool._build_batched_solve donate_argnums=(0, 1)).
            donate_leaves=(0, 1),
            sp_budget=_sp_budget_ba(world=1, use_tiled=False, lanes=4),
            build=lambda: _lower_batched(lanes=4)),
        # ---- factor-registry canonical programs ----------------------
        # One per new family (ISSUE 13): each is lowered through the
        # registry seam itself (flat_solve(factor=...) / solve_pgo
        # (factor=...)), so the audited program IS what a registered-
        # factor solve dispatches — a registry refactor that changed
        # the lowering, leaked a dtype through a new residual, or added
        # a collective fails this gate, exactly like the BAL/PGO
        # originals.
        "ba_rig_single_f32": ProgramSpec(
            name="ba_rig_single_f32", float_family="f32", world=1,
            # Single device: the rig's shared-body-block Schur solve
            # must carry zero collectives like every single-device
            # program.
            pcg_psums=0,
            donate_leaves=(0, 1),
            sp_budget=_sp_budget_ba(world=1, use_tiled=False,
                                     factor="rig"),
            build=lambda: _lower_factor("rig")),
        "ba_radial_single_f32": ProgramSpec(
            name="ba_radial_single_f32", float_family="f32", world=1,
            pcg_psums=0,
            donate_leaves=(0, 1),
            sp_budget=_sp_budget_ba(world=1, use_tiled=False,
                                     factor="pinhole_radial"),
            build=lambda: _lower_factor("pinhole_radial")),
        "prior_single_f64": ProgramSpec(
            name="prior_single_f64", float_family="f64", world=1,
            # The unary-prior family runs f64 (its GPS/marginalization
            # use cases are precision-sensitive), exercising the
            # inverse dtype census on a registry factor: an f32 leak in
            # the prior residual's rotation chain fails here.
            pcg_psums=0,
            donate_leaves=(0, 1),
            sp_budget=_sp_budget_ba(world=1, use_tiled=False,
                                     factor="pose_prior"),
            build=lambda: _lower_factor("pose_prior", np.float64)),
        "pgo_sim3_single_f64": ProgramSpec(
            name="pgo_sim3_single_f64", float_family="f64", world=1,
            # The sim(3) family rides the genericized PGO driver; its
            # 7-dof blocks must lower collective-free on one device
            # exactly like the SE(3) program.
            pcg_psums=0,
            donate_leaves=(0,),
            sp_budget=_sp_budget_pgo(world=1, pose_dim=7),
            build=lambda: _lower_sim3(world=1)),
        "pgo_single_f64": ProgramSpec(
            name="pgo_single_f64", float_family="f64", world=1, pcg_psums=0,
            donate_leaves=(0,),
            sp_budget=_sp_budget_pgo(world=1),
            build=lambda: _lower_pgo(world=1)),
        "pgo_sharded_w2_f64": ProgramSpec(
            name="pgo_sharded_w2_f64", float_family="f64", world=2,
            # PGO's matrix-free H·x has a single segment-reduce psum
            # (models/pgo.py matvec) — one reduction per CG step.
            pcg_psums=1,
            donate_leaves=_pgo_sharded_donation(),
            sp_budget=_sp_budget_pgo(world=2),
            build=lambda: _lower_pgo(world=2)),
    }


def audit_program(spec: ProgramSpec,
                  lowered: Optional[object] = None) -> ProgramAudit:
    """Lower (unless given), compile, and census one canonical program."""
    lowered = spec.build() if lowered is None else lowered
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jaxlib: one dict per device
        ca = ca[0] if ca else {}
    try:
        mem = compiled.memory_analysis()
    except Exception:  # backend without memory analysis
        mem = None
    return ProgramAudit(
        spec=spec,
        stablehlo=lowered.as_text(),
        compiled_text=compiled.as_text(),
        flops=float(ca.get("flops", -1.0)),
        bytes_accessed=float(ca.get("bytes accessed", -1.0)),
        peak_temp_bytes=float(
            getattr(mem, "temp_size_in_bytes", -1) if mem else -1),
        argument_bytes=float(
            getattr(mem, "argument_size_in_bytes", -1) if mem else -1),
        output_bytes=float(
            getattr(mem, "output_size_in_bytes", -1) if mem else -1),
    )


def audit_all(names: Optional[List[str]] = None) -> Dict[str, ProgramAudit]:
    specs = program_specs()
    if names:
        unknown = sorted(set(names) - set(specs))
        if unknown:
            raise ValueError(
                f"unknown program(s) {unknown}; known: {sorted(specs)}")
        specs = {n: specs[n] for n in names}
    return {name: audit_program(spec) for name, spec in specs.items()}
