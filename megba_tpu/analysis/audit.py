"""Compiled-program audit CLI: `python -m megba_tpu.analysis.audit`.

Gate 4 of scripts/lint.sh.  Lowers + compiles the canonical solver
programs on the CPU backend (tiny synthetic problems, no solver
execution) and runs the four audit passes of
analysis/program_audit.py; with `--check` (the default) the budget pass
compares against the committed ANALYSIS_BUDGET.json, with `--update` it
re-baselines after an intentional change.

Exit status: 0 clean, 1 violations / budget drift, 2 usage error.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Optional, Sequence


_DEVICE_COUNT_RE = re.compile(
    r"--?xla_force_host_platform_device_count=(\d+)")


def ensure_host_device_floor(flags: str, floor: int) -> str:
    """XLA_FLAGS with `--xla_force_host_platform_device_count` raised
    to at least `floor`: appended when absent, rewritten when a pre-set
    value is lower (e.g. the 2 this module exported before ba_2d_w4_f32
    existed, persisted in a dev shell or CI env), left alone when
    already sufficient.  Shared with bench.py's MEGBA_BENCH_MESH2D
    knob, which needs the same raise-to-floor before backend init."""
    m = _DEVICE_COUNT_RE.search(flags)
    if m is None:
        return (flags +
                f" --xla_force_host_platform_device_count={floor}").strip()
    if int(m.group(1)) < floor:
        return (flags[:m.start()] +
                f"--xla_force_host_platform_device_count={floor}" +
                flags[m.end():])
    return flags


def _ensure_cpu_env() -> None:
    """Pin the audit to the CPU backend with >= 4 virtual devices.

    jax is typically already *imported* here (the package __init__ pulls
    it), but the backend initialises lazily at the first device query:
    until then XLA_FLAGS (read at client creation) and
    `jax.config.jax_platforms` still take effect.  Once a backend
    exists (the pytest path — conftest configured 8 CPU devices + x64,
    which satisfies the audit) this is a no-op.
    """
    try:
        from jax._src import xla_bridge

        if xla_bridge._backends:
            return  # backend already up; caller's device config rules
    except Exception:
        pass
    # 4 devices: the 2-D canonical program (ba_2d_w4_f32) lowers on a
    # 2x2 mesh; the w2 programs use the first two.
    os.environ["XLA_FLAGS"] = ensure_host_device_floor(
        os.environ.get("XLA_FLAGS", ""), 4)
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m megba_tpu.analysis.audit",
        description="MegBA-TPU compiled-program auditor "
                    "(HLO transfer/collective/dtype census + AOT budget)")
    mode = parser.add_mutually_exclusive_group()
    mode.add_argument("--check", action="store_true",
                      help="fail on budget drift vs ANALYSIS_BUDGET.json "
                           "(default)")
    mode.add_argument("--update", action="store_true",
                      help="re-baseline ANALYSIS_BUDGET.json from this "
                           "run's measurements")
    parser.add_argument("--baseline", metavar="PATH", default=None,
                        help="baseline JSON path (default: the committed "
                             "ANALYSIS_BUDGET.json at the repo root)")
    parser.add_argument("--program", action="append", dest="programs",
                        metavar="NAME",
                        help="audit only this canonical program "
                             "(repeatable)")
    parser.add_argument("--summary", action="store_true",
                        help="print per-program JSON summaries")
    args = parser.parse_args(argv)

    _ensure_cpu_env()
    import jax

    if not jax.config.jax_enable_x64:
        # The f64 canonical programs (and weak-literal leaks) only exist
        # under x64; without it the dtype census would vacuously pass.
        jax.config.update("jax_enable_x64", True)
    from megba_tpu.utils.backend import enable_persistent_compile_cache

    enable_persistent_compile_cache()

    from megba_tpu.analysis import budget as budget_mod
    from megba_tpu.analysis import program_audit

    try:
        audits = program_audit.audit_all(args.programs)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    failures = []
    measured = {}
    for name in sorted(audits):
        audit = audits[name]
        bad = audit.violations()
        measured[name] = audit.metrics()
        status = "FAIL" if bad else "ok"
        census = audit.pcg_body_kind_census()
        pcg = census.get("all_reduce", 0)
        extra = {k: v for k, v in census.items() if k != "all_reduce"}
        extra_s = f", pcg_body_extra={extra}" if extra else ""
        print(f"[audit] {name}: {status} "
              f"(flops={audit.flops:.3g}, bytes={audit.bytes_accessed:.3g}, "
              f"temp={audit.peak_temp_bytes:.3g}, "
              f"pcg_body_all_reduces={pcg}, "
              f"bytes_per_sp={measured[name]['collective_bytes_per_sp']:g}"
              f"{extra_s})")
        failures.extend(bad)
        if args.summary:
            import json

            print(json.dumps(audit.summary(), sort_keys=True))

    if args.update:
        meta = {"jax": jax.__version__,
                "note": "regenerate with `python -m megba_tpu.analysis."
                        "audit --update` after intentional changes"}
        if args.programs:
            # Partial update: merge into the existing baseline so the
            # unaudited programs keep their committed numbers.
            merged = budget_mod.load_baseline(args.baseline)
            merged.update(measured)
            measured = merged
        path = budget_mod.write_baseline(measured, args.baseline, meta=meta)
        print(f"[audit] baseline written: {path}")
    else:
        baseline = budget_mod.load_baseline(args.baseline)
        if not baseline:
            failures.append(
                "no ANALYSIS_BUDGET.json baseline found — run "
                "`python -m megba_tpu.analysis.audit --update` and commit "
                "the result")
        else:
            if args.programs:
                baseline = {n: v for n, v in baseline.items()
                            if n in measured}
            failures.extend(budget_mod.compare(baseline, measured))

    for f in failures:
        print(f"AUDIT VIOLATION: {f}", file=sys.stderr)
    if failures:
        print(f"{len(failures)} audit violation(s)", file=sys.stderr)
        return 1
    print("[audit] all programs within contract and budget")
    return 0


if __name__ == "__main__":
    sys.exit(main())
