"""AST module index + jit-reachability call graph for the linter.

Pure standard library (ast) — the linter must run in CI before any heavy
import, so nothing here imports jax or numpy, and nothing ever executes
the code under analysis.

The model is deliberately simple and conservative:

- Every `def` in the package is indexed under a dotted qualname
  (`megba_tpu.algo.lm.lm_solve`, `megba_tpu.solve._build_single_solve.fn`).
- A function is a *jit entry* when it (a) is decorated with `jax.jit` /
  `functools.partial(jax.jit, ...)`, (b) is passed by name into a call
  whose callee ends in `jit` or `shard_map`, or (c) carries an inline
  `# megba: jit-entry` pragma on its `def` line (for engines that only
  ever arrive inside a jitted computation through a parameter, e.g. the
  residual engines `make_residual_jacobian_fn` hands to `flat_solve`).
- Reachability: any *reference* (not just call) from a reachable
  function's body to another indexed function marks that function
  reachable — this over-approximates calls, which is exactly right for
  a linter: functions passed to `lax.while_loop` / `lax.cond` / `vmap`
  inside a jitted body are traced even though they are never "called"
  by name.
- Each function additionally carries its raw attribute-read sets
  (`FunctionInfo.attr_reads`: root name -> full dotted chains read off
  it) and its simple-alias assignments (`FunctionInfo.assigns`) — the
  per-function field-read pass the program-identity lane
  (analysis/identity.py) resolves against named option parameters.

Resolution is lexical: local defs, enclosing defs, module-level defs,
then imports (`from megba_tpu.algo.lm import lm_solve` and
`from megba_tpu.parallel import mesh; mesh.get_or_build_program` both
resolve).  Anything unresolvable is silently ignored — the linter never
guesses.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

PRAGMA_RE = re.compile(r"#\s*megba:\s*([a-zA-Z0-9_,\s-]+)")

_JIT_WRAP_NAMES = {"jit", "shard_map"}


def pragmas_on_line(source_lines: List[str], lineno: int) -> Set[str]:
    """Inline `# megba: tok[, tok...]` tokens on a 1-based physical line."""
    if not (1 <= lineno <= len(source_lines)):
        return set()
    m = PRAGMA_RE.search(source_lines[lineno - 1])
    if not m:
        return set()
    return {t.strip() for t in m.group(1).replace(",", " ").split() if t.strip()}


@dataclasses.dataclass
class FunctionInfo:
    qualname: str  # module-dotted path, nesting flattened with "."
    module: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    parent: Optional[str]  # enclosing function qualname
    children: List[str] = dataclasses.field(default_factory=list)
    refs: Set[str] = dataclasses.field(default_factory=set)
    is_entry: bool = False
    # Dotted qualname of the innermost enclosing class, when this def is
    # a method (None for plain functions).  Lets `self.method()` calls
    # resolve to the defining class — the cross-method lock edges the
    # concurrency passes follow.
    classname: Optional[str] = None
    # Attribute-read sets (raw material for the identity lane, reusable
    # by any future rule): root Name -> dotted attribute chains read
    # off it in THIS function's own body (a nested def records its own
    # reads on its own FunctionInfo, so closure reads resolve through
    # `parent`).  `option.solver_option.bf16` records
    # {"solver_option.bf16"} under "option"; only FULL chains are
    # recorded (never their suffixes), only Load contexts count, and
    # chains that resolve to indexed functions stay refs, not reads.
    attr_reads: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)
    # Simple local aliases: `solver_opt = option.solver_option` records
    # {"solver_opt": "option.solver_option"} — the single-level
    # resolution step a consumer needs to root alias reads back at a
    # named parameter (last assignment wins; only pure Name/Attribute
    # chain values are recorded).
    assigns: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModuleInfo:
    name: str  # dotted module name
    path: str
    tree: ast.Module
    source_lines: List[str]
    # local alias -> fully qualified dotted target ("np" -> "numpy",
    # "lm_solve" -> "megba_tpu.algo.lm.lm_solve")
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)
    functions: Dict[str, str] = dataclasses.field(default_factory=dict)
    # module-level simple name -> function qualname


class PackageIndex:
    """Parsed view of a set of Python files plus the jit call graph."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.reachable: Set[str] = set()
        # class qualname -> method simple name -> function qualname
        # (immediate methods only; no inheritance walking — the linter
        # never guesses).
        self.classes: Dict[str, Dict[str, str]] = {}

    # ------------------------------------------------------------- build
    @classmethod
    def build(cls, paths: Iterable[str]) -> "PackageIndex":
        index = cls()
        for path, modname in _iter_module_files(paths):
            index._add_module(path, modname)
        for mod in index.modules.values():
            index._collect_refs_and_entries(mod)
        index._propagate_reachability()
        return index

    def _add_module(self, path: str, modname: str) -> None:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
        mod = ModuleInfo(
            name=modname, path=path, tree=tree,
            source_lines=source.splitlines())
        self.modules[modname] = mod
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    mod.imports[alias.asname or alias.name.split(".")[0]] = (
                        alias.name if alias.asname else alias.name.split(".")[0])
                    if alias.asname:
                        mod.imports[alias.asname] = alias.name
            elif isinstance(node, ast.ImportFrom):
                if node.module is None or node.level:
                    continue  # relative imports unused in this repo
                for alias in node.names:
                    mod.imports[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}")
        self._index_functions(mod, tree, parent=None, prefix=modname)

    def _index_functions(self, mod: ModuleInfo, node: ast.AST,
                         parent: Optional[str], prefix: str,
                         classname: Optional[str] = None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}"
                info = FunctionInfo(
                    qualname=qual, module=mod.name, node=child, parent=parent,
                    classname=classname)
                self.functions[qual] = info
                if classname is not None:
                    self.classes.setdefault(classname, {})[child.name] = qual
                if parent is not None:
                    self.functions[parent].children.append(qual)
                else:
                    mod.functions[child.name] = qual
                # A method's own nested defs are plain functions again.
                self._index_functions(mod, child, parent=qual, prefix=qual,
                                      classname=None)
            elif isinstance(child, ast.ClassDef):
                # Methods are indexed too (flat qualname through the class).
                self._index_functions(
                    mod, child, parent=parent, prefix=f"{prefix}.{child.name}",
                    classname=f"{prefix}.{child.name}")
            elif isinstance(child, (ast.If, ast.Try, ast.With, ast.For,
                                    ast.While)):
                # Compound statements at the same scope can hold defs —
                # mesh.py's shard_map fallback lives in an `except:` block.
                self._index_functions(mod, child, parent, prefix, classname)

    # -------------------------------------------------- refs and entries
    def _scope_chain(self, mod: ModuleInfo,
                     func: Optional[FunctionInfo]) -> List[FunctionInfo]:
        chain = []
        cur = func
        while cur is not None:
            chain.append(cur)
            cur = self.functions.get(cur.parent) if cur.parent else None
        return chain

    def resolve(self, mod: ModuleInfo, func: Optional[FunctionInfo],
                name_node: ast.AST) -> Optional[str]:
        """Resolve a Name/Attribute node to an indexed function qualname."""
        dotted = _dotted(name_node)
        if dotted is None:
            return None
        head, *rest = dotted.split(".")
        # 0. `self.method(...)` resolves to the innermost enclosing
        #    class's own method (closures nested in methods capture
        #    `self`, so the whole scope chain is searched).  Immediate
        #    methods only — no inheritance guessing.
        if head == "self" and len(rest) == 1:
            for scope in self._scope_chain(mod, func):
                if scope.classname is not None:
                    q = self.classes.get(scope.classname, {}).get(rest[0])
                    if q is not None:
                        return q
                    break  # innermost class decides; never walk outward
        # 1. lexical function scopes: own nested defs, then siblings via
        #    each enclosing function's children
        if not rest:
            for scope in self._scope_chain(mod, func):
                for child_q in scope.children:
                    if child_q.rsplit(".", 1)[-1] == head:
                        return child_q
        # 2. module-level defs
        if not rest and head in mod.functions:
            return mod.functions[head]
        # 3. imports: direct function import, or `import pkg.mod` /
        #    `from pkg import mod` followed by `mod.fn`
        target = mod.imports.get(head)
        if target is None:
            return None
        full = ".".join([target] + rest)
        return full if full in self.functions else None

    def _collect_refs_and_entries(self, mod: ModuleInfo) -> None:
        """One pass over the module: per-function references + entries."""

        index = self

        def owner_of(node_stack) -> Optional[FunctionInfo]:
            for n in reversed(node_stack):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = getattr(n, "_megba_qualname", None)
                    if q:
                        return index.functions[q]
            return None

        # annotate nodes with their qualnames for owner lookup
        for q, info in self.functions.items():
            if info.module == mod.name:
                info.node._megba_qualname = q  # type: ignore[attr-defined]

        class Visitor(ast.NodeVisitor):
            def __init__(self) -> None:
                self.stack: List[ast.AST] = []

            def generic_visit(self, node: ast.AST) -> None:
                self.stack.append(node)
                super().generic_visit(node)
                self.stack.pop()

            def visit_FunctionDef(self, node):  # noqa: N802
                q = getattr(node, "_megba_qualname", None)
                if q is not None:
                    info = index.functions[q]
                    if _has_jit_decorator(node):
                        info.is_entry = True
                    if "jit-entry" in pragmas_on_line(
                            mod.source_lines, node.lineno):
                        info.is_entry = True
                self.generic_visit(node)

            visit_AsyncFunctionDef = visit_FunctionDef  # noqa: N815

            def visit_Call(self, node):  # noqa: N802
                owner = owner_of(self.stack)
                callee = _dotted(node.func)
                if callee is not None and callee.split(".")[-1] in _JIT_WRAP_NAMES:
                    # jax.jit(fn, ...) / shard_map(fn, ...): every
                    # function reference anywhere in the argument
                    # expressions becomes a jit entry — including ones
                    # wrapped in adapters, e.g. jax.jit(traced("s", fn)).
                    for arg in list(node.args) + [kw.value for kw in node.keywords]:
                        for sub in ast.walk(arg):
                            if isinstance(sub, (ast.Name, ast.Attribute)):
                                q = index.resolve(mod, owner, sub)
                                if q is not None:
                                    index.functions[q].is_entry = True
                self.generic_visit(node)

            def visit_Name(self, node):  # noqa: N802
                if isinstance(node.ctx, ast.Load):
                    owner = owner_of(self.stack)
                    if owner is not None:
                        q = index.resolve(mod, owner, node)
                        if q is not None and q != owner.qualname:
                            owner.refs.add(q)
                self.generic_visit(node)

            def visit_Attribute(self, node):  # noqa: N802
                if isinstance(node.ctx, ast.Load):
                    owner = owner_of(self.stack)
                    if owner is not None:
                        q = index.resolve(mod, owner, node)
                        if q is not None and q != owner.qualname:
                            owner.refs.add(q)
                            return  # don't double-count the inner Name
                        # Not a function reference: record the full
                        # attribute-read chain on its owner — but only
                        # at the OUTERMOST Attribute of a chain (an
                        # inner `a.b` of `a.b.c` sees its parent
                        # Attribute on the stack and is skipped, so
                        # suffixes are never recorded).
                        if not (self.stack
                                and isinstance(self.stack[-1], ast.Attribute)):
                            dotted = _dotted(node)
                            if dotted is not None:
                                root, _, chain = dotted.partition(".")
                                owner.attr_reads.setdefault(
                                    root, set()).add(chain)
                self.generic_visit(node)

            def visit_Assign(self, node):  # noqa: N802
                owner = owner_of(self.stack)
                if (owner is not None and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    val = _dotted(node.value)
                    if val is not None:
                        owner.assigns[node.targets[0].id] = val
                self.generic_visit(node)

            def visit_AnnAssign(self, node):  # noqa: N802
                owner = owner_of(self.stack)
                if (owner is not None and node.value is not None
                        and isinstance(node.target, ast.Name)):
                    val = _dotted(node.value)
                    if val is not None:
                        owner.assigns[node.target.id] = val
                self.generic_visit(node)

        Visitor().visit(mod.tree)

    def _propagate_reachability(self) -> None:
        frontier = [q for q, f in self.functions.items() if f.is_entry]
        seen = set(frontier)
        while frontier:
            q = frontier.pop()
            info = self.functions[q]
            # A reachable function's nested defs are traced with it.
            for nxt in list(info.refs) + list(info.children):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        self.reachable = seen

    # ------------------------------------------------------------ helpers
    def module_of_path(self, path: str) -> Optional[ModuleInfo]:
        for mod in self.modules.values():
            if os.path.samefile(mod.path, path):
                return mod
        return None


def _dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` Attribute/Name chain -> "a.b.c", else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _has_jit_decorator(node) -> bool:
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        dotted = _dotted(target)
        if dotted is None:
            continue
        tail = dotted.split(".")[-1]
        if tail in _JIT_WRAP_NAMES:
            return True
        if tail == "partial":
            # functools.partial(jax.jit, ...) as a decorator factory
            if isinstance(dec, ast.Call) and dec.args:
                inner = _dotted(dec.args[0])
                if inner is not None and inner.split(".")[-1] in _JIT_WRAP_NAMES:
                    return True
    return False


def _iter_module_files(paths: Iterable[str]) -> List[Tuple[str, str]]:
    """Expand files/dirs into (path, dotted module name) pairs.

    The dotted name is rooted at the nearest ancestor directory that is
    NOT a package (has no __init__.py), so `megba_tpu/algo/lm.py` maps
    to `megba_tpu.algo.lm` whether the linter is invoked from the repo
    root or given an absolute path.
    """
    out: List[Tuple[str, str]] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if not d.startswith(".") and d != "__pycache__")
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        fp = os.path.join(dirpath, fn)
                        out.append((fp, _module_name(fp)))
        elif os.path.isfile(p) and p.endswith(".py"):
            out.append((p, _module_name(p)))
        else:
            # A vanished path must FAIL the gate, not lint zero files
            # and report clean — a typo'd directory in scripts/lint.sh
            # would otherwise turn the whole acceptance gate green.
            raise ValueError(f"not a directory or .py file: {p!r}")
    if not out:
        raise ValueError(f"no Python files found under: {list(paths)!r}")
    return out


def _module_name(path: str) -> str:
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    name = ".".join(reversed(parts))
    return name[: -len(".__init__")] if name.endswith(".__init__") else name
