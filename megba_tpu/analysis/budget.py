"""AOT FLOP/byte budget baseline for the canonical solver programs.

The committed `ANALYSIS_BUDGET.json` (repo root) records, per canonical
program, the XLA AOT cost model's view of the compiled executable:
FLOPs, bytes accessed, peak temp allocation, and the collective census
totals.  `python -m megba_tpu.analysis.audit --check` re-measures and
fails on any tolerance-breaking drift — a refactor that doubles the
Schur build's FLOPs, fattens the PCG's transient memory, or adds a
collective fails CI without running a single benchmark;
`--update` re-baselines after an intentional change.

Tolerances are per-metric: the continuous cost-model metrics get a
relative band (default 15%, both directions — an unrecorded 2x
improvement is also a baseline that no longer describes the program);
the discrete collective counts are exact (one extra all-reduce IS the
regression this layer exists to catch).  All stdlib, no jax.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

SCHEMA = "megba_tpu.analysis_budget/v1"

# metric name -> relative tolerance (0.0 = exact match required).
TOLERANCES: Dict[str, float] = {
    "flops": 0.15,
    "bytes_accessed": 0.15,
    "peak_temp_bytes": 0.15,
    "argument_bytes": 0.15,
    "output_bytes": 0.15,
    "all_reduce_count": 0.0,
    "other_collective_count": 0.0,
    # Ring-model bytes each device moves per CG step (PCG-body
    # collectives: operand bytes x replica-group shape —
    # analysis/hlo.collective_bytes_moved).  Exact: communication
    # volume is discrete, and a fatter (or world-scoped) collective
    # inside the body IS the regression this axis exists to catch;
    # an overlap/subgroup win re-baselines with --update and is
    # thereby pinned.
    "collective_bytes_per_sp": 0.0,
    # Declared analytical edge-pipeline axes (analysis/edge_budget.py):
    # per-device flops and HBM bytes touched per S·p, priced from the
    # problem geometry + edge-stream plan + dtype surface with zero
    # compiler in the loop.  Exact: the same pure function prices both
    # --update and --check, so a mismatch means the INPUTS drifted —
    # a plan/quantum/dtype-surface change that must be intentional.
    "flops_per_sp": 0.0,
    "bytes_touched_per_sp": 0.0,
}


def default_baseline_path() -> str:
    """The committed baseline at the repo root."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(os.path.dirname(os.path.dirname(here)),
                        "ANALYSIS_BUDGET.json")


def load_baseline(path: Optional[str] = None) -> Dict[str, Dict[str, float]]:
    """program -> metric -> value.  {} when the file does not exist."""
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return {}
    with open(path) as fh:
        doc = json.load(fh)
    return doc.get("programs", {})


def write_baseline(measured: Dict[str, Dict[str, float]],
                   path: Optional[str] = None,
                   meta: Optional[Dict[str, str]] = None) -> str:
    path = path or default_baseline_path()
    doc = {"schema": SCHEMA, "programs": measured}
    if meta:
        doc["meta"] = meta
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def compare(baseline: Dict[str, Dict[str, float]],
            measured: Dict[str, Dict[str, float]],
            tolerances: Optional[Dict[str, float]] = None) -> List[str]:
    """Violation messages (empty = within budget), program+metric named.

    A program missing from the baseline, or a baseline program no longer
    measured, is itself a violation: the committed budget must describe
    exactly the canonical program set (run `--update` to re-baseline).
    """
    tolerances = TOLERANCES if tolerances is None else tolerances
    out: List[str] = []
    for prog in sorted(measured):
        if prog not in baseline:
            out.append(
                f"{prog}: not in ANALYSIS_BUDGET.json baseline "
                "(new program? run `audit --update`)")
            continue
        base = baseline[prog]
        for metric in sorted(measured[prog]):
            tol = tolerances.get(metric)
            if tol is None:
                continue  # informational metric, not gated
            got = float(measured[prog][metric])
            if metric not in base:
                out.append(f"{prog}: metric {metric} missing from "
                           "baseline (run `audit --update`)")
                continue
            want = float(base[metric])
            if tol == 0.0:
                if got != want:
                    out.append(
                        f"{prog}: {metric} changed {want:g} -> {got:g} "
                        "(exact-match metric; an added/removed collective "
                        "must be intentional — re-baseline with --update)")
                continue
            ref = max(abs(want), 1.0)
            drift = (got - want) / ref
            if drift > tol:
                out.append(
                    f"{prog}: {metric} regressed {want:g} -> {got:g} "
                    f"(+{100 * drift:.1f}% > {100 * tol:.0f}% budget)")
            elif drift < -tol:
                out.append(
                    f"{prog}: {metric} dropped {want:g} -> {got:g} "
                    f"({100 * drift:.1f}%; unrecorded improvement — "
                    "re-baseline with --update)")
        # Gated metrics the baseline pins but this run could not measure
        # (backend without cost/memory analysis): the gate must degrade
        # LOUDLY — a silent skip would disarm the budget, and comparing
        # a sentinel would read as a fake 100% improvement.
        for metric in sorted(base):
            if metric in measured[prog]:
                continue
            if tolerances.get(metric) is None:
                continue
            out.append(
                f"{prog}: {metric} unavailable on this backend (baseline "
                f"pins {float(base[metric]):g}; gate cannot run — audit "
                "on a cost-model-capable backend, or `--update` there)")
    for prog in sorted(baseline):
        if prog not in measured:
            out.append(
                f"{prog}: in ANALYSIS_BUDGET.json but no longer audited "
                "(removed program? run `audit --update`)")
    return out
