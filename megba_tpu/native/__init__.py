"""Native (C++) host runtime: fast BAL parsing + graph index building.

The ctypes binding layer over `libmegba_native.so` — the TPU framework's
equivalent of the reference's host-side C++ runtime (BAL line parsing in
examples/BAL_Double.cpp:74-139, HessianEntrance / positionContainer /
CSR-skeleton preprocessing, and MemoryPool's partition arithmetic; see
the .cpp files for the per-function mapping).  Everything here degrades
gracefully: if the shared library is missing it is built on first use
with g++, and if that fails callers fall back to the NumPy paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libmegba_native.so")
_SOURCES = ["bal_parser.cpp", "index_builder.cpp"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    # Build to a temp name then os.replace: concurrent importers (multi-
    # host shared filesystems) never see a half-written .so, and a killed
    # build can't leave a corrupt library with a fresh mtime.  No
    # -march=native: the .so may be shared across heterogeneous hosts.
    tmp = _SO + f".tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp,
    ] + [os.path.join(_DIR, s) for s in _SOURCES]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not os.path.exists(_SO) or any(
            os.path.getmtime(os.path.join(_DIR, s)) > os.path.getmtime(_SO)
            for s in _SOURCES
        ):
            if not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _build_failed = True
            return None

        i64, i32, f64 = ctypes.c_int64, ctypes.c_int32, ctypes.c_double
        p = ctypes.POINTER
        lib.megba_bal_header.argtypes = [ctypes.c_char_p, p(i64), p(i64), p(i64)]
        lib.megba_bal_header.restype = ctypes.c_int
        lib.megba_bal_parse.argtypes = [
            ctypes.c_char_p, i64, i64, i64, p(f64), p(i32), p(i32), p(f64), p(f64),
        ]
        lib.megba_bal_parse.restype = ctypes.c_int
        lib.megba_sort_edges.argtypes = [p(i32), i64, i64, p(i64)]
        lib.megba_sort_edges.restype = ctypes.c_int
        lib.megba_degree_stats.argtypes = [
            p(i32), p(i32), i64, i64, i64, p(i64), p(i64), p(i64),
        ]
        lib.megba_degree_stats.restype = ctypes.c_int
        lib.megba_partition_bounds.argtypes = [i64, i64, p(i64)]
        lib.megba_partition_bounds.restype = ctypes.c_int
        _lib = lib
        return _lib


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def parse_bal_native(path: str, dtype=np.float64):
    """Parse a BAL file with the native parser; None if lib unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n_cam = ctypes.c_int64()
    n_pt = ctypes.c_int64()
    n_obs = ctypes.c_int64()
    rc = lib.megba_bal_header(path.encode(), ctypes.byref(n_cam),
                              ctypes.byref(n_pt), ctypes.byref(n_obs))
    if rc != 0:
        raise ValueError(f"BAL header parse failed ({rc}): {path}")
    nc, npt, no = n_cam.value, n_pt.value, n_obs.value
    obs = np.empty((no, 2), np.float64)
    cam_idx = np.empty(no, np.int32)
    pt_idx = np.empty(no, np.int32)
    cameras = np.empty((nc, 9), np.float64)
    points = np.empty((npt, 3), np.float64)
    rc = lib.megba_bal_parse(
        path.encode(), nc, npt, no,
        _ptr(obs, ctypes.c_double), _ptr(cam_idx, ctypes.c_int32),
        _ptr(pt_idx, ctypes.c_int32), _ptr(cameras, ctypes.c_double),
        _ptr(points, ctypes.c_double))
    if rc != 0:
        raise ValueError(f"BAL parse failed (code {rc}): {path}")
    from megba_tpu.io.bal import BALFile

    return BALFile(
        cameras=cameras.astype(dtype, copy=False),
        points=points.astype(dtype, copy=False),
        obs=obs.astype(dtype, copy=False),
        cam_idx=cam_idx, pt_idx=pt_idx)


def sort_edges_by_camera(cam_idx: np.ndarray, num_cameras: int) -> np.ndarray:
    """Stable permutation sorting edges by camera (scatter locality).

    Native counting sort when available, else np.argsort(kind='stable').
    """
    lib = get_lib()
    n = cam_idx.shape[0]
    if lib is None:
        return np.argsort(cam_idx, kind="stable").astype(np.int64)
    cam_idx = np.ascontiguousarray(cam_idx, np.int32)
    perm = np.empty(n, np.int64)
    rc = lib.megba_sort_edges(_ptr(cam_idx, ctypes.c_int32), n, num_cameras,
                              _ptr(perm, ctypes.c_int64))
    if rc != 0:
        raise ValueError(f"sort_edges failed (code {rc})")
    return perm


def degree_stats(cam_idx: np.ndarray, pt_idx: np.ndarray, num_cameras: int,
                 num_points: int):
    """Per-vertex degrees + (max_cam_degree, max_pt_degree, hpl_nnz_blocks).

    The planning view of the reference's HessianEntrance sparsity
    discovery (base_problem.cpp:17-48): solve_bal(verbose=True) prints it
    and users can size explicit-mode memory from hpl_nnz_blocks.
    hpl_nnz_blocks is -1 unless edges are camera-sorted.  NumPy fallback
    when the native lib is unavailable.
    """
    lib = get_lib()
    if lib is None:
        cam_counts = np.bincount(cam_idx, minlength=num_cameras).astype(np.int64)
        pt_counts = np.bincount(pt_idx, minlength=num_points).astype(np.int64)
        from megba_tpu.core.types import is_cam_sorted

        sorted_ = is_cam_sorted(cam_idx)
        nnz = (
            int(np.unique(cam_idx.astype(np.int64) * num_points
                          + pt_idx.astype(np.int64)).size)
            if sorted_ else -1)
        return cam_counts, pt_counts, (int(cam_counts.max(initial=0)),
                                       int(pt_counts.max(initial=0)), nnz)
    cam_idx = np.ascontiguousarray(cam_idx, np.int32)
    pt_idx = np.ascontiguousarray(pt_idx, np.int32)
    cam_counts = np.empty(num_cameras, np.int64)
    pt_counts = np.empty(num_points, np.int64)
    stats = np.empty(3, np.int64)
    rc = lib.megba_degree_stats(
        _ptr(cam_idx, ctypes.c_int32), _ptr(pt_idx, ctypes.c_int32),
        cam_idx.shape[0], num_cameras, num_points,
        _ptr(cam_counts, ctypes.c_int64), _ptr(pt_counts, ctypes.c_int64),
        _ptr(stats, ctypes.c_int64))
    if rc != 0:
        raise ValueError(f"degree_stats failed (code {rc})")
    return cam_counts, pt_counts, tuple(int(s) for s in stats)


def partition_bounds(n_edge: int, world_size: int) -> np.ndarray:
    """Equal contiguous shard bounds (padded) for the edge axis."""
    lib = get_lib()
    if lib is None:
        padded = -(-n_edge // world_size) * world_size
        per = padded // world_size
        return np.arange(world_size + 1, dtype=np.int64) * per
    out = np.empty(world_size + 1, np.int64)
    rc = lib.megba_partition_bounds(n_edge, world_size, _ptr(out, ctypes.c_int64))
    if rc != 0:
        raise ValueError("partition_bounds failed")
    return out
