// Host-side graph preprocessing — the native runtime's index builder.
//
// Role equivalent of the reference's HessianEntrance sparsity discovery
// (reference src/problem/base_problem.cpp:17-48), positionContainer
// construction (reference src/edge/base_edge.cpp:224-262, OpenMP there)
// and CSR skeleton build (reference
// src/linear_system/schur_LM_linear_system.cpp:20-84).  The TPU compute
// path needs none of those CSR structures — segment_sum replaces them —
// but it DOES want (a) edges sorted by camera for scatter-reduce
// locality, and (b) block-sparsity statistics for planning.  All
// counting-sort based, O(nE + Nc + Np), no comparisons.

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Stable counting-sort permutation of edges by key index.
//   key      [n] int32 in [0, num_keys)
//   perm_out [n] int64: output order (perm_out[i] = original position of
//            the i-th edge in sorted order)
// Returns 0 on success.
int megba_sort_edges(const int32_t* key, int64_t n, int64_t num_keys,
                     int64_t* perm_out) {
  if (n < 0 || num_keys <= 0) return -1;
  std::vector<int64_t> counts(static_cast<size_t>(num_keys) + 1, 0);
  for (int64_t i = 0; i < n; ++i) {
    int32_t k = key[i];
    if (k < 0 || k >= num_keys) return -2;
    ++counts[static_cast<size_t>(k) + 1];
  }
  for (int64_t k = 0; k < num_keys; ++k) counts[k + 1] += counts[k];
  for (int64_t i = 0; i < n; ++i)
    perm_out[counts[static_cast<size_t>(key[i])]++] = i;
  return 0;
}

// Per-vertex edge counts (the segment sizes segment_sum will reduce) and
// block-sparsity statistics.  Outputs:
//   cam_counts [n_cam] int64, pt_counts [n_pt] int64
//   stats[0] = max camera degree, stats[1] = max point degree,
//   stats[2] = number of distinct (cam, pt) pairs (== nnz blocks of Hpl)
//              when edges are pre-sorted by camera (pairs grouped);
//              -1 if the input is not camera-sorted.
int megba_degree_stats(const int32_t* cam_idx, const int32_t* pt_idx,
                       int64_t n, int64_t n_cam, int64_t n_pt,
                       int64_t* cam_counts, int64_t* pt_counts,
                       int64_t* stats) {
  std::memset(cam_counts, 0, sizeof(int64_t) * static_cast<size_t>(n_cam));
  std::memset(pt_counts, 0, sizeof(int64_t) * static_cast<size_t>(n_pt));
  bool sorted = true;
  for (int64_t i = 0; i < n; ++i) {
    int32_t c = cam_idx[i], p = pt_idx[i];
    if (c < 0 || c >= n_cam || p < 0 || p >= n_pt) return -2;
    ++cam_counts[c];
    ++pt_counts[p];
    if (i > 0 && cam_idx[i] < cam_idx[i - 1]) sorted = false;
  }
  int64_t max_c = 0, max_p = 0;
  for (int64_t c = 0; c < n_cam; ++c)
    if (cam_counts[c] > max_c) max_c = cam_counts[c];
  for (int64_t p = 0; p < n_pt; ++p)
    if (pt_counts[p] > max_p) max_p = pt_counts[p];
  stats[0] = max_c;
  stats[1] = max_p;
  if (!sorted) {
    stats[2] = -1;
    return 0;
  }
  // Distinct (cam, pt) pairs within each camera group: sort each group's
  // point ids via a reusable seen-marker array.
  std::vector<int64_t> last_seen(static_cast<size_t>(n_pt), -1);
  int64_t nnz = 0;
  for (int64_t i = 0; i < n; ++i) {
    int32_t c = cam_idx[i], p = pt_idx[i];
    if (last_seen[p] != c) {
      last_seen[p] = c;
      ++nnz;
    }
  }
  stats[2] = nnz;
  return 0;
}

// Contiguous equal partition bounds for the edge axis over `world` shards
// (the arithmetic of the reference's MemoryPool::getItemNum,
// memory_pool.h:48-63, made explicit): bounds_out[w] = start of shard w,
// bounds_out[world] = padded total (n rounded up to a multiple of world).
int megba_partition_bounds(int64_t n, int64_t world, int64_t* bounds_out) {
  if (n < 0 || world <= 0) return -1;
  int64_t padded = ((n + world - 1) / world) * world;
  int64_t per = padded / world;
  for (int64_t w = 0; w <= world; ++w) bounds_out[w] = w * per;
  return 0;
}

}  // extern "C"
