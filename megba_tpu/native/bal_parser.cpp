// Fast BAL text parser — the native data-loader of the host runtime.
//
// Role equivalent of the reference's example-side line parser
// (reference examples/BAL_Double.cpp:74-139, which fscanf's 4.5M
// observation lines for Final-13682) and of its host-side problem
// construction costs (SURVEY.md section 3.1 flags SoA appends as the
// build bottleneck).  Design is new: mmap the whole file, scan the token
// stream once with a branch-light float reader, write straight into
// caller-provided (numpy) buffers.  C ABI for ctypes binding — no
// pybind11 in this image.

#include <cctype>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Cursor {
  const char* p;
  const char* end;
};

inline void skip_space(Cursor& c) {
  while (c.p < c.end && std::isspace(static_cast<unsigned char>(*c.p))) ++c.p;
}

// strtod on a bounded buffer; BAL files are '\0'-free text so strtod's
// scan terminates at whitespace well before `end`.
inline bool next_double(Cursor& c, double* out) {
  skip_space(c);
  if (c.p >= c.end) return false;
  char* after = nullptr;
  *out = std::strtod(c.p, &after);
  if (after == c.p) return false;
  c.p = after;
  return true;
}

inline bool next_long(Cursor& c, long* out) {
  skip_space(c);
  if (c.p >= c.end) return false;
  char* after = nullptr;
  *out = std::strtol(c.p, &after, 10);
  if (after == c.p) return false;
  c.p = after;
  return true;
}

struct Mapped {
  const char* data = nullptr;
  size_t size = 0;
  int fd = -1;

  bool open_file(const char* path) {
    fd = ::open(path, O_RDONLY);
    if (fd < 0) return false;
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size <= 0) return false;
    size = static_cast<size_t>(st.st_size);
    void* m = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (m == MAP_FAILED) return false;
    data = static_cast<const char*>(m);
    ::madvise(const_cast<char*>(data), size, MADV_SEQUENTIAL);
    return true;
  }

  ~Mapped() {
    if (data) munmap(const_cast<char*>(data), size);
    if (fd >= 0) ::close(fd);
  }
};

}  // namespace

extern "C" {

// Reads only the header. Returns 0 on success.
int megba_bal_header(const char* path, int64_t* n_cam, int64_t* n_pt,
                     int64_t* n_obs) {
  Mapped m;
  if (!m.open_file(path)) return -1;
  Cursor c{m.data, m.data + m.size};
  long a, b, d;
  if (!next_long(c, &a) || !next_long(c, &b) || !next_long(c, &d)) return -2;
  if (a < 0 || b < 0 || d < 0) return -3;
  *n_cam = a;
  *n_pt = b;
  *n_obs = d;
  return 0;
}

// Full parse into caller-allocated buffers:
//   obs      [n_obs * 2] double
//   cam_idx  [n_obs] int32
//   pt_idx   [n_obs] int32
//   cameras  [n_cam * 9] double
//   points   [n_pt * 3] double
// Returns 0 on success, negative error codes on malformed input.
int megba_bal_parse(const char* path, int64_t n_cam, int64_t n_pt,
                    int64_t n_obs, double* obs, int32_t* cam_idx,
                    int32_t* pt_idx, double* cameras, double* points) {
  Mapped m;
  if (!m.open_file(path)) return -1;
  Cursor c{m.data, m.data + m.size};
  long a, b, d;
  if (!next_long(c, &a) || !next_long(c, &b) || !next_long(c, &d)) return -2;
  if (a != n_cam || b != n_pt || d != n_obs) return -3;

  for (int64_t i = 0; i < n_obs; ++i) {
    long ci, pi;
    double u, v;
    if (!next_long(c, &ci) || !next_long(c, &pi) || !next_double(c, &u) ||
        !next_double(c, &v))
      return -4;
    if (ci < 0 || ci >= n_cam || pi < 0 || pi >= n_pt) return -5;
    cam_idx[i] = static_cast<int32_t>(ci);
    pt_idx[i] = static_cast<int32_t>(pi);
    obs[2 * i] = u;
    obs[2 * i + 1] = v;
  }
  for (int64_t i = 0; i < n_cam * 9; ++i)
    if (!next_double(c, &cameras[i])) return -6;
  for (int64_t i = 0; i < n_pt * 3; ++i)
    if (!next_double(c, &points[i])) return -7;
  skip_space(c);
  if (c.p != c.end) return -8;  // trailing garbage
  return 0;
}

}  // extern "C"
