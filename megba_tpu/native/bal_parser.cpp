// Fast BAL text parser — the native data-loader of the host runtime.
//
// Role equivalent of the reference's example-side line parser
// (reference examples/BAL_Double.cpp:74-139, which fscanf's 4.5M
// observation lines for Final-13682) and of its host-side problem
// construction costs (SURVEY.md section 3.1 flags SoA appends as the
// build bottleneck).  Design is new: read the file into one
// NUL-terminated buffer (safe for token scanners even when the file ends
// mid-token) and scan it once with std::from_chars — locale-independent,
// allocation-free number parsing.  C ABI for ctypes binding — no
// pybind11 in this image.

#include <cctype>
#include <cerrno>
#include <charconv>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct Cursor {
  const char* p;
  const char* end;  // points at the trailing '\0'
};

inline void skip_space(Cursor& c) {
  while (c.p < c.end && std::isspace(static_cast<unsigned char>(*c.p))) ++c.p;
}

// Locale-independent double parse; BAL files use plain C formatting.
inline bool next_double(Cursor& c, double* out) {
  skip_space(c);
  if (c.p >= c.end) return false;
  auto res = std::from_chars(c.p, c.end, *out);
  if (res.ec != std::errc() || res.ptr == c.p) return false;
  c.p = res.ptr;
  return true;
}

inline bool next_long(Cursor& c, long* out) {
  skip_space(c);
  if (c.p >= c.end) return false;
  auto res = std::from_chars(c.p, c.end, *out, 10);
  if (res.ec != std::errc() || res.ptr == c.p) return false;
  c.p = res.ptr;
  return true;
}

// Whole-file read with a trailing NUL so scanning can never run past the
// buffer (mmap would leave the final token unterminated when the file
// size is an exact multiple of the page size).
struct Buffer {
  std::vector<char> data;

  bool load(const char* path) {
    std::FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    std::fseek(f, 0, SEEK_END);
    long sz = std::ftell(f);
    if (sz < 0) {
      std::fclose(f);
      return false;
    }
    std::fseek(f, 0, SEEK_SET);
    data.resize(static_cast<size_t>(sz) + 1);
    size_t got = sz ? std::fread(data.data(), 1, static_cast<size_t>(sz), f) : 0;
    std::fclose(f);
    if (got != static_cast<size_t>(sz)) return false;
    data[static_cast<size_t>(sz)] = '\0';
    return true;
  }

  Cursor cursor() const {
    return Cursor{data.data(), data.data() + data.size() - 1};
  }
};

}  // namespace

extern "C" {

// Reads only the header. Returns 0 on success.
int megba_bal_header(const char* path, int64_t* n_cam, int64_t* n_pt,
                     int64_t* n_obs) {
  Buffer b;
  if (!b.load(path)) return -1;
  Cursor c = b.cursor();
  long a, bb, d;
  if (!next_long(c, &a) || !next_long(c, &bb) || !next_long(c, &d)) return -2;
  if (a < 0 || bb < 0 || d < 0) return -3;
  *n_cam = a;
  *n_pt = bb;
  *n_obs = d;
  return 0;
}

// Full parse into caller-allocated buffers:
//   obs      [n_obs * 2] double
//   cam_idx  [n_obs] int32
//   pt_idx   [n_obs] int32
//   cameras  [n_cam * 9] double
//   points   [n_pt * 3] double
// Returns 0 on success, negative error codes on malformed input.
int megba_bal_parse(const char* path, int64_t n_cam, int64_t n_pt,
                    int64_t n_obs, double* obs, int32_t* cam_idx,
                    int32_t* pt_idx, double* cameras, double* points) {
  Buffer b;
  if (!b.load(path)) return -1;
  Cursor c = b.cursor();
  long a, bb, d;
  if (!next_long(c, &a) || !next_long(c, &bb) || !next_long(c, &d)) return -2;
  if (a != n_cam || bb != n_pt || d != n_obs) return -3;

  for (int64_t i = 0; i < n_obs; ++i) {
    long ci, pi;
    double u, v;
    if (!next_long(c, &ci) || !next_long(c, &pi) || !next_double(c, &u) ||
        !next_double(c, &v))
      return -4;
    if (ci < 0 || ci >= n_cam || pi < 0 || pi >= n_pt) return -5;
    cam_idx[i] = static_cast<int32_t>(ci);
    pt_idx[i] = static_cast<int32_t>(pi);
    obs[2 * i] = u;
    obs[2 * i + 1] = v;
  }
  for (int64_t i = 0; i < n_cam * 9; ++i)
    if (!next_double(c, &cameras[i])) return -6;
  for (int64_t i = 0; i < n_pt * 3; ++i)
    if (!next_double(c, &points[i])) return -7;
  skip_space(c);
  if (c.p != c.end) return -8;  // trailing garbage
  return 0;
}

}  // extern "C"
