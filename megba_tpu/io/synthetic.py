"""Synthetic BAL-like problem generator.

Stands in for the public BAL datasets (which the reference's examples
load from text files, examples/BAL_Double.cpp:74-139) in tests and
benchmarks — this sandbox has no network egress, so problems of any size
are generated procedurally with known ground truth.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class SyntheticBAL:
    """Ground-truth + perturbed initial parameters for a synthetic scene."""

    cameras_gt: np.ndarray  # [Nc, 9]
    points_gt: np.ndarray  # [Np, 3]
    cameras0: np.ndarray  # perturbed initial cameras
    points0: np.ndarray  # perturbed initial points
    obs: np.ndarray  # [nE, 2]
    cam_idx: np.ndarray  # [nE] int32
    pt_idx: np.ndarray  # [nE] int32


def rotate_batch(w: np.ndarray, points: np.ndarray) -> np.ndarray:
    """Vectorised NumPy Rodrigues rotation: R(w_i) @ points_i, [n, 3].

    The host-side twin of the on-device rotation in ops/geo.py — shared
    by the synthetic generator and the pre-flight triage checks
    (robustness/triage.py), so "what does this camera see" has exactly
    one host definition.
    """
    theta = np.linalg.norm(w, axis=1, keepdims=True)
    safe = theta > 1e-12
    theta_safe = np.where(safe, theta, 1.0)
    k = w / theta_safe
    cos_t = np.cos(theta)
    sin_t = np.sin(theta)
    dot = np.sum(k * points, axis=1, keepdims=True)
    RX = points * cos_t + np.cross(k, points) * sin_t + k * dot * (1 - cos_t)
    return np.where(safe, RX, points + np.cross(w, points))


def project_batch_depth(
    cameras: np.ndarray, points: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorised NumPy BAL projection with the camera-frame depth.

    cameras [n, 9] x points [n, 3] -> (uv [n, 2], z [n]) where z is the
    camera-frame third coordinate BEFORE the -P/P.z divide: the BAL
    convention puts visible scene at z < 0, so z >= 0 is a cheirality
    violation (point behind — or exactly on — the camera plane).
    """
    w, t = cameras[:, 0:3], cameras[:, 3:6]
    f, k1, k2 = cameras[:, 6], cameras[:, 7], cameras[:, 8]
    P = rotate_batch(w, points) + t
    with np.errstate(divide="ignore", invalid="ignore"):
        p = -P[:, 0:2] / P[:, 2:3]
        n = np.sum(p * p, axis=1)
        uv = (f * (1 + k1 * n + k2 * n * n))[:, None] * p
    return uv, P[:, 2]


def _project_batch(cameras: np.ndarray, points: np.ndarray) -> np.ndarray:
    """Vectorised NumPy projection: cameras [n,9] x points [n,3] -> [n,2]."""
    return project_batch_depth(cameras, points)[0]


def camera_centers(cameras: np.ndarray) -> np.ndarray:
    """Camera centers C = -R^T t for [Nc, >=6] blocks laid out
    [angle-axis(3), translation(3), ...].

    THE host definition of "where does this camera sit" — shared by the
    factor registry's triage hooks (factors/{bal,rig,radial}.py) and
    the triage default (robustness/triage.py), so the parallax
    viewing-ray origin can never diverge between factor families.
    """
    return -rotate_batch(-cameras[:, 0:3], cameras[:, 3:6])


LOCALITY_MODES = (None, "ring", "grid")


def _locality_assign(
    anchors: np.ndarray,
    pts_xy: np.ndarray,
    kf: int,
    kc: int,
    n_hi: int,
):
    """k-nearest-anchor windowed visibility: [Np, 2] point positions vs
    [Nc, 2] camera anchors -> (cam_idx, pt_idx) edge streams.

    Each point keeps its `kc` nearest cameras sorted nearest-first, the
    tail points beyond `n_hi` drop down to their `kf` nearest — the
    fractional obs-per-point rule of the base generator, applied in
    DISTANCE order so dropping observations never breaks locality.
    """
    num_points = pts_xy.shape[0]
    num_cameras = anchors.shape[0]
    kc = min(kc, num_cameras)
    kf = min(kf, kc)
    # Chunk the [chunk, Nc] distance/argpartition work over points: a
    # full [Np, Nc] matrix is ~14 GB f64 at venice scale and ~480 GB at
    # BAL-Final — the same host-RAM blowup the base generator's chunked
    # projection loop guards against.  ~5e7 elements per chunk keeps
    # the transient a few hundred MB at any supported scale.
    chunk = max(1, int(50_000_000 // max(num_cameras, 1)))
    near = np.empty((num_points, kc), np.int64)
    # Per-camera running nearest point (for the missing-camera fixup
    # below) — accumulated chunk-wise so no full column is ever needed.
    nearest_pt_d2 = np.full(num_cameras, np.inf)
    nearest_pt = np.zeros(num_cameras, np.int64)
    for lo in range(0, num_points, chunk):
        hi = min(lo + chunk, num_points)
        d2 = np.sum((pts_xy[lo:hi, None, :] - anchors[None, :, :]) ** 2,
                    axis=2)
        if kc < num_cameras:
            nc = np.argpartition(d2, kc - 1, axis=1)[:, :kc]
        else:
            nc = np.broadcast_to(np.arange(num_cameras),
                                 (hi - lo, kc)).copy()
        order = np.argsort(np.take_along_axis(d2, nc, axis=1), axis=1,
                           kind="stable")
        near[lo:hi] = np.take_along_axis(nc, order, axis=1)  # nearest 1st
        cmin = np.argmin(d2, axis=0)
        cd2 = d2[cmin, np.arange(num_cameras)]
        better = cd2 < nearest_pt_d2
        nearest_pt_d2[better] = cd2[better]
        nearest_pt[better] = cmin[better] + lo
    keep = np.ones((num_points, kc), dtype=bool)
    if kc > kf:
        keep[n_hi:, kf:] = False
    cam_idx = near[keep]
    pt_idx = np.broadcast_to(
        np.arange(num_points)[:, None], (num_points, kc))[keep]
    # Guarantee every camera appears: attach a missing camera to its
    # NEAREST point (not a random one — a long-range edge would puncture
    # the banded structure this mode exists to produce).
    missing = np.setdiff1d(np.arange(num_cameras), cam_idx,
                           assume_unique=False)
    if missing.size:
        cam_idx = np.concatenate([cam_idx, missing])
        pt_idx = np.concatenate([pt_idx, nearest_pt[missing]])
    return cam_idx, pt_idx


def make_synthetic_bal(
    num_cameras: int = 4,
    num_points: int = 24,
    obs_per_point: float = 3,
    pixel_noise: float = 0.5,
    param_noise: float = 1e-2,
    seed: int = 0,
    dtype: np.dtype = np.float64,
    n_orphan_points: int = 0,
    n_behind_camera: int = 0,
    n_disconnect: int = 0,
    locality: Optional[str] = None,
) -> SyntheticBAL:
    """Build a well-posed synthetic scene.

    Points live in a unit ball at the origin; cameras sit ~5 units up the
    +z axis with small random rotations, looking down (BAL convention:
    scene depth is negative in the camera frame, matching the -P/P.z
    projection).  Each point is observed by `obs_per_point` distinct
    cameras; every camera gets at least one observation.

    `obs_per_point` may be fractional: a `frac(obs_per_point)` share of
    points gets `ceil` observations, the rest `floor`, so the total edge
    count tracks `num_points * obs_per_point` — this is how the bench
    matches the real BAL datasets' observation counts while keeping the
    point count exact.

    Degeneracy injection (pre-flight triage test fixtures — each knob
    appends a deterministic pathology the robustness/triage.py checks
    must catch; all draws come from the SAME rng, strictly after the
    base scene's draws, so every knob at 0 reproduces the unmodified
    scene byte-for-byte and the make_fleet prefix-stability contract is
    untouched):

    - `n_orphan_points`: points observed by exactly ONE camera (deg-1
      — the predicted-singular-Hll pathology), with a garbage initial
      estimate placed far along the viewing ray (the failed-
      triangulation model: a single ray fixes bearing, not depth).
    - `n_behind_camera`: points placed BEHIND the rig (world z ~ +6,
      cameras look down from z ~ -5), each observed by two cameras —
      every such edge is a cheirality violation at the initial
      estimate.
    - `n_disconnect`: a disconnected island of `n_disconnect` extra
      cameras observing `4 * n_disconnect` extra points that no main
      camera sees (gauge-deficient second component).  With
      n_disconnect = 1 the island's points are additionally deg-1.

    Locality modes (`locality="ring"` / `"grid"`; same strictly-after-
    the-base-draws contract as the degeneracy knobs, so `locality=None`
    reproduces the historical scene byte-for-byte): the base generator
    assigns each point's cameras as `(base + j*stride) mod Nc` — an
    EXPANDER camera graph with no cluster structure, which real BAL
    scenes (street-level ladybug rigs, photo-tourism venice) do not
    have.  A locality mode instead stations cameras on a spatial
    layout (a closed ring of arc anchors, or a ceil(sqrt(Nc))-wide
    grid), scatters points NEAR the camera track, and gives every
    point WINDOWED visibility: its `obs_per_point` nearest cameras.
    Camera co-observation is then banded/blocked — cameras share
    points only with spatial neighbours — producing exactly the
    cluster-constant slow modes the camera-graph coarse-space
    preconditioners (solver/precond.py TWO_LEVEL / MULTILEVEL) exist
    to remove.  The degeneracy knobs compose on top unchanged.
    """
    if locality not in LOCALITY_MODES:
        raise ValueError(
            f"locality must be one of {LOCALITY_MODES}, got {locality!r}")
    for name, v in (("n_orphan_points", n_orphan_points),
                    ("n_behind_camera", n_behind_camera),
                    ("n_disconnect", n_disconnect)):
        if v < 0:
            raise ValueError(f"{name} must be >= 0, got {v}")
    r = np.random.default_rng(seed)
    obs_per_point = min(float(obs_per_point), float(num_cameras))

    points_gt = r.uniform(-1.0, 1.0, size=(num_points, 3))
    cameras_gt = np.zeros((num_cameras, 9))
    cameras_gt[:, 0:3] = r.normal(scale=0.05, size=(num_cameras, 3))  # small tilt
    cameras_gt[:, 3:5] = r.normal(scale=0.2, size=(num_cameras, 2))  # x/y offset
    cameras_gt[:, 5] = -5.0 + r.normal(scale=0.2, size=num_cameras)  # z: scene in front
    cameras_gt[:, 6] = 500.0 + r.normal(scale=5.0, size=num_cameras)  # focal
    cameras_gt[:, 7] = r.normal(scale=1e-4, size=num_cameras)  # k1
    cameras_gt[:, 8] = r.normal(scale=1e-6, size=num_cameras)  # k2

    # k distinct cameras per point, fully vectorised: (base + j*stride) mod
    # Nc for j < k is duplicate-free whenever stride*k <= Nc.  Fractional
    # obs_per_point: the first n_hi points get kc=ceil observations, the
    # rest kf=floor, so the total matches num_points*obs_per_point.
    kf = max(int(np.floor(obs_per_point)), 1)
    kc = int(np.ceil(obs_per_point))
    n_hi = int(round((obs_per_point - kf) * num_points)) if kc > kf else 0
    base = r.integers(0, num_cameras, size=(num_points, 1))
    max_stride = max(num_cameras // max(kc, 1), 1)
    stride = 1 + r.integers(0, max_stride, size=(num_points, 1))
    grid = (base + np.arange(kc)[None, :] * stride) % num_cameras
    keep = np.ones((num_points, kc), dtype=bool)
    if kc > kf:
        keep[n_hi:, kf:] = False
    cam_idx = grid[keep]
    pt_idx = np.broadcast_to(np.arange(num_points)[:, None], (num_points, kc))[keep]
    # Guarantee every camera appears (random draws may miss some).
    missing = np.setdiff1d(np.arange(num_cameras), cam_idx, assume_unique=False)
    if missing.size:
        cam_idx = np.concatenate([cam_idx, missing])
        pt_idx = np.concatenate(
            [pt_idx, r.integers(0, num_points, size=missing.size)])
    if locality is not None:
        # Locality mode: REPLACE the expander observation assignment
        # with a spatial camera layout + windowed visibility.  The base
        # scene's draws above are kept (and burned) so these draws sit
        # strictly after them — locality=None stays byte-identical, and
        # every locality scene is deterministic in (seed, knobs).
        if locality == "ring":
            # Camera anchors on a closed ring; points scattered in an
            # annulus around the camera track (street-scene shape).
            phi = 2.0 * np.pi * np.arange(num_cameras) / num_cameras
            ring_r = 3.0
            anchors = ring_r * np.stack([np.cos(phi), np.sin(phi)], axis=1)
            psi = r.uniform(0.0, 2.0 * np.pi, size=num_points)
            rad = ring_r + r.uniform(-0.8, 0.8, size=num_points)
            pts_xy = np.stack([rad * np.cos(psi), rad * np.sin(psi)], axis=1)
        else:  # grid (aerial-survey shape)
            g = int(np.ceil(np.sqrt(num_cameras)))
            extent = 6.0
            ii = np.arange(num_cameras)
            anchors = ((np.stack([ii % g, ii // g], axis=1) + 0.5)
                       * (extent / g) - extent / 2.0)
            pts_xy = r.uniform(-extent / 2.0, extent / 2.0,
                               size=(num_points, 2))
        points_gt = np.concatenate(
            [pts_xy, r.uniform(-0.5, 0.5, size=(num_points, 1))], axis=1)
        # Cameras keep their base-drawn tilt/intrinsics/z offset; only
        # the xy translation is re-anchored over the layout (t ~ -center
        # under the small drawn tilts), so each camera looks down at its
        # own neighbourhood of the track.
        cameras_gt = cameras_gt.copy()
        cameras_gt[:, 3:5] -= anchors
        cam_idx, pt_idx = _locality_assign(anchors, pts_xy, kf, kc, n_hi)
    # Chunk the projection: at BAL-Final scale (~29M edges) one shot would
    # materialise ~10 float64 [nE,3] temporaries (~7 GB host RAM).
    n_edge_total = cam_idx.shape[0]
    chunk = 4_000_000
    if n_edge_total <= chunk:
        uv = _project_batch(cameras_gt[cam_idx], points_gt[pt_idx])
    else:
        uv = np.empty((n_edge_total, 2))
        for lo in range(0, n_edge_total, chunk):
            hi = min(lo + chunk, n_edge_total)
            uv[lo:hi] = _project_batch(
                cameras_gt[cam_idx[lo:hi]], points_gt[pt_idx[lo:hi]])
    obs = uv + r.normal(scale=pixel_noise, size=uv.shape)

    # ---- degeneracy injection (knob order: orphan, behind, island) ----
    # Draws happen only inside taken branches, strictly after the base
    # scene's draws: all-zero knobs leave the rng stream — and thus the
    # scene — byte-identical to the knob-free generator.
    orphan_rows: Optional[np.ndarray] = None
    orphan_init: Optional[np.ndarray] = None
    if n_orphan_points:
        gt = r.uniform(-1.0, 1.0, size=(n_orphan_points, 3))
        cam = r.integers(0, num_cameras, size=n_orphan_points)
        uv1 = _project_batch(cameras_gt[cam], gt)
        ob1 = uv1 + r.normal(scale=pixel_noise, size=uv1.shape)
        orphan_rows = points_gt.shape[0] + np.arange(n_orphan_points)
        # Failed-triangulation initial estimate: one ray fixes bearing
        # but not depth, so the "triangulated" depth lands far out along
        # the viewing ray from the observing camera's center.
        centers = -rotate_batch(-cameras_gt[cam, 0:3], cameras_gt[cam, 3:6])
        ray = gt - centers
        ray = ray / np.linalg.norm(ray, axis=1, keepdims=True)
        depth_far = np.linalg.norm(gt - centers, axis=1, keepdims=True) \
            * r.uniform(50.0, 150.0, size=(n_orphan_points, 1))
        orphan_init = centers + depth_far * ray
        points_gt = np.concatenate([points_gt, gt])
        cam_idx = np.concatenate([cam_idx, cam])
        pt_idx = np.concatenate([pt_idx, orphan_rows])
        obs = np.concatenate([obs, ob1])
    if n_behind_camera:
        gt = r.uniform(-1.0, 1.0, size=(n_behind_camera, 3))
        gt[:, 2] = 6.0 + r.uniform(0.0, 1.0, size=n_behind_camera)
        rows = points_gt.shape[0] + np.arange(n_behind_camera)
        c1 = r.integers(0, num_cameras, size=n_behind_camera)
        if num_cameras > 1:
            c2 = (c1 + 1 + r.integers(0, num_cameras - 1,
                                      size=n_behind_camera)) % num_cameras
        else:
            c2 = None
        cams_b = [c1] if c2 is None else [c1, c2]
        for cb in cams_b:
            uvb = _project_batch(cameras_gt[cb], gt)
            obb = uvb + r.normal(scale=pixel_noise, size=uvb.shape)
            cam_idx = np.concatenate([cam_idx, cb])
            pt_idx = np.concatenate([pt_idx, rows])
            obs = np.concatenate([obs, obb])
        points_gt = np.concatenate([points_gt, gt])
    if n_disconnect:
        nis = n_disconnect
        isl = np.zeros((nis, 9))
        isl[:, 0:3] = r.normal(scale=0.05, size=(nis, 3))
        isl[:, 3:5] = r.normal(scale=0.2, size=(nis, 2))
        isl[:, 5] = -5.0 + r.normal(scale=0.2, size=nis)
        isl[:, 6] = 500.0 + r.normal(scale=5.0, size=nis)
        isl[:, 7] = r.normal(scale=1e-4, size=nis)
        isl[:, 8] = r.normal(scale=1e-6, size=nis)
        gt = r.uniform(-1.0, 1.0, size=(4 * nis, 3))
        rows = points_gt.shape[0] + np.arange(4 * nis)
        j = np.arange(4 * nis)
        pairs = [j % nis] if nis == 1 else [j % nis, (j + 1) % nis]
        cam_base = cameras_gt.shape[0]
        for cb in pairs:
            uvi = _project_batch(isl[cb], gt)
            obi = uvi + r.normal(scale=pixel_noise, size=uvi.shape)
            cam_idx = np.concatenate([cam_idx, cam_base + cb])
            pt_idx = np.concatenate([pt_idx, rows])
            obs = np.concatenate([obs, obi])
        cameras_gt = np.concatenate([cameras_gt, isl])
        points_gt = np.concatenate([points_gt, gt])

    order = np.argsort(cam_idx, kind="stable")  # BAL files are cam-sorted
    cam_idx = np.asarray(cam_idx, dtype=np.int32)[order]
    pt_idx = np.asarray(pt_idx, dtype=np.int32)[order]
    obs = np.asarray(obs, dtype=dtype)[order]

    cameras0 = cameras_gt + r.normal(scale=param_noise, size=cameras_gt.shape) * np.array(
        [1, 1, 1, 1, 1, 1, 100.0, 1e-3, 1e-5]
    )
    points0 = points_gt + r.normal(scale=param_noise, size=points_gt.shape)
    if orphan_rows is not None:
        points0[orphan_rows] = orphan_init

    # Same ingestion gate as the BAL parsers: a generator bug can no
    # longer hand the solver what a file would have been refused for.
    # The degeneracy knobs stay within the gate by construction — they
    # inject GEOMETRIC/STRUCTURAL pathologies (deg-1, behind-camera,
    # disconnection: the triage layer's jurisdiction), never the
    # non-finite/duplicate poison the parser boundary rejects.
    from megba_tpu.io.bal import validate_problem

    validate_problem(cameras0, points0, obs, cam_idx, pt_idx,
                     where=f"make_synthetic_bal(seed={seed})")

    return SyntheticBAL(
        cameras_gt=cameras_gt.astype(dtype),
        points_gt=points_gt.astype(dtype),
        cameras0=cameras0.astype(dtype),
        points0=points0.astype(dtype),
        obs=obs,
        cam_idx=cam_idx,
        pt_idx=pt_idx,
    )


def make_fleet(
    n_problems: int,
    size_range: Tuple[int, int] = (12, 96),
    rng: Optional[np.random.Generator] = None,
    *,
    seed: int = 0,
    obs_per_point_range: Tuple[float, float] = (2.0, 3.5),
    pixel_noise: float = 0.4,
    param_noise: float = 2e-2,
    dtype: np.dtype = np.float64,
) -> List[SyntheticBAL]:
    """Generate a heterogeneous fleet of small BA problems, reproducibly.

    The one generator the serving tests AND the fleet bench draw from,
    so "16 synthetic problems" means the same 16 scenes everywhere.
    `size_range` bounds the per-problem POINT count (inclusive); the
    camera count scales with it (~1 camera per 8 points, >= 3) and
    `obs_per_point_range` bounds the edge density, so problem i's
    (n_cam, n_pt, n_edge) triple is drawn from `rng` — pass a
    `np.random.default_rng(seed)` or let `seed` build one.

    Determinism contract: problem i's SCENE seed is derived from `seed`
    and i alone (not from the rng draw order), so
    `make_fleet(8, ...)[:4]` and `make_fleet(4, ...)` produce the same
    first four scenes for the same seed — fleets compose and shrink
    without reshuffling their members.
    """
    if n_problems < 1:
        raise ValueError(f"n_problems must be >= 1, got {n_problems}")
    lo, hi = int(size_range[0]), int(size_range[1])
    if not 1 <= lo <= hi:
        raise ValueError(f"bad size_range {size_range}")
    olo, ohi = float(obs_per_point_range[0]), float(obs_per_point_range[1])
    if not 1.0 <= olo <= ohi:
        raise ValueError(f"bad obs_per_point_range {obs_per_point_range}")

    fleet: List[SyntheticBAL] = []
    for i in range(n_problems):
        # Per-problem rng: sizes AND scene content both derive from
        # (seed, i) only — stable under fleet growth.
        r_i = np.random.default_rng(np.random.SeedSequence([seed, i]))
        n_pt = int(r_i.integers(lo, hi + 1))
        n_cam = max(3, n_pt // 8)
        opp = float(r_i.uniform(olo, ohi))
        fleet.append(make_synthetic_bal(
            num_cameras=n_cam, num_points=n_pt, obs_per_point=opp,
            pixel_noise=pixel_noise, param_noise=param_noise,
            seed=int(r_i.integers(0, 2**31 - 1)), dtype=dtype))
    if rng is not None:
        # Caller-supplied rng only shuffles the ORDER (heterogeneous
        # arrival patterns for queue tests); scene content stays pinned.
        rng.shuffle(fleet)
    return fleet
