from megba_tpu.io.synthetic import make_synthetic_bal

__all__ = ["make_synthetic_bal"]
