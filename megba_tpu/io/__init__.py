from megba_tpu.io.synthetic import make_synthetic_bal

__all__ = ["make_synthetic_bal"]

# megba_tpu.io.bal (BAL text format) and megba_tpu.io.g2o (g2o pose
# graphs) are import-on-demand submodules: both pull in jax at import
# time, which io/__init__ keeps off the fast path for host-side tools.
