"""BAL (Bundle Adjustment in the Large) dataset IO.

Text format (one whitespace-separated token stream — the format the
reference's examples parse line-by-line, examples/BAL_Double.cpp:74-139):

    num_cameras num_points num_observations
    cam_idx pt_idx u v                # x num_observations
    <camera parameter>                # x num_cameras x 9
    <point coordinate>                # x num_points x 3

Cameras are 9-dof: angle-axis(3), translation(3), f, k1, k2.

The fast path tokenises the whole file with a single `np.fromfile(sep)`
call (C-speed) instead of per-line parsing; the optional native C++
parser (megba_tpu.native) is used automatically when built, which
matters at Final-13682 scale (4.5M observations).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Union

import numpy as np


@dataclasses.dataclass
class BALFile:
    """Parsed BAL problem."""

    cameras: np.ndarray  # [Nc, 9]
    points: np.ndarray  # [Np, 3]
    obs: np.ndarray  # [nE, 2]
    cam_idx: np.ndarray  # [nE] int32
    pt_idx: np.ndarray  # [nE] int32

    @property
    def num_cameras(self) -> int:
        return self.cameras.shape[0]

    @property
    def num_points(self) -> int:
        return self.points.shape[0]

    @property
    def num_observations(self) -> int:
        return self.obs.shape[0]


def _is_ram_backed(directory: str) -> bool:
    """True when `directory` sits on tmpfs/ramfs (Linux; False elsewhere).

    shutil.disk_usage on tmpfs reports a RAM cap as 'free' space, so a
    size check alone would route large decompressions into memory.
    """
    try:
        best_fs, best_len = "", -1
        # surrogateescape: the kernel passes non-UTF-8 mountpoint bytes
        # through raw; they must not raise out of a path heuristic.
        with open("/proc/mounts", errors="surrogateescape") as f:
            real = os.fsencode(os.path.realpath(directory))
            for line in f:
                parts = line.split()
                if len(parts) < 3:
                    continue
                # /proc/mounts octal-escapes exactly \040 \011 \012 \134
                # (space, tab, newline, backslash); decode those at the
                # byte level so non-ASCII mountpoints compare correctly.
                mnt = os.fsencode(parts[1])
                for esc, raw in ((rb"\040", b" "), (rb"\011", b"\t"),
                                 (rb"\012", b"\n"), (rb"\134", b"\\")):
                    mnt = mnt.replace(esc, raw)
                fstype = parts[2]
                # >= : of duplicate mountpoint entries the LAST one listed
                # is the effective (over)mount.
                if (real == mnt or real.startswith(mnt.rstrip(b"/") + b"/")) \
                        and len(mnt) >= best_len:
                    best_fs, best_len = fstype, len(mnt)
        return best_fs in ("tmpfs", "ramfs")
    except (OSError, ValueError):
        return False


def load_bal(path: Union[str, os.PathLike], dtype=np.float64) -> BALFile:
    """Parse a BAL text file (.txt or the .bz2 the BAL site distributes)."""
    if not os.path.exists(path):
        raise FileNotFoundError(f"BAL file not found: {path}")

    if str(path).lower().endswith(".bz2"):
        # Decompress to a temp file once so the mmap-based native parser
        # still applies; BAL .bz2 expand ~4x (Final-13682 ~350MB text).
        import bz2
        import shutil
        import tempfile

        # Prefer the system temp dir when it is disk-backed and has room
        # for the expanded text (~5x the archive, Final-13682 ~350MB) —
        # expanding next to the archive can fill shared dataset mounts
        # when several jobs load concurrently.  RAM-backed tmpfs temp
        # dirs are skipped (the expansion would eat physical memory the
        # Final-scale parse itself needs); so are full/small mounts.
        need = 5 * os.path.getsize(path) + (64 << 20)
        tmp = tempfile.gettempdir()
        try:
            tmp_ok = (shutil.disk_usage(tmp).free >= need
                      and not _is_ram_backed(tmp))
        except OSError:
            tmp_ok = False
        archive_dir = os.path.dirname(os.path.abspath(path))
        candidates = (None, archive_dir) if tmp_ok else (archive_dir, None)
        last_err = None
        for tmp_dir in candidates:
            try:
                fd, tmp = tempfile.mkstemp(suffix=".txt", dir=tmp_dir)
            except OSError as e:
                last_err = e
                continue
            try:
                with os.fdopen(fd, "wb") as dst, bz2.open(path, "rb") as srcf:
                    shutil.copyfileobj(srcf, dst, length=1 << 24)
                return load_bal(tmp, dtype)
            except OSError as e:
                last_err = e
                continue
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        raise last_err

    try:
        from megba_tpu.native import parse_bal_native

        parsed = parse_bal_native(str(path), dtype)
        if parsed is not None:
            # The native scanner validates token counts/indices; the
            # semantic checks (finiteness, duplicate edges) are shared
            # here so both parsers enforce one contract.
            _validate(parsed, where=str(path))
            return parsed
    except ImportError:
        pass
    except ValueError as exc:
        if _is_semantic_error(exc):
            raise
        # Native parse rejected the file; the NumPy tokenizer is the
        # arbiter (it raises the user-facing error if truly malformed).

    with open(path, "rb") as f:
        tokens = np.fromfile(f, sep=" ")
    return _assemble(tokens, dtype, where=str(path))


def loads_bal(text: str, dtype=np.float64) -> BALFile:
    """Parse BAL content from a string (tests)."""
    tokens = np.array(text.split(), dtype=np.float64)
    return _assemble(tokens, dtype, where="<string>")


def _is_semantic_error(exc: BaseException) -> bool:
    """True for _validate's own rejections (they must not be retried
    through the NumPy tokenizer, which would just re-raise them)."""
    return str(exc).startswith("BAL semantic error")


def validate_problem(cameras: np.ndarray, points: np.ndarray,
                     obs: np.ndarray, cam_idx: np.ndarray,
                     pt_idx: np.ndarray, *, where: str,
                     unique_edges: bool = True) -> None:
    """Reject semantically-poisoned problems with actionable context.

    A single NaN observation silently poisons every psum-reduced cost in
    the solver (the exact failure mode the RobustOption guards contain
    at runtime — but data that arrives broken should be refused at the
    boundary, not recovered from); duplicate (cam, pt) edges double-
    count a factor, which BAL — unlike g2o's repeated-constraint
    convention — never legitimately encodes.

    THE shared ingestion gate: both BAL parsers, the synthetic
    generator (io/synthetic.py) and the serving layer's FleetProblem
    boundary (serving/batcher.py, serving/queue.py) all route through
    this one definition, so no path into the solver accepts what
    another rejects.  Array-based so callers without a BALFile (fleet
    problems, synthetic scenes) pay no repacking.  The pre-flight
    triage checks (robustness/triage.py) are the REPAIRING superset;
    a caller that armed triage skips this gate — triage either fixes
    or typed-rejects the same pathologies with a full HealthReport.
    """
    cam_idx = np.asarray(cam_idx).reshape(-1)
    pt_idx = np.asarray(pt_idx).reshape(-1)
    n_cam, n_pt = int(cameras.shape[0]), int(points.shape[0])
    n_obs = int(cam_idx.shape[0])
    if n_obs and (int(cam_idx.max()) >= n_cam or int(pt_idx.max()) >= n_pt
                  or int(cam_idx.min()) < 0 or int(pt_idx.min()) < 0):
        raise ValueError(
            f"BAL semantic error in {where}: observation indices out of "
            f"range for {n_cam} cameras / {n_pt} points")
    bad = ~np.isfinite(obs).all(axis=1)
    if bad.any():
        i = int(np.argmax(bad))
        raise ValueError(
            f"BAL semantic error in {where}: observation {i} "
            f"(cam {int(cam_idx[i])}, pt {int(pt_idx[i])}) has "
            f"non-finite pixel coordinates {np.asarray(obs)[i].tolist()}")
    bad = ~np.isfinite(cameras).all(axis=1)
    if bad.any():
        i = int(np.argmax(bad))
        raise ValueError(
            f"BAL semantic error in {where}: camera {i} has non-finite "
            f"parameters {np.asarray(cameras)[i].tolist()}")
    bad = ~np.isfinite(points).all(axis=1)
    if bad.any():
        i = int(np.argmax(bad))
        raise ValueError(
            f"BAL semantic error in {where}: point {i} has non-finite "
            f"coordinates {np.asarray(points)[i].tolist()}")
    # Duplicate refusal is FACTOR semantics, not array hygiene: BAL
    # edges are unique by construction, but a rig factor repeats a
    # (body, point) pair once per physical camera and a prior factor
    # may repeat a constraint — such families pass unique_edges=False
    # (factors.FactorSpec.unique_edges) and skip only this check.
    if n_obs and unique_edges:
        key = (cam_idx.astype(np.int64) * np.int64(n_pt)
               + pt_idx.astype(np.int64))
        uniq, first, counts = np.unique(key, return_index=True,
                                        return_counts=True)
        if (counts > 1).any():
            d = int(first[np.argmax(counts > 1)])
            dupes = np.nonzero(key == key[d])[0]
            raise ValueError(
                f"BAL semantic error in {where}: duplicate observation of "
                f"(cam {int(cam_idx[d])}, pt {int(pt_idx[d])}) at "
                f"observation indices {dupes.tolist()} — BAL edges must be "
                "unique (a repeated row double-counts the factor)")


def _validate(bal: BALFile, where: str) -> None:
    """BALFile adapter over the shared array-based gate."""
    validate_problem(bal.cameras, bal.points, bal.obs, bal.cam_idx,
                     bal.pt_idx, where=where)


def _assemble(tokens: np.ndarray, dtype, where: str = "<tokens>") -> BALFile:
    if tokens.size < 3:
        raise ValueError("not a BAL file: missing header")
    n_cam, n_pt, n_obs = (int(t) for t in tokens[:3])
    expect = 3 + 4 * n_obs + 9 * n_cam + 3 * n_pt
    if tokens.size != expect:
        raise ValueError(
            f"BAL token count mismatch: header promises {expect}, file has {tokens.size}"
        )
    ob = tokens[3 : 3 + 4 * n_obs].reshape(n_obs, 4)
    if not np.isfinite(ob[:, :2]).all():
        i = int(np.argmax(~np.isfinite(ob[:, :2]).all(axis=1)))
        raise ValueError(
            f"BAL semantic error in {where}: observation {i} has a "
            "non-finite camera/point index")
    cam_idx = ob[:, 0].astype(np.int32)
    pt_idx = ob[:, 1].astype(np.int32)
    obs = ob[:, 2:4].astype(dtype)
    if n_obs and (cam_idx.max() >= n_cam or pt_idx.max() >= n_pt or cam_idx.min() < 0 or pt_idx.min() < 0):
        raise ValueError("BAL observation indices out of range")
    off = 3 + 4 * n_obs
    cameras = tokens[off : off + 9 * n_cam].reshape(n_cam, 9).astype(dtype)
    off += 9 * n_cam
    points = tokens[off : off + 3 * n_pt].reshape(n_pt, 3).astype(dtype)
    bal = BALFile(cameras=cameras, points=points, obs=obs, cam_idx=cam_idx, pt_idx=pt_idx)
    _validate(bal, where=where)
    return bal


def save_bal(path: Union[str, os.PathLike], bal: BALFile) -> None:
    """Write a BAL text file (round-trips with load_bal)."""
    with open(path, "w") as f:
        f.write(f"{bal.num_cameras} {bal.num_points} {bal.num_observations}\n")
        for c, p, (u, v) in zip(bal.cam_idx, bal.pt_idx, bal.obs):
            f.write(f"{int(c)} {int(p)} {u:.17g} {v:.17g}\n")
        for cam in bal.cameras:
            f.write("\n".join(f"{x:.17g}" for x in cam) + "\n")
        for pt in bal.points:
            f.write("\n".join(f"{x:.17g}" for x in pt) + "\n")
