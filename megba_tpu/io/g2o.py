"""g2o text-format pose graphs: read, write, solve.

The reference advertises a g2o-compatible object API but has no file
ingestion beyond BAL (examples/BAL_Double.cpp:74-139 is its only
loader).  Real pose-graph datasets (sphere2500, garage, manhattan,
intel, ...) ship as `.g2o` text, so this module closes the loop for the
PGO family (models/pgo.py): parse -> solve on the TPU pipeline -> write
back.

Supported records
-----------------
- ``VERTEX_SE3:QUAT id x y z qx qy qz qw``
- ``EDGE_SE3:QUAT i j x y z qx qy qz qw  <21 upper-tri info entries>``
- ``VERTEX_SE2 id x y theta``
- ``EDGE_SE2 i j dx dy dtheta  <6 upper-tri info entries>``
- ``FIX id``  (gauge anchors; default: lowest vertex id)

SE(2) records are lifted into the SE(3) solver: theta becomes a z-axis
rotation, (x, y) an in-plane translation, and the lifted information
matrix gets unit weight on the three out-of-plane error rows — every
edge then constrains relative out-of-plane motion to zero, which is
exactly the planar-rigidity the SE(2) graph encodes.

Information-matrix convention
-----------------------------
g2o orders the SE(3) error as [translation, rotation-(qx,qy,qz)]; our
residual (models/pgo.py:between_residual) is [log_SO3, translation].
The reader permutes rows/columns accordingly and applies the
quaternion-vector -> log-map chart factor (dq ~= d(aa)/2 to first
order): rotation rows AND columns are scaled by 1/2, so
``r_ours^T Omega_ours r_ours == r_g2o^T Omega_g2o r_g2o`` for small
errors.  ``solve_g2o`` hands the solver a matrix square root W of each
Omega (symmetric-eigendecomposition based, so positive-semidefinite
info factors cleanly; ||W r||^2 = r^T Omega r).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, TextIO, Union

import numpy as np

import jax
import jax.numpy as jnp

from megba_tpu.ops import geo

# Our residual row order is [rotation (log map), translation]; g2o's is
# [translation, quaternion vector].  _PERM maps our row a to g2o row
# _PERM[a].
_PERM = np.array([3, 4, 5, 0, 1, 2])


@dataclasses.dataclass
class G2OGraph:
    """A pose graph in the solver's native coordinates.

    poses [N, 6] = [angle_axis, translation] (SE(2) inputs lifted);
    info [nE, 6, 6] is in OUR row order (rotation first, chart-corrected
    — see module docstring); ids holds the original g2o vertex ids in
    index order so writers can round-trip non-contiguous numbering.
    """

    poses: np.ndarray
    edge_i: np.ndarray
    edge_j: np.ndarray
    meas: np.ndarray
    info: np.ndarray
    fixed: np.ndarray
    ids: np.ndarray
    se2: bool = False


def _upper_tri_to_full(vals: Sequence[float], n: int) -> np.ndarray:
    m = np.zeros((n, n))
    k = 0
    for a in range(n):
        for b in range(a, n):
            m[a, b] = m[b, a] = vals[k]
            k += 1
    return m


def _quat_xyzw_to_aa(q_xyzw: np.ndarray) -> np.ndarray:
    """[..., 4] (qx,qy,qz,qw) -> [..., 3] angle-axis (host-side)."""
    q_wxyz = np.concatenate([q_xyzw[..., 3:4], q_xyzw[..., :3]], axis=-1)
    return np.asarray(
        jax.vmap(geo.quaternion_to_angle_axis)(
            jnp.asarray(q_wxyz.reshape(-1, 4))),
        dtype=np.float64).reshape(*q_xyzw.shape[:-1], 3)


def _aa_to_quat_xyzw(aa: np.ndarray) -> np.ndarray:
    """[..., 3] angle-axis -> [..., 4] (qx,qy,qz,qw) via R (host-side)."""
    q_wxyz = np.asarray(
        jax.vmap(lambda a: geo.rotation_matrix_to_quaternion(
            geo.angle_axis_to_rotation_matrix(a)))(
                jnp.asarray(aa.reshape(-1, 3))),
        dtype=np.float64)
    return np.concatenate(
        [q_wxyz[:, 1:4], q_wxyz[:, 0:1]],
        axis=-1).reshape(*aa.shape[:-1], 4)


def _info_g2o_to_ours(info_g2o: np.ndarray) -> np.ndarray:
    """Permute [t, q] -> [rot, t] and apply the dq = d(aa)/2 chart."""
    m = info_g2o[np.ix_(_PERM, _PERM)]
    scale = np.array([0.5, 0.5, 0.5, 1.0, 1.0, 1.0])
    return m * scale[:, None] * scale[None, :]


def _info_ours_to_g2o(info_ours: np.ndarray) -> np.ndarray:
    inv = np.argsort(_PERM)
    scale = np.array([0.5, 0.5, 0.5, 1.0, 1.0, 1.0])
    m = info_ours / (scale[:, None] * scale[None, :])
    return m[np.ix_(inv, inv)]


def _lift_se2_info(info3: np.ndarray) -> np.ndarray:
    """SE(2) info over (x, y, theta) -> our 6x6 [rot, t] order.

    In-plane entries land on rows [rz(=2), tx(=3), ty(=4)]; the three
    out-of-plane rows (rx, ry, tz) get unit weight so lifted edges pin
    relative out-of-plane motion to zero.
    """
    out = np.eye(6)
    # our row indices: theta -> 2 (z rotation), x -> 3, y -> 4
    idx = np.array([3, 4, 2])  # g2o (x, y, theta) -> our rows
    out[np.ix_(idx, idx)] = info3
    return out


def read_g2o(source: Union[str, TextIO]) -> G2OGraph:
    """Parse a .g2o file (SE3:QUAT or SE2 records; FIX supported)."""
    if isinstance(source, str):
        with open(source) as f:
            return read_g2o(f)

    # Parse into flat host lists first; the quaternion -> angle-axis
    # conversions happen ONCE on the batched arrays afterwards (a vmap
    # dispatch per line would cost a blocking JAX round-trip each on
    # files with thousands of records).
    verts: dict[int, np.ndarray] = {}  # vid -> [t(3), quat_xyzw(4)]
    fixed_ids: set[int] = set()
    edges: list[tuple[int, int, np.ndarray, np.ndarray]] = []  # raw 7 + info
    se2_seen = False
    se3_seen = False

    for ln, line in enumerate(source, 1):
        tok = line.split()
        if not tok or tok[0].startswith("#"):
            continue
        tag = tok[0]
        if tag == "VERTEX_SE3:QUAT":
            vals = np.array([float(x) for x in tok[2:]])
            if vals.shape[0] != 7:
                raise ValueError(
                    f"line {ln}: VERTEX_SE3:QUAT needs 7 values "
                    f"(x y z qx qy qz qw), got {vals.shape[0]}")
            verts[int(tok[1])] = vals
            se3_seen = True
        elif tag == "VERTEX_SE2":
            if len(tok) != 5:
                raise ValueError(
                    f"line {ln}: VERTEX_SE2 needs 3 values (x y theta), "
                    f"got {len(tok) - 2}")
            x, y, th = (float(v) for v in tok[2:5])
            # theta as a z-axis quaternion, converted with the batch.
            verts[int(tok[1])] = np.array([x, y, 0.0, 0.0, 0.0,
                                           np.sin(th / 2), np.cos(th / 2)])
            se2_seen = True
        elif tag == "EDGE_SE3:QUAT":
            i, j = int(tok[1]), int(tok[2])
            vals = np.array([float(x) for x in tok[3:]])
            if vals.shape[0] != 7 + 21:
                raise ValueError(
                    f"line {ln}: EDGE_SE3:QUAT needs 7 measurement + 21 "
                    f"info values, got {vals.shape[0]}")
            info = _info_g2o_to_ours(_upper_tri_to_full(vals[7:], 6))
            edges.append((i, j, vals[:7], info))
            se3_seen = True
        elif tag == "EDGE_SE2":
            i, j = int(tok[1]), int(tok[2])
            vals = np.array([float(x) for x in tok[3:]])
            if vals.shape[0] != 3 + 6:
                raise ValueError(
                    f"line {ln}: EDGE_SE2 needs 3 measurement + 6 info "
                    f"values, got {vals.shape[0]}")
            dx, dy, dth = vals[:3]
            raw = np.array([dx, dy, 0.0, 0.0, 0.0,
                            np.sin(dth / 2), np.cos(dth / 2)])
            info = _lift_se2_info(_upper_tri_to_full(vals[3:], 3))
            edges.append((i, j, raw, info))
            se2_seen = True
        elif tag == "FIX":
            fixed_ids.update(int(t) for t in tok[1:])
        # Unknown tags (VERTEX_TRACKXYZ, landmark edges, ...) are
        # skipped: partial ingestion of mixed graphs is standard g2o
        # tool behaviour.

    if not verts:
        raise ValueError("no supported VERTEX records found")
    ids = np.array(sorted(verts), dtype=np.int64)
    index = {vid: k for k, vid in enumerate(ids)}
    raw_v = np.stack([verts[vid] for vid in ids])  # [N, 7]
    poses = np.concatenate(
        [_quat_xyzw_to_aa(raw_v[:, 3:7]), raw_v[:, :3]], axis=1)

    n_e = len(edges)
    edge_i = np.zeros(n_e, np.int32)
    edge_j = np.zeros(n_e, np.int32)
    raw_e = np.zeros((n_e, 7))
    info = np.zeros((n_e, 6, 6))
    for k, (i, j, raw, om) in enumerate(edges):
        if i not in index or j not in index:
            raise ValueError(f"edge ({i}, {j}) references unknown vertex")
        edge_i[k] = index[i]
        edge_j[k] = index[j]
        raw_e[k] = raw
        info[k] = om
    meas = (np.concatenate(
        [_quat_xyzw_to_aa(raw_e[:, 3:7]), raw_e[:, :3]], axis=1)
        if n_e else np.zeros((0, 6)))

    fixed = np.zeros(len(ids), bool)
    for vid in fixed_ids:
        if vid in index:
            fixed[index[vid]] = True
    if not fixed.any():
        fixed[0] = True  # gauge anchor, same default as solve_pgo

    return G2OGraph(poses=poses, edge_i=edge_i, edge_j=edge_j, meas=meas,
                    info=info, fixed=fixed, ids=ids,
                    se2=se2_seen and not se3_seen)


def write_g2o(dest: Union[str, TextIO], graph: G2OGraph,
              poses: Optional[np.ndarray] = None) -> None:
    """Write SE3:QUAT records (optionally with updated poses).

    Always writes the SE(3) form — lifted SE(2) graphs round-trip
    through it losslessly (z/roll/pitch stay zero at the optimum).
    """
    if isinstance(dest, str):
        with open(dest, "w") as f:
            write_g2o(f, graph, poses)
        return

    p = np.asarray(graph.poses if poses is None else poses)
    quat_v = _aa_to_quat_xyzw(p[:, :3])
    for k, vid in enumerate(graph.ids):
        t = p[k, 3:]
        q = quat_v[k]
        dest.write(
            f"VERTEX_SE3:QUAT {int(vid)} "
            f"{t[0]:.9g} {t[1]:.9g} {t[2]:.9g} "
            f"{q[0]:.9g} {q[1]:.9g} {q[2]:.9g} {q[3]:.9g}\n")
    for k in range(len(graph.ids)):
        if graph.fixed[k]:
            dest.write(f"FIX {int(graph.ids[k])}\n")
    meas_q = _aa_to_quat_xyzw(graph.meas[:, :3])
    for e in range(graph.edge_i.shape[0]):
        m_t = graph.meas[e, 3:]
        q = meas_q[e]
        om = _info_ours_to_g2o(graph.info[e])
        tri = " ".join(
            f"{om[a, b]:.9g}" for a in range(6) for b in range(a, 6))
        dest.write(
            f"EDGE_SE3:QUAT {int(graph.ids[graph.edge_i[e]])} "
            f"{int(graph.ids[graph.edge_j[e]])} "
            f"{m_t[0]:.9g} {m_t[1]:.9g} {m_t[2]:.9g} "
            f"{q[0]:.9g} {q[1]:.9g} {q[2]:.9g} {q[3]:.9g} {tri}\n")


def sqrt_info_of(graph: G2OGraph) -> Optional[np.ndarray]:
    """Matrix square-root weights W of the edge info matrices.

    ||W r||^2 = r^T Omega r, i.e. W^T W = Omega.  Uses a symmetric
    eigendecomposition rather than Cholesky so positive-SEMIdefinite
    matrices (a zero row = deliberately unconstrained DOF, common in
    partial-sensor exports) factor cleanly instead of crashing; small
    negative eigenvalues from text round-off are clamped to zero.
    Returns None when every info matrix is the identity (the unweighted
    fast path).
    """
    if np.allclose(graph.info, np.eye(6)[None]):
        return None
    w, v = np.linalg.eigh(graph.info)  # Omega = V diag(w) V^T
    floor = -1e-9 * np.maximum(w.max(axis=-1, keepdims=True), 1.0)
    bad = np.nonzero((w < floor).any(axis=-1))[0]
    if bad.size:
        raise ValueError(
            f"edge {int(bad[0])} (of {len(w)}) has an indefinite "
            f"information matrix (eigenvalues {w[bad[0]]})")
    # W = diag(sqrt(w)) V^T satisfies W^T W = Omega.
    return np.sqrt(np.maximum(w, 0.0))[:, :, None] * np.transpose(
        v, (0, 2, 1))


def solve_g2o(source, option=None, verbose: bool = False):
    """Read (path / file / G2OGraph), solve, return (graph, PGOResult)."""
    from megba_tpu.models.pgo import solve_pgo

    graph = source if isinstance(source, G2OGraph) else read_g2o(source)
    result = solve_pgo(
        graph.poses, graph.edge_i, graph.edge_j, graph.meas,
        option, sqrt_info=sqrt_info_of(graph), fixed=graph.fixed,
        verbose=verbose)
    return graph, result
