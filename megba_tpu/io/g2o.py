"""g2o text-format pose graphs: read, write, solve.

The reference advertises a g2o-compatible object API but has no file
ingestion beyond BAL (examples/BAL_Double.cpp:74-139 is its only
loader).  Real pose-graph datasets (sphere2500, garage, manhattan,
intel, ...) ship as `.g2o` text, so this module closes the loop for the
PGO family (models/pgo.py): parse -> solve on the TPU pipeline -> write
back.

Supported records
-----------------
- ``VERTEX_SE3:QUAT id x y z qx qy qz qw``
- ``EDGE_SE3:QUAT i j x y z qx qy qz qw  <21 upper-tri info entries>``
- ``VERTEX_SE2 id x y theta``
- ``EDGE_SE2 i j dx dy dtheta  <6 upper-tri info entries>``
- ``EDGE_SE3_PRIOR id x y z qx qy qz qw  <21 upper-tri info entries>``
  (unary pose prior — GPS/INS/surveyed-station anchors; parsed into
  ``G2OGraph.prior_idx/prior_meas/prior_info`` and folded into the
  solve as unary prior factors.  The g2o variant carrying an offset
  PARAMS id is refused with a typed error: silently ignoring a
  non-identity sensor offset would corrupt the anchor.)
- ``VERTEX_SIM3:QUAT id x y z qx qy qz qw s``  (s = scale > 0)
- ``EDGE_SIM3:QUAT i j x y z qx qy qz qw s  <28 upper-tri info entries>``
  (scale-aware pose graphs — monocular loop closing; solved through
  the ``sim3_between`` factor, factors/sim3.py.  Sim(3) and SE(2)/SE(3)
  records cannot be mixed in one file — typed error naming the line.
  The 7x7 information is over our error chart order lifted to the file
  order [t, q, log-scale]; rotation rows carry the same dq = d(aa)/2
  chart factor as SE(3).)
- ``FIX id``  (gauge anchors; default: lowest vertex id)

SE(2) records are lifted into the SE(3) solver: theta becomes a z-axis
rotation, (x, y) an in-plane translation, and the lifted information
matrix gets unit weight on the three out-of-plane error rows — every
edge then constrains relative out-of-plane motion to zero, which is
exactly the planar-rigidity the SE(2) graph encodes.

Information-matrix convention
-----------------------------
g2o orders the SE(3) error as [translation, rotation-(qx,qy,qz)]; our
residual (models/pgo.py:between_residual) is [log_SO3, translation].
The reader permutes rows/columns accordingly and applies the
quaternion-vector -> log-map chart factor (dq ~= d(aa)/2 to first
order): rotation rows AND columns are scaled by 1/2, so
``r_ours^T Omega_ours r_ours == r_g2o^T Omega_g2o r_g2o`` for small
errors.  ``solve_g2o`` hands the solver a matrix square root W of each
Omega (symmetric-eigendecomposition based, so positive-semidefinite
info factors cleanly; ||W r||^2 = r^T Omega r).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, TextIO, Union

import numpy as np

from megba_tpu.core.host_se3 import aa_to_quat, quat_to_aa

# Our residual row order is [rotation (log map), translation]; g2o's is
# [translation, quaternion vector].  _PERM maps our row a to g2o row
# _PERM[a].
_PERM = np.array([3, 4, 5, 0, 1, 2])

# Row/col pairs of the g2o upper-triangular info serialization, row
# major: (0,0) (0,1) ... (0,5) (1,1) ... (5,5).
_TRIU = np.triu_indices(6)


@dataclasses.dataclass
class G2OGraph:
    """A pose graph in the solver's native coordinates.

    poses [N, 6] = [angle_axis, translation] (SE(2) inputs lifted);
    info [nE, 6, 6] is in OUR row order (rotation first, chart-corrected
    — see module docstring); ids holds the original g2o vertex ids in
    index order so writers can round-trip non-contiguous numbering.
    """

    poses: np.ndarray
    edge_i: np.ndarray
    edge_j: np.ndarray
    meas: np.ndarray
    info: np.ndarray
    fixed: np.ndarray
    ids: np.ndarray
    se2: bool = False
    # Whether the source file carried explicit FIX records.  read_g2o
    # defaults fixed[0]=True when none were present (the solver needs a
    # gauge anchor), but write_g2o must not materialize that default as
    # a FIX line the original file never had — external g2o consumers
    # treat FIX as a semantic statement about gauge handling.
    had_fix: bool = True
    # Unary pose priors (EDGE_SE3_PRIOR records): anchored vertex
    # indices (into `poses`), prior poses [P, 6] in our chart, and the
    # chart-corrected [P, 6, 6] information.  Empty on files without
    # prior records.
    prior_idx: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int32))
    prior_meas: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros((0, 6)))
    prior_info: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros((0, 6, 6)))
    # Scale-aware graph (VERTEX/EDGE_SIM3:QUAT): poses/meas are then
    # [*, 7] = [angle_axis, translation, log-scale] and info [*, 7, 7];
    # solve_g2o dispatches the sim3_between factor.
    sim3: bool = False


def _upper_tri_to_full_batch(tri: np.ndarray, n: int = 6) -> np.ndarray:
    """[..., n(n+1)/2] row-major upper-tri values -> [..., n, n] full."""
    rows, cols = np.triu_indices(n)
    m = np.zeros((*tri.shape[:-1], n, n))
    m[..., rows, cols] = tri
    m[..., cols, rows] = tri
    return m


# Vectorised host-side chart maps (shared with the synthetic pose-graph
# generator; see core/host_se3.py for the branch/double-cover details).
_quat_xyzw_to_aa = quat_to_aa
_aa_to_quat_xyzw = aa_to_quat


_CHART_SCALE = np.array([0.5, 0.5, 0.5, 1.0, 1.0, 1.0])


def _info_g2o_to_ours(info_g2o: np.ndarray) -> np.ndarray:
    """Permute [t, q] -> [rot, t] and apply the dq = d(aa)/2 chart.

    Batched: works on [..., 6, 6].
    """
    m = info_g2o[..., _PERM[:, None], _PERM[None, :]]
    return m * _CHART_SCALE[:, None] * _CHART_SCALE[None, :]


def _info_ours_to_g2o(info_ours: np.ndarray) -> np.ndarray:
    inv = np.argsort(_PERM)
    m = info_ours / (_CHART_SCALE[:, None] * _CHART_SCALE[None, :])
    return m[..., inv[:, None], inv[None, :]]


# Sim(3): our residual row order is [rotation log map, translation,
# log-scale]; the file order is [translation, quaternion vector,
# log-scale].  Rotation rows carry the same dq = d(aa)/2 chart factor;
# the scale row is already in log coordinates on both sides.
_PERM7 = np.array([3, 4, 5, 0, 1, 2, 6])
_TRIU7 = np.triu_indices(7)
_CHART_SCALE7 = np.array([0.5, 0.5, 0.5, 1.0, 1.0, 1.0, 1.0])


def _info7_g2o_to_ours(info_g2o: np.ndarray) -> np.ndarray:
    m = info_g2o[..., _PERM7[:, None], _PERM7[None, :]]
    return m * _CHART_SCALE7[:, None] * _CHART_SCALE7[None, :]


def _info7_ours_to_g2o(info_ours: np.ndarray) -> np.ndarray:
    inv = np.argsort(_PERM7)
    m = info_ours / (_CHART_SCALE7[:, None] * _CHART_SCALE7[None, :])
    return m[..., inv[:, None], inv[None, :]]


def _lift_se2_info(info3: np.ndarray) -> np.ndarray:
    """SE(2) info over (x, y, theta) [..., 3, 3] -> our 6x6 [rot, t].

    In-plane entries land on rows [rz(=2), tx(=3), ty(=4)]; the three
    out-of-plane rows (rx, ry, tz) get unit weight so lifted edges pin
    relative out-of-plane motion to zero.
    """
    out = np.tile(np.eye(6), (*info3.shape[:-2], 1, 1))
    # our row indices: theta -> 2 (z rotation), x -> 3, y -> 4
    idx = np.array([3, 4, 2])  # g2o (x, y, theta) -> our rows
    out[..., idx[:, None], idx[None, :]] = info3
    return out


def _assemble_sim3(s_verts, s_e_ids, s_e_vals, s_e_lns, fixed_ids,
                   had_fix) -> "G2OGraph":
    """Batch-assemble a VERTEX/EDGE_SIM3:QUAT graph (poses/meas [*, 7]
    = [angle_axis, translation, log-scale])."""
    if not s_verts:
        raise ValueError("no supported VERTEX records found")
    ids = np.array(sorted(s_verts), dtype=np.int64)
    index = {vid: k for k, vid in enumerate(ids)}

    raw_v = np.asarray([s_verts[vid][0] for vid in ids],
                       np.float64).reshape(-1, 8)
    bad_v = ~np.isfinite(raw_v).all(axis=1)
    if bad_v.any():
        k = int(np.argmax(bad_v))
        vid = int(ids[k])
        raise ValueError(
            f"line {s_verts[vid][1]}: VERTEX {vid} has non-finite "
            "values — a NaN/inf estimate would poison every solver "
            "reduction; fix or drop the record")
    bad_s = raw_v[:, 7] <= 0
    if bad_s.any():
        k = int(np.argmax(bad_s))
        vid = int(ids[k])
        raise ValueError(
            f"line {s_verts[vid][1]}: VERTEX_SIM3:QUAT {vid} has "
            f"non-positive scale {raw_v[k, 7]:g} — a sim(3) scale must "
            "be > 0 (the chart stores log-scale)")
    poses = np.concatenate(
        [_quat_xyzw_to_aa(raw_v[:, 3:7]), raw_v[:, :3],
         np.log(raw_v[:, 7:8])], axis=1)

    n_e = len(s_e_ids)
    for (a, b), ln in zip(s_e_ids, s_e_lns):
        if a not in index or b not in index:
            missing = a if a not in index else b
            raise ValueError(
                f"line {ln}: EDGE_SIM3:QUAT references unknown vertex "
                f"{missing}")
    edge_i = np.asarray([index[i] for i, _ in s_e_ids],
                        np.int32).reshape(n_e)
    edge_j = np.asarray([index[j] for _, j in s_e_ids],
                        np.int32).reshape(n_e)
    if n_e:
        raw_e = np.asarray(s_e_vals, np.float64).reshape(-1, 36)
        bad_e = ~np.isfinite(raw_e).all(axis=1)
        if bad_e.any():
            k = int(np.argmax(bad_e))
            raise ValueError(
                f"line {s_e_lns[k]}: EDGE {s_e_ids[k][0]} -> "
                f"{s_e_ids[k][1]} has non-finite "
                "measurement/information values — a NaN/inf factor "
                "would poison every solver reduction; fix or drop the "
                "record")
        bad_ms = raw_e[:, 7] <= 0
        if bad_ms.any():
            k = int(np.argmax(bad_ms))
            raise ValueError(
                f"line {s_e_lns[k]}: EDGE_SIM3:QUAT {s_e_ids[k][0]} -> "
                f"{s_e_ids[k][1]} has non-positive scale "
                f"{raw_e[k, 7]:g} — a sim(3) scale must be > 0")
        meas = np.concatenate(
            [_quat_xyzw_to_aa(raw_e[:, 3:7]), raw_e[:, :3],
             np.log(raw_e[:, 7:8])], axis=1)
        info = _info7_g2o_to_ours(
            _upper_tri_to_full_batch(raw_e[:, 8:], 7))
    else:
        meas = np.zeros((0, 7))
        info = np.zeros((0, 7, 7))

    fixed = np.zeros(len(ids), bool)
    for vid in fixed_ids:
        if vid in index:
            fixed[index[vid]] = True
    had_fix = had_fix and bool(fixed.any())
    if not fixed.any():
        fixed[0] = True
    return G2OGraph(poses=poses, edge_i=edge_i, edge_j=edge_j, meas=meas,
                    info=info, fixed=fixed, ids=ids, se2=False,
                    had_fix=had_fix, sim3=True)


def _open_text(path: str, mode: str = "rt"):
    """Open a (possibly .gz / .bz2 compressed) text file — public
    pose-graph datasets ship in all three forms."""
    lower = path.lower()
    if lower.endswith(".gz"):
        import gzip

        return gzip.open(path, mode)
    if lower.endswith(".bz2"):
        import bz2

        return bz2.open(path, mode)
    return open(path, mode)


def read_g2o(source: Union[str, TextIO]) -> G2OGraph:
    """Parse a .g2o file (SE3:QUAT or SE2 records; FIX supported;
    .gz/.bz2 transparently decompressed)."""
    if isinstance(source, str):
        with _open_text(source) as f:
            return read_g2o(f)

    # Parse into flat per-tag token lists first; ALL numeric work (float
    # conversion, tri -> full info expansion, permutation/chart, quat ->
    # angle-axis) happens once on batched numpy arrays afterwards — a
    # per-line conversion costs more than the whole batched pass on
    # files with tens of thousands of records.
    verts: dict[int, tuple[bool, list, int]] = {}  # vid -> (se2, toks, ln)
    fixed_ids: set[int] = set()
    e_ids: list[tuple[int, int]] = []
    e_se2: list[bool] = []
    e_vals: list[list] = []  # SE3: 28 tokens; SE2: 9 tokens
    e_lns: list[int] = []  # source line of each edge (error context)
    p_ids: list[int] = []  # EDGE_SE3_PRIOR anchored vertex ids
    p_vals: list[list] = []  # 28 tokens (7 meas + 21 info)
    p_lns: list[int] = []
    s_verts: dict[int, tuple[list, int]] = {}  # sim3 vid -> (toks, ln)
    s_e_ids: list[tuple[int, int]] = []
    s_e_vals: list[list] = []  # 36 tokens (8 meas + 28 info)
    s_e_lns: list[int] = []
    se2_seen = False
    se3_seen = False
    sim3_seen = False
    had_fix = False

    def _no_mix(ln: int, tag: str) -> None:
        # Sim(3) and SE(2)/SE(3) records describe different state
        # manifolds; a mixed file has no single solver to go to.
        if tag.startswith(("VERTEX_SIM3", "EDGE_SIM3")):
            if se3_seen or se2_seen or p_ids:
                raise ValueError(
                    f"line {ln}: {tag} cannot be mixed with "
                    "SE(2)/SE(3) records in one file — split the graph")
        elif sim3_seen:
            raise ValueError(
                f"line {ln}: {tag} cannot be mixed with SIM3 records "
                "in one file — split the graph")

    for ln, line in enumerate(source, 1):
        tok = line.split()
        if not tok or tok[0].startswith("#"):
            continue
        tag = tok[0]
        if tag == "VERTEX_SE3:QUAT":
            _no_mix(ln, tag)
            if len(tok) != 9:
                raise ValueError(
                    f"line {ln}: VERTEX_SE3:QUAT needs 7 values "
                    f"(x y z qx qy qz qw), got {max(0, len(tok) - 2)} "
                    f"({len(tok)} tokens)")
            vid = int(tok[1])
            if vid in verts:
                raise ValueError(f"line {ln}: duplicate VERTEX id {vid}")
            verts[vid] = (False, tok[2:], ln)
            se3_seen = True
        elif tag == "VERTEX_SE2":
            _no_mix(ln, tag)
            if len(tok) != 5:
                raise ValueError(
                    f"line {ln}: VERTEX_SE2 needs 3 values (x y theta), "
                    f"got {max(0, len(tok) - 2)} ({len(tok)} tokens)")
            vid = int(tok[1])
            if vid in verts:
                raise ValueError(f"line {ln}: duplicate VERTEX id {vid}")
            verts[vid] = (True, tok[2:], ln)
            se2_seen = True
        elif tag == "EDGE_SE3:QUAT":
            _no_mix(ln, tag)
            if len(tok) != 3 + 7 + 21:
                raise ValueError(
                    f"line {ln}: EDGE_SE3:QUAT needs 7 measurement + 21 "
                    f"info values, got {max(0, len(tok) - 3)} "
                    f"({len(tok)} tokens)")
            e_ids.append((int(tok[1]), int(tok[2])))
            e_se2.append(False)
            e_vals.append(tok[3:])
            e_lns.append(ln)
            se3_seen = True
        elif tag == "EDGE_SE2":
            _no_mix(ln, tag)
            if len(tok) != 3 + 3 + 6:
                raise ValueError(
                    f"line {ln}: EDGE_SE2 needs 3 measurement + 6 info "
                    f"values, got {max(0, len(tok) - 3)} "
                    f"({len(tok)} tokens)")
            e_ids.append((int(tok[1]), int(tok[2])))
            e_se2.append(True)
            e_vals.append(tok[3:])
            e_lns.append(ln)
            se2_seen = True
        elif tag == "EDGE_SE3_PRIOR":
            _no_mix(ln, tag)
            # Our dialect: 1 vertex id + 7 measurement + 21 info = 29
            # tokens.  The upstream g2o type ALSO carries an offset
            # PARAMS id as token 2 (30 tokens) — refused typed rather
            # than mis-read: swallowing a sensor-offset transform would
            # silently anchor the pose to the wrong frame.
            if len(tok) == 2 + 1 + 7 + 21:
                raise ValueError(
                    f"line {ln}: EDGE_SE3_PRIOR with an offset PARAMS "
                    "id (30-token upstream-g2o form) is not supported "
                    "— bake the sensor offset into the measurement and "
                    "drop the id")
            if len(tok) != 2 + 7 + 21:
                raise ValueError(
                    f"line {ln}: EDGE_SE3_PRIOR needs 7 measurement + "
                    f"21 info values after the vertex id, got "
                    f"{max(0, len(tok) - 2)} ({len(tok)} tokens)")
            p_ids.append(int(tok[1]))
            p_vals.append(tok[2:])
            p_lns.append(ln)
            se3_seen = True
        elif tag == "VERTEX_SIM3:QUAT":
            _no_mix(ln, tag)
            if len(tok) != 10:
                raise ValueError(
                    f"line {ln}: VERTEX_SIM3:QUAT needs 8 values "
                    f"(x y z qx qy qz qw s), got "
                    f"{max(0, len(tok) - 2)} ({len(tok)} tokens)")
            vid = int(tok[1])
            if vid in s_verts:
                raise ValueError(f"line {ln}: duplicate VERTEX id {vid}")
            s_verts[vid] = (tok[2:], ln)
            sim3_seen = True
        elif tag == "EDGE_SIM3:QUAT":
            _no_mix(ln, tag)
            if len(tok) != 3 + 8 + 28:
                raise ValueError(
                    f"line {ln}: EDGE_SIM3:QUAT needs 8 measurement + "
                    f"28 info values, got {max(0, len(tok) - 3)} "
                    f"({len(tok)} tokens)")
            s_e_ids.append((int(tok[1]), int(tok[2])))
            s_e_vals.append(tok[3:])
            s_e_lns.append(ln)
            sim3_seen = True
        elif tag == "FIX":
            had_fix = True
            fixed_ids.update(int(t) for t in tok[1:])
        # Unknown tags (VERTEX_TRACKXYZ, landmark edges, ...) are
        # skipped: partial ingestion of mixed graphs is standard g2o
        # tool behaviour.

    if sim3_seen:
        return _assemble_sim3(s_verts, s_e_ids, s_e_vals, s_e_lns,
                              fixed_ids, had_fix)

    if not verts:
        raise ValueError("no supported VERTEX records found")
    ids = np.array(sorted(verts), dtype=np.int64)
    index = {vid: k for k, vid in enumerate(ids)}

    def split_rows(flags, toks, width_se3, width_se2):
        """Mixed SE3/SE2 token rows -> ([n,7] pose raw, per-kind floats).

        The [n, 7] form is [t(3), quat_xyzw(4)] with SE2 thetas encoded
        as z-axis quaternions.  Float conversion happens in ONE numpy
        call per kind (C-level string parsing).
        """
        flags = np.asarray(flags, bool)
        se3_rows = np.nonzero(~flags)[0]
        se2_rows = np.nonzero(flags)[0]
        se3_raw = np.asarray(
            [toks[k] for k in se3_rows], np.float64).reshape(-1, width_se3)
        se2_raw = np.asarray(
            [toks[k] for k in se2_rows], np.float64).reshape(-1, width_se2)
        raw7 = np.zeros((len(toks), 7))
        raw7[:, 6] = 1.0  # identity quaternion default
        raw7[se3_rows] = se3_raw[:, :7]
        raw7[se2_rows, 0] = se2_raw[:, 0]
        raw7[se2_rows, 1] = se2_raw[:, 1]
        raw7[se2_rows, 5] = np.sin(se2_raw[:, 2] / 2)
        raw7[se2_rows, 6] = np.cos(se2_raw[:, 2] / 2)
        return raw7, se3_raw, se2_raw, se3_rows, se2_rows

    raw_v, _, _, _, _ = split_rows(
        [verts[vid][0] for vid in ids],
        [verts[vid][1] for vid in ids], 7, 3)
    bad_v = ~np.isfinite(raw_v).all(axis=1)
    if bad_v.any():
        k = int(np.argmax(bad_v))
        vid = int(ids[k])
        raise ValueError(
            f"line {verts[vid][2]}: VERTEX {vid} has non-finite "
            "values — a NaN/inf estimate would poison every solver "
            "reduction; fix or drop the record")
    poses = np.concatenate(
        [_quat_xyzw_to_aa(raw_v[:, 3:7]), raw_v[:, :3]], axis=1)

    n_e = len(e_ids)
    try:
        edge_i = np.asarray([index[i] for i, _ in e_ids],
                            np.int32).reshape(n_e)
        edge_j = np.asarray([index[j] for _, j in e_ids],
                            np.int32).reshape(n_e)
    except KeyError as exc:
        raise ValueError(
            f"edge references unknown vertex {exc.args[0]}") from None
    if n_e:
        raw_e, se3_raw, se2_raw, se3_rows, se2_rows = split_rows(
            e_se2, e_vals, 28, 9)
        bad_rows = np.zeros(n_e, bool)
        # The full token payload (measurement AND information entries)
        # must be finite; check per kind, then map back to source lines.
        bad_rows[se3_rows] = ~np.isfinite(se3_raw).all(axis=1)
        bad_rows[se2_rows] = ~np.isfinite(se2_raw).all(axis=1)
        if bad_rows.any():
            k = int(np.argmax(bad_rows))
            raise ValueError(
                f"line {e_lns[k]}: EDGE {e_ids[k][0]} -> {e_ids[k][1]} "
                "has non-finite measurement/information values — a "
                "NaN/inf factor would poison every solver reduction; "
                "fix or drop the record")
        meas = np.concatenate(
            [_quat_xyzw_to_aa(raw_e[:, 3:7]), raw_e[:, :3]], axis=1)
        info = np.zeros((n_e, 6, 6))
        if se3_rows.size:
            info[se3_rows] = _info_g2o_to_ours(
                _upper_tri_to_full_batch(se3_raw[:, 7:], 6))
        if se2_rows.size:
            info[se2_rows] = _lift_se2_info(
                _upper_tri_to_full_batch(se2_raw[:, 3:], 3))
    else:
        meas = np.zeros((0, 6))
        info = np.zeros((0, 6, 6))

    # ---- unary pose priors (EDGE_SE3_PRIOR) --------------------------
    prior_idx = np.zeros(0, np.int32)
    prior_meas = np.zeros((0, 6))
    prior_info = np.zeros((0, 6, 6))
    if p_ids:
        rows = []
        for vid, ln in zip(p_ids, p_lns):
            if vid not in index:
                raise ValueError(
                    f"line {ln}: EDGE_SE3_PRIOR references unknown "
                    f"vertex {vid}")
            rows.append(index[vid])
        prior_idx = np.asarray(rows, np.int32)
        raw_p = np.asarray(p_vals, np.float64).reshape(-1, 28)
        bad_p = ~np.isfinite(raw_p).all(axis=1)
        if bad_p.any():
            k = int(np.argmax(bad_p))
            raise ValueError(
                f"line {p_lns[k]}: EDGE_SE3_PRIOR on vertex "
                f"{p_ids[k]} has non-finite measurement/information "
                "values — a NaN/inf anchor would poison every solver "
                "reduction; fix or drop the record")
        prior_meas = np.concatenate(
            [_quat_xyzw_to_aa(raw_p[:, 3:7]), raw_p[:, :3]], axis=1)
        prior_info = _info_g2o_to_ours(
            _upper_tri_to_full_batch(raw_p[:, 7:], 6))

    fixed = np.zeros(len(ids), bool)
    for vid in fixed_ids:
        if vid in index:
            fixed[index[vid]] = True
    # had_fix must mean "the output's FIX rows came from the file":
    # a FIX that only referenced skipped vertices (mixed graphs with
    # unknown tags) leaves nothing anchored, and the fallback anchor
    # below is ours, not the file's.
    had_fix = had_fix and bool(fixed.any())
    if not fixed.any():
        fixed[0] = True  # gauge anchor, same default as solve_pgo

    return G2OGraph(poses=poses, edge_i=edge_i, edge_j=edge_j, meas=meas,
                    info=info, fixed=fixed, ids=ids,
                    se2=se2_seen and not se3_seen, had_fix=had_fix,
                    prior_idx=prior_idx, prior_meas=prior_meas,
                    prior_info=prior_info)


def write_g2o(dest: Union[str, TextIO], graph: G2OGraph,
              poses: Optional[np.ndarray] = None) -> None:
    """Write SE3:QUAT records (optionally with updated poses).

    Always writes the SE(3) form — lifted SE(2) graphs round-trip
    through it losslessly (z/roll/pitch stay zero at the optimum).
    A .gz/.bz2 destination is compressed transparently.  FIX records
    are written only when the graph carried them (``had_fix``): the
    solver's default gauge anchor (fixed[0]) is an internal choice, and
    materializing it would hand external g2o consumers a FIX the
    original file never declared.
    """
    if isinstance(dest, str):
        with _open_text(dest, "wt") as f:
            write_g2o(f, graph, poses)
        return

    p = np.asarray(graph.poses if poses is None else poses)
    quat_v = _aa_to_quat_xyzw(p[:, :3])
    if graph.sim3:
        for k, vid in enumerate(graph.ids):
            t = p[k, 3:6]
            q = quat_v[k]
            dest.write(
                f"VERTEX_SIM3:QUAT {int(vid)} "
                f"{t[0]:.9g} {t[1]:.9g} {t[2]:.9g} "
                f"{q[0]:.9g} {q[1]:.9g} {q[2]:.9g} {q[3]:.9g} "
                f"{np.exp(p[k, 6]):.9g}\n")
    else:
        for k, vid in enumerate(graph.ids):
            t = p[k, 3:]
            q = quat_v[k]
            dest.write(
                f"VERTEX_SE3:QUAT {int(vid)} "
                f"{t[0]:.9g} {t[1]:.9g} {t[2]:.9g} "
                f"{q[0]:.9g} {q[1]:.9g} {q[2]:.9g} {q[3]:.9g}\n")
    if graph.had_fix:
        for k in range(len(graph.ids)):
            if graph.fixed[k]:
                dest.write(f"FIX {int(graph.ids[k])}\n")
    meas_q = _aa_to_quat_xyzw(graph.meas[:, :3])
    if graph.sim3:
        tri_all = _info7_ours_to_g2o(graph.info)[:, _TRIU7[0], _TRIU7[1]]
        for e in range(graph.edge_i.shape[0]):
            m_t = graph.meas[e, 3:6]
            q = meas_q[e]
            tri = " ".join(f"{v:.9g}" for v in tri_all[e])
            dest.write(
                f"EDGE_SIM3:QUAT {int(graph.ids[graph.edge_i[e]])} "
                f"{int(graph.ids[graph.edge_j[e]])} "
                f"{m_t[0]:.9g} {m_t[1]:.9g} {m_t[2]:.9g} "
                f"{q[0]:.9g} {q[1]:.9g} {q[2]:.9g} {q[3]:.9g} "
                f"{np.exp(graph.meas[e, 6]):.9g} {tri}\n")
        return
    tri_all = _info_ours_to_g2o(graph.info)[:, _TRIU[0], _TRIU[1]]
    for e in range(graph.edge_i.shape[0]):
        m_t = graph.meas[e, 3:]
        q = meas_q[e]
        tri = " ".join(f"{v:.9g}" for v in tri_all[e])
        dest.write(
            f"EDGE_SE3:QUAT {int(graph.ids[graph.edge_i[e]])} "
            f"{int(graph.ids[graph.edge_j[e]])} "
            f"{m_t[0]:.9g} {m_t[1]:.9g} {m_t[2]:.9g} "
            f"{q[0]:.9g} {q[1]:.9g} {q[2]:.9g} {q[3]:.9g} {tri}\n")
    if graph.prior_idx.shape[0]:
        pq = _aa_to_quat_xyzw(graph.prior_meas[:, :3])
        ptri = _info_ours_to_g2o(graph.prior_info)[:, _TRIU[0], _TRIU[1]]
        for e in range(graph.prior_idx.shape[0]):
            m_t = graph.prior_meas[e, 3:]
            q = pq[e]
            tri = " ".join(f"{v:.9g}" for v in ptri[e])
            dest.write(
                f"EDGE_SE3_PRIOR {int(graph.ids[graph.prior_idx[e]])} "
                f"{m_t[0]:.9g} {m_t[1]:.9g} {m_t[2]:.9g} "
                f"{q[0]:.9g} {q[1]:.9g} {q[2]:.9g} {q[3]:.9g} {tri}\n")


def sqrt_info_of(graph: G2OGraph) -> Optional[np.ndarray]:
    """Matrix square-root weights W of the edge info matrices.

    ||W r||^2 = r^T Omega r, i.e. W^T W = Omega.  Uses a symmetric
    eigendecomposition rather than Cholesky so positive-SEMIdefinite
    matrices (a zero row = deliberately unconstrained DOF, common in
    partial-sensor exports) factor cleanly instead of crashing; small
    negative eigenvalues from text round-off are clamped to zero.
    Returns None when every info matrix is the identity (the unweighted
    fast path).
    """
    n = graph.info.shape[-1]  # 6 (SE3) or 7 (sim3)
    if np.allclose(graph.info, np.eye(n)[None]):
        return None
    from megba_tpu.core.linalg import psd_sqrt

    return psd_sqrt(graph.info, what="edge")


def solve_g2o(source, option=None, verbose: bool = False,
              init: str = "file",
              prior_ids=None, prior_weight: float = 1e4):
    """Read (path / file / G2OGraph), solve, return (graph, PGOResult).

    `init="spanning_tree"` re-initializes poses by composing
    measurements along a BFS spanning tree from the anchors
    (models/pgo.spanning_tree_init) instead of trusting the file's
    VERTEX estimates — the standard bootstrap for exports with garbage
    or missing initial guesses.

    `prior_ids` (g2o VERTEX ids) anchors those poses at their FILE
    estimates via unary prior factors weighted `prior_weight * I`
    (models/pgo.with_priors) — the surveying workflow of holding known
    stations softly instead of hard-FIXing them.  File-carried
    ``EDGE_SE3_PRIOR`` records ride the same machinery with their OWN
    measured poses and information (W = psd_sqrt(Omega)), composing
    with `prior_ids`.  The returned result's poses are sliced back to
    the graph's own poses (the virtual anchor poses are internal).

    Sim(3) graphs (``graph.sim3``) dispatch the ``sim3_between``
    factor; `prior_ids` and `init="spanning_tree"` are SE(3)-only and
    refused typed there.
    """
    from megba_tpu.models.pgo import (
        solve_pgo, spanning_tree_init, with_priors)

    graph = source if isinstance(source, G2OGraph) else read_g2o(source)
    n = graph.poses.shape[0]
    poses0 = graph.poses
    edge_i, edge_j, meas = graph.edge_i, graph.edge_j, graph.meas
    fixed = graph.fixed
    sqrt_info = sqrt_info_of(graph)
    if graph.sim3:
        if prior_ids is not None and len(prior_ids) > 0:
            raise ValueError(
                "prior_ids anchors via SE(3) unary priors "
                "(models/pgo.with_priors) and is not supported for "
                "sim(3) graphs")
        if init == "spanning_tree":
            raise ValueError(
                "init='spanning_tree' composes SE(3) odometry and is "
                "not supported for sim(3) graphs; use init='file'")
        if init != "file":
            raise ValueError(f"init must be 'file' or 'spanning_tree', "
                             f"got {init!r}")
        result = solve_pgo(poses0, edge_i, edge_j, meas, option,
                           sqrt_info=sqrt_info, fixed=fixed,
                           verbose=verbose, factor="sim3_between")
        return graph, result
    file_p = int(graph.prior_idx.shape[0])
    user_idx = np.zeros(0, np.int32)
    if prior_ids is not None and len(prior_ids) > 0:
        index = {int(vid): k for k, vid in enumerate(graph.ids)}
        try:
            user_idx = np.array([index[int(v)] for v in prior_ids],
                                np.int32)
        except KeyError as exc:
            raise ValueError(
                f"prior id {exc.args[0]} is not a vertex of this graph"
            ) from None
    if file_p or user_idx.shape[0]:
        # File priors first, then the caller's soft anchors; both ride
        # with_priors as one combined prior set.
        idx = np.concatenate(
            [graph.prior_idx.astype(np.int32), user_idx])
        prior_poses = np.concatenate(
            [graph.prior_meas, graph.poses[user_idx]])
        if file_p:
            from megba_tpu.core.linalg import psd_sqrt

            w_file = psd_sqrt(graph.prior_info, what="prior")
        else:
            w_file = np.zeros((0, 6, 6))
        w_user = np.broadcast_to(
            np.eye(6) * float(prior_weight),
            (user_idx.shape[0], 6, 6))
        prior_W = np.concatenate([w_file, w_user])
        p = idx.shape[0]
        # Priors carry the gauge; the parser's defaulted anchor (a FIX
        # the file never declared) would fight them.  File-declared FIX
        # records are kept.  The default anchor is decided PER CONNECTED
        # COMPONENT: a component some prior reaches gets its gauge from
        # that prior (keeping a hard anchor there would bias the solve
        # toward the file estimate — the exact conflict this path
        # avoids); a component no prior reaches is anchored at one of
        # its OWN poses (the parser's fixed[0] only covers pose 0's
        # component; an unreached component would otherwise keep a free
        # 6-DOF gauge and a singular system).
        if not graph.had_fix:
            from collections import deque

            adj: list[list[int]] = [[] for _ in range(n)]
            for a, b in zip(np.asarray(edge_i), np.asarray(edge_j)):
                adj[int(a)].append(int(b))
                adj[int(b)].append(int(a))
            comp = np.full(n, -1, np.int64)
            n_comp = 0
            for start in range(n):
                if comp[start] >= 0:
                    continue
                comp[start] = n_comp
                queue = deque([start])
                while queue:
                    a = queue.popleft()
                    for b in adj[a]:
                        if comp[b] < 0:
                            comp[b] = n_comp
                            queue.append(b)
                n_comp += 1
            has_prior = np.zeros(n_comp, bool)
            has_prior[comp[idx]] = True
            fixed = np.zeros(n, bool)
            # First member of every component in one pass (labels are
            # assigned in first-occurrence order, so unique's sorted
            # values are 0..n_comp-1 and return_index gives the first
            # pose of each) — a per-component argmax scan would go
            # quadratic on fragmented FIX-less graphs.
            _, first = np.unique(comp, return_index=True)
            fixed[first[~has_prior]] = True
        poses0, edge_i, edge_j, meas, fixed, sqrt_info = with_priors(
            poses0, edge_i, edge_j, meas,
            prior_idx=idx, prior_poses=prior_poses,
            prior_sqrt_info=prior_W,
            fixed=fixed, sqrt_info=sqrt_info)
    if init == "spanning_tree":
        poses0 = spanning_tree_init(poses0, edge_i, edge_j, meas, fixed)
    elif init != "file":
        raise ValueError(f"init must be 'file' or 'spanning_tree', "
                         f"got {init!r}")
    result = solve_pgo(
        poses0, edge_i, edge_j, meas,
        option, sqrt_info=sqrt_info, fixed=fixed,
        verbose=verbose)
    if result.poses.shape[0] != n:  # drop internal virtual anchors
        result = result._replace(poses=result.poses[:n])
    return graph, result
