"""Compensated float32 reductions.

The reference runs its flagship examples in double precision on GPU
(examples/BAL_Double.cpp:163) and computes residual norms / gain ratios
with f64 cuBLAS dots (src/algo/lm_algo.cu:25-51,60-126).  TPU f64 is
software-emulated, so this framework solves in float32 — but a plain
f32 sum over ~29M residual terms (BAL Final) carries O(n*eps) ~ 1e-1
relative worst-case error, enough to flip LM accept/reject decisions
near convergence (SURVEY.md §7 names "fp32 + compensated residual
norms" as the mitigation).

`comp_sum` restores f64-class accuracy while staying in f32: a log-depth
pairwise reduction where every addition's rounding error is recovered
exactly with the two-sum error-free transformation (Knuth TAOCP v2
§4.2.2) and carried in a parallel "lo" stream.  Worst-case error is
O(eps + n*eps^2) — at n = 2^25, ~1e-7 relative, matching a f64
accumulator rounded to f32.  Cost: ~4 elementwise ops per element and
one extra pass of HBM traffic over the operand, all fused by XLA; the
tree has static shape so it jits into straight-line code.

XLA does not reassociate floating-point arithmetic by default, so the
EFT identities survive compilation (verified by tests/test_accum.py
against f64 ground truth).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def two_sum(a: jax.Array, b: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Error-free transformation: a + b = s + err exactly (Knuth)."""
    s = a + b
    t = s - a
    err = (a - (s - t)) + (b - t)
    return s, err


def comp_sum(x: jax.Array) -> jax.Array:
    """Compensated sum of all elements of `x` (any shape), in x.dtype.

    Log-depth halves tree (fold top half onto bottom half) — same
    error class as the classic pairwise two-sum tree, but every level
    operates on CONTIGUOUS row ranges of a [rows, 128] reshape, so the
    TPU lowering is plain full-width vector ops with no lane-strided
    relayouts (the original `hi[0::2]` formulation forced a cross-lane
    shuffle per level, which dominated the reduction cost on v5e).
    For float64 (CPU verification path) the plain sum is already exact
    enough, so f64 short-circuits to jnp.sum.
    """
    if x.dtype == jnp.float64:
        return jnp.sum(x)
    flat = x.ravel()
    n = flat.shape[0]
    if n == 0:
        return jnp.zeros((), x.dtype)
    lanes = 128 if n >= 128 else 1
    rows = -(-n) // lanes if lanes == 1 else -(-n // lanes)
    pad = rows * lanes - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    hi = flat.reshape(rows, lanes)
    lo = jnp.zeros_like(hi)
    while hi.shape[0] > 1:
        m = hi.shape[0]
        half = (m + 1) // 2
        top_h, top_l = hi[half:], lo[half:]
        if top_h.shape[0] < half:  # odd: pad the folded half with zeros
            z = jnp.zeros((half - top_h.shape[0], lanes), hi.dtype)
            top_h = jnp.concatenate([top_h, z])
            top_l = jnp.concatenate([top_l, z])
        s, e = two_sum(hi[:half], top_h)
        lo = lo[:half] + top_l + e
        hi = s
    # Fold the 128 lanes of the single remaining row the same way.
    hi = hi[0]
    lo = lo[0]
    while hi.shape[0] > 1:
        m = hi.shape[0]
        half = (m + 1) // 2
        top_h, top_l = hi[half:], lo[half:]
        if top_h.shape[0] < half:
            z = jnp.zeros((half - top_h.shape[0],), hi.dtype)
            top_h = jnp.concatenate([top_h, z])
            top_l = jnp.concatenate([top_l, z])
        s, e = two_sum(hi[:half], top_h)
        lo = lo[:half] + top_l + e
        hi = s
    return hi[0] + lo[0]


def comp_sum_sq(x: jax.Array) -> jax.Array:
    """Compensated Sum x_i^2 — the residual-norm / cost reduction."""
    return comp_sum(x * x)


def comp_dot(a: jax.Array, b: jax.Array) -> jax.Array:
    """Compensated <a, b>.

    The elementwise products round once each (non-accumulating, one ulp
    relative); only the summation error compounds with n, and that is
    what the two-sum tree removes.
    """
    return comp_sum(a * b)
