"""Residual + Jacobian engine.

The TPU-native replacement for the reference's entire operator layer: the
JetVector forward-mode dual numbers (reference include/operator/jet_vector.h,
src/operator/jet_vector_math_impl.cu — ~40 CUDA kernels), the Eigen
injector (include/operator/eigen_injector.h) and the hand-fused geo kernels
all collapse into ONE jitted function: a per-edge residual written in plain
JAX numpy, vmapped over the edge axis, with Jacobians from reverse-mode
`jax.vjp` (AUTODIFF — od pullbacks, the cheap direction for short
residuals), forward-mode `jax.jacfwd` (AUTODIFF_FORWARD — the
reference-faithful direction), or a hand-derived closed form (ANALYTICAL,
the equivalent of reference src/geo/analytical_derivatives.cu:162-322).

In the reference every JetVector op is its own kernel launch
(jet_vector.cpp:207-224); here XLA fuses the whole forward pass into a
single TPU program.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from megba_tpu.common import JacobianMode
from megba_tpu.ops import geo

# A residual function maps (camera[cd], point[pd], obs[od]) -> r[od].
ResidualFn = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]


def bal_residual(camera: jnp.ndarray, point: jnp.ndarray, obs: jnp.ndarray) -> jnp.ndarray:
    """The standard BAL reprojection residual, one edge.

    camera = [angle_axis(3), translation(3), f, k1, k2]; point = (3,);
    obs = (2,).  Mirrors the user `forward()` of reference
    examples/BAL_Double.cpp:18-33: rotate, translate, perspective divide
    with the BAL minus convention, radial distortion, subtract observation.
    """
    w = camera[0:3]
    t = camera[3:6]
    f, k1, k2 = camera[6], camera[7], camera[8]
    P = geo.angle_axis_rotate_point(w, point) + t
    # BAL convention: projection plane at z = -1.
    p = -P[0:2] / P[2]
    proj = geo.radial_distortion(p, f, k1, k2)
    return proj - obs


def bal_residual_jacobian_analytical(
    camera: jnp.ndarray, point: jnp.ndarray, obs: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Hand-derived residual + full Jacobian for the BAL model, one edge.

    Returns (r[2], Jc[2,9], Jp[2,3]).  The closed-form equivalent of the
    fused kernel in reference src/geo/analytical_derivatives.cu:162-285
    (which hand-propagates partials through rotate/translate/divide/distort)
    — README.md:16 credits this path with -30% time / -40% memory vs the
    autodiff module.
    """
    w = camera[0:3]
    t = camera[3:6]
    f, k1, k2 = camera[6], camera[7], camera[8]

    RX = geo.angle_axis_rotate_point(w, point)
    P = RX + t
    inv_z = 1.0 / P[2]
    p = -P[0:2] * inv_z

    n = jnp.dot(p, p)
    rd = 1.0 + k1 * n + k2 * n * n
    proj = f * rd * p
    r = proj - obs

    # d proj / d p = f * (rd I + 2 (k1 + 2 k2 n) p p^T)
    dproj_dp = f * (rd * jnp.eye(2, dtype=camera.dtype) + 2.0 * (k1 + 2.0 * k2 * n) * jnp.outer(p, p))
    # d p / d P = [[-1/z, 0, x/z^2], [0, -1/z, y/z^2]]
    zero = jnp.zeros((), dtype=camera.dtype)
    dp_dP = jnp.array(
        [
            [-inv_z, zero, P[0] * inv_z * inv_z],
            [zero, -inv_z, P[1] * inv_z * inv_z],
        ]
    )
    dr_dP = geo.mm(dproj_dp, dp_dP)  # (2,3)

    J_t = dr_dP
    J_w = geo.mm(dr_dP, geo.drotated_dangle_axis(w, point))  # (2,3)
    J_X = geo.mm(dr_dP, geo.angle_axis_to_rotation_matrix(w))  # (2,3)
    J_f = (rd * p)[:, None]  # (2,1)
    J_k1 = (f * n * p)[:, None]
    J_k2 = (f * n * n * p)[:, None]

    Jc = jnp.concatenate([J_w, J_t, J_f, J_k1, J_k2], axis=1)  # (2,9)
    return r, Jc, J_X


@functools.lru_cache(maxsize=64)
def make_residual_fn(
    residual_fn: ResidualFn = bal_residual,
) -> Callable[..., jnp.ndarray]:
    """Vectorised residual evaluation over gathered per-edge params.

    Returns fn(cam_params[nE,cd], pt_params[nE,pd], obs[nE,od]) -> r[nE,od].
    The equivalent of reference EdgeVector::forward (base_edge.cpp:160-163)
    value plane only.
    """
    return jax.vmap(residual_fn, in_axes=(0, 0, 0))


def build_residual_jacobian_fn(
    residual_fn: ResidualFn = bal_residual,
    mode: JacobianMode = JacobianMode.AUTODIFF,
    analytical_fn: Optional[Callable[..., Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]]] = None,
) -> Callable[..., Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]]:
    """Build the vectorised residual+Jacobian evaluator (uncached).

    Use this directly for per-problem closure engines (BaseProblem's
    custom edges): routing those through the memoised wrapper would pin
    each closure — and the prototype edge it captures — in a global
    cache long after the problem is dropped.  `make_residual_jacobian_fn`
    below is the memoised front for hashable, long-lived configs
    (built-in engines, module-level residual functions).

    Returns fn(cam_params[nE,cd], pt_params[nE,pd], obs[nE,od])
      -> (r[nE,od], Jc[nE,od,cd], Jp[nE,od,pd]).

    AUTODIFF (reverse-mode vjp) and AUTODIFF_FORWARD (jacfwd — the
    direction the reference's JetVector pass uses, SURVEY.md §3.4)
    compute the same Jacobian; ANALYTICAL uses a closed-form function
    (default: the BAL one above).  See common.JacobianMode for when each
    direction wins.
    """
    if mode == JacobianMode.ANALYTICAL:
        fn = analytical_fn
        if fn is None:
            if residual_fn is not bal_residual:
                raise ValueError(
                    "ANALYTICAL mode needs analytical_fn for custom residuals"
                )
            fn = bal_residual_jacobian_analytical
        return jax.vmap(fn, in_axes=(0, 0, 0))

    if mode == JacobianMode.AUTODIFF_FORWARD:

        def value_and_jac_fwd(camera, point, obs):
            # jax.linearize: ONE primal evaluation plus cd+pd cheap
            # pushforwards of the linearised map (jacfwd would recompute
            # the primal per basis vector and lean on XLA CSE).
            r, jvp = jax.linearize(
                lambda c, p: residual_fn(c, p, obs), camera, point)
            cd, pd = camera.shape[0], point.shape[0]
            eye_c = jnp.eye(cd, dtype=camera.dtype)
            eye_p = jnp.eye(pd, dtype=point.dtype)
            Jc = jax.vmap(lambda t: jvp(t, jnp.zeros_like(point)))(eye_c)
            Jp = jax.vmap(lambda t: jvp(jnp.zeros_like(camera), t))(eye_p)
            return r, Jc.T, Jp.T

        return jax.vmap(value_and_jac_fwd, in_axes=(0, 0, 0))

    def value_and_jac(camera, point, obs):
        # Reverse mode: od pullbacks instead of (cd+pd) pushforwards —
        # the cheap direction for short residuals (see JacobianMode).
        r, pull = jax.vjp(lambda c, p: residual_fn(c, p, obs), camera, point)
        # Stamp the primal's varying-axes type onto the cotangent basis so
        # the pullback is well-typed inside shard_map.  Routing through
        # isfinite keeps the stamp exactly zero even when a residual
        # component is inf/NaN (0*inf would poison the whole basis).
        stamp = (jnp.isfinite(r).astype(r.dtype) * 0.0)[None, :]
        eye = jnp.eye(r.shape[0], dtype=r.dtype) + stamp
        Jc, Jp = jax.vmap(pull)(eye)
        return r, Jc, Jp

    return jax.vmap(value_and_jac, in_axes=(0, 0, 0))


@functools.lru_cache(maxsize=64)
def make_residual_jacobian_fn(
    residual_fn: ResidualFn = bal_residual,
    mode: JacobianMode = JacobianMode.AUTODIFF,
    analytical_fn: Optional[Callable[..., Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]]] = None,
) -> Callable[..., Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]]:
    """Memoised `build_residual_jacobian_fn` — same engine config returns
    the identical callable, keeping jax.jit / the distributed solve cache
    hot across separate solves.  Only pass long-lived hashable
    `residual_fn`s (module-level functions); per-problem closures go
    through `build_residual_jacobian_fn` to avoid cache retention."""
    return build_residual_jacobian_fn(residual_fn, mode, analytical_fn)


def apply_sqrt_info(
    r: jnp.ndarray,
    Jc: jnp.ndarray,
    Jp: jnp.ndarray,
    sqrt_info: Optional[jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pre-whiten residuals and Jacobians by the sqrt information matrix.

    Weighted least squares: with information Sigma^-1 = L^T L this scales
    r~ = L r, J~ = L J so that H = J~^T J~ and g = -J~^T r~.  Covers the
    reference's information-matrix path (BaseEdge information,
    build_linear_system.cu JMulInfo :148-239) with standard WLS semantics.
    """
    if sqrt_info is None:
        return r, Jc, Jp
    hi = jax.lax.Precision.HIGHEST
    r = jnp.einsum("eij,ej->ei", sqrt_info, r, precision=hi)
    Jc = jnp.einsum("eij,ejk->eik", sqrt_info, Jc, precision=hi)
    Jp = jnp.einsum("eij,ejk->eik", sqrt_info, Jp, precision=hi)
    return r, Jc, Jp
