"""Residual + Jacobian engine (feature-major).

The TPU-native replacement for the reference's entire operator layer: the
JetVector forward-mode dual numbers (reference include/operator/jet_vector.h,
src/operator/jet_vector_math_impl.cu — ~40 CUDA kernels), the Eigen
injector (include/operator/eigen_injector.h) and the hand-fused geo kernels
all collapse into ONE jitted function over feature-major rows (see
core/fm.py for the layout rationale): a per-edge residual written in plain
JAX numpy, vmapped over the minor edge axis, with Jacobians from
reverse-mode `jax.vjp` (AUTODIFF — od pullbacks, the cheap direction for
short residuals), forward-mode (AUTODIFF_FORWARD — the reference-faithful
direction), or a hand-derived closed form (ANALYTICAL, the equivalent of
reference src/geo/analytical_derivatives.cu:162-322).

Engine contract: fn(cam [cd, nE], pt [pd, nE], obs [od, nE]) ->
  (r [od, nE], Jc [od*cd, nE], Jp [od*pd, nE]) with row o*d+a = dr_o/dx_a.

In the reference every JetVector op is its own kernel launch
(jet_vector.cpp:207-224); here XLA fuses the whole forward pass into a
single TPU program of row-wise VPU ops — the feature-major twin of how
the reference's analytical kernel unrolls per-thread scalar math
(analytical_derivatives.cu:162-285), but vectorised across 128-edge lanes
instead of CUDA threads.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from megba_tpu.common import JacobianMode
from megba_tpu.utils.memo import normalized_lru_cache

_SMALL_ANGLE = 1e-12

# A residual function maps (camera[cd], point[pd], obs[od]) -> r[od]
# for ONE edge; engines vectorise it over the minor edge axis.
ResidualFn = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]


def bal_residual(camera: jnp.ndarray, point: jnp.ndarray, obs: jnp.ndarray) -> jnp.ndarray:  # megba: jit-entry
    """The standard BAL reprojection residual, one edge.

    camera = [angle_axis(3), translation(3), f, k1, k2]; point = (3,);
    obs = (2,).  Mirrors the user `forward()` of reference
    examples/BAL_Double.cpp:18-33: rotate, translate, perspective divide
    with the BAL minus convention, radial distortion, subtract observation.
    """
    from megba_tpu.ops import geo

    w = camera[0:3]
    t = camera[3:6]
    f, k1, k2 = camera[6], camera[7], camera[8]
    P = geo.angle_axis_rotate_point(w, point) + t
    # BAL convention: projection plane at z = -1.
    p = -P[0:2] / P[2]
    proj = geo.radial_distortion(p, f, k1, k2)
    return proj - obs


def bal_residual_jacobian_analytical_fm(  # megba: jit-entry
    cam: jnp.ndarray, pt: jnp.ndarray, obs: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Hand-derived residual + full Jacobian for the BAL model, row form.

    cam [9, nE], pt [3, nE], obs [2, nE] ->
      (r [2, nE], Jc [18, nE], Jp [6, nE]).

    The closed-form equivalent of the fused kernel in reference
    src/geo/analytical_derivatives.cu:162-285 (which hand-propagates
    partials through rotate/translate/divide/distort; README.md:16 credits
    that path with -30% time / -40% memory vs the reference's autodiff).
    Here each scalar of the derivation is one [nE] row — the whole
    function is a straight line of VPU ops over 128-edge lanes.
    Rotation derivative d(R(w)x)/dw is the Gallego & Yezzi (2015) closed
    form with the small-angle limit -[x]_x.
    """
    w0, w1, w2 = cam[0], cam[1], cam[2]
    t0, t1, t2 = cam[3], cam[4], cam[5]
    f, k1, k2 = cam[6], cam[7], cam[8]
    x0, x1, x2 = pt[0], pt[1], pt[2]
    one = jnp.ones_like(w0)

    # --- Rodrigues rotation, small-angle guarded (both branches always
    # evaluated under jit; the untaken one must stay finite).
    theta2 = w0 * w0 + w1 * w1 + w2 * w2
    safe = theta2 > _SMALL_ANGLE
    # `one`, not Python 1.0: a weak float literal materialises as a
    # tensor<f64> constant + convert under x64 — an f64 op inside the
    # f32 program that the compiled-program auditor's dtype census
    # (analysis/program_audit.py) rightly flags.
    th2s = jnp.where(safe, theta2, one)
    th = jnp.sqrt(th2s)
    ct, st = jnp.cos(th), jnp.sin(th)
    inv_th = 1.0 / th
    e0, e1, e2 = w0 * inv_th, w1 * inv_th, w2 * inv_th
    one_ct = 1.0 - ct

    def W(val_full, val_small):
        return jnp.where(safe, val_full, val_small)

    # Rotation matrix rows R_ij (full: ct I + one_ct e e^T + st [e]_x;
    # small: I + [w]_x).
    R00 = W(ct + one_ct * e0 * e0, one)
    R01 = W(one_ct * e0 * e1 - st * e2, -w2)
    R02 = W(one_ct * e0 * e2 + st * e1, w1)
    R10 = W(one_ct * e1 * e0 + st * e2, w2)
    R11 = W(ct + one_ct * e1 * e1, one)
    R12 = W(one_ct * e1 * e2 - st * e0, -w0)
    R20 = W(one_ct * e2 * e0 - st * e1, -w1)
    R21 = W(one_ct * e2 * e1 + st * e0, w0)
    R22 = W(ct + one_ct * e2 * e2, one)

    RX0 = R00 * x0 + R01 * x1 + R02 * x2
    RX1 = R10 * x0 + R11 * x1 + R12 * x2
    RX2 = R20 * x0 + R21 * x1 + R22 * x2

    # --- project + distort
    P0, P1, P2 = RX0 + t0, RX1 + t1, RX2 + t2
    iz = 1.0 / P2
    px = -P0 * iz
    py = -P1 * iz
    n = px * px + py * py
    rd = 1.0 + k1 * n + k2 * n * n
    r0 = f * rd * px - obs[0]
    r1 = f * rd * py - obs[1]

    # d proj / d p = f (rd I + 2 (k1 + 2 k2 n) p p^T)
    c2 = 2.0 * (k1 + 2.0 * k2 * n)
    D00 = f * (rd + c2 * px * px)
    D01 = f * (c2 * px * py)
    D11 = f * (rd + c2 * py * py)

    # d r / d P = D @ [[-iz, 0, P0 iz^2], [0, -iz, P1 iz^2]]
    iz2 = iz * iz
    G00 = -D00 * iz
    G01 = -D01 * iz
    G02 = (D00 * P0 + D01 * P1) * iz2
    G10 = -D01 * iz
    G11 = -D11 * iz
    G12 = (D01 * P0 + D11 * P1) * iz2

    # --- Jp = G @ R  (dP/dX = R)
    Jp00 = G00 * R00 + G01 * R10 + G02 * R20
    Jp01 = G00 * R01 + G01 * R11 + G02 * R21
    Jp02 = G00 * R02 + G01 * R12 + G02 * R22
    Jp10 = G10 * R00 + G11 * R10 + G12 * R20
    Jp11 = G10 * R01 + G11 * R11 + G12 * R21
    Jp12 = G10 * R02 + G11 * R12 + G12 * R22

    # --- d(Rx)/dw: M = -(R [x]_x)(w w^T + (R^T - I)[w]_x)/theta^2,
    # small-angle limit -[x]_x.
    # B = R @ skew(x)
    B00 = R01 * x2 - R02 * x1
    B01 = -R00 * x2 + R02 * x0
    B02 = R00 * x1 - R01 * x0
    B10 = R11 * x2 - R12 * x1
    B11 = -R10 * x2 + R12 * x0
    B12 = R10 * x1 - R11 * x0
    B20 = R21 * x2 - R22 * x1
    B21 = -R20 * x2 + R22 * x0
    B22 = R20 * x1 - R21 * x0
    # C = R^T - I; A = w w^T + C @ skew(w)
    C00, C01, C02 = R00 - 1.0, R10, R20
    C10, C11, C12 = R01, R11 - 1.0, R21
    C20, C21, C22 = R02, R12, R22 - 1.0
    A00 = w0 * w0 + (C01 * w2 - C02 * w1)
    A01 = w0 * w1 + (-C00 * w2 + C02 * w0)
    A02 = w0 * w2 + (C00 * w1 - C01 * w0)
    A10 = w1 * w0 + (C11 * w2 - C12 * w1)
    A11 = w1 * w1 + (-C10 * w2 + C12 * w0)
    A12 = w1 * w2 + (C10 * w1 - C11 * w0)
    A20 = w2 * w0 + (C21 * w2 - C22 * w1)
    A21 = w2 * w1 + (-C20 * w2 + C22 * w0)
    A22 = w2 * w2 + (C20 * w1 - C21 * w0)
    inv_t2 = 1.0 / th2s
    zero = jnp.zeros_like(x0)
    M00 = W(-(B00 * A00 + B01 * A10 + B02 * A20) * inv_t2, zero)
    M01 = W(-(B00 * A01 + B01 * A11 + B02 * A21) * inv_t2, x2)
    M02 = W(-(B00 * A02 + B01 * A12 + B02 * A22) * inv_t2, -x1)
    M10 = W(-(B10 * A00 + B11 * A10 + B12 * A20) * inv_t2, -x2)
    M11 = W(-(B10 * A01 + B11 * A11 + B12 * A21) * inv_t2, zero)
    M12 = W(-(B10 * A02 + B11 * A12 + B12 * A22) * inv_t2, x0)
    M20 = W(-(B20 * A00 + B21 * A10 + B22 * A20) * inv_t2, x1)
    M21 = W(-(B20 * A01 + B21 * A11 + B22 * A21) * inv_t2, -x0)
    M22 = W(-(B20 * A02 + B21 * A12 + B22 * A22) * inv_t2, zero)

    # J_w = G @ M
    Jw00 = G00 * M00 + G01 * M10 + G02 * M20
    Jw01 = G00 * M01 + G01 * M11 + G02 * M21
    Jw02 = G00 * M02 + G01 * M12 + G02 * M22
    Jw10 = G10 * M00 + G11 * M10 + G12 * M20
    Jw11 = G10 * M01 + G11 * M11 + G12 * M21
    Jw12 = G10 * M02 + G11 * M12 + G12 * M22

    # Intrinsics columns.
    Jf0, Jf1 = rd * px, rd * py
    Jk10, Jk11 = f * n * px, f * n * py
    Jk20, Jk21 = f * n * n * px, f * n * n * py

    r = jnp.stack([r0, r1])
    Jc = jnp.stack([
        Jw00, Jw01, Jw02, G00, G01, G02, Jf0, Jk10, Jk20,
        Jw10, Jw11, Jw12, G10, G11, G12, Jf1, Jk11, Jk21,
    ])
    Jp = jnp.stack([Jp00, Jp01, Jp02, Jp10, Jp11, Jp12])
    return r, Jc, Jp


def bal_residual_jacobian_analytical(
    camera: jnp.ndarray, point: jnp.ndarray, obs: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Single-edge view of the analytical BAL Jacobian.

    (camera[9], point[3], obs[2]) -> (r[2], Jc[2,9], Jp[2,3]).  A thin
    per-edge lens over the row-form engine for callers (tests, custom
    models) that think in one-edge terms; the solver pipeline uses the
    feature-major form directly.
    """
    r, Jc, Jp = bal_residual_jacobian_analytical_fm(
        camera[:, None], point[:, None], obs[:, None])
    return r[:, 0], Jc[:, 0].reshape(2, 9), Jp[:, 0].reshape(2, 3)


@normalized_lru_cache(maxsize=64)
def make_residual_fn(
    residual_fn: ResidualFn = bal_residual,
) -> Callable[..., jnp.ndarray]:
    """Vectorised residual evaluation over feature-major per-edge params.

    Returns fn(cam [cd, nE], pt [pd, nE], obs [od, nE]) -> r [od, nE].
    The equivalent of reference EdgeVector::forward (base_edge.cpp:160-163)
    value plane only.
    """
    return jax.vmap(residual_fn, in_axes=(-1, -1, -1), out_axes=-1)


def build_residual_jacobian_fn(
    residual_fn: ResidualFn = bal_residual,
    mode: JacobianMode = JacobianMode.AUTODIFF,
    analytical_fn: Optional[Callable[..., Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]]] = None,
) -> Callable[..., Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]]:
    """Build the vectorised residual+Jacobian evaluator (uncached).

    Use this directly for per-problem closure engines (BaseProblem's
    custom edges): routing those through the memoised wrapper would pin
    each closure — and the prototype edge it captures — in a global
    cache long after the problem is dropped.  `make_residual_jacobian_fn`
    below is the memoised front for hashable, long-lived configs
    (built-in engines, module-level residual functions).

    Returns fn(cam [cd, nE], pt [pd, nE], obs [od, nE])
      -> (r [od, nE], Jc [od*cd, nE], Jp [od*pd, nE]).

    AUTODIFF (reverse-mode vjp) and AUTODIFF_FORWARD (jax.linearize — the
    direction the reference's JetVector pass uses, SURVEY.md §3.4)
    compute the same Jacobian; ANALYTICAL uses a closed-form row-form
    function (default: the BAL one above).  See common.JacobianMode for
    when each direction wins.
    """
    if mode == JacobianMode.ANALYTICAL:
        fn = analytical_fn
        if fn is None:
            if residual_fn is not bal_residual:
                raise ValueError(
                    "ANALYTICAL mode needs analytical_fn for custom residuals"
                )
            fn = bal_residual_jacobian_analytical_fm
        return fn

    if mode == JacobianMode.AUTODIFF_FORWARD:

        def value_and_jac_fwd(camera, point, obs):
            # jax.linearize: ONE primal evaluation plus cd+pd cheap
            # pushforwards of the linearised map (jacfwd would recompute
            # the primal per basis vector and lean on XLA CSE).
            r, jvp = jax.linearize(
                lambda c, p: residual_fn(c, p, obs), camera, point)
            cd, pd = camera.shape[0], point.shape[0]
            eye_c = jnp.eye(cd, dtype=camera.dtype)
            eye_p = jnp.eye(pd, dtype=point.dtype)
            Jc = jax.vmap(lambda t: jvp(t, jnp.zeros_like(point)))(eye_c)
            Jp = jax.vmap(lambda t: jvp(jnp.zeros_like(camera), t))(eye_p)
            return r, Jc.T, Jp.T  # -> [od, cd], [od, pd]

        per_edge = value_and_jac_fwd
    else:

        def value_and_jac(camera, point, obs):
            # Reverse mode: od pullbacks instead of (cd+pd) pushforwards —
            # the cheap direction for short residuals (see JacobianMode).
            r, pull = jax.vjp(lambda c, p: residual_fn(c, p, obs), camera, point)
            # Stamp the primal's varying-axes type onto the cotangent basis
            # so the pullback is well-typed inside shard_map.  Routing
            # through isfinite keeps the stamp exactly zero even when a
            # residual component is inf/NaN (0*inf would poison the basis).
            stamp = (jnp.isfinite(r).astype(r.dtype) * 0.0)[None, :]
            eye = jnp.eye(r.shape[0], dtype=r.dtype) + stamp
            Jc, Jp = jax.vmap(pull)(eye)
            return r, Jc, Jp  # [od], [od, cd], [od, pd]

        per_edge = value_and_jac

    mapped = jax.vmap(per_edge, in_axes=(-1, -1, -1), out_axes=(-1, -1, -1))

    def fm_fn(cam, pt, obs):  # megba: jit-entry
        r, Jc, Jp = mapped(cam, pt, obs)
        od, cd, nE = Jc.shape
        pd = Jp.shape[1]
        return r, Jc.reshape(od * cd, nE), Jp.reshape(od * pd, nE)

    return fm_fn


@normalized_lru_cache(maxsize=64)
def make_residual_jacobian_fn(
    residual_fn: ResidualFn = bal_residual,
    mode: JacobianMode = JacobianMode.AUTODIFF,
    analytical_fn: Optional[Callable[..., Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]]] = None,
) -> Callable[..., Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]]:
    """Memoised `build_residual_jacobian_fn` — same engine config returns
    the identical callable, keeping jax.jit / the distributed solve cache
    hot across separate solves.  Only pass long-lived hashable
    `residual_fn`s (module-level functions); per-problem closures go
    through `build_residual_jacobian_fn` to avoid cache retention.

    Call-shape normalised (utils/memo.normalized_lru_cache — the
    generalised form of PR 6's hand-written wrapper here), so
    `make_residual_jacobian_fn()` and
    `make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF)` return the
    IDENTICAL object (raw functools.lru_cache keys keyword and
    positional spellings separately — two engines for one config would
    silently double every jit/program cache keyed on engine identity,
    e.g. the serving compile pool).  The factor registry's
    `factors.engine.engine_for` additionally canonicalises
    mode-IRRELEVANT fields (an `analytical_fn` that AUTODIFF would
    ignore) before landing here, so a registry lookup and a direct
    default call can never mint two engines for one program."""
    return build_residual_jacobian_fn(residual_fn, mode, analytical_fn)


def apply_sqrt_info(
    r: jnp.ndarray,
    Jc: jnp.ndarray,
    Jp: jnp.ndarray,
    sqrt_info: Optional[jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pre-whiten residuals and Jacobians by the sqrt information matrix.

    Row form: sqrt_info is [od*od, nE] (row o*od+j = L_oj per edge).
    Weighted least squares: with information Sigma^-1 = L^T L this scales
    r~ = L r, J~ = L J so that H = J~^T J~ and g = -J~^T r~.  Covers the
    reference's information-matrix path (BaseEdge information,
    build_linear_system.cu JMulInfo :148-239) with standard WLS semantics.
    """
    if sqrt_info is None:
        return r, Jc, Jp
    od = r.shape[0]
    cd = Jc.shape[0] // od
    pd = Jp.shape[0] // od

    def rows(J, d):
        return jnp.stack([
            sum(sqrt_info[o * od + j] * J[j * d + a] for j in range(od))
            for o in range(od) for a in range(d)
        ])

    r_w = jnp.stack([
        sum(sqrt_info[o * od + j] * r[j] for j in range(od))
        for o in range(od)
    ])
    return r_w, rows(Jc, cd), rows(Jp, pd)
