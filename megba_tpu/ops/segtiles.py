"""Block-aligned tiled segment reduction / expansion (the TPU scatter killer).

The reference accumulates per-edge Hessian contributions with CUDA
atomicAdd (src/edge/build_linear_system.cu:88-146) and applies the
coupling blocks with cuSPARSE SpMV / per-edge scatter kernels
(src/solver/implicit_schur_pcg_solver.cu:20-90).  The direct XLA
translation — `out.at[:, idx].add(rows)` — is catastrophic on TPU:
XLA:TPU lowers scatter-add to a serialized per-update loop (~45 ns per
edge measured on a v5e), which puts sixty full-edge-axis scatters per LM
iteration on the critical path.

This module replaces every large gather/scatter with dense one-hot
matmuls that ride the MXU, organised by a host-side *plan*:

  1. Sort edges by segment (camera or point id) and PAD so that each
     tile of `tile` consecutive edge slots touches segments from exactly
     ONE aligned block of `block` segments.  Padding slots carry zero
     data, so they are inert in every reduction.
  2. `tile_reduce`: a Pallas grid over tiles; each tile computes
     `data[F, T] @ onehot[T, B] -> [F, B]` in VMEM and accumulates into
     the output block `[F, B]` shared by consecutive tiles (the per-tile
     block index is non-decreasing by construction, so revisits are
     always consecutive — the canonical Pallas accumulation pattern).
     The output is written exactly once per block: no scatter exists.
  3. `tile_expand`: the transpose — `table[F, B] @ onehot[B, T]` —
     replaces `jnp.take(table, idx, axis=1)` (segment -> edge gather).

Everything is feature-major ([F, N] rows, see core/fm.py).  A pure-XLA
fallback with identical semantics (`reduce_fallback` / `expand_fallback`)
runs the same plan on CPU / in tests and under the sharded mesh path.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import sys
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from megba_tpu.ops import fused as fused_ops

# Defaults chosen for v5e VMEM (~128 MB) and MXU tile shapes:
# onehot [T, B] f32 must stay a few MB.  The camera axis is short
# (thousands), so narrow blocks waste nothing; the point axis is long
# (millions) with ~5 edges per point, so B ~ 2 * T keeps the padding
# overhead ~10% while amortising block switches.
DEFAULT_TILE_CAM = 2048
DEFAULT_BLOCK_CAM = 128
DEFAULT_TILE_PT = 1024
DEFAULT_BLOCK_PT = 2048


def _fit_tile(t: int, n: int) -> int:
    """Shrink tile size t so it does not dwarf an n-edge problem."""
    while t > 128 and t >= 4 * n:
        t //= 2
    return t


@dataclasses.dataclass(frozen=True)
class TilePlan:
    """Static reordering of one edge axis for block-aligned reduction.

    All index arrays are host numpy; callers move them on-device once at
    lowering.  `perm[s]` is the source edge for slot s (padding slots
    repeat a valid source and are masked).  `n_slots = n_tiles * tile`.
    """

    tile: int
    block: int
    num_segments: int  # true segment count (outputs sliced to this)
    num_blocks: int
    n_edges: int  # real edges (before padding)
    perm: np.ndarray  # [n_slots] int32 source edge per slot
    seg: np.ndarray  # [n_slots] int32 segment id per slot (in-block valid)
    local: np.ndarray  # [n_slots] int32 seg - block_base, in [0, block)
    mask: np.ndarray  # [n_slots] float32 1.0 real / 0.0 padding
    tile_block: np.ndarray  # [n_tiles] int32 block index per tile
    tile_first: np.ndarray  # [n_tiles] int32 1 if first tile of its block

    @property
    def n_slots(self) -> int:
        return self.perm.shape[0]

    @property
    def n_tiles(self) -> int:
        return self.tile_block.shape[0]

    @property
    def padded_segments(self) -> int:
        return self.num_blocks * self.block


def build_tile_plan(
    idx: np.ndarray,
    num_segments: int,
    tile: int,
    block: int,
) -> TilePlan:
    """Plan a block-aligned order for edges with segment ids `idx`.

    Stable-sorts edges by segment, then pads each aligned block of
    `block` segments to a whole number of `tile`-edge tiles.  Every
    block gets at least one tile (possibly all-padding) so the kernel
    initialises every output block — unvisited VMEM would be garbage.
    """
    idx = np.asarray(idx)
    n_edges = int(idx.shape[0])
    num_blocks = max(1, -(-num_segments // block))
    order = np.argsort(idx, kind="stable").astype(np.int64)
    seg_sorted = idx[order]
    blk_sorted = seg_sorted // block
    counts = np.bincount(blk_sorted, minlength=num_blocks)
    tiles_per_block = np.maximum(1, -(-counts // tile))
    n_tiles = int(tiles_per_block.sum())
    n_slots = n_tiles * tile

    # Vectorised slot construction (no per-block Python loop): block b's
    # slots start at tile * cumsum(tiles_per_block)[b-1]; sorted edge i
    # lands at its block's slot base + its rank within the block.
    slot_base = np.zeros(num_blocks, np.int64)
    np.cumsum(tiles_per_block[:-1] * tile, out=slot_base[1:])
    first_pos = np.cumsum(counts) - counts  # first sorted-edge per block
    slot_of_edge = slot_base[blk_sorted] + (
        np.arange(n_edges, dtype=np.int64) - first_pos[blk_sorted])

    tile_block = np.repeat(
        np.arange(num_blocks, dtype=np.int32), tiles_per_block)
    tile_first = np.ones(n_tiles, np.int32)
    tile_first[1:] = tile_block[1:] != tile_block[:-1]

    # Padding slots carry their block's running-max real segment (block
    # base for empty blocks) and, arbitrarily, source edge 0 — their data
    # is masked out.  Running-max (not block base) keeps the whole slot
    # seg stream non-decreasing: real ids sort ascending within a block,
    # padding sits at their max, and the next block starts strictly
    # higher — so every `indices_are_sorted=True` scatter over this
    # stream (reduce_fallback, the Hessian build, the SCHUR_DIAG
    # preconditioner) rests on a true promise.
    blk_fill = np.arange(num_blocks, dtype=np.int64) * block
    has = counts > 0
    last = np.cumsum(counts) - 1
    blk_fill[has] = seg_sorted[last[has]]
    perm = np.zeros(n_slots, np.int32)
    seg = np.repeat(blk_fill[tile_block], tile)
    mask = np.zeros(n_slots, np.float32)
    perm[slot_of_edge] = order
    seg[slot_of_edge] = seg_sorted
    mask[slot_of_edge] = 1.0
    local = seg - np.repeat(tile_block, tile).astype(np.int64) * block
    return TilePlan(
        tile=tile,
        block=block,
        num_segments=num_segments,
        num_blocks=num_blocks,
        n_edges=n_edges,
        perm=perm,
        seg=seg.astype(np.int32),
        local=local.astype(np.int32),
        mask=mask,
        tile_block=tile_block,
        tile_first=tile_first,
    )


@dataclasses.dataclass(frozen=True)
class DevicePlan:
    """The on-device half of a TilePlan (static ints + device arrays).

    Registered as a pytree so it can ride through jit closures and
    lax.while_loop carries untouched (all leaves are constants).
    """

    tile: int
    block: int
    num_segments: int
    num_blocks: int
    local: jax.Array  # [1, n_slots] int32 (2-D for Mosaic block specs)
    tile_block: jax.Array  # [n_tiles] int32
    tile_first: jax.Array  # [n_tiles] int32
    mask: jax.Array  # [n_slots] f32
    perm: jax.Array  # [n_slots] int32
    inv: Optional[jax.Array]  # [n_other] int32: slot in THIS plan holding
    # the other-order slot's edge (cross-order permute), or None


def device_plan(
    plan: TilePlan, inv: Optional[np.ndarray] = None
) -> DevicePlan:
    return DevicePlan(
        tile=plan.tile,
        block=plan.block,
        num_segments=plan.num_segments,
        num_blocks=plan.num_blocks,
        local=jnp.asarray(plan.local)[None, :],
        tile_block=jnp.asarray(plan.tile_block),
        tile_first=jnp.asarray(plan.tile_first),
        mask=jnp.asarray(plan.mask),
        perm=jnp.asarray(plan.perm),
        inv=None if inv is None else jnp.asarray(inv),
    )


jax.tree_util.register_dataclass(
    DevicePlan,
    data_fields=["local", "tile_block", "tile_first", "mask", "perm", "inv"],
    meta_fields=["tile", "block", "num_segments", "num_blocks"],
)


def cross_perm(primary: TilePlan, secondary: TilePlan) -> np.ndarray:
    """inv[s_primary] = slot in `secondary` holding the same edge.

    Lets `x_primary = gather(x_secondary, inv)` re-order per-edge rows
    between the two plans.  Padding slots of `primary` point at slot 0
    of `secondary` (their values are masked anyway).
    """
    slot_of_edge = np.zeros(secondary.n_edges, np.int64)
    real = secondary.mask > 0
    slot_of_edge[secondary.perm[real]] = np.nonzero(real)[0]
    inv = slot_of_edge[primary.perm]
    inv[primary.mask == 0] = 0
    return inv.astype(np.int32)


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------


def _reduce_kernel(tb_ref, tf_ref, local_ref, data_ref, out_ref, *, block):
    """Accumulate one tile's [F, T] rows into its block's [F, B] sums."""
    i = pl.program_id(0)
    tile = local_ref.shape[1]
    onehot = (
        local_ref[:, :] == jax.lax.broadcasted_iota(
            jnp.int32, (block, tile), 0)
    ).astype(jnp.float32)  # [B, T]
    partial = jax.lax.dot_general(
        data_ref[:, :].astype(jnp.float32), onehot,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [F, B]

    @pl.when(tf_ref[i] == 1)
    def _init():
        out_ref[:, :] = partial.astype(out_ref.dtype)

    @pl.when(tf_ref[i] == 0)
    def _acc():
        out_ref[:, :] = (out_ref[:, :] + partial).astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("tile", "block", "num_blocks", "interpret"))
def _tile_reduce_call(
    data, local, tile_block, tile_first, *, tile, block, num_blocks,
    interpret,
):
    F = data.shape[0]
    n_tiles = tile_block.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # tile_block, tile_first
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, tile), lambda i, tb, tf: (0, i)),
            pl.BlockSpec((F, tile), lambda i, tb, tf: (0, i)),
        ],
        out_specs=pl.BlockSpec(
            (F, block), lambda i, tb, tf: (0, tb[i])),
    )
    return pl.pallas_call(
        functools.partial(_reduce_kernel, block=block),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((F, num_blocks * block), jnp.float32),
        interpret=interpret,
    )(tile_block, tile_first, local, data)


def tile_reduce(
    data: jax.Array, plan: DevicePlan, interpret: bool = False
) -> jax.Array:
    """Sum plan-ordered [F, n_slots] rows into [F, num_segments].

    Equivalent (up to f32 summation order) to
    `zeros.at[:, seg].add(data * mask)`; `data` must already be in plan
    slot order with padding slots zero (use `mask_rows` after a gather
    if unsure).
    """
    out = _tile_reduce_call(
        data, plan.local, plan.tile_block, plan.tile_first,
        tile=plan.tile, block=plan.block, num_blocks=plan.num_blocks,
        interpret=interpret,
    )
    return out[:, : plan.num_segments].astype(data.dtype)


def _expand_kernel(tb_ref, local_ref, table_ref, out_ref, *, block):
    tile = local_ref.shape[1]
    onehot = (
        local_ref[:, :] == jax.lax.broadcasted_iota(
            jnp.int32, (block, tile), 0)
    ).astype(jnp.float32)  # [B, T]
    out_ref[:, :] = jax.lax.dot_general(
        table_ref[:, :].astype(jnp.float32), onehot,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("tile", "block", "num_blocks", "interpret"))
def _tile_expand_call(
    table, local, tile_block, *, tile, block, num_blocks, interpret
):
    F = table.shape[0]
    n_tiles = tile_block.shape[0]
    pad = num_blocks * block - table.shape[1]
    table_p = jnp.pad(table, ((0, 0), (0, pad))) if pad else table
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # tile_block
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, tile), lambda i, tb: (0, i)),
            pl.BlockSpec((F, block), lambda i, tb: (0, tb[i])),
        ],
        out_specs=pl.BlockSpec((F, tile), lambda i, tb: (0, i)),
    )
    return pl.pallas_call(
        functools.partial(_expand_kernel, block=block),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (F, n_tiles * tile), table.dtype),
        interpret=interpret,
    )(tile_block, local, table_p)


def tile_expand(
    table: jax.Array, plan: DevicePlan, interpret: bool = False
) -> jax.Array:
    """Gather segment rows to plan-ordered edges: [F, nS] -> [F, n_slots].

    Equivalent to `jnp.take(table, seg, axis=1)` (padding slots read
    their block's running-max real segment; mask before reducing).
    """
    return _tile_expand_call(
        table, plan.local, plan.tile_block,
        tile=plan.tile, block=plan.block, num_blocks=plan.num_blocks,
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# Pure-XLA fallbacks (CPU tests, sharded mesh path)
# ---------------------------------------------------------------------------


def reduce_fallback(data: jax.Array, plan: DevicePlan) -> jax.Array:
    out = jnp.zeros((data.shape[0], plan.num_segments), data.dtype)
    seg = plan.local + plan.tile_block.repeat(plan.tile) * plan.block
    # The sorted promise is honest: build_tile_plan fills padding slots
    # with each block's running-max real segment, so `seg` is globally
    # non-decreasing (junk-block tiles appended by _pad_device_plan sit
    # past num_segments and are dropped).
    return out.at[:, seg[0]].add(
        data, indices_are_sorted=True, mode="drop")


def expand_fallback(table: jax.Array, plan: DevicePlan) -> jax.Array:
    seg = plan.local + plan.tile_block.repeat(plan.tile) * plan.block
    return jnp.take(table, seg[0], axis=1, mode="clip")


def seg_reduce(
    data: jax.Array, plan: DevicePlan, use_kernels: bool
) -> jax.Array:
    """Plan-ordered rows -> per-segment sums; kernel or XLA fallback."""
    if use_kernels:
        return tile_reduce(data, plan)
    return reduce_fallback(data, plan)


def seg_expand(
    table: jax.Array, plan: DevicePlan, use_kernels: bool
) -> jax.Array:
    """Per-segment rows -> plan-ordered per-edge rows (gather)."""
    if use_kernels:
        return tile_expand(table, plan)
    return expand_fallback(table, plan)


# ---------------------------------------------------------------------------
# Fused J^T J + gradient build (the makeHSchur / makeHppHllSchur analog)
# ---------------------------------------------------------------------------


def _jtj_kernel(tb_ref, tf_ref, local_ref, j_ref, r_ref, out_ref,
                *, block, d, od):
    """One tile: rows of J^T J (d*d) and -J^T r (d) reduced to its block.

    The per-edge outer-product rows are built in VMEM from the [od*d, T]
    Jacobian block and immediately contracted onto the block axis with
    one MXU matmul — the feature rows never touch HBM (the reference
    fuses the same way with shared-memory staging + atomicAdd,
    build_linear_system.cu:88-146).
    """
    i = pl.program_id(0)
    tile = local_ref.shape[1]
    rows = []
    for a in range(d):
        for b in range(d):
            acc = None
            for o in range(od):
                t = j_ref[o * d + a, :] * j_ref[o * d + b, :]
                acc = t if acc is None else acc + t
            rows.append(acc[None, :])
    for a in range(d):
        acc = None
        for o in range(od):
            t = j_ref[o * d + a, :] * r_ref[o, :]
            acc = t if acc is None else acc + t
        rows.append(-acc[None, :])
    feat = jnp.concatenate(rows, axis=0).astype(jnp.float32)  # [d*d+d, T]
    onehot = (
        local_ref[:, :] == jax.lax.broadcasted_iota(
            jnp.int32, (block, tile), 0)
    ).astype(jnp.float32)
    partial = jax.lax.dot_general(
        feat, onehot, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [d*d+d, B]

    @pl.when(tf_ref[i] == 1)
    def _init():
        out_ref[:, :] = partial.astype(out_ref.dtype)

    @pl.when(tf_ref[i] == 0)
    def _acc():
        out_ref[:, :] = (out_ref[:, :] + partial).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("d", "od", "tile", "block", "num_blocks", "interpret"))
def _jtj_reduce_call(
    J, r, local, tile_block, tile_first, *, d, od, tile, block, num_blocks,
    interpret,
):
    n_tiles = tile_block.shape[0]
    feat = d * d + d
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, tile), lambda i, tb, tf: (0, i)),
            pl.BlockSpec((od * d, tile), lambda i, tb, tf: (0, i)),
            pl.BlockSpec((od, tile), lambda i, tb, tf: (0, i)),
        ],
        out_specs=pl.BlockSpec(
            (feat, block), lambda i, tb, tf: (0, tb[i])),
    )
    return pl.pallas_call(
        functools.partial(_jtj_kernel, block=block, d=d, od=od),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (feat, num_blocks * block), jnp.float32),
        interpret=interpret,
    )(tile_block, tile_first, local, J, r)


def jtj_grad_reduce(
    J: jax.Array,
    r: jax.Array,
    plan: DevicePlan,
    use_kernels: bool,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused block-diagonal Hessian + gradient for one vertex kind.

    J [od*d, n_slots], r [od, n_slots] in plan slot order (weighted and
    masked).  Returns (h_rows [d*d, nS], g_rows [d, nS]) — the rows of
    sum_e J_e^T J_e and -J_e^T r_e per segment.
    """
    od = r.shape[0]
    d = J.shape[0] // od
    if use_kernels or interpret:
        out = _jtj_reduce_call(
            J, r, plan.local, plan.tile_block, plan.tile_first,
            d=d, od=od, tile=plan.tile, block=plan.block,
            num_blocks=plan.num_blocks, interpret=interpret)
        out = out[:, : plan.num_segments].astype(J.dtype)
    else:
        out = _jtj_fallback_chunked(J, r, plan, d, od)
    return out[: d * d], out[d * d:]


def _jtj_fallback_chunked(J, r, plan: DevicePlan, d: int, od: int,
                          chunk: int = 65_536) -> jax.Array:
    """XLA fallback of the fused build, chunked over slots.

    This is the degradation route when Mosaic rejects the kernels on a
    real TPU (probe_kernels False) and the CPU test path — so its
    transient memory must stay bounded: at Final scale the un-chunked
    [d*d+d, n_slots] feature-row matrix is ~10 GB.  Slot chunks keep it
    to [d*d+d, chunk] (~23 MB at the default), and slices of the
    plan-sorted seg stream stay non-decreasing, so the scatter keeps its
    sorted promise.
    """
    feat = d * d + d
    seg = (plan.local
           + plan.tile_block.repeat(plan.tile)[None, :] * plan.block)[0]
    n = seg.shape[0]
    # Derive the accumulator from J so that inside shard_map its
    # varying-axes type matches the loop body's output (J/r/seg are
    # device-varying; a plain jnp.zeros carry is replicated-typed and
    # lax.fori_loop rejects the carry-type mismatch).  isnan keeps the
    # seed finite-zero even when J[0, 0] is inf/NaN (J * 0 would
    # broadcast NaN into every accumulator cell) while still making the
    # value data-dependent for the varying-axes tracer.
    seed = jnp.isnan(J[0, 0]).astype(J.dtype) * 0
    out = jnp.zeros((feat, plan.num_segments), J.dtype) + seed

    def rows_of(Jc, rc):
        return jnp.concatenate([
            jnp.stack([
                sum(Jc[o * d + a] * Jc[o * d + b] for o in range(od))
                for a in range(d) for b in range(d)]),
            jnp.stack([
                -sum(Jc[o * d + a] * rc[o] for o in range(od))
                for a in range(d)]),
        ])

    if n <= chunk:
        return out.at[:, seg].add(
            rows_of(J, r), indices_are_sorted=True, mode="drop")

    # Pad to a whole number of chunks with inert slots (zero data,
    # out-of-range segment -> dropped by the scatter) so every fori_loop
    # step slices a full static-size chunk — no clamped dynamic_slice
    # overlap double-counting the tail.
    pad = (-n) % chunk
    if pad:
        J = jnp.pad(J, ((0, 0), (0, pad)))
        r = jnp.pad(r, ((0, 0), (0, pad)))
        # Pad with num_blocks*block, not num_segments: sharded plans
        # padded by _pad_device_plan carry junk-block slots with seg up
        # to num_blocks*block - 1 >= num_segments, so only this value is
        # guaranteed >= every live or junk seg — keeping the padded tail
        # non-decreasing as indices_are_sorted=True promises.  Still
        # out of range, so the scatter drops it.
        seg = jnp.pad(seg, (0, pad),
                      constant_values=plan.num_blocks * plan.block)

    def body(k, acc):
        start = k * chunk
        Jc = jax.lax.dynamic_slice_in_dim(J, start, chunk, axis=1)
        rc = jax.lax.dynamic_slice_in_dim(r, start, chunk, axis=1)
        sc = jax.lax.dynamic_slice_in_dim(seg, start, chunk)
        return acc.at[:, sc].add(
            rows_of(Jc, rc), indices_are_sorted=True, mode="drop")

    return jax.lax.fori_loop(0, seg.shape[0] // chunk, body, out)


# ---------------------------------------------------------------------------
# Fused coupling-product kernels: (expand -> J.x) and (J^T.u -> reduce)
# ---------------------------------------------------------------------------


def _expand_matvec_kernel(tb_ref, local_ref, j_ref, table_ref, out_ref,
                          *, block, d):
    """u[o] = sum_a J[o*d+a] * table[a, seg]: gather + per-edge matvec.

    The vertex table block lives entirely in VMEM; the gather is the
    one-hot matmul, the [od, T] product rows are the only HBM write —
    the [d, T] expanded rows never exist outside VMEM.
    """
    tile = local_ref.shape[1]
    od = j_ref.shape[0] // d
    onehot = (
        local_ref[:, :] == jax.lax.broadcasted_iota(
            jnp.int32, (block, tile), 0)
    ).astype(jnp.float32)  # [B, T]
    pe = jax.lax.dot_general(
        table_ref[:, :].astype(jnp.float32), onehot,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [d, T]
    for o in range(od):
        acc = None
        for a in range(d):
            t = j_ref[o * d + a, :].astype(jnp.float32) * pe[a, :]
            acc = t if acc is None else acc + t
        out_ref[o, :] = acc.astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("d", "tile", "block", "num_blocks", "interpret"))
def _expand_matvec_call(
    J, table, local, tile_block, *, d, tile, block, num_blocks, interpret
):
    od = J.shape[0] // d
    n_tiles = tile_block.shape[0]
    pad = num_blocks * block - table.shape[1]
    table_p = jnp.pad(table, ((0, 0), (0, pad))) if pad else table
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, tile), lambda i, tb: (0, i)),
            pl.BlockSpec((J.shape[0], tile), lambda i, tb: (0, i)),
            pl.BlockSpec((d, block), lambda i, tb: (0, tb[i])),
        ],
        out_specs=pl.BlockSpec((od, tile), lambda i, tb: (0, i)),
    )
    return pl.pallas_call(
        functools.partial(_expand_matvec_kernel, block=block, d=d),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((od, n_tiles * tile), jnp.float32),
        interpret=interpret,
    )(tile_block, local, J, table_p)


def _matvec_reduce_kernel(tb_ref, tf_ref, local_ref, j_ref, u_ref, out_ref,
                          *, block, d):
    """out[b, seg] += sum_o J[o*d+b] * u[o]: per-edge J^T u + reduce.

    The [d, T] product rows are formed in VMEM and immediately
    contracted onto the block axis — they never touch HBM.
    """
    i = pl.program_id(0)
    tile = local_ref.shape[1]
    od = u_ref.shape[0]
    rows = []
    for b in range(d):
        acc = None
        for o in range(od):
            t = (j_ref[o * d + b, :].astype(jnp.float32)
                 * u_ref[o, :].astype(jnp.float32))
            acc = t if acc is None else acc + t
        rows.append(acc[None, :])
    te = jnp.concatenate(rows, axis=0)  # [d, T]
    onehot = (
        local_ref[:, :] == jax.lax.broadcasted_iota(
            jnp.int32, (block, tile), 0)
    ).astype(jnp.float32)
    partial = jax.lax.dot_general(
        te, onehot, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [d, B]

    @pl.when(tf_ref[i] == 1)
    def _init():
        out_ref[:, :] = partial.astype(out_ref.dtype)

    @pl.when(tf_ref[i] == 0)
    def _acc():
        out_ref[:, :] = (out_ref[:, :] + partial).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("d", "tile", "block", "num_blocks", "interpret"))
def _matvec_reduce_call(
    J, u, local, tile_block, tile_first, *, d, tile, block, num_blocks,
    interpret,
):
    n_tiles = tile_block.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, tile), lambda i, tb, tf: (0, i)),
            pl.BlockSpec((J.shape[0], tile), lambda i, tb, tf: (0, i)),
            pl.BlockSpec((u.shape[0], tile), lambda i, tb, tf: (0, i)),
        ],
        out_specs=pl.BlockSpec(
            (d, block), lambda i, tb, tf: (0, tb[i])),
    )
    return pl.pallas_call(
        functools.partial(_matvec_reduce_kernel, block=block, d=d),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (d, num_blocks * block), jnp.float32),
        interpret=interpret,
    )(tile_block, tile_first, local, J, u)


def coupling_expand(
    table: jax.Array,
    J: jax.Array,
    plan: DevicePlan,
    d: int,
    use_kernels: bool,
    interpret: bool = False,
) -> jax.Array:
    """u[o] = sum_a J[o*d+a] * table[a, seg]  -> [od, n_slots] rows.

    The fused (gather + J.x) half of a coupling product: J in plan slot
    order, table [d, num_segments].  Output is float32.
    """
    if use_kernels or interpret:
        return _expand_matvec_call(
            J, table.astype(jnp.float32), plan.local, plan.tile_block,
            d=d, tile=plan.tile, block=plan.block,
            num_blocks=plan.num_blocks, interpret=interpret)
    od = J.shape[0] // d
    pe = expand_fallback(table, plan)
    return jnp.stack([
        sum(J[o * d + a].astype(jnp.float32) * pe[a] for a in range(d))
        for o in range(od)
    ])


def coupling_reduce(
    J: jax.Array,
    u: jax.Array,
    plan: DevicePlan,
    d: int,
    use_kernels: bool,
    interpret: bool = False,
) -> jax.Array:
    """out[b, seg] = sum_edges sum_o J[o*d+b] * u[o]  -> [d, nS].

    The fused (J^T.u + segment reduce) half of a coupling product.
    """
    if use_kernels or interpret:
        out = _matvec_reduce_call(
            J, u, plan.local, plan.tile_block, plan.tile_first,
            d=d, tile=plan.tile, block=plan.block,
            num_blocks=plan.num_blocks, interpret=interpret)
        return out[:, : plan.num_segments]
    od = u.shape[0]
    te = jnp.stack([
        sum(J[o * d + b].astype(jnp.float32) * u[o] for o in range(od))
        for b in range(d)
    ])
    return reduce_fallback(te, plan)


# ---------------------------------------------------------------------------
# Dual plans: camera-sorted primary order + point-sorted secondary order
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DualPlans:
    """Both edge orderings of one BA problem + cross permutations.

    The primary (slot) order of all edge arrays is `cam`'s slot order;
    `pt.inv[s_pt]` is the cam slot holding pt-slot s_pt's edge, and
    `cam.inv[s_cam]` the reverse.  `use_kernels` selects the Pallas
    kernels (real TPU) vs the XLA fallback (CPU tests, interpret-free).

    `fused_to_pt`/`fused_to_cam` are the OPTIONAL bucket-structured
    plans of the fused edge-pipeline kernels (ops/fused.py), expressed
    over the SAME cam-slot edge stream; None (the default) keeps the
    pytree — and every lowered program — byte-identical to the
    pre-fused layout, so attaching them only under
    `SolverOption(fused_kernels=True)` is the dark-landing guarantee.
    """

    cam: DevicePlan
    pt: DevicePlan
    use_kernels: bool
    fused_to_pt: Optional["fused_ops.DeviceFusedPlan"] = None
    fused_to_cam: Optional["fused_ops.DeviceFusedPlan"] = None

    # -- conversions between the two slot orders (per-edge rows) --
    def to_pt(self, rows_cam: jax.Array) -> jax.Array:
        return jnp.take(
            rows_cam, self.pt.inv, axis=1, mode="clip") * self.pt.mask

    def to_cam(self, rows_pt: jax.Array) -> jax.Array:
        return jnp.take(
            rows_pt, self.cam.inv, axis=1, mode="clip") * self.cam.mask


jax.tree_util.register_dataclass(
    DualPlans,
    data_fields=["cam", "pt", "fused_to_pt", "fused_to_cam"],
    meta_fields=["use_kernels"])


def make_dual_plans(
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    num_cameras: int,
    num_points: int,
    tile_cam: int = DEFAULT_TILE_CAM,
    block_cam: int = DEFAULT_BLOCK_CAM,
    tile_pt: int = DEFAULT_TILE_PT,
    block_pt: int = DEFAULT_BLOCK_PT,
    use_kernels: Optional[bool] = None,
    fit: bool = True,
) -> Tuple[TilePlan, DualPlans]:
    """Plan both orderings.  Returns (cam_host_plan, device DualPlans).

    The caller must reorder every edge array into the cam plan's slot
    order (`arr[:, cam_plan.perm] * cam_plan.mask`) — that order is the
    canonical edge axis from here on.  The pt plan is expressed in
    cam-slot space, so `pt.inv` indexes cam slots directly.

    `fit=False` uses `tile_cam`/`tile_pt` verbatim — the sharded planner
    fits them ONCE from the largest shard so every shard's plan leaves
    share one tile shape and stack cleanly.
    """
    cam_idx = np.asarray(cam_idx)
    pt_idx = np.asarray(pt_idx)
    if fit:
        # Keep tiles from dwarfing tiny problems (tests, toy datasets).
        n = cam_idx.shape[0]
        tile_cam = _fit_tile(tile_cam, n)
        tile_pt = _fit_tile(tile_pt, n)

    plan_c = build_tile_plan(cam_idx, num_cameras, tile_cam, block_cam)
    # The pt plan is built over the CAM-SLOT edge stream: segment id of a
    # cam slot is its edge's point (padding slots get an out-of-range
    # marker sorted to the end and masked).
    pt_of_slot = np.where(
        plan_c.mask > 0, pt_idx[plan_c.perm], num_points)
    plan_p_raw = build_tile_plan(
        np.minimum(pt_of_slot, num_points - 1).astype(np.int64),
        num_points, tile_pt, block_pt)
    # Mask out slots whose source cam slot was itself padding.
    src_mask = (plan_c.mask > 0)[plan_p_raw.perm]
    mask_p = plan_p_raw.mask * src_mask
    plan_p = dataclasses.replace(plan_p_raw, mask=mask_p.astype(np.float32))

    inv_pt = plan_p.perm.astype(np.int32)  # pt slot -> cam slot
    inv_pt = np.where(plan_p.mask > 0, inv_pt, 0).astype(np.int32)
    # cam slot -> pt slot
    slot_of_cam = np.zeros(plan_c.n_slots, np.int64)
    real_p = plan_p.mask > 0
    slot_of_cam[plan_p.perm[real_p]] = np.nonzero(real_p)[0]
    inv_cam = np.where(
        plan_c.mask > 0, slot_of_cam[np.arange(plan_c.n_slots)], 0
    ).astype(np.int32)

    if use_kernels is None:
        use_kernels = probe_kernels()
    dp_c = device_plan(plan_c)
    dp_c = dataclasses.replace(dp_c, inv=jnp.asarray(inv_cam))
    dp_p = device_plan(plan_p)
    dp_p = dataclasses.replace(dp_p, inv=jnp.asarray(inv_pt))
    return plan_c, DualPlans(cam=dp_c, pt=dp_p, use_kernels=use_kernels)


def _pad_device_plan(dp: DevicePlan, n_tiles_to: int, junk_block: bool):
    """Append inert tiles so stacked shards share one tile count.

    Padding tiles target a dedicated JUNK block appended after the real
    ones (first=1 on the first padding tile) — pointing them at a real
    block would revisit it non-consecutively, which the sequential-
    accumulation kernels do not support.
    """
    n_tiles = dp.tile_block.shape[0]
    add = n_tiles_to - n_tiles
    nb = dp.num_blocks + (1 if junk_block else 0)
    if add == 0 and not junk_block:
        return dp
    if add:
        lb = jnp.full((1, add * dp.tile), 0, jnp.int32)
        local = jnp.concatenate([dp.local, lb], axis=1)
        tb = jnp.concatenate([
            dp.tile_block,
            jnp.full((add,), nb - 1, jnp.int32)])
        tf = jnp.concatenate([
            dp.tile_first,
            jnp.asarray([1] + [0] * (add - 1), jnp.int32)])
        mask = jnp.concatenate([dp.mask, jnp.zeros((add * dp.tile,),
                                                   dp.mask.dtype)])
        perm = jnp.concatenate([dp.perm, jnp.zeros((add * dp.tile,),
                                                   jnp.int32)])
        inv = dp.inv
        if inv is not None:
            inv = jnp.concatenate(
                [inv, jnp.zeros((add * dp.tile,), jnp.int32)])
    else:
        local, tb, tf, mask, perm, inv = (
            dp.local, dp.tile_block, dp.tile_first, dp.mask, dp.perm,
            dp.inv)
    return dataclasses.replace(
        dp, num_blocks=nb, local=local, tile_block=tb, tile_first=tf,
        mask=mask, perm=perm, inv=inv)


def make_sharded_dual_plans(
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    num_cameras: int,
    num_points: int,
    world_size: int,
    tile_cam: int = DEFAULT_TILE_CAM,
    block_cam: int = DEFAULT_BLOCK_CAM,
    tile_pt: int = DEFAULT_TILE_PT,
    block_pt: int = DEFAULT_BLOCK_PT,
    use_kernels: Optional[bool] = None,
):
    """Per-shard dual plans for the edge-sharded mesh path.

    Edges are camera-sorted and split into `world_size` contiguous
    chunks (the reference's contiguous partition, memory_pool.h:48-63);
    each shard gets its own dual plans over its local edges — so every
    reduction, expansion, and cross permute stays shard-local, and the
    psums in builder/pcg combine the full-size per-shard outputs exactly
    as in the fallback path.

    Returns (perm [ws, slots_c], mask [ws, slots_c], cam_seg
    [ws, slots_c], stacked DualPlans whose leaves carry a leading shard
    axis): shard k's edge arrays are `arr[perm[k]] * mask[k]`, and
    `cam_seg[k]` is the camera id per slot — non-decreasing within the
    shard (padding carries each block's running-max camera; junk-block
    slots are clipped to num_cameras-1), so it can be used directly as a
    sorted `cam_idx` stream.  Every per-shard plan covers ALL global
    segments (so outputs align for the psum); both plan kinds are padded
    to the max per-shard tile count with junk-block tiles, and tile
    sizes are fitted ONCE from the largest shard so every shard's plan
    leaves share one tile shape (stacking would fail otherwise).
    """
    cam_idx = np.asarray(cam_idx)
    pt_idx = np.asarray(pt_idx)
    n = cam_idx.shape[0]
    order = np.argsort(cam_idx, kind="stable")
    bounds = [(k * n) // world_size for k in range(world_size + 1)]
    n_max = max(bounds[k + 1] - bounds[k] for k in range(world_size))
    tile_cam = _fit_tile(tile_cam, n_max)
    tile_pt = _fit_tile(tile_pt, n_max)

    plans = []
    for k in range(world_size):
        sel = order[bounds[k]: bounds[k + 1]]
        _, dp = make_dual_plans(
            cam_idx[sel], pt_idx[sel], num_cameras, num_points,
            tile_cam, block_cam, tile_pt, block_pt, use_kernels,
            fit=False)
        # Re-express perms in global edge ids.
        sel32 = sel.astype(np.int64)
        cam_perm = sel32[np.asarray(dp.cam.perm)]
        plans.append((dp, cam_perm))

    max_tc = max(int(dp.cam.tile_block.shape[0]) for dp, _ in plans)
    max_tp = max(int(dp.pt.tile_block.shape[0]) for dp, _ in plans)
    stacked_c, stacked_p, perms = [], [], []
    for dp, cam_perm in plans:
        slots_before = int(dp.cam.mask.shape[0])
        c = _pad_device_plan(dp.cam, max_tc, junk_block=True)
        p = _pad_device_plan(dp.pt, max_tp, junk_block=True)
        pad_slots = int(c.mask.shape[0]) - slots_before
        if pad_slots:
            cam_perm = np.concatenate(
                [cam_perm, np.zeros(pad_slots, np.int64)])
        stacked_c.append(c)
        stacked_p.append(p)
        perms.append(cam_perm)

    def stack(dps):
        leaves = [jax.tree_util.tree_leaves(d) for d in dps]
        stacked = [jnp.stack(vals) for vals in zip(*leaves)]
        treedef = jax.tree_util.tree_structure(dps[0])
        return jax.tree_util.tree_unflatten(treedef, stacked)

    dual = DualPlans(
        cam=stack(stacked_c), pt=stack(stacked_p),
        use_kernels=plans[0][0].use_kernels)
    masks = np.stack([np.asarray(c.mask) for c in stacked_c])
    cam_segs = np.stack([
        np.minimum(
            np.asarray(c.local)[0]
            + np.repeat(np.asarray(c.tile_block), c.tile) * c.block,
            num_cameras - 1,
        ).astype(np.int32)
        for c in stacked_c])
    return np.stack(perms), masks, cam_segs, dual


def squeeze_plans(plans: DualPlans) -> DualPlans:
    """Drop the leading shard axis inside a shard_map body."""
    return jax.tree_util.tree_map(lambda x: x[0], plans)


# ---------------------------------------------------------------------------
# Host plan cache
# ---------------------------------------------------------------------------
#
# Plan construction is pure host work (argsorts + bincounts over the
# edge axis; ~270 ms per venice-scale solve, PROFILE.md) and depends
# only on the problem GRAPH and the tile geometry — not on parameters
# or observations.  Repeated solves of one problem (bench reruns,
# chunked/checkpointed drivers, the auditor's canonical lowerings,
# parameter sweeps) therefore reuse one plan, keyed by a content
# fingerprint of the index arrays.  A strong digest (blake2b), not
# Python's hash(): a collision would silently solve the wrong graph.

_PLAN_CACHE: "dict" = {}
_PLAN_CACHE_DEFAULT_MAX = 8  # LRU bound: plans pin host+device index arrays
# Monotone eviction counter: a fleet of mixed shape classes churning a
# too-small cache shows up here (solve.flat_solve surfaces the delta as
# a `plan_cache_evict` PhaseTimer event next to `plan_cache_hit`).
_PLAN_CACHE_EVICTIONS = 0


def plan_cache_capacity() -> int:
    """LRU capacity of the host plan cache.

    `MEGBA_PLAN_CACHE=<n>` overrides the default of
    `_PLAN_CACHE_DEFAULT_MAX` (8): a fleet serving many shape classes
    evicts pathologically at 8, while a single-problem pipeline gains
    nothing from more.  Read at insertion time so tests (and long-lived
    services) can retune without reimporting; `<n> >= 1`.
    """
    env = os.environ.get("MEGBA_PLAN_CACHE")
    if env is None:
        return _PLAN_CACHE_DEFAULT_MAX
    try:
        cap = int(env)
    except ValueError as e:
        raise ValueError(
            f"MEGBA_PLAN_CACHE must be an integer >= 1, got {env!r}") from e
    if cap < 1:
        raise ValueError(
            f"MEGBA_PLAN_CACHE must be an integer >= 1, got {env!r}")
    return cap


def plan_cache_evictions() -> int:
    """Total plan-cache evictions this process (monotone counter)."""
    return _PLAN_CACHE_EVICTIONS


def _array_digest(a: np.ndarray) -> bytes:
    import hashlib

    a = np.ascontiguousarray(a)
    h = hashlib.blake2b(digest_size=16)
    h.update(str(a.dtype).encode())
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.digest()


def _plan_cache_get(key):
    hit = _PLAN_CACHE.get(key)
    if hit is not None:
        # Refresh LRU position (dicts preserve insertion order).
        _PLAN_CACHE.pop(key)
        _PLAN_CACHE[key] = hit
    return hit


def _plan_cache_put(key, value):
    global _PLAN_CACHE_EVICTIONS
    cap = plan_cache_capacity()
    while len(_PLAN_CACHE) >= cap:
        _PLAN_CACHE.pop(next(iter(_PLAN_CACHE)))
        _PLAN_CACHE_EVICTIONS += 1
    _PLAN_CACHE[key] = value


def cached_dual_plans(
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    num_cameras: int,
    num_points: int,
    tile_cam: int = DEFAULT_TILE_CAM,
    block_cam: int = DEFAULT_BLOCK_CAM,
    tile_pt: int = DEFAULT_TILE_PT,
    block_pt: int = DEFAULT_BLOCK_PT,
    use_kernels: Optional[bool] = None,
):
    """`make_dual_plans` behind the host plan cache.

    Returns ((cam_host_plan, DualPlans), cache_hit).  `use_kernels` is
    resolved (probe_kernels) BEFORE keying, so a plan probed on one
    backend can never serve a solve on another.
    """
    if use_kernels is None:
        use_kernels = probe_kernels()
    key = ("single", _array_digest(cam_idx), _array_digest(pt_idx),
           int(num_cameras), int(num_points),
           tile_cam, block_cam, tile_pt, block_pt, use_kernels)
    hit = _plan_cache_get(key)
    if hit is not None:
        return hit, True
    value = make_dual_plans(
        cam_idx, pt_idx, num_cameras, num_points,
        tile_cam, block_cam, tile_pt, block_pt, use_kernels)
    _plan_cache_put(key, value)
    return value, False


def cached_sharded_dual_plans(
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    num_cameras: int,
    num_points: int,
    world_size: int,
    tile_cam: int = DEFAULT_TILE_CAM,
    block_cam: int = DEFAULT_BLOCK_CAM,
    tile_pt: int = DEFAULT_TILE_PT,
    block_pt: int = DEFAULT_BLOCK_PT,
    use_kernels: Optional[bool] = None,
):
    """`make_sharded_dual_plans` behind the host plan cache.

    Returns ((perms, masks, cam_segs, DualPlans), cache_hit)."""
    if use_kernels is None:
        use_kernels = probe_kernels()
    key = ("sharded", _array_digest(cam_idx), _array_digest(pt_idx),
           int(num_cameras), int(num_points), int(world_size),
           tile_cam, block_cam, tile_pt, block_pt, use_kernels)
    hit = _plan_cache_get(key)
    if hit is not None:
        return hit, True
    value = make_sharded_dual_plans(
        cam_idx, pt_idx, num_cameras, num_points, world_size,
        tile_cam, block_cam, tile_pt, block_pt, use_kernels)
    _plan_cache_put(key, value)
    return value, False


# ---------------------------------------------------------------------------
# Camera-graph cluster plan (two-level Schur preconditioner coarse space)
# ---------------------------------------------------------------------------
#
# The two-level preconditioner (solver/precond.py) needs three pieces of
# pure GRAPH structure, all host-computable at plan time and cacheable
# behind the same content-fingerprint LRU as the tile plans:
#
#   1. an aggregation of cameras into O(sqrt(Nc)) clusters — greedy,
#      co-observation-weighted (cameras that share many points merge
#      first), so a point's edges concentrate in few clusters;
#   2. the distinct (point, cluster) incidences ("pc-slots"): the
#      coarse-projected coupling R·Hpl has one [cd, pd] block per
#      incidence (V_{p,I} = Σ_{e: pt(e)=p, cluster(cam(e))=I} W_e), and
#      the device build scatter-adds per-edge W rows into them via the
#      per-edge `pc_slot` stream;
#   3. the (edge, pc-slot-of-same-point) incidence pairs ("ec-pairs"):
#      the columns of G = S_d·Rᵀ pick up one W_e·Hll⁻¹·V_sᵀ block per
#      pair — enumerated once here (Σ_e k_{pt(e)} entries, k_p =
#      clusters seeing point p, small under co-observation clustering)
#      so the device side is a plain gather → block product → segment
#      scatter into the [cd·cd, Nc·C] coarse-coupling table.  G is what
#      makes the MULTIPLICATIVE two-level cycle collective-free inside
#      the PCG body: the cycle's S applications only ever hit vectors
#      in range(Rᵀ), which G materialises once per build.
#
# Everything downstream is selects/gathers/scatter-adds over these
# static index arrays.  Sharding story: `pc_slot` and the ec arrays
# follow the edge shards; V and G are each psum-combined once per build
# (OUTSIDE the PCG body — the all-reduce kind the solver already
# emits), and everything after is identical tiny replicated work per
# shard.  The per-apply cycle adds no collectives at all.


@dataclasses.dataclass(frozen=True)
class ClusterPlan:
    """Host half of the camera-cluster coarse-space plan.

    The ec arrays are laid out in `world_size` equal-length contiguous
    shard groups (each padded to the common max with inert entries —
    local edge 0, slot 0, out-of-range segment), and `ec_edge` holds
    SHARD-LOCAL edge indices, so a `P(EDGE_AXIS)` split hands every
    shard exactly the pairs of its own edges.
    """

    num_cameras: int
    num_clusters: int  # actual cluster count C (>= the target)
    n_pc: int  # distinct (point, cluster) incidences
    n_ec: int  # real (unpadded) edge-incidence pairs
    world_size: int
    cluster: np.ndarray  # [Nc] int32 cluster id per camera
    pc_slot: np.ndarray  # [nE] int32 incidence per edge (n_pc = inert)
    pc_pt: np.ndarray  # [n_pc] int32 point of each incidence
    ec_edge: np.ndarray  # [ws*L] int32 shard-LOCAL edge per pair
    ec_slot: np.ndarray  # [ws*L] int32 pc-slot per pair
    ec_seg: np.ndarray  # [ws*L] int32 cam*C+cluster (Nc*C on padding)


@dataclasses.dataclass(frozen=True)
class DeviceClusterPlan:
    """Device half: static ints + index arrays, registered as a pytree
    so it rides jit/shard_map operands like DualPlans does."""

    num_clusters: int
    n_pc: int
    cluster: jax.Array  # [Nc] int32
    pc_slot: jax.Array  # [nE] int32 (edge axis; shard-local when sharded)
    pc_pt: jax.Array  # [n_pc] int32
    ec_edge: jax.Array  # [ws*L] int32 (edge-sharded; local edge ids)
    ec_slot: jax.Array  # [ws*L] int32 (edge-sharded)
    ec_seg: jax.Array  # [ws*L] int32 (edge-sharded)


jax.tree_util.register_dataclass(
    DeviceClusterPlan,
    data_fields=["cluster", "pc_slot", "pc_pt", "ec_edge", "ec_slot",
                 "ec_seg"],
    meta_fields=["num_clusters", "n_pc"],
)


def device_cluster_plan(plan: ClusterPlan) -> DeviceClusterPlan:
    return DeviceClusterPlan(
        num_clusters=plan.num_clusters,
        n_pc=plan.n_pc,
        cluster=jnp.asarray(plan.cluster),
        pc_slot=jnp.asarray(plan.pc_slot),
        pc_pt=jnp.asarray(plan.pc_pt),
        ec_edge=jnp.asarray(plan.ec_edge),
        ec_slot=jnp.asarray(plan.ec_slot),
        ec_seg=jnp.asarray(plan.ec_seg),
    )


def cluster_partition_specs(cplan: DeviceClusterPlan, edge_spec=None):
    """shard_map in_specs tree for a DeviceClusterPlan operand: the
    per-edge `pc_slot` stream and the per-pair ec arrays follow the
    edge shards (the plan builder laid the pairs out in equal-length
    shard groups with shard-local edge ids); the cluster table and
    incidence tables ride replicated (the coarse assembly after the V/G
    psums is identical tiny work per shard).  `edge_spec` overrides the
    edge-following spec — the 2-D mesh passes
    P((EDGE_AXIS, CAM_AXIS)), whose device-block order the plan's
    world_size shard groups must match (parallel/mesh.py lays both out
    edge-major, camera-minor)."""
    from jax.sharding import PartitionSpec as P

    from megba_tpu.parallel.mesh import EDGE_AXIS

    if edge_spec is None:
        edge_spec = P(EDGE_AXIS)
    return DeviceClusterPlan(
        num_clusters=cplan.num_clusters, n_pc=cplan.n_pc,
        cluster=P(), pc_slot=edge_spec, pc_pt=P(),
        ec_edge=edge_spec, ec_slot=edge_spec, ec_seg=edge_spec)


def build_camera_clusters(
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    num_cameras: int,
    target: int = 0,
    mask: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Greedy co-observation-weighted aggregation into ~target clusters.

    target = 0 selects the two-level default, ceil(sqrt(Nc)).  Camera
    pairs are weighted by how many points they co-observe (counted over
    consecutive cameras in each point's sorted camera list — Σ(deg_p−1)
    pairs total, so the host cost stays O(nE log nE) at any scale) and
    merged heaviest-first under a size cap of ceil(Nc / target) via
    union-find.  Returns [Nc] int32 cluster ids in [0, C); C >= target
    whenever the cap binds, and every camera (including edge-less ones)
    gets a cluster.
    """
    cam_idx = np.asarray(cam_idx, np.int64)
    pt_idx = np.asarray(pt_idx, np.int64)
    if mask is not None:
        keep = np.asarray(mask) > 0
        cam_idx, pt_idx = cam_idx[keep], pt_idx[keep]
    if target <= 0:
        target = max(1, int(np.ceil(np.sqrt(num_cameras))))
    target = min(target, num_cameras)
    cap = max(1, -(-num_cameras // target))

    parent = np.arange(num_cameras, dtype=np.int64)
    size = np.ones(num_cameras, np.int64)

    def find(i):
        root = i
        while parent[root] != root:
            root = parent[root]
        while parent[i] != root:  # path compression
            parent[i], i = root, parent[i]
        return root

    if cam_idx.size and cap > 1:
        order = np.argsort(pt_idx, kind="stable")
        ps, cs = pt_idx[order], cam_idx[order]
        adj = ps[1:] == ps[:-1]
        a, b = cs[:-1][adj], cs[1:][adj]
        neq = a != b
        a, b = a[neq], b[neq]
        lo, hi = np.minimum(a, b), np.maximum(a, b)
        pairs, counts = np.unique(lo * num_cameras + hi, return_counts=True)
        for key in pairs[np.argsort(-counts, kind="stable")]:
            ra, rb = find(key // num_cameras), find(key % num_cameras)
            if ra != rb and size[ra] + size[rb] <= cap:
                parent[rb] = ra
                size[ra] += size[rb]

    roots = np.asarray([find(i) for i in range(num_cameras)])
    _, cluster = np.unique(roots, return_inverse=True)
    return cluster.astype(np.int32)


def build_cluster_plan(
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    num_cameras: int,
    num_points: int,
    target: int = 0,
    mask: Optional[np.ndarray] = None,
    world_size: int = 1,
) -> ClusterPlan:
    """Plan the two-level coarse space over one (possibly padded) edge
    stream.  `cam_idx`/`pt_idx` are in the SOLVER's final edge order
    (post-sort/-plan, padding included, `world_size` equal contiguous
    shards when sharded); `mask` marks real edges — padding edges get
    the inert pc-slot n_pc, so the device scatter drops them (their
    data rows are zero anyway)."""
    cam_idx = np.asarray(cam_idx, np.int64)
    pt_idx = np.asarray(pt_idx, np.int64)
    n_edges = int(cam_idx.shape[0])
    cluster = build_camera_clusters(
        cam_idx, pt_idx, num_cameras, target, mask)
    C = int(cluster.max()) + 1 if num_cameras else 1

    real = (np.ones(n_edges, bool) if mask is None
            else np.asarray(mask) > 0)
    key = pt_idx * C + cluster[cam_idx]  # (point, cluster) incidence id
    uniq, inv = np.unique(key[real], return_inverse=True)
    n_pc = int(uniq.shape[0])
    pc_slot = np.full(n_edges, n_pc, np.int32)
    pc_slot[real] = inv.astype(np.int32)
    pc_pt = (uniq // C).astype(np.int32)
    pc_cluster = (uniq % C).astype(np.int32)

    # ec-pairs: for every real edge e, one entry per pc-slot of pt(e)
    # (the incidences of one point are contiguous in the sorted uniq
    # keys).  Σ_e k_{pt(e)} entries, k_p = number of distinct clusters
    # seeing point p — a small multiple of nE under co-observation
    # clustering.
    pts, pstarts, pcounts = np.unique(pc_pt, return_index=True,
                                      return_counts=True)
    start_of_pt = np.zeros(max(num_points, 1), np.int64)
    count_of_pt = np.zeros(max(num_points, 1), np.int64)
    start_of_pt[pts] = pstarts
    count_of_pt[pts] = pcounts
    edge_ids = np.nonzero(real)[0]
    k_of_edge = count_of_pt[pt_idx[edge_ids]]
    n_ec = int(k_of_edge.sum())
    ec_edge_g = np.repeat(edge_ids, k_of_edge)
    off = np.arange(n_ec, dtype=np.int64) - np.repeat(
        np.cumsum(k_of_edge) - k_of_edge, k_of_edge)
    ec_slot = (start_of_pt[pt_idx[ec_edge_g]] + off).astype(np.int32)
    ec_seg = (cam_idx[ec_edge_g] * C
              + pc_cluster[ec_slot]).astype(np.int32)

    # Shard-group the pairs: each pair belongs to its edge's shard
    # (equal contiguous edge shards), shard groups are padded to the
    # common max with inert entries and edge ids are made SHARD-LOCAL,
    # so a P(EDGE_AXIS) split of the ec arrays is self-consistent.
    ws = max(1, int(world_size))
    if n_edges % ws:
        # The documented precondition, made LOUD: a ragged edge stream
        # would silently assign the tail edges to a shard the grouping
        # loop never collects, dropping their coupling terms from G.
        # flat_solve always pads to ws*EDGE_QUANTUM before planning;
        # direct callers must do the same.
        raise ValueError(
            f"cluster plan needs world_size ({ws}) equal contiguous "
            f"edge shards, got {n_edges} edges (not divisible); pad "
            "the edge stream first (core.types.pad_edges)")
    shard_edges = n_edges // ws
    shard_of = ec_edge_g // max(shard_edges, 1)
    groups = []
    for k in range(ws):
        sel = shard_of == k
        groups.append((ec_edge_g[sel] - k * shard_edges,
                       ec_slot[sel], ec_seg[sel]))
    L = max(1, max(g[0].shape[0] for g in groups))
    ee, es, eg = [], [], []
    for local_e, slot, seg in groups:
        pad = L - local_e.shape[0]
        ee.append(np.concatenate(
            [local_e, np.zeros(pad, np.int64)]).astype(np.int32))
        es.append(np.concatenate([slot, np.zeros(pad, np.int32)]))
        # Out-of-range segment: the scatter (mode="drop") ignores it.
        eg.append(np.concatenate(
            [seg, np.full(pad, num_cameras * C, np.int32)]))
    return ClusterPlan(
        num_cameras=num_cameras, num_clusters=C, n_pc=max(n_pc, 1),
        n_ec=n_ec, world_size=ws, cluster=cluster, pc_slot=pc_slot,
        pc_pt=(pc_pt if n_pc else np.zeros(1, np.int32)),
        ec_edge=np.concatenate(ee), ec_slot=np.concatenate(es),
        ec_seg=np.concatenate(eg))


def cached_cluster_plan(
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    num_cameras: int,
    num_points: int,
    target: int = 0,
    mask: Optional[np.ndarray] = None,
    world_size: int = 1,
    smooth_omega: float = 0.0,
):
    """`build_cluster_plan` behind the host plan cache.

    Returns ((ClusterPlan, DeviceClusterPlan), cache_hit) — keyed by a
    blake2b content fingerprint of the index arrays + mask + EVERY
    aggregation parameter (target, world_size, smoothing omega),
    exactly like the tile plans, so repeated solves of one problem
    (bench reruns, chunked drivers, the auditor's canonical lowerings)
    build the cluster graph once.  `smooth_omega` does not change the
    plan CONTENT today (smoothing is a device-side build step over the
    planned indices), but it is part of the key by contract: a
    SolverOption knob flip must never be able to serve a stale plan
    from the LRU, including under future plans that do consume it."""
    key = ("cluster", _array_digest(np.asarray(cam_idx)),
           _array_digest(np.asarray(pt_idx)),
           (None if mask is None
            else _array_digest(np.asarray(mask) > 0)),
           int(num_cameras), int(num_points), int(target),
           int(world_size), float(smooth_omega))
    hit = _plan_cache_get(key)
    if hit is not None:
        return hit, True
    plan = build_cluster_plan(cam_idx, pt_idx, num_cameras, num_points,
                              target, mask, world_size=world_size)
    value = (plan, device_cluster_plan(plan))
    _plan_cache_put(key, value)
    return value, False


# ---------------------------------------------------------------------------
# Recursive camera-graph hierarchy (MULTILEVEL Schur preconditioner)
# ---------------------------------------------------------------------------
#
# The L-level preconditioner (solver/precond.py) re-aggregates the
# level-1 cluster graph recursively: level l+1's "cameras" are level
# l's clusters, and the co-observation weights between them are exactly
# the camera co-observation weights with cameras relabelled by their
# cluster — so every level reuses build_camera_clusters over the SAME
# edge stream with relabelled camera ids.  All of it is host graph
# work, planned once and cached; on device the extra levels are just
# tiny replicated [C_l] assignment gathers (dense Galerkin contractions
# in solver/precond.py), so the hierarchy adds no per-edge state and no
# collectives anywhere.


@dataclasses.dataclass(frozen=True)
class MultiLevelPlan:
    """Host half of the recursive camera-cluster hierarchy.

    `base` is the level-1 plan (cameras -> C_1 clusters, with the
    pc/ec streams the device Galerkin build consumes);
    `level_sizes[i]` is the cluster count of coarse level i+1
    (level_sizes[0] == base.num_clusters), and `assign[i]` maps level
    i+1's blocks onto level i+2's clusters ([level_sizes[i]] int32).
    Total hierarchy depth = 1 (fine) + len(level_sizes)."""

    base: ClusterPlan
    level_sizes: Tuple[int, ...]
    assign: Tuple[np.ndarray, ...]


@dataclasses.dataclass(frozen=True)
class DeviceMultiLevelPlan:
    """Device half: the level-1 DeviceClusterPlan + per-level
    assignment arrays, registered as a pytree so the whole hierarchy
    rides jit/shard_map as ONE operand (like DualPlans)."""

    base: DeviceClusterPlan
    level_sizes: Tuple[int, ...]
    assign: Tuple[jax.Array, ...]


jax.tree_util.register_dataclass(
    DeviceMultiLevelPlan,
    data_fields=["base", "assign"],
    meta_fields=["level_sizes"],
)


def device_multilevel_plan(plan: MultiLevelPlan) -> DeviceMultiLevelPlan:
    return DeviceMultiLevelPlan(
        base=device_cluster_plan(plan.base),
        level_sizes=plan.level_sizes,
        assign=tuple(jnp.asarray(a) for a in plan.assign),
    )


def multilevel_partition_specs(mplan: DeviceMultiLevelPlan, edge_spec=None):
    """shard_map in_specs tree for a DeviceMultiLevelPlan operand: the
    level-1 plan follows `cluster_partition_specs`; the coarse
    assignment tables ride replicated (every level >= 2 is identical
    tiny dense work per shard)."""
    from jax.sharding import PartitionSpec as P

    return DeviceMultiLevelPlan(
        base=cluster_partition_specs(mplan.base, edge_spec=edge_spec),
        level_sizes=mplan.level_sizes,
        assign=tuple(P() for _ in mplan.assign),
    )


def coarse_plan_partition_specs(plan, edge_spec=None):
    """Partition specs for either coarse-space plan operand kind."""
    if isinstance(plan, DeviceMultiLevelPlan):
        return multilevel_partition_specs(plan, edge_spec=edge_spec)
    return cluster_partition_specs(plan, edge_spec=edge_spec)


def build_multilevel_plan(
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    num_cameras: int,
    num_points: int,
    target: int = 0,
    mask: Optional[np.ndarray] = None,
    world_size: int = 1,
    coarsen_factor: float = 4.0,
    max_levels: int = 3,
) -> MultiLevelPlan:
    """Plan the recursive hierarchy over one (padded) edge stream.

    Level 1 is `build_cluster_plan` (same contract); each further level
    aggregates the previous level's cluster graph toward
    `ceil(C / coarsen_factor)` clusters, stopping at `max_levels` total
    levels (fine included), when the graph stops shrinking, or when the
    coarsest space is already trivial (<= 2 blocks — a dense solve of 2
    blocks is cheaper than another level's bookkeeping)."""
    if not coarsen_factor > 1.0:
        raise ValueError(
            f"coarsen_factor must be > 1, got {coarsen_factor}")
    if max_levels < 2:
        raise ValueError(f"max_levels must be >= 2, got {max_levels}")
    base = build_cluster_plan(cam_idx, pt_idx, num_cameras, num_points,
                              target, mask, world_size=world_size)
    sizes = [base.num_clusters]
    assign: list = []
    edge_cl = base.cluster[np.asarray(cam_idx, np.int64)]
    while len(sizes) + 1 < max_levels and sizes[-1] > 2:
        cur = sizes[-1]
        tgt = max(1, int(np.ceil(cur / coarsen_factor)))
        if tgt >= cur:
            break
        nxt = build_camera_clusters(edge_cl, pt_idx, cur, tgt, mask)
        C = int(nxt.max()) + 1
        if C >= cur:
            break  # aggregation found nothing to merge
        assign.append(nxt.astype(np.int32))
        sizes.append(C)
        edge_cl = nxt[edge_cl]
    return MultiLevelPlan(base=base, level_sizes=tuple(sizes),
                          assign=tuple(assign))


def cached_multilevel_plan(
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    num_cameras: int,
    num_points: int,
    target: int = 0,
    mask: Optional[np.ndarray] = None,
    world_size: int = 1,
    coarsen_factor: float = 4.0,
    max_levels: int = 3,
    smooth_omega: float = 0.0,
):
    """`build_multilevel_plan` behind the host plan cache.

    Returns ((MultiLevelPlan, DeviceMultiLevelPlan), cache_hit).  The
    fingerprint includes EVERY aggregation parameter — target,
    world_size, coarsen_factor, max_levels AND the smoothing omega —
    so flipping any SolverOption preconditioner knob can never serve a
    stale hierarchy from the LRU (the coarse level count and cluster
    shapes are baked into the compiled program's operand shapes)."""
    key = ("multilevel", _array_digest(np.asarray(cam_idx)),
           _array_digest(np.asarray(pt_idx)),
           (None if mask is None
            else _array_digest(np.asarray(mask) > 0)),
           int(num_cameras), int(num_points), int(target),
           int(world_size), float(coarsen_factor), int(max_levels),
           float(smooth_omega))
    hit = _plan_cache_get(key)
    if hit is not None:
        return hit, True
    plan = build_multilevel_plan(
        cam_idx, pt_idx, num_cameras, num_points, target, mask,
        world_size=world_size, coarsen_factor=coarsen_factor,
        max_levels=max_levels)
    value = (plan, device_multilevel_plan(plan))
    _plan_cache_put(key, value)
    return value, False


# ---------------------------------------------------------------------------
# 2-D camera-tile plan (camera x edge mesh distribution)
# ---------------------------------------------------------------------------
#
# The 2-D mesh lowering (parallel/mesh.make_mesh_2d + SolverOption.
# mesh_2d) factors the world into edge_shards x cam_blocks and tiles the
# camera range into cam_blocks contiguous blocks.  This plan is the host
# half: it assigns every edge to the camera COLUMN owning its camera's
# tile, orders each column's edges co-observation-first (PI-BA, arXiv
# 1905.02373: camera-major, point-minor — each fetched point shard is
# fully consumed before the stream moves to the next), pads columns to a
# common quantum-aligned length, and lays the device blocks out
# edge-major/camera-minor — exactly the block order a
# P(None, (EDGE_AXIS, CAM_AXIS)) shard_map split produces.
#
# The device half additionally carries, per device, the point-SHARD
# buckets of its local edges (slot/point-local/mask triples padded to a
# common width): the double-buffered matvec tile loop
# (solver/pcg.make_matvec_2d) contracts bucket s while the collective
# fetching shard s+1 is already in flight, so the ICI transfer of the
# next tile overlaps the MXU contraction of the current one.


def coobservation_edge_order(cam_idx: np.ndarray,
                             pt_idx: np.ndarray) -> np.ndarray:
    """PI-BA co-observation-first edge permutation (camera-major,
    point-minor, stable).

    Edges sharing a camera become contiguous and, within one camera,
    edges touching nearby points cluster — the ordering that maximises
    tile reuse before any transfer (arXiv 1905.02373).  Pure host
    argsort; applying it only reorders summation (results agree at
    solver tolerance, never bitwise).
    """
    return np.lexsort((np.asarray(pt_idx), np.asarray(cam_idx)))


def edge_stream_reuse(cam_idx: np.ndarray,
                      pt_idx: np.ndarray,
                      cam_tile: int,
                      pt_tile: int,
                      mask: Optional[np.ndarray] = None) -> dict:
    """Streaming tile-reuse statistics of one edge order.

    Model: a consumer walks the edge stream holding ONE (camera-tile,
    point-tile) pair resident; every time a consecutive edge needs a
    different pair it pays a tile transfer.  `switches` counts those
    transitions (the first edge's fetch included), `reuse_factor` is
    edges consumed per fetched pair — the quantity the co-observation
    ordering (EdgeOrder.COOBS) strictly improves on locality-structured
    scenes, and the honest denominator of the 2-D plan's "each gathered
    tile fully consumed" claim.
    """
    cam_idx = np.asarray(cam_idx, np.int64)
    pt_idx = np.asarray(pt_idx, np.int64)
    if mask is not None:
        keep = np.asarray(mask) > 0
        cam_idx, pt_idx = cam_idx[keep], pt_idx[keep]
    n = int(cam_idx.shape[0])
    if n == 0:
        return {"edges": 0, "switches": 0, "reuse_factor": 0.0}
    key = (cam_idx // max(1, int(cam_tile)),
           pt_idx // max(1, int(pt_tile)))
    changed = (key[0][1:] != key[0][:-1]) | (key[1][1:] != key[1][:-1])
    switches = int(changed.sum()) + 1  # first fetch counts
    return {"edges": n, "switches": switches,
            "reuse_factor": float(n) / float(switches)}


@dataclasses.dataclass(frozen=True)
class CameraTilePlan:
    """Host half of the 2-D camera x edge distribution plan.

    The padded edge stream (length `n_edges_padded` =
    cam_blocks * column_len) is addressed THROUGH `perm`/`mask`:
    position i of the stream carries caller edge `perm[i]` when
    `mask[i] > 0` and inert padding otherwise.  Device block b of a
    P((EDGE_AXIS, CAM_AXIS)) split (b = edge_shard * cam_blocks +
    cam_block) is the contiguous slice [b*chunk, (b+1)*chunk) — the
    plan lays columns out so that block b holds edge-shard
    b // cam_blocks of camera column b % cam_blocks.
    """

    num_cameras: int
    num_points: int
    edge_shards: int  # E
    cam_blocks: int  # C
    tile_cams: int  # Tc: cameras per tile (C * Tc >= Nc)
    shard_points: int  # Sp: points per shard (C * Sp >= Np)
    n_edges_real: int
    n_edges_padded: int
    bucket_width: int  # Lb
    perm: np.ndarray  # [nE_pad] int64 caller edge per stream slot
    mask: np.ndarray  # [nE_pad] float64 1=real 0=padding
    cam_idx: np.ndarray  # [nE_pad] int32 GLOBAL camera per slot
    pt_idx: np.ndarray  # [nE_pad] int32 GLOBAL point per slot
    cam_local: np.ndarray  # [nE_pad] int32 tile-LOCAL camera per slot
    bucket_slot: np.ndarray  # [E*C*C, Lb] int32 device-local edge slot
    bucket_ptl: np.ndarray  # [E*C*C, Lb] int32 shard-LOCAL point
    bucket_mask: np.ndarray  # [E*C*C, Lb] int32 1=real pair
    # Streaming-reuse statistics of the final stream (bench evidence).
    reuse: dict


@dataclasses.dataclass(frozen=True)
class DeviceCameraTilePlan:
    """Device half: static tile geometry + index streams, registered as
    a pytree so the whole plan rides jit/shard_map as ONE operand."""

    cam_blocks: int
    tile_cams: int
    shard_points: int
    cam_local: jax.Array  # [nE] int32 (edge axis; device-local slice)
    bucket_slot: jax.Array  # [C, Lb] int32 per device after the split
    bucket_ptl: jax.Array  # [C, Lb] int32
    bucket_mask: jax.Array  # [C, Lb] int32


jax.tree_util.register_dataclass(
    DeviceCameraTilePlan,
    data_fields=["cam_local", "bucket_slot", "bucket_ptl", "bucket_mask"],
    meta_fields=["cam_blocks", "tile_cams", "shard_points"],
)


def device_camera_tile_plan(plan: CameraTilePlan) -> DeviceCameraTilePlan:
    return DeviceCameraTilePlan(
        cam_blocks=plan.cam_blocks,
        tile_cams=plan.tile_cams,
        shard_points=plan.shard_points,
        cam_local=jnp.asarray(plan.cam_local),
        bucket_slot=jnp.asarray(plan.bucket_slot),
        bucket_ptl=jnp.asarray(plan.bucket_ptl),
        bucket_mask=jnp.asarray(plan.bucket_mask),
    )


def tile_plan_partition_specs(tplan: DeviceCameraTilePlan, edge_spec):
    """shard_map in_specs tree for a DeviceCameraTilePlan operand: the
    per-edge cam_local stream follows the 2-D edge split, and the
    per-device bucket tables split the same way on their leading axis
    (the builder stacked them in device-block order, cam_blocks rows
    per device)."""
    return DeviceCameraTilePlan(
        cam_blocks=tplan.cam_blocks, tile_cams=tplan.tile_cams,
        shard_points=tplan.shard_points, cam_local=edge_spec,
        bucket_slot=edge_spec, bucket_ptl=edge_spec,
        bucket_mask=edge_spec)


def build_camera_tile_plan(
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    num_cameras: int,
    num_points: int,
    edge_shards: int,
    cam_blocks: int,
    quantum: int = 0,
) -> CameraTilePlan:
    """Plan the 2-D camera x edge distribution over a caller edge set.

    Edges land in the camera column owning their camera's tile
    (contiguous tiles of `tile_cams = ceil(Nc / cam_blocks)` cameras),
    ordered co-observation-first within each column, and every column
    is padded to one common length — a multiple of
    `edge_shards * quantum` (quantum defaults to core.fm.EDGE_QUANTUM,
    matching the 1-D shard padding) — so each of the E*C device chunks
    is equal-size and the chunked Schur build's slices stay
    static-shape.  Padding slots repeat the column's LAST real camera
    (keeping every per-device stream camera-sorted for the
    indices_are_sorted scatter promise) and point 0, under mask 0.
    """
    from megba_tpu.core.fm import EDGE_QUANTUM

    if quantum <= 0:
        quantum = EDGE_QUANTUM
    E = int(edge_shards)
    C = int(cam_blocks)
    if E < 1 or C < 1:
        raise ValueError(
            f"edge_shards and cam_blocks must be >= 1, got {E} x {C}")
    cam_idx = np.asarray(cam_idx, np.int64)
    pt_idx = np.asarray(pt_idx, np.int64)
    n_real = int(cam_idx.shape[0])
    Tc = max(1, -(-int(num_cameras) // C))
    Sp = max(1, -(-int(num_points) // C))
    col = np.minimum(cam_idx // Tc, C - 1)

    # Column streams: co-observation order (camera-major, point-minor)
    # inside each column, padded to the common quantum-aligned length.
    col_ids = []
    for c in range(C):
        ids = np.nonzero(col == c)[0]
        ids = ids[coobservation_edge_order(cam_idx[ids], pt_idx[ids])]
        col_ids.append(ids)
    Lc = max(1, max(ids.shape[0] for ids in col_ids))
    Lc = -(-Lc // (E * quantum)) * (E * quantum)
    chunk = Lc // E

    perm = np.zeros(C * Lc, np.int64)
    mask = np.zeros(C * Lc, np.float64)
    cam_s = np.zeros(C * Lc, np.int32)
    pt_s = np.zeros(C * Lc, np.int32)
    cam_l = np.zeros(C * Lc, np.int32)
    pos = 0
    # Device-block order: edge-shard-major, camera-minor (the order a
    # P((EDGE_AXIS, CAM_AXIS)) split hands to device (e, c) = block
    # e*C + c).
    for e in range(E):
        for c in range(C):
            ids = col_ids[c]
            seg = ids[e * chunk:(e + 1) * chunk]
            n = seg.shape[0]
            sl = slice(pos, pos + chunk)
            perm[sl][:n] = seg
            mask[pos:pos + n] = 1.0
            # Padding cameras: the column's last REAL camera (stream
            # stays sorted, index stays inside the tile); a column with
            # no real edges anchors to its tile's first in-range camera.
            if ids.shape[0]:
                pad_cam = int(cam_idx[ids[-1]])
            else:
                pad_cam = min(c * Tc, max(0, int(num_cameras) - 1))
            cams = np.full(chunk, pad_cam, np.int32)
            cams[:n] = cam_idx[seg]
            pts = np.zeros(chunk, np.int32)
            pts[:n] = pt_idx[seg]
            cam_s[sl] = cams
            pt_s[sl] = pts
            cam_l[sl] = np.clip(cams - c * Tc, 0, Tc - 1)
            pos += chunk

    # Per-device point-shard buckets over the REAL local edges.
    n_dev = E * C
    rows = []
    for d in range(n_dev):
        sl = slice(d * chunk, (d + 1) * chunk)
        ptd, md = pt_s[sl], mask[sl]
        rows.append([
            np.nonzero((ptd // Sp == s) & (md > 0))[0] for s in range(C)
        ])
    Lb = max(1, max(max((r.shape[0] for r in dev), default=0)
                    for dev in rows))
    b_slot = np.zeros((n_dev * C, Lb), np.int32)
    b_ptl = np.zeros((n_dev * C, Lb), np.int32)
    b_mask = np.zeros((n_dev * C, Lb), np.int32)
    for d, dev in enumerate(rows):
        for s, sel in enumerate(dev):
            n = sel.shape[0]
            b_slot[d * C + s, :n] = sel
            b_ptl[d * C + s, :n] = pt_s[d * chunk + sel] - s * Sp
            b_mask[d * C + s, :n] = 1

    # Per-DEVICE streaming stats: every device walks its own chunk and
    # pays its own first fetch, so the metric is aggregated over the
    # E*C independent block walks — one concatenated walk would charge
    # a phantom switch at every device-block boundary.
    edges_t = switches_t = 0
    for d in range(n_dev):
        sl = slice(d * chunk, (d + 1) * chunk)
        r = edge_stream_reuse(cam_s[sl], pt_s[sl], Tc, Sp, mask=mask[sl])
        edges_t += r["edges"]
        switches_t += r["switches"]
    reuse = {"edges": edges_t, "switches": switches_t,
             "reuse_factor": float(edges_t) / float(max(switches_t, 1))}
    return CameraTilePlan(
        num_cameras=int(num_cameras), num_points=int(num_points),
        edge_shards=E, cam_blocks=C, tile_cams=Tc, shard_points=Sp,
        n_edges_real=n_real, n_edges_padded=C * Lc, bucket_width=Lb,
        perm=perm, mask=mask, cam_idx=cam_s, pt_idx=pt_s,
        cam_local=cam_l, bucket_slot=b_slot, bucket_ptl=b_ptl,
        bucket_mask=b_mask, reuse=reuse)


def cached_camera_tile_plan(
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    num_cameras: int,
    num_points: int,
    edge_shards: int,
    cam_blocks: int,
    quantum: int = 0,
):
    """`build_camera_tile_plan` behind the host plan cache.

    Returns ((CameraTilePlan, DeviceCameraTilePlan), cache_hit), keyed
    by a blake2b content fingerprint of the index arrays plus EVERY
    geometry knob, exactly like the tile/cluster plans."""
    key = ("mesh2d", _array_digest(np.asarray(cam_idx)),
           _array_digest(np.asarray(pt_idx)),
           int(num_cameras), int(num_points), int(edge_shards),
           int(cam_blocks), int(quantum))
    hit = _plan_cache_get(key)
    if hit is not None:
        return hit, True
    plan = build_camera_tile_plan(
        cam_idx, pt_idx, num_cameras, num_points, edge_shards,
        cam_blocks, quantum=quantum)
    value = (plan, device_camera_tile_plan(plan))
    _plan_cache_put(key, value)
    return value, False


@functools.lru_cache(maxsize=1)
def probe_kernels() -> bool:
    """True iff ALL five Pallas kernels compile AND match on this backend.

    Guards production entry points (bench, CLIs) against an unexpected
    Mosaic lowering failure: degrade to the XLA fallback path instead of
    dying.  Off-TPU returns False without compiling anything (interpret
    mode is correct but far slower than the fallback).

    Probes every kernel the tiled solve ships — tile_reduce, tile_expand,
    jtj_grad_reduce, coupling_expand, coupling_reduce — at BOTH
    production plan geometries: the camera side (DEFAULT_TILE_CAM /
    DEFAULT_BLOCK_CAM, d=9, od=2 — 18- and 90-row blocks) and the point
    side (DEFAULT_TILE_PT / DEFAULT_BLOCK_PT, d=3 — 6- and 12-row
    blocks).  None of these row counts are sublane multiples, and Mosaic
    rejections are shape-dependent, so toy shapes would not certify the
    shapes the solve actually compiles.  Each result is checked against
    the XLA fallback so a kernel that compiles but miscomputes also
    fails the probe.
    """
    if jax.default_backend() != "tpu":
        return False
    try:
        rng = np.random.default_rng(0)

        def close(a, b, tol=1e-3):
            return bool(jnp.max(jnp.abs(a - b)) < tol)

        ok = True
        for tile, block, ns, d, od in (
            (DEFAULT_TILE_CAM, DEFAULT_BLOCK_CAM, 200, 9, 2),
            (DEFAULT_TILE_PT, DEFAULT_BLOCK_PT, 3000, 3, 2),
        ):
            n = 4 * tile  # several tiles; some blocks get >1 (accumulate)
            idx = rng.integers(0, ns, n).astype(np.int32)
            plan = build_tile_plan(idx, ns, tile=tile, block=block)
            dp = device_plan(plan)
            m = jnp.asarray(plan.mask)

            data = jnp.asarray(rng.standard_normal(
                (3, plan.n_slots)).astype(np.float32)) * m
            ok &= close(tile_reduce(data, dp), reduce_fallback(data, dp))
            table = jnp.asarray(
                rng.standard_normal((3, ns)).astype(np.float32))
            ok &= close(tile_expand(table, dp) * m,
                        expand_fallback(table, dp) * m)

            J = jnp.asarray(rng.standard_normal(
                (od * d, plan.n_slots)).astype(np.float32)) * m
            r = jnp.asarray(rng.standard_normal(
                (od, plan.n_slots)).astype(np.float32)) * m
            h_k, g_k = jtj_grad_reduce(J, r, dp, use_kernels=True)
            h_f, g_f = jtj_grad_reduce(J, r, dp, use_kernels=False)
            ok &= close(h_k, h_f) and close(g_k, g_f)

            vt = jnp.asarray(
                rng.standard_normal((d, ns)).astype(np.float32))
            ok &= close(
                coupling_expand(vt, J, dp, d, use_kernels=True) * m,
                coupling_expand(vt, J, dp, d, use_kernels=False) * m)
            u = jnp.asarray(rng.standard_normal(
                (od, plan.n_slots)).astype(np.float32)) * m
            ok &= close(
                coupling_reduce(J, u, dp, d, use_kernels=True),
                coupling_reduce(J, u, dp, d, use_kernels=False))
        if not ok:  # pragma: no cover - backend specific
            print("segtiles kernel probe: kernels compiled but mismatched "
                  "the fallback; using XLA fallback path",
                  file=sys.stderr, flush=True)
        return ok
    except Exception as e:  # pragma: no cover - backend specific
        print(f"segtiles kernel probe failed ({type(e).__name__}: {e}); "
              "using XLA fallback path", file=sys.stderr, flush=True)
        return False
