from megba_tpu.ops import geo
from megba_tpu.ops.residuals import (
    bal_residual,
    make_residual_jacobian_fn,
    make_residual_fn,
)

__all__ = [
    "geo",
    "bal_residual",
    "make_residual_fn",
    "make_residual_jacobian_fn",
]
