from megba_tpu.ops import geo
from megba_tpu.ops.jet import Jet, seed_jets
from megba_tpu.ops.residuals import (
    bal_residual,
    make_residual_jacobian_fn,
    make_residual_fn,
)

__all__ = [
    "Jet",
    "bal_residual",
    "geo",
    "make_residual_fn",
    "make_residual_jacobian_fn",
    "seed_jets",
]
