"""Pallas TPU kernels for the Hessian-assembly hot path.

The fusion the reference gets from its hand-written `makeHSchur` CUDA
kernel (src/edge/build_linear_system.cu:88-146 — one pass over the
Jacobians, accumulating Hpp and g in shared memory/atomics), rebuilt for
the TPU memory hierarchy: the XLA path materialises the per-edge outer
products `hpp_e [nE,9,9]` in HBM (~728 B/edge of traffic for Hpp at
float32: write + re-read + the Jacobian read); this kernel computes them
in VMEM and reduces tile-locally, so HBM sees only the Jacobian/residual
read (~80 B/edge) plus a tiny per-tile partial buffer.

Layout exploited: edges are camera-sorted (BaseProblem lowering
guarantees it), so each tile of `tile` edges touches a narrow window of
consecutive cameras.  Each grid step emits its window's partial sums
`[window, cd*cd + cd]`; a cheap XLA scatter-add combines the
`[n_tiles, window, ...]` partials (a few MB) into the final blocks.

The camera window start per tile is just `cam_idx[i*tile]` — data-
dependent, delivered via `PrefetchScalarGridSpec` scalar prefetch.
Feasibility (every tile spans < window cameras) is a static property of
the problem topology; `camera_window_plan` checks it host-side at
lowering.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_TILE = 512
DEFAULT_WINDOW = 16


def camera_window_plan(
    cam_idx: np.ndarray, tile: int = DEFAULT_TILE, max_window: int = 64
) -> Tuple[bool, int]:
    """Host-side static check: (feasible, window) for this topology.

    A tile of `tile` consecutive camera-sorted edges spans
    `cam_idx[end] - cam_idx[start] + 1` cameras; the kernel needs that
    bounded by a compile-time window.  The check slides over EVERY
    possible tile offset (not just multiples of `tile`), so the plan
    stays valid for any shard boundary when the edge axis is split by
    shard_map.  Returns the smallest power-of-two window covering the
    worst tile (min DEFAULT_WINDOW), or (False, 0) when it would exceed
    `max_window` — the kernel statically unrolls the window loop, so
    large windows mean huge programs; fall back to the XLA path instead.
    """
    n = len(cam_idx)
    if n == 0:
        return False, 0
    cam_idx = np.asarray(cam_idx)
    if np.any(np.diff(cam_idx) < 0):
        # The kernel is only valid on camera-sorted edges; a plan computed
        # on a different order than the kernel runs on silently drops
        # out-of-window contributions.
        return False, 0
    if n <= tile:
        span = int(cam_idx[-1] - cam_idx[0] + 1)
    else:
        span = int(np.max(cam_idx[tile - 1 :] - cam_idx[: n - tile + 1]) + 1)
    window = DEFAULT_WINDOW
    while window < span:
        window *= 2
    if window > max_window:
        return False, 0
    return True, window


def _hessian_cam_kernel(
    starts_ref, cam_idx_ref, jc_ref, r_ref, out_ref, *, window, cd, od
):
    """One tile: partial (Hpp, g) sums for `window` consecutive cameras.

    out_ref block: [1, window, cd*cd + cd] — H flattened then g.

    Strategy: build the per-edge feature matrix [tile, cd*cd + cd]
    (outer-product columns of J_o^T J_o summed over residual components,
    then -J^T r columns) with cheap elementwise ops, and reduce it onto
    the window axis with ONE MXU matmul `onehot^T @ feat` per tile.
    This keeps VMEM tiny (one [tile, ~90] buffer) and avoids both the
    (cd,cd)->(cd*cd,) vector reshape Mosaic cannot lower and the
    window*od unrolled small-dot pattern that overflowed scoped VMEM.
    """
    i = pl.program_id(0)
    base = starts_ref[i]
    tile = cam_idx_ref.shape[0]
    local = cam_idx_ref[:, 0] - base  # [tile] ints in [0, window) by plan

    cols = []
    for a in range(cd):  # static: cd small (BAL: 9)
        acc = None
        for o in range(od):
            jo = jc_ref[:, o * cd : (o + 1) * cd]  # [tile, cd]
            term = jo[:, a : a + 1] * jo  # [tile, cd]
            acc = term if acc is None else acc + term
        cols.append(acc)  # row a of the (cd, cd) outer-product block
    ge = None
    for o in range(od):
        jo = jc_ref[:, o * cd : (o + 1) * cd]
        term = jo * r_ref[:, o : o + 1]
        ge = term if ge is None else ge + term
    cols.append(-ge)
    feat_mat = jnp.concatenate(cols, axis=1)  # [tile, cd*cd + cd]

    onehot = (
        local[:, None]
        == jax.lax.broadcasted_iota(jnp.int32, (tile, window), 1)
    ).astype(feat_mat.dtype)
    out_ref[0, :, :] = jax.lax.dot_general(
        onehot, feat_mat, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    ).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("num_cameras", "tile", "window", "interpret"),
)
def camera_hessian_gradient(
    Jc: jax.Array,
    r: jax.Array,
    cam_idx: jax.Array,
    num_cameras: int,
    tile: int = DEFAULT_TILE,
    window: int = DEFAULT_WINDOW,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused camera-side Hessian diagonal + gradient.

    Jc: [nE, od, cd] weighted camera Jacobians (camera-sorted edges),
    r: [nE, od] weighted residuals, cam_idx: [nE] int32 nondecreasing.
    Returns (Hpp [num_cameras, cd, cd], g_cam [num_cameras, cd]) equal to
    the segment_sum path up to float addition order.
    """
    nE, od, cd = Jc.shape
    dtype = Jc.dtype

    # Pad edge axis to a tile multiple with inert rows (zero J/r; camera
    # index repeats the last edge so tiles stay sorted).
    n_pad = (-nE) % tile
    if n_pad:
        Jc = jnp.concatenate([Jc, jnp.zeros((n_pad, od, cd), dtype)])
        r = jnp.concatenate([r, jnp.zeros((n_pad, od), dtype)])
        cam_idx = jnp.concatenate([cam_idx, jnp.broadcast_to(cam_idx[-1], (n_pad,))])
    n_tiles = Jc.shape[0] // tile

    jc_flat = Jc.reshape(Jc.shape[0], od * cd)
    starts = cam_idx[:: tile].astype(jnp.int32)  # [n_tiles]
    feat = cd * cd + cd

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile, 1), lambda i, s: (i, 0)),
            pl.BlockSpec((tile, od * cd), lambda i, s: (i, 0)),
            pl.BlockSpec((tile, od), lambda i, s: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, window, feat), lambda i, s: (i, 0, 0)),
    )

    partials = pl.pallas_call(
        functools.partial(
            _hessian_cam_kernel, window=window, cd=cd, od=od),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tiles, window, feat), dtype),
        interpret=interpret,
    )(starts, cam_idx[:, None].astype(jnp.int32), jc_flat, r)

    # Combine: scatter-add each tile's window into the (padded) camera
    # axis.  The [n_tiles, window, feat] partials are tiny next to the
    # per-edge outer products the XLA path would materialise.
    cam_targets = starts[:, None] + jnp.arange(window)[None, :]  # [n_tiles, window]
    out = jnp.zeros((num_cameras + window, feat), dtype)
    out = out.at[cam_targets.reshape(-1)].add(partials.reshape(-1, feat))
    out = out[:num_cameras]
    Hpp = out[:, : cd * cd].reshape(num_cameras, cd, cd)
    g = out[:, cd * cd :]
    return Hpp, g
