"""Pallas TPU kernels for the Hessian-assembly hot path (feature-major).

The fusion the reference gets from its hand-written `makeHSchur` CUDA
kernel (src/edge/build_linear_system.cu:88-146 — one pass over the
Jacobians, accumulating Hpp and g in shared memory/atomics), rebuilt for
the TPU memory hierarchy: the XLA path scatter-adds chunked outer-product
rows (builder.py) — still one extra HBM round-trip of the [90, chunk]
feature rows; this kernel computes those rows in VMEM and reduces them
tile-locally with ONE MXU matmul per tile, so HBM sees only the
Jacobian/residual read plus a tiny per-tile partial buffer.

Layout exploited: edges are camera-sorted (BaseProblem lowering
guarantees it), so each tile of `tile` edges touches a narrow window of
consecutive cameras.  Each grid step emits its window's partial sums
`[window, cd*cd + cd]`; a cheap XLA scatter-add combines the
`[n_tiles, window, ...]` partials (a few MB) into the final rows.

The camera window start per tile is just `cam_idx[i*tile]` — data-
dependent, delivered via `PrefetchScalarGridSpec` scalar prefetch.
Feasibility (every tile spans < window cameras) is a static property of
the problem topology; `camera_window_plan` checks it host-side at
lowering.

Mosaic constraints honoured (learned the hard way): no in-register
reshapes that move data across lanes (e.g. (9,9)->(81,)), everything
2-D, reductions expressed as lane-contracting `dot_general`s.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from megba_tpu.core.fm import EDGE_QUANTUM

DEFAULT_TILE = EDGE_QUANTUM  # lowering pads the edge axis to this quantum
DEFAULT_WINDOW = 16


def camera_window_plan(
    cam_idx: np.ndarray, tile: int = DEFAULT_TILE, max_window: int = 64
) -> Tuple[bool, int]:
    """Host-side static check: (feasible, window) for this topology.

    A tile of `tile` consecutive camera-sorted edges spans
    `cam_idx[end] - cam_idx[start] + 1` cameras; the kernel needs that
    bounded by a compile-time window.  The check slides over EVERY
    possible tile offset (not just multiples of `tile`), so the plan
    stays valid for any shard boundary when the edge axis is split by
    shard_map.  Returns the smallest power-of-two window covering the
    worst tile (min DEFAULT_WINDOW), or (False, 0) when it would exceed
    `max_window` — wide windows mean most one-hot matmul work is zeros;
    fall back to the XLA path instead.
    """
    n = len(cam_idx)
    if n == 0:
        return False, 0
    cam_idx = np.asarray(cam_idx)
    if np.any(np.diff(cam_idx) < 0):
        # The kernel is only valid on camera-sorted edges; a plan computed
        # on a different order than the kernel runs on silently drops
        # out-of-window contributions.
        return False, 0
    if n <= tile:
        span = int(cam_idx[-1] - cam_idx[0] + 1)
    else:
        span = int(np.max(cam_idx[tile - 1 :] - cam_idx[: n - tile + 1]) + 1)
    window = DEFAULT_WINDOW
    while window < span:
        window *= 2
    if window > max_window:
        return False, 0
    return True, window


def _hessian_cam_kernel(
    starts_ref, cam_idx_ref, jc_ref, r_ref, out_ref, *, window, cd, od
):
    """One tile: partial (Hpp rows, g rows) sums for `window` cameras.

    jc_ref block [od*cd, tile], r_ref [od, tile], cam_idx_ref [1, tile];
    out_ref block [1, window, cd*cd + cd].

    Build the per-edge feature rows (outer-product rows of J^T J summed
    over residual components, then -J^T r rows) with elementwise ops on
    (1, tile) slices, and reduce onto the window axis with ONE MXU
    matmul `onehot @ feat^T` per tile.
    """
    i = pl.program_id(0)
    base = starts_ref[i]
    tile = cam_idx_ref.shape[1]

    rows = []
    for a in range(cd):  # static: cd small (BAL: 9)
        for b in range(cd):
            acc = None
            for o in range(od):
                term = jc_ref[o * cd + a, :] * jc_ref[o * cd + b, :]
                acc = term if acc is None else acc + term
            rows.append(acc[None, :])
    for a in range(cd):
        acc = None
        for o in range(od):
            term = jc_ref[o * cd + a, :] * r_ref[o, :]
            acc = term if acc is None else acc + term
        rows.append(-acc[None, :])
    feat_mat = jnp.concatenate(rows, axis=0)  # [cd*cd + cd, tile]

    local = cam_idx_ref[:, :] - base  # [1, tile] ints in [0, window)
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, (window, tile), 0) == local
    ).astype(feat_mat.dtype)
    out_ref[0, :, :] = jax.lax.dot_general(
        onehot, feat_mat, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    ).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("num_cameras", "tile", "window", "interpret"),
)
def camera_hessian_gradient(
    Jc: jax.Array,
    r: jax.Array,
    cam_idx: jax.Array,
    num_cameras: int,
    tile: int = DEFAULT_TILE,
    window: int = DEFAULT_WINDOW,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused camera-side Hessian diagonal + gradient, feature-major.

    Jc: [od*cd, nE] weighted camera Jacobian rows (camera-sorted edges),
    r: [od, nE] weighted residual rows, cam_idx: [nE] int32 nondecreasing.
    Returns (hpp_rows [cd*cd, num_cameras], g_cam [cd, num_cameras])
    equal to the scatter-add path up to float addition order.
    """
    ocd, nE = Jc.shape
    od = r.shape[0]
    cd = ocd // od
    dtype = Jc.dtype

    # Pad edge axis to a tile multiple with inert rows (zero J/r; camera
    # index repeats the last edge so tiles stay sorted).  Lowering pads
    # to EDGE_QUANTUM already, so this is normally a no-op.
    n_pad = (-nE) % tile
    if n_pad:
        Jc = jnp.pad(Jc, ((0, 0), (0, n_pad)))
        r = jnp.pad(r, ((0, 0), (0, n_pad)))
        cam_idx = jnp.concatenate(
            [cam_idx, jnp.broadcast_to(cam_idx[-1], (n_pad,))])
    n_tiles = Jc.shape[1] // tile

    starts = cam_idx[::tile].astype(jnp.int32)  # [n_tiles]
    feat = cd * cd + cd

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, tile), lambda i, s: (0, i)),
            pl.BlockSpec((ocd, tile), lambda i, s: (0, i)),
            pl.BlockSpec((od, tile), lambda i, s: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, window, feat), lambda i, s: (i, 0, 0)),
    )

    partials = pl.pallas_call(
        functools.partial(
            _hessian_cam_kernel, window=window, cd=cd, od=od),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tiles, window, feat), dtype),
        interpret=interpret,
    )(starts, cam_idx[None, :].astype(jnp.int32), Jc, r)

    # Combine: scatter-add each tile's window into the (padded) camera
    # axis.  The [n_tiles, window, feat] partials are tiny next to the
    # per-edge rows the kernel consumed.
    cam_targets = (starts[:, None] + jnp.arange(window)[None, :]).reshape(-1)
    out = jnp.zeros((feat, num_cameras + window), dtype)
    out = out.at[:, cam_targets].add(
        jnp.swapaxes(partials.reshape(-1, feat), 0, 1))
    out = out[:, :num_cameras]
    return out[: cd * cd], out[cd * cd :]
