"""Fused edge-pipeline mega-kernels: gather -> contract -> scatter in ONE
Pallas kernel per direction (ISSUE 19 / ROADMAP raw-speed item).

The tiled Schur matvec of ops/segtiles runs the coupling product as
separate passes — expand the Krylov vector to edges, contract the W /
Jc·Jp rows, reduce onto the output segments — so every PCG iteration
streams the co-observation-ordered edge tiles through HBM up to three
times.  This module fuses all three stages: each grid step holds one
edge tile resident in VMEM, one-hot-gathers its input-block rows,
contracts the coupling rows against them, and one-hot-scatters the
result onto its output block, all before the tile leaves VMEM.  The
per-edge expanded rows NEVER touch HBM.

The price of full fusion is plan structure.  A single kernel needs BOTH
one-hots block-sized, so every edge tile must live inside one
(input_block, output_block) BUCKET — the `build_camera_tile_plan` idea
applied to the 1-D edge stream.  `build_fused_plan` sorts edges
output-block-major (Pallas accumulates into an output block only across
CONSECUTIVE grid steps), input-block-minor inside it (co-observation
locality survives the stable sort), and pads each bucket to whole
tiles.  Each matvec direction (cam->pt and pt->cam) needs its own
bucket order, so each direction carries its own edge permutation: the
coupling rows are re-permuted ONCE per PCG solve (`permute_rows`) and
then reused across every CG iteration.

Precision contract (the PR 15 Bf16Surface discipline, extended into the
kernel bodies): operand tiles may be bf16 — the one-hot gather runs as
a bf16 x bf16 MXU dot_general with `preferred_element_type=f32` (the
one-hot is exact in any dtype), per-edge products multiply in bf16 and
upcast — while EVERY accumulator is f32 (asserted at trace time inside
the kernels).  f64 operands accumulate in f64 (the CPU verification
lane).

Backend policy mirrors ops/segtiles: on TPU the kernels compile through
Mosaic (`tpu_custom_call`); on every other backend the SAME kernel
bodies run under Pallas interpret mode — that interpret-mode parity is
the CPU-lane certificate that the fused program computes the XLA
lowering's product (tests/test_fused.py pins the tolerances).
Wall-clock evidence is deferred to a TPU window; the transferable
evidence on the CPU lane is structural (the `flops_per_sp` /
`bytes_touched_per_sp` budget axes and the custom-call census in
analysis/).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Default fused-plan geometry.  Smaller blocks than the segtiles
# defaults: the kernel keeps BOTH one-hots resident ([in_block, tile] +
# [out_block, tile] f32 ≈ 1.5 MB at these sizes), alongside the coupling
# rows ([cd*pd, tile]) and the vertex table block.  Smaller blocks cost
# more buckets (more tile padding) but bound VMEM; `_fit_tile` shrinks
# the tile for toy problems exactly like the segtiles planner.
FUSED_TILE = 512
FUSED_BLOCK_CAM = 256
FUSED_BLOCK_PT = 512


def kernels_supported() -> bool:
    """True iff the fused kernels should COMPILE (Mosaic) rather than
    run under interpret mode.  Off-TPU the same kernels run interpreted
    — numerically the certificate lane, never the fast path.
    `MEGBA_FUSED_INTERPRET=1` forces interpret mode everywhere."""
    if os.environ.get("MEGBA_FUSED_INTERPRET") == "1":
        return False
    return jax.default_backend() == "tpu"


def _fit_tile(t: int, n: int) -> int:
    """Shrink tile size t so it does not dwarf an n-edge problem
    (same policy as ops/segtiles._fit_tile)."""
    while t > 128 and t >= 4 * n:
        t //= 2
    return t


# ---------------------------------------------------------------------------
# Host plan: bucket-structured edge order for one matvec direction
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FusedPlan:
    """Static edge reordering for one fused gather->contract->scatter
    direction.

    `perm[s]` is the SOURCE edge-stream slot feeding fused slot s
    (padding slots point at 0 under mask 0); `in_local`/`out_local` are
    the block-local input/output segment of each slot; tile t reads
    input block `tile_in[t]`, accumulates into output block
    `tile_out[t]`, and initialises it when `tile_first[t]` — output
    blocks are visited in contiguous runs (the Pallas sequential-
    accumulation contract).
    """

    tile: int
    in_block: int
    out_block: int
    num_in_segments: int
    num_out_segments: int
    num_in_blocks: int
    num_out_blocks: int
    n_edges: int  # real (unmasked) edges routed through the plan
    perm: np.ndarray  # [n_slots] int32 source slot per fused slot
    mask: np.ndarray  # [n_slots] float32 1=real 0=padding
    in_local: np.ndarray  # [n_slots] int32
    out_local: np.ndarray  # [n_slots] int32
    tile_in: np.ndarray  # [n_tiles] int32
    tile_out: np.ndarray  # [n_tiles] int32
    tile_first: np.ndarray  # [n_tiles] int32

    @property
    def n_slots(self) -> int:
        return int(self.perm.shape[0])

    @property
    def n_tiles(self) -> int:
        return int(self.tile_in.shape[0])

    @property
    def occupancy(self) -> float:
        """Real edges per slot — the bucket-padding overhead metric."""
        return float(self.n_edges) / float(max(1, self.n_slots))


def build_fused_plan(
    in_idx: np.ndarray,
    out_idx: np.ndarray,
    mask: Optional[np.ndarray],
    num_in: int,
    num_out: int,
    tile: int = FUSED_TILE,
    in_block: int = FUSED_BLOCK_CAM,
    out_block: int = FUSED_BLOCK_PT,
    fit: bool = True,
) -> FusedPlan:
    """Plan one direction over a (possibly already padded) edge stream.

    `in_idx`/`out_idx` are per-slot segment ids of the SOURCE stream;
    slots with `mask <= 0` (the source plan's padding) are dropped.
    Every output block gets at least one tile (all-padding tail tiles
    for edgeless blocks) so the kernel initialises the whole output.
    """
    in_idx = np.asarray(in_idx, np.int64)
    out_idx = np.asarray(out_idx, np.int64)
    real = (np.asarray(mask) > 0 if mask is not None
            else np.ones(in_idx.shape[0], bool))
    edges = np.nonzero(real)[0]
    iin, iout = in_idx[edges], out_idx[edges]
    if fit:
        tile = _fit_tile(tile, max(1, edges.shape[0]))
    num_in_blocks = max(1, -(-num_in // in_block))
    num_out_blocks = max(1, -(-num_out // out_block))

    key = (iout // out_block) * num_in_blocks + (iin // in_block)
    order = np.argsort(key, kind="stable")
    uk, counts = np.unique(key[order], return_counts=True)
    padded = ((counts + tile - 1) // tile) * tile
    offsets = np.concatenate([[0], np.cumsum(padded[:-1])]).astype(np.int64)
    starts = np.concatenate([[0], np.cumsum(counts[:-1])]).astype(np.int64)
    within = np.arange(edges.shape[0]) - np.repeat(starts, counts)
    dest = np.repeat(offsets, counts) + within

    n_slots = int(padded.sum())
    perm = np.zeros(n_slots, np.int32)
    slot_mask = np.zeros(n_slots, np.float32)
    in_local = np.zeros(n_slots, np.int32)
    out_local = np.zeros(n_slots, np.int32)
    perm[dest] = edges[order].astype(np.int32)
    slot_mask[dest] = 1.0
    in_local[dest] = (iin[order] % in_block).astype(np.int32)
    out_local[dest] = (iout[order] % out_block).astype(np.int32)

    tiles_per_bucket = (padded // tile).astype(np.int64)
    tile_in = np.repeat(uk % num_in_blocks, tiles_per_bucket).astype(np.int32)
    tile_out = np.repeat(uk // num_in_blocks,
                         tiles_per_bucket).astype(np.int32)
    # Edgeless output blocks still need ONE initialising tile (the
    # kernel writes zeros: all-padding tile => zeroed coupling columns).
    # Appended at the END: revisit-consecutiveness only needs each
    # output block's tiles contiguous, not globally sorted.
    missing = np.setdiff1d(np.arange(num_out_blocks), np.unique(tile_out))
    if missing.size:
        perm = np.concatenate([perm, np.zeros(missing.size * tile, np.int32)])
        slot_mask = np.concatenate(
            [slot_mask, np.zeros(missing.size * tile, np.float32)])
        in_local = np.concatenate(
            [in_local, np.zeros(missing.size * tile, np.int32)])
        out_local = np.concatenate(
            [out_local, np.zeros(missing.size * tile, np.int32)])
        tile_in = np.concatenate(
            [tile_in, np.zeros(missing.size, np.int32)])
        tile_out = np.concatenate([tile_out, missing.astype(np.int32)])
        n_slots = int(perm.shape[0])

    n_tiles = int(tile_in.shape[0])
    tile_first = np.zeros(n_tiles, np.int32)
    if n_tiles:
        tile_first[0] = 1
        tile_first[1:] = (tile_out[1:] != tile_out[:-1]).astype(np.int32)

    return FusedPlan(
        tile=tile, in_block=in_block, out_block=out_block,
        num_in_segments=num_in, num_out_segments=num_out,
        num_in_blocks=num_in_blocks, num_out_blocks=num_out_blocks,
        n_edges=int(edges.shape[0]), perm=perm, mask=slot_mask,
        in_local=in_local, out_local=out_local, tile_in=tile_in,
        tile_out=tile_out, tile_first=tile_first)


@dataclasses.dataclass(frozen=True)
class DeviceFusedPlan:
    """Device half of a FusedPlan, registered as a pytree so both
    directions ride the solve program as ordinary operands (toggling
    `fused_kernels` never bakes indices into a compiled program)."""

    tile: int
    in_block: int
    out_block: int
    num_in_blocks: int
    num_out_blocks: int
    num_in_segments: int
    num_out_segments: int
    n_edges: int
    perm: jax.Array  # [n_slots] int32
    mask: jax.Array  # [n_slots] float32
    in_local: jax.Array  # [1, n_slots] int32 (kernel row-block layout)
    out_local: jax.Array  # [1, n_slots] int32
    tile_in: jax.Array  # [n_tiles] int32
    tile_out: jax.Array  # [n_tiles] int32
    tile_first: jax.Array  # [n_tiles] int32


jax.tree_util.register_dataclass(
    DeviceFusedPlan,
    data_fields=["perm", "mask", "in_local", "out_local",
                 "tile_in", "tile_out", "tile_first"],
    meta_fields=["tile", "in_block", "out_block", "num_in_blocks",
                 "num_out_blocks", "num_in_segments", "num_out_segments",
                 "n_edges"],
)


def device_fused_plan(plan: FusedPlan) -> DeviceFusedPlan:
    return DeviceFusedPlan(
        tile=plan.tile, in_block=plan.in_block, out_block=plan.out_block,
        num_in_blocks=plan.num_in_blocks,
        num_out_blocks=plan.num_out_blocks,
        num_in_segments=plan.num_in_segments,
        num_out_segments=plan.num_out_segments,
        n_edges=plan.n_edges,
        perm=jnp.asarray(plan.perm), mask=jnp.asarray(plan.mask),
        in_local=jnp.asarray(plan.in_local)[None, :],
        out_local=jnp.asarray(plan.out_local)[None, :],
        tile_in=jnp.asarray(plan.tile_in),
        tile_out=jnp.asarray(plan.tile_out),
        tile_first=jnp.asarray(plan.tile_first))


def build_fused_dual_plans(
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    mask: Optional[np.ndarray],
    num_cameras: int,
    num_points: int,
    tile: int = FUSED_TILE,
    block_cam: int = FUSED_BLOCK_CAM,
    block_pt: int = FUSED_BLOCK_PT,
) -> Tuple[FusedPlan, FusedPlan, DeviceFusedPlan, DeviceFusedPlan]:
    """Both directions over the canonical (cam-slot) edge stream.

    Returns (host_to_pt, host_to_cam, device_to_pt, device_to_cam):
    cam->pt gathers camera blocks and scatters point blocks; pt->cam
    the reverse.  Padding slots of the source stream (mask 0) are
    dropped — their coupling columns are zero by construction.
    """
    to_pt = build_fused_plan(
        cam_idx, pt_idx, mask, num_cameras, num_points,
        tile=tile, in_block=block_cam, out_block=block_pt)
    to_cam = build_fused_plan(
        pt_idx, cam_idx, mask, num_points, num_cameras,
        tile=tile, in_block=block_pt, out_block=block_cam)
    return to_pt, to_cam, device_fused_plan(to_pt), device_fused_plan(to_cam)


def permute_rows(rows: jax.Array, fplan: DeviceFusedPlan) -> jax.Array:
    """Reorder per-edge rows into one direction's fused slot order,
    zeroing padding slots.  Runs ONCE per PCG solve (the coupling rows
    are fixed across CG iterations) — the documented cost of carrying
    one bucket order per direction."""
    return (jnp.take(rows, fplan.perm, axis=1, mode="clip")
            * fplan.mask.astype(rows.dtype))


def fused_plan_summary(plan: FusedPlan) -> Dict[str, Any]:
    """JSON-able structure metrics of one direction (SolveReport)."""
    return {
        "tiles": plan.n_tiles,
        "tile": plan.tile,
        "occupancy": round(plan.occupancy, 4),
        "edges": plan.n_edges,
        "slots": plan.n_slots,
    }


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


def _acc_dtype(*dtypes) -> jnp.dtype:
    """f32 accumulation for f32/bf16 operands, f64 for f64 (CPU lane)."""
    out = jnp.float32
    for d in dtypes:
        out = jnp.promote_types(out, d)
    if out not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.float64)):
        out = jnp.dtype(jnp.float32)
    return out


def _gather_block(in_l_ref, table_ref, in_block, acc_dt):
    """One-hot gather of an input block's rows to tile columns.

    The one-hot is exact in every dtype, so a bf16 table rides a
    bf16 x bf16 MXU dot — with the f32 (f64 on the CPU lane)
    accumulator the precision contract requires.
    """
    tile = in_l_ref.shape[1]
    onehot = (
        in_l_ref[:, :] == jax.lax.broadcasted_iota(
            jnp.int32, (in_block, tile), 0)
    ).astype(table_ref.dtype)  # [Bi, T]
    return jax.lax.dot_general(
        table_ref[:, :], onehot, (((1,), (0,)), ((), ())),
        preferred_element_type=acc_dt,
    )  # [d_in, T] accumulator dtype


def _scatter_block(te, out_l_ref, out_ref, tf_ref, i, out_block):
    """One-hot scatter-accumulate of per-edge rows onto the tile's
    output block; init-vs-accumulate is predicated on tile_first (the
    consecutive-revisit contract)."""
    tile = out_l_ref.shape[1]
    onehot = (
        out_l_ref[:, :] == jax.lax.broadcasted_iota(
            jnp.int32, (out_block, tile), 0)
    ).astype(te.dtype)  # [Bo, T]
    partial = jax.lax.dot_general(
        te, onehot, (((1,), (1,)), ((), ())),
        preferred_element_type=te.dtype,
    )  # [d_out, Bo]

    @pl.when(tf_ref[i] == 1)
    def _init():
        out_ref[:, :] = partial.astype(out_ref.dtype)

    @pl.when(tf_ref[i] == 0)
    def _acc():
        out_ref[:, :] = (out_ref[:, :] + partial).astype(out_ref.dtype)


def _contract_rows(row, vec_rows, a, acc, acc_dt, bf16_operands):
    """One product row into the f32/f64 accumulator.  The bf16 arm
    multiplies IN bf16 (the MXU operand format — same grouping as
    pcg._edge_precision's acc(up(w) * v)) and upcasts the product; the
    full-precision arm upcasts the stored row first."""
    if bf16_operands:
        t = (row * vec_rows[a, :]).astype(acc_dt)
    else:
        t = row.astype(acc_dt) * vec_rows[a, :]
    out = t if acc is None else acc + t
    # The fused-kernel precision contract: accumulators NEVER narrow.
    assert out.dtype == acc_dt, (out.dtype, acc_dt)
    return out


def _fused_w_kernel(ti_ref, to_ref, tf_ref, in_l_ref, out_l_ref, w_ref,
                    table_ref, out_ref, *, in_block, out_block, d_out,
                    w_in_major, bf16_operands, acc_dt):
    """EXPLICIT fused direction: te[b] = sum_a W[row(a,b)] * pe[a],
    gathered and scattered without leaving VMEM.

    W layout is [a*pd + b] (a = camera dim, b = point dim): cam->pt
    consumes it input-major (`w_in_major=True`), pt->cam output-major.
    """
    i = pl.program_id(0)
    d_in = table_ref.shape[0]
    pe = _gather_block(in_l_ref, table_ref, in_block, acc_dt)
    pe_op = pe.astype(jnp.bfloat16) if bf16_operands else pe
    rows = []
    for b in range(d_out):
        acc = None
        for a in range(d_in):
            r = (a * d_out + b) if w_in_major else (b * d_in + a)
            acc = _contract_rows(w_ref[r, :], pe_op, a, acc, acc_dt,
                                 bf16_operands)
        rows.append(acc[None, :])
    te = jnp.concatenate(rows, axis=0)  # [d_out, T] accumulator dtype
    _scatter_block(te, out_l_ref, out_ref, tf_ref, i, out_block)


def _fused_j_kernel(ti_ref, to_ref, tf_ref, in_l_ref, out_l_ref, jin_ref,
                    jout_ref, table_ref, out_ref, *, in_block, out_block,
                    d_out, bf16_operands, acc_dt):
    """IMPLICIT fused direction: u[o] = sum_a Jin[o*d_in+a] * pe[a],
    te[b] = sum_o Jout[o*d_out+b] * u[o] — the J_in product AND the
    J_out^T product happen on the same resident tile."""
    i = pl.program_id(0)
    d_in = table_ref.shape[0]
    od = jin_ref.shape[0] // d_in
    pe = _gather_block(in_l_ref, table_ref, in_block, acc_dt)
    pe_op = pe.astype(jnp.bfloat16) if bf16_operands else pe
    us = []
    for o in range(od):
        acc = None
        for a in range(d_in):
            acc = _contract_rows(jin_ref[o * d_in + a, :], pe_op, a, acc,
                                 acc_dt, bf16_operands)
        us.append(acc[None, :])
    u = jnp.concatenate(us, axis=0)  # [od, T] accumulator dtype
    u_op = u.astype(jnp.bfloat16) if bf16_operands else u
    rows = []
    for b in range(d_out):
        acc = None
        for o in range(od):
            acc = _contract_rows(jout_ref[o * d_out + b, :], u_op, o, acc,
                                 acc_dt, bf16_operands)
        rows.append(acc[None, :])
    te = jnp.concatenate(rows, axis=0)
    _scatter_block(te, out_l_ref, out_ref, tf_ref, i, out_block)


def _block_diag_kernel(h_ref, x_ref, out_ref, *, d, bf16_operands, acc_dt):
    """Fused block-diagonal apply: out[i] = sum_j H[i,j] * x[j] over one
    camera block, H stored feature-major ([d*d, CB] rows)."""
    xs = x_ref[:, :]
    if bf16_operands:
        xs = xs.astype(jnp.bfloat16)
    for i in range(d):
        acc = None
        for j in range(d):
            acc = _contract_rows(h_ref[i * d + j, :], xs, j, acc, acc_dt,
                                 bf16_operands)
        out_ref[i, :] = acc.astype(out_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


def _grid_spec(tile, in_block, out_block, row_heights, d_in, d_out,
               n_tiles):
    in_specs = [
        pl.BlockSpec((1, tile), lambda i, ti, to, tf: (0, i)),
        pl.BlockSpec((1, tile), lambda i, ti, to, tf: (0, i)),
    ]
    for h in row_heights:
        in_specs.append(
            pl.BlockSpec((h, tile), lambda i, ti, to, tf: (0, i)))
    in_specs.append(
        pl.BlockSpec((d_in, in_block), lambda i, ti, to, tf: (0, ti[i])))
    return pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # tile_in, tile_out, tile_first
        grid=(n_tiles,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (d_out, out_block), lambda i, ti, to, tf: (0, to[i])),
    )


def _pad_table(table, num_blocks, block):
    pad = num_blocks * block - table.shape[1]
    return jnp.pad(table, ((0, 0), (0, pad))) if pad else table


@functools.partial(
    jax.jit,
    static_argnames=("tile", "in_block", "out_block", "num_in_blocks",
                     "num_out_blocks", "w_in_major", "bf16_operands",
                     "interpret"))
def _fused_w_call(W, table, in_local, out_local, tile_in, tile_out,
                  tile_first, *, tile, in_block, out_block, num_in_blocks,
                  num_out_blocks, w_in_major, bf16_operands, interpret):
    d_in = table.shape[0]
    d_out = W.shape[0] // d_in
    acc_dt = _acc_dtype(W.dtype, table.dtype)
    n_tiles = tile_in.shape[0]
    table_p = _pad_table(table, num_in_blocks, in_block)
    return pl.pallas_call(
        functools.partial(
            _fused_w_kernel, in_block=in_block, out_block=out_block,
            d_out=d_out, w_in_major=w_in_major,
            bf16_operands=bf16_operands, acc_dt=acc_dt),
        grid_spec=_grid_spec(tile, in_block, out_block, (W.shape[0],),
                             d_in, d_out, n_tiles),
        out_shape=jax.ShapeDtypeStruct(
            (d_out, num_out_blocks * out_block), acc_dt),
        interpret=interpret,
    )(tile_in, tile_out, tile_first, in_local, out_local, W, table_p)


@functools.partial(
    jax.jit,
    static_argnames=("tile", "in_block", "out_block", "num_in_blocks",
                     "num_out_blocks", "bf16_operands", "interpret"))
def _fused_j_call(Jin, Jout, table, in_local, out_local, tile_in, tile_out,
                  tile_first, *, tile, in_block, out_block, num_in_blocks,
                  num_out_blocks, bf16_operands, interpret):
    d_in = table.shape[0]
    od = Jin.shape[0] // d_in
    d_out = Jout.shape[0] // od
    acc_dt = _acc_dtype(Jin.dtype, Jout.dtype, table.dtype)
    n_tiles = tile_in.shape[0]
    table_p = _pad_table(table, num_in_blocks, in_block)
    return pl.pallas_call(
        functools.partial(
            _fused_j_kernel, in_block=in_block, out_block=out_block,
            d_out=d_out, bf16_operands=bf16_operands, acc_dt=acc_dt),
        grid_spec=_grid_spec(tile, in_block, out_block,
                             (Jin.shape[0], Jout.shape[0]),
                             d_in, d_out, n_tiles),
        out_shape=jax.ShapeDtypeStruct(
            (d_out, num_out_blocks * out_block), acc_dt),
        interpret=interpret,
    )(tile_in, tile_out, tile_first, in_local, out_local, Jin, Jout,
      table_p)


# ---------------------------------------------------------------------------
# Public applies
# ---------------------------------------------------------------------------


def fused_coupling_apply(
    W_f: jax.Array,
    table: jax.Array,
    fplan: DeviceFusedPlan,
    w_in_major: bool,
    bf16_operands: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """EXPLICIT fused matvec direction: [d_in, num_in] table ->
    [d_out, num_out_segments] sums.  `W_f` must already be in this
    direction's fused slot order (`permute_rows`)."""
    out = _fused_w_call(
        W_f, table, fplan.in_local, fplan.out_local, fplan.tile_in,
        fplan.tile_out, fplan.tile_first, tile=fplan.tile,
        in_block=fplan.in_block, out_block=fplan.out_block,
        num_in_blocks=fplan.num_in_blocks,
        num_out_blocks=fplan.num_out_blocks, w_in_major=w_in_major,
        bf16_operands=bf16_operands, interpret=interpret)
    return out[:, : fplan.num_out_segments]


def fused_coupling_apply_implicit(
    Jin_f: jax.Array,
    Jout_f: jax.Array,
    table: jax.Array,
    fplan: DeviceFusedPlan,
    bf16_operands: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """IMPLICIT fused matvec direction (Jc/Jp rows pre-permuted)."""
    out = _fused_j_call(
        Jin_f, Jout_f, table, fplan.in_local, fplan.out_local,
        fplan.tile_in, fplan.tile_out, fplan.tile_first, tile=fplan.tile,
        in_block=fplan.in_block, out_block=fplan.out_block,
        num_in_blocks=fplan.num_in_blocks,
        num_out_blocks=fplan.num_out_blocks,
        bf16_operands=bf16_operands, interpret=interpret)
    return out[:, : fplan.num_out_segments]


def _pick_tile(n: int) -> int:
    for t in (FUSED_TILE, 256, 128):
        if n % t == 0:
            return t
    return n


def fused_single_block_apply(
    rows: jax.Array,
    table: jax.Array,
    in_local: jax.Array,
    out_local: jax.Array,
    out_block: int,
    w_in_major: bool = False,
    rows_out: Optional[jax.Array] = None,
    bf16_operands: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """Degenerate fused plan: ONE input block (the whole table) and ONE
    output block — the 2-D mesh's ring-step contraction, where the
    rotating point shard is the input block and the camera tile the
    output.  `rows` columns must be pre-masked (padding pairs zeroed).
    With `rows_out`, runs the implicit two-stage contraction."""
    n = in_local.shape[-1]
    tile = _pick_tile(int(n))
    n_tiles = n // tile
    tile_zero = jnp.zeros((n_tiles,), jnp.int32)
    tile_first = jnp.concatenate(
        [jnp.ones((1,), jnp.int32), jnp.zeros((n_tiles - 1,), jnp.int32)])
    in_l = in_local.reshape(1, -1).astype(jnp.int32)
    out_l = out_local.reshape(1, -1).astype(jnp.int32)
    if rows_out is None:
        return _fused_w_call(
            rows, table, in_l, out_l, tile_zero, tile_zero, tile_first,
            tile=tile, in_block=table.shape[1], out_block=out_block,
            num_in_blocks=1, num_out_blocks=1, w_in_major=w_in_major,
            bf16_operands=bf16_operands, interpret=interpret)
    return _fused_j_call(
        rows, rows_out, table, in_l, out_l, tile_zero, tile_zero,
        tile_first, tile=tile, in_block=table.shape[1],
        out_block=out_block, num_in_blocks=1, num_out_blocks=1,
        bf16_operands=bf16_operands, interpret=interpret)


# ---------------------------------------------------------------------------
# Fused block-diagonal M^-1 apply
# ---------------------------------------------------------------------------

FUSED_CAM_BLOCK = 512  # cameras per M^-1 apply grid step


@functools.partial(
    jax.jit, static_argnames=("cam_block", "bf16_operands", "interpret"))
def _block_diag_call(Hrows, x, *, cam_block, bf16_operands, interpret):
    d = x.shape[0]
    nc = x.shape[1]
    nb = max(1, -(-nc // cam_block))
    acc_dt = _acc_dtype(Hrows.dtype, x.dtype)
    pad = nb * cam_block - nc
    if pad:
        Hrows = jnp.pad(Hrows, ((0, 0), (0, pad)))
        x = jnp.pad(x, ((0, 0), (0, pad)))
    out = pl.pallas_call(
        functools.partial(_block_diag_kernel, d=d,
                          bf16_operands=bf16_operands, acc_dt=acc_dt),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((d * d, cam_block), lambda i: (0, i)),
            pl.BlockSpec((d, cam_block), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((d, cam_block), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((d, nb * cam_block), acc_dt),
        interpret=interpret,
    )(Hrows, x)
    return out[:, :nc]


def block_diag_rows(Minv: jax.Array) -> jax.Array:
    """[Nc, d, d] inverted block diagonal -> feature-major [d*d, Nc]
    rows (row i*d+j holds M^-1[:, i, j]) — laid out ONCE per solve for
    the fused apply."""
    d = Minv.shape[-1]
    return jnp.transpose(Minv, (1, 2, 0)).reshape(d * d, Minv.shape[0])


def fused_block_diag_apply(
    Hrows: jax.Array,
    x: jax.Array,
    cam_block: int = FUSED_CAM_BLOCK,
    bf16_operands: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """Fused M^-1 apply: one kernel pass over camera blocks, f32 (f64)
    accumulation; the bf16 arm multiplies bf16 x bf16 and upcasts —
    value-for-value the cam_block_matvec(_bf16) einsum contract."""
    cam_block = min(cam_block, max(8, x.shape[1]))
    return _block_diag_call(
        Hrows, x, cam_block=cam_block, bf16_operands=bf16_operands,
        interpret=interpret).astype(x.dtype)


# ---------------------------------------------------------------------------
# XLA reference (parity oracle for tests; never the production fallback —
# with fused_kernels off the production path is the existing segtiles /
# XLA lowering, untouched)
# ---------------------------------------------------------------------------


def reference_coupling_apply(W, table, in_idx, out_idx, num_out,
                             w_in_major, d_in):
    """Plain gather/contract/scatter in XLA ops — the parity oracle."""
    pe = jnp.take(table, in_idx, axis=1, mode="clip")  # [d_in, nE]
    d_out = W.shape[0] // d_in
    te = jnp.stack([
        sum((W[(a * d_out + b) if w_in_major else (b * d_in + a)]
             .astype(pe.dtype) * pe[a])
            for a in range(d_in))
        for b in range(d_out)
    ])
    out = jnp.zeros((d_out, num_out), te.dtype)
    return out.at[:, out_idx].add(te, mode="drop")
