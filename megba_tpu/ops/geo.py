"""Batched geometry ops.

TPU-native equivalents of the reference's hand-fused geo kernels
(reference include/geo/geo.cuh:31-67; src/geo/angle_axis.cu,
src/geo/distortion.cu, src/geo/rotation2D.cu): plain JAX functions on a
single item, designed to be `jax.vmap`-ed over the edge axis and fused by
XLA.  Derivative propagation is free — `jax.jacfwd`/`jax.jvp` of these
functions is the TPU analog of the reference's in-kernel grad math.

All functions avoid data-dependent control flow (`jnp.where` branches with
safe operands) so they compile to straight-line MXU/VPU code under jit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Small fixed-size (2x3 / 3x3) matrix products: always full float32 — on TPU
# the default matmul precision is bf16, which corrupts float32 Jacobians by
# ~1e-2 absolute.  These contractions are tiny (VPU, not MXU), so HIGHEST
# costs nothing; bf16 stays an explicit opt-in for the large PCG matvecs
# (ProblemOption.mixed_precision_pcg).
mm = functools.partial(jnp.matmul, precision=jax.lax.Precision.HIGHEST)

# Threshold below which the Rodrigues formula switches to its Taylor
# expansion (reference angle_axis.cu uses the same small-angle guard).
_SMALL_ANGLE = 1e-12


def angle_axis_rotate_point(angle_axis: jnp.ndarray, pt: jnp.ndarray) -> jnp.ndarray:
    """Rotate `pt` (3,) by the rotation `angle_axis` (3,), Rodrigues form.

    result = pt cos(theta) + (k x pt) sin(theta) + k (k . pt)(1 - cos(theta))
    with the theta -> 0 limit pt + w x pt.  Equivalent of the Ceres-style
    AngleAxisRotatePoint transcribed in reference
    src/geo/analytical_derivatives.cu:16-159 and the fused
    AngleAxisToRotationKernelMatrix path (src/geo/angle_axis.cu).
    """
    theta2 = jnp.dot(angle_axis, angle_axis)
    safe = theta2 > _SMALL_ANGLE
    # Guard against 0-divide inside the untaken branch (both branches are
    # always evaluated under jit).  ones_like, not Python 1.0: a weak
    # literal in a `where` branch materialises as a wide (f64-under-x64)
    # constant — a dtype leak the compiled-program auditor
    # (analysis/program_audit.py) bans from f32 programs.
    theta2_safe = jnp.where(safe, theta2, jnp.ones_like(theta2))
    theta = jnp.sqrt(theta2_safe)
    cos_t = jnp.cos(theta)
    sin_t = jnp.sin(theta)
    k = angle_axis / theta
    cross = jnp.cross(k, pt)
    dot = jnp.dot(k, pt)
    rotated = pt * cos_t + cross * sin_t + k * dot * (1.0 - cos_t)
    # Small-angle first-order expansion: pt + w x pt.
    approx = pt + jnp.cross(angle_axis, pt)
    return jnp.where(safe, rotated, approx)


def angle_axis_to_rotation_matrix(angle_axis: jnp.ndarray) -> jnp.ndarray:
    """(3,) angle-axis -> (3,3) rotation matrix.

    Equivalent of reference geo::AngleAxisToRotationKernelMatrix
    (src/geo/angle_axis.cu:16-130), including the small-angle branch.
    """
    theta2 = jnp.dot(angle_axis, angle_axis)
    safe = theta2 > _SMALL_ANGLE
    # ones_like: see angle_axis_rotate_point (weak-literal dtype leak).
    theta2_safe = jnp.where(safe, theta2, jnp.ones_like(theta2))
    theta = jnp.sqrt(theta2_safe)
    k = angle_axis / theta
    cos_t = jnp.cos(theta)
    sin_t = jnp.sin(theta)
    K = skew(k)
    eye = jnp.eye(3, dtype=angle_axis.dtype)
    R = eye + sin_t * K + (1.0 - cos_t) * mm(K, K)
    R_small = eye + skew(angle_axis)
    return jnp.where(safe, R, R_small)


def skew(v: jnp.ndarray) -> jnp.ndarray:
    """(3,) -> (3,3) cross-product matrix [v]_x."""
    z = jnp.zeros((), dtype=v.dtype)
    return jnp.array(
        [
            [z, -v[2], v[1]],
            [v[2], z, -v[0]],
            [-v[1], v[0], z],
        ]
    )


def rotation2d_to_matrix(theta: jnp.ndarray) -> jnp.ndarray:
    """scalar angle -> (2,2) rotation matrix.

    Equivalent of reference geo::Rotation2DToRotationMatrix
    (src/geo/rotation2D.cu:15-70).
    """
    c = jnp.cos(theta)
    s = jnp.sin(theta)
    return jnp.array([[c, -s], [s, c]])


def radial_distortion(
    p: jnp.ndarray, f: jnp.ndarray, k1: jnp.ndarray, k2: jnp.ndarray
) -> jnp.ndarray:
    """Apply BAL radial distortion: f * (1 + k1 l^2 + k2 l^4) * p.

    `p` is the (2,) normalised image-plane point.  Equivalent of reference
    geo::RadialDistortion (src/geo/distortion.cu:14-80); the three kernel
    variants there (full grad / no-intrinsic grad / one-hot intrinsics) are
    all subsumed by autodiff of this one function.
    """
    n = jnp.dot(p, p)
    r = 1.0 + k1 * n + k2 * n * n
    return f * r * p


def quaternion_to_rotation_matrix(q: jnp.ndarray) -> jnp.ndarray:
    """(4,) unit quaternion (w, x, y, z) -> (3,3) rotation matrix.

    The reference declares this in geo.cuh:43-49 (impl lives in the dead
    quaternion.cu); provided here as a live op.
    """
    w, x, y, z = q[0], q[1], q[2], q[3]
    return jnp.array(
        [
            [1 - 2 * (y * y + z * z), 2 * (x * y - w * z), 2 * (x * z + w * y)],
            [2 * (x * y + w * z), 1 - 2 * (x * x + z * z), 2 * (y * z - w * x)],
            [2 * (x * z - w * y), 2 * (y * z + w * x), 1 - 2 * (x * x + y * y)],
        ]
    )


def rotation_matrix_to_quaternion(R: jnp.ndarray) -> jnp.ndarray:
    """(3,3) rotation matrix -> (4,) unit quaternion (w, x, y, z).

    Branch-free Shepperd-style construction (jnp.where over the four
    candidate pivots) so it is safe under vmap/jit.
    """
    m00, m01, m02 = R[0, 0], R[0, 1], R[0, 2]
    m10, m11, m12 = R[1, 0], R[1, 1], R[1, 2]
    m20, m21, m22 = R[2, 0], R[2, 1], R[2, 2]
    tr = m00 + m11 + m22

    def safe_sqrt(x):
        return jnp.sqrt(jnp.maximum(x, 1e-30))

    # Four candidate constructions; pick the numerically largest pivot.
    qw0 = safe_sqrt(1.0 + tr) / 2.0
    c0 = jnp.stack([qw0, (m21 - m12) / (4 * qw0), (m02 - m20) / (4 * qw0), (m10 - m01) / (4 * qw0)])
    qx1 = safe_sqrt(1.0 + m00 - m11 - m22) / 2.0
    c1 = jnp.stack([(m21 - m12) / (4 * qx1), qx1, (m01 + m10) / (4 * qx1), (m02 + m20) / (4 * qx1)])
    qy2 = safe_sqrt(1.0 - m00 + m11 - m22) / 2.0
    c2 = jnp.stack([(m02 - m20) / (4 * qy2), (m01 + m10) / (4 * qy2), qy2, (m12 + m21) / (4 * qy2)])
    qz3 = safe_sqrt(1.0 - m00 - m11 + m22) / 2.0
    c3 = jnp.stack([(m10 - m01) / (4 * qz3), (m02 + m20) / (4 * qz3), (m12 + m21) / (4 * qz3), qz3])

    scores = jnp.stack([tr, m00, m11, m22])
    best = jnp.argmax(scores)
    q = jnp.where(
        best == 0, c0, jnp.where(best == 1, c1, jnp.where(best == 2, c2, c3))
    )
    return normalize(q)


def normalize(v: jnp.ndarray) -> jnp.ndarray:
    """Normalise a vector to unit length (reference geo.cuh:46 Normalize_)."""
    return v / jnp.sqrt(jnp.maximum(jnp.dot(v, v), 1e-30))


def quaternion_to_angle_axis(q: jnp.ndarray) -> jnp.ndarray:
    """(4,) unit quaternion (w, x, y, z) -> (3,) angle-axis (SO(3) log).

    Small-angle-safe AND autodiff-safe: the scale 2*atan2(n, |w|)/n is
    evaluated through the double-where trick so its gradient stays
    finite at n -> 0 (where the true limit is 2/w), and the sign of w
    is folded in so the returned angle is always in [0, pi].
    """
    w, vec = q[0], q[1:]
    vec = jnp.where(w < 0, -vec, vec)
    w = jnp.abs(w)
    n2 = jnp.dot(vec, vec)
    small = n2 < 1e-14
    # ones_like: keeps sqrt/atan2 grads finite without a weak-literal
    # wide constant (see angle_axis_rotate_point).
    n2_safe = jnp.where(small, jnp.ones_like(n2), n2)
    n = jnp.sqrt(n2_safe)
    # Taylor of 2*atan2(n, w)/n around n=0: 2/w - 2 n^2 / (3 w^3).
    scale = jnp.where(
        small,
        2.0 / jnp.maximum(w, 1e-30) - 2.0 * n2 / (3.0 * jnp.maximum(w, 1e-30) ** 3),
        2.0 * jnp.arctan2(n, w) / n,
    )
    return scale * vec


def rotation_matrix_to_angle_axis(R: jnp.ndarray) -> jnp.ndarray:
    """(3,3) rotation matrix -> (3,) angle-axis: the SO(3) log map.

    Composed via the branch-free quaternion extraction, so it is safe
    under vmap/jit and differentiable away from the pi-rotation cut
    locus.  Inverse of `angle_axis_to_rotation_matrix` (round-trip
    tested).  The reference has no log map at all — its geo library
    (geo.cuh) only exposes the exponential direction.
    """
    return quaternion_to_angle_axis(rotation_matrix_to_quaternion(R))


def drotated_dangle_axis(angle_axis: jnp.ndarray, pt: jnp.ndarray) -> jnp.ndarray:
    """Closed-form d(R(w) pt)/dw, (3,3).

    Gallego & Yezzi (2015) formula:
      d(R x)/dw = -R [x]_x ( w w^T + (R^T - I) [w]_x ) / theta^2
    with the theta -> 0 limit -[x]_x.  This is the analytical core used by
    the hand-written Jacobian path (the equivalent of the hand-derived
    partials in reference src/geo/analytical_derivatives.cu:16-159).
    """
    theta2 = jnp.dot(angle_axis, angle_axis)
    safe = theta2 > _SMALL_ANGLE
    theta2_safe = jnp.where(safe, theta2, jnp.ones_like(theta2))
    R = angle_axis_to_rotation_matrix(angle_axis)
    W = skew(angle_axis)
    X = skew(pt)
    eye = jnp.eye(3, dtype=angle_axis.dtype)
    full = -mm(mm(R, X), jnp.outer(angle_axis, angle_axis) + mm(R.T - eye, W)) / theta2_safe
    return jnp.where(safe, full, -X)
