"""Vectorised forward-mode dual numbers — the JetVector-equivalent API.

Functional parity with the reference's operator layer
(include/operator/jet_vector.h:22-171, jet_vector_op-inl.h:34-91 and the
~40 CUDA kernels of src/operator/jet_vector_math_impl.cu): a `Jet` holds
one scalar slot of ALL edges simultaneously — `value [nItem]` and
`grad [N, nItem]` — and supports +, -, *, / (jet/jet and jet/scalar,
both orders), unary minus, abs, sqrt, sin, cos.

Three reference jet kinds map as:
  * full jet      -> dense `grad`
  * JPV one-hot   -> `seed_jets` builds the one-hot rows (the memory
    optimisation is unnecessary here: XLA fuses the seeding into
    consumers, nothing N x nItem is materialised unless used)
  * scalar vector -> `Jet(value, zeros)` via `constant`

The production solver does NOT route through this class — `jax.jacfwd`
under vmap subsumes it (ops/residuals.py) — but it is the public
building block for users who port JetVector-based code, and each op is
verified against `jax.jvp` in tests/test_jet.py.  Being a pytree, `Jet`
composes with jit/vmap/shard_map like any array.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Union

import jax
import jax.numpy as jnp

Scalar = Union[float, int, jax.Array]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Jet:
    """A batch of dual numbers: value [n], grad [N, n] (grad-major like
    the reference's SoA layout, jet_vector.h:31-41)."""

    value: jax.Array
    grad: jax.Array

    # -- construction ------------------------------------------------------
    @staticmethod
    def constant(value: jax.Array, n_grad: int) -> "Jet":
        """A jet with zero derivative (reference scalar-vector kind)."""
        value = jnp.asarray(value)
        return Jet(value, jnp.zeros((n_grad,) + value.shape, value.dtype))

    @staticmethod
    def variable(value: jax.Array, n_grad: int, index: int) -> "Jet":
        """A differentiation variable: one-hot grad at `index` (the
        reference's JPV grad-position jet, jet_vector.h:38-39)."""
        value = jnp.asarray(value)
        grad = jnp.zeros((n_grad,) + value.shape, value.dtype)
        return Jet(value, grad.at[index].set(1.0))

    @property
    def n_grad(self) -> int:
        return self.grad.shape[0]

    # -- helpers -----------------------------------------------------------
    def _coerce(self, other) -> "Jet":
        if isinstance(other, Jet):
            return other
        return Jet.constant(jnp.broadcast_to(jnp.asarray(other, self.value.dtype),
                                             self.value.shape), self.n_grad)

    # -- arithmetic (value/grad rules mirror jet_vector_math_impl.cu) -----
    def __add__(self, other) -> "Jet":
        o = self._coerce(other)
        return Jet(self.value + o.value, self.grad + o.grad)

    __radd__ = __add__

    def __sub__(self, other) -> "Jet":
        o = self._coerce(other)
        return Jet(self.value - o.value, self.grad - o.grad)

    def __rsub__(self, other) -> "Jet":
        o = self._coerce(other)
        return Jet(o.value - self.value, o.grad - self.grad)

    def __mul__(self, other) -> "Jet":
        o = self._coerce(other)
        return Jet(self.value * o.value,
                   self.grad * o.value + o.grad * self.value)

    __rmul__ = __mul__

    def __truediv__(self, other) -> "Jet":
        o = self._coerce(other)
        inv = 1.0 / o.value
        return Jet(self.value * inv,
                   (self.grad - o.grad * (self.value * inv)) * inv)

    def __rtruediv__(self, other) -> "Jet":
        return self._coerce(other) / self

    def __neg__(self) -> "Jet":
        return Jet(-self.value, -self.grad)

    # -- unary math (reference jet_vector_math_impl.cu:1193-1320) ---------
    def abs(self) -> "Jet":
        sign = jnp.sign(self.value)
        return Jet(jnp.abs(self.value), self.grad * sign)

    def sqrt(self) -> "Jet":
        root = jnp.sqrt(self.value)
        return Jet(root, self.grad * (0.5 / root))

    def sin(self) -> "Jet":
        return Jet(jnp.sin(self.value), self.grad * jnp.cos(self.value))

    def cos(self) -> "Jet":
        return Jet(jnp.cos(self.value), -self.grad * jnp.sin(self.value))


def seed_jets(values: Sequence[jax.Array], dtype=None) -> list:
    """Seed one `Jet` variable per scalar slot across a parameter list.

    values: list of [n] arrays (one per scalar parameter, each holding
    that parameter for all n edges).  Returns Jets whose grads form the
    identity — the vectorised equivalent of the reference's
    setGradShapeAndOffset one-hot assignment (base_vertex.h:142-151).
    """
    n_grad = len(values)
    return [Jet.variable(jnp.asarray(v, dtype), n_grad, i)
            for i, v in enumerate(values)]
