"""Robust loss kernels (IRLS weighting).

Capability beyond the reference (MegBA has NO robust kernels — every
edge is plain squared error), but standard in the BA ecosystem it
competes with (Ceres/g2o loss functions).  Implementation is the classic
triggered reweighting: with s = ||r||^2 per edge, the robustified
objective Sum rho(s) is minimised by weighting the residual and Jacobian
with w = sqrt(rho'(s)) at each linearisation (IRLS; the Triggs
second-order correction is deliberately omitted — standard practice, it
can break positive-definiteness).

All kernels satisfy rho(s) ~= s near 0 and rho'(s) <= 1, so the damped
Schur blocks stay SPD.
"""

from __future__ import annotations

import enum
from typing import Optional, Tuple

import jax.numpy as jnp


class RobustKind(enum.Enum):
    NONE = 0
    HUBER = 1
    CAUCHY = 2


def rho_and_weight(
    s: jnp.ndarray, kind: RobustKind, delta: float
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(rho(s), sqrt(rho'(s))) elementwise over squared norms s >= 0.

    Huber (on squared input, Ceres 'HuberLoss' convention with
    delta^2 = threshold on s):
        rho(s) = s                        for s <= delta^2
               = 2 delta sqrt(s) - delta^2 otherwise
    Cauchy: rho(s) = delta^2 log(1 + s / delta^2).
    """
    d2 = delta * delta
    if kind == RobustKind.NONE:
        return s, jnp.ones_like(s)
    if kind == RobustKind.HUBER:
        sqrt_s = jnp.sqrt(jnp.maximum(s, 1e-30))
        rho = jnp.where(s <= d2, s, 2.0 * delta * sqrt_s - d2)
        # rho'(s) = 1 inside, delta / sqrt(s) outside.
        # ones_like, not Python 1.0: a weak literal in a `where` branch
        # lowers as a wide (f64-under-x64) constant + convert — the
        # dtype-census pass (analysis/program_audit.py) bans those.
        w2 = jnp.where(s <= d2, jnp.ones_like(s), delta / sqrt_s)
        return rho, jnp.sqrt(w2)
    if kind == RobustKind.CAUCHY:
        rho = d2 * jnp.log1p(s / d2)
        w2 = 1.0 / (1.0 + s / d2)  # rho'(s)
        return rho, jnp.sqrt(w2)
    raise ValueError(f"unknown robust kind {kind}")


def robustify(
    r: jnp.ndarray,
    Jc: jnp.ndarray,
    Jp: jnp.ndarray,
    kind: RobustKind,
    delta: float,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Reweight (r, Jc, Jp) per edge; also return per-edge rho(s).

    Feature-major: the already info/mask-weighted residual r [od, nE] and
    Jacobian rows Jc [od*cd, nE] / Jp [od*pd, nE]; the returned rho [nE]
    sums to the robustified cost.  The weighted quantities satisfy
    Sum ||w r||^2 ~ first-order model of Sum rho, which is what the
    Gauss-Newton/LM step needs.
    """
    s = jnp.sum(r * r, axis=0)
    rho, w = rho_and_weight(s, kind, delta)
    wm = w[None, :]
    return r * wm, Jc * wm, Jp * wm, rho
