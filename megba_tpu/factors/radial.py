"""Full-intrinsics radial-distortion pinhole (rolling-shutter-ready).

MegBA's geo layer lists `RadialDistortion` as a first-class op
(src/geo/distortion.cu); the BAL family already optimises its minimal
(f, k1, k2) intrinsics, but real camera calibration wants the FULL
pinhole: separate focal lengths, a principal point, and k1/k2 as
first-class optimisable state — 12 camera dof instead of BAL's 9:

  camera (12) = [angle-axis (3), t (3), fx, fy, cx, cy, k1, k2]
  point  (3)
  obs    (2)  = measured pixel

Projection (BAL minus convention on the normalised plane, then the full
intrinsic map):  p = -P[:2]/P[2],  d = 1 + k1 |p|^2 + k2 |p|^4,
u = fx d p_x + cx,  v = fy d p_y + cy.

Rolling-shutter readiness: the engine contract lets `obs_dim` grow
without touching residual_dim, so a rolling-shutter variant adds a
per-edge row-time constant to obs and velocity state to the camera
block as a NEW registered spec — no solver/serving surgery (the whole
point of the registry seam).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from megba_tpu.factors.registry import FactorSpec, FactorTriage

CAMERA_DIM = 12
POINT_DIM = 3
OBS_DIM = 2


def radial_residual(camera: jnp.ndarray, point: jnp.ndarray,
                    obs: jnp.ndarray) -> jnp.ndarray:  # megba: jit-entry
    """2-row full-intrinsics reprojection residual for one edge."""
    from megba_tpu.ops import geo

    w, t = camera[0:3], camera[3:6]
    fx, fy, cx, cy, k1, k2 = (camera[6], camera[7], camera[8],
                              camera[9], camera[10], camera[11])
    P = geo.angle_axis_rotate_point(w, point) + t
    p = -P[0:2] / P[2]
    n = jnp.dot(p, p)
    d = 1.0 + k1 * n + k2 * n * n
    uv = jnp.stack([fx * d * p[0] + cx, fy * d * p[1] + cy])
    return uv - obs


def _radial_project_depth(cam_blocks: np.ndarray, pt_blocks: np.ndarray,
                          obs: np.ndarray):
    """Host twin of `radial_residual`'s projection + camera-frame depth."""
    from megba_tpu.io.synthetic import rotate_batch

    del obs
    w, t = cam_blocks[:, 0:3], cam_blocks[:, 3:6]
    P = rotate_batch(w, pt_blocks) + t
    with np.errstate(divide="ignore", invalid="ignore"):
        p = -P[:, 0:2] / P[:, 2:3]
        n = np.sum(p * p, axis=1, keepdims=True)
        d = 1.0 + cam_blocks[:, 10:11] * n + cam_blocks[:, 11:12] * n * n
        uv = cam_blocks[:, 6:8] * d * p + cam_blocks[:, 8:10]
    return uv, P[:, 2]


def _radial_centers(cameras: np.ndarray) -> np.ndarray:
    from megba_tpu.io.synthetic import camera_centers

    return camera_centers(cameras)


SPEC = FactorSpec(
    name="pinhole_radial",
    cam_dim=CAMERA_DIM,
    pt_dim=POINT_DIM,
    obs_dim=OBS_DIM,
    residual_dim=2,
    residual_fn=radial_residual,
    triage=FactorTriage(project_depth=_radial_project_depth,
                        uv_cols=(0, 2), camera_centers=_radial_centers),
    description="full-intrinsics pinhole: camera [aa(3), t(3), fx, fy, "
                "cx, cy, k1, k2] with optimisable distortion",
)


@dataclasses.dataclass
class SyntheticRadial:
    """Ground truth + perturbed init for a full-intrinsics scene."""

    cameras_gt: np.ndarray  # [Nc, 12]
    points_gt: np.ndarray
    cameras0: np.ndarray
    points0: np.ndarray
    obs: np.ndarray  # [nE, 2]
    cam_idx: np.ndarray
    pt_idx: np.ndarray


def make_synthetic_radial(
    num_cameras: int = 4,
    num_points: int = 24,
    obs_per_point: int = 3,
    pixel_noise: float = 0.3,
    param_noise: float = 1e-2,
    seed: int = 0,
    dtype: np.dtype = np.float64,
) -> SyntheticRadial:
    """Well-posed full-intrinsics scene (make_synthetic_bal's geometry
    with a 12-dof camera; observations from the model itself)."""
    r = np.random.default_rng(seed)
    obs_per_point = min(obs_per_point, num_cameras)

    points_gt = r.uniform(-1.0, 1.0, size=(num_points, 3))
    cameras_gt = np.zeros((num_cameras, 12))
    cameras_gt[:, 0:3] = r.normal(scale=0.05, size=(num_cameras, 3))
    cameras_gt[:, 3:5] = r.normal(scale=0.2, size=(num_cameras, 2))
    cameras_gt[:, 5] = -5.0 + r.normal(scale=0.2, size=num_cameras)
    cameras_gt[:, 6] = 500.0 + r.normal(scale=5.0, size=num_cameras)  # fx
    cameras_gt[:, 7] = 495.0 + r.normal(scale=5.0, size=num_cameras)  # fy
    cameras_gt[:, 8] = r.normal(scale=2.0, size=num_cameras)  # cx
    cameras_gt[:, 9] = r.normal(scale=2.0, size=num_cameras)  # cy
    cameras_gt[:, 10] = 0.05 + r.normal(scale=5e-3, size=num_cameras)  # k1
    cameras_gt[:, 11] = -0.01 + r.normal(scale=1e-3, size=num_cameras)  # k2

    base = r.integers(0, num_cameras, size=(num_points, 1))
    stride = 1 + r.integers(0, max(num_cameras // max(obs_per_point, 1), 1),
                            size=(num_points, 1))
    cam_idx = ((base + np.arange(obs_per_point)[None, :] * stride)
               % num_cameras).reshape(-1)
    pt_idx = np.repeat(np.arange(num_points), obs_per_point)
    missing = np.setdiff1d(np.arange(num_cameras), cam_idx)
    if missing.size:
        cam_idx = np.concatenate([cam_idx, missing])
        pt_idx = np.concatenate(
            [pt_idx, r.integers(0, num_points, size=missing.size)])

    uv, _ = _radial_project_depth(cameras_gt[cam_idx], points_gt[pt_idx],
                                  None)
    obs = uv + r.normal(scale=pixel_noise, size=uv.shape)

    order = np.argsort(cam_idx, kind="stable")
    scale = np.array([1.0, 1.0, 1.0, 1.0, 1.0, 1.0,
                      20.0, 20.0, 2.0, 2.0, 5e-3, 5e-4])
    cameras0 = cameras_gt + r.normal(
        scale=param_noise, size=cameras_gt.shape) * scale
    points0 = points_gt + r.normal(scale=param_noise, size=points_gt.shape)
    return SyntheticRadial(
        cameras_gt=cameras_gt.astype(dtype),
        points_gt=points_gt.astype(dtype),
        cameras0=cameras0.astype(dtype),
        points0=points0.astype(dtype),
        obs=obs[order].astype(dtype),
        cam_idx=cam_idx[order].astype(np.int32),
        pt_idx=pt_idx[order].astype(np.int32),
    )
