"""GPS/IMU-style unary SE(3) pose priors as a camera/point factor.

The g2o unary-prior machinery (`EDGE_SE3_PRIOR`) as a first-class
registered family: each edge anchors ONE camera-side pose block to a
measured pose carried in the observation vector.  The residual ignores
the point block entirely (`point_coupled=False`) — its point-side
Jacobian is identically zero, the builder's empty-block guard gives
every point an identity Hessian block, and the Schur trick degenerates
gracefully — so a prior problem rides the same lowered program family
as any other factor, needing only a single shared dummy point.

Block layout:
  camera (6) = [angle-axis (3), translation (3)]  (the pose)
  point  (3) = dummy (shared; never moves)
  obs    (6) = the prior pose [angle-axis (3), translation (3)]
  residual (6) = [log_SO3(R_p^T R), R_p^T (t - t_p)]

The residual is the right-invariant pose error of models/pgo.py's
between-factor with the prior as the (fixed) reference pose — i.e.
exactly what `models.pgo.with_priors` encodes via virtual anchor
vertices, now without the virtual-vertex dance.  Partial-sensor priors
(GPS = position only, IMU gravity = roll/pitch only) are expressed the
standard way: a rank-deficient `sqrt_info` zeroing the unmeasured rows.

`robust_ok=False`: a prior is trusted-by-construction information —
for the marginalization priors ROADMAP item 4 retires states into,
IRLS-downweighting the factor would silently corrupt the marginal, so
the solve boundary refuses robust kernels on this family typed.

`unique_edges=False`: several priors on one pose (multi-sensor fusion)
are legitimate repeated constraints, not duplicate-factor poison.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from megba_tpu.factors.registry import FactorSpec

CAMERA_DIM = 6
POINT_DIM = 3
OBS_DIM = 6
RESIDUAL_DIM = 6


def pose_prior_residual(camera: jnp.ndarray, point: jnp.ndarray,
                        obs: jnp.ndarray) -> jnp.ndarray:  # megba: jit-entry
    """6-row unary prior residual for one edge (point block unused)."""
    from megba_tpu.ops import geo

    del point  # unary factor: the point side contributes nothing
    R_p = geo.angle_axis_to_rotation_matrix(obs[0:3])
    R_c = geo.angle_axis_to_rotation_matrix(camera[0:3])
    E_R = geo.mm(R_p.T, R_c)
    E_t = geo.mm(R_p.T, (camera[3:6] - obs[3:6])[:, None])[:, 0]
    return jnp.concatenate([geo.rotation_matrix_to_angle_axis(E_R), E_t])


SPEC = FactorSpec(
    name="pose_prior",
    cam_dim=CAMERA_DIM,
    pt_dim=POINT_DIM,
    obs_dim=OBS_DIM,
    residual_dim=RESIDUAL_DIM,
    residual_fn=pose_prior_residual,
    robust_ok=False,  # a downweighted marginalization prior is corrupt
    unique_edges=False,  # multi-sensor: several priors per pose
    point_coupled=False,
    description="unary SE(3) pose prior (GPS/IMU/marginalization): "
                "camera [aa(3), t(3)] anchored to obs [aa(3), t(3)]",
)


@dataclasses.dataclass
class SyntheticPriors:
    """A pose-estimation problem made purely of unary priors."""

    poses_gt: np.ndarray  # [N, 6]
    cameras0: np.ndarray  # perturbed initial poses
    points0: np.ndarray  # [1, 3] shared dummy point
    obs: np.ndarray  # [nE, 6] prior poses
    cam_idx: np.ndarray
    pt_idx: np.ndarray


def make_synthetic_priors(
    num_poses: int = 8,
    priors_per_pose: int = 1,
    prior_noise: float = 0.0,
    param_noise: float = 5e-2,
    seed: int = 0,
    dtype: np.dtype = np.float64,
) -> SyntheticPriors:
    """Poses on a circle, each anchored by `priors_per_pose` unary
    priors at (optionally noisy) ground truth.  With exact priors the
    optimum is the ground truth itself and the final cost is ~0 — the
    closed-form check tests/test_factors.py pins."""
    r = np.random.default_rng(seed)
    th = 2 * np.pi * np.arange(num_poses) / num_poses
    poses_gt = np.zeros((num_poses, 6))
    poses_gt[:, 2] = th
    poses_gt[:, 3] = np.cos(th)
    poses_gt[:, 4] = np.sin(th)

    cam_idx = np.tile(np.arange(num_poses), priors_per_pose)
    prior = (poses_gt[cam_idx]
             + prior_noise * r.standard_normal((cam_idx.shape[0], 6)))
    cameras0 = poses_gt + param_noise * r.standard_normal(poses_gt.shape)

    order = np.argsort(cam_idx, kind="stable")
    return SyntheticPriors(
        poses_gt=poses_gt.astype(dtype),
        cameras0=cameras0.astype(dtype),
        points0=np.zeros((1, 3), dtype),
        obs=prior[order].astype(dtype),
        cam_idx=cam_idx[order].astype(np.int32),
        pt_idx=np.zeros(cam_idx.shape[0], np.int32),
    )
