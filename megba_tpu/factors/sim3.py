"""Scale-aware sim(3) pose-graph optimization.

Monocular SLAM accumulates SCALE drift alongside rotation/translation
drift; loop closing then needs pose-graph optimization over Sim(3)
(Strasdat's "Scale Drift-Aware Large Scale Monocular SLAM" formulation,
the one ORB-SLAM's EssentialGraph uses).  This family extends the SE(3)
between-factor driver with one log-scale dof per pose:

  pose (7) = [angle-axis (3), translation (3), log-scale l]
  T x = e^l R x + t

Between residual on edge (i, j) with measurement m = expected relative
sim(3) transform T_ij = T_i^{-1} T_j:

  T_rel = (R_i^T R_j,  e^{-l_i} R_i^T (t_j - t_i),  l_j - l_i)
  E     = T_m^{-1} T_rel
  r     = [log_SO3(E_R); E_t; E_l]            (7 rows)

which reduces EXACTLY to the SE(3) between residual on the rotation and
translation rows when every scale is 1 (l = 0) — the parity anchor
tests/test_factors.py pins.  Jacobians come from forward-mode autodiff
of the exact residual, like every pose-graph family.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from megba_tpu.factors.registry import PoseFactorSpec

SIM3_DIM = 7


def sim3_between_residual(pose_i: jnp.ndarray, pose_j: jnp.ndarray,
                          meas: jnp.ndarray) -> jnp.ndarray:  # megba: jit-entry
    """7-row sim(3) between-factor residual for one edge."""
    from megba_tpu.ops import geo

    Ri = geo.angle_axis_to_rotation_matrix(pose_i[0:3])
    Rj = geo.angle_axis_to_rotation_matrix(pose_j[0:3])
    Rm = geo.angle_axis_to_rotation_matrix(meas[0:3])
    li, lj, lm = pose_i[6], pose_j[6], meas[6]
    R_rel = geo.mm(Ri.T, Rj)
    t_rel = jnp.exp(-li) * geo.mm(
        Ri.T, (pose_j[3:6] - pose_i[3:6])[:, None])[:, 0]
    E_R = geo.mm(Rm.T, R_rel)
    E_t = jnp.exp(-lm) * geo.mm(Rm.T, (t_rel - meas[3:6])[:, None])[:, 0]
    E_l = (lj - li) - lm
    return jnp.concatenate(
        [geo.rotation_matrix_to_angle_axis(E_R), E_t, E_l[None]])


SPEC = PoseFactorSpec(
    name="sim3_between",
    pose_dim=SIM3_DIM,
    meas_dim=SIM3_DIM,
    residual_dim=SIM3_DIM,
    residual_fn=sim3_between_residual,
    description="scale-aware sim(3) PGO: pose [aa(3), t(3), log-scale], "
                "error [log_SO3, t, dlog-scale]",
    # PR 13 measured finding as a DEFAULT: the reference's
    # refuse_ratio=1.0 fires on sim(3)'s first inner iteration (mixed
    # rot/trans/log-scale blocks make preconditioned rho non-monotone),
    # silently returning dx=0 and stalling LM ~10x above the optimum;
    # 16 reaches machine-zero cost in 5 LM iterations with exact scale
    # recovery.  Resolved by registry.resolve_refuse_ratio — an
    # explicit caller setting still wins.
    refuse_ratio=16.0,
)


# ---------------------------------------------------------------------------
# Host-side sim(3) chart maps (batched NumPy, mirroring core/host_se3's
# SE(3) pair) + a synthetic scale-drift pose graph.
# ---------------------------------------------------------------------------

def compose_sim3(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """T_a o T_b over [..., 7] sim(3) charts."""
    from megba_tpu.core.host_se3 import compose

    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    b6 = np.concatenate(
        [b[..., 0:3], np.exp(a[..., 6:7]) * b[..., 3:6]], axis=-1)
    se3 = compose(a[..., 0:6], b6)
    return np.concatenate([se3, a[..., 6:7] + b[..., 6:7]], axis=-1)


def relative_sim3(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """T_a^{-1} T_b over [..., 7] sim(3) charts (the measurement on an
    (a, b) edge; `sim3_between_residual(a, b, relative_sim3(a, b))` is
    identically zero — pinned by tests)."""
    from megba_tpu.core.host_se3 import relative

    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    se3 = relative(a[..., 0:6], b[..., 0:6])
    return np.concatenate(
        [se3[..., 0:3], np.exp(-a[..., 6:7]) * se3[..., 3:6],
         b[..., 6:7] - a[..., 6:7]], axis=-1)


@dataclasses.dataclass
class SyntheticSim3Graph:
    """Ground truth + scale-drifted odometry init for a loop-closed
    sim(3) graph."""

    poses_gt: np.ndarray  # [N, 7]
    poses0: np.ndarray
    edge_i: np.ndarray
    edge_j: np.ndarray
    meas: np.ndarray  # [nE, 7]


def make_synthetic_sim3_graph(
    num_poses: int = 24,
    loop_closures: int = 5,
    meas_noise: float = 0.0,
    drift_noise: float = 0.04,
    scale_drift: float = 0.02,
    seed: int = 0,
) -> SyntheticSim3Graph:
    """Circle trajectory with odometry + loop closures, monocular-style:
    the init integrates noisy odometry whose LOG-SCALE also drifts, so
    loop closures must correct rotation, translation AND scale."""
    rng = np.random.default_rng(seed)
    th = 2 * np.pi * np.arange(num_poses) / num_poses
    poses_gt = np.zeros((num_poses, SIM3_DIM))
    poses_gt[:, 2] = th
    poses_gt[:, 3] = np.cos(th)
    poses_gt[:, 4] = np.sin(th)
    poses_gt[:, 5] = 0.05 * np.sin(2 * th)
    # Ground truth carries a gentle scale wave so the scale dof is live
    # even in the noise-free measurements.
    poses_gt[:, 6] = 0.1 * np.sin(th)

    ei = list(range(num_poses - 1))
    ej = list(range(1, num_poses))
    for _ in range(loop_closures):
        a = int(rng.integers(0, num_poses - 4))
        b = int(rng.integers(a + 2, num_poses))
        ei.append(a)
        ej.append(b)
    ei, ej = np.asarray(ei, np.int32), np.asarray(ej, np.int32)

    meas = (relative_sim3(poses_gt[ei], poses_gt[ej])
            + meas_noise * rng.standard_normal((len(ei), SIM3_DIM)))

    poses0 = poses_gt.copy()
    cur = poses_gt[0].copy()
    noise = rng.standard_normal((num_poses - 1, SIM3_DIM))
    noise[:, 0:6] *= drift_noise
    noise[:, 6] *= scale_drift
    for k in range(1, num_poses):
        cur = compose_sim3(cur, meas[k - 1] + noise[k - 1])
        poses0[k] = cur
    return SyntheticSim3Graph(
        poses_gt=poses_gt, poses0=poses0, edge_i=ei, edge_j=ej, meas=meas)
