"""Multi-camera rig BA: N physical cameras sharing one body extrinsic.

The rig family models a camera CLUSTER (stereo head, surround-view car
rig, ladybug sphere): each capture has ONE optimisable body pose, and
every physical camera k on the rig is a FIXED mount extrinsic
T_mount_k composed on top of it.  In the camera/point block layout that
means the camera-side block is the shared body pose (+ the rig's
focal), and the mount rides the edge's OBSERVATION vector as a per-edge
constant — so all K cameras of a capture share one 7-wide block through
the Schur trick, and a rig problem has K edges per (body, point) pair
(hence `unique_edges=False`: repeated (cam_idx, pt_idx) pairs are how
the rig encodes its cameras, not duplicate factors).

Block layout:
  camera (7) = [body angle-axis (3), body translation (3), focal f]
  point  (3)
  obs    (8) = [u, v, mount angle-axis (3), mount translation (3)]

Projection chain (BAL minus convention, shared with the pinhole
families): X_body = R(w_b) X + t_b; X_cam = R(w_m) X_body + t_m;
p = -X_cam[:2] / X_cam[2]; r = f p - [u, v].
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from megba_tpu.factors.registry import FactorSpec, FactorTriage

CAMERA_DIM = 7
POINT_DIM = 3
OBS_DIM = 8


def rig_residual(camera: jnp.ndarray, point: jnp.ndarray,
                 obs: jnp.ndarray) -> jnp.ndarray:  # megba: jit-entry
    """2-row reprojection residual of one rig edge."""
    from megba_tpu.ops import geo

    w_b, t_b, f = camera[0:3], camera[3:6], camera[6]
    uv, w_m, t_m = obs[0:2], obs[2:5], obs[5:8]
    X_body = geo.angle_axis_rotate_point(w_b, point) + t_b
    X_cam = geo.angle_axis_rotate_point(w_m, X_body) + t_m
    p = -X_cam[0:2] / X_cam[2]
    return f * p - uv


def _rig_project_depth(cam_blocks: np.ndarray, pt_blocks: np.ndarray,
                       obs: np.ndarray):
    """Host twin of `rig_residual`'s projection, + camera-frame depth.

    The triage cheirality check needs the PHYSICAL camera's depth, so
    the mount (riding in obs) composes here exactly as on device.
    """
    from megba_tpu.io.synthetic import rotate_batch

    X_body = rotate_batch(cam_blocks[:, 0:3], pt_blocks) + cam_blocks[:, 3:6]
    X_cam = rotate_batch(obs[:, 2:5], X_body) + obs[:, 5:8]
    with np.errstate(divide="ignore", invalid="ignore"):
        p = -X_cam[:, 0:2] / X_cam[:, 2:3]
        uv = cam_blocks[:, 6:7] * p
    return uv, X_cam[:, 2]


def _rig_centers(cameras: np.ndarray) -> np.ndarray:
    """Body-frame centers C = -R_b^T t_b — the parallax proxy origin.

    The physical cameras sit within mount-baseline distance of the body
    center; for the ray-SPREAD proxy (robustness/triage.py) that offset
    is noise, so the body center stands in for all of them.
    """
    from megba_tpu.io.synthetic import camera_centers

    return camera_centers(cameras)


SPEC = FactorSpec(
    name="rig",
    cam_dim=CAMERA_DIM,
    pt_dim=POINT_DIM,
    obs_dim=OBS_DIM,
    residual_dim=2,
    residual_fn=rig_residual,
    unique_edges=False,  # K edges per (body, point): one per rig camera
    triage=FactorTriage(project_depth=_rig_project_depth, uv_cols=(0, 2),
                        camera_centers=_rig_centers),
    description="multi-camera rig BA: shared body pose [aa(3), t(3), f], "
                "per-edge mount extrinsic in obs[2:8]",
)


@dataclasses.dataclass
class SyntheticRig:
    """Ground truth + perturbed init for a synthetic rig scene."""

    cameras_gt: np.ndarray  # [Nb, 7] body blocks
    points_gt: np.ndarray  # [Np, 3]
    cameras0: np.ndarray
    points0: np.ndarray
    obs: np.ndarray  # [nE, 8]
    cam_idx: np.ndarray  # [nE] int32 (body index)
    pt_idx: np.ndarray  # [nE] int32
    mounts: np.ndarray  # [K, 6] the rig's mount extrinsics


def make_synthetic_rig(
    num_bodies: int = 4,
    num_points: int = 24,
    rig_cameras: int = 2,
    obs_per_point: int = 2,
    pixel_noise: float = 0.3,
    param_noise: float = 2e-2,
    seed: int = 0,
    dtype: np.dtype = np.float64,
) -> SyntheticRig:
    """A K-camera rig observing a point cloud from `num_bodies` poses.

    Scene convention mirrors io/synthetic.make_synthetic_bal (points in
    a unit ball, bodies at camera-frame z ~ -5 so everything is visible
    under the BAL minus projection); each observed (body, point) pair
    is seen by ALL `rig_cameras` mounts — K edges per pair, the repeat
    structure `unique_edges=False` exists for.  Observations come from
    the model itself (residual with uv = 0), so generator and residual
    cannot diverge.
    """
    r = np.random.default_rng(seed)
    obs_per_point = min(obs_per_point, num_bodies)

    points_gt = r.uniform(-1.0, 1.0, size=(num_points, 3))
    bodies_gt = np.zeros((num_bodies, 7))
    bodies_gt[:, 0:3] = r.normal(scale=0.05, size=(num_bodies, 3))
    bodies_gt[:, 3:5] = r.normal(scale=0.2, size=(num_bodies, 2))
    bodies_gt[:, 5] = -5.0 + r.normal(scale=0.2, size=num_bodies)
    bodies_gt[:, 6] = 400.0 + r.normal(scale=4.0, size=num_bodies)

    # Mount extrinsics: small rotations, ~0.3-unit baselines (a stereo
    # head / surround cluster).  Identity-mean so the composed chain
    # stays in the visible half-space.
    mounts = np.zeros((rig_cameras, 6))
    mounts[:, 0:3] = r.normal(scale=0.03, size=(rig_cameras, 3))
    mounts[:, 3:6] = r.normal(scale=0.15, size=(rig_cameras, 3))

    base = r.integers(0, num_bodies, size=(num_points, 1))
    stride = 1 + r.integers(0, max(num_bodies // max(obs_per_point, 1), 1),
                            size=(num_points, 1))
    pair_cam = ((base + np.arange(obs_per_point)[None, :] * stride)
                % num_bodies).reshape(-1)
    pair_pt = np.repeat(np.arange(num_points), obs_per_point)
    missing = np.setdiff1d(np.arange(num_bodies), pair_cam)
    if missing.size:
        pair_cam = np.concatenate([pair_cam, missing])
        pair_pt = np.concatenate(
            [pair_pt, r.integers(0, num_points, size=missing.size)])

    # Fan each (body, point) pair out over the K rig cameras.
    k_ax = np.arange(rig_cameras)
    cam_idx = np.repeat(pair_cam, rig_cameras)
    pt_idx = np.repeat(pair_pt, rig_cameras)
    mount_rows = mounts[np.tile(k_ax, pair_cam.shape[0])]

    uv, _ = _rig_project_depth(
        bodies_gt[cam_idx],
        points_gt[pt_idx],
        np.concatenate([np.zeros((cam_idx.shape[0], 2)), mount_rows],
                       axis=1))
    obs = np.concatenate(
        [uv + r.normal(scale=pixel_noise, size=uv.shape), mount_rows],
        axis=1)

    order = np.argsort(cam_idx, kind="stable")
    cameras0 = bodies_gt + r.normal(
        scale=param_noise, size=bodies_gt.shape) * np.array(
            [1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 20.0])
    points0 = points_gt + r.normal(scale=param_noise, size=points_gt.shape)
    return SyntheticRig(
        cameras_gt=bodies_gt.astype(dtype),
        points_gt=points_gt.astype(dtype),
        cameras0=cameras0.astype(dtype),
        points0=points0.astype(dtype),
        obs=obs[order].astype(dtype),
        cam_idx=cam_idx[order].astype(np.int32),
        pt_idx=pt_idx[order].astype(np.int32),
        mounts=mounts.astype(dtype),
    )
