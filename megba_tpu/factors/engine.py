"""Registry-keyed engine resolution.

One factor config must map to ONE engine object, forever: every jit
program cache in the stack (solve._cached_single_solve, the sharded
program cache, the serving compile pool, the AOT artifact keys) keys on
engine IDENTITY, so two engines for one config silently double every
trace and compile.  `engine_for` guarantees that by composing two
normalisations:

1. call-shape normalisation — the lookup rides
   `utils.memo.normalized_lru_cache` (the generalised form of PR 6's
   footgun fix on `make_residual_jacobian_fn`), so positional/keyword/
   defaulted spellings collapse;
2. mode-irrelevant-field canonicalisation — `analytical_fn` is dropped
   from the underlying engine key unless the mode actually selects it
   (AUTODIFF ignores it; keying on it anyway would make the registry's
   `bal` engine a DIFFERENT object from the historical
   `make_residual_jacobian_fn()` default — a duplicate program per
   bucket, which the bitwise-identity tests pin against).
"""

from __future__ import annotations

from typing import Union

from megba_tpu.common import JacobianMode
from megba_tpu.factors.registry import (
    FactorSpec,
    get_factor,
    require_schur,
)
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.utils.memo import normalized_lru_cache


@normalized_lru_cache(maxsize=64)
def _engine_for_spec(spec: FactorSpec, mode: JacobianMode):
    if mode == JacobianMode.ANALYTICAL:
        if spec.analytical_fn is None:
            from megba_tpu.factors.registry import FactorError

            raise FactorError(
                f"factor {spec.name!r} has no analytical Jacobian; use "
                "JacobianMode.AUTODIFF / AUTODIFF_FORWARD, or register "
                "the spec with analytical_fn")
        return make_residual_jacobian_fn(
            spec.residual_fn, mode, spec.analytical_fn)
    # Autodiff modes ignore analytical_fn: canonicalise it OUT of the
    # engine key so get_factor("bal") resolves to the IDENTICAL engine
    # object the historical make_residual_jacobian_fn() default returns
    # (same lru entry -> same jit caches -> zero duplicate programs).
    return make_residual_jacobian_fn(spec.residual_fn, mode, None)


def engine_for(factor: Union[str, FactorSpec],
               mode: JacobianMode = JacobianMode.AUTODIFF):
    """The residual+Jacobian engine of a registered factor.

    Accepts a name or a spec; raises typed `UnknownFactorError` /
    `FactorError` for unknown names, pose-graph factors (they have no
    camera/point engine) and ANALYTICAL requests on factors without a
    closed form.  Memoised: one (spec, mode) -> one engine object.
    """
    spec = require_schur(get_factor(factor), "engine_for")
    return _engine_for_spec(spec, mode)
