"""The BAL pinhole family as a registered factor spec.

The flagship family (models/bal.py), re-declared as registry data: the
spec's `residual_fn` IS `ops.residuals.bal_residual` and its
`analytical_fn` IS the hand-derived feature-major closed form, so
`engine_for("bal", mode)` resolves to the IDENTICAL engine object the
historical `make_residual_jacobian_fn(mode=...)` default returns —
byte-identical programs, zero duplicate cache entries (pinned by
tests/test_factors.py).  The triage hooks wrap the host projection twin
(io/synthetic.project_batch_depth) the pre-registry triage pass called
directly.
"""

from __future__ import annotations

import numpy as np

from megba_tpu.factors.registry import FactorSpec, FactorTriage
from megba_tpu.ops.residuals import (
    bal_residual,
    bal_residual_jacobian_analytical_fm,
)

CAMERA_DIM = 9
POINT_DIM = 3
OBS_DIM = 2


def _project_depth(cam_blocks: np.ndarray, pt_blocks: np.ndarray,
                   obs: np.ndarray):
    """Edge-gathered BAL projection + camera-frame depth (host NumPy)."""
    from megba_tpu.io.synthetic import project_batch_depth

    del obs  # the BAL projection needs no per-edge constants
    return project_batch_depth(cam_blocks, pt_blocks)


def _camera_centers(cameras: np.ndarray) -> np.ndarray:
    """C = -R^T t (the parallax check's viewing-ray origin)."""
    from megba_tpu.io.synthetic import camera_centers

    return camera_centers(cameras)


SPEC = FactorSpec(
    name="bal",
    cam_dim=CAMERA_DIM,
    pt_dim=POINT_DIM,
    obs_dim=OBS_DIM,
    residual_dim=2,
    residual_fn=bal_residual,
    analytical_fn=bal_residual_jacobian_analytical_fm,
    triage=FactorTriage(project_depth=_project_depth, uv_cols=(0, 2),
                        camera_centers=_camera_centers),
    description="BAL pinhole reprojection: camera [angle-axis(3), t(3), "
                "f, k1, k2], point (3,), obs = pixel (2,)",
)
