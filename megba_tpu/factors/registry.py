"""Factor registry: declarative specs for every residual family.

MegBA's public surface is a g2o-compatible Problem/Vertex/Edge API over
one end-to-end vectorised residual engine (arxiv 2112.01349 §3); until
this subsystem the repo hard-coded two residual families (BAL pinhole
reprojection and SE(3) between-factor PGO), each with bespoke plumbing.
The registry turns "a residual family" into DATA: a frozen spec naming
the parameter-block dims, the residual dimension, the per-edge residual
function, the optional closed-form Jacobian, and the host-side triage
hooks — and every layer of the stack dispatches through it:

- `solve.flat_solve(..., factor=)` resolves the engine via
  `factors.engine.engine_for` (all three lowerings, unchanged);
- the serving layer keys shape classes on (factor, dims, dtype), so a
  registered factor is IMMEDIATELY servable through `solve_many` /
  `FleetQueue` with zero cross-factor retraces (engine identity is in
  every program-cache key);
- pre-flight triage dispatches its geometric checks through the spec's
  hooks (cheirality only means something for projective factors);
- the ingestion gate reads `unique_edges` (a rig observes one
  (body, point) pair once per physical camera; a prior may legitimately
  repeat a constraint — neither is the duplicate-factor poison BAL
  ingestion rejects).

Two spec kinds cover the solver's two drivers: `FactorSpec` for the
camera/point (Schur) pipeline and `PoseFactorSpec` for the pose-graph
driver (two same-kind blocks, models/pgo.py).  Both are frozen and
hashable — a spec IS a cache key.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple, Union


class FactorError(ValueError):
    """Base class for registry errors (typed, caller-matchable)."""


class UnknownFactorError(FactorError):
    """A factor name no registered spec answers to.

    Raised at every dispatch boundary (`flat_solve`, `solve_pgo`,
    `solve_many`, `FleetQueue.submit`) so a typo'd factor name fails
    typed at ingestion, never as a shape error mid-lowering.
    """

    def __init__(self, name: str, known: List[str]):
        self.name = name
        self.known = list(known)
        super().__init__(
            f"unknown factor {name!r}; registered factors: "
            f"{', '.join(known) if known else '(none)'}")


class DuplicateFactorError(FactorError):
    """`register_factor` refused to overwrite an existing name.

    Silent re-registration would swap the engine behind every cache
    keyed on the old spec; pass `allow_override=True` only in tests.
    """

    def __init__(self, name: str):
        self.name = name
        super().__init__(
            f"factor {name!r} is already registered; re-registering "
            "would orphan every engine/program cached under the old "
            "spec (pass allow_override=True only if you mean it)")


@dataclasses.dataclass(frozen=True)
class FactorTriage:
    """Host-side geometric hooks for pre-flight triage (pure NumPy).

    Only PROJECTIVE factors can answer "is this point behind the
    camera" — for a factor without hooks the triage geometric pass is
    skipped entirely (structural + non-finite checks still run, and the
    HealthReport records `geometric=False` so downstream gates know the
    projective checks never happened).

    `project_depth(cam_blocks [nE, cd], pt_blocks [nE, pd],
    obs [nE, od]) -> (uv [nE, 2], depth [nE])` projects each edge's
    point through its camera — obs rides along because some factors
    (the rig) carry per-edge constants (the mount extrinsic) the
    projection needs.  `uv_cols` names the obs columns holding the
    measured pixel, for the extreme-residual check.  `camera_centers
    (cameras [Nc, cd]) -> [Nc, 3]` is optional; without it the
    low-parallax check is skipped (it needs 3D viewing rays).
    """

    project_depth: Callable  # (cams, pts, obs) -> (uv, depth)
    uv_cols: Tuple[int, int] = (0, 2)  # obs[:, lo:hi] = measured pixel
    camera_centers: Optional[Callable] = None  # (cameras) -> [Nc, 3]


@dataclasses.dataclass(frozen=True)
class FactorSpec:
    """One camera/point (Schur-pipeline) residual family.

    The engine contract is the one `ops/residuals.py` has always had:
    `residual_fn(camera [cam_dim], point [pt_dim], obs [obs_dim]) ->
    r [residual_dim]` for ONE edge, vectorised over the minor edge axis
    by the engine builder; `analytical_fn`, when present, is the
    feature-major closed form ((cam [cd, nE], pt [pd, nE],
    obs [od, nE]) -> (r, Jc, Jp) row layout) selected by
    `JacobianMode.ANALYTICAL`.

    `obs_dim` and `residual_dim` are independent: obs is the per-edge
    CONSTANT vector (a rig edge carries its mount extrinsic there, a
    prior edge its prior pose), residual_dim is the row count of r —
    `sqrt_info` weights are [residual_dim, residual_dim] per edge.

    `robust_ok=False` marks families whose residual is not a
    reprojection-style error where IRLS reweighting is meaningful
    (validated at solve time).  `unique_edges=False` lifts the
    duplicate-(cam_idx, pt_idx) ingestion refusal — repeated index
    pairs are how rigs (one pair per physical camera) and repeated
    priors encode legitimate factors.  `point_coupled=False` declares
    the residual ignores the point block (unary camera factors): the
    point side assembles to identity Hessian blocks and the Schur trick
    degenerates gracefully.
    """

    name: str
    cam_dim: int
    pt_dim: int
    obs_dim: int
    residual_dim: int
    residual_fn: Callable
    analytical_fn: Optional[Callable] = None
    robust_ok: bool = True
    unique_edges: bool = True
    point_coupled: bool = True
    triage: Optional[FactorTriage] = None
    description: str = ""
    # Per-factor PCG refuse_ratio default (None = the SolverOption
    # class default applies).  A family whose block structure makes
    # the preconditioned residual energy legitimately NON-monotone
    # names its own guard band here, so callers need not know the
    # stall exists — see `resolve_refuse_ratio`.
    refuse_ratio: Optional[float] = None

    kind = "schur"

    def __post_init__(self) -> None:
        for f in ("cam_dim", "pt_dim", "obs_dim", "residual_dim"):
            if getattr(self, f) < 1:
                raise FactorError(
                    f"factor {self.name!r}: {f} must be >= 1, "
                    f"got {getattr(self, f)}")


@dataclasses.dataclass(frozen=True)
class PoseFactorSpec:
    """One pose-graph (two same-kind blocks) residual family.

    Drives the PGO pipeline (models/pgo.py): `residual_fn(pose_i
    [pose_dim], pose_j [pose_dim], meas [meas_dim]) ->
    r [residual_dim]` for one edge; Jacobians come from forward-mode
    autodiff of the exact residual, exactly as the SE(3) family always
    has.  `sqrt_info` weights are [residual_dim, residual_dim].
    """

    name: str
    pose_dim: int
    meas_dim: int
    residual_dim: int
    residual_fn: Callable
    description: str = ""
    # Per-factor PCG refuse_ratio default — the PR 13 measured finding
    # institutionalised: the reference's refuse_ratio=1.0 stalls 7-dof
    # sim(3) inner solves on their FIRST iteration (mixed rot/trans/
    # log-scale blocks make preconditioned rho non-monotone, the refuse
    # guard restores dx=0 and LM flatlines ~10x above the optimum);
    # the sim3 spec declares 16.0 so the DEFAULT configuration solves,
    # instead of requiring every caller to rediscover the stall.
    refuse_ratio: Optional[float] = None

    kind = "pose_graph"

    def __post_init__(self) -> None:
        for f in ("pose_dim", "meas_dim", "residual_dim"):
            if getattr(self, f) < 1:
                raise FactorError(
                    f"factor {self.name!r}: {f} must be >= 1, "
                    f"got {getattr(self, f)}")


AnySpec = Union[FactorSpec, PoseFactorSpec]

_REGISTRY: Dict[str, AnySpec] = {}


def register_factor(spec: AnySpec, allow_override: bool = False) -> AnySpec:
    """Register a factor spec under its name; returns the spec.

    Refuses duplicates (typed `DuplicateFactorError`) unless
    `allow_override=True`: the registry is process-global and every
    engine/program cache keys on spec identity, so silently swapping a
    name would leave stale engines serving the old physics.
    """
    if not isinstance(spec, (FactorSpec, PoseFactorSpec)):
        raise FactorError(
            f"register_factor wants a FactorSpec or PoseFactorSpec, "
            f"got {type(spec).__name__}")
    if spec.name in _REGISTRY and not allow_override:
        raise DuplicateFactorError(spec.name)
    _REGISTRY[spec.name] = spec
    return spec


def unregister_factor(name: str) -> None:
    """Remove a registration (test helper; pairs with allow_override)."""
    _REGISTRY.pop(name, None)


def get_factor(name_or_spec: Union[str, AnySpec]) -> AnySpec:
    """Resolve a factor by name (typed `UnknownFactorError` on a miss);
    specs pass through unchanged so call sites accept either."""
    if isinstance(name_or_spec, (FactorSpec, PoseFactorSpec)):
        return name_or_spec
    spec = _REGISTRY.get(name_or_spec)
    if spec is None:
        raise UnknownFactorError(str(name_or_spec), sorted(_REGISTRY))
    return spec


def list_factors() -> Dict[str, AnySpec]:
    """Snapshot of the registry (name -> spec), registration-stable."""
    return dict(_REGISTRY)


def require_schur(spec: AnySpec, where: str) -> FactorSpec:
    """Typed refusal when a pose-graph factor reaches the Schur
    pipeline (`flat_solve`/`solve_many` cannot solve it — the blocks
    are same-kind; point the caller at `solve_pgo`)."""
    if spec.kind != "schur":
        raise FactorError(
            f"{where}: factor {spec.name!r} is a pose-graph family "
            "(two same-kind blocks); solve it with "
            "megba_tpu.models.pgo.solve_pgo(factor=...), not the "
            "camera/point Schur pipeline")
    return spec  # type: ignore[return-value]


def require_pose_graph(spec: AnySpec, where: str) -> PoseFactorSpec:
    """Typed refusal when a Schur factor reaches the PGO driver."""
    if spec.kind != "pose_graph":
        raise FactorError(
            f"{where}: factor {spec.name!r} is a camera/point (Schur) "
            "family; solve it with megba_tpu.solve.flat_solve / "
            "solve_many(factor=...), not the pose-graph driver")
    return spec  # type: ignore[return-value]


def resolve_refuse_ratio(spec: AnySpec, solver_option) -> float:
    """The effective PCG refuse_ratio for a solve of `spec`.

    The factor's declared default (`spec.refuse_ratio`) applies exactly
    when the caller left `SolverOption.refuse_ratio` at its CLASS
    default (the reference's 1.0) — an explicitly configured value
    always wins, including an explicit 1.0-via-replace (indistinguish-
    able from the default by design: the class default IS the
    reference semantics, and a caller who needs literal 1.0 on a
    factor that declares its own band is overriding a measured stall —
    they can pass 1.0 + epsilon or any other value to make the intent
    unambiguous).  Factors with no declared default change nothing.
    """
    declared = getattr(spec, "refuse_ratio", None)
    if declared is None:
        return solver_option.refuse_ratio
    from megba_tpu.common import SolverOption

    class_default = dataclasses.fields(SolverOption)
    default_value = next(f.default for f in class_default
                         if f.name == "refuse_ratio")
    if solver_option.refuse_ratio == default_value:
        return float(declared)
    return solver_option.refuse_ratio


def apply_factor_solver_defaults(spec: AnySpec, option):
    """Fold a factor's solver defaults into a ProblemOption.

    Returns the option unchanged (same OBJECT — jit/program caches keep
    their keys) when nothing resolves differently; otherwise a
    dataclasses.replace'd copy.  Called by the driver seams
    (models/pgo.solve_pgo, solve.flat_solve) after the spec resolves.
    """
    rr = resolve_refuse_ratio(spec, option.solver_option)
    if rr == option.solver_option.refuse_ratio:
        return option
    return dataclasses.replace(
        option, solver_option=dataclasses.replace(
            option.solver_option, refuse_ratio=rr))


def validate_factor_arrays(spec: FactorSpec, cameras, points, obs,
                           where: str = "flat_solve") -> None:
    """Typed dim check: the arrays' feature widths must match the spec.

    Catching a (cd, pd, od) mismatch HERE names the factor and the
    offending axis; letting it through surfaces as an opaque reshape
    error deep inside the engine vmap.
    """
    got = (int(cameras.shape[1]), int(points.shape[1]), int(obs.shape[1]))
    want = (spec.cam_dim, spec.pt_dim, spec.obs_dim)
    if got != want:
        axes = ("cameras", "points", "obs")
        bad = ", ".join(
            f"{axes[k]} width {got[k]} (factor wants {want[k]})"
            for k in range(3) if got[k] != want[k])
        raise FactorError(
            f"{where}: arrays do not match factor {spec.name!r}: {bad}")
