"""SE(3) between-factor PGO as a registered pose-graph factor.

The historical PGO family (models/pgo.py), re-declared as registry
data.  The spec's `residual_fn` IS `models.pgo.between_residual`, so
`solve_pgo(factor="se3_between")` — the default — traces the exact
program the pre-registry driver traced (byte-identical lowering pinned
by tests/test_factors.py; the `pgo_*` audit budgets are unchanged).
"""

from __future__ import annotations

from megba_tpu.factors.registry import PoseFactorSpec
from megba_tpu.models.pgo import POSE_DIM, between_residual

SPEC = PoseFactorSpec(
    name="se3_between",
    pose_dim=POSE_DIM,
    meas_dim=POSE_DIM,
    residual_dim=POSE_DIM,
    residual_fn=between_residual,
    description="SE(3) between-factor PGO: pose [aa(3), t(3)], "
                "right-invariant error [log_SO3, t]",
)
