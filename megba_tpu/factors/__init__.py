"""Pluggable factor registry — the multi-model residual engine.

Import this package and the built-in families are registered:

Schur (camera/point) families, solved by `solve.flat_solve` /
`serving.solve_many` / `serving.FleetQueue`:

  - ``bal``            BAL pinhole (9/3/2) — the flagship
  - ``planar``         SE(2) planar BA (4/2/1)
  - ``rig``            multi-camera rig, shared body extrinsic (7/3/8)
  - ``pinhole_radial`` full-intrinsics radial pinhole (12/3/2)
  - ``pose_prior``     GPS/IMU/marginalization unary SE(3) prior (6/3/6)

Pose-graph families, solved by `models.pgo.solve_pgo`:

  - ``se3_between``    SE(3) between-factor PGO (6-dof)
  - ``sim3_between``   scale-aware sim(3) PGO (7-dof)

Registering your own (see README "Registering a custom factor"): write
a per-edge residual function, wrap it in a `FactorSpec`, call
`register_factor` — the engine, all three flat_solve lowerings, the
fleet serving tier (shape classes key on (factor, dims, dtype)), triage
and telemetry all dispatch through the spec with zero further wiring.
"""

from megba_tpu.factors.engine import engine_for
from megba_tpu.factors.registry import (
    DuplicateFactorError,
    FactorError,
    FactorSpec,
    FactorTriage,
    PoseFactorSpec,
    UnknownFactorError,
    get_factor,
    list_factors,
    register_factor,
    unregister_factor,
    validate_factor_arrays,
)

# ---- built-in registrations (import order = table order above) ----------
from megba_tpu.factors import bal as _bal
from megba_tpu.factors import planar as _planar
from megba_tpu.factors import rig as _rig
from megba_tpu.factors import radial as _radial
from megba_tpu.factors import priors as _priors
from megba_tpu.factors import pose_graph as _pose_graph
from megba_tpu.factors import sim3 as _sim3

for _spec in (_bal.SPEC, _planar.SPEC, _rig.SPEC, _radial.SPEC,
              _priors.SPEC, _pose_graph.SPEC, _sim3.SPEC):
    # Idempotent: a re-imported package (importlib.reload in tests)
    # must not trip its own duplicate refusal.
    register_factor(_spec, allow_override=True)

__all__ = [
    "DuplicateFactorError",
    "FactorError",
    "FactorSpec",
    "FactorTriage",
    "PoseFactorSpec",
    "UnknownFactorError",
    "engine_for",
    "get_factor",
    "list_factors",
    "register_factor",
    "unregister_factor",
    "validate_factor_arrays",
]
