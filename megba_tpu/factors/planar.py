"""The planar SE(2) family as a registered factor spec.

models/planar.py proved the solver stack dimension-generic; registering
it costs three lines and makes the family servable through the fleet.
No triage hooks: the 1-D image-line projection has no cheirality
half-space in the BAL sense, so the geometric triage pass is skipped
for planar problems (structural checks still run).
"""

from __future__ import annotations

from megba_tpu.factors.registry import FactorSpec
from megba_tpu.models.planar import CAMERA_DIM, OBS_DIM, POINT_DIM, residual

SPEC = FactorSpec(
    name="planar",
    cam_dim=CAMERA_DIM,
    pt_dim=POINT_DIM,
    obs_dim=OBS_DIM,
    residual_dim=1,
    residual_fn=residual,
    description="planar (2D) BA: camera [theta, tx, ty, f], point (2,), "
                "obs = 1-D image coordinate",
)
