"""SolveReport: a structured, machine-readable record of one solve.

One JSON-round-trippable object per solve: problem shape, the full
`ProblemOption` configuration, backend/device topology, per-phase wall
clock (utils/timing.PhaseTimer), device memory stats when the backend
exposes them (utils/meminfo), the final result scalars, and the
materialized on-device convergence trace (trace.SolveTrace).

The sink is opt-in JSONL: `MEGBA_TELEMETRY=<path>` (or the `telemetry`
knob on `ProblemOption`) appends one line per `flat_solve` call — for a
checkpointed solve that is one line per CHUNK, each carrying that
chunk's own iterations/costs/trace (preemption forensics; the stitched
whole-solve trace lives on the returned `LMResult.trace`).  `python -m
megba_tpu.observability.summarize <path>` renders them.  When telemetry
is off this module is never imported (the package `__init__` loads it
lazily and solve.py gates the import on the knob) — the hot path pays
nothing.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import time
from typing import Any, Dict, Optional

import numpy as np

# Schema v2 (PR 16): adds request-scoped identity — `trace_id`/`span_id`
# (the active span context when MEGBA_TRACE is armed) and `worker` (the
# MEGBA_FEDERATION_WORKER tag, promoted from the fleet block to a
# first-class field so multi-worker JSONL aggregation doesn't need to
# dig).  All three are optional and `from_json` filters to known fields,
# so v1 lines load unchanged (MIGRATION.md notes the bump).
SCHEMA = "megba_tpu.solve_report/v2"


def _status_name(code) -> str:
    from megba_tpu.common import status_name

    return status_name(code)


def config_to_dict(option) -> Dict[str, Any]:
    """Serialize an option dataclass tree to plain JSON types.

    Enums become their names, dtypes their numpy names, nested option
    dataclasses nested dicts — generic over the option structs so a new
    field can never silently vanish from reports.
    """
    def conv(v):
        if dataclasses.is_dataclass(v) and not isinstance(v, type):
            return {f.name: conv(getattr(v, f.name))
                    for f in dataclasses.fields(v)}
        if isinstance(v, enum.Enum):
            return v.name
        if isinstance(v, (np.integer, np.floating, np.bool_)):
            return v.item()
        if isinstance(v, (np.dtype, type)):
            return np.dtype(v).name
        return v

    return conv(option)


def backend_topology() -> Dict[str, Any]:
    """Backend + device/process topology of THIS run."""
    import jax

    devices = jax.devices()
    kinds = sorted({d.device_kind for d in devices})
    return {
        "backend": jax.default_backend(),
        "device_count": len(devices),
        "local_device_count": len(jax.local_devices()),
        "device_kinds": kinds,
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
    }


@dataclasses.dataclass
class SolveReport:
    """One solve's telemetry record; `to_json`/`from_json` round-trip."""

    problem: Dict[str, Any]  # num_cameras / num_points / num_edges / ...
    config: Dict[str, Any]  # serialized ProblemOption
    backend: Dict[str, Any]  # platform + device/process topology
    phases: Dict[str, Any]  # PhaseTimer.as_dict(): name -> {total_s, calls}
    result: Dict[str, Any]  # final scalars (costs, iterations, ...)
    trace: Optional[Dict[str, list]] = None  # trace.trace_to_dict output
    memory: Optional[Dict[str, Any]] = None  # utils.meminfo.device_memory_stats
    # Optional compiled-program audit context (analysis/program_audit):
    # a producer-defined JSONable dict so a report line carries the
    # static story next to the runtime one.  bench.py's
    # MEGBA_BENCH_AUDIT=1 lane embeds {"backend", "x64", "gate",
    # "programs": {name: ProgramAudit.summary(), ...}} (or {"backend",
    # "error"} when the audit itself failed).
    program_audit: Optional[Dict[str, Any]] = None
    # Optional serving-layer context (serving/batcher.py): bucket/lane
    # placement, batch latency and a FleetStats snapshot for reports
    # emitted by `solve_many` / `FleetQueue` — the fields the
    # `summarize --aggregate` fleet view keys on.
    fleet: Optional[Dict[str, Any]] = None
    # Optional elastic-distribution context (robustness/elastic.py): a
    # snapshot of one rank's ElasticMonitor ledger — workers lost,
    # collective timeouts, reshards, resumes, time-to-detection samples,
    # keyed by a `monitor` id so the aggregate view can take the LAST
    # snapshot per monitor and sum across monitors without double
    # counting (chunked solves emit one snapshot per chunk).
    elastic: Optional[Dict[str, Any]] = None
    # Optional federation context (serving/federation.py): a
    # FederationStats snapshot — per-worker problem counts, steals,
    # reroutes, worker-lost events and cold-start (artifact-load vs
    # compile) timings — keyed by a `router` id so the aggregate view
    # can take the LAST snapshot per router without double counting.
    # Emitted once per router lifetime by `append_federation_report`.
    federation: Optional[Dict[str, Any]] = None
    # Optional pre-flight triage context (robustness/triage.py): the
    # HealthReport dict of this solve's problem — findings by kind,
    # component count, the action taken and (after REPAIR) the repair
    # counters the `summarize --aggregate` triage view sums.  REJECTED
    # problems never emit a report (zero dispatch): their count rides
    # the fleet stats embedded in later reports, like sheds.
    health: Optional[Dict[str, Any]] = None
    # Request-scoped identity (schema v2, observability plane): the
    # active trace/span context this solve ran under (None when tracing
    # is off) and the federation worker id that produced the line (None
    # outside a worker process).  Lets `summarize --fleet` stitch one
    # fleet solve's reports across N worker JSONL files and lets the
    # trace-event export cross-reference report lines by span id.
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    worker: Optional[str] = None
    # Optional tile-plan metrics (solve.flat_solve): the streaming
    # reuse_factor / tile-occupancy statistics of the planned edge
    # stream — and, under SolverOption.fused_kernels, the per-direction
    # fused bucket-plan summaries — so a fused-kernel win (or the lack
    # of one on a reuse-poor scene) is attributable per solve.  None on
    # the non-tiled lowerings and on pre-existing report lines.
    tiles: Optional[Dict[str, Any]] = None
    schema: str = SCHEMA
    created_unix: float = 0.0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, line: str) -> "SolveReport":
        d = json.loads(line)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


def _decode_fallback_totals(trace, iterations: int) -> Optional[Dict[str, Any]]:
    """Sum the enum-coded per-iteration precond_fallback codes into
    per-level totals; None without a trace.

    'block' = total SCHUR_DIAG camera blocks fallen back to Hpp;
    'coarse' = iterations where ANY hierarchy coarse level degraded
    (for two-level traces this is exactly the historical 0/1-per-iter
    sum); 'coarse_levels' (present only when a coarse degrade
    occurred) = per-hierarchy-level iteration counts, index l-1 =
    coarse level l — the multilevel bit-field, unpacked."""
    if trace is None or getattr(trace, "precond_fallback", None) is None:
        return None
    from megba_tpu.solver.precond import (
        decode_precond_fallback,
        decode_precond_fallback_levels,
    )

    block = coarse = 0
    per_level: list = []
    for code in np.asarray(trace.precond_fallback)[:iterations]:
        code = int(code)
        block += decode_precond_fallback(code)["block"]
        levels = decode_precond_fallback_levels(code)
        if any(levels):
            coarse += 1
        for i, flag in enumerate(levels):
            while len(per_level) <= i:
                per_level.append(0)
            per_level[i] += int(flag)
    out: Dict[str, Any] = {"block": int(block), "coarse": int(coarse)}
    if any(per_level):
        out["coarse_levels"] = per_level
    return out


def build_report(option, result, phases: Dict[str, Any],
                 problem: Dict[str, Any],
                 audit: Optional[Dict[str, Any]] = None,
                 fleet: Optional[Dict[str, Any]] = None,
                 elastic: Optional[Dict[str, Any]] = None,
                 health: Optional[Dict[str, Any]] = None,
                 tiles: Optional[Dict[str, Any]] = None) -> SolveReport:
    """Assemble a SolveReport from a finished solve.

    `result` is an LMResult (trace included when the solve populated
    one); this call materializes the trace and result scalars, so the
    caller must be prepared for the implied device sync — telemetry-off
    paths never get here.  `audit` optionally attaches a compiled-
    program audit summary (analysis/program_audit) for the dispatched
    configuration.
    """
    from megba_tpu.observability.trace import trace_to_dict
    from megba_tpu.utils.meminfo import device_memory_stats

    iterations = int(result.iterations)
    trace = getattr(result, "trace", None)
    result_block = {
            "initial_cost": float(result.initial_cost),
            "final_cost": float(result.cost),
            "iterations": iterations,
            "accepted": int(result.accepted),
            "pcg_iterations": int(result.pcg_iterations),
            "region": float(result.region),
            "stopped": bool(result.stopped),
            # Termination semantics (robustness layer): the status CODE
            # and its name, plus the contained-recovery count — the
            # fields an alerting pipeline keys on.
            "status": (None if getattr(result, "status", None) is None
                       else int(result.status)),
            "status_name": (
                None if getattr(result, "status", None) is None
                else _status_name(result.status)),
            "recoveries": (
                None if getattr(result, "recoveries", None) is None
                else int(result.recoveries)),
            # Per-LEVEL preconditioner fallback totals decoded from the
            # trace's enum-coded per-iteration counts (solver/precond):
            # "block" = SCHUR_DIAG camera blocks fallen back to Hpp,
            # "coarse" = two-level coarse factors degraded to
            # block-Jacobi.  None without a trace.
            "precond_fallback": _decode_fallback_totals(trace, iterations),
    }
    span_ctx = None
    from megba_tpu import observability as _obs

    recorder = _obs.span_recorder()
    if recorder is not None:
        span_ctx = recorder.context()
    return SolveReport(
        problem=problem,
        config=config_to_dict(option),
        backend=backend_topology(),
        phases=phases,
        result=result_block,
        trace=None if trace is None else trace_to_dict(trace, iterations),
        memory=device_memory_stats(),
        program_audit=audit,
        fleet=fleet,
        elastic=elastic,
        health=health,
        tiles=tiles,
        trace_id=None if span_ctx is None else span_ctx["trace_id"],
        span_id=None if span_ctx is None else span_ctx["span_id"],
        worker=os.environ.get("MEGBA_FEDERATION_WORKER") or None,
        created_unix=time.time(),
    )


def append_report(report: SolveReport, path: str) -> None:
    """Append one report as a JSONL line (creates parent dirs)."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "a") as fh:
        fh.write(report.to_json() + "\n")
