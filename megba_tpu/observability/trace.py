"""SolveTrace: on-device per-iteration convergence history.

Fixed-size `[max_iter]` arrays carried through the jitted LM
`lax.while_loop` (algo/lm.py) and written with one `.at[k].set` per
field per iteration — a handful of scalar dynamic-update-slices, so the
trace adds no host callbacks, no extra dispatches, and works unchanged
under `shard_map` (every recorded value is already replicated: costs and
gradients are psum-reduced, the trust-region state is carried
replicated).  Entries at indices >= `LMResult.iterations` are the unused
tail of the fixed-size buffers; `trace_to_dict` masks them off when the
trace is materialized for a report.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

# Field order is the serialization order everywhere (reports, snapshots).
TRACE_FIELDS = (
    "cost",
    "grad_inf_norm",
    "trust_region",
    "rho",
    "accept",
    "pcg_iters",
    "pcg_eta",
    "pcg_r0_ratio",
    "recovery",
    "pcg_breakdown",
    "precond_fallback",
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SolveTrace:
    """Per-iteration LM history, shaped [max_iter] and masked by k.

    `cost` is the TRIAL cost of each iteration (the value the verbose
    line prints — on reject the carried cost stays put, but the trial is
    the convergence observable); `grad_inf_norm` is ||g||_inf of the
    system the iteration ends with; `trust_region` is the region the
    step was computed with; `rho` the gain ratio; `accept` the
    accept/reject decision; `pcg_iters` the inner-solver iterations.
    """

    cost: jax.Array  # [max_iter] float
    grad_inf_norm: jax.Array  # [max_iter] float
    trust_region: jax.Array  # [max_iter] float
    rho: jax.Array  # [max_iter] float
    accept: jax.Array  # [max_iter] bool
    pcg_iters: jax.Array  # [max_iter] int32
    # Inexact-LM observables: the norm-relative forcing tolerance eta_k
    # the iteration's PCG ran with (the static tol when forcing is off),
    # and the warm-start initial-residual ratio |rho0| / <b, M^-1 b>
    # (1.0 on a cold start — see solver/pcg.PCGResult.r0_ratio).
    pcg_eta: jax.Array  # [max_iter] float
    pcg_r0_ratio: jax.Array  # [max_iter] float
    # Robustness observables (megba_tpu/robustness/): whether the
    # iteration was a contained fault recovery (rollback + damping
    # inflation), how many in-loop cold restarts the PCG breakdown
    # guard performed, and how many Schur-diagonal preconditioner
    # blocks fell back to Hpp after a Cholesky NaN.  All zero-filled
    # when guards are off / the HPP preconditioner is in use.
    recovery: jax.Array  # [max_iter] bool
    pcg_breakdown: jax.Array  # [max_iter] int32
    precond_fallback: jax.Array  # [max_iter] int32

    @classmethod
    def empty(cls, max_iter: int, dtype) -> "SolveTrace":
        """Zero-initialised buffers for a solve of <= max_iter iterations."""
        return cls(
            cost=jnp.zeros((max_iter,), dtype),
            grad_inf_norm=jnp.zeros((max_iter,), dtype),
            trust_region=jnp.zeros((max_iter,), dtype),
            rho=jnp.zeros((max_iter,), dtype),
            accept=jnp.zeros((max_iter,), jnp.bool_),
            pcg_iters=jnp.zeros((max_iter,), jnp.int32),
            pcg_eta=jnp.zeros((max_iter,), dtype),
            pcg_r0_ratio=jnp.zeros((max_iter,), dtype),
            recovery=jnp.zeros((max_iter,), jnp.bool_),
            pcg_breakdown=jnp.zeros((max_iter,), jnp.int32),
            precond_fallback=jnp.zeros((max_iter,), jnp.int32),
        )

    def record(self, k, *, cost, grad_inf_norm, trust_region, rho, accept,
               pcg_iters, pcg_eta=None, pcg_r0_ratio=None, recovery=None,
               pcg_breakdown=None, precond_fallback=None) -> "SolveTrace":
        """Write iteration k's observables; returns the updated trace.

        The trailing keyword fields default to None for callers that
        predate them (their buffers keep the zero fill) — and the
        robustness fields stay None in guard-off programs so arming the
        guards is the only thing that adds their update ops."""
        if self.cost.shape[0] == 0:
            # max_iter=0 programs (the checkpointed driver's evaluate-only
            # chunk) still TRACE the loop body; indexing a size-0 buffer
            # would raise at trace time even though the body never runs.
            return self
        return SolveTrace(
            cost=self.cost.at[k].set(cost),
            grad_inf_norm=self.grad_inf_norm.at[k].set(grad_inf_norm),
            trust_region=self.trust_region.at[k].set(trust_region),
            rho=self.rho.at[k].set(rho),
            accept=self.accept.at[k].set(accept),
            pcg_iters=self.pcg_iters.at[k].set(pcg_iters),
            pcg_eta=(self.pcg_eta if pcg_eta is None
                     else self.pcg_eta.at[k].set(pcg_eta)),
            pcg_r0_ratio=(self.pcg_r0_ratio if pcg_r0_ratio is None
                          else self.pcg_r0_ratio.at[k].set(pcg_r0_ratio)),
            recovery=(self.recovery if recovery is None
                      else self.recovery.at[k].set(recovery)),
            pcg_breakdown=(self.pcg_breakdown if pcg_breakdown is None
                           else self.pcg_breakdown.at[k].set(pcg_breakdown)),
            precond_fallback=(
                self.precond_fallback if precond_fallback is None
                else self.precond_fallback.at[k].set(precond_fallback)),
        )


# Host-side dtypes of the non-float fields (empty concats and fillers
# must not silently degrade accept/pcg_iters to float64).
_FIELD_DTYPES = {"accept": np.bool_, "pcg_iters": np.int32,
                 "recovery": np.bool_, "pcg_breakdown": np.int32,
                 "precond_fallback": np.int32}


def trace_slice(trace: SolveTrace, n: int) -> SolveTrace:
    """First n iterations as host numpy (drops the unused tail)."""
    return SolveTrace(**{
        f: np.asarray(getattr(trace, f))[:n] for f in TRACE_FIELDS})


def trace_filler(n: int) -> SolveTrace:
    """n iterations of inert history (NaN costs, no accepts, 0 PCG).

    Used when a checkpointed solve resumes a snapshot written before
    traces existed: the pre-resume iterations are unknowable, but the
    stitched trace must still line up index-for-index with
    `LMResult.iterations` so the `[:iterations]` masking contract holds.
    """
    return SolveTrace(
        cost=np.full((n,), np.nan),
        grad_inf_norm=np.full((n,), np.nan),
        trust_region=np.full((n,), np.nan),
        rho=np.full((n,), np.nan),
        accept=np.zeros((n,), np.bool_),
        pcg_iters=np.zeros((n,), np.int32),
        pcg_eta=np.full((n,), np.nan),
        pcg_r0_ratio=np.full((n,), np.nan),
        recovery=np.zeros((n,), np.bool_),
        pcg_breakdown=np.zeros((n,), np.int32),
        precond_fallback=np.zeros((n,), np.int32),
    )


def trace_concat(parts: Sequence[SolveTrace]) -> SolveTrace:
    """Concatenate per-chunk traces (host numpy) into one solve history.

    The chunked/checkpointed drivers slice each chunk's trace to the
    iterations it actually ran and stitch the chunks back together so a
    resumed solve reports the SAME trace a straight run would.
    """
    return SolveTrace(**{
        f: np.concatenate([np.asarray(getattr(p, f)) for p in parts])
        if parts else np.zeros((0,), _FIELD_DTYPES.get(f, np.float64))
        for f in TRACE_FIELDS})


def trace_to_dict(trace: SolveTrace, iterations: int) -> Dict[str, List]:
    """Materialize the first `iterations` entries as plain Python lists.

    This is the ONLY host transfer in the trace pipeline; it runs in
    telemetry/report code, never inside the solve.
    """
    out: Dict[str, List] = {}
    for f in TRACE_FIELDS:
        a = np.asarray(getattr(trace, f))[:iterations]
        out[f] = [bool(x) if a.dtype == np.bool_ else
                  int(x) if np.issubdtype(a.dtype, np.integer) else float(x)
                  for x in a]
    return out
