"""On-device convergence telemetry, structured solve reports, profiling.

Three pillars (none of which the reference has — its only observability
is a wall-clock print per LM iteration, lm_algo.cu:141-162):

- `trace.SolveTrace`: fixed-size per-iteration history arrays carried
  THROUGH the jitted `lax.while_loop` (algo/lm.py) and returned as
  `LMResult.trace` — captured entirely on-device, zero extra host
  round trips, identical under `shard_map` and multi-process meshes.
- `report.SolveReport`: a structured, JSON-round-trippable record of one
  solve (problem shape, config, backend topology, per-phase wall clock,
  memory stats, the materialized trace) with an opt-in JSONL sink
  (`MEGBA_TELEMETRY=<path>` or `ProblemOption.telemetry`).
- `summarize`: a CLI (`python -m megba_tpu.observability.summarize`)
  rendering recorded reports as convergence tables + phase breakdowns.

`emit` is the single home of all human-readable solver output (the
verbose per-iteration line and the problem-stats block), so stdout and
telemetry can never drift apart.

This `__init__` stays import-light on purpose: `report` and `summarize`
load lazily, so a telemetry-off solve never imports the sink machinery
(tested by tests/test_observability.py).
"""

from megba_tpu.observability.emit import (
    emit_problem_stats,
    emit_verbose_iteration,
    next_verbose_token,
)
from megba_tpu.observability.trace import SolveTrace, trace_to_dict

__all__ = [
    "SolveReport",
    "SolveTrace",
    "append_report",
    "build_report",
    "emit_problem_stats",
    "emit_verbose_iteration",
    "next_verbose_token",
    "trace_to_dict",
]

_LAZY = {"SolveReport", "append_report", "build_report"}


def __getattr__(name):
    # Sink machinery loads on first use, not at package import: the
    # telemetry-off hot path must not pay for (or even import) it.
    if name in _LAZY:
        from megba_tpu.observability import report

        return getattr(report, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
