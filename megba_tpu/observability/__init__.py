"""On-device convergence telemetry, structured solve reports, profiling.

Three pillars (none of which the reference has — its only observability
is a wall-clock print per LM iteration, lm_algo.cu:141-162):

- `trace.SolveTrace`: fixed-size per-iteration history arrays carried
  THROUGH the jitted `lax.while_loop` (algo/lm.py) and returned as
  `LMResult.trace` — captured entirely on-device, zero extra host
  round trips, identical under `shard_map` and multi-process meshes.
- `report.SolveReport`: a structured, JSON-round-trippable record of one
  solve (problem shape, config, backend topology, per-phase wall clock,
  memory stats, the materialized trace) with an opt-in JSONL sink
  (`MEGBA_TELEMETRY=<path>` or `ProblemOption.telemetry`).
- `summarize`: a CLI (`python -m megba_tpu.observability.summarize`)
  rendering recorded reports as convergence tables + phase breakdowns.

`emit` is the single home of all human-readable solver output (the
verbose per-iteration line and the problem-stats block), so stdout and
telemetry can never drift apart.

The observability PLANE (PR 16) adds three service-tier pillars, all
host-side and all off by default:

- `metrics`: process-local counter/gauge/histogram registry with
  Prometheus text exposition + JSON snapshots (`MEGBA_METRICS=1` or
  `ProblemOption.metrics=True`; `FleetRouter.metrics_snapshot()` merges
  worker snapshots over the RPC).
- `spans`: request-scoped spans with trace/span ids propagated in the
  router->worker RPC frames, exported as Chrome/Perfetto trace-event
  JSON (`MEGBA_TRACE=<path>`).
- `flight`: a bounded ring-buffer flight recorder dumped on worker
  death/crash (`MEGBA_FLIGHT=<path>`).

Consumers go through the three `*_registry`/`*_recorder` gate functions
below: an env-dict lookup when the plane is off, a lazy import when on.

This `__init__` stays import-light on purpose: `report`, `summarize`,
`metrics`, `spans` and `flight` load lazily, so a telemetry-off solve
never imports the sink machinery (tested by tests/test_observability.py).
"""

import os

from megba_tpu.observability.emit import (
    emit_problem_stats,
    emit_verbose_iteration,
    next_verbose_token,
)
from megba_tpu.observability.trace import SolveTrace, trace_to_dict

__all__ = [
    "SolveReport",
    "SolveTrace",
    "append_report",
    "build_report",
    "emit_problem_stats",
    "emit_verbose_iteration",
    "flight_recorder",
    "metrics_registry",
    "next_verbose_token",
    "span_recorder",
    "trace_to_dict",
]

_LAZY = {"SolveReport", "append_report", "build_report"}


def __getattr__(name):
    # Sink machinery loads on first use, not at package import: the
    # telemetry-off hot path must not pay for (or even import) it.
    if name in _LAZY:
        from megba_tpu.observability import report

        return getattr(report, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def metrics_registry(enabled: bool = False):
    """The process-default MetricsRegistry, or None when the plane is off.

    Armed by `MEGBA_METRICS` (any non-empty value) or an explicit
    `enabled=True` (the resolved `ProblemOption.metrics` knob).  The off
    path is one env lookup and never imports `metrics` — the same lazy
    posture as the telemetry sink.
    """
    if not (enabled or os.environ.get("MEGBA_METRICS")):
        return None
    from megba_tpu.observability import metrics

    return metrics.default_registry()


def span_recorder(enabled: bool = False):
    """The process-default SpanRecorder, or None (armed by MEGBA_TRACE)."""
    if not (enabled or os.environ.get("MEGBA_TRACE")):
        return None
    from megba_tpu.observability import spans

    return spans.default_recorder()


def flight_recorder(enabled: bool = False):
    """The process-default FlightRecorder, or None (armed by MEGBA_FLIGHT)."""
    if not (enabled or os.environ.get("MEGBA_FLIGHT")):
        return None
    from megba_tpu.observability import flight

    return flight.default_recorder()
