"""Single home of the solver's human-readable output.

Both stdout surfaces — the per-iteration verbose line (the reference's
observable, lm_algo.cu:149-162; parsed back by utils/curves.py for the
committed evidence artifacts) and the problem-stats block `solve_bal`
prints — are formatted HERE, so verbose output and telemetry can never
drift apart, and the curve parser tracks exactly one format definition.

The per-solve verbose clocks live here too: host-side start times keyed
by a per-solve token (a dynamic operand, so jitted programs stay cached
across solves while concurrent/chunked solves each get their own t0).
Iteration 0's callback starts that solve's clock; the table is pruned by
LAST-TOUCH time so a long-running solve that keeps emitting lines can
never lose its clock to a burst of short solves (evicting by insertion
order could drop the oldest STILL-LIVE solve under >_MAX_CLOCKS
concurrent solves — the regression tests/test_observability.py pins).
"""

from __future__ import annotations

import itertools
import time

import jax
import numpy as np

# token -> [t0, last_touch] (host perf_counter seconds).
_VERBOSE_CLOCKS: dict = {}
_MAX_CLOCKS = 64

# Monotonic per-solve token source.  count().__next__ is atomic under
# the GIL, so concurrent solves can never share a token.
next_verbose_token = itertools.count(1).__next__


def _emit_verbose_line(token, k, c, a, p):
    now = time.perf_counter()
    token = int(token)
    entry = _VERBOSE_CLOCKS.get(token)
    if int(k) == 0 or entry is None:
        while len(_VERBOSE_CLOCKS) >= _MAX_CLOCKS:
            # Evict the least-recently-touched clock; never clear() —
            # that would wipe live solves' clocks.
            stalest = min(_VERBOSE_CLOCKS,
                          key=lambda t: _VERBOSE_CLOCKS[t][1])
            _VERBOSE_CLOCKS.pop(stalest)
        entry = _VERBOSE_CLOCKS[token] = [now, now]
    else:
        entry[1] = now
    dt = (now - entry[0]) * 1e3
    # Format contract: utils/curves._LINE parses this line.
    print(
        f"iter {int(k)}: cost {float(c):.6e} "
        f"log10 {np.log10(max(float(c), 1e-300)):.3f} "
        f"accept {bool(a)} pcg_iters {int(p)} "
        f"elapsed {dt:.1f} ms", flush=True)


def emit_verbose_iteration(token, k, cost, accept, pcg_iters,
                           axis_name=None):
    """Emit one per-iteration line from inside a jitted LM body.

    Host callback printing the reference's observable (cost, log10 cost,
    elapsed ms — lm_algo.cu:149-162); elapsed is measured host-side from
    this solve's first callback (iteration 0 starts the clock keyed by
    the per-solve token — jitted programs are cached across solves, so a
    trace-time baseline would be frozen at the FIRST solve's start).
    With `axis_name` set, only shard 0 emits — one line per iteration,
    not one per shard.  Shared by the BA and PGO loops.
    """
    def _print(args):
        jax.debug.callback(_emit_verbose_line, *args)

    args = (token, k, cost, accept, pcg_iters)
    if axis_name is None:
        _print(args)
    else:
        # `axis_name` may be a tuple (the 2-D mesh passes both axes);
        # shard (0, ..., 0) is the single emitter either way.
        names = (axis_name,) if isinstance(axis_name, str) else axis_name
        is_zero = sum(jax.lax.axis_index(n) for n in names) == 0
        jax.lax.cond(is_zero, _print, lambda _: None, args)


def emit_problem_stats(num_cameras, num_points, num_observations,
                       max_cam_degree, max_pt_degree, hpl_blocks):
    """The verbose problem-stats block (solve_bal's pre-solve summary)."""
    print(
        f"problem: {num_cameras} cameras, {num_points} points, "
        f"{num_observations} observations | max camera degree "
        f"{max_cam_degree}, max point degree {max_pt_degree}, Hpl blocks "
        f"{hpl_blocks if hpl_blocks >= 0 else 'n/a (edges unsorted)'}",
        flush=True)
