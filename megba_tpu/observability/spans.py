"""Request-scoped spans with cross-process trace propagation.

One fleet solve fans out router → N worker processes → per-bucket
batched solves; this module makes that render as ONE timeline:

- :class:`SpanRecorder` records completed spans per process (thread-safe,
  bounded).  The active span context lives in a ``threading.local``
  stack, so nested ``with recorder.span(...)`` blocks become parent /
  child automatically.
- Trace context (``trace_id`` + parent ``span_id``) is a plain dict
  (:meth:`SpanRecorder.context`) that rides the router's solve RPC
  frames; the worker adopts it with :meth:`SpanRecorder.adopt` and ships
  its completed spans back in the reply, tagged with its pid.
- :func:`to_chrome_trace` exports any collection of span dicts as
  Chrome / Perfetto trace-event JSON (``ph: "X"`` complete events, µs
  timestamps) — multi-process merge is just concatenating span lists
  before export, because every span carries its own pid/tid.
- PhaseTimer phases join as child spans via the
  ``utils.timing.set_phase_hook`` seam (:func:`install_phase_hook`), so
  the lowering/program/dispatch/execute breakdown nests under the
  request span that caused it.

Timestamps are wall-clock µs (cross-process alignment needs a shared
epoch; durations come from the same reads, and spans are forensic, not
billing-grade).  This module is one of the two sanctioned raw-clock
homes (see the `raw-clock` lint rule).

Off by default behind ``MEGBA_TRACE``; consumers reach it through the
lazy ``observability.span_recorder()`` gate.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

SCHEMA = "megba_tpu.spans/v1"

_MAX_SPANS = 65536  # bounded: a leaked recorder must not grow unbounded


def _new_id() -> str:
    return os.urandom(8).hex()


def now_us() -> float:
    return time.time() * 1e6


class _Ctx(threading.local):
    def __init__(self):
        self.stack: List[Dict] = []


class SpanRecorder:
    """Process-local recorder of completed spans."""

    def __init__(self, process_name: Optional[str] = None):
        self._lock = threading.Lock()
        self._spans: List[Dict] = []  # megba: guarded-by(_lock)
        self._ctx = _Ctx()  # threading.local: per-thread, needs no lock
        self.pid = os.getpid()
        self.process_name = process_name or (
            os.environ.get("MEGBA_FEDERATION_WORKER") or "router")

    # -- context propagation ------------------------------------------------

    def context(self) -> Optional[Dict[str, str]]:
        """Wire form of the ACTIVE span context (None outside any span).

        The returned dict rides an RPC frame; the receiving process
        passes it to :meth:`adopt` so its spans join the same trace.
        """
        if not self._ctx.stack:
            return None
        top = self._ctx.stack[-1]
        return {"trace_id": top["trace_id"], "span_id": top["span_id"]}

    def span(self, name: str, ctx: Optional[Dict[str, str]] = None, **args):
        """Context manager recording one complete span.

        ``ctx`` (a :meth:`context` dict from another process) grafts the
        span under a remote parent; otherwise the parent is the
        innermost active local span, and a fresh trace id is minted at
        the root.
        """
        return _SpanScope(self, name, ctx, args)

    def adopt(self, name: str, ctx: Optional[Dict[str, str]], **args):
        """Alias of :meth:`span` that reads as 'join the remote trace'."""
        return _SpanScope(self, name, ctx, args)

    # -- phase-hook integration ---------------------------------------------

    def record_phase(self, name: str, duration_s: float) -> None:
        """Attach a just-finished PhaseTimer phase as a child span that
        ENDS now (phases only report durations on exit)."""
        end = now_us()
        parent = self._ctx.stack[-1] if self._ctx.stack else None
        span = {
            "name": f"phase.{name}",
            "trace_id": parent["trace_id"] if parent else _new_id(),
            "span_id": _new_id(),
            "parent_id": parent["span_id"] if parent else None,
            "ts_us": end - duration_s * 1e6,
            "dur_us": duration_s * 1e6,
            "pid": self.pid,
            "process": self.process_name,
            "tid": threading.get_ident(),
            "args": {},
        }
        self._append(span)

    # -- collection ---------------------------------------------------------

    def _append(self, span: Dict) -> None:
        with self._lock:
            if len(self._spans) < _MAX_SPANS:
                self._spans.append(span)

    def ingest(self, spans: List[Dict]) -> None:
        """Merge spans drained from another process (worker replies)."""
        for s in spans or []:
            self._append(dict(s))

    def drain(self) -> List[Dict]:
        with self._lock:
            out, self._spans = self._spans, []
            return out

    def spans(self) -> List[Dict]:
        with self._lock:
            return list(self._spans)


class _SpanScope:
    def __init__(self, recorder: SpanRecorder, name: str,
                 ctx: Optional[Dict[str, str]], args: Dict):
        self._r = recorder
        self._name = name
        self._remote = ctx
        self._args = {k: str(v) for k, v in args.items()}
        self.span: Optional[Dict] = None

    def __enter__(self):
        stack = self._r._ctx.stack
        if self._remote:
            trace_id = self._remote["trace_id"]
            parent_id = self._remote.get("span_id")
        elif stack:
            trace_id = stack[-1]["trace_id"]
            parent_id = stack[-1]["span_id"]
        else:
            trace_id = _new_id()
            parent_id = None
        self.span = {
            "name": self._name,
            "trace_id": trace_id,
            "span_id": _new_id(),
            "parent_id": parent_id,
            "ts_us": now_us(),
            "dur_us": 0.0,
            "pid": self._r.pid,
            "process": self._r.process_name,
            "tid": threading.get_ident(),
            "args": self._args,
        }
        stack.append(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb):
        self.span["dur_us"] = max(0.0, now_us() - self.span["ts_us"])
        if exc_type is not None:
            self.span["args"]["error"] = exc_type.__name__
        stack = self._r._ctx.stack
        if stack and stack[-1] is self.span:
            stack.pop()
        self._r._append(self.span)
        return False


def install_phase_hook(recorder: SpanRecorder) -> None:
    """Route completed PhaseTimer phases into `recorder` as child spans."""
    from megba_tpu.utils import timing

    timing.set_phase_hook(recorder.record_phase)


def to_chrome_trace(spans: List[Dict]) -> Dict:
    """Chrome/Perfetto trace-event JSON (the ``chrome://tracing`` load
    format): one ``ph: "X"`` complete event per span plus
    ``process_name`` metadata per pid, so a merged multi-process fleet
    solve renders with every worker as its own named track."""
    events = []
    seen_pids: Dict[int, str] = {}
    for s in sorted(spans, key=lambda s: (s["ts_us"], s["span_id"])):
        pid = int(s.get("pid", 0))
        if pid not in seen_pids:
            seen_pids[pid] = str(s.get("process", pid))
            events.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": seen_pids[pid]},
            })
        args = dict(s.get("args", {}))
        args["trace_id"] = s["trace_id"]
        args["span_id"] = s["span_id"]
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        events.append({
            "ph": "X",
            "name": s["name"],
            "cat": "megba",
            "ts": s["ts_us"],
            "dur": s["dur_us"],
            "pid": pid,
            "tid": int(s.get("tid", 0)) % (1 << 31),
            "args": args,
        })
    return {"schema": SCHEMA, "displayTimeUnit": "ms",
            "traceEvents": events}


def write_chrome_trace(path: str, spans: List[Dict]) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(to_chrome_trace(spans), fh)


# --- process default recorder ----------------------------------------------

_DEFAULT: Optional[SpanRecorder] = None
_DEFAULT_LOCK = threading.Lock()


def default_recorder() -> SpanRecorder:
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = SpanRecorder()
            # Armed processes get PhaseTimer phases as child spans for
            # free: the lowering/program/dispatch/execute breakdown
            # nests under whatever request span is active.
            install_phase_hook(_DEFAULT)
        return _DEFAULT


def reset_default_recorder() -> None:
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is not None:
            from megba_tpu.utils import timing

            timing.set_phase_hook(None)
        _DEFAULT = None
