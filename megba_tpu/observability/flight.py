"""Crash-dump flight recorder: a bounded ring of structured events.

When a worker process dies mid-fleet (the PR 12 SIGKILL host-loss path)
the only forensics today are the router's typed `WorkerLostError` and
the dead worker's captured log tail.  The flight recorder adds the
*surviving* side of the story: every process keeps the last-N structured
events (dispatch chaos injections, circuit-breaker transitions, queue
sheds, escalation retries, reroutes, worker losses) in a fixed-size ring
buffer, and on a death/crash the ring is dumped as JSONL — so a
post-mortem carries what the fleet was doing in the seconds before the
loss, not just the loss itself.

Event shape: ``{"t_unix": <wall s>, "seq": <monotone int>, "kind":
<str>, ...fields}``.  The ring is bounded (default 256 events) and
recording is a deque append under a lock — cheap enough to leave armed
in production, but still off by default behind ``MEGBA_FLIGHT`` (the
value is the dump path prefix), reached through the lazy
``observability.flight_recorder()`` gate.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional

SCHEMA = "megba_tpu.flight/v1"

DEFAULT_CAPACITY = 256


class FlightRecorder:
    """Thread-safe bounded ring buffer of structured events."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 process_name: Optional[str] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.process_name = process_name or (
            os.environ.get("MEGBA_FEDERATION_WORKER") or "router")
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=capacity)  # megba: guarded-by(_lock)
        self._seq = 0  # megba: guarded-by(_lock)
        self._dropped = 0  # megba: guarded-by(_lock)

    def record(self, kind: str, **fields) -> None:
        with self._lock:
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._seq += 1
            event = {"t_unix": time.time(), "seq": self._seq, "kind": kind}
            event.update(fields)
            self._ring.append(event)

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._dropped = 0

    def dump_dict(self, reason: str = "") -> Dict:
        with self._lock:
            return {
                "schema": SCHEMA,
                "process": self.process_name,
                "pid": os.getpid(),
                "reason": reason,
                "dropped": self._dropped,
                "dumped_unix": time.time(),
                "events": list(self._ring),
            }

    def dump(self, path: str, reason: str = "") -> str:
        """Append one JSONL dump line to `path`; returns the path.

        Append-mode JSONL on purpose: N surviving processes dumping on
        the same loss each land their own line instead of clobbering
        each other (the sink discipline SolveReport already uses).
        """
        payload = self.dump_dict(reason=reason)
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(payload, sort_keys=True) + "\n")
        return path


def load_dumps(path: str) -> List[Dict]:
    """Parse a JSONL flight-dump file (skips malformed lines — a dump
    raced by a dying process must not poison the post-mortem)."""
    out = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and rec.get("schema") == SCHEMA:
                    out.append(rec)
    except FileNotFoundError:
        pass
    return out


# --- process default recorder ----------------------------------------------

_DEFAULT: Optional[FlightRecorder] = None
_DEFAULT_LOCK = threading.Lock()


def default_recorder() -> FlightRecorder:
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = FlightRecorder()
        return _DEFAULT


def reset_default_recorder() -> None:
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None


def dump_path() -> Optional[str]:
    """The armed dump path (the MEGBA_FLIGHT value), or None."""
    return os.environ.get("MEGBA_FLIGHT") or None


def dump_default(reason: str = "") -> Optional[str]:
    """Dump the process-default ring to the armed path; best-effort (the
    caller is usually a dying process or a loss handler — a failed dump
    must never mask the original fault)."""
    path = dump_path()
    if not path:
        return None
    try:
        return default_recorder().dump(path, reason=reason)
    except OSError:
        return None
