"""Render recorded SolveReports as convergence tables + phase breakdowns.

Usage: python -m megba_tpu.observability.summarize \
    [--aggregate | --fleet] [--metrics <snapshot.json>] <report.jsonl> [...]

Reads JSONL files written by the `MEGBA_TELEMETRY` sink (one SolveReport
per line) and prints, per report: a header (problem shape, backend,
config essentials), the result summary, the per-iteration convergence
table, the phase wall-clock breakdown, and memory stats when present.

`--aggregate` switches to the FLEET view: one block over all reports in
all given files — per-status counts, problems/sec, p50/p95 solve
latency, and (when the reports carry the serving layer's `fleet`
context) per-bucket problem counts plus the resilience counters
(escalated attempts / retries / sheds / deadline misses / rejections
and circuit-breaker transitions) — so a multi-problem run's JSONL is
readable without ad-hoc scripts.  Reports carrying a pre-flight triage
`health` block (robustness/triage.py) add a triage line — rejected /
repaired counts, repair totals (points fixed, edges masked, cams
anchored, edges downweighted) and findings by kind.  A federation
router's lifetime report (serving/federation.py) adds the federation
block: per-worker problem counts, steals, reroutes, worker-lost events
and per-worker cold-start mode/timing (artifact-load vs compile) with
the first-solve trace count.  Reports carrying the elastic-
distribution context (`SolveReport.elastic`, robustness/elastic.py)
add an elastic line: workers lost, collective timeouts, reshards,
resumes, and time-to-detection p50/max (last snapshot per monitor,
summed across monitors).

`--fleet` is the observability plane's multi-worker view: one
per-bucket table over ALL given JSONL files (solves, workers serving
the bucket, LM/PCG iteration mean+max, latency p50/p95/max), with a
per-worker totals line under it.  Worker attribution reads the v2
schema's `worker` field (router workers stamp it from
`MEGBA_FEDERATION_WORKER`) and falls back to `fleet.worker`, so mixed
v1/v2 streams still tabulate — v1 lines just land in the `-` worker
row.  `--metrics <snapshot.json>` (usable with either mode, or alone)
renders a metrics-registry snapshot — `FleetRouter.metrics_snapshot()`
merged output or a single process's `snapshot_to_json` — as a
counter/gauge/histogram table.
"""

from __future__ import annotations

import math
import sys
from typing import Iterable, List

from megba_tpu.observability.report import SolveReport


def load_reports(path: str) -> List[SolveReport]:
    with open(path) as fh:
        return [SolveReport.from_json(line)
                for line in fh if line.strip()]


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f} {unit}"
        n /= 1024.0
    return f"{n:.1f} TiB"


def format_report(rep: SolveReport, index: int = 0) -> str:
    lines = []
    p, b, r = rep.problem, rep.backend, rep.result
    cfg = rep.config or {}
    lines.append(
        f"== report {index}: {p.get('num_cameras', '?')} cams / "
        f"{p.get('num_points', '?')} pts / {p.get('num_edges', '?')} edges "
        f"| {b.get('backend', '?')} x{b.get('device_count', '?')} "
        f"(process {b.get('process_index', 0)}/{b.get('process_count', 1)})")
    algo = cfg.get("algo_option", {}) or {}
    lines.append(
        f"   config: dtype={cfg.get('dtype')} "
        f"compute={cfg.get('compute_kind')} "
        f"jacobian={cfg.get('jacobian_mode')} "
        f"world_size={cfg.get('world_size')} "
        f"max_iter={algo.get('max_iter')}")
    lines.append(
        f"   result: cost {r.get('initial_cost', float('nan')):.6e} -> "
        f"{r.get('final_cost', float('nan')):.6e} in "
        f"{r.get('iterations')} LM iters ({r.get('accepted')} accepted, "
        f"{r.get('pcg_iterations')} PCG), stopped={r.get('stopped')}")
    fb = r.get("precond_fallback") or {}
    if fb.get("block") or fb.get("coarse"):
        # Per-level preconditioner fallback totals (solver/precond.py
        # enum codes, decoded at report build): block = SCHUR_DIAG
        # blocks fallen back to Hpp, coarse = iterations with a
        # degraded hierarchy level, per-level counts when multilevel.
        per = "".join(
            f" L{i + 1}:{n}" for i, n in
            enumerate(fb.get("coarse_levels") or []) if n)
        lines.append(
            f"   precond fallback: {fb.get('block', 0)} block / "
            f"{fb.get('coarse', 0)} coarse iters{per}")

    tiles = getattr(rep, "tiles", None) or {}
    if tiles:
        # Tile-plan attribution (solve.flat_solve): streaming reuse of
        # the planned edge stream + slot occupancy, and the fused
        # bucket-plan summaries when SolverOption.fused_kernels ran.
        rf = tiles.get("reuse_factor")
        occ = tiles.get("occupancy")
        line = f"   tiles[{tiles.get('plan', '?')}]:"
        if rf is not None:
            line += f" reuse_factor={rf:.1f}"
        if occ is not None:
            line += f" occupancy={occ:.3f}"
        lines.append(line)
        for dname in ("fused_to_pt", "fused_to_cam"):
            fp = tiles.get(dname)
            if fp:
                lines.append(
                    f"     fused {dname}: {fp.get('tiles')} tiles x "
                    f"{fp.get('tile')} slots, "
                    f"occupancy={fp.get('occupancy'):.3f}")

    if rep.trace and rep.trace.get("cost"):
        t = rep.trace
        lines.append("   iter  cost          log10    region     rho"
                     "        accept  pcg")
        for k, cost in enumerate(t["cost"]):
            log10 = math.log10(max(cost, 1e-300))
            lines.append(
                f"   {k:4d}  {cost:.6e}  {log10:7.3f}  "
                f"{t['trust_region'][k]:.3e}  {t['rho'][k]:9.3e}  "
                f"{'yes' if t['accept'][k] else ' no':>6}  "
                f"{t['pcg_iters'][k]:4d}")

    if rep.phases:
        lines.append("   phases:")
        total = 0.0
        for name in sorted(rep.phases,
                           key=lambda n: rep.phases[n]["total_s"],
                           reverse=True):
            ph = rep.phases[name]
            t_ms, c = ph["total_s"] * 1e3, ph["calls"]
            total += ph["total_s"]
            lines.append(f"     {name}: {t_ms:.1f} ms / {c} calls "
                         f"= {t_ms / c:.2f} ms")
        lines.append(f"     total: {total * 1e3:.1f} ms")

    if rep.memory:
        peak = rep.memory.get("peak_bytes_in_use")
        if peak is not None:
            lines.append(f"   memory: peak {_fmt_bytes(peak)} in use")
        else:
            lines.append(f"   memory: {rep.memory}")
    return "\n".join(lines)


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile of an ascending list (q in [0, 100])."""
    if not sorted_vals:
        return float("nan")
    rank = max(int(math.ceil(q / 100.0 * len(sorted_vals))) - 1, 0)
    return sorted_vals[min(rank, len(sorted_vals) - 1)]


def _report_latency(rep: SolveReport) -> float:
    """One report's solve latency: the serving layer's submit-to-result
    latency when present, else the summed phase wall clock."""
    if rep.fleet and rep.fleet.get("latency_s") is not None:
        return float(rep.fleet["latency_s"])
    if rep.phases:
        return sum(ph.get("total_s", 0.0) for ph in rep.phases.values())
    return float("nan")


def aggregate_reports(reports: List[SolveReport]) -> str:
    """The fleet view: status counts, throughput, latency percentiles."""
    if not reports:
        return "no reports"
    lines = []
    by_status: dict = {}
    for rep in reports:
        name = (rep.result or {}).get("status_name") or "unknown"
        by_status[name] = by_status.get(name, 0) + 1
    lats = sorted(l for l in (_report_latency(r) for r in reports)
                  if math.isfinite(l))

    # Throughput: wall span of the run when the reports spread over
    # time; a single batch's reports share one timestamp, so the span
    # is floored by the widest single solve so the rate stays finite
    # and honest.
    stamps = [r.created_unix for r in reports if r.created_unix]
    span = (max(stamps) - min(stamps)) if len(stamps) > 1 else 0.0
    if lats:
        span = max(span, lats[-1])
    rate = len(reports) / span if span > 0 else float("nan")

    lines.append(f"== fleet aggregate: {len(reports)} solves ==")
    for name in sorted(by_status):
        lines.append(f"   status {name}: {by_status[name]}")
    lines.append(f"   throughput: {rate:.2f} problems/s "
                 f"over {span:.3f}s span")
    if lats:
        lines.append(
            f"   latency: p50 {1e3 * _percentile(lats, 50):.1f} ms / "
            f"p95 {1e3 * _percentile(lats, 95):.1f} ms / "
            f"max {1e3 * lats[-1]:.1f} ms")
    buckets: dict = {}
    for rep in reports:
        if rep.fleet and rep.fleet.get("bucket"):
            buckets[rep.fleet["bucket"]] = (
                buckets.get(rep.fleet["bucket"], 0) + 1)
    for bucket in sorted(buckets):
        lines.append(f"   bucket {bucket}: {buckets[bucket]} solves")

    # Resilience view (PR 8): per-report escalation context, plus the
    # service-lifetime counters embedded in each report's fleet.stats —
    # the NEWEST report carries the most complete cumulative view
    # (sheds never emit a report of their own, so only the embedded
    # counters can account for them).  Known limit of a stream-only
    # view: events AFTER the final successful report (e.g. sheds during
    # close, or a run whose every problem was shed) are not in any
    # report — the live `FleetStats.report()` is the authoritative
    # in-process view.
    fleet_reps = [r for r in reports if r.fleet]
    if fleet_reps:
        # One report is emitted PER ATTEMPT (a dispatch that raised
        # emits none), so reports cannot count escalated PROBLEMS
        # exactly — count escalated ATTEMPTS that produced a result
        # instead; the exact re-enqueue total is the `retries` service
        # counter printed beside it.
        escalated = sum(1 for r in fleet_reps
                        if (r.fleet.get("attempts") or 1) > 1)
        max_rung = max((r.fleet.get("rung") or 0) for r in fleet_reps)
        latest = max(fleet_reps,
                     key=lambda r: (r.created_unix or 0.0))
        stats = latest.fleet.get("stats") or {}
        lines.append(
            f"   resilience: {escalated} escalated attempts "
            f"(max rung {max_rung}), "
            f"{stats.get('retries', 0)} retries, "
            f"{stats.get('sheds', 0)} shed, "
            f"{stats.get('deadline_misses', 0)} deadline-missed, "
            f"{stats.get('rejected', 0)} rejected")
        lines.append(
            f"   breaker: {stats.get('breaker_trips', 0)} trips / "
            f"{stats.get('breaker_probes', 0)} probes / "
            f"{stats.get('breaker_recoveries', 0)} recoveries / "
            f"{stats.get('breaker_fast_fails', 0)} fast-fails")

    # Triage view (PR 10): per-report `health` blocks carry each solved
    # problem's pre-flight findings and repair counters; REJECTED
    # problems never emit a report (zero dispatch), so — like sheds —
    # their count can only come from the service-lifetime counters
    # embedded in the NEWEST fleet report's stats.
    health_reps = [r for r in reports if r.health]
    stats_t: dict = {}
    if fleet_reps:
        latest_f = max(fleet_reps, key=lambda r: (r.created_unix or 0.0))
        stats_t = latest_f.fleet.get("stats") or {}
    if health_reps or stats_t.get("triage_rejected"):
        # Escalation retries emit one report per ATTEMPT, each carrying
        # the same health block — dedupe by the fleet problem name so a
        # rung-1 re-solve doesn't double its repair counters (reports
        # without a fleet name are standalone solves and count as-is).
        seen_names: set = set()
        deduped = []
        for rep in health_reps:
            name = (rep.fleet or {}).get("name")
            if name:
                if name in seen_names:
                    continue
                seen_names.add(name)
            deduped.append(rep)
        health_reps = deduped
        by_kind: dict = {}
        repaired = 0
        repair_tot = {"points_fixed": 0, "edges_masked": 0,
                      "cams_anchored": 0, "edges_downweighted": 0}
        for rep in health_reps:
            for f in rep.health.get("findings") or []:
                k = f.get("kind", "unknown")
                by_kind[k] = by_kind.get(k, 0) + int(f.get("count", 0))
            r = rep.health.get("repair")
            if r:
                repaired += 1
                for k in repair_tot:
                    repair_tot[k] += int(r.get(k, 0))
        lines.append(
            f"   triage: {stats_t.get('triage_rejected', 0)} rejected / "
            f"{repaired} repaired solves "
            f"({repair_tot['points_fixed']} points fixed, "
            f"{repair_tot['edges_masked']} edges masked, "
            f"{repair_tot['cams_anchored']} cams anchored, "
            f"{repair_tot['edges_downweighted']} edges downweighted)")
        if by_kind:
            lines.append("   findings: " + ", ".join(
                f"{k}={by_kind[k]}" for k in sorted(by_kind)))

    # Federation view (PR 12): one FederationStats snapshot per router
    # lifetime (serving/federation.append_federation_report) — keep the
    # LAST per router id and sum across routers, same shape as the
    # elastic ledger below.  Worker attribution also rides each fleet
    # report (`fleet.worker`), so the per-worker solve counts can be
    # cross-checked against the router's own routing ledger.
    latest_by_router: dict = {}
    for i, rep in enumerate(reports):
        if not rep.federation:
            continue
        key = rep.federation.get("router") or f"anon{i}"
        prev = latest_by_router.get(key)
        if prev is None or (rep.created_unix or 0.0) >= (
                prev.created_unix or 0.0):
            latest_by_router[key] = rep
    if latest_by_router:
        blocks = [r.federation for r in latest_by_router.values()]
        probs = sum(b.get("problems", 0) for b in blocks)
        steals = sum(b.get("steals", 0) for b in blocks)
        stolen = sum(b.get("stolen_problems", 0) for b in blocks)
        reroutes = sum(b.get("reroutes", 0) for b in blocks)
        lost = sum(b.get("workers_lost", 0) for b in blocks)
        by_worker: dict = {}
        for b in blocks:
            for w, n in (b.get("problems_by_worker") or {}).items():
                by_worker[w] = by_worker.get(w, 0) + n
        per = " / ".join(f"{w}:{by_worker[w]}" for w in sorted(by_worker))
        lines.append(
            f"   federation: {probs} problems across "
            f"{len(by_worker)} workers ({per or 'none'}), "
            f"{steals} steals ({stolen} problems), {reroutes} rerouted, "
            f"{lost} workers lost")
        for b in blocks:
            for w in sorted(b.get("cold_start") or {}):
                cs = b["cold_start"][w]
                fs = (b.get("first_solve") or {}).get(w) or {}
                extra = ""
                if fs.get("traces") is not None:
                    extra = f", first solve {fs['traces']} traces"
                lines.append(
                    f"   cold start {w}: {cs.get('mode', '?')} "
                    f"{float(cs.get('warm_s', float('nan'))):.3f}s "
                    f"({cs.get('artifact_loads', 0)} loaded / "
                    f"{cs.get('artifact_compiles', 0)} compiled)"
                    + extra)

    # Elastic view (PR 9): each elastic block is a CUMULATIVE snapshot
    # of one rank's ElasticMonitor (chunked solves emit one per chunk),
    # so keep the last snapshot per `monitor` id and sum ACROSS
    # monitors — counting every snapshot would multiply the ledger by
    # the chunk count.
    latest_by_monitor: dict = {}
    for i, rep in enumerate(reports):
        if not rep.elastic:
            continue
        key = rep.elastic.get("monitor") or f"anon{i}"
        prev = latest_by_monitor.get(key)
        if prev is None or (rep.created_unix or 0.0) >= (
                prev.created_unix or 0.0):
            latest_by_monitor[key] = rep
    if latest_by_monitor:
        blocks = [r.elastic for r in latest_by_monitor.values()]
        lost = sum(b.get("workers_lost", 0) for b in blocks)
        timeouts = sum(b.get("collective_timeouts", 0) for b in blocks)
        reshards = sum(b.get("reshards", 0) for b in blocks)
        resumes = sum(b.get("resumes", 0) for b in blocks)
        detections = sorted(
            float(s) for b in blocks for s in (b.get("detection_s") or []))
        lines.append(
            f"   elastic: {lost} workers lost, {timeouts} collective "
            f"timeouts, {reshards} reshards, {resumes} resumes "
            f"({len(latest_by_monitor)} monitors)")
        if detections:
            lines.append(
                f"   time-to-detection: p50 "
                f"{_percentile(detections, 50):.3f}s / max "
                f"{detections[-1]:.3f}s over {len(detections)} losses")
    return "\n".join(lines)


def fleet_table(reports: List[SolveReport]) -> str:
    """Per-bucket iteration/latency stats across a multi-worker fleet.

    Buckets come from the serving layer's `fleet.bucket` context
    (reports without one — standalone solves — group under
    "unbatched"); worker attribution prefers the v2 `worker` field and
    falls back to `fleet.worker` so v1 lines still land in the table.
    """
    if not reports:
        return "no reports"
    rows: dict = {}
    by_worker: dict = {}
    for rep in reports:
        fleet = rep.fleet or {}
        bucket = fleet.get("bucket") or "unbatched"
        worker = (getattr(rep, "worker", None)
                  or fleet.get("worker") or "-")
        row = rows.setdefault(
            bucket, {"n": 0, "workers": set(), "lm": [], "pcg": [],
                     "lat": []})
        row["n"] += 1
        row["workers"].add(worker)
        by_worker[worker] = by_worker.get(worker, 0) + 1
        r = rep.result or {}
        if r.get("iterations") is not None:
            row["lm"].append(int(r["iterations"]))
        if r.get("pcg_iterations") is not None:
            row["pcg"].append(int(r["pcg_iterations"]))
        lat = _report_latency(rep)
        if math.isfinite(lat):
            row["lat"].append(lat)

    def _mean(vals: List[float]) -> float:
        return sum(vals) / len(vals) if vals else float("nan")

    lines = [f"== fleet table: {len(reports)} solves / "
             f"{len(rows)} buckets / {len(by_worker)} workers =="]
    header = (f"   {'bucket':<28} {'solves':>6} {'workers':>7} "
              f"{'lm avg':>7} {'lm max':>7} {'pcg avg':>8} "
              f"{'p50 ms':>8} {'p95 ms':>8} {'max ms':>8}")
    lines.append(header)
    for bucket in sorted(rows):
        row = rows[bucket]
        lat = sorted(row["lat"])
        lines.append(
            f"   {bucket:<28} {row['n']:>6} {len(row['workers']):>7} "
            f"{_mean(row['lm']):>7.1f} "
            f"{max(row['lm'], default=0):>7d} "
            f"{_mean(row['pcg']):>8.1f} "
            f"{1e3 * _percentile(lat, 50):>8.1f} "
            f"{1e3 * _percentile(lat, 95):>8.1f} "
            f"{1e3 * (lat[-1] if lat else float('nan')):>8.1f}")
    per = " / ".join(f"{w}:{by_worker[w]}" for w in sorted(by_worker))
    lines.append(f"   by worker: {per}")
    traced = sum(1 for r in reports if getattr(r, "trace_id", None))
    if traced:
        n_traces = len({r.trace_id for r in reports
                        if getattr(r, "trace_id", None)})
        lines.append(f"   traced: {traced} solves in {n_traces} traces")
    return "\n".join(lines)


def format_metrics_snapshot(snap: dict) -> str:
    """Render a metrics-registry snapshot (one process's or the
    router's merged fleet view) as a readable table."""
    lines = [f"== metrics snapshot ({snap.get('schema', '?')}) =="]
    for name in sorted(snap.get("metrics") or {}):
        m = snap["metrics"][name]
        kind = m.get("kind", "?")
        lines.append(f"   {name} ({kind})")
        for key in sorted(m.get("series") or {}):
            s = m["series"][key]
            label = f"{{{key}}}" if key else ""
            if kind == "histogram":
                count = s.get("count", 0)
                total = s.get("sum", 0.0)
                mean = total / count if count else float("nan")
                lines.append(
                    f"     {label or '(no labels)'}: count {count}, "
                    f"sum {total:.6g}, mean {mean:.6g}")
            else:
                lines.append(
                    f"     {label or '(no labels)'}: {float(s):g}")
    return "\n".join(lines)


def fleet_paths(paths: Iterable[str]) -> str:
    reports: List[SolveReport] = []
    for path in paths:
        reports.extend(load_reports(path))
    return fleet_table(reports)


def aggregate_paths(paths: Iterable[str]) -> str:
    reports: List[SolveReport] = []
    for path in paths:
        reports.extend(load_reports(path))
    return aggregate_reports(reports)


def summarize_paths(paths: Iterable[str]) -> str:
    blocks = []
    for path in paths:
        reports = load_reports(path)
        blocks.append(f"{path}: {len(reports)} report(s)")
        blocks.extend(format_report(rep, i) for i, rep in enumerate(reports))
    return "\n".join(blocks)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0 if argv else 2
    aggregate = "--aggregate" in argv
    fleet = "--fleet" in argv
    metrics_path = None
    paths = []
    it = iter(a for a in argv if a not in ("--aggregate", "--fleet"))
    for a in it:
        if a == "--metrics":
            metrics_path = next(it, None)
            if metrics_path is None:
                print("--metrics requires a snapshot path",
                      file=sys.stderr)
                return 2
        else:
            paths.append(a)
    if not paths and metrics_path is None:
        print(__doc__.strip())
        return 2
    if paths:
        if fleet:
            print(fleet_paths(paths))
        elif aggregate:
            print(aggregate_paths(paths))
        else:
            print(summarize_paths(paths))
    if metrics_path is not None:
        import json

        with open(metrics_path) as fh:
            print(format_metrics_snapshot(json.load(fh)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
